"""Downloaders: sharding logic via local fixtures (no network)."""

import os
import tarfile

import pytest

from lddl_tpu.download.utils import _ShardWriter, shard_documents
from lddl_tpu.download.wikipedia import aggregate_extracted
from lddl_tpu.download.books import shard_books
from lddl_tpu.download.openwebtext import shard_pages
from lddl_tpu.download.common_crawl import ArticleBuffer, aggregate_txt
from lddl_tpu.preprocess.readers import discover_source_files, read_documents, plan_blocks


def _read_all_docs(outdir):
    files = discover_source_files({"x": outdir})
    docs = []
    for b in plan_blocks(files, len(files)):
        # read_documents yields raw bytes (zero-decode pipeline); these
        # assertions are about downloader CONTENT, so decode for clarity.
        docs.extend((d.decode("utf-8"), t.decode("utf-8"))
                    for d, t in read_documents(b))
    return docs


def test_shard_writer_contract(tmp_path):
    n = shard_documents(
        [("id-{}".format(i), "text with\nnewlines {}".format(i))
         for i in range(10)],
        str(tmp_path), 3)
    assert n == 10
    docs = _read_all_docs(str(tmp_path))
    assert len(docs) == 10
    ids = {d for d, _ in docs}
    assert ids == {"id-{}".format(i) for i in range(10)}
    # Newlines flattened: one doc per line held.
    assert all("\n" not in t for _, t in docs)
    with pytest.raises(ValueError, match="whitespace"):
        shard_documents([("bad id", "text")], str(tmp_path / "y"), 1)


def test_wikipedia_aggregation(tmp_path):
    extracted = tmp_path / "extracted" / "AA"
    extracted.mkdir(parents=True)
    (extracted / "wiki_00").write_text(
        '<doc id="12" url="u" title="Python">\n'
        "Python\n"
        "\n"
        "Python is a language.\n"
        "It is widely used.\n"
        "</doc>\n"
        '<doc id="34" title="JAX">\n'
        "JAX\n"
        "JAX is a library.\n"
        "</doc>\n")
    out = str(tmp_path / "out")
    n = aggregate_extracted(str(tmp_path / "extracted"), out, 2)
    assert n == 2
    docs = dict(_read_all_docs(out))
    assert docs["wiki-12"] == "Python is a language. It is widely used."
    assert docs["wiki-34"] == "JAX is a library."  # title dropped


def test_books_sharding(tmp_path):
    books = tmp_path / "books"
    books.mkdir()
    (books / "Moby Dick.txt").write_text("Call me Ishmael.\nSome years ago.")
    (books / "notes.pdf").write_text("not a book")
    out = str(tmp_path / "out")
    n = shard_books(str(books), out, 1)
    assert n == 1
    docs = _read_all_docs(out)
    assert docs[0][0] == "Moby-Dick.txt"
    assert "Ishmael" in docs[0][1]


def test_openwebtext_sharding(tmp_path):
    pages = tmp_path / "pages" / "subset0"
    pages.mkdir(parents=True)
    (pages / "page-a.txt").write_text("Content of page a.")
    (pages / "page-b.txt").write_text("Content of page b.")
    out = str(tmp_path / "out")
    n = shard_pages(str(tmp_path / "pages"), out, 2)
    assert n == 2
    ids = {d for d, _ in _read_all_docs(out)}
    assert ids == {"page-a", "page-b"}


def test_common_crawl_buffer_and_aggregate(tmp_path):
    txt_dir = str(tmp_path / "txt")
    buf = ArticleBuffer(txt_dir, "cc", articles_per_write=2)
    for i in range(5):
        buf.add("cc-article-{}".format(i), "Body number {}.".format(i))
    buf.flush()
    assert len(os.listdir(txt_dir)) == 3  # 2+2+1
    out = str(tmp_path / "out")
    n = aggregate_txt(txt_dir, out, 2)
    assert n == 5
    ids = {d for d, _ in _read_all_docs(out)}
    assert ids == {"cc-article-{}".format(i) for i in range(5)}


def test_shard_files_parallel_pool_matches_sequential(tmp_path):
    """The process-pool sharding path produces byte-identical shard files
    to the sequential path (same file->shard assignment)."""
    from lddl_tpu.download.utils import shard_files_parallel
    from lddl_tpu.download.books import parse_book_file
    books = tmp_path / "books"
    books.mkdir()
    paths = []
    for i in range(11):
        p = books / "book-{}.txt".format(i)
        p.write_text("Text of book {}.\nSecond line.".format(i))
        paths.append(str(p))
    seq = str(tmp_path / "seq")
    par = str(tmp_path / "par")
    n1 = shard_files_parallel(paths, seq, 3, parse_book_file,
                              num_processes=1)
    n2 = shard_files_parallel(paths, par, 3, parse_book_file,
                              num_processes=3)
    assert n1 == n2 == 11
    for k in range(3):
        a = open(os.path.join(seq, "source", "{}.txt".format(k))).read()
        b = open(os.path.join(par, "source", "{}.txt".format(k))).read()
        assert a == b and a


def test_common_crawl_cli_flag_parity():
    """The CC CLI exposes the reference's full flag surface
    (ref: lddl/download/common_crawl.py:100-260)."""
    from lddl_tpu.download.common_crawl import attach_args
    parser = attach_args()
    args = parser.parse_args([
        "--outdir", "/tmp/x",
        "--valid-hosts", "example.com", "news.org",
        "--start-date", "2020-01-01",
        "--end-date", "2020-06-01",
        "--warc-files-start-date", "2020-01-01",
        "--warc-files-end-date", "2020-02-01",
        "--langs", "en",
        "--no-strict-date",
        "--no-reuse-previously-downloaded-files",
        "--no-continue-after-error",
        "--show-download-progress",
        "--no-delete-warc-after-extraction",
        "--no-continue-process",
        "--number-of-extraction-processes", "4",
        "--number-of-sharding-processes", "2",
        "--no-newsplease",
    ])
    assert args.valid_hosts == ["example.com", "news.org"]
    assert not args.strict_date
    assert not args.reuse_previously_downloaded_files
    assert not args.continue_after_error
    assert args.show_download_progress
    assert not args.delete_warc_after_extraction
    assert not args.continue_process
    assert args.number_of_extraction_processes == 4
    assert args.number_of_sharding_processes == 2
    assert not args.newsplease and args.shard


def test_common_crawl_no_newsplease_aggregates_outdir_txt(tmp_path):
    """--no-newsplease skips the crawl but still shards <outdir>/txt."""
    from lddl_tpu.download.common_crawl import attach_args, main
    outdir = tmp_path / "cc"
    txt = outdir / "txt"
    txt.mkdir(parents=True)
    (txt / "host-1-2-0-3.txt").write_text("cc-a Body a.\ncc-b Body b.\n")
    args = attach_args().parse_args(
        ["--outdir", str(outdir), "--num-shards", "2", "--no-newsplease",
         "--number-of-sharding-processes", "1"])
    main(args)
    ids = {d for d, _ in _read_all_docs(str(outdir))}
    assert ids == {"cc-a", "cc-b"}
