"""Downloaders: sharding logic via local fixtures (no network)."""

import os
import tarfile

import pytest

from lddl_tpu.download.utils import _ShardWriter, shard_documents
from lddl_tpu.download.wikipedia import aggregate_extracted
from lddl_tpu.download.books import shard_books
from lddl_tpu.download.openwebtext import shard_pages
from lddl_tpu.download.common_crawl import ArticleBuffer, aggregate_txt
from lddl_tpu.preprocess.readers import discover_source_files, read_documents, plan_blocks


def _read_all_docs(outdir):
    files = discover_source_files({"x": outdir})
    docs = []
    for b in plan_blocks(files, len(files)):
        docs.extend(read_documents(b))
    return docs


def test_shard_writer_contract(tmp_path):
    n = shard_documents(
        [("id-{}".format(i), "text with\nnewlines {}".format(i))
         for i in range(10)],
        str(tmp_path), 3)
    assert n == 10
    docs = _read_all_docs(str(tmp_path))
    assert len(docs) == 10
    ids = {d for d, _ in docs}
    assert ids == {"id-{}".format(i) for i in range(10)}
    # Newlines flattened: one doc per line held.
    assert all("\n" not in t for _, t in docs)
    with pytest.raises(ValueError, match="whitespace"):
        shard_documents([("bad id", "text")], str(tmp_path / "y"), 1)


def test_wikipedia_aggregation(tmp_path):
    extracted = tmp_path / "extracted" / "AA"
    extracted.mkdir(parents=True)
    (extracted / "wiki_00").write_text(
        '<doc id="12" url="u" title="Python">\n'
        "Python\n"
        "\n"
        "Python is a language.\n"
        "It is widely used.\n"
        "</doc>\n"
        '<doc id="34" title="JAX">\n'
        "JAX\n"
        "JAX is a library.\n"
        "</doc>\n")
    out = str(tmp_path / "out")
    n = aggregate_extracted(str(tmp_path / "extracted"), out, 2)
    assert n == 2
    docs = dict(_read_all_docs(out))
    assert docs["wiki-12"] == "Python is a language. It is widely used."
    assert docs["wiki-34"] == "JAX is a library."  # title dropped


def test_books_sharding(tmp_path):
    books = tmp_path / "books"
    books.mkdir()
    (books / "Moby Dick.txt").write_text("Call me Ishmael.\nSome years ago.")
    (books / "notes.pdf").write_text("not a book")
    out = str(tmp_path / "out")
    n = shard_books(str(books), out, 1)
    assert n == 1
    docs = _read_all_docs(out)
    assert docs[0][0] == "Moby-Dick.txt"
    assert "Ishmael" in docs[0][1]


def test_openwebtext_sharding(tmp_path):
    pages = tmp_path / "pages" / "subset0"
    pages.mkdir(parents=True)
    (pages / "page-a.txt").write_text("Content of page a.")
    (pages / "page-b.txt").write_text("Content of page b.")
    out = str(tmp_path / "out")
    n = shard_pages(str(tmp_path / "pages"), out, 2)
    assert n == 2
    ids = {d for d, _ in _read_all_docs(out)}
    assert ids == {"page-a", "page-b"}


def test_common_crawl_buffer_and_aggregate(tmp_path):
    txt_dir = str(tmp_path / "txt")
    buf = ArticleBuffer(txt_dir, "cc", articles_per_write=2)
    for i in range(5):
        buf.add("cc-article-{}".format(i), "Body number {}.".format(i))
    buf.flush()
    assert len(os.listdir(txt_dir)) == 3  # 2+2+1
    out = str(tmp_path / "out")
    n = aggregate_txt(txt_dir, out, 2)
    assert n == 5
    ids = {d for d, _ in _read_all_docs(out)}
    assert ids == {"cc-article-{}".format(i) for i in range(5)}
