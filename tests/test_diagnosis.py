"""Diagnosis layer (PR 17): time-series telemetry (series segments,
windowed rates), loader critical-path attribution with the bound
verdict, the declarative alert-rules engine, spool retention/GC, the
arm-time snapshot stamp, backend op latency histograms — and the
contracts that hold it all together: byte-inertness (series +
attribution armed vs off changes no batch byte), torn-tail tolerance,
and crash-coherent series flushing on SIGTERM.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu import observability as obs  # noqa: E402
from lddl_tpu.observability import (alerts, attribution, fleet,  # noqa: E402
                                    series, tracing)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ENVS = (fleet.ENV_FLEET_DIR, fleet.ENV_HOLDER, fleet.ENV_TTL,
         fleet.ENV_INTERVAL, fleet.ENV_ROTATE_BYTES,
         fleet.ENV_RETAIN_BYTES, fleet.ENV_RETAIN_AGE_S,
         series.ENV_RING, "LDDL_TPU_METRICS_DIR", "LDDL_TPU_METRICS_RANK")


def _scrub_env():
    for name in _ENVS:
        os.environ.pop(name, None)


@pytest.fixture
def clean_telemetry():
    _scrub_env()
    obs.registry().reset()
    tracing._reset_for_tests()
    fleet._reset_for_tests()
    yield
    _scrub_env()
    obs.registry().reset()
    tracing._reset_for_tests()
    fleet._reset_for_tests()


# ------------------------------------------------------------ series core


def test_series_sample_diffs_and_key_roundtrip(clean_telemetry, tmp_path):
    os.environ["LDDL_TPU_METRICS_DIR"] = str(tmp_path)
    obs.inc("units_total", 3)
    obs.inc("stage_seconds_total", 0.5, stage="decode")
    obs.set_gauge("backlog_docs", 42.0)
    obs.observe("op_latency_seconds", 0.01)
    p1 = series.sample()
    assert p1["d"]["units_total"] == 3
    assert p1["d"]["stage_seconds_total{stage=decode}"] == 0.5
    assert p1["g"]["backlog_docs"] == 42.0
    assert p1["h"]["op_latency_seconds"]["n"] == 1
    # No movement -> counters drop out of the next point entirely.
    obs.set_gauge("backlog_docs", 40.0)
    p2 = series.sample()
    assert "units_total" not in p2.get("d", {})
    assert p2["g"]["backlog_docs"] == 40.0
    obs.inc("units_total", 2)
    p3 = series.sample()
    assert p3["d"]["units_total"] == 2  # delta, not cumulative
    name, labels = series.split_key("stage_seconds_total{stage=decode}")
    assert (name, labels) == ("stage_seconds_total", "stage=decode")
    assert series.split_key("plain") == ("plain", "")


def test_series_window_rollup_rates_gauges_histograms():
    now = 1000.0
    points = []
    for i in range(10):
        points.append({"wall": now - 90 + i * 10, "mono": i, "pid": 1,
                       "d": {"units_total": 5.0},
                       "g": {"backlog": 100.0 - i},
                       "h": {"lat": {"n": 2, "s": 0.2,
                                     "b": {"le_0.25": 2}}}})
    roll = series.window_rollup(points, 60.0, now=now)
    # 7 points inside [now-60, now]; 5 units each over a 60 s span.
    assert roll["points"] == 7
    assert roll["rates"]["units_total"] == pytest.approx(35.0 / 60.0)
    g = roll["gauges"]["backlog"]
    assert g["last"] < g["first"] and g["trend"] < 0
    h = roll["histograms"]["lat"]
    assert h["count"] == 14 and h["mean"] == pytest.approx(0.1)
    assert h["p50"] == pytest.approx(0.25)
    # Empty window stays well-formed.
    empty = series.window_rollup(points, 60.0, now=now + 10_000)
    assert empty["points"] == 0 and empty["rates"] == {}


def test_percentile_from_buckets():
    buckets = {"le_0.001": 10, "le_0.01": 80, "le_0.1": 10}
    assert series.percentile_from_buckets(buckets, 0.5) == \
        pytest.approx(0.01)
    assert series.percentile_from_buckets(buckets, 0.99) == \
        pytest.approx(0.1)
    assert series.percentile_from_buckets({}, 0.5) is None


def test_series_torn_tail_is_end_of_stream(clean_telemetry, tmp_path):
    spool = tmp_path / ".telemetry" / "h1"
    spool.mkdir(parents=True)
    good = json.dumps({"wall": 1.0, "mono": 0.0, "pid": 7,
                       "d": {"units_total": 4.0}})
    (spool / "series-pid7.jsonl").write_text(good + "\n" + good[:11])
    points, torn = series.read_series(str(tmp_path), "h1",
                                      warn=lambda *a: None)
    assert len(points) == 1 and torn == 1
    assert points[0]["d"]["units_total"] == 4.0


def test_series_flush_publishes_segments_via_heartbeat(
        clean_telemetry, tmp_path):
    root = str(tmp_path)
    spool = fleet.configure(root, holder_id="hostS", ttl=30, interval=3600)
    obs.inc("units_total", 9)
    fleet.heartbeat()
    files = [n for n in sorted(os.listdir(spool))
             if n.startswith(series.SEGMENT_PREFIX)]
    assert files, sorted(os.listdir(spool))
    points, torn = series.read_series(root, "hostS")
    assert torn == 0
    assert sum(p.get("d", {}).get("units_total", 0) for p in points) == 9


# --------------------------------------------------- rotation + retention


def test_event_spool_rotation_reads_seamlessly(clean_telemetry, tmp_path):
    root = str(tmp_path)
    os.environ[fleet.ENV_ROTATE_BYTES] = "256"
    spool = fleet.configure(root, holder_id="rot", ttl=30, interval=3600)
    for i in range(40):
        fleet.record("unit.claimed", unit="g{}".format(i), epoch=0,
                     holder="rot")
        fleet.flush_events()
    names = sorted(os.listdir(spool))
    segs = [n for n in names if n.startswith("events-pid")
            and ".seg" in n]
    assert segs, names  # rotation actually happened
    # The reader merges base + rotated segments into one stream.
    loaded = fleet.load_spool(root, "rot")
    kinds = [ev["kind"] for ev in loaded["events"]]
    assert kinds.count("unit.claimed") == 40
    units = [ev["args"]["unit"] for ev in loaded["events"]]
    assert units == ["g{}".format(i) for i in range(40)]


def test_gc_spool_bounds_size_and_age_keeps_live(clean_telemetry, tmp_path):
    root = str(tmp_path)
    os.environ[fleet.ENV_ROTATE_BYTES] = "256"
    spool = fleet.configure(root, holder_id="gc", ttl=30, interval=3600)
    for i in range(40):
        fleet.record("unit.claimed", unit="g{}".format(i), epoch=0,
                     holder="gc")
        fleet.flush_events()
    obs.inc("units_total", 1)
    fleet.heartbeat()
    segs = [n for n in sorted(os.listdir(spool)) if ".seg" in n]
    assert segs
    # Generous budgets: nothing is eligible yet.
    assert fleet.gc_spool(spool) == 0
    # Tiny byte budget: frozen segments go oldest-first, the live append
    # targets and the open snapshot survive.
    os.environ[fleet.ENV_RETAIN_BYTES] = "1"
    live = {os.path.basename(fleet._ev_segment["path"] or ""),
            os.path.basename(series._segment["path"] or "")}
    removed = fleet.gc_spool(spool)
    assert removed == len([n for n in segs if n not in live])
    left = sorted(os.listdir(spool))
    assert fleet._ev_segment["path"] is not None
    assert os.path.basename(fleet._ev_segment["path"]) in left
    assert any(n.startswith("snapshot-pid") for n in left)
    # A closed snapshot from ANOTHER pid ages out; our own never does.
    foreign = os.path.join(spool, "snapshot-pid99999.json")
    with open(foreign, "w") as f:
        json.dump({"holder": "gc", "pid": 99999, "closed": True}, f)
    os.environ[fleet.ENV_RETAIN_AGE_S] = "0"
    os.environ[fleet.ENV_RETAIN_BYTES] = str(1 << 30)
    assert fleet.gc_spool(spool, now=time.time() + 10.0) >= 1
    assert not os.path.exists(foreign)
    assert any(n.startswith("snapshot-pid{}".format(os.getpid()))
               for n in sorted(os.listdir(spool)))


def test_arm_time_snapshot_stamps_before_first_heartbeat(
        clean_telemetry, tmp_path):
    """A run dying between configure() and the first heartbeat must
    leave a start stamp, not an empty spool."""
    root = str(tmp_path)
    spool = fleet.configure(root, holder_id="stamp", ttl=30, interval=3600)
    snaps = [n for n in sorted(os.listdir(spool))
             if n.startswith("snapshot-pid")]
    assert snaps, sorted(os.listdir(spool))
    snap = fleet._read_json(os.path.join(spool, snaps[0]))
    assert snap["closed"] is False and snap["started_wall"] is not None
    # And the aggregator can age it into STALLED from the stamp alone.
    report = fleet.aggregate(root, now=time.time() + 10_000.0)
    assert report["hosts"]["stamp"]["stalled"]


# ------------------------------------------------------------ attribution


def test_attribution_verdict_rules_pure():
    rep = attribution.from_stage_seconds(
        {"batch_wait": 8.0, "step_gap": 2.0, "shard_read": 3.0,
         "decode": 1.0})
    assert rep["verdict"] == "input-bound" and rep["boundary"] == "loader"
    assert rep["input_share"] == pytest.approx(0.8)
    assert sum(rep["shares"].values()) == pytest.approx(1.0)
    assert rep["top_stage"]["stage"] == "shard_read"
    assert rep["shares"]["shard_read"] == pytest.approx(0.8 * 0.75)

    rep = attribution.from_stage_seconds(
        {"batch_wait": 1.0, "step_gap": 9.0})
    assert rep["verdict"] == "compute-bound"
    assert rep["shares"]["queue_wait"] == pytest.approx(0.1)
    assert sum(rep["shares"].values()) == pytest.approx(1.0)

    rep = attribution.from_stage_seconds(
        {"batch_wait": 3.0, "step_gap": 7.0})
    assert rep["verdict"] == "balanced"

    # The prefetch boundary wins when present (outermost iterator).
    rep = attribution.from_stage_seconds(
        {"prefetch_wait": 5.0, "prefetch_gap": 5.0,
         "batch_wait": 99.0, "step_gap": 1.0, "h2d": 2.0})
    assert rep["boundary"] == "prefetch"
    assert rep["input_share"] == pytest.approx(0.5)

    assert attribution.from_stage_seconds({}) is None
    assert attribution.from_stage_seconds({"decode": 1.0}) is None


@pytest.fixture(scope="module")
def ingested(tmp_path_factory):
    """One tiny ingested dataset shared by the loader-path tests."""
    from lddl_tpu.ingest import ingest_once
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer

    _scrub_env()
    td = tmp_path_factory.mktemp("diag")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    landing = str(td / "landing")
    os.makedirs(os.path.join(landing, "source"))
    shutil.copy(os.path.join(corpus, "source", "0.txt"),
                os.path.join(landing, "source", "0.txt"))
    root = str(td / "data")
    tok = get_tokenizer(vocab_file=vocab)
    cfg = BertPretrainConfig(max_seq_length=32, masking=False)
    ingest_once(root, tok, landing=landing, config=cfg, num_shards=4,
                seed=7, num_blocks=4)
    return root, vocab


def _batches(loader):
    return [{k: np.asarray(v) for k, v in b.items()} for b in loader]


def test_attribution_from_real_loader_with_known_step_sleep(
        clean_telemetry, tmp_path, ingested):
    """Instrumentation end-to-end: iterate the real loader with a known
    consumer step (sleep), then the verdict must partition the observed
    wall — shares summing to ~100%, step_gap covering the sleeps, and
    every self-time stage the thread-mode path visits recorded."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    root, vocab = ingested
    os.environ["LDDL_TPU_METRICS_DIR"] = str(tmp_path / "m")
    loader = get_bert_pretrain_data_loader(root, vocab_file=vocab,
                                           batch_size=8, base_seed=5)
    step_s = 0.02
    t0 = time.perf_counter()
    n = 0
    for _ in loader:
        time.sleep(step_s)
        n += 1
    wall = time.perf_counter() - t0
    assert n > 0
    rep = loader.attribution_snapshot()
    assert rep is not None
    assert rep["boundary"] == "loader"
    assert sum(rep["shares"].values()) == pytest.approx(1.0)
    # The observed wall is the full iteration wall minus the pre-first-
    # batch setup; it must cover every sleep and stay under the total.
    assert rep["wall_seconds"] >= n * step_s * 0.9
    assert rep["wall_seconds"] <= wall + 0.001
    stages = rep["stages_seconds"]
    assert stages["step_gap"] >= n * step_s * 0.9
    for stage in ("shard_read", "decode", "collate"):
        assert stages.get(stage, 0.0) > 0.0, (stage, stages)
    # snapshot() published the verdict gauges for the fleet rollup.
    snap = obs.registry().snapshot()
    assert attribution.VERDICT_GAUGE in snap
    assert attribution.INPUT_SHARE_GAUGE in snap


def test_series_and_attribution_are_byte_inert(clean_telemetry, tmp_path,
                                               ingested):
    """The PR's inertness pin: telemetry off vs armed (metrics + fleet +
    tiny rotation bound, so series/attribution instrumentation AND spool
    rotation all actually run) yields an identical batch stream."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    root, vocab = ingested
    off = _batches(get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5))

    _scrub_env()
    obs.registry().reset()
    fleet._reset_for_tests()
    out = str(tmp_path / "armed")
    os.environ[fleet.ENV_ROTATE_BYTES] = "512"
    fleet.configure(out, holder_id="inert", ttl=30, interval=3600)
    on = _batches(get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5))
    fleet.heartbeat(closed=True)

    assert len(off) == len(on) and len(off) > 0
    for x, y in zip(off, on):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k], err_msg=k)
    # And the armed run actually produced series + attribution telemetry.
    points, _ = series.read_series(out, "inert")
    keys = {k for p in points for k in p.get("d", {})}
    assert any(k.startswith(attribution.STAGE_METRIC) for k in keys)


# ------------------------------------------------------------ alert rules


def _write_rules(path, rules):
    with open(path, "w") as f:
        json.dump({"rules": rules}, f)
    return path


def _mk_series(root, holder, points):
    d = os.path.join(root, ".telemetry", holder)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "series-pid1.jsonl"), "w") as f:
        for p in points:
            f.write(json.dumps(p) + "\n")


def test_alert_rules_validation():
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "r.json")
        for bad in (
                [{"type": "threshold", "metric": "m", "value": 1}],  # name
                [{"name": "a", "type": "nope", "metric": "m",
                  "value": 1}],
                [{"name": "a", "type": "threshold", "metric": "m",
                  "op": "~", "value": 1}],
                [{"name": "a", "type": "threshold", "metric": "m"}],
                [{"name": "a", "type": "threshold", "metric": "m",
                  "value": 1}] * 2,  # duplicate names
                [{"name": "a", "type": "threshold", "value": 1}],  # metric
        ):
            _write_rules(p, bad)
            with pytest.raises(ValueError):
                alerts.load_rules(p)
        _write_rules(p, [{"name": "ok", "metric": "m", "value": 5}])
        (rule,) = alerts.load_rules(p)
        assert rule["type"] == "threshold" and rule["op"] == ">"


def test_alert_threshold_fire_resolve_persists_state(tmp_path):
    root = str(tmp_path)
    rules = [{"name": "backlog", "type": "threshold",
              "metric": "totals.counters.backlog", "op": ">", "value": 10}]
    report = {"totals": {"counters": {"backlog": 50}}, "hosts": {}}
    eng = alerts.AlertEngine(rules, root)
    res = eng.evaluate(report=report, now=100.0)
    assert res["firing"] == ["backlog"]
    assert [t["kind"] for t in res["transitions"]] == ["alert.fired"]
    # Second pass, still firing: no new transition, since_wall sticks.
    res2 = eng.evaluate(report=report, now=110.0)
    assert res2["transitions"] == []
    assert res2["alerts"][0]["since_wall"] == 100.0
    # A NEW engine (one-shot CLI pattern) sees the persisted state and
    # journals the resolve.
    report["totals"]["counters"]["backlog"] = 3
    eng2 = alerts.AlertEngine(alerts.load_rules(_write_rules(
        os.path.join(root, "r.json"), rules)), root)
    res3 = eng2.evaluate(report=report, now=120.0)
    assert res3["firing"] == []
    assert [t["kind"] for t in res3["transitions"]] == ["alert.resolved"]
    events, torn = alerts.read_alert_events(root)
    assert torn == 0
    assert [(e["kind"], e["args"]["rule"]) for e in events] == \
        [("alert.fired", "backlog"), ("alert.resolved", "backlog")]


def test_alert_wildcard_report_path(tmp_path):
    rules = [{"name": "worst-beat", "type": "threshold",
              "metric": "hosts.*.heartbeat_age_s", "op": ">", "value": 60}]
    report = {"hosts": {"a": {"heartbeat_age_s": 5.0},
                        "b": {"heartbeat_age_s": 120.0}}}
    res = alerts.AlertEngine(rules, str(tmp_path)).evaluate(
        report=report, now=0.0)
    assert res["firing"] == ["worst-beat"]
    assert res["alerts"][0]["value"] == 120.0


def test_alert_rate_rule_windows(tmp_path):
    root = str(tmp_path)
    now = 1000.0
    # 10 units at t=950, 10 at t=990: rate depends on the window.
    _mk_series(root, "h1", [
        {"wall": 950.0, "mono": 0, "pid": 1, "d": {"units_total": 10.0}},
        {"wall": 990.0, "mono": 1, "pid": 1, "d": {"units_total": 10.0}},
    ])
    report = {"hosts": {}, "totals": {"counters": {}}}
    fast = [{"name": "r", "type": "rate", "metric": "units_total",
             "window_s": 60, "op": ">", "value": 0.3}]
    res = alerts.AlertEngine(fast, root).evaluate(report=report, now=now)
    assert res["firing"] == ["r"]  # 20 units / 40s span = 0.5/s
    narrow = [{"name": "r", "type": "rate", "metric": "units_total",
               "window_s": 20, "op": ">", "value": 0.3}]
    res = alerts.AlertEngine(narrow, root).evaluate(report=report, now=now)
    # Only the t=990 point is inside; a single point's span floors at
    # the 1 s heartbeat-ish minimum, so 10 units read as 10/s.
    assert res["alerts"][0]["value"] == pytest.approx(10.0)
    cold = [{"name": "r", "type": "rate", "metric": "units_total",
             "window_s": 60, "op": ">", "value": 0.3}]
    res = alerts.AlertEngine(cold, root).evaluate(
        report=report, now=now + 10_000)
    assert res["firing"] == []  # window empty -> rate 0


def test_alert_rate_tolerates_torn_series_tail(tmp_path):
    root = str(tmp_path)
    d = os.path.join(root, ".telemetry", "h1")
    os.makedirs(d)
    line = json.dumps({"wall": 990.0, "mono": 0, "pid": 1,
                       "d": {"units_total": 30.0}})
    with open(os.path.join(d, "series-pid1.jsonl"), "w") as f:
        f.write(line + "\n" + line[:17])  # torn tail = end of stream
    rules = [{"name": "r", "type": "rate", "metric": "units_total",
              "window_s": 60, "op": ">", "value": 0.1}]
    res = alerts.AlertEngine(rules, root).evaluate(
        report={"hosts": {}}, now=1000.0, warn=lambda *a: None)
    assert res["firing"] == ["r"]
    assert res["alerts"][0].get("error") is None


def test_alert_absence_fires_then_resolves(clean_telemetry, tmp_path):
    root = str(tmp_path)
    rules = [{"name": "no-loader", "type": "absence",
              "metric": "loader_batches_total"}]
    report = {"hosts": {}}
    eng = alerts.AlertEngine(rules, root)
    res = eng.evaluate(report=report, now=100.0)
    assert res["firing"] == ["no-loader"]
    # The metric appearing in a holder snapshot resolves it.
    spool = fleet.configure(root, holder_id="h1", ttl=30, interval=3600)
    assert spool
    obs.inc("loader_batches_total", 5)
    fleet.heartbeat()
    res = eng.evaluate(report=report, now=110.0)
    assert res["firing"] == []
    assert [t["kind"] for t in res["transitions"]] == ["alert.resolved"]
    # windowed absence: no series point inside the window re-fires it.
    windowed = [{"name": "no-loader", "type": "absence",
                 "metric": "loader_batches_total", "window_s": 30}]
    res = alerts.AlertEngine(windowed, root).evaluate(
        report=report, now=time.time() + 10_000.0)
    assert res["firing"] == ["no-loader"]


def test_alert_bad_metric_is_error_not_crash(tmp_path):
    rules = [{"name": "weird", "type": "threshold",
              "metric": "no.such.path", "op": ">", "value": 1}]
    res = alerts.AlertEngine(rules, str(tmp_path)).evaluate(
        report={"hosts": {}}, now=0.0)
    # Unresolvable threshold metric = not firing (absence is the rule
    # type that alarms on missing data).
    assert res["firing"] == [] and res["alerts"][0]["value"] is None


def test_alerts_fired_counter_increments(clean_telemetry, tmp_path):
    root = str(tmp_path)
    os.environ["LDDL_TPU_METRICS_DIR"] = str(tmp_path / "m")
    rules = [{"name": "hot", "type": "threshold",
              "metric": "totals.counters.x", "op": ">", "value": 1}]
    alerts.AlertEngine(rules, root).evaluate(
        report={"totals": {"counters": {"x": 5}}, "hosts": {}}, now=0.0)
    snap = obs.registry().snapshot()
    assert snap[alerts.FIRED_COUNTER]["values"]["rule=hot"] == 1


# --------------------------------------------------- status CLI + rollup


def test_pipeline_status_window_alerts_and_backend(clean_telemetry,
                                                   tmp_path, capsys):
    from tools import pipeline_status

    root = str(tmp_path)
    fleet.configure(root, holder_id="cli", ttl=30, interval=3600)
    obs.inc("elastic_units_completed_total", 4, phase="gather")
    stage = attribution.stage_counter()
    stage.inc(0.6, stage="shard_read")
    stage.inc(0.8, stage="batch_wait")
    stage.inc(0.2, stage="step_gap")
    fleet.heartbeat(closed=True)

    rules = _write_rules(os.path.join(root, "rules.json"), [
        {"name": "trip", "type": "threshold",
         "metric": "totals.counters.units_completed", "op": "<",
         "value": 100},
    ])
    rc = pipeline_status.main([root, "--json", "--window", "120",
                               "--alerts", rules])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2  # healthy, but the tripped alert forces exit 2
    assert doc["health"]["ok"]
    assert doc["alerts"]["firing"] == ["trip"]
    assert doc["attribution"]["verdict"] == "input-bound"
    assert any(k.startswith("backend_ops_total")
               for k in doc["window"]["rates"])
    assert doc["backend"]["ops"]  # snapshot writes counted put ops
    assert any(lbl.startswith("backend=")
               for lbl in doc["backend"]["latency"])
    win = doc["hosts"]["cli"]["window"]
    assert win["rates"].get(
        "loader_stage_seconds_total{stage=shard_read}") == \
        pytest.approx(0.6 / win["span_s"])

    # Resolving rule -> exit 0, resolve journaled as a fleet-style event.
    _write_rules(rules, [
        {"name": "trip", "type": "threshold",
         "metric": "totals.counters.units_completed", "op": "<",
         "value": 0}])
    rc = pipeline_status.main([root, "--json", "--alerts", rules])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["alerts"]["firing"] == []
    events, _ = alerts.read_alert_events(root)
    assert [e["kind"] for e in events] == ["alert.fired",
                                           "alert.resolved"]
    assert all("wall" in e and "mono" in e and "pid" in e for e in events)

    # Text mode renders the verdict, sparkline window and alert rows.
    rc = pipeline_status.main([root, "--window", "120"])
    text = capsys.readouterr().out
    assert rc == 0
    assert "loader bound verdict: input-bound" in text
    assert "window: last 120s" in text


def test_backend_latency_histogram_from_io_ops(clean_telemetry, tmp_path):
    from lddl_tpu.resilience import io as rio

    os.environ["LDDL_TPU_METRICS_DIR"] = str(tmp_path / "m")
    p = str(tmp_path / "f.bin")
    rio.atomic_write(p, b"payload")
    assert rio.read_bytes(p) == b"payload"
    assert rio.list_dir(str(tmp_path)) is not None
    rio.remove(p)
    snap = obs.registry().snapshot()
    lat = snap["backend_op_latency_seconds"]
    assert lat["type"] == "histogram"
    ops = {lbl.split("op=")[1].split(",")[0] for lbl in lat["values"]}
    assert {"put", "get", "list", "delete"} <= ops
    for stats in lat["values"].values():
        assert stats["count"] >= 1 and stats["sum"] >= 0.0


# ------------------------------------------------ SIGTERM series flushing

_SIGTERM_SERIES_DRIVER = """
import os, sys, time
root = sys.argv[1]
os.environ["LDDL_TPU_FLEET_DIR"] = root
os.environ["LDDL_TPU_FLEET_HOLDER"] = "sender"
os.environ["LDDL_TPU_FLEET_INTERVAL_S"] = "3600"  # only exit paths flush
from lddl_tpu.observability import fleet
import lddl_tpu.observability as obs
fleet.ensure_started()
obs.inc("units_total", 7)
from lddl_tpu.observability import attribution
attribution.stage_counter().inc(0.25, stage="decode")
print("READY", flush=True)
time.sleep(120)
"""


def test_sigterm_flushes_series_segments(tmp_path):
    """Series history must ride the same abnormal-exit flush as the
    snapshot: with the heartbeat parked for an hour, only the SIGTERM
    handler can have published these points."""
    root = str(tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    for name in _ENVS:
        env.pop(name, None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _SIGTERM_SERIES_DRIVER, root], env=env,
        cwd=REPO_ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    assert proc.stdout.readline().strip() == "READY"
    proc.send_signal(signal.SIGTERM)
    out = proc.communicate(timeout=60)[0]
    assert proc.returncode == -signal.SIGTERM, out
    points, torn = series.read_series(root, "sender")
    assert torn == 0
    deltas = {}
    for p in points:
        for k, v in p.get("d", {}).items():
            deltas[k] = deltas.get(k, 0.0) + v
    assert deltas.get("units_total") == 7
    assert deltas.get(
        attribution.STAGE_METRIC + "{stage=decode}") == pytest.approx(0.25)
