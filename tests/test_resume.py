"""Preprocess fault tolerance + resume: unit ledger, worker-death retry,
byte-identical completion (VERDICT r2 #7).
"""

import json
import os
import signal
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu.preprocess.runner import run_sharded_pipeline  # noqa: E402


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("resume")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def goldens():
    with open(gs.GOLDEN_FILE) as f:
        return json.load(f)


class _FailOnce:
    """process_bucket wrapper that raises for chosen buckets unless a flag
    file exists (so the resume run succeeds). Picklable for spawn pools."""

    def __init__(self, inner, fail_buckets, flag_path):
        self.inner = inner
        self.fail_buckets = set(fail_buckets)
        self.flag_path = flag_path

    def __call__(self, texts, bucket):
        if bucket in self.fail_buckets and not os.path.exists(self.flag_path):
            raise RuntimeError("injected failure for bucket {}".format(bucket))
        return self.inner(texts, bucket)

    def fingerprint(self):
        # Delegate so the manifest records the REAL processor digest —
        # the mismatch tests must pin fingerprint() field sensitivity,
        # not wrapper-vs-raw inequality.
        return self.inner.fingerprint()


class _KillOnce:
    """SIGKILLs its own worker process for one bucket on the first attempt
    (flag file marks the kill as spent) — simulates OOM-kill/preemption."""

    def __init__(self, inner, kill_bucket, flag_path):
        self.inner = inner
        self.kill_bucket = kill_bucket
        self.flag_path = flag_path

    def __call__(self, texts, bucket):
        if bucket == self.kill_bucket and not os.path.exists(self.flag_path):
            with open(self.flag_path, "w") as f:
                f.write("killed\n")
            os.kill(os.getpid(), signal.SIGKILL)
        return self.inner(texts, bucket)


def _bert_processor(vocab, out_dir):
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    from lddl_tpu.preprocess.runner import BertBucketProcessor
    tok = get_tokenizer(vocab_file=vocab)
    # schema_version=1: these tests compare against the pinned v1 golden
    # bytes (see tests/golden_spool.py — resume semantics are
    # schema-independent).
    cfg = BertPretrainConfig(max_seq_length=32, masking=True,
                             schema_version=1)
    return BertBucketProcessor(tok, cfg, 4242, out_dir, 8, "parquet")


_RUN_KW = dict(num_blocks=12, sample_ratio=0.9, seed=4242,
               global_shuffle=True, progress_interval=0.0)


def test_failed_unit_is_isolated_then_resumed(fixture_dirs, goldens,
                                              tmp_path):
    """A raising unit fails the run AFTER healthy units complete; --resume
    with the failure cleared redoes only the failed units and the final
    shards are byte-identical to a clean run (the pinned goldens)."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "fixed.flag")
    proc = _FailOnce(_bert_processor(vocab, out), [3, 7], flag)

    with pytest.raises(RuntimeError, match="re-run with resume"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, **_RUN_KW)
    # Healthy units completed and were journaled before the raise.
    ledgers = [n for n in os.listdir(os.path.join(out, "_done"))
               if n.startswith("group-")]
    assert len(ledgers) == 12 - 2

    with open(flag, "w") as f:
        f.write("ok\n")
    run_sharded_pipeline({"wikipedia": corpus}, out, proc, resume=True,
                         **_RUN_KW)
    assert not os.path.isdir(os.path.join(out, "_done"))  # cleaned up
    assert gs.hash_outputs(out) == goldens["binned_masked"]


def test_worker_sigkill_retried_in_run(fixture_dirs, goldens, tmp_path):
    """kill -9 of a pool worker mid-run: the pool is rebuilt and the unit
    retried inside the SAME run; output is byte-identical to the golden."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "killed.flag")
    proc = _KillOnce(_bert_processor(vocab, out), 5, flag)

    run_sharded_pipeline({"wikipedia": corpus}, out, proc, num_workers=2,
                         **_RUN_KW)
    assert os.path.exists(flag)  # the kill really happened
    assert gs.hash_outputs(out) == goldens["binned_masked"]


class _KillAlwaysUntilFlag(_KillOnce):
    """Kills the worker on every attempt until the flag file appears."""

    def __call__(self, texts, bucket):
        if bucket == self.kill_bucket and not os.path.exists(self.flag_path):
            os.kill(os.getpid(), signal.SIGKILL)
        return self.inner(texts, bucket)


def test_worker_sigkill_exhausted_then_resume(fixture_dirs, goldens,
                                              tmp_path):
    """If a unit keeps killing its worker it is marked failed (max
    attempts), the run raises, and a later resume completes it."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "fixed.flag")
    proc = _KillAlwaysUntilFlag(_bert_processor(vocab, out), 5, flag)

    with pytest.raises(RuntimeError, match="re-run with resume"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, num_workers=2,
                             **_RUN_KW)
    with open(flag, "w") as f:
        f.write("ok\n")
    run_sharded_pipeline({"wikipedia": corpus}, out, proc, num_workers=2,
                         resume=True, **_RUN_KW)
    assert gs.hash_outputs(out) == goldens["binned_masked"]


def test_resume_with_incomplete_scatter_redoes_scatter(fixture_dirs, goldens,
                                                       tmp_path):
    """A run killed during scatter leaves no completion marker; resume must
    wipe the partial spool, redo the scatter, and still match the golden."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _bert_processor(vocab, out)

    # Simulate a dead run: half-written spool, no marker, no ledger.
    spool = os.path.join(out, "_shuffle", "group-0")
    os.makedirs(spool)
    with open(os.path.join(spool, "w0-999.txt"), "w") as f:
        f.write("0 0 doc-torn torn line from a dead writer\n")

    run_sharded_pipeline({"wikipedia": corpus}, out, proc, resume=True,
                         **_RUN_KW)
    assert gs.hash_outputs(out) == goldens["binned_masked"]


def test_resume_refuses_mismatched_arguments(fixture_dirs, tmp_path):
    """Resuming with a different unit plan (num_blocks/spool_groups/seed)
    must refuse loudly: ledger ids would denote different bucket sets."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "never.flag")
    proc = _FailOnce(_bert_processor(vocab, out), [3], flag)
    with pytest.raises(RuntimeError, match="re-run with resume"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, **_RUN_KW)
    bad = dict(_RUN_KW, num_blocks=24)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, resume=True,
                             **bad)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, resume=True,
                             **dict(_RUN_KW, seed=999))


def test_resume_refuses_changed_corpus_or_processor_config(fixture_dirs,
                                                           tmp_path):
    """Unit identity is not enough: resuming with a different corpus, bin
    width, masking config or vocab would pass the old unit-plan check yet
    mix shards from two incompatible configurations (ADVICE round 3)."""
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    from lddl_tpu.preprocess.runner import BertBucketProcessor
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "never.flag")
    proc = _FailOnce(_bert_processor(vocab, out), [3], flag)
    with pytest.raises(RuntimeError, match="re-run with resume"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, **_RUN_KW)

    # Different corpus paths, same unit plan.
    other_corpus = os.path.join(str(tmp_path), "other_corpus")
    os.makedirs(os.path.join(other_corpus, "source"))
    with open(os.path.join(other_corpus, "source", "0.txt"), "w") as f:
        f.write("doc-0 Completely different corpus. Same block plan.\n")
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": other_corpus}, out, proc,
                             resume=True, **_RUN_KW)

    # Different processor parameters (bin width), same unit plan.
    tok = get_tokenizer(vocab_file=vocab)
    cfg = BertPretrainConfig(max_seq_length=32, masking=True)
    rebinned = BertBucketProcessor(tok, cfg, 4242, out, 16, "parquet")
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": corpus}, out, rebinned,
                             resume=True, **_RUN_KW)

    # Different masking config, same unit plan.
    cfg2 = BertPretrainConfig(max_seq_length=32, masking=False)
    remasked = BertBucketProcessor(tok, cfg2, 4242, out, 8, "parquet")
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": corpus}, out, remasked,
                             resume=True, **_RUN_KW)


def test_resume_refuses_same_size_vocab_swap(fixture_dirs, tmp_path):
    """A same-size in-place token swap must refuse resume (VERDICT r4:
    the old digest memo was keyed by vocab SIZE on the mutable tokenizer
    object, so exactly this mutation hit a stale cache). The digest now
    hashes the TokenizerInfo snapshot, so any content change refuses."""
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    from lddl_tpu.preprocess.runner import BertBucketProcessor
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "never.flag")
    proc = _FailOnce(_bert_processor(vocab, out), [3], flag)
    with pytest.raises(RuntimeError, match="re-run with resume"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, **_RUN_KW)

    # Same vocab SIZE, one ordinary token replaced in place.
    with open(vocab) as f:
        tokens = f.read().splitlines()
    swap_at = max(i for i, t in enumerate(tokens)
                  if not (t.startswith("[") and t.endswith("]")))
    tokens[swap_at] = "swappedtoken"
    swapped = str(tmp_path / "vocab_swapped.txt")
    with open(swapped, "w") as f:
        f.write("\n".join(tokens) + "\n")
    tok = get_tokenizer(vocab_file=swapped)
    assert len(tok) == len(get_tokenizer(vocab_file=vocab))
    cfg = BertPretrainConfig(max_seq_length=32, masking=True)
    reproc = BertBucketProcessor(tok, cfg, 4242, out, 8, "parquet")
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": corpus}, out, reproc,
                             resume=True, **_RUN_KW)


def test_vocab_digest_ignores_stale_tokenizer_cache(fixture_dirs, tmp_path):
    """Guard against regressing to the round-4 scheme: a digest cached on
    the tokenizer OBJECT can outlive an in-place vocab mutation. The
    fingerprint must derive from the TokenizerInfo snapshot and ignore
    any attribute planted on the tokenizer."""
    td, corpus, vocab = fixture_dirs
    proc = _bert_processor(vocab, str(tmp_path / "o1"))
    fp = proc.fingerprint()
    # Plant a stale same-size cache entry where the old code kept it.
    proc.tokenizer._lddl_tpu_vocab_digest = (len(proc.tokenizer),
                                             "deadbeefdeadbeef")
    assert proc.fingerprint() == fp


def test_fresh_dir_refuses_without_resume(fixture_dirs, tmp_path):
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _bert_processor(vocab, out)
    run_sharded_pipeline({"wikipedia": corpus}, out, proc, **_RUN_KW)
    with pytest.raises(ValueError, match="resume"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc, **_RUN_KW)
