"""Streaming ingestion: journal diffing, incremental generations, the
delta balancer's invariants, crash-resume byte identity, and
generation-aware loading.

The load-bearing guarantees pinned here:

- untouched prior shards stay byte-identical across N incremental rounds
  (carryover mode never opens them for write);
- the ±1 sample-count invariant holds across generations, per bin;
- an incremental directory that lived through crashes, resumes, and
  reversed filesystem enumeration is byte-identical — shards AND batch
  streams (unbinned/binned/packed) — to a clean from-scratch replay of
  the same ingest sequence;
- a loader in follow mode picks up a newly published generation at the
  next epoch boundary without restart;
- growing directories invalidate only the affected .num_samples.json
  entries, never forcing a full re-count.
"""

import hashlib
import json
import os
import shutil
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu.balance import delta as delta_mod  # noqa: E402
from lddl_tpu.ingest import (Journal, diff_landing,  # noqa: E402
                             doc_content_hash, ingest_once)
from lddl_tpu.ingest import journal as journal_mod  # noqa: E402
from lddl_tpu.resilience import faults  # noqa: E402
from lddl_tpu.utils.fs import (  # noqa: E402
    get_all_parquets_under,
    get_bin_id_of_path,
    get_generation_of_path,
    get_num_samples_of_parquet,
    read_num_samples_cache,
    trusted_num_samples_entries,
    write_num_samples_cache,
)


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("ingest")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def tok(fixture_dirs):
    from lddl_tpu.preprocess import get_tokenizer
    return get_tokenizer(vocab_file=fixture_dirs[2])


def _config(**kw):
    from lddl_tpu.preprocess import BertPretrainConfig
    kw.setdefault("max_seq_length", 32)
    kw.setdefault("masking", False)
    return BertPretrainConfig(**kw)


def _landing(base, corpus, n_files, name="landing"):
    """A landing dir holding the first ``n_files`` corpus source shards
    (the growing-corpus simulation: each round adds one file)."""
    d = os.path.join(base, name, "source")
    os.makedirs(d, exist_ok=True)
    for i in range(n_files):
        shutil.copy(os.path.join(corpus, "source", "{}.txt".format(i)),
                    os.path.join(d, "{}.txt".format(i)))
    return os.path.join(base, name)


def _shard_hashes(root):
    return {os.path.relpath(p, root):
            hashlib.sha256(open(p, "rb").read()).hexdigest()
            for p in get_all_parquets_under(root)}


def _bin_counts(root):
    by_bin = {}
    for p in get_all_parquets_under(root):
        by_bin.setdefault(get_bin_id_of_path(p), []).append(
            get_num_samples_of_parquet(p))
    return by_bin


def _assert_balanced(root):
    for b, counts in _bin_counts(root).items():
        assert max(counts) - min(counts) <= 1, (b, sorted(counts))


def _batches(loader):
    out = []
    for batch in loader:
        out.append({k: np.asarray(v).copy() for k, v in batch.items()})
    return out


def _assert_same_batches(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k], err_msg=k)


# ------------------------------------------------------------ journal unit


def test_doc_content_hash_is_content_only():
    assert doc_content_hash(b"hello world") == doc_content_hash("hello world")
    assert doc_content_hash(b"a") != doc_content_hash(b"b")


def test_diff_landing_dedups_by_content(tmp_path):
    d = tmp_path / "land" / "source"
    d.mkdir(parents=True)
    (d / "a.txt").write_text("d1 same text\nd2 other text\n")
    (d / "b.txt").write_text("d3 same text\n")  # duplicate content, new id
    j = Journal(str(tmp_path / "root"))
    docs, stats = diff_landing(j, landing=str(tmp_path / "land"))
    assert stats["docs_seen"] == 3
    assert len(docs) == 2  # content identity collapses the duplicate
    j.entries[doc_content_hash(b"other text")] = 0
    docs, _ = diff_landing(j, landing=str(tmp_path / "land"))
    assert len(docs) == 1


def test_torn_journal_cache_degrades_to_segment_rescan(tmp_path):
    root = str(tmp_path)
    j = Journal(root)
    j.publish_generation(0, ["h1", "h2"], "fp")
    j.publish_generation(1, ["h3"], "fp", carry={"unbinned": "c.parquet"})
    # Tear the compaction cache; the segments must reconstruct the union.
    cache = os.path.join(journal_mod.ingest_root(root), "journal.json")
    with open(cache, "w") as f:
        f.write('{"entries": {"h1"')
    j2 = Journal.load(root)
    assert j2.entries == {"h1": 0, "h2": 0, "h3": 1}
    assert j2.generation == 1
    assert j2.carry == {"unbinned": "c.parquet"}


def test_torn_journal_read_fault_site(tmp_path):
    """The dedicated journal-read truncate fault downgrades a clean cache
    read to torn -> segment rescan, proving the chaos harness can reach
    exactly this degradation."""
    root = str(tmp_path)
    j = Journal(root)
    j.publish_generation(0, ["h1"], "fp")
    faults.arm("journal-read:truncate:nth=1:path=journal.json")
    try:
        j2 = Journal.load(root)
    finally:
        faults.disarm()
    assert j2.entries == {"h1": 0}


def test_torn_segment_is_fatal(tmp_path):
    root = str(tmp_path)
    j = Journal(root)
    j.publish_generation(0, ["h1"], "fp")
    seg = journal_mod.segment_path(root, 0)
    with open(seg, "w") as f:
        f.write('{"generation"')
    os.remove(os.path.join(journal_mod.ingest_root(root), "journal.json"))
    with pytest.raises(ValueError, match="torn or unparseable"):
        Journal.load(root)


def test_missing_segment_is_fatal(tmp_path):
    """A lost (not merely torn) segment must stop the rescan loudly: its
    hashes are absent from the union, so ingesting on top would silently
    re-ingest those documents as duplicates."""
    root = str(tmp_path)
    j = Journal(root)
    j.publish_generation(0, ["h1"], "fp")
    j.publish_generation(1, ["h2"], "fp")
    j.publish_generation(2, ["h3"], "fp")
    os.remove(journal_mod.segment_path(root, 1))
    os.remove(os.path.join(journal_mod.ingest_root(root), "journal.json"))
    with pytest.raises(ValueError, match=r"generation\(s\) \[1\] are "
                                         r"missing"):
        Journal.load(root)


def test_journal_bytes_are_content_hash_only(tmp_path):
    """Journal bytes must be a pure function of ingested content: no
    wall-clock, pids, or FS order (the manifest-determinism analyzer rule
    guards the builders; this pins the actual bytes)."""
    payloads = []
    for sub in ("a", "b"):
        root = str(tmp_path / sub)
        j = Journal(root)
        j.publish_generation(0, ["h2", "h1"], "fp")  # unsorted on purpose
        with open(journal_mod.segment_path(root, 0), "rb") as f:
            payloads.append(f.read())
    assert payloads[0] == payloads[1]
    assert json.loads(payloads[0])["hashes"] == ["h1", "h2"]


# ------------------------------------------------------- delta plan math


def test_plan_bin_delta_arithmetic():
    # m=100: 250 rows -> 2 new shards (first takes the +1... no: 250 =
    # 2*100 + 50; plus_new = min(50, 2) = 2, carry = 48.
    assert delta_mod.plan_bin_delta([100, 100, 101], 250) == (100, 2, 2, 48)
    # Exactly one shard's worth: no carry.
    assert delta_mod.plan_bin_delta([100], 100) == (100, 1, 0, 0)
    # Less than one shard's worth: everything carries.
    assert delta_mod.plan_bin_delta([100, 100], 60) == (100, 0, 0, 60)
    with pytest.raises(ValueError, match="not balanced"):
        delta_mod.plan_bin_delta([100, 102], 10)


def test_plan_flush_picks_cheaper_move():
    # carry 2 vs pull 98: absorb wins, touches 2 shards at m.
    assert delta_mod.plan_flush([100] * 10, 100, 2) == ("absorb", 2)
    # carry 98 vs pull 2: pull wins, touches 2 shards at m+1.
    assert delta_mod.plan_flush([101] * 10, 100, 98) == ("pull", 2)
    # Neither feasible: 4 shards cannot place 50 leftover rows ±1-wise.
    with pytest.raises(ValueError, match="cannot flush"):
        delta_mod.plan_flush([100, 100, 101, 101], 100, 50)


# ------------------------------------------------- incremental generations


KW = dict(num_shards=4, seed=7)


def test_gen0_classic_layout_and_journal(fixture_dirs, tok, tmp_path):
    td, corpus, vocab = fixture_dirs
    root = str(tmp_path / "root")
    rep = ingest_once(root, tok, landing=_landing(str(tmp_path), corpus, 2),
                      config=_config(), **KW)
    assert not rep["noop"] and rep["generation"] == 0
    names = sorted(os.path.basename(p) for p in get_all_parquets_under(root))
    assert names == ["shard-0.parquet", "shard-1.parquet",
                     "shard-2.parquet", "shard-3.parquet"]
    _assert_balanced(root)
    from lddl_tpu.resilience.integrity import read_manifest
    meta = read_manifest(root)["__meta__"]
    assert meta["generation"] == 0
    assert meta["generations"]["0"] == names
    cache = read_num_samples_cache(root)
    assert set(cache["__sizes__"]) == set(names)
    j = Journal.load(root)
    assert j.generation == 0 and rep["docs"] == len(j.entries)


def test_incremental_rounds_untouched_bytes(fixture_dirs, tok, tmp_path):
    """N incremental rounds: prior shards byte-identical after every
    round, ±1 holds across generations, re-scan is a no-op."""
    td, corpus, vocab = fixture_dirs
    root = str(tmp_path / "root")
    base = str(tmp_path)
    prior_hashes = {}
    for n_files in (1, 2, 3):
        rep = ingest_once(root, tok,
                          landing=_landing(base, corpus, n_files),
                          config=_config(), **KW)
        assert not rep["noop"]
        assert rep["touched_prior_shards"] == []
        hashes = _shard_hashes(root)
        for rel, digest in prior_hashes.items():
            assert hashes[rel] == digest, "prior shard rewritten: " + rel
        prior_hashes = hashes
        _assert_balanced(root)
    rep = ingest_once(root, tok, landing=_landing(base, corpus, 3),
                      config=_config(), **KW)
    assert rep["noop"]
    # Every generation seen so far is in the manifest meta.
    from lddl_tpu.resilience.integrity import read_manifest
    meta = read_manifest(root)["__meta__"]
    assert meta["generation"] == Journal.load(root).generation
    gens = {get_generation_of_path(root, p)
            for p in get_all_parquets_under(root)}
    assert 0 in gens and len(gens) >= 2


def test_carryover_defers_and_later_flushes(fixture_dirs, tok, tmp_path):
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    # Generation 0 consumes everything (classic balance: no carry); the
    # generation-1 delta leaves a sub-shard remainder in carryover.
    ingest_once(root, tok, landing=_landing(base, corpus, 1),
                config=_config(), **KW)
    ingest_once(root, tok, landing=_landing(base, corpus, 2),
                config=_config(), **KW)
    j = Journal.load(root)
    carried = sum(
        get_num_samples_of_parquet(
            os.path.join(journal_mod.carry_dir(root), name))
        for name in j.carry.values())
    journaled_docs = len(j.entries)
    visible = sum(sum(c) for c in _bin_counts(root).values())
    assert carried > 0, "fixture should leave a carryover remainder"
    h_before = _shard_hashes(root)
    # Flush with no new documents: a carry-only generation.
    rep = ingest_once(root, tok, landing=_landing(base, corpus, 2),
                      config=_config(), flush_tail=True, **KW)
    assert not rep["noop"] and rep["docs"] == 0
    assert rep["carry_rows"] == 0
    assert not Journal.load(root).carry
    _assert_balanced(root)
    visible_after = sum(sum(c) for c in _bin_counts(root).values())
    assert visible_after == visible + carried
    # Untouched shards (not in the touched set) kept their bytes.
    h_after = _shard_hashes(root)
    for rel in h_before:
        if rel not in rep["touched_prior_shards"]:
            assert h_after.get(rel) == h_before[rel], rel
    assert len(Journal.load(root).entries) == journaled_docs


def test_binned_generations(fixture_dirs, tok, tmp_path):
    """Binned ingest: per-bin budgets, per-bin carry, prior untouched."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    kw = dict(num_shards=2, seed=7, bin_size=16)
    cfg = _config(masking=True)
    ingest_once(root, tok, landing=_landing(base, corpus, 2), config=cfg,
                **kw)
    h1 = _shard_hashes(root)
    _assert_balanced(root)
    rep = ingest_once(root, tok, landing=_landing(base, corpus, 3),
                      config=cfg, **kw)
    assert not rep["noop"] and rep["touched_prior_shards"] == []
    h2 = _shard_hashes(root)
    assert all(h2[k] == h1[k] for k in h1)
    _assert_balanced(root)
    # The binned loader streams the multi-generation directory whole.
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5)
    n = sum(len(b["input_ids"]) for b in loader)
    assert n > 0


def test_adoption_of_existing_balanced_dir(fixture_dirs, tok, tmp_path):
    """A classic offline-balanced directory grows via ingest: the root is
    adopted as generation 0 (bytes untouched), deltas append."""
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.preprocess import run_bert_preprocess
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    pre = str(tmp_path / "pre")
    root = str(tmp_path / "root")
    run_bert_preprocess({"wikipedia": _landing(base, corpus, 2)}, pre, tok,
                        config=_config(), num_blocks=4, sample_ratio=1.0,
                        seed=7)
    balance_shards(pre, root, 4)
    h_before = _shard_hashes(root)
    rep = ingest_once(root, tok, landing=_landing(base, corpus, 3),
                      config=_config(), **KW)
    assert not rep["noop"] and rep["generation"] == 1
    h_after = _shard_hashes(root)
    assert all(h_after[k] == h_before[k] for k in h_before)
    _assert_balanced(root)
    j = Journal.load(root)
    # Adoption journals generation 0 with no documents: only the delta's
    # docs are deduplicated from here on.
    assert j.generation == 1
    assert 0 not in set(j.entries.values()) or not j.entries


def test_config_drift_refused(fixture_dirs, tok, tmp_path):
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    ingest_once(root, tok, landing=_landing(base, corpus, 1),
                config=_config(), **KW)
    with pytest.raises(ValueError, match="drift"):
        ingest_once(root, tok, landing=_landing(base, corpus, 2),
                    config=_config(), num_shards=4, seed=8)


def test_explicit_file_list(fixture_dirs, tok, tmp_path):
    td, corpus, vocab = fixture_dirs
    root = str(tmp_path / "root")
    files = [os.path.join(corpus, "source", "0.txt")]
    rep = ingest_once(root, tok, files=files, config=_config(), **KW)
    assert not rep["noop"]
    rep = ingest_once(root, tok, files=files, config=_config(), **KW)
    assert rep["noop"]


# ---------------------------------------------- crash / replay equivalence


def _replay(root, tok, base, corpus, rounds, **kw):
    # One landing dir PER replay target: _landing only ever adds files,
    # so sharing one would leak a later round's files into another
    # target's earlier round.
    name = "landing-" + os.path.basename(root)
    for n_files in rounds:
        ingest_once(root, tok,
                    landing=_landing(base, corpus, n_files, name=name),
                    config=_config(), **kw)


def test_crash_and_fs_order_equivalence(fixture_dirs, tok, tmp_path,
                                        monkeypatch):
    """The acceptance pin: an incremental directory that crashed at the
    intake publish, crashed at the journal commit, was resumed, and ran
    one round under REVERSED filesystem enumeration is byte-identical —
    shards, manifests, journal segments, and every batch stream
    (unbinned, packed) — to a clean from-scratch replay of the same
    ingest sequence."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    clean = str(tmp_path / "clean")
    dirty = str(tmp_path / "dirty")
    _replay(clean, tok, base, corpus, (1, 2, 3), **KW)

    # Round 1 (gen 0): clean.
    _replay(dirty, tok, base, corpus, (1,), **KW)
    # Round 2: die at the final journal-segment commit, then resume.
    faults.arm("journal-publish:eio:nth=1:path=journal/gen-0001")
    with pytest.raises(OSError):
        _replay(dirty, tok, base, corpus, (2,), **KW)
    faults.disarm()
    _replay(dirty, tok, base, corpus, (2,), **KW)
    # Round 3: die at the intake publish (before any work), then resume
    # with filesystem enumeration REVERSED end to end.
    faults.arm("journal-publish:eio:nth=1:path=intake")
    with pytest.raises(OSError):
        _replay(dirty, tok, base, corpus, (3,), **KW)
    faults.disarm()
    real_walk, real_listdir = os.walk, os.listdir

    def reversed_walk(top, **kwargs):
        for dirpath, dirnames, filenames in real_walk(top, **kwargs):
            rd = list(reversed(sorted(dirnames)))
            yield dirpath, rd, list(reversed(sorted(filenames)))
            # Propagate the consumer's in-place pruning (e.g. the
            # hidden-dir filter in get_all_files_paths_under) back to
            # the real walker, like os.walk itself would honor it.
            dirnames[:] = rd

    monkeypatch.setattr(os, "walk", reversed_walk)
    monkeypatch.setattr(
        os, "listdir",
        lambda p=".": list(reversed(sorted(real_listdir(p)))))
    _replay(dirty, tok, base, corpus, (3,), **KW)
    monkeypatch.undo()

    assert _shard_hashes(dirty) == _shard_hashes(clean)
    for rel in (".manifest.json", ".num_samples.json",
                os.path.join(".ingest", "journal.json")):
        with open(os.path.join(clean, rel), "rb") as f:
            want = f.read()
        with open(os.path.join(dirty, rel), "rb") as f:
            assert f.read() == want, rel

    from lddl_tpu.loader import get_bert_pretrain_data_loader
    for kwargs in (
            dict(batch_size=16),
            dict(batch_size=16, pack_seq_length=64, pack_rows=4)):
        a = _batches(get_bert_pretrain_data_loader(
            clean, vocab_file=vocab, base_seed=5, **kwargs))
        b = _batches(get_bert_pretrain_data_loader(
            dirty, vocab_file=vocab, base_seed=5, **kwargs))
        _assert_same_batches(a, b)


def test_join_pending_generation_completes_crashed_round(fixture_dirs, tok,
                                                         tmp_path):
    """The autoscaler's helper mode end to end: an elastic ingest round
    dies mid-preprocess AFTER the intake record froze the doc set; a
    join_pending_generation helper finishes the generation's elastic
    preprocess from the journal alone (no landing scan, no journal
    commit); the primary's resume then publishes the round, and the
    bytes match a clean replay."""
    from lddl_tpu.ingest import join_pending_generation
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    clean = str(tmp_path / "clean")
    dirty = str(tmp_path / "dirty")
    _replay(clean, tok, base, corpus, (1, 2), **KW)

    # Nothing in flight yet: the helper refuses politely.
    _replay(dirty, tok, base, corpus, (1,), **KW)
    rep = join_pending_generation(dirty, tok, config=_config())
    assert rep["joined"] is False

    landing = _landing(base, corpus, 2, name="landing-dirty")
    faults.arm("sink-write:eio:p=1.0")
    try:
        with pytest.raises(RuntimeError, match="re-run with resume"):
            ingest_once(dirty, tok, landing=landing, config=_config(),
                        elastic=True, lease_ttl=5.0, holder_id="primary",
                        **KW)
    finally:
        faults.disarm()

    rep = join_pending_generation(dirty, tok, config=_config(),
                                  lease_ttl=5.0, holder_id="helper")
    assert rep["joined"] is True and rep["generation"] == 1
    # The helper never commits the journal: the round is still pending.
    assert Journal.load(dirty).pending_work() is not None
    # A second helper finds the preprocess already finalized.
    rep = join_pending_generation(dirty, tok, config=_config(),
                                  lease_ttl=5.0, holder_id="helper2")
    assert rep["joined"] is False

    # Config drift refuses exactly like a mismatched resume.
    with pytest.raises(ValueError, match="fingerprint"):
        join_pending_generation(dirty, tok,
                                config=_config(duplicate_factor=2))

    ingest_once(dirty, tok, landing=landing, config=_config(),
                elastic=True, lease_ttl=5.0, holder_id="primary", **KW)
    assert _shard_hashes(dirty) == _shard_hashes(clean)


KWP = dict(num_shards=4, seed=7, pack_seq_length=64, pack_max_per_row=8)


def test_packed_generation_append_byte_identity(fixture_dirs, tok, tmp_path,
                                                monkeypatch):
    """Packed corpora grow by generations too (the delta balancer is
    row-wise over packed rows): an offline-packed gen-0 directory that
    took a generation append through a journal-commit crash + resume
    under REVERSED filesystem enumeration is byte-identical — shards,
    manifests, journal — to a clean from-scratch replay, and the packed
    batch streams (the loader's auto-detected zero-copy path) match."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    clean = str(tmp_path / "clean")
    dirty = str(tmp_path / "dirty")
    _replay(clean, tok, base, corpus, (1, 2), **KWP)
    _replay(dirty, tok, base, corpus, (1,), **KWP)
    faults.arm("journal-publish:eio:nth=1:path=journal/gen-0001")
    with pytest.raises(OSError):
        _replay(dirty, tok, base, corpus, (2,), **KWP)
    faults.disarm()
    real_walk, real_listdir = os.walk, os.listdir

    def reversed_walk(top, **kwargs):
        for dirpath, dirnames, filenames in real_walk(top, **kwargs):
            rd = list(reversed(sorted(dirnames)))
            yield dirpath, rd, list(reversed(sorted(filenames)))
            dirnames[:] = rd

    monkeypatch.setattr(os, "walk", reversed_walk)
    monkeypatch.setattr(
        os, "listdir",
        lambda p=".": list(reversed(sorted(real_listdir(p)))))
    _replay(dirty, tok, base, corpus, (2,), **KWP)
    monkeypatch.undo()

    assert _shard_hashes(dirty) == _shard_hashes(clean)
    for rel in (".manifest.json", ".num_samples.json",
                os.path.join(".ingest", "journal.json")):
        with open(os.path.join(clean, rel), "rb") as f:
            want = f.read()
        with open(os.path.join(dirty, rel), "rb") as f:
            assert f.read() == want, rel
    meta = json.load(open(os.path.join(clean, ".manifest.json")))["__meta__"]
    assert meta["packed"] == {"pack_seq_length": 64, "pack_max_per_row": 8}

    from lddl_tpu.loader import get_bert_pretrain_data_loader
    from lddl_tpu.loader.bert import BertPrepackedCollate
    loaders = [get_bert_pretrain_data_loader(d, vocab_file=vocab,
                                             base_seed=5, batch_size=4)
               for d in (clean, dirty)]
    assert all(isinstance(ldr._collate_fn, BertPrepackedCollate)
               for ldr in loaders)
    a, b = (_batches(ldr) for ldr in loaders)
    _assert_same_batches(a, b)


def test_crash_after_staging_republish_is_idempotent(fixture_dirs, tok,
                                                     tmp_path):
    """A crash between the balance plan marker and the journal commit
    re-enters at the publish phase: staged bytes are copied again and the
    end state is byte-identical to the uninterrupted run."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    clean = str(tmp_path / "clean")
    dirty = str(tmp_path / "dirty")
    _replay(clean, tok, base, corpus, (2, 3), **KW)
    _replay(dirty, tok, base, corpus, (2,), **KW)
    # Fail the SECOND journal-publish of the round (the segment commit
    # happens after the staged publish + bookkeeping refresh).
    faults.arm("journal-publish:eio:nth=1:path=journal/gen-0001")
    with pytest.raises(OSError):
        _replay(dirty, tok, base, corpus, (3,), **KW)
    faults.disarm()
    # The plan marker exists: the resume must SKIP restaging.
    wdir = journal_mod.work_dir(dirty, 1)
    assert delta_mod.read_plan(os.path.join(wdir, "balance")) is not None
    _replay(dirty, tok, base, corpus, (3,), **KW)
    assert not os.path.isdir(wdir)
    assert _shard_hashes(dirty) == _shard_hashes(clean)


# ----------------------------------------------- generation-aware loading


def test_loader_picks_up_generation_at_epoch_boundary(fixture_dirs, tok,
                                                      tmp_path):
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    _replay(root, tok, base, corpus, (2,), **KW)
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5,
        follow_generations=True)
    e0 = _batches(loader)
    _replay(root, tok, base, corpus, (3,), **KW)
    e1 = _batches(loader)  # next epoch boundary: new generation visible
    n0 = sum(len(b["input_ids"]) for b in e0)
    n1 = sum(len(b["input_ids"]) for b in e1)
    assert n1 > n0
    # The grown epoch is reproducible: a fresh loader started at the same
    # epoch index over the same directory yields identical batches.
    loader2 = get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5, start_epoch=1,
        follow_generations=True)
    _assert_same_batches(e1, _batches(loader2))


def test_loader_process_mode_respawns_pool_on_generation(fixture_dirs, tok,
                                                         tmp_path):
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    _replay(root, tok, base, corpus, (2,), **KW)
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5,
        follow_generations=True, worker_mode="process")
    try:
        n0 = sum(len(b["input_ids"]) for b in loader)
        procs0 = list(loader._procs)
        _replay(root, tok, base, corpus, (3,), **KW)
        n1 = sum(len(b["input_ids"]) for b in loader)
        assert n1 > n0
        # The persistent pool was respawned so workers re-pickled the
        # refreshed dataset (stale pickled copies would miss the new
        # generation's files).
        assert loader._procs is not None
        assert all(p not in procs0 for p in loader._procs)
    finally:
        loader.shutdown_workers()


def test_mid_publish_generation_is_gated(fixture_dirs, tok, tmp_path):
    """Shards of a generation whose root-manifest gate has not advanced
    yet (a publish in flight) are invisible to a follow-mode loader."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    _replay(root, tok, base, corpus, (2, 3), **KW)
    # Roll the gate back to generation 0: the loader must serve only the
    # root generation even though gen-0001 files exist on disk.
    from lddl_tpu.resilience.integrity import MANIFEST_NAME
    path = os.path.join(root, MANIFEST_NAME)
    with open(path) as f:
        manifest = json.load(f)
    manifest["__meta__"]["generation"] = 0
    with open(path, "w") as f:
        json.dump(manifest, f)
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        root, vocab_file=vocab, batch_size=8, base_seed=5,
        follow_generations=True)
    assert all(get_generation_of_path(root, f.path) == 0
               for f in loader.dataset._files)


# ------------------------------------------- growing-dir cache staleness


def test_trusted_entries_per_entry_invalidation(tmp_path):
    d = str(tmp_path)
    for name, payload in (("shard-0.parquet", b"aaaa"),
                          ("shard-1.parquet", b"bbbbbb")):
        with open(os.path.join(d, name), "wb") as f:
            f.write(payload)
    write_num_samples_cache(d, {"shard-0.parquet": 10,
                                "shard-1.parquet": 11}, with_sizes=True)
    cache = read_num_samples_cache(d)
    trusted, untrusted = trusted_num_samples_entries(d, cache)
    assert trusted == {"shard-0.parquet": 10, "shard-1.parquet": 11}
    assert untrusted == set()
    # Rewrite one shard (size changes): ONLY that entry is distrusted.
    with open(os.path.join(d, "shard-1.parquet"), "wb") as f:
        f.write(b"ccccccccc")
    trusted, untrusted = trusted_num_samples_entries(d, cache)
    assert trusted == {"shard-0.parquet": 10}
    assert untrusted == {"shard-1.parquet"}
    # A new file (appended generation style) is untrusted, others keep.
    with open(os.path.join(d, "shard-2.parquet"), "wb") as f:
        f.write(b"dd")
    trusted, untrusted = trusted_num_samples_entries(d, cache)
    assert "shard-0.parquet" in trusted
    assert untrusted == {"shard-1.parquet", "shard-2.parquet"}


def test_legacy_cache_stays_all_or_nothing(tmp_path):
    d = str(tmp_path)
    with open(os.path.join(d, "shard-0.parquet"), "wb") as f:
        f.write(b"x")
    legacy = {"shard-0.parquet": 5}
    trusted, untrusted = trusted_num_samples_entries(d, legacy)
    assert trusted == legacy and not untrusted
    # Key-set mismatch distrusts the WHOLE legacy cache (old contract).
    with open(os.path.join(d, "shard-1.parquet"), "wb") as f:
        f.write(b"y")
    trusted, untrusted = trusted_num_samples_entries(d, legacy)
    assert trusted == {} and untrusted == {"shard-0.parquet",
                                           "shard-1.parquet"}


def test_census_recounts_only_untrusted_entries(fixture_dirs, tok, tmp_path,
                                                monkeypatch):
    """Appending a generation must not force a full re-count: the loader
    census reads footers only for entries the sized cache cannot vouch
    for."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    root = str(tmp_path / "root")
    _replay(root, tok, base, corpus, (2, 3), **KW)
    # Invalidate ONE root entry by lying about its size.
    cache = read_num_samples_cache(root)
    victim = sorted(n for n in cache if n.endswith(".parquet"))[0]
    cache["__sizes__"][victim] += 1
    with open(os.path.join(root, ".num_samples.json"), "w") as f:
        json.dump(cache, f)

    import lddl_tpu.loader.datasets as datasets_mod
    calls = []
    real = datasets_mod.get_num_samples_of_parquet
    monkeypatch.setattr(
        datasets_mod, "get_num_samples_of_parquet",
        lambda p: calls.append(p) or real(p))
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    get_bert_pretrain_data_loader(root, vocab_file=vocab, batch_size=8,
                                  base_seed=5)
    assert [os.path.basename(p) for p in calls] == [victim]


# --------------------------------------------------------------- CLI


def test_ingest_watch_cli_once(fixture_dirs, tmp_path, capsys):
    td, corpus, vocab = fixture_dirs
    from lddl_tpu.cli.ingest_watch import attach_args, main
    base = str(tmp_path)
    root = str(tmp_path / "root")
    argv = ["--landing", _landing(base, corpus, 2), "--sink", root,
            "--vocab-file", vocab, "--target-seq-length", "32",
            "--num-shards", "4", "--seed", "7", "--duplicate-factor", "5",
            "--once"]
    main(attach_args().parse_args(argv))
    out = capsys.readouterr().out
    assert "'generation': 0" in out
    _assert_balanced(root)
    main(attach_args().parse_args(argv))
    assert "'noop': True" in capsys.readouterr().out
