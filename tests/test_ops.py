"""TPU ops: batch masking kernels (numpy + jax engines), packing."""

import numpy as np
import pytest

from lddl_tpu.ops import (
    mask_batch_numpy,
    make_jax_masker,
    pad_to_bucket,
    plan_num_to_predict,
    round_up,
)
from lddl_tpu.utils import rng as lrng


def _setup(n=64, L=128, vocab=1000, seed=0):
    g = np.random.default_rng(seed)
    lens = g.integers(10, L, n)
    ids = g.integers(10, vocab, (n, L)).astype(np.int32)
    valid = np.arange(L)[None, :] < lens[:, None]
    candidate = valid.copy()
    candidate[:, 0] = False  # "[CLS]"
    return ids, candidate, lens


def _check_masking(orig, masked, selected, candidate, num_to_predict,
                   mask_id, vocab):
    # Only candidates get selected; selection count = min(budget, cands).
    assert not (selected & ~candidate).any()
    want = np.minimum(num_to_predict, candidate.sum(axis=1))
    np.testing.assert_array_equal(selected.sum(axis=1), want)
    # Unselected positions unchanged.
    assert (masked[~selected] == orig[~selected]).all()
    # Action stats over all selected positions.
    n_mask = (masked[selected] == mask_id).sum()
    n_keep = (masked[selected] == orig[selected]).sum()
    total = selected.sum()
    assert 0.72 < n_mask / total < 0.88
    assert 0.04 < n_keep / total < 0.18


def test_mask_batch_numpy():
    ids, candidate, lens = _setup(n=256)
    num = plan_num_to_predict(lens, 0.15, 20)
    g = lrng.sample_rng(1, 2)
    masked, selected = mask_batch_numpy(ids, candidate, num, g, 3, 1000)
    _check_masking(ids, masked, selected, candidate, num, 3, 1000)
    # Deterministic.
    masked2, selected2 = mask_batch_numpy(
        ids, candidate, num, lrng.sample_rng(1, 2), 3, 1000)
    np.testing.assert_array_equal(masked, masked2)


def test_mask_batch_jax():
    ids, candidate, lens = _setup(n=256)
    num = plan_num_to_predict(lens, 0.15, 20)
    masker = make_jax_masker(3, 1000)
    masked, selected = masker(ids, candidate, num, seed=7)
    _check_masking(ids, masked, selected, candidate, num, 3, 1000)
    masked2, _ = masker(ids, candidate, num, seed=7)
    np.testing.assert_array_equal(masked, masked2)
    masked3, _ = masker(ids, candidate, num, seed=8)
    assert not np.array_equal(masked, masked3)


def test_plan_num_to_predict():
    np.testing.assert_array_equal(
        plan_num_to_predict([100, 10, 1, 500], 0.15, 20), [15, 2, 1, 20])


def test_pad_to_bucket():
    ids, valid = pad_to_bucket([[1, 2, 3], [4] * 200], pad_id=0,
                               length_multiple=128)
    assert ids.shape == (2, 256)
    assert valid[0].sum() == 3 and valid[1].sum() == 200
    assert ids[0, 3:].sum() == 0
    assert round_up(1, 128) == 128 and round_up(129, 128) == 256


def test_engine_parity_e2e(tmp_path, tiny_corpus):
    """numpy and jax engines produce structurally-identical shard sets
    (same pairs; only the mask randomness differs)."""
    from lddl_tpu.preprocess import (BertPretrainConfig, build_wordpiece_vocab,
                                     get_tokenizer, run_bert_preprocess)
    from lddl_tpu.utils.fs import get_all_parquets_under
    import pyarrow.parquet as pq

    vocab = build_wordpiece_vocab(
        ["alpha beta gamma delta epsilon zeta eta theta iota kappa"] * 3,
        str(tmp_path / "v.txt"), vocab_size=200)
    tok = get_tokenizer(vocab_file=vocab)
    outs = {}
    for engine in ("numpy", "jax"):
        out = str(tmp_path / engine)
        run_bert_preprocess(
            {"w": tiny_corpus}, out, tok,
            config=BertPretrainConfig(max_seq_length=64, duplicate_factor=1,
                                      masking=True, engine=engine),
            num_blocks=2, sample_ratio=1.0, seed=0, bin_size=16)
        outs[engine] = {
            p: pq.read_table(p).to_pylist()
            for p in get_all_parquets_under(out)
        }
    npy = [r for t in outs["numpy"].values() for r in t]
    jx = [r for t in outs["jax"].values() for r in t]
    assert len(npy) == len(jx) > 0
    # Pair structure identical: same (num_tokens, is_random_next) multiset.
    key = lambda r: (r["num_tokens"], r["is_random_next"])
    assert sorted(map(key, npy)) == sorted(map(key, jx))
    # Both engines actually masked.
    assert any(r["masked_lm_labels"] for r in npy)
    assert any(r["masked_lm_labels"] for r in jx)
