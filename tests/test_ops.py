"""TPU ops: batch masking kernels (numpy + jax engines), packing."""

import numpy as np
import pytest

from lddl_tpu.ops import (
    mask_batch_numpy,
    make_jax_masker,
    pad_to_bucket,
    plan_num_to_predict,
    round_up,
)
from lddl_tpu.utils import rng as lrng


def _setup(n=64, L=128, vocab=1000, seed=0):
    g = np.random.default_rng(seed)
    lens = g.integers(10, L, n)
    ids = g.integers(10, vocab, (n, L)).astype(np.int32)
    valid = np.arange(L)[None, :] < lens[:, None]
    candidate = valid.copy()
    candidate[:, 0] = False  # "[CLS]"
    return ids, candidate, lens


def _check_masking(orig, masked, selected, candidate, num_to_predict,
                   mask_id, vocab):
    # Only candidates get selected; selection count = min(budget, cands).
    assert not (selected & ~candidate).any()
    want = np.minimum(num_to_predict, candidate.sum(axis=1))
    np.testing.assert_array_equal(selected.sum(axis=1), want)
    # Unselected positions unchanged.
    assert (masked[~selected] == orig[~selected]).all()
    # Action stats over all selected positions.
    n_mask = (masked[selected] == mask_id).sum()
    n_keep = (masked[selected] == orig[selected]).sum()
    total = selected.sum()
    assert 0.72 < n_mask / total < 0.88
    assert 0.04 < n_keep / total < 0.18


def test_mask_batch_numpy():
    ids, candidate, lens = _setup(n=256)
    num = plan_num_to_predict(lens, 0.15, 20)
    g = lrng.sample_rng(1, 2)
    masked, selected = mask_batch_numpy(ids, candidate, num, g, 3, 1000)
    _check_masking(ids, masked, selected, candidate, num, 3, 1000)
    # Deterministic.
    masked2, selected2 = mask_batch_numpy(
        ids, candidate, num, lrng.sample_rng(1, 2), 3, 1000)
    np.testing.assert_array_equal(masked, masked2)


def test_mask_batch_jax():
    ids, candidate, lens = _setup(n=256)
    num = plan_num_to_predict(lens, 0.15, 20)
    masker = make_jax_masker(3, 1000)
    masked, selected = masker(ids, candidate, num, seed=7)
    _check_masking(ids, masked, selected, candidate, num, 3, 1000)
    masked2, _ = masker(ids, candidate, num, seed=7)
    np.testing.assert_array_equal(masked, masked2)
    masked3, _ = masker(ids, candidate, num, seed=8)
    assert not np.array_equal(masked, masked3)


def test_plan_num_to_predict():
    np.testing.assert_array_equal(
        plan_num_to_predict([100, 10, 1, 500], 0.15, 20), [15, 2, 1, 20])


def test_pad_to_bucket():
    ids, valid = pad_to_bucket([[1, 2, 3], [4] * 200], pad_id=0,
                               length_multiple=128)
    assert ids.shape == (2, 256)
    assert valid[0].sum() == 3 and valid[1].sum() == 200
    assert ids[0, 3:].sum() == 0
    assert round_up(1, 128) == 128 and round_up(129, 128) == 256


def test_engine_parity_e2e(tmp_path, tiny_corpus):
    """numpy and jax engines produce structurally-identical shard sets
    (same pairs; only the mask randomness differs)."""
    from lddl_tpu.preprocess import (BertPretrainConfig, build_wordpiece_vocab,
                                     get_tokenizer, run_bert_preprocess)
    from lddl_tpu.utils.fs import get_all_parquets_under
    import pyarrow.parquet as pq

    vocab = build_wordpiece_vocab(
        ["alpha beta gamma delta epsilon zeta eta theta iota kappa"] * 3,
        str(tmp_path / "v.txt"), vocab_size=200)
    tok = get_tokenizer(vocab_file=vocab)
    outs = {}
    for engine in ("numpy", "jax"):
        out = str(tmp_path / engine)
        run_bert_preprocess(
            {"w": tiny_corpus}, out, tok,
            config=BertPretrainConfig(max_seq_length=64, duplicate_factor=1,
                                      masking=True, engine=engine),
            num_blocks=2, sample_ratio=1.0, seed=0, bin_size=16)
        outs[engine] = {
            p: pq.read_table(p).to_pylist()
            for p in get_all_parquets_under(out)
        }
    npy = [r for t in outs["numpy"].values() for r in t]
    jx = [r for t in outs["jax"].values() for r in t]
    assert len(npy) == len(jx) > 0
    # Pair structure identical: same (num_tokens, is_random_next) multiset.
    key = lambda r: (r["num_tokens"], r["is_random_next"])
    assert sorted(map(key, npy)) == sorted(map(key, jx))
    # Both engines actually masked.
    assert any(r["masked_lm_labels"] for r in npy)
    assert any(r["masked_lm_labels"] for r in jx)


def _wwm_row_oracle(ids, candidate, num_to_predict, g, mask_id, vocab,
                    is_subword):
    """Per-row whole-word masking consuming the SAME frozen draw contract
    as mask_whole_word_batch_numpy (scores/action/random_ids matrices), so
    parity is bit-exact."""
    n, L = ids.shape
    scores = g.random(ids.shape)
    action = g.random(ids.shape)
    random_ids = g.integers(0, vocab, ids.shape,
                            dtype=np.int64).astype(np.int32)
    out = ids.copy()
    selected = np.zeros_like(candidate)
    for r in range(n):
        cols = np.nonzero(candidate[r])[0]
        groups = []
        for c in cols:
            if groups and is_subword[ids[r, c]] and groups[-1][-1] == c - 1:
                groups[-1].append(int(c))
            else:
                groups.append([int(c)])
        gscores = [scores[r, grp[0]] for grp in groups]
        order = np.argsort(gscores, kind="stable")
        budget = int(num_to_predict[r])
        taken = 0
        for gi in order:
            grp = groups[gi]
            if taken >= budget:
                break
            if taken + len(grp) > budget:
                continue
            for c in grp:
                if action[r, c] < 0.8:
                    out[r, c] = mask_id
                elif action[r, c] < 0.9:
                    out[r, c] = random_ids[r, c]
                selected[r, c] = True
                taken += 1
    return out, selected


def _wwm_setup(n=128, L=96, vocab=1000, seed=3, sub_frac=0.3):
    g = np.random.default_rng(seed)
    ids, candidate, lens = _setup(n=n, L=L, vocab=vocab, seed=seed)
    # Mark a fraction of the vocab as subword continuations so real
    # multi-token groups form.
    is_subword = g.random(vocab) < sub_frac
    is_subword[:10] = False  # specials never continue a word
    return ids, candidate, lens, is_subword


def test_mask_whole_word_batch_matches_row_oracle():
    from lddl_tpu.ops import mask_whole_word_batch_numpy
    ids, candidate, lens, is_subword = _wwm_setup(n=256)
    num = plan_num_to_predict(lens, 0.15, 20)
    masked, selected = mask_whole_word_batch_numpy(
        ids, candidate, num, lrng.sample_rng(5, 1), 3, 1000, is_subword)
    ref_masked, ref_selected = _wwm_row_oracle(
        ids, candidate, num, lrng.sample_rng(5, 1), 3, 1000, is_subword)
    np.testing.assert_array_equal(selected, ref_selected)
    np.testing.assert_array_equal(masked, ref_masked)


def _check_wwm_invariants(ids, candidate, is_subword, selected, num):
    # Budget respected.
    assert (selected.sum(axis=1) <= num).all()
    # Whole words selected atomically: selection state constant per group.
    for r in range(ids.shape[0]):
        cols = np.nonzero(candidate[r])[0]
        prev = None
        for c in cols:
            if prev is not None and prev == c - 1 and is_subword[ids[r, c]]:
                assert selected[r, c] == selected[r, c - 1]
            prev = c
    # Only candidates selected.
    assert not (selected & ~candidate).any()


def test_mask_whole_word_batch_invariants():
    from lddl_tpu.ops import mask_whole_word_batch_numpy
    ids, candidate, lens, is_subword = _wwm_setup(n=128)
    num = plan_num_to_predict(lens, 0.15, 20)
    masked, selected = mask_whole_word_batch_numpy(
        ids, candidate, num, lrng.sample_rng(5, 2), 3, 1000, is_subword)
    _check_wwm_invariants(ids, candidate, is_subword, selected, num)
    assert selected.sum() > 0
    # Unselected positions unchanged.
    assert (masked[~selected] == ids[~selected]).all()


def test_mask_whole_word_jax():
    from lddl_tpu.ops import make_jax_whole_word_masker
    ids, candidate, lens, is_subword = _wwm_setup(n=64, L=64)
    num = plan_num_to_predict(lens, 0.15, 20)
    masker = make_jax_whole_word_masker(3, 1000, is_subword)
    masked, selected = masker(ids, candidate, num, seed=11)
    _check_wwm_invariants(ids, candidate, is_subword, selected, num)
    assert selected.sum() > 0
    assert (masked[~selected] == ids[~selected]).all()
    masked2, _ = masker(ids, candidate, num, seed=11)
    np.testing.assert_array_equal(masked, masked2)
    masked3, _ = masker(ids, candidate, num, seed=12)
    assert not np.array_equal(masked, masked3)


def test_wwm_e2e_both_engines(tmp_path, tiny_corpus):
    """whole_word_masking runs through both engines end-to-end with
    identical pair structure."""
    from lddl_tpu.preprocess import (BertPretrainConfig, build_wordpiece_vocab,
                                     get_tokenizer, run_bert_preprocess)
    from lddl_tpu.utils.fs import get_all_parquets_under
    import pyarrow.parquet as pq

    vocab = build_wordpiece_vocab(
        ["alpha beta gamma delta epsilon zeta eta theta iota kappa"] * 3,
        str(tmp_path / "v.txt"), vocab_size=60)  # small -> real subwords
    tok = get_tokenizer(vocab_file=vocab)
    outs = {}
    for engine in ("numpy", "jax"):
        out = str(tmp_path / engine)
        run_bert_preprocess(
            {"w": tiny_corpus}, out, tok,
            config=BertPretrainConfig(max_seq_length=64, duplicate_factor=1,
                                      masking=True, engine=engine,
                                      whole_word_masking=True),
            num_blocks=2, sample_ratio=1.0, seed=0, bin_size=16)
        outs[engine] = [r for p in get_all_parquets_under(out)
                        for r in pq.read_table(p).to_pylist()]
    npy, jx = outs["numpy"], outs["jax"]
    assert len(npy) == len(jx) > 0
    key = lambda r: (r["num_tokens"], r["is_random_next"])
    assert sorted(map(key, npy)) == sorted(map(key, jx))
    assert any(r["masked_lm_labels"] for r in npy)
    assert any(r["masked_lm_labels"] for r in jx)


def test_mask_batch_numpy_degenerate_inputs():
    """num_to_predict beyond the row width selects every candidate (the
    rank-based behavior); an empty batch returns empty outputs."""
    g = np.random.default_rng(4)
    ids = g.integers(10, 1000, (6, 6)).astype(np.int32)
    candidate = np.ones((6, 6), dtype=bool)
    candidate[:, 0] = False
    num = np.full(6, 8, dtype=np.int32)  # > L
    masked, selected = mask_batch_numpy(ids, candidate, num,
                                        lrng.sample_rng(2, 9), 3, 1000)
    np.testing.assert_array_equal(selected, candidate)
    empty_ids = np.zeros((0, 8), np.int32)
    empty_cand = np.zeros((0, 8), bool)
    m, s = mask_batch_numpy(empty_ids, empty_cand,
                            np.zeros(0, np.int32),
                            lrng.sample_rng(2, 10), 3, 1000)
    assert m.shape == (0, 8) and s.shape == (0, 8)
