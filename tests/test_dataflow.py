"""The interprocedural dataflow engine (lddl_tpu/analysis/{project,
dataflow,flow_rules}).

Layers:

1. Project model — import/name resolution across modules, relative
   imports, re-export chains, method binding.
2. Fixture corpus — for EACH of the four flow rules: at least one
   interprocedural true positive its syntactic ancestor cannot see
   (the laundering helper lives in another function/file) and at least
   one sanitized case that must stay silent.
3. Integration — suppressions and the baseline apply to flow findings
   exactly as to syntactic ones; same-function (non-crossing) flows are
   left to the syntactic rules.
4. The cache — content-hash hits skip re-analysis; editing one file
   recomputes its facts AND its dependents' findings while untouched
   files are served from cache.
"""

import ast
import textwrap

from lddl_tpu import analysis
from lddl_tpu.analysis import dataflow, flow_rules, project


def write_tree(root, files):
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))


def run_tree(tmp_path, files, rules=None, cache=False, **kw):
    write_tree(tmp_path, files)
    top = sorted({rel.split("/")[0] for rel in files})
    return analysis.run_check(
        top, root=str(tmp_path), baseline_path=kw.pop("baseline_path", ""),
        rules=analysis.get_rules(rules) if rules else None,
        cache_path=str(tmp_path / "cache.json") if cache else None, **kw)


def flow_findings(report, rule=None):
    out = [f for f in report.new if f.rule.endswith("-flow")]
    if rule:
        out = [f for f in out if f.rule == rule]
    return out


# ---------------------------------------------------------- project model


def test_module_name_mapping():
    assert project.module_name_of("lddl_tpu/utils/fs.py") == \
        "lddl_tpu.utils.fs"
    assert project.module_name_of("lddl_tpu/analysis/__init__.py") == \
        "lddl_tpu.analysis"
    assert project.module_name_of("tools/lddl_check.py") == \
        "tools.lddl_check"


def test_relative_import_and_reexport_resolution():
    proj = project.build_project({
        "pkg/__init__.py": "from .impl import helper\n",
        "pkg/impl.py": "def helper():\n    return 1\n",
        "pkg/sub/user.py": ("from .. import impl\n"
                            "from ..impl import helper as h2\n"
                            "import pkg\n"
                            "def a():\n    return impl.helper()\n"
                            "def b():\n    return h2()\n"
                            "def c():\n    return pkg.helper()\n"),
    })
    user = proj.modules_by_path["pkg/sub/user.py"]
    target = "pkg.impl.helper"
    for dotted in ("pkg.impl.helper", "pkg.helper"):
        fi = proj.resolve_function(user, dotted)
        assert fi is not None and fi.qualname == target, dotted
    # Aliased from-import resolves through the alias map.
    assert proj.resolve_dotted(
        user, ast.parse("h2").body[0].value) == "pkg.impl.helper"


def test_self_method_resolution():
    proj = project.build_project({
        "pkg/mod.py": ("class C:\n"
                       "    def helper(self):\n        return 1\n"
                       "    def run(self):\n"
                       "        return self.helper()\n"),
    })
    mod = proj.modules_by_path["pkg/mod.py"]
    fi = proj.resolve_function(mod, "self.helper", cls="C")
    assert fi is not None and fi.qualname == "pkg.mod.C.helper"


# -------------------------------------------- wall-clock-flow fixtures


WALLCLOCK_HELPER = """
    import time
    import os

    def now_tag():
        return "run-{}".format(time.time())

    def pid_of():
        return os.getpid()

    def fixed_tag(version):
        return "run-{}".format(version)
"""


def test_wall_clock_flow_interprocedural_true_positive(tmp_path):
    """A clock value laundered through a helper in an ALLOWLISTED file
    (tracing legitimately reads clocks; the observability allowlist
    names files individually so autoscale.py stays checked) reaching
    manifest content — invisible to the syntactic wall-clock rule, which
    never fires in the allowlisted file and sees no time.* at the
    manifest call site."""
    report = run_tree(tmp_path, {
        "lddl_tpu/observability/tracing.py": WALLCLOCK_HELPER,
        "lddl_tpu/balance/manifest.py": """
            from ..observability.tracing import now_tag

            def build_manifest(names):
                return {"tag": now_tag(), "shards": sorted(names)}
        """,
    })
    [f] = flow_findings(report, "wall-clock-flow")
    assert f.path == "lddl_tpu/balance/manifest.py"
    assert "time.time" in f.message and "now_tag" in f.message
    # The syntactic ancestor indeed misses it.
    assert not any(f.rule == "wall-clock" for f in report.new)
    assert not any(f.rule == "manifest-determinism" for f in report.new)


def test_wall_clock_flow_publish_argument_sink(tmp_path):
    """A pid flowing into an atomic_write PATH argument: the published
    NAME would differ across ranks even though the write is atomic."""
    report = run_tree(tmp_path, {
        "lddl_tpu/observability/stamp.py": WALLCLOCK_HELPER,
        "lddl_tpu/preprocess/sink.py": """
            from ..resilience.io import atomic_write
            from ..observability.stamp import pid_of

            def publish(out_dir, data):
                atomic_write(out_dir + "/shard-{}.json".format(pid_of()),
                             data)
        """,
        "lddl_tpu/resilience/io.py": "def atomic_write(path, data):\n"
                                     "    raise NotImplementedError\n",
    }, rules=["wall-clock-flow"])
    [f] = flow_findings(report, "wall-clock-flow")
    assert "os.getpid" in f.message and "atomic_write" in f.message


def test_wall_clock_flow_sanitized_false_positive(tmp_path):
    """A helper returning a value built from its (deterministic) argument
    must NOT taint the manifest: summaries distinguish param passthrough
    from source introduction."""
    report = run_tree(tmp_path, {
        "lddl_tpu/observability/stamp.py": WALLCLOCK_HELPER,
        "lddl_tpu/balance/manifest.py": """
            from ..observability.stamp import fixed_tag

            def build_manifest(names, version):
                return {"tag": fixed_tag(version),
                        "shards": sorted(names)}
        """,
    })
    assert flow_findings(report) == []


# --------------------------------------------------- rng-flow fixtures


RNG_HELPER = """
    import numpy as np

    def thread_rng():
        return np.random.default_rng()

    def keyed_rng(seed):
        return np.random.default_rng(seed)
"""


def test_rng_flow_interprocedural_true_positive(tmp_path):
    """An UNKEYED generator built inside utils/rng.py — the file the
    syntactic global-rng rule ALLOWLISTS (it may construct whatever it
    needs) — escaping to pipeline code that draws from it. Only the flow
    rule can see the draw is unkeyed."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/rng.py": RNG_HELPER,
        "lddl_tpu/loader/pick.py": """
            from ..utils.rng import thread_rng

            def choose(files):
                g = thread_rng()
                g.shuffle(files)
                return files
        """,
    })
    [f] = flow_findings(report, "rng-flow")
    assert f.path == "lddl_tpu/loader/pick.py"
    assert "default_rng" in f.message and "shuffle" in f.message
    assert not any(f.rule == "global-rng" for f in report.new)


def test_rng_flow_keyed_stream_is_clean(tmp_path):
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/rng.py": RNG_HELPER,
        "lddl_tpu/loader/pick.py": """
            from ..utils.rng import keyed_rng

            def choose(files, seed):
                g = keyed_rng(seed)
                g.shuffle(files)
                return files
        """,
    })
    assert flow_findings(report) == []


def test_rng_flow_module_global_generator(tmp_path):
    """Module-global unkeyed RNG state consumed inside a function — the
    flow crosses a scope boundary no per-function rule can see."""
    report = run_tree(tmp_path, {
        "lddl_tpu/loader/jitterbug.py": """
            import random

            _rng = random.Random()

            def pick_delay(base):
                return base * _rng.uniform(0.5, 1.5)
        """,
    })
    [f] = flow_findings(report, "rng-flow")
    assert "module global _rng" in f.message


# --------------------------------------------- fs-order-flow fixtures


FS_HELPER = """
    import os

    def entries(d):
        # raw listing; callers must sort -- lddl: disable=unsorted-iteration
        return os.listdir(d)

    def entries_sorted(d):
        return sorted(os.listdir(d))
"""


def test_fs_order_flow_interprocedural_true_positive(tmp_path):
    """Unsorted listdir escaping through a helper whose own listing is
    SUPPRESSED ("callers must sort") and iterated by a caller that does
    not sort — across files, which the statement-local syntactic rule
    cannot track, and past a producer-side suppression that silences it
    entirely."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/listing.py": FS_HELPER,
        "lddl_tpu/balance/scan.py": """
            from ..utils.listing import entries

            def shards(d):
                out = []
                for n in entries(d):
                    out.append(n)
                return out
        """,
    })
    [f] = flow_findings(report, "fs-order-flow")
    assert f.path == "lddl_tpu/balance/scan.py"
    assert "os.listdir" in f.message and "entries" in f.message
    assert not any(f.rule == "unsorted-iteration" for f in report.new)


def test_fs_order_flow_sink_side_laundering(tmp_path):
    """The DUAL direction: the caller produces the listing and a helper
    iterates it — the finding lands at the call site that handed the
    unsorted value over."""
    report = run_tree(tmp_path, {
        "lddl_tpu/balance/scan.py": """
            import os

            def census(names):
                out = {}
                for n in names:
                    out[n] = 1
                return out

            def run(d):
                return census(os.listdir(d))
        """,
    }, rules=["fs-order-flow"])
    [f] = flow_findings(report, "fs-order-flow")
    assert "census" in f.message


def test_fs_order_flow_sorted_and_reductions_are_clean(tmp_path):
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/listing.py": FS_HELPER,
        "lddl_tpu/balance/scan.py": """
            from ..utils.listing import entries, entries_sorted

            def shards(d):
                return [n for n in entries_sorted(d)]

            def count(d):
                return len(entries(d))

            def uniq(d):
                return set(entries(d))

            def shards2(d):
                return sorted(entries(d))
        """,
    })
    assert flow_findings(report) == []


def test_fs_order_flow_error_text_sink(tmp_path):
    """FS-ordered content rendered into exception text diverges error
    messages across hosts (the PR 4 balancer bug, now cross-function)."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/listing.py": FS_HELPER,
        "lddl_tpu/balance/guard.py": """
            from ..utils.listing import entries

            def refuse_dirty(d):
                stale = entries(d)
                raise ValueError("dirty dir, e.g. {}".format(stale[0]))
        """,
    }, rules=["fs-order-flow"])
    found = flow_findings(report, "fs-order-flow")
    assert found, "indexing/formatting an unsorted listing must flag"


# ------------------------------------------ publish-path-flow fixtures


def test_publish_path_flow_interprocedural_true_positive(tmp_path):
    """A raw write hidden in a helper OUTSIDE the shard packages, invoked
    from preprocess: the syntactic atomic-publish rule scopes write-mode
    open() to shard packages, so only the flow rule can see this."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/textio.py": """
            def write_text(path, text):
                with open(path, "w") as f:
                    f.write(text)
        """,
        "lddl_tpu/preprocess/sink.py": """
            from ..utils.textio import write_text

            def dump(out_dir, rows):
                write_text(out_dir + "/x.txt", rows)
        """,
    })
    [f] = flow_findings(report, "publish-path-flow")
    assert f.path == "lddl_tpu/preprocess/sink.py"
    assert "write_text" in f.message and "open(mode='w')" in f.message
    assert not any(f.rule == "atomic-publish" for f in report.new)


def test_publish_path_flow_transitive_chain(tmp_path):
    """The effect propagates through intermediate helpers."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/textio.py": """
            def _raw(path, text):
                with open(path, "w") as f:
                    f.write(text)

            def write_text(path, text):
                _raw(path, text)
        """,
        "lddl_tpu/balance/sink.py": """
            from ..utils.textio import write_text

            def dump(out_dir, rows):
                write_text(out_dir + "/x.txt", rows)
        """,
    }, rules=["publish-path-flow"])
    [f] = flow_findings(report, "publish-path-flow")
    assert "write_text" in f.message


def test_publish_path_flow_through_async_sink_submit(tmp_path):
    """The writer-thread boundary cannot launder a raw write: a raw-
    writing helper handed to ``writer.submit(...)`` (deferred execution
    on the sink thread) is treated as called at the enqueue site, so the
    publish-path rule still fires in the enqueuing shard-package
    function."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/rawio.py": """
            def raw_dump():
                with open("/out/x.parquet", "w") as f:
                    f.write("bytes")
        """,
        "lddl_tpu/preprocess/sink.py": """
            class ShardWriter:
                def __init__(self):
                    self._q = []

                def submit(self, unit, fn, fence=None):
                    self._q.append((unit, fn, fence))
        """,
        "lddl_tpu/preprocess/runner.py": """
            from ..utils.rawio import raw_dump
            from .sink import ShardWriter

            def gather(out_dir, rows):
                writer = ShardWriter()
                writer.submit(7, raw_dump)
        """,
    }, rules=["publish-path-flow"])
    [f] = flow_findings(report, "publish-path-flow")
    assert f.path == "lddl_tpu/preprocess/runner.py"
    assert "raw_dump" in f.message


def test_publish_path_flow_async_sink_lambda_argument(tmp_path):
    """A lambda enqueued on the sink is walked at the enqueue site: the
    raw write reached through its body is attributed to the enqueuing
    function."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/rawio.py": """
            def raw_dump(path):
                with open(path, "w") as f:
                    f.write("bytes")
        """,
        "lddl_tpu/preprocess/runner.py": """
            from ..utils.rawio import raw_dump

            def gather(writer, out_dir):
                writer.submit(7, lambda: raw_dump(out_dir + "/x.parquet"))
        """,
    }, rules=["publish-path-flow"])
    [f] = flow_findings(report, "publish-path-flow")
    assert f.path == "lddl_tpu/preprocess/runner.py"
    assert "raw_dump" in f.message


def test_publish_path_flow_async_sink_clean_closure_is_silent(tmp_path):
    """The sanctioned pattern — a deferred closure publishing through
    resilience.io — stays silent across the submit boundary."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/io.py": """
            import os

            def write_table_atomic(table, path):
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(table)
                os.replace(tmp, path)
        """,
        "lddl_tpu/preprocess/runner.py": """
            from ..resilience.io import write_table_atomic

            def publish_shard():
                write_table_atomic(b"t", "/out/part.0.parquet")

            def gather(writer):
                writer.submit(7, publish_shard)
        """,
    }, rules=["publish-path-flow"])
    assert flow_findings(report) == []


def test_publish_path_flow_atomic_publisher_is_sanctioned(tmp_path):
    """Calling through resilience.io is THE sanctioned path: no finding,
    even though io.py internally write-opens and os.replaces."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/io.py": """
            import os

            def atomic_write(path, data):
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
        """,
        "lddl_tpu/preprocess/sink.py": """
            from ..resilience.io import atomic_write

            def dump(out_dir, rows):
                atomic_write(out_dir + "/x.txt", rows)
        """,
    }, rules=["publish-path-flow"])
    assert flow_findings(report) == []


def test_publish_path_flow_observability_writes_exempt(tmp_path):
    """Trace/metrics writers never land in shard dirs by construction;
    a shard-package call into them is not a publish violation."""
    report = run_tree(tmp_path, {
        "lddl_tpu/observability/tracing.py": """
            def flush(path, buf):
                with open(path, "a") as f:
                    f.write(buf)
        """,
        "lddl_tpu/preprocess/runner.py": """
            from ..observability.tracing import flush

            def finish(trace_path, buf):
                flush(trace_path, buf)
        """,
    }, rules=["publish-path-flow"])
    assert flow_findings(report) == []


def test_publish_path_flow_fleet_spool_writes_exempt_shard_write_caught(
        tmp_path):
    """The fleet-telemetry spool writers (.telemetry/ event logs and
    snapshots, observability/fleet.py) are non-shard sinks by
    construction: lifecycle emission from the elastic claim loop must not
    read as a publish violation. A raw write laundered through a
    NON-exempt helper on the same call path is still caught — the
    exemption is the module, never the caller."""
    report = run_tree(tmp_path, {
        "lddl_tpu/observability/fleet.py": """
            def flush_events(spool_dir, batch):
                with open(spool_dir + "/events-pid0.jsonl", "a") as f:
                    f.write(batch)
        """,
        "lddl_tpu/utils/rawio.py": """
            def dump(path, data):
                with open(path, "w") as f:
                    f.write(data)
        """,
        "lddl_tpu/preprocess/steal.py": """
            from ..observability.fleet import flush_events
            from ..utils.rawio import dump

            def complete_unit(out_dir, rec):
                flush_events(out_dir + "/.telemetry/h0", rec)
                dump(out_dir + "/part.0.txt", rec)
        """,
    }, rules=["publish-path-flow"])
    findings = flow_findings(report, "publish-path-flow")
    assert len(findings) == 1, [f.format() for f in findings]
    assert findings[0].path == "lddl_tpu/preprocess/steal.py"
    assert "dump" in findings[0].message
    assert "flush_events" not in findings[0].message


# ------------------------------------------ lease-isolation fixtures


def _lease_findings(report):
    return [f for f in report.new if f.rule == "lease-isolation"]


# A minimal stand-in for the real lease module at its real path (the
# engine keys lease sources off dataflow.LEASE_MODULE).
_LEASE_FIXTURE = """
    import json, os, time

    def try_acquire(root, unit, holder, ttl_s):
        rec = {"unit": unit, "holder": holder, "epoch": 0,
               "deadline": time.time() + ttl_s}
        with open(os.path.join(root, unit), "w") as f:
            json.dump(rec, f)
        return rec

    def read_lease(root, unit):
        with open(os.path.join(root, unit)) as f:
            return json.load(f)
"""


def test_lease_isolation_publish_argument_true_positive(tmp_path):
    """Lease state (the epoch) flowing into an atomic_write payload in a
    pipeline module: the exact corruption the fence exists to prevent —
    lease scheduling state reaching published bytes."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/leases.py": _LEASE_FIXTURE,
        "lddl_tpu/resilience/io.py": """
            def atomic_write(path, data):
                return None
        """,
        "lddl_tpu/preprocess/bad.py": """
            from ..resilience import leases
            from ..resilience.io import atomic_write

            def journal(root, unit, out):
                lease = leases.try_acquire(root, unit, "h", 5.0)
                atomic_write(out, str(lease["epoch"]))
        """,
    })
    found = _lease_findings(report)
    assert any(f.path == "lddl_tpu/preprocess/bad.py" for f in found)
    assert any("try_acquire" in f.message for f in found)


def test_lease_isolation_manifest_content_true_positive(tmp_path):
    """Lease state stored into manifest/ledger builder content."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/leases.py": _LEASE_FIXTURE,
        "lddl_tpu/balance/census.py": """
            from ..resilience import leases

            def build_manifest_entry(root, unit):
                lease = leases.read_lease(root, unit)
                entry = {}
                entry["holder"] = lease["holder"]
                return entry
        """,
    })
    found = _lease_findings(report)
    assert any(f.path == "lddl_tpu/balance/census.py" for f in found)


def test_lease_isolation_control_flow_only_is_silent(tmp_path):
    """Using a lease to DECIDE (claim check, fence branch) is the whole
    point; only data flows into published bytes may fire."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/leases.py": _LEASE_FIXTURE,
        "lddl_tpu/resilience/io.py": """
            def atomic_write(path, data):
                return None
        """,
        "lddl_tpu/preprocess/ok.py": """
            from ..resilience import leases
            from ..resilience.io import atomic_write

            def guarded_publish(root, unit, out, data):
                lease = leases.try_acquire(root, unit, "h", 5.0)
                if lease is not None:
                    atomic_write(out, data)
        """,
    })
    assert _lease_findings(report) == []


def test_lease_isolation_lease_module_writes_exempt(tmp_path):
    """The lease module's own publishes ARE lease files — exempt at the
    engine level, so no caller-side or module-side finding fires for the
    protocol's own I/O."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/leases.py": """
            from .io import atomic_write

            def renew(root, unit, holder, epoch, deadline):
                rec = "{}:{}:{}".format(holder, epoch, deadline)
                atomic_write(root + "/" + unit, rec)
        """,
        "lddl_tpu/resilience/io.py": """
            def atomic_write(path, data):
                return None
        """,
        "lddl_tpu/preprocess/user.py": """
            from ..resilience import leases

            def keep_alive(root, unit):
                leases.renew(root, unit, "h", 1, 2.0)
        """,
    }, rules=["lease-isolation"])
    assert _lease_findings(report) == []


def test_lease_isolation_suppression_applies(tmp_path):
    """The one sanctioned epoch-into-record flow pattern (steal.py's
    fence record) silences with a why-commented inline suppression, like
    every other rule."""
    report = run_tree(tmp_path, {
        "lddl_tpu/resilience/leases.py": _LEASE_FIXTURE,
        "lddl_tpu/resilience/io.py": """
            def atomic_write(path, data):
                return None
        """,
        "lddl_tpu/preprocess/steal.py": """
            from ..resilience import leases
            from ..resilience.io import atomic_write

            def journal(root, unit, out):
                lease = leases.try_acquire(root, unit, "h", 5.0)
                # The record IS the epoch fence for spool bytes.
                atomic_write(out, str(lease["epoch"]))  # lddl: disable=lease-isolation,wall-clock-flow
        """,
    })
    assert _lease_findings(report) == []
    assert any(f.rule == "lease-isolation" for f in report.suppressed)


# ------------------------------------------------- framework integration


def test_flow_findings_respect_inline_suppressions(tmp_path):
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/listing.py": FS_HELPER,
        "lddl_tpu/balance/scan.py": """
            from ..utils.listing import entries

            def shards(d):
                # order-insensitive census -- lddl: disable=fs-order-flow
                for n in entries(d):
                    yield n
        """,
    })
    assert flow_findings(report) == []
    assert any(f.rule == "fs-order-flow" for f in report.suppressed)


def test_flow_findings_respect_baseline_and_counts(tmp_path):
    files = {
        "lddl_tpu/utils/listing.py": FS_HELPER,
        "lddl_tpu/balance/scan.py": """
            from ..utils.listing import entries

            def shards(d):
                for n in entries(d):
                    yield n
        """,
    }
    write_tree(tmp_path, files)
    report = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                                baseline_path="")
    [f] = flow_findings(report, "fs-order-flow")
    import json
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps(
        {"entries": [analysis.baseline_entry(f, reason="fixture")]}))
    report = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                                baseline_path=str(baseline))
    assert flow_findings(report) == []
    assert [b.rule for b in report.baselined] == ["fs-order-flow"]


def test_same_function_flow_is_left_to_syntactic_rules(tmp_path):
    """for n in os.listdir(d) in ONE function: unsorted-iteration fires,
    fs-order-flow stays silent — one violation, one finding."""
    report = run_tree(tmp_path, {
        "lddl_tpu/balance/scan.py": """
            import os

            def shards(d):
                return [n for n in os.listdir(d)]
        """,
    })
    assert [f.rule for f in report.new] == ["unsorted-iteration"]


def test_count_aware_baseline_blocks_duplicate_lines():
    """One baseline entry must absorb exactly ONE copy of an identical
    line; a pasted duplicate is a NEW finding (the old matcher let any
    number ride on one entry)."""
    src = ("import os\n"
           "names = os.listdir(d)\n"
           "names = os.listdir(d)\n")
    findings, _ = analysis.analyze_source(src, "lddl_tpu/x.py")
    assert len(findings) == 2
    assert findings[0].key() == findings[1].key()
    entry = analysis.baseline_entry(findings[0], "grandfathered")
    new, old = analysis.split_baselined(findings, [entry])
    assert len(old) == 1 and len(new) == 1
    # count=2 absorbs both; the CLI's --write-baseline emits counts.
    entry2 = analysis.baseline_entry(findings[0], "grandfathered", count=2)
    new, old = analysis.split_baselined(findings, [entry2])
    assert (len(new), len(old)) == (0, 2)


# ----------------------------------------------------------- the cache


CACHE_TREE = {
    "lddl_tpu/utils/listing.py": FS_HELPER,
    "lddl_tpu/balance/scan.py": """
        from ..utils.listing import entries

        def shards(d):
            out = []
            for n in entries(d):
                out.append(n)
            return out
    """,
}


def test_cache_hit_serves_unchanged_files(tmp_path):
    r1 = run_tree(tmp_path, CACHE_TREE, cache=True)
    assert r1.files_cached == 0
    assert len(flow_findings(r1, "fs-order-flow")) == 1
    r2 = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                            baseline_path="",
                            cache_path=str(tmp_path / "cache.json"))
    assert r2.files_cached == r2.files == 2
    # Identical results from a fully-cached run, flow findings included.
    assert [f.format() for f in r2.new] == [f.format() for f in r1.new]


def test_cache_invalidation_recomputes_editee_and_dependents(tmp_path):
    run_tree(tmp_path, CACHE_TREE, cache=True)
    # Fix the HELPER only: its hash changes (re-analyzed), the caller is
    # served from cache, and the caller's finding must still disappear —
    # dependents' findings flow from the recomputed fixpoint, not from
    # stale cached output.
    (tmp_path / "lddl_tpu/utils/listing.py").write_text(textwrap.dedent("""
        import os

        def entries(d):
            return sorted(os.listdir(d))

        def entries_sorted(d):
            return sorted(os.listdir(d))
    """))
    r = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                           baseline_path="",
                           cache_path=str(tmp_path / "cache.json"))
    assert r.files == 2 and r.files_cached == 1  # only the caller cached
    assert flow_findings(r) == []
    # And the reverse edit reintroduces the finding.
    (tmp_path / "lddl_tpu/utils/listing.py").write_text(
        textwrap.dedent(FS_HELPER))
    r = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                           baseline_path="",
                           cache_path=str(tmp_path / "cache.json"))
    assert len(flow_findings(r, "fs-order-flow")) == 1


def test_path_filtered_run_does_not_poison_full_tree_cache(tmp_path):
    """Facts extracted under a PARTIAL project model (explicit-path run)
    record cross-package calls as opaque externals; reusing them in a
    full-tree run would silently drop flow findings. The analyzed path
    set is part of the cache signature, so the full run re-extracts."""
    write_tree(tmp_path, CACHE_TREE)
    cache = str(tmp_path / "cache.json")
    partial = analysis.run_check(["lddl_tpu/balance"], root=str(tmp_path),
                                 baseline_path="", cache_path=cache)
    assert flow_findings(partial) == []  # helper not in scope: no flow
    full = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                              baseline_path="", cache_path=cache)
    assert full.files_cached == 0  # partial-run cache must NOT be reused
    assert len(flow_findings(full, "fs-order-flow")) == 1


def test_overlapping_paths_analyze_each_file_once(tmp_path):
    """Overlapping path args must not analyze a file twice: duplicate
    findings would overflow count-aware baseline entries and report.files
    would double-count."""
    write_tree(tmp_path, CACHE_TREE)
    once = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                              baseline_path="")
    twice = analysis.run_check(["lddl_tpu", "lddl_tpu/balance"],
                               root=str(tmp_path), baseline_path="")
    assert twice.files == once.files == 2
    assert [f.format() for f in twice.new] == \
        [f.format() for f in once.new]


def test_cache_tolerates_corruption(tmp_path):
    write_tree(tmp_path, CACHE_TREE)
    cache = tmp_path / "cache.json"
    cache.write_text("{ not json")
    r = analysis.run_check(["lddl_tpu"], root=str(tmp_path),
                           baseline_path="", cache_path=str(cache))
    assert r.files_cached == 0
    assert len(flow_findings(r, "fs-order-flow")) == 1


# ------------------------------------------------- engine unit coverage


def _summaries_of(files):
    proj = project.build_project(
        {p: textwrap.dedent(s) for p, s in files.items()})
    facts = [dataflow.extract_module_facts(proj, proj.modules_by_path[p])
             for p in sorted(proj.modules_by_path)]
    eng = dataflow.Engine(facts)
    eng.solve()
    return eng


def test_summaries_param_passthrough_vs_source():
    eng = _summaries_of({
        "m.py": """
            import time

            def ident(x):
                return x

            def stamped():
                return time.time()
        """,
    })
    ident = eng.summaries["m.ident"]
    stamped = eng.summaries["m.stamped"]
    assert ident.ret_params["wallclock"] == frozenset({0})
    assert ident.ret_srcs["wallclock"] == frozenset()
    assert not stamped.ret_params["wallclock"]
    [(name, path, line)] = stamped.ret_srcs["wallclock"]
    assert name == "time.time"


def test_summaries_recursive_functions_terminate():
    eng = _summaries_of({
        "m.py": """
            import os

            def a(d, depth):
                if depth:
                    return a(d, depth - 1)
                return os.listdir(d)

            def b(d):
                return c(d)

            def c(d):
                return b(d)
        """,
    })
    assert eng.summaries["m.a"].ret_srcs["fsorder"]


def test_flow_rule_ids_are_registered():
    ids = {r.id for r in analysis.all_rules()}
    for rid in flow_rules.FLOW_RULE_IDS:
        assert rid in ids


def test_fixture_rules_scope_marking():
    by_id = {r.id: r for r in analysis.all_rules()}
    assert by_id["fs-order-flow"].scope == "project"
    assert by_id["unsorted-iteration"].scope == "file"


# ----------------------- ingest journal/generation builders (PR 8)


def test_wall_clock_flow_into_journal_builder_content(tmp_path):
    """Journal segments are resume-compared, content-hash-only bytes —
    a clock value laundered through an observability helper into a
    journal builder's content must flag exactly like a manifest."""
    report = run_tree(tmp_path, {
        "lddl_tpu/observability/stamp.py": WALLCLOCK_HELPER,
        "lddl_tpu/ingest/journal.py": """
            from ..observability.stamp import now_tag

            def build_journal_segment(hashes):
                return {"stamp": now_tag(), "hashes": sorted(hashes)}
        """,
    })
    [f] = flow_findings(report, "wall-clock-flow")
    assert f.path == "lddl_tpu/ingest/journal.py"
    assert "time.time" in f.message
    # Direct-call rule has nothing to see (the clock is in the helper).
    assert not any(f.rule == "manifest-determinism" for f in report.new)


def test_manifest_determinism_covers_ingest_builder_names(tmp_path):
    """The syntactic rule's name gate extends to the ingest record
    builders: journal / intake / generation functions drawing
    nondeterminism directly each flag."""
    report = run_tree(tmp_path, {
        "lddl_tpu/ingest/records.py": """
            import os
            import time
            import uuid

            def build_journal_record(hashes):
                return {"at": time.time(), "hashes": sorted(hashes)}

            def publish_intake_record(docs):
                return {"pid": os.getpid(), "docs": sorted(docs)}

            def generation_meta(n):
                return {"id": str(uuid.uuid4()), "generation": n}
        """,
    }, rules=["manifest-determinism"])
    found = [f for f in report.new if f.rule == "manifest-determinism"]
    assert len(found) == 3


def test_fs_order_flow_into_journal_record(tmp_path):
    """Landing-scan order must never shape journal bytes: an unsorted
    listing crossing into an intake builder and iterated there flags."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/listing.py": FS_HELPER,
        "lddl_tpu/ingest/scan.py": """
            from ..utils.listing import entries

            def build_intake_hashes(d):
                out = []
                for name in entries(d):
                    out.append(name)
                return out
        """,
    })
    [f] = flow_findings(report, "fs-order-flow")
    assert f.path == "lddl_tpu/ingest/scan.py"


def test_journal_builder_content_hash_only_is_clean(tmp_path):
    """The sanctioned shape: content hashes + sorted iteration + a
    deterministic generation counter — silent under BOTH rule families."""
    report = run_tree(tmp_path, {
        "lddl_tpu/ingest/journal.py": """
            import hashlib
            import os

            def doc_hash(text):
                return hashlib.blake2b(text, digest_size=16).hexdigest()

            def build_journal_segment(generation, texts):
                hashes = sorted(doc_hash(t) for t in texts)
                return {"generation": generation, "hashes": hashes}

            def scan_landing(d):
                return sorted(os.listdir(d))
        """,
    })
    assert report.new == []


def test_publish_path_flow_covers_ingest_package(tmp_path):
    """lddl_tpu/ingest/ is a shard package: a raw write laundered
    through an outside helper flags exactly as it would from
    preprocess/."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/textio.py": """
            def write_text(path, text):
                with open(path, "w") as f:
                    f.write(text)
        """,
        "lddl_tpu/ingest/sink.py": """
            from ..utils.textio import write_text

            def dump_segment(out_dir, payload):
                write_text(out_dir + "/gen-0001.json", payload)
        """,
    }, rules=["publish-path-flow"])
    [f] = flow_findings(report, "publish-path-flow")
    assert f.path == "lddl_tpu/ingest/sink.py"


# ----------------------- offline packer module (PR 11)


def test_manifest_determinism_covers_pack_meta_builder(tmp_path):
    """The packer's manifest-meta fragment (pack_meta_of) is
    resume-compared content: the builder-name gate extends to pack_meta
    so a clock-shaped packed shape flags like any other manifest
    nondeterminism."""
    report = run_tree(tmp_path, {
        "lddl_tpu/preprocess/packing.py": """
            import time

            def pack_meta_of(budget, per_row):
                return {"pack_seq_length": budget,
                        "pack_max_per_row": per_row,
                        "packed_at": time.time()}
        """,
    }, rules=["manifest-determinism"])
    found = [f for f in report.new if f.rule == "manifest-determinism"]
    assert len(found) == 1
    assert found[0].path == "lddl_tpu/preprocess/packing.py"


def test_publish_path_flow_covers_packer_module(tmp_path):
    """The packer module lives in a shard package: a raw parquet write
    laundered through an outside helper on its call path flags — the
    packed sink must publish through resilience.io like every other
    sink."""
    report = run_tree(tmp_path, {
        "lddl_tpu/utils/rawpq.py": """
            import pyarrow.parquet as pq

            def dump_table(table, path):
                pq.write_table(table, path)
        """,
        "lddl_tpu/preprocess/packing.py": """
            from ..utils.rawpq import dump_table

            def write_packed_shard(table, out_dir):
                dump_table(table, out_dir + "/part.0.parquet")
        """,
    }, rules=["publish-path-flow"])
    [f] = flow_findings(report, "publish-path-flow")
    assert f.path == "lddl_tpu/preprocess/packing.py"
