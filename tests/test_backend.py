"""Pluggable storage backend (lddl_tpu/resilience/backend.py): mock
object-store semantics (versioned objects, CAS, multipart-upload-then-
commit, fault program), the CAS lease protocol, journal exactly-once
commits, and local-vs-mock byte identity — the fast in-process half of
the chaos proof (the 3-host SIGKILL matrix on the mock store lives in
tests/test_chaos.py, -m slow).
"""

import hashlib
import json
import os
import shutil
import sys
import threading

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu import observability as obs  # noqa: E402
from lddl_tpu.resilience import backend as storage  # noqa: E402
from lddl_tpu.resilience import faults  # noqa: E402
from lddl_tpu.resilience import io as rio  # noqa: E402
from lddl_tpu.resilience import leases  # noqa: E402

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setenv("LDDL_TPU_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("LDDL_TPU_RETRY_MAX_DELAY_S", "0.01")


@pytest.fixture
def mock_bk(monkeypatch):
    """The mock store selected for this test (env-scoped, like a spawned
    worker would inherit it)."""
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    return storage.get_backend()


def _metrics(monkeypatch, tmp_path):
    monkeypatch.setenv("LDDL_TPU_METRICS_DIR", str(tmp_path / "metrics"))
    obs.registry().reset()
    return obs.registry()


# ------------------------------------------------- mock store semantics


def test_mock_put_get_roundtrip_versions_and_view(mock_bk, tmp_path):
    p = str(tmp_path / "rec.json")
    mock_bk.put_atomic(p, b"v1")
    assert mock_bk.get(p) == b"v1"
    assert mock_bk.get_versioned(p) == (b"v1", 1)
    mock_bk.put_atomic(p, b"v2-longer")
    assert mock_bk.get_versioned(p) == (b"v2-longer", 2)
    # The materialized view keeps unchanged data-plane readers working
    # (plain open, no backend dispatch) ...
    with open(p, "rb") as f:
        assert f.read() == b"v2-longer"
    # ... while the commit records stay authoritative in the sidecar.
    odir = str(tmp_path / (storage.OBJ_PREFIX + "rec.json"))
    assert os.path.isdir(odir)
    assert mock_bk._current_gen(odir) == 2


def test_mock_cas_create_and_conditional_replace(mock_bk, tmp_path):
    p = str(tmp_path / "lease.json")
    assert mock_bk.put_if_match(p, b"a", None) == 1
    with pytest.raises(storage.CASConflict):
        mock_bk.put_if_match(p, b"b", None)  # create: already exists
    assert mock_bk.put_if_match(p, b"b", 1) == 2
    with pytest.raises(storage.CASConflict):
        mock_bk.put_if_match(p, b"c", 1)  # stale generation
    assert mock_bk.get_versioned(p) == (b"b", 2)


def test_mock_ranged_get_and_range_read_fault(mock_bk, tmp_path):
    p = str(tmp_path / "blob")
    mock_bk.put_atomic(p, b"0123456789")
    assert mock_bk.get(p, start=3) == b"3456789"
    assert mock_bk.get(p, start=3, length=4) == b"3456"
    assert mock_bk.get(p, length=2) == b"01"
    faults.arm("range-read:truncate:nth=1")
    torn = mock_bk.get(p, start=0, length=10)
    assert torn == b"0123"  # chopped mid-range: the torn-read shape
    faults.disarm()
    assert mock_bk.get(p, start=0, length=10) == b"0123456789"


def test_mock_multipart_parts_torn_upload_and_retry(tmp_path, monkeypatch):
    """A put larger than the part size uploads multiple parts; a fault at
    the commit leaves an ABANDONED multipart upload (orphan parts, no
    commit record, object invisible) and a retried put publishes clean —
    the torn-multipart crash shape the chaos matrix replays."""
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    monkeypatch.setenv("LDDL_TPU_MOCK_PART_BYTES", "4")
    bk = storage.MockObjectStore()  # fresh instance: part size is ctor-read
    p = str(tmp_path / "shard.bin")
    odir = bk._obj_dir(p)
    data = b"abcdefghij" * 3  # 30 bytes -> 8 parts of <=4
    faults.arm("multipart-commit:eio:nth=1")
    with pytest.raises(OSError):
        bk.put_if_match(p, data, None)
    faults.disarm()
    orphans = [n for n in os.listdir(odir) if ".p" in n]
    assert len(orphans) == 8  # parts staged, never referenced
    assert bk.get_versioned(p) == (None, None)  # invisible to readers
    with pytest.raises(FileNotFoundError):
        bk.get(p)
    assert bk.put_if_match(p, data, None) == 1  # retry publishes clean
    assert bk.get(p) == data
    meta = bk._read_meta(odir, 1)
    assert len(meta["parts"]) == 8
    assert not set(meta["parts"]) & set(orphans)  # orphans unreferenced


def test_mock_injected_cas_conflict_counts(mock_bk, tmp_path, monkeypatch):
    reg = _metrics(monkeypatch, tmp_path)
    p = str(tmp_path / "x.json")
    faults.arm("cas-put:conflict:nth=1")
    with pytest.raises(storage.CASConflict):
        mock_bk.put_if_match(p, b"a", None)
    faults.disarm()
    assert reg.counter("backend_cas_conflicts_total").total() >= 1
    # Unconditional puts HEAL injected conflicts (last-writer-wins
    # retries the race) — only conditional ops surface them.
    faults.arm("cas-put:conflict:nth=1")
    mock_bk.put_atomic(p, b"b")
    faults.disarm()
    assert mock_bk.get(p) == b"b"


def test_mock_stale_list_serves_previous_snapshot(mock_bk, tmp_path):
    d = str(tmp_path / "ledger")
    os.makedirs(d)
    mock_bk.put_atomic(os.path.join(d, "a.json"), b"{}")
    assert mock_bk.list(d) == ["a.json"]  # snapshot cached
    mock_bk.put_atomic(os.path.join(d, "b.json"), b"{}")
    faults.arm("list:stale:nth=1")
    assert mock_bk.list(d) == ["a.json"]  # list-after-put staleness
    faults.disarm()
    assert mock_bk.list(d) == ["a.json", "b.json"]


def test_mock_list_merges_objects_and_external_files(mock_bk, tmp_path):
    d = str(tmp_path / "mixed")
    os.makedirs(d)
    mock_bk.put_atomic(os.path.join(d, "obj.json"), b"{}")
    with open(os.path.join(d, "plain.txt"), "w") as f:
        f.write("x")
    with open(os.path.join(d, "x.tmp.123"), "w") as f:
        f.write("scratch")
    assert mock_bk.list(d) == ["obj.json", "plain.txt"]
    assert mock_bk.list(str(tmp_path / "absent")) is None


def test_mock_delete_and_conditional_delete(mock_bk, tmp_path):
    p = str(tmp_path / "l.json")
    gen = mock_bk.put_if_match(p, b"a", None)
    with pytest.raises(storage.CASConflict):
        mock_bk.delete_if_match(p, gen + 1)
    assert mock_bk.get_versioned(p)[0] == b"a"  # survived the refused delete
    assert mock_bk.delete_if_match(p, gen)
    assert mock_bk.get_versioned(p) == (None, None)
    assert not os.path.exists(p)  # view gone too
    mock_bk.delete(p)  # deleting the deleted: fine


def test_mock_gc_bounds_generations(mock_bk, tmp_path):
    p = str(tmp_path / "renewed.json")
    for i in range(10):
        mock_bk.put_atomic(p, b"rec-%d" % i)
    odir = mock_bk._obj_dir(p)
    gens = [n for n in os.listdir(odir)
            if n.startswith("g") and n.endswith(".json")]
    assert len(gens) <= mock_bk._KEEP_GENS  # renew-heavy objects stay small
    assert mock_bk.get_versioned(p) == (b"rec-9", 10)


def test_local_backend_interface_parity(tmp_path):
    """LocalBackend implements the same surface with POSIX semantics:
    create-only CAS, generation-less reads, advisory conditional
    delete."""
    bk = storage.LocalBackend()
    assert not bk.is_cas
    p = str(tmp_path / "r.json")
    bk.put_atomic(p, b"v1")
    assert bk.get(p) == b"v1"
    assert bk.get(p, start=1, length=1) == b"1"
    st = os.stat(p)
    assert bk.get_versioned(p) == (b"v1", (st.st_size, st.st_mtime_ns))
    assert bk.head(p) == (st.st_size, (st.st_size, st.st_mtime_ns))
    assert bk.get_versioned(str(tmp_path / "absent")) == (None, None)
    assert bk.head(str(tmp_path / "absent")) == (None, None)
    with pytest.raises(storage.CASConflict):
        bk.put_if_match(p, b"x", None)  # exists: create refused
    with pytest.raises(NotImplementedError):
        bk.put_if_match(p, b"x", 1)  # POSIX has no conditional replace
    q = str(tmp_path / "new.json")
    assert bk.put_if_match(q, b"made", None) == 1
    with open(q, "rb") as f:
        assert f.read() == b"made"
    assert bk.list(str(tmp_path)) == ["new.json", "r.json"]
    assert bk.list(str(tmp_path / "absent")) is None
    bk.delete(q)
    bk.delete(q)  # idempotent
    assert bk.delete_if_match(p, 0)
    assert not os.path.exists(p)


def test_backend_selection_env_and_flag(monkeypatch):
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    assert storage.active_name() == "local"
    assert storage.get_backend().name == "local"
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    assert storage.get_backend().name == "mock"
    with pytest.raises(ValueError):
        storage.set_backend("s3")  # not wired: refuse loudly
    # The CLI flag is sugar for the env var (so spawned workers inherit).
    import argparse
    from lddl_tpu.cli.common import apply_storage_backend, attach_storage_arg
    ap = argparse.ArgumentParser()
    attach_storage_arg(ap)
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    apply_storage_backend(ap.parse_args([]))
    assert storage.ENV_VAR not in os.environ  # default: env untouched
    apply_storage_backend(ap.parse_args(["--storage-backend", "mock"]))
    assert os.environ[storage.ENV_VAR] == "mock"


# --------------------------------------------------- CAS lease protocol


def test_lease_cas_acquire_renew_steal_release(mock_bk, tmp_path):
    root = str(tmp_path / "_leases")
    os.makedirs(root)
    now = [1000.0]

    def clock():
        return now[0]

    a = leases.try_acquire(root, "u1", "hostA", 10.0, now_fn=clock)
    assert a is not None and a.epoch == 0 and a.gen == 1
    # Valid lease: a second claimant stands down.
    assert leases.try_acquire(root, "u1", "hostB", 10.0,
                              now_fn=clock) is None
    # Renewal advances deadline AND generation (conditional put).
    leases.renew(a, 10.0, now_fn=clock)
    assert a.gen == 2
    leases.renew_fast(a, 10.0, now_fn=clock)
    assert a.gen == 3
    assert leases.verify(a)
    assert leases.scan_units(root) == {"u1"}
    # Expiry: the steal is a conditional put at epoch+1.
    now[0] += 20.0
    b = leases.try_acquire(root, "u1", "hostB", 10.0, now_fn=clock)
    assert b is not None and b.epoch == 1
    # The loser's next renewal trips the CAS precondition, not a timer.
    with pytest.raises(leases.LeaseLost):
        leases.renew_fast(a, 10.0, now_fn=clock)
    assert a.lost and not leases.verify(a)
    leases.release(b, now_fn=clock)
    assert leases.scan_units(root) == set()


def test_lease_cas_create_race_loses_cleanly(mock_bk, tmp_path):
    root = str(tmp_path / "_leases")
    os.makedirs(root)
    faults.arm("cas-put:conflict:nth=1")
    assert leases.try_acquire(root, "u1", "hostA", 10.0) is None
    faults.disarm()
    got = leases.try_acquire(root, "u1", "hostA", 10.0)
    assert got is not None and got.epoch == 0


def test_stall_at_cas_put_forces_mock_store_steal(mock_bk, tmp_path,
                                                 monkeypatch):
    """The chaos shape CAS fencing exists for: holder A's renewal stalls
    at the conditional put past the TTL, B steals, and A's put — now
    against a superseded generation — loses the CAS instead of
    overwriting B's lease (on the local path this window is closed
    after-the-fact by the publish fence; here it never opens)."""
    reg = _metrics(monkeypatch, tmp_path)
    root = str(tmp_path / "_leases")
    os.makedirs(root)
    a = leases.try_acquire(root, "u1", "hostA", 0.6)
    assert a is not None
    # The flag latch file is written the instant the stall FIRES (before
    # its sleep), so the main thread can wait until A is provably parked
    # mid-put before stealing — no schedule luck.
    flag = str(tmp_path / "stall.flag")
    faults.arm("cas-put:stall:nth=1:delay=1.5:flag={}".format(flag))
    outcome = {}

    def renew_a():
        try:
            leases.renew(a, 0.6)
        except leases.LeaseLost as e:
            outcome["lost"] = e

    t = threading.Thread(target=renew_a)
    t.start()
    while not os.path.exists(flag):
        pass
    # A is parked at its conditional put and its TTL is behind us from
    # B's clock: steal. B's own cas-put sees no fault (nth=1 consumed).
    deadline = a.deadline
    b = leases.try_acquire(root, "u1", "hostB", 10.0,
                           now_fn=lambda: deadline + 0.05)
    assert b is not None and b.epoch == 1
    t.join(timeout=30.0)
    assert not t.is_alive()
    assert isinstance(outcome.get("lost"), leases.LeaseLost)
    assert a.lost
    assert leases.verify(b)  # the thief's lease survived intact
    assert reg.counter("backend_cas_conflicts_total").total() >= 1
    leases.release(b)


# ----------------------------------------- journal exactly-once commits


def test_put_exclusive_semantics(mock_bk, tmp_path):
    p = str(tmp_path / "seg.json")
    assert rio.put_exclusive(p, '{"a": 1}') == "ok"
    assert rio.put_exclusive(p, '{"a": 2}') == "conflict"
    assert mock_bk.get(p) == b'{"a": 1}'  # loser never overwrote


def test_put_exclusive_local_matches_pre_backend(tmp_path, monkeypatch):
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    p = str(tmp_path / "seg.json")
    assert rio.put_exclusive(p, "data") == "ok"
    with open(p) as f:
        assert f.read() == "data"


def test_journal_exclusive_commit_idempotent_vs_conflicting(
        mock_bk, tmp_path, monkeypatch):
    from lddl_tpu.ingest import journal as journal_mod
    reg = _metrics(monkeypatch, tmp_path)
    p = str(tmp_path / ".ingest" / "journal" / "gen-0000.json")
    payload = {"generation": 0, "hashes": ["h1", "h2"]}
    journal_mod.publish_record(p, payload, exclusive=True)
    # A raced duplicate commit of IDENTICAL content is absorbed
    # idempotently (redo after a crash-after-commit) ...
    journal_mod.publish_record(p, payload, exclusive=True)
    assert reg.counter(
        "ingest_journal_idempotent_commits_total").total() == 1
    # ... while different content for the same generation refuses loudly.
    with pytest.raises(ValueError, match="DIFFERENT content"):
        journal_mod.publish_record(p, {"generation": 0, "hashes": ["x"]},
                                   exclusive=True)
    assert json.loads(mock_bk.get(p)) == payload


# -------------------------------------------- local-vs-mock byte identity


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("backend")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def goldens():
    with open(gs.GOLDEN_FILE) as f:
        return json.load(f)


def test_mock_preprocess_matches_pinned_goldens(fixture_dirs, goldens,
                                                tmp_path, monkeypatch):
    """The whole preprocess pipeline on the mock store produces the
    PINNED golden bytes — the backend is publish plumbing and must never
    reach shard content (no golden regeneration: these are the seed's
    own hashes)."""
    td, corpus, vocab = fixture_dirs
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    out = str(tmp_path / "out")
    assert gs.run_case(corpus, vocab, out, binned=True) \
        == goldens["binned_masked"]
    # Vacuity guard: the run really went through the object store.
    sidecars = [n for n in os.listdir(out)
                if n.startswith(storage.OBJ_PREFIX)]
    assert sidecars, "no .obj.* sidecars: mock store was never exercised"


def test_elastic_on_mock_store_matches_goldens(fixture_dirs, goldens,
                                               tmp_path, monkeypatch):
    """One elastic host coordinating through CAS leases on the mock
    store == the pinned static bytes (lease protocol never reaches shard
    content on ANY backend)."""
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    from lddl_tpu.preprocess.runner import (BertBucketProcessor,
                                            run_sharded_pipeline)
    td, corpus, vocab = fixture_dirs
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    out = str(tmp_path / "out")
    tok = get_tokenizer(vocab_file=vocab)
    cfg = BertPretrainConfig(max_seq_length=32, masking=True,
                             schema_version=1)
    proc = BertBucketProcessor(tok, cfg, 4242, out, 8, "parquet")
    written = run_sharded_pipeline(
        {"wikipedia": corpus}, out, proc, elastic=True, lease_ttl=5.0,
        holder_id="solo-mock", num_blocks=12, sample_ratio=0.9, seed=4242,
        global_shuffle=True, progress_interval=0.0)
    assert written and sum(written.values()) > 0
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    # Scheduling state fully cleaned up on the mock store too.
    assert not os.path.isdir(os.path.join(out, "_leases"))
    assert not os.path.isdir(os.path.join(out, "_done"))


# --------------------------------------- ingest crash matrix (in-process)


def _tree_hashes(root):
    """sha256 of every visible published file, keyed by relpath. Mock
    sidecars (.obj.*) and telemetry are backend implementation detail,
    excluded from the identity claim."""
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith((storage.OBJ_PREFIX,
                                                  ".telemetry")))
        for name in sorted(filenames):
            if ".tmp." in name:
                continue
            path = os.path.join(dirpath, name)
            with open(path, "rb") as f:
                out[os.path.relpath(path, root)] = hashlib.sha256(
                    f.read()).hexdigest()
    return out


def test_ingest_crash_matrix_on_mock_matches_local(fixture_dirs, tmp_path,
                                                   monkeypatch):
    """The ingest acceptance pin, replayed on the object store: a mock-
    store incremental directory that crashed at the intake publish,
    crashed at the generation commit, absorbed a torn multipart upload
    and an injected CAS conflict, and ran a round under REVERSED
    filesystem enumeration ends byte-identical — shards, manifests,
    journal segments — to a clean LocalBackend replay of the same
    sequence, with every generation journaled exactly once."""
    from lddl_tpu.ingest import ingest_once
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    td, corpus, vocab = fixture_dirs
    tok = get_tokenizer(vocab_file=vocab)
    cfg = BertPretrainConfig(max_seq_length=32, masking=False)
    KW = dict(num_shards=4, seed=7)

    def landing(base, n_files, name):
        d = os.path.join(base, name, "source")
        os.makedirs(d, exist_ok=True)
        for i in range(n_files):
            shutil.copy(os.path.join(corpus, "source",
                                     "{}.txt".format(i)),
                        os.path.join(d, "{}.txt".format(i)))
        return os.path.join(base, name)

    base = str(tmp_path)
    clean = str(tmp_path / "clean")
    dirty = str(tmp_path / "dirty")

    # Reference: clean two-round replay on the default LocalBackend.
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    for n in (1, 2):
        ingest_once(clean, tok, landing=landing(base, n, "l-clean"),
                    config=cfg, **KW)

    # Dirty: the same sequence on the mock store, crashing along the way.
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    # Round 1: die at the intake publish (before any work), then resume.
    faults.arm("journal-publish:eio:nth=1:path=intake")
    with pytest.raises(OSError):
        ingest_once(dirty, tok, landing=landing(base, 1, "l-dirty"),
                    config=cfg, **KW)
    faults.disarm()
    ingest_once(dirty, tok, landing=landing(base, 1, "l-dirty"),
                config=cfg, **KW)
    # Round 2: one torn multipart upload (commit dies once, orphan parts
    # left behind; the retry classifier republishes) plus one injected
    # CAS conflict on a shard put (healed by last-writer-wins retry),
    # then die at the generation commit and resume with filesystem
    # enumeration REVERSED end to end.
    faults.arm("multipart-commit:eio:nth=1:path=part,"
               "cas-put:conflict:nth=1:path=part,"
               "journal-publish:eio:nth=1:path=journal/gen-0001")
    with pytest.raises(OSError):
        ingest_once(dirty, tok, landing=landing(base, 2, "l-dirty"),
                    config=cfg, **KW)
    faults.disarm()
    real_walk, real_listdir = os.walk, os.listdir

    def reversed_walk(top, **kwargs):
        for dirpath, dirnames, filenames in real_walk(top, **kwargs):
            rd = list(reversed(sorted(dirnames)))
            yield dirpath, rd, list(reversed(sorted(filenames)))
            dirnames[:] = rd

    monkeypatch.setattr(os, "walk", reversed_walk)
    monkeypatch.setattr(
        os, "listdir",
        lambda p=".": list(reversed(sorted(real_listdir(p)))))
    ingest_once(dirty, tok, landing=landing(base, 2, "l-dirty"),
                config=cfg, **KW)
    monkeypatch.undo()

    assert _tree_hashes(dirty) == _tree_hashes(clean)
    # Exactly-once journaling: one segment per generation, no holes.
    segs = sorted(os.listdir(os.path.join(dirty, ".ingest", "journal")))
    assert [s for s in segs if s.startswith("gen-")] \
        == ["gen-0000.json", "gen-0001.json"]
