"""Ring attention: exact parity with dense attention on a virtual mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lddl_tpu.parallel import compat


@pytest.fixture(scope="module")
def sp_mesh():
    from lddl_tpu.parallel import make_mesh
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh({"dp": 2, "sp": 4})


def _inputs(seed=0, b=4, l=32, h=4, d=16, dtype=jnp.float32):
    g = np.random.default_rng(seed)
    q = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    k = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    # Ragged validity incl. one fully-padded ring block (cols 24..31 of
    # row 0) to hit the all-masked-block path.
    mask = np.ones((b, l), np.int32)
    mask[0, 20:] = 0
    mask[1, 29:] = 0
    return q, k, v, jnp.asarray(mask)


def test_ring_matches_dense_forward(sp_mesh):
    from lddl_tpu.ops.ring_attention import (dense_attention_reference,
                                             ring_attention)
    q, k, v, mask = _inputs()
    with compat.set_mesh(sp_mesh):
        out = jax.jit(lambda *a: ring_attention(*a, mesh=sp_mesh))(
            q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_dense_gradients(sp_mesh):
    from lddl_tpu.ops.ring_attention import (dense_attention_reference,
                                             ring_attention)
    q, k, v, mask = _inputs(seed=3)

    def loss_ring(q, k, v):
        out = ring_attention(q, k, v, mask, mesh=sp_mesh)
        return (out * out).sum()

    def loss_dense(q, k, v):
        out = dense_attention_reference(q, k, v, mask)
        return (out * out).sum()

    with compat.set_mesh(sp_mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # ~14s: full compile+train on CPU devices, budget-gated from tier-1
def test_bert_ring_matches_dense_logits(sp_mesh):
    """The full model produces (numerically) the same logits under
    attention_impl='ring' and 'dense' with identical params."""
    import flax.linen as nn
    from lddl_tpu.models import BertConfig, BertForPreTraining
    from lddl_tpu.models.bert import axis_rules_for
    from lddl_tpu.models.testing import fake_pretrain_batch

    cfg_kw = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                  intermediate_size=64, max_position_embeddings=64,
                  dtype=jnp.float32)
    cfg_dense = BertConfig(attention_impl="dense", **cfg_kw)
    cfg_ring = BertConfig(attention_impl="ring", **cfg_kw)
    batch = fake_pretrain_batch(cfg_dense.vocab_size, 4, 32, seed=1,
                                segment_split=True)
    model_d = BertForPreTraining(cfg_dense)
    model_r = BertForPreTraining(cfg_ring)
    with compat.set_mesh(sp_mesh), nn.logical_axis_rules(
            axis_rules_for(sp_mesh)):
        params = nn.meta.unbox(model_d.init(
            jax.random.PRNGKey(0), batch["input_ids"],
            batch["token_type_ids"], batch["attention_mask"],
            deterministic=True))["params"]

        def fwd(model):
            return jax.jit(lambda p: model.apply(
                {"params": p}, batch["input_ids"],
                batch["token_type_ids"], batch["attention_mask"],
                deterministic=True))(params)

        mlm_d, nsp_d = fwd(model_d)
        mlm_r, nsp_r = fwd(model_r)
    np.testing.assert_allclose(np.asarray(mlm_r), np.asarray(mlm_d),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(nsp_r), np.asarray(nsp_d),
                               rtol=5e-4, atol=5e-4)


@pytest.mark.slow  # ~32s: full compile+train on CPU devices, budget-gated from tier-1
def test_ring_train_step_runs(sp_mesh):
    from lddl_tpu.loader import to_device_batch
    from lddl_tpu.models import (BertConfig, create_train_state,
                                 make_sharded_train_step)
    from lddl_tpu.models.testing import fake_pretrain_batch
    from lddl_tpu.models.train import make_optimizer

    cfg = BertConfig.tiny(attention_impl="ring")
    batch_np = fake_pretrain_batch(cfg.vocab_size, 4, 32, seed=0,
                                   segment_split=True)
    state, _ = create_train_state(
        cfg, sp_mesh, batch_np,
        optimizer=make_optimizer(warmup_steps=1, total_steps=5))
    step = make_sharded_train_step(sp_mesh, cfg)
    batch = to_device_batch(batch_np, sp_mesh)
    state, metrics = step(state, batch, seed=0)
    assert np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(state.step)) == 1


@pytest.mark.slow  # ~8s: full compile+train on CPU devices, budget-gated from tier-1
def test_bart_encoder_ring_matches_dense(sp_mesh):
    """BART with attention_impl='ring' (encoder bidirectional attention
    rides the ring; decoder stays dense/causal) matches the dense model's
    logits with shared params."""
    import flax.linen as nn
    from lddl_tpu.models import BartConfig, BartForPreTraining
    from lddl_tpu.models.bert import axis_rules_for

    cfg_kw = dict(vocab_size=128, hidden_size=32, num_encoder_layers=2,
                  num_decoder_layers=1, num_heads=4, intermediate_size=64,
                  max_position_embeddings=64, dtype=jnp.float32)
    cfg_d = BartConfig(attention_impl="dense", **cfg_kw)
    cfg_r = BartConfig(attention_impl="ring", **cfg_kw)
    g = np.random.default_rng(7)
    batch = {
        "input_ids": g.integers(5, 128, (4, 32)).astype(np.int32),
        "attention_mask": np.ones((4, 32), np.int32),
        "decoder_input_ids": g.integers(5, 128, (4, 32)).astype(np.int32),
    }
    batch["attention_mask"][0, 20:] = 0
    model_d = BartForPreTraining(cfg_d)
    model_r = BartForPreTraining(cfg_r)
    with compat.set_mesh(sp_mesh), nn.logical_axis_rules(
            axis_rules_for(sp_mesh)):
        params = nn.meta.unbox(model_d.init(
            jax.random.PRNGKey(0), batch["input_ids"],
            batch["attention_mask"], batch["decoder_input_ids"],
            deterministic=True))["params"]

        def fwd(model):
            return jax.jit(lambda p: model.apply(
                {"params": p}, batch["input_ids"],
                batch["attention_mask"], batch["decoder_input_ids"],
                deterministic=True))(params)

        out_d = fwd(model_d)
        out_r = fwd(model_r)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               rtol=5e-4, atol=5e-4)
