"""Pallas fused attention: parity with the dense reference (interpret
mode on CPU; the same entry point compiles and runs on a real TPU —
FLASH_ATTENTION_BENCH.json records a hardware run)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lddl_tpu.ops.flash_attention import flash_attention
from lddl_tpu.ops.ring_attention import dense_attention_reference


def _inputs(b=2, l=200, h=4, d=64, dtype=jnp.float32, seed=0):
    g = np.random.default_rng(seed)
    q = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    k = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    mask = np.ones((b, l), np.int32)
    mask[0, 128:] = 0   # KV block [128, 256) fully masked (post-pad)
    mask[1, l - 3:] = 0
    return q, k, v, jnp.asarray(mask)


def test_forward_matches_dense():
    q, k, v, mask = _inputs()          # L=200: exercises the padding path
    out = flash_attention(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_bf16():
    q, k, v, mask = _inputs(l=128, dtype=jnp.bfloat16)
    out = np.asarray(flash_attention(q, k, v, mask), np.float32)
    ref = np.asarray(dense_attention_reference(q, k, v, mask), np.float32)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_gradients_match_dense():
    # L=200: the backward's padding path (padded ct/out, masked padded
    # keys, dropped padded query rows) is live.
    q, k, v, mask = _inputs(l=200, seed=3)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, mask) ** 2).sum()

    def loss_d(q, k, v):
        return (dense_attention_reference(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_jit_composes():
    q, k, v, mask = _inputs(l=128)
    out = jax.jit(flash_attention)(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_onekv_dispatch_boundary():
    """L_pad <= 896 runs the single-block kernels (nbh=1 above 512),
    above runs online."""
    from lddl_tpu.ops.flash_attention import _use_onekv, _nbh_for

    assert _use_onekv(512, 64)       # the reference's headline config
    assert _use_onekv(128, 64)
    assert _use_onekv(640, 64) and _use_onekv(896, 64)   # the former band
    assert not _use_onekv(1024, 64)  # online regime
    # the 640-896 extension is compile-validated at head_dim 64 only:
    # wider heads keep the conservative 512 bound (VMEM)
    assert _use_onekv(512, 128) and not _use_onekv(640, 128)
    assert not _use_onekv(512, 256)  # d > 128 is always online
    assert _nbh_for(16, 512) == 4 and _nbh_for(12, 512) == 4  # bert heads
    assert _nbh_for(6, 512) == 2 and _nbh_for(7, 512) == 1
    # single-row cells above 512 (VMEM: [L,L] fp32 temporaries)
    assert _nbh_for(16, 640) == 1 and _nbh_for(12, 896) == 1


def test_onekv_band_matches_dense():
    """L=600 (l_pad=640): the nbh=1 single-block cells that took over the
    former 512 < l_pad < 1024 dense band — forward and gradients vs the
    dense reference."""
    q, k, v, _ = _inputs(l=600, seed=5)
    mask = np.ones((2, 600), np.int32)
    mask[0, 550:] = 0
    mask = jnp.asarray(mask)

    out = flash_attention(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, mask) ** 2).sum()

    def loss_d(q, k, v):
        return (dense_attention_reference(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_online_nondividing_blocks_match_dense():
    """l_pad=640 with head_dim=256 (d > 128 fails the single-block gate,
    so this is the reachable online path in the 512-896 range): exercises
    _block_sizes' power-of-two halving fallback (640 % 256 != 0 ->
    tq=tk=128), forward and gradients vs the dense reference."""
    q, k, v, _ = _inputs(l=600, h=2, d=256, seed=13)
    mask = np.ones((2, 600), np.int32)
    mask[0, 550:] = 0
    mask = jnp.asarray(mask)

    out = flash_attention(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, mask) ** 2).sum()

    def loss_d(q, k, v):
        return (dense_attention_reference(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_online_path_matches_dense_above_896():
    """L=1000 (l_pad=1024 > ONEKV_MAX_L_PAD): the online-softmax kernels,
    forward AND gradients vs the dense reference."""
    q, k, v, _ = _inputs(l=1000, seed=5)
    mask = np.ones((2, 1000), np.int32)
    mask[0, 900:] = 0
    mask = jnp.asarray(mask)

    out = flash_attention(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, mask) ** 2).sum()

    def loss_d(q, k, v):
        return (dense_attention_reference(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("h", [4, 6, 3])
def test_onekv_segments_match_dense_everywhere(h):
    """Packed rows through the single-block kernels: block-diagonal
    attention matches dense at EVERY row, including degenerate
    (segment-id 0 = padding) rows, which softmax the all(-1e9) row to
    the uniform value average under the shared bias convention.
    h=4 runs nbh=4 cells, h=6 nbh=2 cells (three cells per batch row, so
    the mask block index g*nbh//h diverges from the row block index),
    h=3 the nbh=1 cells."""
    g = np.random.default_rng(7)
    b, l, d = 2, 256, 64
    q = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    k = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    segs_np = np.zeros((b, l), np.int32)
    segs_np[0, :100] = 1
    segs_np[0, 100:200] = 2
    segs_np[1, :250] = 1
    segs = jnp.asarray(segs_np)

    def dense_packed(q, k, v, segs):
        scale = 1.0 / np.sqrt(q.shape[-1])
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        allowed = ((segs[:, None, :, None] == segs[:, None, None, :])
                   & (segs[:, None, None, :] > 0))
        probs = jax.nn.softmax(
            scores + jnp.where(allowed, 0.0, -1e9), axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    out = np.asarray(flash_attention(q, k, v, segments=segs))
    ref = np.asarray(dense_packed(q, k, v, segs))
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_masked_outlier_key_cannot_underflow_live_rows():
    """Round-5 review regression: a DISALLOWED key whose raw score
    dwarfs every allowed score (gap >> 88, the fp32 exp range) must not
    drag the softmax row max up and underflow the allowed probabilities.
    The -1e9 additive bias keeps the max on the allowed side; a
    multiply-after-exp scheme (max over raw scores) returns 0 here."""
    g = np.random.default_rng(11)
    b, l, h, d = 1, 128, 4, 64
    q = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    k_np = g.standard_normal((b, l, h, d))
    k_np[0, 70] = 100.0 * np.asarray(q[0, 0])   # raw score ~ 800 vs ~ O(1)
    k = jnp.asarray(k_np, jnp.float32)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    segs = np.ones((b, l), np.int32)
    segs[0, 70] = 2                              # the outlier is DISALLOWED
    segs[0, 100:] = 0                            # for q rows in segment 1
    segs = jnp.asarray(segs)

    out = flash_attention(q, k, v, segments=segs)
    assert float(jnp.abs(out[0, 0]).max()) > 1e-3   # row did not collapse

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, segments=segs) ** 2).sum()

    dq = jax.grad(loss_f)(q, k, v)
    assert np.isfinite(np.asarray(dq)).all()
    assert float(jnp.abs(dq[0, 0]).max()) > 1e-6


def test_bert_flash_matches_dense_logits():
    """attention_impl='flash' in the full model (interpret mode off-TPU)
    matches dense logits with shared params."""
    import flax.linen as nn
    from lddl_tpu.models import BertConfig, BertForPreTraining
    from lddl_tpu.models.testing import fake_pretrain_batch

    cfg_kw = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                  intermediate_size=64, max_position_embeddings=128,
                  dtype=jnp.float32)
    cfg_d = BertConfig(attention_impl="dense", **cfg_kw)
    cfg_f = BertConfig(attention_impl="flash", **cfg_kw)
    batch = fake_pretrain_batch(cfg_d.vocab_size, 2, 128, seed=1)
    model_d = BertForPreTraining(cfg_d)
    model_f = BertForPreTraining(cfg_f)
    params = nn.meta.unbox(model_d.init(
        jax.random.PRNGKey(0), batch["input_ids"],
        batch["token_type_ids"], batch["attention_mask"],
        deterministic=True))["params"]

    def fwd(model):
        return model.apply({"params": params}, batch["input_ids"],
                           batch["token_type_ids"], batch["attention_mask"],
                           deterministic=True)

    mlm_d, nsp_d = fwd(model_d)
    mlm_f, nsp_f = fwd(model_f)
    np.testing.assert_allclose(np.asarray(mlm_f), np.asarray(mlm_d),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(nsp_f), np.asarray(nsp_d),
                               rtol=5e-4, atol=5e-4)
