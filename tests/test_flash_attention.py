"""Pallas fused attention: parity with the dense reference (interpret
mode on CPU; the same entry point compiles and runs on a real TPU —
FLASH_ATTENTION_BENCH.json records a hardware run)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lddl_tpu.ops.flash_attention import flash_attention
from lddl_tpu.ops.ring_attention import dense_attention_reference


def _inputs(b=2, l=200, h=4, d=64, dtype=jnp.float32, seed=0):
    g = np.random.default_rng(seed)
    q = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    k = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    mask = np.ones((b, l), np.int32)
    mask[0, 128:] = 0   # KV block [128, 256) fully masked (post-pad)
    mask[1, l - 3:] = 0
    return q, k, v, jnp.asarray(mask)


def test_forward_matches_dense():
    q, k, v, mask = _inputs()          # L=200: exercises the padding path
    out = flash_attention(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_bf16():
    q, k, v, mask = _inputs(l=128, dtype=jnp.bfloat16)
    out = np.asarray(flash_attention(q, k, v, mask), np.float32)
    ref = np.asarray(dense_attention_reference(q, k, v, mask), np.float32)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_gradients_match_dense():
    # L=200: the backward's padding path (padded ct/out, masked padded
    # keys, dropped padded query rows) is live.
    q, k, v, mask = _inputs(l=200, seed=3)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, mask) ** 2).sum()

    def loss_d(q, k, v):
        return (dense_attention_reference(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_jit_composes():
    q, k, v, mask = _inputs(l=128)
    out = jax.jit(flash_attention)(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_bert_flash_matches_dense_logits():
    """attention_impl='flash' in the full model (interpret mode off-TPU)
    matches dense logits with shared params."""
    import flax.linen as nn
    from lddl_tpu.models import BertConfig, BertForPreTraining
    from lddl_tpu.models.testing import fake_pretrain_batch

    cfg_kw = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                  intermediate_size=64, max_position_embeddings=128,
                  dtype=jnp.float32)
    cfg_d = BertConfig(attention_impl="dense", **cfg_kw)
    cfg_f = BertConfig(attention_impl="flash", **cfg_kw)
    batch = fake_pretrain_batch(cfg_d.vocab_size, 2, 128, seed=1)
    model_d = BertForPreTraining(cfg_d)
    model_f = BertForPreTraining(cfg_f)
    params = nn.meta.unbox(model_d.init(
        jax.random.PRNGKey(0), batch["input_ids"],
        batch["token_type_ids"], batch["attention_mask"],
        deterministic=True))["params"]

    def fwd(model):
        return model.apply({"params": params}, batch["input_ids"],
                           batch["token_type_ids"], batch["attention_mask"],
                           deterministic=True)

    mlm_d, nsp_d = fwd(model_d)
    mlm_f, nsp_f = fwd(model_f)
    np.testing.assert_allclose(np.asarray(mlm_f), np.asarray(mlm_d),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(nsp_f), np.asarray(nsp_d),
                               rtol=5e-4, atol=5e-4)
