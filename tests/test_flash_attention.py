"""Pallas fused attention: parity with the dense reference (interpret
mode on CPU; the same entry point compiles and runs on a real TPU —
FLASH_ATTENTION_BENCH.json records a hardware run)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lddl_tpu.ops.flash_attention import flash_attention
from lddl_tpu.ops.ring_attention import dense_attention_reference


def _inputs(b=2, l=200, h=4, d=64, dtype=jnp.float32, seed=0):
    g = np.random.default_rng(seed)
    q = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    k = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), dtype)
    mask = np.ones((b, l), np.int32)
    mask[0, 128:] = 0   # KV block [128, 256) fully masked (post-pad)
    mask[1, l - 3:] = 0
    return q, k, v, jnp.asarray(mask)


def test_forward_matches_dense():
    q, k, v, mask = _inputs()          # L=200: exercises the padding path
    out = flash_attention(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forward_bf16():
    q, k, v, mask = _inputs(l=128, dtype=jnp.bfloat16)
    out = np.asarray(flash_attention(q, k, v, mask), np.float32)
    ref = np.asarray(dense_attention_reference(q, k, v, mask), np.float32)
    np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


def test_gradients_match_dense():
    q, k, v, mask = _inputs(l=128, seed=3)

    def loss_f(q, k, v):
        return (flash_attention(q, k, v, mask) ** 2).sum()

    def loss_d(q, k, v):
        return (dense_attention_reference(q, k, v, mask) ** 2).sum()

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_jit_composes():
    q, k, v, mask = _inputs(l=128)
    out = jax.jit(flash_attention)(q, k, v, mask)
    ref = dense_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
