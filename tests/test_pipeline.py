"""Pipeline parallelism (pp axis): GPipe schedule == unpipelined stack,
forward AND gradients, on a virtual multi-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

from lddl_tpu.models import BertConfig
from lddl_tpu.models.bert import BertForPreTraining
from lddl_tpu.parallel import make_mesh
from lddl_tpu.parallel.pipeline import (make_pipelined_encoder,
                                        reference_encoder,
                                        stack_layer_params,
                                        unstack_layer_params)


@pytest.fixture(scope="module")
def setup():
    cfg = BertConfig.tiny(num_layers=4, hidden_dropout=0.0,
                          attention_dropout=0.0)
    model = BertForPreTraining(cfg)
    g = np.random.default_rng(0)
    B, T = 8, 32
    input_ids = g.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    token_type = np.zeros((B, T), np.int32)
    mask = np.ones((B, T), np.int32)
    mask[0, T - 5:] = 0
    variables = model.init(jax.random.PRNGKey(0), input_ids, token_type,
                           mask, deterministic=True)
    params = nn.meta.unbox(variables)["params"]
    stacked = stack_layer_params(params, cfg.num_layers)
    x = jnp.asarray(g.standard_normal((B, T, cfg.hidden_size)),
                    jnp.float32)
    return cfg, stacked, x, jnp.asarray(mask)


def test_stack_roundtrip(setup):
    cfg, stacked, _, _ = setup
    un = unstack_layer_params(stacked, cfg.num_layers)
    for i in range(cfg.num_layers):
        for a, b in zip(jax.tree.leaves(un["layer_{}".format(i)]),
                        jax.tree.leaves(stacked)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b)[i])


@pytest.mark.slow  # ~14s: full compile+train on CPU devices, budget-gated from tier-1
@pytest.mark.parametrize("pp,n_micro", [(2, 2), (2, 4), (4, 4)])
def test_pipeline_matches_reference_forward(setup, pp, n_micro):
    cfg, stacked, x, mask = setup
    mesh = make_mesh({"pp": pp, "dp": 8 // pp})
    pipe = make_pipelined_encoder(mesh, cfg, n_micro)
    ref = reference_encoder(cfg)
    got = np.asarray(jax.jit(pipe)(stacked, x, mask))
    want = np.asarray(jax.jit(ref)(stacked, x, mask))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # ~19s: full compile+train on CPU devices, budget-gated from tier-1
def test_pipeline_matches_reference_gradients(setup):
    cfg, stacked, x, mask = setup
    mesh = make_mesh({"pp": 2, "dp": 4})
    pipe = make_pipelined_encoder(mesh, cfg, n_micro=4)
    ref = reference_encoder(cfg)

    def loss_of(fn):
        def loss(params, x):
            y = fn(params, x, mask)
            return (y.astype(jnp.float32) ** 2).mean()
        return jax.jit(jax.grad(loss, argnums=(0, 1)))

    gp, gx = loss_of(pipe)(stacked, x)
    rp, rx = loss_of(ref)(stacked, x)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=5e-3, atol=1e-5)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(rp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-5)


def test_pipeline_rejects_indivisible_layers(setup):
    cfg, _, _, _ = setup
    mesh = make_mesh({"pp": 8})
    with pytest.raises(ValueError, match="not divisible"):
        make_pipelined_encoder(mesh, cfg, n_micro=2)
