"""Fleet telemetry (lddl_tpu/observability/fleet.py + tools/
pipeline_status.py): spool publishing, torn-tail tolerance, cluster
aggregation with stall/wedge verdicts, clock-aligned trace merging,
abnormal-exit flushing (SIGTERM + SIGKILL), and — the contract that
matters most — byte-inertness: fleet telemetry on vs off changes no
shard, manifest, journal, or batch byte.

The real 3-process SIGKILL acceptance run (dead host identified from
telemetry alone, totals matching journaled ground truth, merged trace
spanning all hosts) lives in tests/test_chaos.py (-m slow); here the
subprocesses are cheap observability-only drivers so the suite stays
inside tier-1's budget.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu import observability as obs  # noqa: E402
from lddl_tpu.observability import fleet, tracing  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_FLEET_ENVS = (fleet.ENV_FLEET_DIR, fleet.ENV_HOLDER, fleet.ENV_TTL,
               fleet.ENV_INTERVAL, "LDDL_TPU_METRICS_DIR",
               "LDDL_TPU_METRICS_RANK")


def _scrub_env():
    # Plain os.environ.pop, NOT monkeypatch.delenv: monkeypatch records
    # the deleted value and RESTORES it at teardown, which would leak an
    # armed metrics dir into later test modules.
    for name in _FLEET_ENVS:
        os.environ.pop(name, None)


@pytest.fixture
def clean_telemetry():
    """Fleet/metrics armed state is process-global env + module state;
    isolate each test."""
    _scrub_env()
    obs.registry().reset()
    tracing._reset_for_tests()
    fleet._reset_for_tests()
    yield
    _scrub_env()
    obs.registry().reset()
    tracing._reset_for_tests()
    fleet._reset_for_tests()


# ------------------------------------------------------------- publishing


def test_disabled_everything_is_noop(clean_telemetry, tmp_path):
    assert not fleet.enabled()
    fleet.record("unit.claimed", unit="u0", epoch=0)
    assert fleet.heartbeat() is None
    assert fleet.flush_events() is None
    fleet.ensure_started()
    assert fleet._hb["thread"] is None
    assert not os.path.isdir(str(tmp_path / ".telemetry"))


def test_spool_publish_and_roundtrip(clean_telemetry, tmp_path):
    root = str(tmp_path)
    spool = fleet.configure(root, holder_id="hostA", ttl=5, interval=60)
    assert spool == os.path.join(root, ".telemetry", "hostA")
    # configure() armed metrics into the spool (none were armed before).
    assert obs.metrics_dir() == spool
    fleet.record("unit.claimed", unit="group-1", epoch=0, holder="hostA")
    fleet.record("unit.journaled", unit="group-1", epoch=0, holder="hostA",
                 phase="gather")
    obs.inc("elastic_units_completed_total", 1, phase="gather")
    fleet.heartbeat()
    pid = os.getpid()
    events, torn = fleet.read_jsonl(
        os.path.join(spool, "events-pid{}.jsonl".format(pid)))
    assert torn == 0
    assert [ev["kind"] for ev in events] == ["unit.claimed",
                                             "unit.journaled"]
    assert all("wall" in ev and "mono" in ev for ev in events)
    snap = fleet._read_json(
        os.path.join(spool, "snapshot-pid{}.json".format(pid)))
    assert snap["holder"] == "hostA" and snap["closed"] is False
    assert snap["ttl_s"] == 5.0
    assert "elastic_units_completed_total" in snap["metrics"]
    # Clean shutdown marks the snapshot closed.
    fleet.heartbeat(closed=True, reason="test")
    snap = fleet._read_json(
        os.path.join(spool, "snapshot-pid{}.json".format(pid)))
    assert snap["closed"] is True and snap["closed_reason"] == "test"


def test_env_only_arming_colocates_metrics(clean_telemetry, tmp_path):
    """Arming via LDDL_TPU_FLEET_DIR alone (no configure(), no
    --fleet-telemetry) must still produce non-empty registry snapshots:
    the first record() points the metrics dir at the spool, so the
    status report never silently shows every counter as zero."""
    os.environ[fleet.ENV_FLEET_DIR] = str(tmp_path)
    os.environ[fleet.ENV_HOLDER] = "envhost"
    os.environ[fleet.ENV_INTERVAL] = "60"
    fleet.record("unit.claimed", unit="u0", epoch=0, holder="envhost")
    assert obs.metrics_dir() == fleet.spool_dir()
    obs.inc("elastic_units_completed_total", 1, phase="gather")
    fleet.heartbeat()
    report = fleet.aggregate(str(tmp_path))
    assert report["hosts"]["envhost"]["counters"]["units_completed"] == 1


def test_read_jsonl_torn_tail_is_end_of_stream(tmp_path):
    p = str(tmp_path / "events.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "a", "wall": 1.0}) + "\n")
        f.write(json.dumps({"kind": "b", "wall": 2.0}) + "\n")
        f.write('{"kind": "c", "wal')  # torn mid-append
    warnings = []
    records, torn = fleet.read_jsonl(p, warn=lambda msg, *a: warnings.append(
        msg % a if a else msg))
    assert [r["kind"] for r in records] == ["a", "b"]
    assert torn == 1
    assert any("end-of-stream" in w for w in warnings)
    # Torn INTERIOR line: skipped with a warning, the tail still parses.
    with open(p, "w") as f:
        f.write('{"kind": "a"\n')
        f.write(json.dumps({"kind": "b"}) + "\n")
        f.write(json.dumps({"kind": "c"}) + "\n")
    records, torn = fleet.read_jsonl(p, warn=lambda *a: None)
    assert [r["kind"] for r in records] == ["b", "c"] and torn == 1


# ------------------------------------------------------------- aggregation


def _fake_spool(root, holder, pid, wall, counters=None, gauges=None,
                closed=False, ttl=5.0, events=(), torn_tail=False,
                started=None):
    d = os.path.join(root, ".telemetry", holder)
    os.makedirs(d, exist_ok=True)
    metrics = {}
    for name, total in (counters or {}).items():
        metrics[name] = {"type": "counter", "values": {"": total}}
    for name, value in (gauges or {}).items():
        metrics[name] = {"type": "gauge", "values": {"": value}}
    snap = {"holder": holder, "pid": pid, "rank": 0, "wall": wall,
            "mono": 100.0, "started_wall": started if started is not None
            else wall - 60.0, "interval_s": 1.0, "ttl_s": ttl,
            "closed": closed, "metrics": metrics}
    with open(os.path.join(d, "snapshot-pid{}.json".format(pid)), "w") as f:
        json.dump(snap, f)
    with open(os.path.join(d, "events-pid{}.jsonl".format(pid)), "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")
        if torn_tail:
            f.write('{"kind": "unit.cl')
    return d


def test_aggregate_flags_dead_host_stalled(tmp_path):
    root = str(tmp_path)
    now = 10000.0
    _fake_spool(root, "h-live", 1, wall=now - 1.0, ttl=5.0,
                counters={"elastic_units_completed_total": 10,
                          "lease_steals_total": 2},
                events=[{"kind": "unit.journaled", "wall": now - 1.0,
                         "mono": 99.0, "pid": 1}])
    _fake_spool(root, "h-closed", 2, wall=now - 500.0, ttl=5.0, closed=True,
                counters={"elastic_units_completed_total": 5})
    _fake_spool(root, "h-dead", 3, wall=now - 300.0, ttl=5.0,
                counters={"elastic_units_completed_total": 9,
                          "lease_fence_rejects_total": 1},
                events=[{"kind": "unit.claimed", "wall": now - 301.0,
                         "mono": 50.0, "pid": 3}],
                torn_tail=True)
    report = fleet.aggregate(root, now=now, warn=lambda *a: None)
    health = report["health"]
    assert health["stalled_hosts"] == ["h-dead"]
    assert health["closed_hosts"] == ["h-closed"]
    assert health["live_hosts"] == ["h-live"]
    assert not health["ok"]
    assert any("h-dead" in v and "STALLED" in v for v in health["verdicts"])
    # The dead host's partial spool still contributes coherent numbers.
    assert report["hosts"]["h-dead"]["counters"]["units_completed"] == 9
    assert report["hosts"]["h-dead"]["torn_lines"] == 1
    assert report["totals"]["counters"]["units_completed"] == 24
    assert report["totals"]["counters"]["steals"] == 2
    assert report["totals"]["counters"]["fence_rejects"] == 1
    json.dumps(report)  # the --json contract: fully serializable


def test_wedge_requires_pending_work(tmp_path):
    root = str(tmp_path)
    now = 50000.0
    # A live host, heartbeating, whose last progress is ancient.
    old_progress = [{"kind": "generation.committed", "wall": now - 10000.0,
                     "mono": 1.0, "pid": 7}]
    _fake_spool(root, "svc", 7, wall=now - 1.0, ttl=5.0,
                events=old_progress)
    # No pending work -> idle, not wedged.
    report = fleet.aggregate(root, now=now, wedge_window=60.0)
    assert not report["health"]["wedged"] and report["health"]["ok"]
    # Pending work (nonzero backlog gauge) -> wedged.
    _fake_spool(root, "svc", 7, wall=now - 1.0, ttl=5.0,
                gauges={"ingest_backlog_docs": 12}, events=old_progress)
    report = fleet.aggregate(root, now=now, wedge_window=60.0)
    assert report["health"]["wedged"] and not report["health"]["ok"]
    assert any("WEDGED" in v for v in report["health"]["verdicts"])
    # Fresh progress inside the window heals it.
    _fake_spool(root, "svc", 7, wall=now - 1.0, ttl=5.0,
                gauges={"ingest_backlog_docs": 12},
                events=[{"kind": "generation.committed", "wall": now - 5.0,
                         "mono": 2.0, "pid": 7}])
    report = fleet.aggregate(root, now=now, wedge_window=60.0)
    assert not report["health"]["wedged"]


def test_wedge_no_progress_ever_counts_from_host_start(tmp_path):
    """A fresh service whose FIRST unit/generation is still in flight has
    no progress stamp at all — that must not instant-wedge it; the
    window counts from the earliest host start instead."""
    root = str(tmp_path)
    now = 90000.0
    # Started 10s ago, window 60s: healthy, just young.
    _fake_spool(root, "svc", 7, wall=now - 1.0, ttl=5.0,
                gauges={"ingest_backlog_docs": 3}, events=[],
                started=now - 10.0)
    report = fleet.aggregate(root, now=now, wedge_window=60.0)
    assert not report["health"]["wedged"], report["health"]["verdicts"]
    # Same host started 500s ago with still no progress: wedged.
    _fake_spool(root, "svc", 7, wall=now - 1.0, ttl=5.0,
                gauges={"ingest_backlog_docs": 3}, events=[],
                started=now - 500.0)
    report = fleet.aggregate(root, now=now, wedge_window=60.0)
    assert report["health"]["wedged"]


def test_cli_auto_holder_names_spool_and_leases_identically(tmp_path):
    """--fleet-telemetry on an elastic run WITHOUT --elastic-host-id must
    still give the spool and the lease files one shared holder name (an
    auto-generated lease holder is pinned into the args before the
    kwargs snapshot)."""
    from lddl_tpu.cli import common
    from lddl_tpu.cli.preprocess_bert_pretrain import attach_args
    _scrub_env()
    fleet._reset_for_tests()
    try:
        args = attach_args().parse_args(
            ["--wikipedia", "c", "--sink", str(tmp_path / "sink"),
             "--vocab-file", "v", "--elastic", "--fleet-telemetry"])
        assert args.elastic_host_id is None
        common.arm_fleet_if_requested(args, args.sink)
        assert args.elastic_host_id is not None
        assert fleet.holder() == args.elastic_host_id
        assert common.elastic_kwargs_of(args)["holder_id"] \
            == args.elastic_host_id
    finally:
        fleet._reset_for_tests()
        _scrub_env()


def test_pipeline_status_cli_exit_codes_and_json(tmp_path, capsys):
    from tools import pipeline_status

    root = str(tmp_path)
    _fake_spool(root, "h-ok", 1, wall=time.time(), closed=True,
                counters={"elastic_units_completed_total": 3})
    assert pipeline_status.main([root, "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["health"]["ok"]
    assert report["hosts"]["h-ok"]["counters"]["units_completed"] == 3
    # A stalled host flips the exit code to 2 in text mode too.
    _fake_spool(root, "h-dead", 2, wall=time.time() - 900.0, ttl=5.0,
                counters={"elastic_units_completed_total": 1})
    assert pipeline_status.main([root]) == 2
    out = capsys.readouterr().out
    assert "UNHEALTHY" in out and "STALLED" in out and "h-dead" in out


# ------------------------------------------------------------ trace merge


def test_clock_step_correction_unit():
    # Stable clock: no correction segments.
    assert fleet._step_corrections([(0.0, 2000.0), (10.0, 2010.0)]) == []
    # A +100s wall step between samples: later events shift back.
    segs = fleet._step_corrections([(0.0, 2000.0), (10.0, 2110.0)])
    assert segs == [(2110.0, pytest.approx(100.0))]
    assert fleet._corrected_ts(2115.0 * 1e6, segs) == \
        pytest.approx(2015.0 * 1e6)
    # Events before the step are untouched.
    assert fleet._corrected_ts(2005.0 * 1e6, segs) == \
        pytest.approx(2005.0 * 1e6)


def _write_trace(root, holder, pid, events):
    d = os.path.join(root, ".telemetry", holder)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "trace-rank0-pid{}.jsonl".format(pid)),
              "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def test_merge_traces_spans_hosts_with_alignment(tmp_path):
    root = str(tmp_path)
    # hostA: stable clock.
    _fake_spool(root, "hostA", 1, wall=3000.0,
                events=[{"kind": "clock", "wall": 1000.0, "mono": 0.0,
                         "pid": 1},
                        {"kind": "clock", "wall": 1010.0, "mono": 10.0,
                         "pid": 1}])
    _write_trace(root, "hostA", 1, [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "rank0 pid1"}},
        {"name": "preprocess.gather", "ph": "X", "ts": 1005.0 * 1e6,
         "dur": 5e6, "pid": 1, "tid": 1},
    ])
    # hostB: wall clock stepped +100s mid-run; pid collides with hostA's.
    _fake_spool(root, "hostB", 1, wall=4000.0,
                events=[{"kind": "clock", "wall": 2000.0, "mono": 0.0,
                         "pid": 1},
                        {"kind": "clock", "wall": 2110.0, "mono": 10.0,
                         "pid": 1}])
    _write_trace(root, "hostB", 1, [
        {"name": "preprocess.gather", "ph": "X", "ts": 2115.0 * 1e6,
         "dur": 5e6, "pid": 1, "tid": 1},
    ])
    events, lanes = fleet.merge_traces(root, warn=lambda *a: None)
    assert [(h, p) for _, h, p in lanes] == [("hostA", 1), ("hostB", 1)]
    names = {}
    spans = []
    for ev in events:
        if ev["ph"] == "M" and ev["name"] == "process_name":
            names[ev["pid"]] = ev["args"]["name"]
        elif ev["ph"] == "X":
            spans.append(ev)
    # Per-host lanes: the colliding real pids land on distinct lane pids.
    assert sorted(names.values()) == ["hostA pid1", "hostB pid1"]
    assert len({ev["pid"] for ev in spans}) == 2
    # hostB's post-step span was re-anchored (2115 -> 2015).
    by_lane = {names[ev["pid"]]: ev for ev in spans}
    assert by_lane["hostB pid1"]["ts"] == pytest.approx(2015.0 * 1e6)
    assert by_lane["hostA pid1"]["ts"] == pytest.approx(1005.0 * 1e6)


def test_trace_summary_merge_cli(tmp_path, capsys):
    from tools import trace_summary

    root = str(tmp_path)
    _fake_spool(root, "hostA", 1, wall=3000.0)
    _write_trace(root, "hostA", 1, [
        {"name": "preprocess.gather", "ph": "X", "ts": 1e9, "dur": 1e6,
         "pid": 1, "tid": 1}])
    _fake_spool(root, "hostB", 2, wall=3000.0)
    _write_trace(root, "hostB", 2, [
        {"name": "balance.run", "ph": "X", "ts": 2e9, "dur": 1e6,
         "pid": 2, "tid": 1}])
    out_path = str(tmp_path / "merged.json")
    assert trace_summary.main([root, "--merge", out_path]) == 0
    text = capsys.readouterr().out
    # Summary mode found both hosts' spool traces via .telemetry/.
    assert "preprocess" in text and "balance" in text
    merged = json.load(open(out_path))
    lanes = {ev["args"]["name"] for ev in merged
             if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    assert lanes == {"hostA pid1", "hostB pid2"}


# ------------------------------------------------- abnormal-exit flushing

_SIGTERM_DRIVER = """
import os, sys, time
root = sys.argv[1]
os.environ["LDDL_TPU_FLEET_DIR"] = root
os.environ["LDDL_TPU_FLEET_HOLDER"] = "polite"
os.environ["LDDL_TPU_FLEET_INTERVAL_S"] = "3600"  # only exit paths flush
from lddl_tpu.observability import fleet
fleet.ensure_started()
fleet.record("unit.claimed", unit="group-0", epoch=0, holder="polite")
print("READY", flush=True)
time.sleep(120)
"""

_SIGKILL_DRIVER = """
import os, sys, time
root = sys.argv[1]
os.environ["LDDL_TPU_FLEET_DIR"] = root
os.environ["LDDL_TPU_FLEET_HOLDER"] = "victim"
os.environ["LDDL_TPU_FLEET_TTL_S"] = "2"
os.environ["LDDL_TPU_FLEET_INTERVAL_S"] = "0.05"
from lddl_tpu.observability import fleet
import lddl_tpu.observability as obs
fleet.configure(root, holder_id="victim", ttl=2, interval=0.05)
i = 0
while True:
    fleet.record("unit.claimed", unit="g%d" % i, epoch=0, holder="victim")
    obs.inc("elastic_units_completed_total", 1, phase="gather")
    fleet.record("unit.journaled", unit="g%d" % i, epoch=0,
                 holder="victim")
    i += 1
    time.sleep(0.01)
"""


def _spawn(driver, root):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    for name in _FLEET_ENVS:
        env.pop(name, None)
    return subprocess.Popen([sys.executable, "-c", driver, root],
                            env=env, cwd=REPO_ROOT, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def test_sigterm_flushes_events_and_marks_closed(tmp_path):
    """A politely-killed host (TERM) leaves a fully-flushed spool with a
    clean-shutdown marker — the heartbeat interval is set far past the
    test, so ONLY the signal handler can have written these bytes."""
    root = str(tmp_path)
    proc = _spawn(_SIGTERM_DRIVER, root)
    assert proc.stdout.readline().strip() == "READY"
    proc.send_signal(signal.SIGTERM)
    out = proc.communicate(timeout=60)[0]
    assert proc.returncode == -signal.SIGTERM, out
    spool = os.path.join(root, ".telemetry", "polite")
    events_files = [n for n in sorted(os.listdir(spool))
                    if n.startswith("events-pid")]
    assert events_files, sorted(os.listdir(spool))
    records, torn = fleet.read_jsonl(os.path.join(spool, events_files[0]))
    assert torn == 0
    assert [r["kind"] for r in records] == ["unit.claimed"]
    snaps = [n for n in sorted(os.listdir(spool))
             if n.startswith("snapshot-pid")]
    snap = fleet._read_json(os.path.join(spool, snaps[0]))
    assert snap["closed"] is True and snap["closed_reason"] == "sigterm"
    # Closed hosts are never stall-flagged, no matter how old the beat.
    report = fleet.aggregate(root, now=time.time() + 10000.0)
    assert report["health"]["stalled_hosts"] == []
    assert report["health"]["closed_hosts"] == ["polite"]


_SIGIGN_DRIVER = """
import os, signal, sys, time
signal.signal(signal.SIGTERM, signal.SIG_IGN)  # app chose to ignore TERM
root = sys.argv[1]
os.environ["LDDL_TPU_FLEET_DIR"] = root
os.environ["LDDL_TPU_FLEET_HOLDER"] = "ignorer"
os.environ["LDDL_TPU_FLEET_INTERVAL_S"] = "3600"
from lddl_tpu.observability import fleet
fleet.ensure_started()
fleet.record("unit.claimed", unit="g0", epoch=0, holder="ignorer")
print("READY", flush=True)
time.sleep(2.0)
print("SURVIVED", flush=True)
"""


def test_sigterm_flush_preserves_sig_ign(tmp_path):
    """A process that had SIGTERM ignored must stay ignored: the flush
    handler flushes the spool but never turns an ignored signal into a
    death."""
    root = str(tmp_path)
    proc = _spawn(_SIGIGN_DRIVER, root)
    assert proc.stdout.readline().strip() == "READY"
    proc.send_signal(signal.SIGTERM)
    out = proc.communicate(timeout=60)[0]
    assert proc.returncode == 0, out
    assert "SURVIVED" in out
    spool = os.path.join(root, ".telemetry", "ignorer")
    events_files = [n for n in sorted(os.listdir(spool))
                    if n.startswith("events-pid")]
    records, _ = fleet.read_jsonl(os.path.join(spool, events_files[0]))
    assert any(r["kind"] == "unit.claimed" for r in records)


def test_sigkill_leaves_parseable_spool_and_stall_verdict(tmp_path):
    """A SIGKILLed host can flush nothing at death; the heartbeat trail
    it left must still aggregate into a coherent report that flags it
    stalled (no clean-shutdown marker) and preserves its counters."""
    root = str(tmp_path)
    proc = _spawn(_SIGKILL_DRIVER, root)
    spool = os.path.join(root, ".telemetry", "victim")
    deadline = time.monotonic() + 60.0
    target = os.path.join(spool, "snapshot-pid{}.json".format(proc.pid))
    while time.monotonic() < deadline:
        snap = fleet._read_json(target, warn=lambda *a: None) \
            if os.path.exists(target) else None
        if snap and fleet._counter_total(
                snap.get("metrics"), "elastic_units_completed_total") >= 5:
            break
        time.sleep(0.02)
    proc.kill()
    proc.communicate(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    report = fleet.aggregate(root, now=time.time() + 60.0,
                             warn=lambda *a: None)
    host = report["hosts"]["victim"]
    assert not host["closed"]
    assert report["health"]["stalled_hosts"] == ["victim"]
    assert host["counters"]["units_completed"] >= 5
    assert host["event_counts"].get("unit.claimed", 0) >= 1
    json.dumps(report)


# ----------------------------------------------- byte-inertness (elastic)


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("fleet")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def goldens():
    with open(gs.GOLDEN_FILE) as f:
        return json.load(f)


def _bert_processor(vocab, out_dir):
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    from lddl_tpu.preprocess.runner import BertBucketProcessor
    tok = get_tokenizer(vocab_file=vocab)
    cfg = BertPretrainConfig(max_seq_length=32, masking=True,
                             schema_version=1)
    return BertBucketProcessor(tok, cfg, 4242, out_dir, 8, "parquet")


_RUN_KW = dict(num_blocks=12, sample_ratio=0.9, seed=4242,
               global_shuffle=True, progress_interval=0.0)


def test_two_host_elastic_with_fleet_is_byte_inert_and_aggregates(
        clean_telemetry, fixture_dirs, goldens, tmp_path, capsys):
    """The acceptance pin, fast flavor: a 2-host elastic run with fleet
    telemetry armed produces shards byte-identical to the pinned goldens
    (= a telemetry-off run) and a manifest byte-identical to a
    telemetry-off elastic run, while the spool aggregates to the run's
    journaled ground truth (24 units) and the merged trace carries the
    stage spans."""
    from lddl_tpu.preprocess.runner import run_sharded_pipeline

    td, corpus, vocab = fixture_dirs
    # Reference: telemetry-off elastic run (same plan).
    ref = str(tmp_path / "ref")
    run_sharded_pipeline({"wikipedia": corpus}, ref,
                         _bert_processor(vocab, ref), elastic=True,
                         lease_ttl=5.0, holder_id="refhost", **_RUN_KW)
    assert gs.hash_outputs(ref) == goldens["binned_masked"]

    out = str(tmp_path / "out")
    fleet.configure(out, holder_id="fleethost", ttl=5.0, interval=60)
    procs = {h: _bert_processor(vocab, out) for h in ("hostA", "hostB")}
    results, errors = {}, {}

    def host(hid, delay):
        time.sleep(delay)
        try:
            results[hid] = run_sharded_pipeline(
                {"wikipedia": corpus}, out, procs[hid], elastic=True,
                lease_ttl=5.0, holder_id=hid, **_RUN_KW)
        except Exception as e:  # noqa: BLE001 - surfaced via assert
            errors[hid] = e

    threads = [threading.Thread(target=host, args=("hostA", 0.0)),
               threading.Thread(target=host, args=("hostB", 0.1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    # Shards: byte-identical to the goldens (telemetry-off bytes).
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    # Manifest: byte-identical to the telemetry-off elastic reference.
    with open(os.path.join(ref, ".manifest.json"), "rb") as f:
        want = f.read()
    with open(os.path.join(out, ".manifest.json"), "rb") as f:
        assert f.read() == want
    # Spool aggregates to the journaled ground truth: 12 scatter + 12
    # gather units, all lifecycle-logged (both thread-hosts share one
    # process, hence one spool).
    fleet.heartbeat(closed=True, reason="test")
    report = fleet.aggregate(out)
    assert report["totals"]["counters"]["units_completed"] == 24
    counts = report["hosts"]["fleethost"]["event_counts"]
    assert counts.get("unit.journaled") == 24
    assert counts.get("unit.claimed", 0) >= 24  # epoch-0 claims
    assert report["health"]["ok"], report["health"]["verdicts"]

    # pipeline_status --json over the same artifacts agrees.
    from tools import pipeline_status
    assert pipeline_status.main([out, "--json"]) == 0
    cli_report = json.loads(capsys.readouterr().out)
    assert cli_report["totals"]["counters"]["units_completed"] == 24

    # The merged trace spans the run's stage spans.
    events, lanes = fleet.merge_traces(out)
    span_names = {ev.get("name") for ev in events if ev.get("ph") == "X"}
    assert "preprocess.gather" in span_names
    assert "preprocess.finalize" in span_names
    assert lanes and lanes[0][1] == "fleethost"


# --------------------------------------------- byte-inertness (ingest)


def test_ingest_with_fleet_is_byte_inert_and_logs_lifecycle(
        clean_telemetry, fixture_dirs, tmp_path):
    """Streaming-ingest flavor of the inertness pin: fleet telemetry on
    vs off leaves shards, manifests, the intake journal, and the loader's
    batch stream byte-identical — and the spool carries the generation
    lifecycle (intake -> preprocess -> delta-balance -> gate-advance ->
    committed)."""
    import shutil

    from lddl_tpu.ingest import ingest_once
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer

    td, corpus, vocab = fixture_dirs
    tok = get_tokenizer(vocab_file=vocab)
    cfg = BertPretrainConfig(max_seq_length=32, masking=False)
    landing = str(tmp_path / "landing")
    os.makedirs(os.path.join(landing, "source"))
    shutil.copy(os.path.join(corpus, "source", "0.txt"),
                os.path.join(landing, "source", "0.txt"))
    kw = dict(config=cfg, num_shards=4, seed=7, num_blocks=4)

    root_off = str(tmp_path / "off")
    ingest_once(root_off, tok, landing=landing, **kw)

    root_on = str(tmp_path / "on")
    fleet.configure(root_on, holder_id="svc", ttl=5.0, interval=60)
    ingest_once(root_on, tok, landing=landing, **kw)
    fleet.heartbeat(closed=True)

    def tree_bytes(root):
        out = {}
        for base, dirs, files in os.walk(root):
            dirs[:] = sorted(d for d in dirs if d != ".telemetry")
            for name in sorted(files):
                p = os.path.join(base, name)
                with open(p, "rb") as f:
                    out[os.path.relpath(p, root)] = f.read()
        return out

    off, on = tree_bytes(root_off), tree_bytes(root_on)
    assert sorted(off) == sorted(on)
    for rel in off:
        assert on[rel] == off[rel], rel

    a = [{k: v for k, v in b.items()} for b in get_bert_pretrain_data_loader(
        root_off, vocab_file=vocab, batch_size=8, base_seed=5)]
    b = [{k: v for k, v in b.items()} for b in get_bert_pretrain_data_loader(
        root_on, vocab_file=vocab, batch_size=8, base_seed=5,
        follow_generations=True)]
    assert len(a) == len(b) and len(a) > 0
    import numpy as np
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(np.asarray(x[k]),
                                          np.asarray(y[k]), err_msg=k)

    report = fleet.aggregate(root_on)
    counts = report["hosts"]["svc"]["event_counts"]
    for kind in ("generation.intake", "generation.preprocess",
                 "generation.delta_balance", "generation.gate_advance",
                 "generation.committed"):
        assert counts.get(kind, 0) >= 1, (kind, counts)
    assert report["health"]["ok"], report["health"]["verdicts"]
