"""Core utils: bin-id filename protocol, parquet helpers, serialization."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from lddl_tpu.utils import (
    File,
    get_all_bin_ids,
    get_all_parquets_under,
    get_file_paths_for_bin_id,
    get_num_samples_of_parquet,
    serialize_np_array,
    deserialize_np_array,
)
from lddl_tpu.utils.fs import (
    get_bin_id_of_path,
    read_num_samples_cache,
    write_num_samples_cache,
)
from lddl_tpu.utils.args import parse_str_of_num_bytes


def test_bin_id_protocol():
    assert get_bin_id_of_path("/x/part.0.parquet_3") == 3
    assert get_bin_id_of_path("/x/part.0.parquet_12") == 12
    assert get_bin_id_of_path("/x/part.0.parquet") is None
    assert get_bin_id_of_path("/x/shard-5.parquet_0") == 0


def test_bin_ids_contiguous():
    paths = ["a.parquet_1", "b.parquet_0", "c.parquet_2", "d.parquet_1"]
    assert get_all_bin_ids(paths) == [0, 1, 2]
    assert get_file_paths_for_bin_id(paths, 1) == ["a.parquet_1", "d.parquet_1"]
    with pytest.raises(ValueError):
        get_all_bin_ids(["a.parquet_1", "b.parquet_2"])


def test_parquet_discovery_and_counts(tmp_path):
    t = pa.table({"A": ["a b c", "d e"], "num_tokens": [3, 2]})
    p0 = str(tmp_path / "part.0.parquet")
    p1 = str(tmp_path / "part.1.parquet_0")
    pq.write_table(t, p0)
    pq.write_table(t, p1)
    (tmp_path / "notes.txt").write_text("not a shard")
    (tmp_path / ".num_samples.json").write_text("{}")
    found = get_all_parquets_under(str(tmp_path))
    assert found == [p0, p1]
    assert get_num_samples_of_parquet(p0) == 2


def test_num_samples_cache_roundtrip(tmp_path):
    counts = {"shard-0.parquet": 10, "shard-1.parquet": 11}
    write_num_samples_cache(str(tmp_path), counts)
    assert read_num_samples_cache(str(tmp_path)) == counts
    assert read_num_samples_cache(str(tmp_path / "missing")) is None


def test_np_array_serialization():
    for a in [np.array([1, 5, 9], dtype=np.int64),
              np.array([], dtype=np.int32),
              np.arange(12, dtype=np.uint16)]:
        b = serialize_np_array(a)
        assert isinstance(b, bytes)
        out = deserialize_np_array(b)
        np.testing.assert_array_equal(a, out)
        assert a.dtype == out.dtype


def test_parse_size():
    assert parse_str_of_num_bytes("128") == 128
    assert parse_str_of_num_bytes("4k") == 4096
    assert parse_str_of_num_bytes("2M") == 2 * 1024**2
    assert parse_str_of_num_bytes("1G") == 1024**3


def test_file_type():
    f = File("/a/b.parquet", 17)
    assert f.path == "/a/b.parquet"
    assert f.num_samples == 17
