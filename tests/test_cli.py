"""Console-script surfaces: every entry point builds its parser and
rejects bad input cleanly (the reference's 8-script surface,
setup.py:63-73 / our pyproject [project.scripts])."""

import pytest


ENTRY_POINTS = [
    ("lddl_tpu.download.wikipedia", "attach_args"),
    ("lddl_tpu.download.books", "attach_args"),
    ("lddl_tpu.download.openwebtext", "attach_args"),
    ("lddl_tpu.download.common_crawl", "attach_args"),
    ("lddl_tpu.cli.preprocess_bert_pretrain", "attach_args"),
    ("lddl_tpu.cli.preprocess_bart_pretrain", "attach_args"),
    ("lddl_tpu.cli.balance_shards", "attach_args"),
    ("lddl_tpu.cli.generate_num_samples_cache", "attach_args"),
]


@pytest.mark.parametrize("module,fn", ENTRY_POINTS)
def test_entry_point_parser_builds(module, fn):
    import importlib
    mod = importlib.import_module(module)
    parser = getattr(mod, fn)()
    # --help exits 0; unknown flags exit nonzero.
    with pytest.raises(SystemExit) as e:
        parser.parse_args(["--help"])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        parser.parse_args(["--definitely-not-a-flag"])
    assert e.value.code != 0


def test_pyproject_scripts_resolve():
    """Every [project.scripts] target exists and is callable."""
    import importlib
    import re
    with open("pyproject.toml") as f:
        text = f.read()
    block = re.search(r"\[project\.scripts\]\n(.*?)\n\[", text,
                      re.S).group(1)
    entries = re.findall(r'^\S+ = "([\w\.]+):(\w+)"', block, re.M)
    assert len(entries) == 8
    for module, attr in entries:
        assert callable(getattr(importlib.import_module(module), attr))
