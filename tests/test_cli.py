"""Console-script surfaces: every entry point builds its parser and
rejects bad input cleanly (the reference's 8-script surface,
setup.py:63-73 / our pyproject [project.scripts])."""

import os

import pytest


ENTRY_POINTS = [
    ("lddl_tpu.download.wikipedia", "attach_args"),
    ("lddl_tpu.download.books", "attach_args"),
    ("lddl_tpu.download.openwebtext", "attach_args"),
    ("lddl_tpu.download.common_crawl", "attach_args"),
    ("lddl_tpu.cli.preprocess_bert_pretrain", "attach_args"),
    ("lddl_tpu.cli.preprocess_bart_pretrain", "attach_args"),
    ("lddl_tpu.cli.balance_shards", "attach_args"),
    ("lddl_tpu.cli.generate_num_samples_cache", "attach_args"),
    ("lddl_tpu.cli.ingest_watch", "attach_args"),
]


@pytest.mark.parametrize("module,fn", ENTRY_POINTS)
def test_entry_point_parser_builds(module, fn):
    import importlib
    mod = importlib.import_module(module)
    parser = getattr(mod, fn)()
    # --help exits 0; unknown flags exit nonzero.
    with pytest.raises(SystemExit) as e:
        parser.parse_args(["--help"])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        parser.parse_args(["--definitely-not-a-flag"])
    assert e.value.code != 0


def test_pyproject_scripts_resolve():
    """Every [project.scripts] target exists and is callable."""
    import importlib
    import re
    with open("pyproject.toml") as f:
        text = f.read()
    block = re.search(r"\[project\.scripts\]\n(.*?)\n\[", text,
                      re.S).group(1)
    entries = re.findall(r'^\S+ = "([\w\.]+):(\w+)"', block, re.M)
    assert len(entries) == 9
    for module, attr in entries:
        assert callable(getattr(importlib.import_module(module), attr))


def test_elastic_flags_parse_and_forward():
    """--elastic/--lease-ttl/--elastic-host-id/--scatter-units parse on
    both preprocess CLIs and map onto the runner kwargs."""
    from lddl_tpu.cli import common
    from lddl_tpu.cli.preprocess_bert_pretrain import attach_args
    args = attach_args().parse_args(
        ["--wikipedia", "c", "--sink", "s", "--vocab-file", "v",
         "--elastic", "--lease-ttl", "45", "--elastic-host-id", "h1",
         "--scatter-units", "8"])
    assert common.elastic_kwargs_of(args) == {
        "elastic": True, "lease_ttl": 45.0, "holder_id": "h1",
        "scatter_units": 8}
    # Defaults: elastic off, nothing else forced.
    args = attach_args().parse_args(
        ["--wikipedia", "c", "--sink", "s", "--vocab-file", "v"])
    kw = common.elastic_kwargs_of(args)
    assert kw["elastic"] is False and kw["holder_id"] is None


def test_fleet_telemetry_flag_parses_and_arms(tmp_path):
    """--fleet-telemetry parses on the preprocess and ingest CLIs and
    arms the fleet env (spool under <sink>/.telemetry/<holder>/, metrics
    colocated); without the flag nothing is armed."""
    # Plain os.environ.pop, NOT monkeypatch.delenv: monkeypatch would
    # RESTORE the armed value at teardown and leak it into later modules.
    for name in ("LDDL_TPU_FLEET_DIR", "LDDL_TPU_FLEET_HOLDER",
                 "LDDL_TPU_FLEET_TTL_S", "LDDL_TPU_FLEET_INTERVAL_S",
                 "LDDL_TPU_METRICS_DIR"):
        os.environ.pop(name, None)
    from lddl_tpu.cli import common
    from lddl_tpu.cli.ingest_watch import attach_args as ingest_args
    from lddl_tpu.cli.preprocess_bert_pretrain import attach_args
    from lddl_tpu.observability import fleet
    fleet._reset_for_tests()
    sink = str(tmp_path / "sink")
    args = attach_args().parse_args(
        ["--wikipedia", "c", "--sink", sink, "--vocab-file", "v"])
    assert args.fleet_telemetry is False
    common.arm_fleet_if_requested(args, args.sink)
    assert not fleet.enabled()
    args = ingest_args().parse_args(
        ["--landing", "l", "--sink", sink, "--vocab-file", "v",
         "--fleet-telemetry", "--elastic-host-id", "hZ",
         "--lease-ttl", "7"])
    assert args.fleet_telemetry is True
    common.arm_fleet_if_requested(args, args.sink)
    try:
        assert fleet.enabled() and fleet.fleet_dir() == sink
        assert fleet.holder() == "hZ"
        assert fleet.spool_dir() == os.path.join(sink, ".telemetry", "hZ")
        import lddl_tpu.observability as obs
        assert obs.metrics_dir() == fleet.spool_dir()
    finally:
        fleet._reset_for_tests()
        for name in ("LDDL_TPU_FLEET_DIR", "LDDL_TPU_FLEET_HOLDER",
                     "LDDL_TPU_FLEET_TTL_S", "LDDL_TPU_FLEET_INTERVAL_S",
                     "LDDL_TPU_METRICS_DIR"):
            os.environ.pop(name, None)


def test_elastic_and_multihost_mutually_exclusive():
    from lddl_tpu.cli import common
    from lddl_tpu.cli.preprocess_bart_pretrain import attach_args
    args = attach_args().parse_args(
        ["--wikipedia", "c", "--sink", "s", "--elastic", "--multihost"])
    with pytest.raises(SystemExit, match="mutually exclusive"):
        common.elastic_kwargs_of(args)
