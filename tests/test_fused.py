"""Fused zero-copy preprocess hot path: byte identity + replay contracts.

PR 9 collapses the per-bucket BERT pipeline into one native pass
(lddl_bert_instances: split + normalize + WordPiece + NSP pairs) fed
zero-copy from the spool reader (readers.DocSpans) and drained zero-copy
into Arrow buffers, plus a native replay of the numpy static-masking
stream (lddl_mask_batch). Every rung of the runtime ladder
(fused -> staged native -> hf) must emit byte-identical shards; these
tests pin that, the numpy-Philox replay contract, the vectorized spool
parsers, and the .so staleness metadata.
"""

import gc
import hashlib
import os

import numpy as np
import pytest

from lddl_tpu import native
from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer
from lddl_tpu.preprocess.bert import TokenizerInfo
from lddl_tpu.utils import rng as lrng

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native engine did not build")

from test_native import DOCS  # noqa: E402  (shared corpus fixture)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("fvocab") / "vocab.txt"
    return build_wordpiece_vocab(DOCS * 3, str(path), vocab_size=400)


@pytest.fixture(scope="module")
def hf_tokenizer(vocab_file):
    return get_tokenizer(vocab_file=vocab_file)


@pytest.fixture()
def corpus_dir(tmp_path):
    source = tmp_path / "corpus" / "source"
    source.mkdir(parents=True)
    with open(source / "0.txt", "w", encoding="utf-8") as f:
        for i, d in enumerate(DOCS * 4):
            if d.strip():
                f.write("doc-{} {}\n".format(i, d.replace("\n", " ")
                                             .replace("\r", " ")
                                             .replace("\t", " ")
                                             .replace("\x00", "")))
    return str(tmp_path / "corpus")


def _shard_hashes(out_dir):
    digests = {}
    for name in sorted(os.listdir(out_dir)):
        if "parquet" in name or name.endswith(".txt"):
            with open(os.path.join(out_dir, name), "rb") as f:
                digests[name] = hashlib.sha256(f.read()).hexdigest()
    return digests


# ---------------------------------------------------------------------------
# numpy-Philox replay (the masking stream contract)
# ---------------------------------------------------------------------------


def test_philox_replay_parity():
    """sample_key_bytes reconstructs sample_rng's exact stream: the key is
    the whole contract the C++ replay builds on."""
    for seed, scope in [(0, ()), (12345, (0x3A5C, 7)), (99, (1, 2, 3))]:
        key = lrng.sample_key_bytes(seed, *scope)
        g = np.random.Generator(
            np.random.Philox(key=np.frombuffer(key, dtype=np.uint64)))
        ref = lrng.sample_rng(seed, *scope)
        assert np.array_equal(g.random(17), ref.random(17))
        assert np.array_equal(g.integers(0, 30522, 17, dtype=np.int64),
                              ref.integers(0, 30522, 17, dtype=np.int64))


def test_native_mask_matches_numpy():
    """The C++ masking kernel is a bit-exact replay of mask_batch_numpy on
    the same stream — shapes, vocab sizes and degenerate rows included."""
    from lddl_tpu.ops.masking import mask_batch_numpy
    g0 = np.random.default_rng(11)
    cases = [(0, 16, 100), (1, 8, 2), (5, 128, 30522), (40, 128, 377),
             (17, 64, 4_000_000), (3, 128, 30522)]
    for trial, (n, width, vocab) in enumerate(cases):
        ids = g0.integers(0, vocab, (n, width)).astype(np.int32)
        cand = g0.random((n, width)) < 0.6
        ntp = g0.integers(0, 30, n).astype(np.int64)
        if n:
            ntp[0] = 0            # selected[ntp<=0] = False branch
            cand[-1] = False      # all-inf row
        key = lrng.sample_key_bytes(7, 0x3A5C, trial)
        got = native.mask_batch(key, ids, cand, ntp, 4, vocab)
        assert got is not None
        m_ref, s_ref = mask_batch_numpy(ids, cand, ntp,
                                        lrng.sample_rng(7, 0x3A5C, trial),
                                        4, vocab)
        np.testing.assert_array_equal(got[0], m_ref)
        np.testing.assert_array_equal(got[1], s_ref)


def test_native_mask_refuses_out_of_contract_vocab():
    """vocab sizes outside [2, 2^32) fall back to numpy (return None)
    instead of silently diverging from the frozen integers replay."""
    ids = np.zeros((2, 8), dtype=np.int32)
    cand = np.ones((2, 8), dtype=bool)
    ntp = np.ones(2, dtype=np.int64)
    key = lrng.sample_key_bytes(1)
    assert native.mask_batch(key, ids, cand, ntp, 0, 1) is None
    assert native.mask_batch(key, ids, cand, ntp, 0, 2**33) is None


# ---------------------------------------------------------------------------
# Fused kernel vs staged engine (in-process arrays)
# ---------------------------------------------------------------------------


def test_fused_matches_staged_arrays(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d for d in DOCS if d.strip()] * 4
    for seed, bucket in [(0, 0), (12345, 7)]:
        ids, sl, dc = nat.tokenize_docs(texts)
        ref = native.bert_pairs(ids, sl, dc, 48, 0.1, 3, seed, bucket,
                                info.cls_id, info.sep_id)
        got = nat.bert_instances(texts, 48, 0.1, 3, seed, bucket,
                                 info.cls_id, info.sep_id, want_ab=True)
        seq_ids, seq_lens, a_lens, rn, a_ids, b_ids = got
        np.testing.assert_array_equal(seq_ids, ref[0])
        np.testing.assert_array_equal(seq_lens, ref[1])
        np.testing.assert_array_equal(a_lens, ref[2])
        np.testing.assert_array_equal(rn, ref[3])
        # want_ab: the flat A/B segments must equal the per-row slices.
        offs = np.cumsum(seq_lens) - seq_lens
        flat_a = ref[0][np.concatenate(
            [np.arange(o + 1, o + 1 + a)
             for o, a in zip(offs, a_lens)]).astype(np.int64)] \
            if len(a_lens) else np.zeros(0, np.int32)
        np.testing.assert_array_equal(a_ids, flat_a)
        assert len(b_ids) == len(seq_ids) - len(a_ids) - 3 * len(seq_lens)


def _expected_masked_arrays(info, texts, config_kw, seed, bucket, scope):
    """Ground truth for the fused-masked kernel: the staged path run
    entirely through the NUMPY masking engine (fused instances + padded
    matrix + mask_batch_numpy), re-deriving exactly the flat arrays
    materialize_columns' masking branch gathers."""
    from lddl_tpu.preprocess.arrowcols import concat_aranges
    from lddl_tpu.preprocess.bert import (BertPretrainConfig, InstanceBatch,
                                          apply_static_masking)
    cfg = BertPretrainConfig(**config_kw)
    nat = info.native_tokenizer()
    seq_ids, seq_lens, a_lens, rn, _, _ = nat.bert_instances(
        texts, cfg.max_seq_length, cfg.short_seq_prob, cfg.duplicate_factor,
        seed, bucket, info.cls_id, info.sep_id)
    batch = InstanceBatch(seq_ids, seq_lens, a_lens, rn)
    prior = os.environ.get("LDDL_TPU_NATIVE_MASK")
    os.environ["LDDL_TPU_NATIVE_MASK"] = "0"  # force the numpy reference
    try:
        masked, selected, ids, a_lens, seq_lens = apply_static_masking(
            batch, cfg, info, seed, scope)
    finally:
        if prior is None:
            del os.environ["LDDL_TPU_NATIVE_MASK"]
        else:
            os.environ["LDDL_TPU_NATIVE_MASK"] = prior
    n = len(seq_lens)
    a_lens = np.asarray(a_lens, dtype=np.int64)
    seq_lens = np.asarray(seq_lens, dtype=np.int64)
    b_lens = seq_lens - a_lens - 3
    rows = np.arange(n, dtype=np.int64)
    flat_a = masked[np.repeat(rows, a_lens), 1 + concat_aranges(a_lens)]
    flat_b = masked[np.repeat(rows, b_lens),
                    np.repeat(2 + a_lens, b_lens) + concat_aranges(b_lens)]
    sel_rows, sel_cols = np.nonzero(selected)
    sel_lens = np.bincount(sel_rows, minlength=n)
    return (a_lens, seq_lens, np.asarray(rn, bool), flat_a, flat_b,
            sel_cols, sel_lens, ids[sel_rows, sel_cols])


def test_fused_masked_matches_numpy_replay(hf_tokenizer):
    """lddl_bert_instances_masked is a bit-exact replay of the staged
    numpy path: same instances, same Philox selections, same 80/10/10
    replacements, same row-relative positions and labels — across
    seq-length/ratio shapes."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d for d in DOCS if d.strip()] * 4
    for seed, bucket, msl, ratio, mp in [(7, 0, 48, 0.15, None),
                                         (12345, 3, 128, 0.15, None),
                                         (9, 1, 48, 0.4, 5)]:
        kw = dict(max_seq_length=msl, duplicate_factor=2,
                  masking=True, masked_lm_ratio=ratio)
        if mp is not None:
            kw["max_predictions_per_seq"] = mp
        from lddl_tpu.preprocess.bert import BertPretrainConfig
        cfg = BertPretrainConfig(**kw)
        scope = (0x3A5C, bucket)
        got = nat.bert_instances_masked(
            texts, cfg.max_seq_length, cfg.short_seq_prob,
            cfg.duplicate_factor, seed, bucket, info.cls_id, info.sep_id,
            lrng.sample_key_bytes(seed, *scope), info.mask_id,
            info.vocab_size, cfg.masked_lm_ratio,
            cfg.max_predictions_per_seq, min(128, cfg.max_seq_length))
        assert got is not None
        ref = _expected_masked_arrays(info, texts, kw, seed, bucket, scope)
        names = ("a_lens", "seq_lens", "is_random_next", "flat_a",
                 "flat_b", "sel_positions", "sel_lens", "label_ids")
        for name, g_arr, r_arr in zip(names, got, ref):
            np.testing.assert_array_equal(np.asarray(g_arr),
                                          np.asarray(r_arr), err_msg=name)


def test_fused_masked_out_of_contract_refuses_into_ladder(hf_tokenizer,
                                                          monkeypatch):
    """masked_instances_from_texts must return None — never a diverging
    engine fork — for every parameter outside the frozen replay contract
    (wwm, jax engine, out-of-range vocab, force-disable env)."""
    from lddl_tpu.preprocess.bert import (BertPretrainConfig,
                                          masked_instances_from_texts)
    info = TokenizerInfo(hf_tokenizer)
    texts = [d for d in DOCS if d.strip()]
    base = dict(max_seq_length=48, duplicate_factor=1, masking=True)

    def attempt(cfg):
        return masked_instances_from_texts(texts, info, cfg, 7, 0,
                                           (0x3A5C, 0))

    assert attempt(BertPretrainConfig(**base)) is not None
    assert attempt(BertPretrainConfig(whole_word_masking=True,
                                      **base)) is None
    assert attempt(BertPretrainConfig(engine="jax", **base)) is None
    assert attempt(BertPretrainConfig(masking=False, max_seq_length=48,
                                      duplicate_factor=1)) is None
    monkeypatch.setattr(info, "vocab_size", 2**33)
    assert attempt(BertPretrainConfig(**base)) is None
    monkeypatch.undo()
    monkeypatch.setenv("LDDL_TPU_NATIVE_FUSED_MASK", "0")
    assert attempt(BertPretrainConfig(**base)) is None
    monkeypatch.delenv("LDDL_TPU_NATIVE_FUSED_MASK")
    # The global "no C++ masking" triage knob must drop this rung too.
    monkeypatch.setenv("LDDL_TPU_NATIVE_MASK", "0")
    assert attempt(BertPretrainConfig(**base)) is None
    monkeypatch.delenv("LDDL_TPU_NATIVE_MASK")
    monkeypatch.setenv("LDDL_TPU_NATIVE_FUSED", "0")
    assert attempt(BertPretrainConfig(**base)) is None


def test_fused_masked_identity_across_mask_ladder(hf_tokenizer, corpus_dir,
                                                  tmp_path, monkeypatch):
    """Shard bytes are identical whether masking ran fused in-kernel,
    staged native (lddl_mask_batch), or pure numpy — the masking ladder
    is an implementation swap all the way down."""
    fused_mask = _run_bert(corpus_dir, str(tmp_path / "fm"), hf_tokenizer,
                           monkeypatch, bin_size=16)
    staged_mask = _run_bert(corpus_dir, str(tmp_path / "sm"), hf_tokenizer,
                            monkeypatch,
                            env={"LDDL_TPU_NATIVE_FUSED_MASK": "0"},
                            bin_size=16)
    numpy_mask = _run_bert(corpus_dir, str(tmp_path / "nm"), hf_tokenizer,
                           monkeypatch,
                           env={"LDDL_TPU_NATIVE_FUSED_MASK": "0",
                                "LDDL_TPU_NATIVE_MASK": "0"},
                           bin_size=16)
    assert fused_mask == staged_mask == numpy_mask
    assert fused_mask


def test_fused_accepts_doc_spans(hf_tokenizer):
    """DocSpans input (the zero-copy spool view) tokenizes identically to
    the packed list path, including after an offset-array shuffle."""
    from lddl_tpu.preprocess.readers import DocSpans
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d.encode("utf-8") for d in DOCS if d.strip()] * 3
    spans = DocSpans.from_texts(texts)
    g1 = lrng.sample_rng(5, 0x9A1A, 3)
    g2 = lrng.sample_rng(5, 0x9A1A, 3)
    shuffled_list = lrng.shuffle(g1, list(texts))
    lrng.shuffle(g2, spans)
    assert list(spans) == shuffled_list  # same single-draw contract
    a = nat.bert_instances(spans, 48, 0.1, 2, 9, 1, info.cls_id,
                           info.sep_id)
    b = nat.bert_instances(shuffled_list, 48, 0.1, 2, 9, 1, info.cls_id,
                           info.sep_id)
    for x, y in zip(a[:4], b[:4]):
        np.testing.assert_array_equal(x, y)


def test_owned_buffers_are_zero_copy_and_survive_release(hf_tokenizer):
    """Result arrays wrap kernel buffers (no .copy() at the boundary) and
    stay valid after the result struct is released and the tokenizer
    handle goes away; finalizers free without crashing."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    ids, sl, dc = nat.tokenize_docs([d for d in DOCS if d.strip()])
    assert not ids.flags.owndata  # wraps the kernel's buffer
    snapshot = ids.copy()
    view = ids[1:]
    del ids
    gc.collect()
    np.testing.assert_array_equal(view, snapshot[1:])  # base chain holds
    del view, sl, dc
    gc.collect()  # finalizers run; must not crash or double-free


# ---------------------------------------------------------------------------
# End-to-end shard byte identity across the engine ladder
# ---------------------------------------------------------------------------


def _run_bert(corpus_dir, out, tokenizer, monkeypatch=None, env=None,
              **kwargs):
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess
    cfg = dict(max_seq_length=48, duplicate_factor=2, masking=True,
               tokenizer_engine="native")
    cfg.update({k: kwargs.pop(k) for k in list(kwargs)
                if k in ("masking", "tokenizer_engine", "schema_version")})
    for key, value in (env or {}).items():
        monkeypatch.setenv(key, value)
    try:
        run_bert_preprocess(
            {"wikipedia": corpus_dir}, out, tokenizer,
            config=BertPretrainConfig(**cfg),
            num_blocks=3, sample_ratio=1.0, seed=7, **kwargs)
    finally:
        for key in (env or {}):
            monkeypatch.delenv(key, raising=False)
    return _shard_hashes(out)


def test_fused_identity_smoke(hf_tokenizer, corpus_dir, tmp_path,
                              monkeypatch):
    """CI smoke: masked + binned + schema-v2 shards are byte-identical
    across fused / staged / hf."""
    fused = _run_bert(corpus_dir, str(tmp_path / "fused"), hf_tokenizer,
                      monkeypatch, bin_size=16)
    staged = _run_bert(corpus_dir, str(tmp_path / "staged"), hf_tokenizer,
                       monkeypatch, env={"LDDL_TPU_NATIVE_FUSED": "0"},
                       bin_size=16)
    hf = _run_bert(corpus_dir, str(tmp_path / "hf"), hf_tokenizer,
                   monkeypatch, tokenizer_engine="hf", bin_size=16)
    assert fused == staged == hf
    assert fused


def test_fused_identity_unbinned_unmasked(hf_tokenizer, corpus_dir,
                                          tmp_path, monkeypatch):
    """The want_ab fast path (kernel-emitted A/B segments feeding the
    schema-v2 columns) changes no bytes."""
    fused = _run_bert(corpus_dir, str(tmp_path / "fused"), hf_tokenizer,
                      monkeypatch, masking=False)
    staged = _run_bert(corpus_dir, str(tmp_path / "staged"), hf_tokenizer,
                       monkeypatch, env={"LDDL_TPU_NATIVE_FUSED": "0"},
                       masking=False)
    hf = _run_bert(corpus_dir, str(tmp_path / "hf"), hf_tokenizer,
                   monkeypatch, tokenizer_engine="hf", masking=False)
    assert fused == staged == hf
    assert fused


def test_fused_identity_schema_v1(hf_tokenizer, corpus_dir, tmp_path,
                                  monkeypatch):
    fused = _run_bert(corpus_dir, str(tmp_path / "fused"), hf_tokenizer,
                      monkeypatch, schema_version=1)
    hf = _run_bert(corpus_dir, str(tmp_path / "hf"), hf_tokenizer,
                   monkeypatch, tokenizer_engine="hf", schema_version=1)
    assert fused == hf
    assert fused


def test_fused_identity_across_process_pool(hf_tokenizer, corpus_dir,
                                            tmp_path, monkeypatch):
    """The fused engine rebuilt behind the pickle boundary (spawned pool
    workers) emits the same bytes as the serial staged engine."""
    pooled = _run_bert(corpus_dir, str(tmp_path / "pool"), hf_tokenizer,
                       monkeypatch, bin_size=16, num_workers=2)
    serial = _run_bert(corpus_dir, str(tmp_path / "serial"), hf_tokenizer,
                       monkeypatch, env={"LDDL_TPU_NATIVE_FUSED": "0"},
                       bin_size=16)
    assert pooled == serial
    assert pooled


def test_bart_native_split_identity(corpus_dir, tmp_path, monkeypatch):
    """BART's whole-bucket native sentence split (zero-copy spool view in,
    byte ranges out) produces shards byte-identical to the Python
    splitter path."""
    from lddl_tpu.preprocess import BartPretrainConfig, run_bart_preprocess

    def run(out, force_python):
        if force_python:
            monkeypatch.setenv("LDDL_TPU_BART_NATIVE_SPLIT", "0")
        else:
            monkeypatch.delenv("LDDL_TPU_BART_NATIVE_SPLIT", raising=False)
        run_bart_preprocess(
            {"wikipedia": corpus_dir}, out,
            config=BartPretrainConfig(target_seq_length=48),
            num_blocks=3, sample_ratio=1.0, seed=11)
        return _shard_hashes(out)

    a = run(str(tmp_path / "native"), force_python=False)
    b = run(str(tmp_path / "python"), force_python=True)
    assert a == b
    assert a


# ---------------------------------------------------------------------------
# Vectorized spool parsers == scalar reference semantics
# ---------------------------------------------------------------------------


def test_scan_block_documents_matches_read_documents(tmp_path):
    """The scatter's vectorized block scanner replays read_documents
    exactly: blank lines, leading whitespace, multi-ws separators,
    id-only lines, sampling draws and block-boundary line snapping."""
    from lddl_tpu.preprocess.readers import Block, read_documents
    from lddl_tpu.preprocess.runner import _scan_block_documents
    path = tmp_path / "block.txt"
    lines = [
        b"doc-0 plain text line",
        b"",
        b"   ",
        b"\tdoc-1 leading tab id",
        b"doc-2\t\t  multi separator   text  ",
        b"doc-3",            # id only -> dropped
        b"doc-4 x",
        b"  doc-5   spaced everywhere ",
        b"doc-6 tail line no newline",
    ]
    data = b"\n".join(lines)
    path.write_bytes(data)
    size = len(data)
    # several byte ranges incl. mid-line starts and ends
    for start, end in [(0, size), (0, 10), (5, 40), (22, size - 3),
                       (size - 5, size), (0, 1)]:
        for ratio in (1.0, 0.6):
            block = Block(3, str(path), start, end)
            ref = [text for _, text in read_documents(
                block, sample_ratio=ratio, base_seed=99)]
            buf, starts, ends = _scan_block_documents(block, ratio, 99)
            got = [bytes(buf[s:e]) for s, e in zip(starts, ends)]
            assert got == ref, (start, end, ratio)


def test_read_group_texts_matches_scalar_reference(tmp_path):
    """The vectorized gather parser (DocSpans out) reproduces the old
    per-line parser's documents, order and edge cases: interleaved
    headers, malformed headers, '#'-prefixed document text, empty lines,
    torn (newline-less) tails, multiple files, accept filtering."""
    from lddl_tpu.preprocess.runner import _SPOOL_DIR, _read_group_texts
    out_dir = tmp_path
    gdir = tmp_path / _SPOOL_DIR / "group-1"
    gdir.mkdir(parents=True)
    (gdir / "w0-1.txt").write_bytes(
        b"#B 7 1\n doc a\n doc b\n"
        b"#B 3 5\n doc c\n\n d\n"
        b"#B bad\n ignored after malformed\n"
        b"#B 3 1\n back to bucket 1\n #hash doc text\n")
    (gdir / "w1-2.txt").write_bytes(
        b"#B 7 5\n another\n \n"      # " " -> empty doc dropped
        b"#B 7 1\n same unit second file\n torn tail")
    (gdir / "zz-ignored.txt").write_bytes(b"#B 9 1\n fenced out\n")

    def scalar_reference(names):
        by_bucket = {b: {} for b in (1, 5)}  # group 1 of 4 groups, 8 buckets
        for name in names:
            data = (gdir / name).read_bytes()
            current = None
            for line in data.split(b"\n"):
                if line.startswith(b"#B "):
                    hdr = line.split()
                    blocks = (by_bucket.get(int(hdr[2].decode()))
                              if len(hdr) == 3 else None)
                    current = (None if blocks is None
                               else blocks.setdefault(hdr[1], []))
                elif current is not None:
                    text = line[1:]
                    if text:
                        current.append(text)
        return {b: [t for _, ts in sorted(blocks.items()) for t in ts]
                for b, blocks in by_bucket.items()}

    accept = {"w0-1.txt", "w1-2.txt"}
    expected = scalar_reference(sorted(accept))
    got = _read_group_texts(str(out_dir), 1, 8, 4, accept=accept)
    assert set(got) == set(expected)
    for b in expected:
        assert [bytes(t) for t in got[b]] == expected[b], b
    # no accept filter: the zz file joins in sorted order
    expected_all = scalar_reference(sorted(os.listdir(gdir)))
    got_all = _read_group_texts(str(out_dir), 1, 8, 4)
    for b in expected_all:
        assert [bytes(t) for t in got_all[b]] == expected_all[b], b


def test_doc_spans_view_semantics():
    from lddl_tpu.preprocess.readers import DocSpans
    texts = [b"alpha", b"", b"gamma delta", b"z"]
    spans = DocSpans.from_texts(texts)
    assert len(spans) == 4
    assert list(spans) == texts
    assert spans[2] == b"gamma delta"
    assert spans[1:3] == [b"", b"gamma delta"]
    spans.take_(np.array([3, 0, 2, 1]))
    assert list(spans) == [b"z", b"alpha", b"gamma delta", b""]


# ---------------------------------------------------------------------------
# .so staleness: the cached binary must carry a digest of its sources
# ---------------------------------------------------------------------------


def test_so_meta_pins_source_digest():
    """A freshly ensured .so records a digest of lddl_native.cpp +
    unicode_tables.h; content drift (even with preserved mtimes) then
    fails the staleness check loudly instead of serving old kernels."""
    from lddl_tpu.native import build
    path = build.ensure_built()
    assert path is not None
    with open(build.LIB_META) as f:
        meta = f.read().strip()
    digest = build.source_digest()
    assert "src=" + digest in meta
    assert not build._lib_stale()
    # Simulate a stale binary: meta recorded for different sources.
    try:
        with open(build.LIB_META, "w") as f:
            f.write(meta.replace("src=" + digest, "src=" + "0" * 16))
        assert build._lib_stale()
    finally:
        with open(build.LIB_META, "w") as f:
            f.write(meta + "\n")
    assert not build._lib_stale()
