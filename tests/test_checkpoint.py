"""Sharded checkpoint roundtrip + exact-resume composition."""

import numpy as np
import pytest

import jax

from lddl_tpu.loader import to_device_batch
from lddl_tpu.models import BertConfig, create_train_state, \
    make_sharded_train_step
from lddl_tpu.models.checkpoint import (latest_step, restore_train_state,
                                        save_train_state)
from lddl_tpu.models.testing import fake_pretrain_batch
from lddl_tpu.models.train import make_optimizer
from lddl_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def setup():
    cfg = BertConfig.tiny()
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    batch_np = fake_pretrain_batch(cfg.vocab_size, 4, 32, seed=0,
                                   segment_split=True)
    opt = make_optimizer(warmup_steps=1, total_steps=10)
    return cfg, mesh, batch_np, opt


@pytest.mark.slow  # ~79s: full compile+train on CPU devices, budget-gated from tier-1
def test_checkpoint_roundtrip_and_exact_resume(setup, tmp_path):
    cfg, mesh, batch_np, opt = setup
    ckpt = str(tmp_path / "ckpt")
    state, shardings = create_train_state(cfg, mesh, batch_np, optimizer=opt)
    step = make_sharded_train_step(mesh, cfg)
    batch = to_device_batch(batch_np, mesh)
    state, _ = step(state, batch, seed=0)
    state, _ = step(state, batch, seed=0)

    assert save_train_state(ckpt, state) == 2
    assert latest_step(ckpt) == 2

    # Restore into a DIFFERENTLY-seeded fresh state: every leaf must come
    # from the checkpoint, restored as shards on the same mesh.
    fresh, sh = create_train_state(cfg, mesh, batch_np, optimizer=opt,
                                   seed=99)
    restored = restore_train_state(ckpt, fresh, sh)
    assert int(jax.device_get(restored.step)) == 2
    # Values equal the trained state; shardings equal the DECLARED tree
    # (the live state's can differ where GSPMD propagated something
    # stronger than the annotation, e.g. an unannotated bias).
    for a, b, s in zip(jax.tree.leaves(state.params),
                       jax.tree.leaves(restored.params),
                       jax.tree.leaves(sh.params)):
        np.testing.assert_array_equal(jax.device_get(a), jax.device_get(b))
        assert b.sharding.is_equivalent_to(s, b.ndim)

    # The resumed run continues bit-for-bit like the uninterrupted one
    # (dropout is deterministic in (seed, step)).
    _, m_resumed = step(restored, batch, seed=0)
    _, m_straight = step(state, batch, seed=0)
    assert float(m_resumed["loss"]) == float(m_straight["loss"])


@pytest.mark.slow  # ~52s: full compile+train on CPU devices, budget-gated from tier-1
def test_checkpoint_keep_prunes_old_steps(setup, tmp_path):
    cfg, mesh, batch_np, opt = setup
    ckpt = str(tmp_path / "ckpt")
    state, _ = create_train_state(cfg, mesh, batch_np, optimizer=opt)
    step = make_sharded_train_step(mesh, cfg)
    batch = to_device_batch(batch_np, mesh)
    for _ in range(4):
        state, _ = step(state, batch, seed=0)
        save_train_state(ckpt, state, keep=2)
    assert latest_step(ckpt) == 4
    import os
    kept = {d for d in os.listdir(ckpt) if d.isdigit()}
    assert kept == {"3", "4"}


@pytest.mark.slow  # ~10s: full compile+train on CPU devices, budget-gated from tier-1
def test_restore_missing_raises(setup, tmp_path):
    cfg, mesh, batch_np, opt = setup
    state, sh = create_train_state(cfg, mesh, batch_np, optimizer=opt)
    with pytest.raises(FileNotFoundError):
        restore_train_state(str(tmp_path / "none"), state, sh)
