"""Subprocess body for test_multiprocess_loader: builds the production
loader under a real 2-process jax.distributed group. The shard census
runs through JaxCommunicator (the .num_samples.json cache is removed by
the test), then each rank reports (a) its dp-partition sample set and
(b) a digest of the encoded batch stream for dp_rank=0 — which must be
identical on every rank (TP/PP-peer contract)."""

import hashlib
import json
import sys


def sample_key(s):
    """Identity string of a raw sample: v1 shards yield token strings,
    schema-v2 (the default) yields int32 id arrays."""
    def part(v):
        return v if isinstance(v, str) else " ".join(map(str, v))
    return part(s[0]) + "|" + part(s[1])


def main():
    rank, world = int(sys.argv[1]), int(sys.argv[2])
    coordinator, shards, vocab = sys.argv[3], sys.argv[4], sys.argv[5]
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=world, process_id=rank)

    from lddl_tpu.parallel.distributed import get_communicator
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    comm = get_communicator()

    # (a) This rank's dp partition (raw samples, census via comm).
    loader = get_bert_pretrain_data_loader(
        shards, dp_rank=rank, num_dp_groups=world, vocab_file=vocab,
        batch_size=8, base_seed=5, return_raw_samples=True, comm=comm)
    mine = sorted(sample_key(s) for batch in loader for s in batch)
    print("SAMPLES " + json.dumps(mine), flush=True)

    # (b) TP-peer identity: every rank of dp group 0 must produce the
    # exact same encoded batch stream.
    comm.barrier()
    loader0 = get_bert_pretrain_data_loader(
        shards, dp_rank=0, num_dp_groups=world, vocab_file=vocab,
        batch_size=8, base_seed=5, comm=comm)
    h = hashlib.sha256()
    for batch in loader0:
        for key in sorted(batch):
            h.update(batch[key].tobytes())
    print("IDENTITY " + h.hexdigest(), flush=True)
    comm.barrier()
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
