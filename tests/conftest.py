"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax import.

This is the fake multi-process harness the reference lacks (SURVEY.md §4):
mesh/sharding tests run on 8 virtual CPU devices; multi-rank lockstep
algorithms run on ThreadGroupCommunicator rank-threads.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Process-worker CORRECTNESS tests must exercise the real process path
# even on single-core CI/bench hosts where the loader's measured
# auto-fallback would otherwise switch them to threads.
os.environ["LDDL_TPU_FORCE_PROCESS_WORKERS"] = "1"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The environment's sitecustomize may import jax at interpreter startup with
# JAX_PLATFORMS already pointing at a real accelerator; config.update still
# works because the backend itself initializes lazily.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) has no jax_num_cpu_devices option; the XLA_FLAGS
    # setting above already provides the 8 virtual CPU devices there.
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.Generator(np.random.Philox(key=[0, 42]))


@pytest.fixture
def tiny_corpus(tmp_path):
    """A tiny one-document-per-line source corpus (downloader output
    contract: first whitespace token of each line is the document id,
    ref lddl/dask/readers.py:131-136)."""
    source = tmp_path / "source"
    source.mkdir()
    docs = []
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    g = np.random.Generator(np.random.Philox(key=[0, 7]))
    for d in range(48):
        n_sents = int(g.integers(2, 9))
        sents = []
        for _ in range(n_sents):
            n_words = int(g.integers(4, 14))
            picks = [words[int(g.integers(0, len(words)))] for _ in range(n_words)]
            sents.append(" ".join(picks).capitalize() + ".")
        docs.append("doc-{} {}".format(d, " ".join(sents)))
    for shard in range(4):
        with open(source / "{}.txt".format(shard), "w") as f:
            for line in docs[shard::4]:
                f.write(line + "\n")
    return str(tmp_path)
