"""The lease protocol (lddl_tpu/resilience/leases.py): acquire / renew /
expiry / epoch-bump steal races, fencing, the keeper thread, and the
torn-read degradation. Pure-filesystem tests — fast, tier-1.
"""

import json
import os
import threading
import time

import pytest

from lddl_tpu.resilience import faults, leases


@pytest.fixture
def root(tmp_path):
    return str(tmp_path / "leases")


# ------------------------------------------------------------- acquisition


def test_fresh_acquire_epoch_zero(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    assert lease is not None
    assert lease.epoch == 0 and lease.holder == "hostA"
    rec = leases.read_lease(root, "u0")
    assert rec["holder"] == "hostA" and rec["epoch"] == 0
    assert rec["deadline"] > time.time()


def test_live_lease_refuses_second_claimant(root):
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is not None
    assert leases.try_acquire(root, "u0", "hostB", ttl_s=10.0) is None
    # Even the same holder id is a conflict: a respawned process must not
    # adopt its dead predecessor's lease mid-TTL.
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is None


def test_concurrent_fresh_acquire_exactly_one_winner(root):
    """N threads race the exclusive create; os.link semantics guarantee
    exactly one winner."""
    winners, barrier = [], threading.Barrier(8)

    def claim(i):
        barrier.wait()
        lease = leases.try_acquire(root, "u0", "host{}".format(i),
                                   ttl_s=10.0)
        if lease is not None:
            winners.append(lease)

    threads = [threading.Thread(target=claim, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(winners) == 1


# ------------------------------------------------------- renew/expiry/steal


def test_renew_extends_deadline_same_epoch(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.5)
    d0 = lease.deadline
    time.sleep(0.05)
    leases.renew(lease, ttl_s=10.0)
    assert lease.epoch == 0
    assert lease.deadline > d0
    assert leases.verify(lease)


def test_expired_lease_is_stolen_with_epoch_bump(root):
    lease_a = leases.try_acquire(root, "u0", "hostA", ttl_s=0.1)
    assert lease_a is not None
    time.sleep(0.15)
    lease_b = leases.try_acquire(root, "u0", "hostB", ttl_s=10.0)
    assert lease_b is not None
    assert lease_b.epoch == 1 and lease_b.holder == "hostB"


def test_fence_two_claimants_one_winner(root):
    """The epoch-bump race resolved by the fence: A steals, B overwrites
    at the same bump — the LAST write wins and exactly one fence check
    passes (the losing holder must self-terminate its unit)."""
    stale = leases.try_acquire(root, "u0", "old", ttl_s=0.05)
    assert stale is not None
    time.sleep(0.1)
    lease_a = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    assert lease_a is not None and lease_a.epoch == 1
    # B replays the same steal A just won (simulating the replace race:
    # B read the expired epoch-0 record before A's replace landed).
    leases._publish(leases.lease_path(root, "u0"),
                    leases._record("u0", "hostB", 1,
                                   time.time() + 10.0), "hostB")
    assert not leases.verify(lease_a)  # A lost: same epoch, other holder
    with pytest.raises(leases.LeaseLost):
        leases.renew(lease_a, ttl_s=10.0)
    assert lease_a.lost


def test_stale_epoch_fence_rejects_resurrected_holder(root):
    """A stalled holder resurrects after a steal: its (holder, epoch) no
    longer match, verify() is False, renew() raises."""
    zombie = leases.try_acquire(root, "u0", "zombie", ttl_s=0.05)
    time.sleep(0.1)
    thief = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
    assert thief.epoch == zombie.epoch + 1
    assert leases.verify(thief)
    assert not leases.verify(zombie)
    assert not leases.verify_at(zombie.root, zombie.unit, zombie.holder,
                                zombie.epoch)
    with pytest.raises(leases.LeaseLost):
        leases.renew(zombie, ttl_s=10.0)


def test_release_then_fresh_reacquire(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    leases.release(lease)
    assert leases.read_lease(root, "u0") is None
    fresh = leases.try_acquire(root, "u0", "hostB", ttl_s=10.0)
    assert fresh is not None and fresh.epoch == 0


def test_release_is_fenced(root):
    """A zombie's release must not unlink the thief's lease."""
    zombie = leases.try_acquire(root, "u0", "zombie", ttl_s=0.05)
    time.sleep(0.1)
    thief = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
    leases.release(zombie)  # verify fails -> no unlink
    assert leases.verify(thief)


# ------------------------------------------------------- torn reads, keeper


def test_torn_lease_reads_as_expired_and_is_stolen(root):
    os.makedirs(root)
    with open(leases.lease_path(root, "u0"), "w") as f:
        f.write('{"holder": "hostA", "ep')  # torn mid-write by flaky FS
    rec = leases.read_lease(root, "u0")
    assert rec["torn"] and rec["deadline"] == 0.0
    lease = leases.try_acquire(root, "u0", "hostB", ttl_s=10.0)
    assert lease is not None and lease.epoch == 1


def test_keeper_renews_until_stopped(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    keeper.add(lease)
    try:
        time.sleep(1.0)  # several TTLs: only renewals keep it alive
        assert leases.verify(lease)
        assert not lease.lost
    finally:
        keeper.stop()


def test_keeper_marks_stolen_lease_lost(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    keeper.add(lease)
    try:
        # Thief overwrites: next renewal must discover the loss.
        leases._publish(leases.lease_path(root, "u0"),
                        leases._record("u0", "thief", lease.epoch + 1,
                                       time.time() + 30.0), "thief")
        deadline = time.time() + 3.0
        while not lease.lost and time.time() < deadline:
            time.sleep(0.05)
        assert lease.lost
        assert not leases.verify(lease)
    finally:
        keeper.stop()


# ------------------------------------------------------------- fault sites


def test_lease_acquire_fault_site_injects(root):
    faults.arm("lease-acquire:eio:nth=1")
    try:
        with pytest.raises(OSError):
            leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    finally:
        faults.disarm()
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is not None


def test_stall_fault_freezes_renewal_past_deadline(root):
    """The chaos scenario the fence exists for, in miniature: a stall at
    the lease-renew site outlives the TTL, a thief steals, and the
    stalled holder's renewal comes back LeaseLost."""
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.3)
    faults.arm("lease-renew:stall:nth=1:delay=0.5")
    try:
        stolen = {}

        def thief():
            deadline = time.time() + 3.0
            while time.time() < deadline:
                got = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
                if got is not None:
                    stolen["lease"] = got
                    return
                time.sleep(0.02)

        t = threading.Thread(target=thief)
        t.start()
        with pytest.raises(leases.LeaseLost):
            leases.renew(lease, ttl_s=0.3)  # stalls 0.5s, then finds theft
        t.join()
    finally:
        faults.disarm()
    assert stolen["lease"].epoch == lease.epoch + 1


def test_stall_kind_parses_with_long_default_delay():
    clause = faults._parse_clause("lease-renew:stall:nth=1", 0)
    assert clause["kind"] == "stall" and clause["delay"] == 30.0
    clause = faults._parse_clause("lease-renew:stall:nth=1:delay=2.5", 0)
    assert clause["delay"] == 2.5


def test_holder_sanitization():
    assert leases.sanitize_holder("host a/b:1") == "host-a-b-1"
    with pytest.raises(ValueError):
        leases.sanitize_holder("///")
    h = leases.default_holder()
    assert h == leases.sanitize_holder(h)  # already file-name safe


def test_lease_record_roundtrip(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    with open(lease.path) as f:
        rec = json.load(f)
    assert set(rec) == {"unit", "holder", "epoch", "deadline"}
    assert rec["unit"] == "u0"
