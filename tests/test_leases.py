"""The lease protocol (lddl_tpu/resilience/leases.py): acquire / renew /
expiry / epoch-bump steal races, fencing, the keeper thread, and the
torn-read degradation. Pure-filesystem tests — fast, tier-1.
"""

import json
import os
import threading
import time

import pytest

from lddl_tpu.resilience import faults, leases


@pytest.fixture
def root(tmp_path):
    return str(tmp_path / "leases")


# ------------------------------------------------------------- acquisition


def test_fresh_acquire_epoch_zero(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    assert lease is not None
    assert lease.epoch == 0 and lease.holder == "hostA"
    rec = leases.read_lease(root, "u0")
    assert rec["holder"] == "hostA" and rec["epoch"] == 0
    assert rec["deadline"] > time.time()


def test_live_lease_refuses_second_claimant(root):
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is not None
    assert leases.try_acquire(root, "u0", "hostB", ttl_s=10.0) is None
    # Even the same holder id is a conflict: a respawned process must not
    # adopt its dead predecessor's lease mid-TTL.
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is None


def test_concurrent_fresh_acquire_exactly_one_winner(root):
    """N threads race the exclusive create; os.link semantics guarantee
    exactly one winner."""
    winners, barrier = [], threading.Barrier(8)

    def claim(i):
        barrier.wait()
        lease = leases.try_acquire(root, "u0", "host{}".format(i),
                                   ttl_s=10.0)
        if lease is not None:
            winners.append(lease)

    threads = [threading.Thread(target=claim, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(winners) == 1


# ------------------------------------------------------- renew/expiry/steal


def test_renew_extends_deadline_same_epoch(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.5)
    d0 = lease.deadline
    time.sleep(0.05)
    leases.renew(lease, ttl_s=10.0)
    assert lease.epoch == 0
    assert lease.deadline > d0
    assert leases.verify(lease)


def test_expired_lease_is_stolen_with_epoch_bump(root):
    lease_a = leases.try_acquire(root, "u0", "hostA", ttl_s=0.1)
    assert lease_a is not None
    time.sleep(0.15)
    lease_b = leases.try_acquire(root, "u0", "hostB", ttl_s=10.0)
    assert lease_b is not None
    assert lease_b.epoch == 1 and lease_b.holder == "hostB"


def test_fence_two_claimants_one_winner(root):
    """The epoch-bump race resolved by the fence: A steals, B overwrites
    at the same bump — the LAST write wins and exactly one fence check
    passes (the losing holder must self-terminate its unit)."""
    stale = leases.try_acquire(root, "u0", "old", ttl_s=0.05)
    assert stale is not None
    time.sleep(0.1)
    lease_a = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    assert lease_a is not None and lease_a.epoch == 1
    # B replays the same steal A just won (simulating the replace race:
    # B read the expired epoch-0 record before A's replace landed).
    leases._publish(leases.lease_path(root, "u0"),
                    leases._record("u0", "hostB", 1,
                                   time.time() + 10.0), "hostB")
    assert not leases.verify(lease_a)  # A lost: same epoch, other holder
    with pytest.raises(leases.LeaseLost):
        leases.renew(lease_a, ttl_s=10.0)
    assert lease_a.lost


def test_stale_epoch_fence_rejects_resurrected_holder(root):
    """A stalled holder resurrects after a steal: its (holder, epoch) no
    longer match, verify() is False, renew() raises."""
    zombie = leases.try_acquire(root, "u0", "zombie", ttl_s=0.05)
    time.sleep(0.1)
    thief = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
    assert thief.epoch == zombie.epoch + 1
    assert leases.verify(thief)
    assert not leases.verify(zombie)
    assert not leases.verify_at(zombie.root, zombie.unit, zombie.holder,
                                zombie.epoch)
    with pytest.raises(leases.LeaseLost):
        leases.renew(zombie, ttl_s=10.0)


def test_release_then_fresh_reacquire(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    leases.release(lease)
    assert leases.read_lease(root, "u0") is None
    fresh = leases.try_acquire(root, "u0", "hostB", ttl_s=10.0)
    assert fresh is not None and fresh.epoch == 0


def test_release_is_fenced(root):
    """A zombie's release must not unlink the thief's lease."""
    zombie = leases.try_acquire(root, "u0", "zombie", ttl_s=0.05)
    time.sleep(0.1)
    thief = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
    leases.release(zombie)  # verify fails -> no unlink
    assert leases.verify(thief)


# ------------------------------------------------------- torn reads, keeper


def test_torn_lease_reads_as_expired_and_is_stolen(root):
    os.makedirs(root)
    with open(leases.lease_path(root, "u0"), "w") as f:
        f.write('{"holder": "hostA", "ep')  # torn mid-write by flaky FS
    rec = leases.read_lease(root, "u0")
    assert rec["torn"] and rec["deadline"] == 0.0
    lease = leases.try_acquire(root, "u0", "hostB", ttl_s=10.0)
    assert lease is not None and lease.epoch == 1


def test_keeper_renews_until_stopped(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    keeper.add(lease)
    try:
        time.sleep(1.0)  # several TTLs: only renewals keep it alive
        assert leases.verify(lease)
        assert not lease.lost
    finally:
        keeper.stop()


def test_keeper_marks_stolen_lease_lost(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    keeper.add(lease)
    try:
        # Thief overwrites: next renewal must discover the loss.
        leases._publish(leases.lease_path(root, "u0"),
                        leases._record("u0", "thief", lease.epoch + 1,
                                       time.time() + 30.0), "thief")
        deadline = time.time() + 3.0
        while not lease.lost and time.time() < deadline:
            time.sleep(0.05)
        assert lease.lost
        assert not leases.verify(lease)
    finally:
        keeper.stop()


# ------------------------------------------------------------- fault sites


def test_lease_acquire_fault_site_injects(root):
    faults.arm("lease-acquire:eio:nth=1")
    try:
        with pytest.raises(OSError):
            leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    finally:
        faults.disarm()
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is not None


def test_stall_fault_freezes_renewal_past_deadline(root):
    """The chaos scenario the fence exists for, in miniature: a stall at
    the lease-renew site outlives the TTL, a thief steals, and the
    stalled holder's renewal comes back LeaseLost."""
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.3)
    faults.arm("lease-renew:stall:nth=1:delay=0.5")
    try:
        stolen = {}

        def thief():
            deadline = time.time() + 3.0
            while time.time() < deadline:
                got = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
                if got is not None:
                    stolen["lease"] = got
                    return
                time.sleep(0.02)

        t = threading.Thread(target=thief)
        t.start()
        with pytest.raises(leases.LeaseLost):
            leases.renew(lease, ttl_s=0.3)  # stalls 0.5s, then finds theft
        t.join()
    finally:
        faults.disarm()
    assert stolen["lease"].epoch == lease.epoch + 1


def test_stall_kind_parses_with_long_default_delay():
    clause = faults._parse_clause("lease-renew:stall:nth=1", 0)
    assert clause["kind"] == "stall" and clause["delay"] == 30.0
    clause = faults._parse_clause("lease-renew:stall:nth=1:delay=2.5", 0)
    assert clause["delay"] == 2.5


# ------------------------------------------- batched renewal / op budget


@pytest.fixture
def ops(monkeypatch, tmp_path):
    """Arm metrics (counters are inert otherwise), reset the registry,
    and return a reader for lease_ops_total."""
    from lddl_tpu import observability as obs
    monkeypatch.setenv("LDDL_TPU_METRICS_DIR", str(tmp_path / "metrics"))
    obs.registry().reset()

    def read(op=None):
        c = obs.registry().counter("lease_ops_total")
        return c.total() if op is None else c.value(op=op)

    return read


def test_scan_units_snapshot(root, ops):
    for u in ("u0", "u1", "group-2"):
        assert leases.try_acquire(root, u, "hostA", ttl_s=10.0) is not None
    with open(os.path.join(root, "u9.json.tmp.123"), "w") as f:
        f.write("debris")
    before = ops(op="scan")
    assert leases.scan_units(root) == {"u0", "u1", "group-2"}
    assert ops(op="scan") == before + 1
    assert leases.scan_units(str(root) + ".gone") is None


def test_renew_fast_is_one_read_one_publish(root, ops):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    r0, p0 = ops(op="read"), ops(op="publish")
    leases.renew_fast(lease, ttl_s=10.0)
    assert ops(op="read") == r0 + 1      # legacy renew() does two
    assert ops(op="publish") == p0 + 1
    assert leases.verify(lease)


def test_renew_fast_fences_stolen_lease(root):
    """The batched pass keeps full fence semantics: a steal landing
    before the grouped renewal marks the loser lost, and the loser's
    publish never resurrects over the thief's record."""
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    leases._publish(leases.lease_path(root, "u0"),
                    leases._record("u0", "thief", lease.epoch + 1,
                                   time.time() + 30.0), "thief")
    with pytest.raises(leases.LeaseLost):
        leases.renew_fast(lease, ttl_s=10.0)
    assert lease.lost
    rec = leases.read_lease(root, "u0")
    assert rec["holder"] == "thief" and rec["epoch"] == lease.epoch + 1


def test_try_acquire_known_missing_skips_read(root, ops):
    r0 = ops(op="read")
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0,
                               known_missing=True)
    assert lease is not None
    # Exclusive create ONLY: the initial existence read was answered by
    # the caller's scan snapshot, and batched mode also skips the
    # post-create read-back (an O_EXCL winner's fresh record cannot be
    # validly stolen before its deadline; the publish-time fence covers
    # the stale-replace race the read-back merely narrowed).
    assert ops(op="read") == r0
    # Stale snapshot: the unit exists after all -> falls back to the read
    # path and reports a clean conflict, never a crash or a double-claim.
    assert leases.try_acquire(root, "u0", "hostB", ttl_s=10.0,
                              known_missing=True) is None


def test_try_acquire_held_cache_skips_filesystem(root, ops):
    from lddl_tpu import observability as obs
    assert leases.try_acquire(root, "u0", "hostA", ttl_s=10.0) is not None
    cache = {}
    assert leases.try_acquire(root, "u0", "hostB", ttl_s=10.0,
                              held_cache=cache) is None
    assert cache["u0"] > time.time()
    t0, c0 = ops(), obs.registry().counter(
        "lease_acquire_conflicts_total").total()
    # Cached valid-held conflict: zero FS ops, no conflict counted.
    assert leases.try_acquire(root, "u0", "hostB", ttl_s=10.0,
                              held_cache=cache) is None
    assert ops() == t0
    assert obs.registry().counter(
        "lease_acquire_conflicts_total").total() == c0
    # An expired cache entry is dropped and the claim proceeds for real.
    cache["u0"] = time.time() - 1.0
    leases.release(leases.Lease(root, "u0", "hostA", 0,
                                leases.read_lease(root, "u0")["deadline"]))
    assert leases.try_acquire(root, "u0", "hostB", ttl_s=10.0,
                              held_cache=cache) is not None


def test_fence_at_deadline_cache_skips_reads(root, ops):
    """Inside the cached deadline the fence is free; past it, a real read
    refreshes the cache from the (renewed) record; a steal past the
    deadline trips the fence on the first real read — and the trip is
    final."""
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    fence = leases.fence_at(root, "u0", "hostA", 0,
                            deadline=lease.deadline)
    r0 = ops(op="read")
    for _ in range(5):
        assert fence()
    assert ops(op="read") == r0  # all answered from the deadline cache
    # An unseeded fence pays exactly one read, then caches the record's
    # deadline for subsequent calls.
    cold = leases.fence_at(root, "u0", "hostA", 0)
    assert cold() and cold() and cold()
    assert ops(op="read") == r0 + 1
    # Past the deadline: a thief's record is detected on the real read.
    late = leases.fence_at(root, "u0", "hostA", 0,
                           now_fn=lambda: lease.deadline + 1.0)
    leases._publish(leases.lease_path(root, "u0"),
                    leases._record("u0", "thief", 1, time.time() + 30.0),
                    "thief")
    assert not late()
    assert not late()  # tripped fences never recover


def test_fence_at_stall_past_deadline_trips(root):
    """The chaos scenario: a holder stalls past its deadline and a thief
    steals. The stall itself carries the wall clock past the cached
    deadline, so the first post-stall fence call is a REAL read and
    self-terminates the zombie — same detection point as an every-call
    read."""
    victim = leases.try_acquire(root, "u0", "hostA", ttl_s=0.05)
    fence = leases.fence_at(root, "u0", "hostA", 0,
                            deadline=victim.deadline)
    assert fence()  # inside the deadline: still ours
    time.sleep(0.1)  # the "stall": deadline passes, nobody renews
    thief = leases.try_acquire(root, "u0", "thief", ttl_s=10.0)
    assert thief is not None and thief.epoch == 1
    assert not fence()


def test_still_held_skips_read_inside_deadline(root, ops):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    t0 = ops()
    assert leases.still_held(lease)
    assert ops() == t0  # deadline ahead: zero FS ops
    # A lost flag wins without any read.
    lease.lost = True
    assert not leases.still_held(lease)
    assert ops() == t0
    # Past the deadline the look is a real verify read.
    lease.lost = False
    lease.deadline = time.time() - 1.0
    assert leases.still_held(lease)  # record on disk still names us
    assert ops() > t0


def test_release_inside_deadline_is_unlink_only(root, ops):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    r0, u0 = ops(op="read"), ops(op="unlink")
    leases.release(lease)
    assert ops(op="read") == r0  # no pre-unlink verify read
    assert ops(op="unlink") == u0 + 1
    assert leases.read_lease(root, "u0") is None


def test_legacy_pins_read_backed_acquire_and_fence(root, ops, monkeypatch):
    """LDDL_TPU_COORD_LEGACY=1 restores the ancestor op pattern: acquire
    read-back, every-call fence reads, verified release."""
    monkeypatch.setenv("LDDL_TPU_COORD_LEGACY", "1")
    r0 = ops(op="read")
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0,
                               known_missing=True)
    assert lease is not None
    assert ops(op="read") == r0 + 1  # the post-create read-back
    fence = leases.fence_at(root, "u0", "hostA", 0,
                            deadline=lease.deadline)
    r1 = ops(op="read")
    assert fence() and fence()
    assert ops(op="read") == r1 + 2  # one real read per call
    r2 = ops(op="read")
    assert leases.still_held(lease)
    assert ops(op="read") == r2 + 1  # pre-publish look reads too
    r3 = ops(op="read")
    leases.release(lease)
    assert ops(op="read") == r3 + 1  # verified unlink


def test_batched_keeper_pass_op_budget(root, ops):
    """One keeper pass over n held leases costs 1 scan + 2n ops (the
    ≥3x amortization the batched pass exists for), and keeps them alive."""
    held = [leases.try_acquire(root, "u{}".format(i), "hostA", ttl_s=0.4)
            for i in range(4)]
    assert all(held)
    t0 = ops()
    keeper = leases.LeaseKeeper(0.4)
    try:
        for lease in held:
            keeper.add(lease)
        time.sleep(1.0)  # several TTLs: only batched renewals keep them
        assert all(leases.verify(x) and not x.lost for x in held)
    finally:
        keeper.stop()
    passes = ops(op="scan")  # one scan per pass (single root)
    assert passes >= 1
    # 2n (read+publish) per pass per survivor, +1 scan — strictly under
    # the 3n-per-pass legacy budget. The verify() sweep above cost one
    # read per lease inside the measurement window.
    spent = ops() - t0 - len(held)
    assert spent <= passes * (1 + 2 * len(held))


def test_batched_keeper_marks_missing_lease_lost_without_read(root):
    """A lease file missing from the pass's scan (stolen-then-released,
    or finalized) is marked lost from the snapshot alone."""
    keep = leases.try_acquire(root, "ukeep", "hostA", ttl_s=0.4)
    gone = leases.try_acquire(root, "ugone", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    try:
        keeper.add(keep)
        keeper.add(gone)
        os.unlink(gone.path)
        deadline = time.time() + 3.0
        while not gone.lost and time.time() < deadline:
            time.sleep(0.05)
        assert gone.lost
        assert leases.verify(keep) and not keep.lost
    finally:
        keeper.stop()


def test_batched_keeper_fences_steal_between_renewals(root):
    """A thief's record lands between grouped renewals: the file is still
    present in the scan, so the fence inside renew_fast must catch it."""
    victim = leases.try_acquire(root, "u0", "hostA", ttl_s=0.4)
    other = leases.try_acquire(root, "u1", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    try:
        keeper.add(victim)
        keeper.add(other)
        leases._publish(leases.lease_path(root, "u0"),
                        leases._record("u0", "thief", victim.epoch + 1,
                                       time.time() + 30.0), "thief")
        deadline = time.time() + 3.0
        while not victim.lost and time.time() < deadline:
            time.sleep(0.05)
        assert victim.lost
        rec = leases.read_lease(root, "u0")
        assert rec["holder"] == "thief"  # never resurrected over the thief
        assert leases.verify(other) and not other.lost
    finally:
        keeper.stop()


def test_legacy_coordination_env_pin(root, monkeypatch):
    assert not leases.legacy_coordination()
    monkeypatch.setenv("LDDL_TPU_COORD_LEGACY", "1")
    assert leases.legacy_coordination()
    # The legacy keeper path still keeps leases alive.
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=0.4)
    keeper = leases.LeaseKeeper(0.4)
    try:
        keeper.add(lease)
        time.sleep(1.0)
        assert leases.verify(lease) and not lease.lost
    finally:
        keeper.stop()


def test_holder_sanitization():
    assert leases.sanitize_holder("host a/b:1") == "host-a-b-1"
    with pytest.raises(ValueError):
        leases.sanitize_holder("///")
    h = leases.default_holder()
    assert h == leases.sanitize_holder(h)  # already file-name safe


def test_lease_record_roundtrip(root):
    lease = leases.try_acquire(root, "u0", "hostA", ttl_s=10.0)
    with open(lease.path) as f:
        rec = json.load(f)
    assert set(rec) == {"unit", "holder", "epoch", "deadline"}
    assert rec["unit"] == "u0"
