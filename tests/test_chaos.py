"""Chaos tests: REAL process death (SIGKILL) of the preprocess runner
mid-scatter and mid-gather, then kill-and-resume byte-identity.

These launch actual subprocesses and full pipeline runs, so they are
marked ``slow`` (excluded from tier-1; run with ``-m slow``). The fast
injector-based resilience suite lives in tests/test_resilience.py.
"""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

pytestmark = [pytest.mark.slow, pytest.mark.fault]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Driver executed as a subprocess so a SIGKILL takes out the WHOLE runner
# (serial num_workers=1: the scatter/gather runs in the runner process
# itself, exactly like a preempted pod host). argv: corpus vocab out resume
_DRIVER = """
import sys
from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
from lddl_tpu.preprocess.runner import run_bert_preprocess

corpus, vocab, out, resume = sys.argv[1:5]
tok = get_tokenizer(vocab_file=vocab)
cfg = BertPretrainConfig(max_seq_length=32, masking=True)
run_bert_preprocess(
    {"wikipedia": corpus}, out, tok, config=cfg, num_blocks=12,
    sample_ratio=0.9, seed=4242, bin_size=8, global_shuffle=True,
    resume=(resume == "resume"))
"""


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("chaos")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def reference_hashes(fixture_dirs, tmp_path_factory):
    """Hashes of an UNINTERRUPTED run in this environment — the
    byte-identity reference for the kill-and-resume tests. (Computed
    fresh rather than from tests/golden_spool.json: the pinned goldens
    additionally pin parquet codec bytes across library versions, which
    is a different invariant than crash-recovery identity.)"""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path_factory.mktemp("reference") / "out")
    proc = _run_driver(corpus, vocab, out, resume=False)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    hashes = gs.hash_outputs(out)
    assert hashes  # produced shards
    return hashes


def _run_driver(corpus, vocab, out, resume, fault_spec=None, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if fault_spec:
        env["LDDL_TPU_FAULTS"] = fault_spec
    else:
        env.pop("LDDL_TPU_FAULTS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER, corpus, vocab, out,
         "resume" if resume else "fresh"],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout)
    return proc


def test_sigkill_mid_scatter_then_resume_is_byte_identical(fixture_dirs,
                                                           reference_hashes,
                                                           tmp_path):
    """SIGKILL the runner while it is appending to the shuffle spool
    (open:kill on a _shuffle path). The rerun with --resume must wipe the
    poisoned partial spool, redo the scatter, and produce output
    byte-identical to an uninterrupted run."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _run_driver(corpus, vocab, out, resume=False,
                       fault_spec="open:kill:nth=5:path=_shuffle")
    assert proc.returncode == -9, proc.stdout + proc.stderr  # really SIGKILLed
    # The kill landed mid-scatter: spool exists, completion marker doesn't.
    assert os.path.isdir(os.path.join(out, "_shuffle"))
    assert not os.path.exists(
        os.path.join(out, "_shuffle", ".scatter_done"))

    proc = _run_driver(corpus, vocab, out, resume=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert gs.hash_outputs(out) == reference_hashes


def test_sigkill_mid_gather_then_resume_is_byte_identical(fixture_dirs,
                                                          reference_hashes,
                                                          tmp_path):
    """SIGKILL the runner between gather units (replace:kill on a _done
    ledger publish — after some units completed, others not). The resume
    must redo ONLY the unfinished units, and the final shards must be
    byte-identical to an uninterrupted run."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _run_driver(corpus, vocab, out, resume=False,
                       fault_spec="replace:kill:nth=4:path=_done/group-")
    assert proc.returncode == -9, proc.stdout + proc.stderr
    # The kill landed mid-gather: scatter completed, some ledgers exist.
    assert os.path.exists(os.path.join(out, "_shuffle", ".scatter_done"))
    done = [n for n in os.listdir(os.path.join(out, "_done"))
            if n.startswith("group-")]
    assert 0 < len(done) < 12  # genuinely mid-gather

    proc = _run_driver(corpus, vocab, out, resume=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert not os.path.isdir(os.path.join(out, "_done"))  # cleaned up
    assert gs.hash_outputs(out) == reference_hashes


def test_uninterrupted_runs_are_deterministic(fixture_dirs,
                                              reference_hashes, tmp_path):
    """Control: two independent fault-free runs are byte-identical, so
    the kill tests above compare against a stable reference."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _run_driver(corpus, vocab, out, resume=False)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert gs.hash_outputs(out) == reference_hashes


# --------------------------------------------------- elastic work stealing

# Driver for the elastic claim loop (same plan as _DRIVER, so the SAME
# reference hashes apply — leases must never change output bytes).
# argv: corpus vocab out holder ttl [fleet]
_ELASTIC_DRIVER = """
import sys
from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
from lddl_tpu.preprocess.runner import run_bert_preprocess
from lddl_tpu import observability as obs

corpus, vocab, out, holder, ttl = sys.argv[1:6]
if "fleet" in sys.argv[6:]:
    # The CLI --fleet-telemetry path: spool under <out>/.telemetry/,
    # metrics armed into the spool, heartbeats on a short interval.
    obs.fleet.configure(out, holder_id=holder, ttl=float(ttl),
                        interval=0.5)
tok = get_tokenizer(vocab_file=vocab)
cfg = BertPretrainConfig(max_seq_length=32, masking=True)
run_bert_preprocess(
    {"wikipedia": corpus}, out, tok, config=cfg, num_blocks=12,
    sample_ratio=0.9, seed=4242, bin_size=8, global_shuffle=True,
    elastic=True, lease_ttl=float(ttl), holder_id=holder, log=print)
obs.write_summary()
"""


def _spawn_elastic(corpus, vocab, out, holder, ttl, fault_spec=None,
                   metrics_dir=None, fleet=False, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("LDDL_TPU_STORAGE_BACKEND", None)
    if extra_env:
        env.update(extra_env)
    if fault_spec:
        env["LDDL_TPU_FAULTS"] = fault_spec
    else:
        env.pop("LDDL_TPU_FAULTS", None)
    if metrics_dir:
        env["LDDL_TPU_METRICS_DIR"] = metrics_dir
    else:
        env.pop("LDDL_TPU_METRICS_DIR", None)
    for name in ("LDDL_TPU_FLEET_DIR", "LDDL_TPU_FLEET_HOLDER",
                 "LDDL_TPU_FLEET_TTL_S", "LDDL_TPU_FLEET_INTERVAL_S"):
        env.pop(name, None)
    argv = [sys.executable, "-c", _ELASTIC_DRIVER, corpus, vocab, out,
            holder, str(ttl)]
    if fleet:
        argv.append("fleet")
    return subprocess.Popen(
        argv, env=env, cwd=REPO_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def _counter_total(metrics_dir, name):
    """Sum a counter over every process that exported into metrics_dir:
    summaries for cleanly-exited hosts, the LAST metrics-*.jsonl snapshot
    for SIGKILLed ones (the kill fault flushes telemetry first; such a
    process never writes a summary)."""
    import glob
    import json
    total = 0
    seen_pids = set()
    for path in sorted(glob.glob(os.path.join(metrics_dir,
                                              "summary-*.json"))):
        seen_pids.add(path.rsplit("pid", 1)[1].split(".")[0])
        with open(path) as f:
            snap = json.load(f)["metrics"].get(name)
        if snap:
            total += sum(snap["values"].values())
    for path in sorted(glob.glob(os.path.join(metrics_dir,
                                              "metrics-*.jsonl"))):
        if path.rsplit("pid", 1)[1].split(".")[0] in seen_pids:
            continue  # clean exit: already counted via its summary
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            continue
        snap = json.loads(lines[-1])["metrics"].get(name)
        if snap:
            total += sum(snap["values"].values())
    return total


def test_elastic_sigkill_one_host_survivors_byte_identical(
        fixture_dirs, reference_hashes, tmp_path):
    """Three elastic host processes with FLEET TELEMETRY armed; one is
    SIGKILLed mid-gather (while holding a unit's lease, before journaling
    it). The survivors steal and redo its unit, run the lease-guarded
    finalize, and the merged output — shards AND manifest — is
    byte-identical to the single-host telemetry-off reference run.

    The fleet acceptance pin rides the same run: from the telemetry
    artifacts alone, `pipeline_status --json` identifies the dead host as
    stalled, its totals match the run's journaled ground truth (24 units,
    >=1 steal), and the merged Chrome trace spans all three hosts."""
    td, corpus, vocab = fixture_dirs
    ref_out = str(tmp_path / "ref")
    proc = _run_driver(corpus, vocab, ref_out, resume=False)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    out = str(tmp_path / "out")
    # Per-host metrics land in the fleet spools (fleet=True arms the
    # metrics dir into <out>/.telemetry/<holder>/).
    mdirs = {h: os.path.join(out, ".telemetry", h)
             for h in ("h0", "h1", "h2")}
    # h0 dies at the os.replace publishing its FIRST gather ledger
    # record: it dies holding that unit's lease with the unit's work
    # fully done but unjournaled — the exact "host dies holding a unit"
    # case. It gets a head start so it is GUARANTEED to reach a gather
    # publish before the survivors can drain the queue: the survivors
    # launch only once h0's first scatter record is ON DISK (a blind
    # sleep would flake on a loaded machine), and they join the
    # in-progress run through the fingerprint manifest.
    import time
    procs = {
        "h0": _spawn_elastic(corpus, vocab, out, "h0", 2.0,
                             fault_spec="replace:kill:nth=1:path=_done/group-",
                             fleet=True),
    }
    records = os.path.join(out, "_done")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and procs["h0"].poll() is None:
        if os.path.isdir(records) and any(
                n.startswith("scatter-") for n in os.listdir(records)):
            break
        time.sleep(0.1)
    procs["h1"] = _spawn_elastic(corpus, vocab, out, "h1", 2.0, fleet=True)
    procs["h2"] = _spawn_elastic(corpus, vocab, out, "h2", 2.0, fleet=True)
    outs = {h: p.communicate(timeout=600)[0] for h, p in procs.items()}
    assert procs["h0"].returncode == -9, outs["h0"]  # really SIGKILLed
    assert procs["h1"].returncode == 0, outs["h1"]
    assert procs["h2"].returncode == 0, outs["h2"]

    assert gs.hash_outputs(out) == reference_hashes
    with open(os.path.join(ref_out, ".manifest.json"), "rb") as f:
        ref_manifest = f.read()
    with open(os.path.join(out, ".manifest.json"), "rb") as f:
        assert f.read() == ref_manifest
    # All scheduling state cleaned up by the finalizer.
    assert not os.path.isdir(os.path.join(out, "_leases"))
    assert not os.path.isdir(os.path.join(out, "_done"))
    assert not os.path.isdir(os.path.join(out, "_shuffle"))
    # The dead host's unit really was stolen by a survivor.
    steals = (_counter_total(mdirs["h1"], "lease_steals_total")
              + _counter_total(mdirs["h2"], "lease_steals_total"))
    assert steals >= 1
    # Every unit journaled exactly once across the cluster: survivors +
    # the victim's pre-kill completions account for 12 scatter slices +
    # 12 gather groups with no double counting. (The victim's counters
    # survive because the kill fault flushes telemetry first.)
    done = sum(_counter_total(m, "elastic_units_completed_total")
               for m in mdirs.values())
    assert done == 24, done

    # ---- fleet acceptance: the report from telemetry artifacts alone.
    import json as _json
    merged_path = str(tmp_path / "merged_trace.json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    status = subprocess.run(
        [sys.executable, "-m", "tools.pipeline_status", out, "--json",
         "--merge-trace", merged_path],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True)
    # Exit 2: the dead host makes the report unhealthy by design.
    assert status.returncode == 2, status.stdout + status.stderr
    report = _json.loads(status.stdout)
    # The SIGKILLed host is the one and only stalled host (it never wrote
    # a clean-shutdown marker; the survivors did).
    assert report["health"]["stalled_hosts"] == ["h0"]
    assert sorted(report["health"]["closed_hosts"]) == ["h1", "h2"]
    # Totals match the journaled ground truth computed above.
    totals = report["totals"]["counters"]
    assert totals["units_completed"] == 24
    assert totals["steals"] >= 1
    assert totals["steals"] >= steals
    assert totals["fence_rejects"] == sum(
        _counter_total(m, "lease_fence_rejects_total")
        for m in mdirs.values())
    # Lifecycle event log agrees with the counters: 24 unit.journaled
    # events across the fleet, and the steal shows as unit.stolen.
    journaled = sum(st["event_counts"].get("unit.journaled", 0)
                    for st in report["hosts"].values())
    assert journaled == 24
    stolen_events = sum(st["event_counts"].get("unit.stolen", 0)
                        for st in report["hosts"].values())
    assert stolen_events >= 1
    # The merged Chrome trace spans ALL three hosts, dead one included
    # (its kill-fault flush published the pre-kill trace buffer).
    merged = _json.load(open(merged_path))
    lane_names = {ev["args"]["name"] for ev in merged
                  if ev.get("ph") == "M"
                  and ev.get("name") == "process_name"}
    for h in ("h0", "h1", "h2"):
        assert any(name.startswith(h + " ") for name in lane_names), (
            h, sorted(lane_names))
    assert any(ev.get("ph") == "X" for ev in merged)


def test_elastic_forced_stall_fence_reject(fixture_dirs, reference_hashes,
                                           tmp_path):
    """Force the stall-steal-fence sequence end to end: host h0's first
    lease renewal stalls past the TTL while its unit is artificially
    slowed, h1 steals and redoes the unit, and h0's late publish is
    FENCED — counted in lease_fence_rejects_total, never reaching the
    ledger — while the final bytes stay identical to the reference."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    mdirs = {h: str(tmp_path / ("m_" + h)) for h in ("h0", "h1")}
    procs = {
        # Stall the first renewal for 30s (far past the 1.5s TTL) AND
        # slow one of the stalled unit's spool appends by 5s, so the unit
        # genuinely outlives its lease.
        "h0": _spawn_elastic(
            corpus, vocab, out, "h0", 1.5,
            fault_spec=("lease-renew:stall:nth=1:delay=30,"
                        "open:slow:nth=2:path=_shuffle:delay=5"),
            metrics_dir=mdirs["h0"]),
        "h1": _spawn_elastic(corpus, vocab, out, "h1", 1.5,
                             metrics_dir=mdirs["h1"]),
    }
    outs = {h: p.communicate(timeout=600)[0] for h, p in procs.items()}
    assert procs["h0"].returncode == 0, outs["h0"]
    assert procs["h1"].returncode == 0, outs["h1"]

    assert gs.hash_outputs(out) == reference_hashes
    # The fence fired on the stalled host and the thief stole the unit.
    assert _counter_total(mdirs["h0"], "lease_fence_rejects_total") >= 1
    assert _counter_total(mdirs["h1"], "lease_steals_total") >= 1
    # The fenced publish never reached the ledger: the 24 units were
    # journaled exactly once across both hosts.
    done = sum(_counter_total(m, "elastic_units_completed_total")
               for m in mdirs.values())
    assert done == 24, done


def test_elastic_sigkill_on_mock_store_byte_identical(
        fixture_dirs, reference_hashes, tmp_path):
    """The chaos proof beyond the shared FS: three elastic hosts
    coordinating through the MOCK OBJECT STORE (CAS leases, multipart-
    upload-then-commit publishes — no rename anywhere on the
    coordination plane). h0 is SIGKILLed inside its first gather-ledger
    MULTIPART COMMIT — before the commit record linearizes, so it dies
    holding the unit's lease with an abandoned multipart upload behind
    it (the torn-upload crash shape). A survivor additionally absorbs an
    injected CAS conflict on its first lease put. The survivors steal
    and redo, and the output is byte-identical to the LOCAL single-host
    reference — shards AND manifest — with all 24 units journaled
    exactly once and the conflict visible in the backend counters."""
    import time
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    mock = {"LDDL_TPU_STORAGE_BACKEND": "mock"}
    mdirs = {h: os.path.join(out, ".telemetry", h)
             for h in ("h0", "h1", "h2")}
    # Same head-start choreography as the local 3-host test: survivors
    # launch only once h0's first scatter record is on disk.
    procs = {
        "h0": _spawn_elastic(
            corpus, vocab, out, "h0", 2.0,
            fault_spec="multipart-commit:kill:nth=1:path=_done/group-",
            fleet=True, extra_env=mock),
    }
    records = os.path.join(out, "_done")
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and procs["h0"].poll() is None:
        if os.path.isdir(records) and any(
                n.startswith("scatter-") for n in os.listdir(records)):
            break
        time.sleep(0.1)
    procs["h1"] = _spawn_elastic(
        corpus, vocab, out, "h1", 2.0,
        fault_spec="cas-put:conflict:nth=1:path=_leases",
        fleet=True, extra_env=mock)
    procs["h2"] = _spawn_elastic(corpus, vocab, out, "h2", 2.0,
                                 fleet=True, extra_env=mock)
    outs = {h: p.communicate(timeout=600)[0] for h, p in procs.items()}
    assert procs["h0"].returncode == -9, outs["h0"]  # really SIGKILLed
    assert procs["h1"].returncode == 0, outs["h1"]
    assert procs["h2"].returncode == 0, outs["h2"]

    # Byte identity ACROSS BACKENDS: the mock-store fleet's merged
    # output equals the local-backend single-host reference.
    assert gs.hash_outputs(out) == reference_hashes
    ref_out = str(tmp_path / "ref")
    proc = _run_driver(corpus, vocab, ref_out, resume=False)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(os.path.join(ref_out, ".manifest.json"), "rb") as f:
        ref_manifest = f.read()
    with open(os.path.join(out, ".manifest.json"), "rb") as f:
        assert f.read() == ref_manifest
    # Scheduling state (lease/ledger objects AND their commit-record
    # sidecars) fully cleaned up.
    assert not os.path.isdir(os.path.join(out, "_leases"))
    assert not os.path.isdir(os.path.join(out, "_done"))
    assert not os.path.isdir(os.path.join(out, "_shuffle"))
    # The dead host's unit was stolen via a CONDITIONAL put, the
    # injected conflict registered, and every unit journaled exactly
    # once across the cluster.
    steals = (_counter_total(mdirs["h1"], "lease_steals_total")
              + _counter_total(mdirs["h2"], "lease_steals_total"))
    assert steals >= 1
    conflicts = sum(_counter_total(m, "backend_cas_conflicts_total")
                    for m in mdirs.values())
    assert conflicts >= 1
    done = sum(_counter_total(m, "elastic_units_completed_total")
               for m in mdirs.values())
    assert done == 24, done


# --------------------------------------------------- streaming ingestion

# Driver for one ingest round (journal diff -> incremental preprocess ->
# delta balance -> journal commit). argv: landing vocab root
_INGEST_DRIVER = """
import sys
from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
from lddl_tpu.ingest import ingest_once

landing, vocab, root = sys.argv[1:4]
tok = get_tokenizer(vocab_file=vocab)
cfg = BertPretrainConfig(max_seq_length=32, masking=False)
print("REPORT", ingest_once(root, tok, landing=landing, config=cfg,
                            num_shards=4, seed=7, log=print))
"""


def _run_ingest(landing, vocab, root, fault_spec=None, timeout=600,
                extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("LDDL_TPU_STORAGE_BACKEND", None)
    if extra_env:
        env.update(extra_env)
    if fault_spec:
        env["LDDL_TPU_FAULTS"] = fault_spec
    else:
        env.pop("LDDL_TPU_FAULTS", None)
    return subprocess.run(
        [sys.executable, "-c", _INGEST_DRIVER, landing, vocab, root],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout)


def _hash_tree(root):
    """Every visible file under ``root`` (shards, manifests, caches,
    journal) — the ingest end state has no timestamps, so full-tree
    bytes compare. Mock-store commit-record sidecars (``.obj.*``) are
    backend implementation detail, excluded so a mock tree compares
    against a local one."""
    import hashlib
    out = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith(".obj."))
        for name in filenames:
            path = os.path.join(dirpath, name)
            with open(path, "rb") as f:
                out[os.path.relpath(path, root)] = hashlib.sha256(
                    f.read()).hexdigest()
    return out


def _ingest_landing(base, corpus, n_files, name):
    import shutil
    d = os.path.join(base, name, "source")
    os.makedirs(d, exist_ok=True)
    for i in range(n_files):
        shutil.copy(os.path.join(corpus, "source", "{}.txt".format(i)),
                    os.path.join(d, "{}.txt".format(i)))
    return os.path.join(base, name)


def test_sigkill_during_ingest_generation_resumes_byte_identical(
        fixture_dirs, tmp_path):
    """SIGKILL the ingest service while it is publishing generation 1's
    shards (after preprocess, after the balance plan marker, BEFORE the
    journal commit). The journal must still read generation 0, and the
    re-run must resume the in-flight generation from its intake record
    and converge to a tree byte-identical — shards, manifests, caches,
    AND journal — to an uninterrupted incremental sequence."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    land2 = _ingest_landing(base, corpus, 2, "land2")
    land3 = _ingest_landing(base, corpus, 3, "land3")

    ref = str(tmp_path / "ref")
    for landing in (land2, land3):
        proc = _run_ingest(landing, vocab, ref)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    root = str(tmp_path / "root")
    proc = _run_ingest(land2, vocab, root)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_ingest(land3, vocab, root,
                       fault_spec="replace:kill:nth=1:path=gen-0001/shard-")
    assert proc.returncode == -9, proc.stdout + proc.stderr  # really killed
    # Mid-generation: the delta's work was in flight but nothing committed
    # — the journal still reads generation 0 and the intake record of the
    # crashed generation is on disk.
    assert not os.path.exists(
        os.path.join(root, ".ingest", "journal", "gen-0001.json"))
    assert os.path.exists(
        os.path.join(root, ".ingest", "work", "gen-0001", "intake.json"))

    proc = _run_ingest(land3, vocab, root)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "generation" in proc.stdout
    assert _hash_tree(root) == _hash_tree(ref)


def test_sigkill_during_mock_ingest_resumes_byte_identical(
        fixture_dirs, tmp_path):
    """The ingest half of the mock-store chaos proof: the ingest service
    runs on the MockObjectStore and is SIGKILLed inside a shard's
    MULTIPART COMMIT during generation 1 — it dies with an abandoned
    multipart upload (orphan parts, no commit record) and the journal
    still at generation 0. The resume — which additionally absorbs an
    injected CAS conflict on a shard put — converges to a tree
    byte-identical to an uninterrupted LOCAL-backend sequence, with both
    generations journaled exactly once."""
    td, corpus, vocab = fixture_dirs
    base = str(tmp_path)
    land2 = _ingest_landing(base, corpus, 2, "mland2")
    land3 = _ingest_landing(base, corpus, 3, "mland3")
    mock = {"LDDL_TPU_STORAGE_BACKEND": "mock"}

    ref = str(tmp_path / "ref")
    for landing in (land2, land3):
        proc = _run_ingest(landing, vocab, ref)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    root = str(tmp_path / "root")
    proc = _run_ingest(land2, vocab, root, extra_env=mock)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _run_ingest(
        land3, vocab, root, extra_env=mock,
        fault_spec="multipart-commit:kill:nth=1:path=gen-0001/shard-")
    assert proc.returncode == -9, proc.stdout + proc.stderr
    # Died mid-multipart: no generation-1 journal record, and the torn
    # upload left orphan parts in the shard's sidecar with no commit
    # record referencing them.
    assert not os.path.exists(
        os.path.join(root, ".ingest", "journal", "gen-0001.json"))
    assert os.path.exists(
        os.path.join(root, ".ingest", "work", "gen-0001", "intake.json"))

    proc = _run_ingest(land3, vocab, root, extra_env=mock,
                       fault_spec="cas-put:conflict:nth=1:path=shard-")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "generation" in proc.stdout
    assert _hash_tree(root) == _hash_tree(ref)
    # Exactly-once journaling on the object store: one committed segment
    # per generation, no duplicates, no holes.
    segs = sorted(n for n in os.listdir(
        os.path.join(root, ".ingest", "journal")) if n.startswith("gen-"))
    assert segs == ["gen-0000.json", "gen-0001.json"]
