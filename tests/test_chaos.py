"""Chaos tests: REAL process death (SIGKILL) of the preprocess runner
mid-scatter and mid-gather, then kill-and-resume byte-identity.

These launch actual subprocesses and full pipeline runs, so they are
marked ``slow`` (excluded from tier-1; run with ``-m slow``). The fast
injector-based resilience suite lives in tests/test_resilience.py.
"""

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

pytestmark = [pytest.mark.slow, pytest.mark.fault]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Driver executed as a subprocess so a SIGKILL takes out the WHOLE runner
# (serial num_workers=1: the scatter/gather runs in the runner process
# itself, exactly like a preempted pod host). argv: corpus vocab out resume
_DRIVER = """
import sys
from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
from lddl_tpu.preprocess.runner import run_bert_preprocess

corpus, vocab, out, resume = sys.argv[1:5]
tok = get_tokenizer(vocab_file=vocab)
cfg = BertPretrainConfig(max_seq_length=32, masking=True)
run_bert_preprocess(
    {"wikipedia": corpus}, out, tok, config=cfg, num_blocks=12,
    sample_ratio=0.9, seed=4242, bin_size=8, global_shuffle=True,
    resume=(resume == "resume"))
"""


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("chaos")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def reference_hashes(fixture_dirs, tmp_path_factory):
    """Hashes of an UNINTERRUPTED run in this environment — the
    byte-identity reference for the kill-and-resume tests. (Computed
    fresh rather than from tests/golden_spool.json: the pinned goldens
    additionally pin parquet codec bytes across library versions, which
    is a different invariant than crash-recovery identity.)"""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path_factory.mktemp("reference") / "out")
    proc = _run_driver(corpus, vocab, out, resume=False)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    hashes = gs.hash_outputs(out)
    assert hashes  # produced shards
    return hashes


def _run_driver(corpus, vocab, out, resume, fault_spec=None, timeout=600):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    if fault_spec:
        env["LDDL_TPU_FAULTS"] = fault_spec
    else:
        env.pop("LDDL_TPU_FAULTS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER, corpus, vocab, out,
         "resume" if resume else "fresh"],
        env=env, cwd=REPO_ROOT, capture_output=True, text=True,
        timeout=timeout)
    return proc


def test_sigkill_mid_scatter_then_resume_is_byte_identical(fixture_dirs,
                                                           reference_hashes,
                                                           tmp_path):
    """SIGKILL the runner while it is appending to the shuffle spool
    (open:kill on a _shuffle path). The rerun with --resume must wipe the
    poisoned partial spool, redo the scatter, and produce output
    byte-identical to an uninterrupted run."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _run_driver(corpus, vocab, out, resume=False,
                       fault_spec="open:kill:nth=5:path=_shuffle")
    assert proc.returncode == -9, proc.stdout + proc.stderr  # really SIGKILLed
    # The kill landed mid-scatter: spool exists, completion marker doesn't.
    assert os.path.isdir(os.path.join(out, "_shuffle"))
    assert not os.path.exists(
        os.path.join(out, "_shuffle", ".scatter_done"))

    proc = _run_driver(corpus, vocab, out, resume=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert gs.hash_outputs(out) == reference_hashes


def test_sigkill_mid_gather_then_resume_is_byte_identical(fixture_dirs,
                                                          reference_hashes,
                                                          tmp_path):
    """SIGKILL the runner between gather units (replace:kill on a _done
    ledger publish — after some units completed, others not). The resume
    must redo ONLY the unfinished units, and the final shards must be
    byte-identical to an uninterrupted run."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _run_driver(corpus, vocab, out, resume=False,
                       fault_spec="replace:kill:nth=4:path=_done/group-")
    assert proc.returncode == -9, proc.stdout + proc.stderr
    # The kill landed mid-gather: scatter completed, some ledgers exist.
    assert os.path.exists(os.path.join(out, "_shuffle", ".scatter_done"))
    done = [n for n in os.listdir(os.path.join(out, "_done"))
            if n.startswith("group-")]
    assert 0 < len(done) < 12  # genuinely mid-gather

    proc = _run_driver(corpus, vocab, out, resume=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert not os.path.isdir(os.path.join(out, "_done"))  # cleaned up
    assert gs.hash_outputs(out) == reference_hashes


def test_uninterrupted_runs_are_deterministic(fixture_dirs,
                                              reference_hashes, tmp_path):
    """Control: two independent fault-free runs are byte-identical, so
    the kill tests above compare against a stable reference."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _run_driver(corpus, vocab, out, resume=False)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert gs.hash_outputs(out) == reference_hashes
