"""Elastic work-stealing preprocess (lddl_tpu/preprocess/steal.py):
byte-identity vs the pinned goldens, multi-host concurrency, dead-host
reclamation, fencing, and failure/resume semantics. In-process and fast
(threads stand in for hosts — the protocol is pure filesystem, so thread
vs process changes nothing); the real SIGKILL chaos runs in
tests/test_chaos.py (-m slow).
"""

import json
import os
import threading
import time

import pytest

import sys
sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu import observability as obs  # noqa: E402
from lddl_tpu.preprocess.runner import run_sharded_pipeline  # noqa: E402
from lddl_tpu.preprocess import steal  # noqa: E402
from lddl_tpu.resilience import leases  # noqa: E402


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("elastic")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def goldens():
    with open(gs.GOLDEN_FILE) as f:
        return json.load(f)


def _bert_processor(vocab, out_dir):
    from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
    from lddl_tpu.preprocess.runner import BertBucketProcessor
    tok = get_tokenizer(vocab_file=vocab)
    # schema_version=1: compared against the pinned v1 goldens (elastic
    # scheduling is schema-independent).
    cfg = BertPretrainConfig(max_seq_length=32, masking=True,
                             schema_version=1)
    return BertBucketProcessor(tok, cfg, 4242, out_dir, 8, "parquet")


_RUN_KW = dict(num_blocks=12, sample_ratio=0.9, seed=4242,
               global_shuffle=True, progress_interval=0.0)


def _run_elastic(corpus, out, proc, holder, ttl=5.0, **kw):
    return run_sharded_pipeline({"wikipedia": corpus}, out, proc,
                                elastic=True, lease_ttl=ttl,
                                holder_id=holder, **dict(_RUN_KW, **kw))


def test_single_elastic_host_matches_golden(fixture_dirs, goldens, tmp_path):
    """One elastic host == the static single-host bytes (the pinned
    goldens), manifest included, with all scheduling state cleaned up."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    written = _run_elastic(corpus, out, _bert_processor(vocab, out), "solo")
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    assert not os.path.isdir(os.path.join(out, "_leases"))
    assert not os.path.isdir(os.path.join(out, "_done"))
    assert not os.path.isdir(os.path.join(out, "_shuffle"))
    assert written and sum(written.values()) > 0


def test_two_elastic_hosts_split_work_byte_identical(fixture_dirs, goldens,
                                                     tmp_path):
    """Two concurrent hosts (threads over the same shared dir — the
    protocol is pure FS) divide the units via leases and produce the
    golden bytes; both return the same GLOBAL census."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    # Processors built before the threads start (transformers' lazy
    # import machinery is not concurrent-first-import safe; real elastic
    # hosts are separate processes).
    procs = {h: _bert_processor(vocab, out) for h in ("hostA", "hostB")}
    results, errors = {}, {}

    def host(hid, delay):
        time.sleep(delay)
        try:
            results[hid] = _run_elastic(corpus, out, procs[hid], hid)
        except Exception as e:  # noqa: BLE001 - surfaced via assert
            errors[hid] = e

    threads = [threading.Thread(target=host, args=("hostA", 0.0)),
               threading.Thread(target=host, args=("hostB", 0.1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    assert results["hostA"] == results["hostB"]  # same global census


def test_dead_host_units_are_reclaimed(fixture_dirs, goldens, tmp_path):
    """A 'dead host' left expired leases, a missing scatter record with
    partial spool appends, a partial bucket output and atomic-write
    debris; a surviving host joining the directory steals every unit,
    sweeps the wreckage, and still produces the goldens."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")

    # Phase 1 — produce a faithful "cluster died mid-gather" state with a
    # REAL elastic run whose gather units all fail: fingerprint manifest
    # and scatter records in place, spool on disk, zero gather ledgers.
    flag_never = str(tmp_path / "never")

    class FailAlways:
        def __init__(self, inner):
            self.inner = inner

        def fingerprint(self):
            return self.inner.fingerprint()

        def __call__(self, texts, bucket):
            if not os.path.exists(flag_never):
                raise RuntimeError("host dies before finishing any bucket")
            return self.inner(texts, bucket)

    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out, FailAlways(_bert_processor(vocab, out)),
                     "deadhost", ttl=0.3)
    assert os.path.exists(os.path.join(out, "_done", "manifest.json"))

    # Phase 2 — plant mid-unit wreckage exactly as a SIGKILLed holder
    # leaves it: unreleased (now expired) leases, a scatter slice whose
    # record is gone but whose partial appends remain, a torn bucket
    # output and its atomic-write temp.
    root = leases.lease_root(out)
    dead = "deadhost2"
    assert leases.try_acquire(root, "group-2", dead, ttl_s=0.01) is not None
    os.remove(os.path.join(out, "_done", "scatter-0.json"))
    assert leases.try_acquire(root, "scatter-0", dead,
                              ttl_s=0.01) is not None
    gdir = os.path.join(out, "_shuffle", "group-2")
    with open(os.path.join(gdir, steal.spool_name(0, 0, dead)), "w") as f:
        f.write("#B 0 2\n torn partial append from a dead host\n")
    with open(os.path.join(out, "part.2.parquet_1"), "wb") as f:
        f.write(b"torn parquet bytes")
    with open(os.path.join(out, "part.2.parquet_1.tmp.999"), "wb") as f:
        f.write(b"tmp debris")
    time.sleep(0.05)  # both planted leases now expired

    # Phase 3 — a survivor joins (no --resume needed: the fingerprint
    # manifest proves the directory belongs to this plan), reclaims, and
    # finishes byte-identically.
    with open(flag_never, "w") as f:
        f.write("alive\n")
    _run_elastic(corpus, out,
                 FailAlways(_bert_processor(vocab, out)), "survivor")
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    assert not os.path.exists(os.path.join(out, "part.2.parquet_1.tmp.999"))
    assert not os.path.isdir(os.path.join(out, "_shuffle"))


def test_fence_rejects_stolen_unit_and_unit_is_redone(fixture_dirs, goldens,
                                                      tmp_path, monkeypatch):
    """Force the stall-steal-fence sequence deterministically: the first
    gather unit this host runs gets its lease overwritten mid-unit (as a
    thief would after the TTL). The host must discard that attempt
    (fence reject counted), redo nothing itself (the 'thief' is then
    expired and the unit reclaimed at a higher epoch), and the final
    bytes must still match the goldens."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    inner = _bert_processor(vocab, out)
    state = {"stolen": False, "calls": 0}
    root = leases.lease_root(out)

    class StealOnce:
        def __init__(self, inner):
            self.inner = inner

        def fingerprint(self):
            return self.inner.fingerprint()

        def __call__(self, texts, bucket):
            state["calls"] += 1
            if not state["stolen"]:
                state["stolen"] = True
                # Thief overwrites this unit's lease at a bumped epoch
                # with an ALREADY-EXPIRED deadline: the fence rejects our
                # publish, and the next scan steals it back and redoes it.
                group = bucket % 12  # ngroups == nbuckets == 12 here
                cur = leases.read_lease(root, "group-{}".format(group))
                assert cur is not None
                leases._publish(
                    leases.lease_path(root, "group-{}".format(group)),
                    leases._record("group-{}".format(group), "thief",
                                   cur["epoch"] + 1, 0.0), "thief")
            return self.inner(texts, bucket)

    monkeypatch.setenv("LDDL_TPU_METRICS_DIR", str(tmp_path / "metrics"))
    obs.registry().reset()
    written = _run_elastic(corpus, out, StealOnce(inner), "victim", ttl=5.0)
    assert state["stolen"]
    assert state["calls"] >= 13  # 12 buckets + at least the redone one
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    assert written and sum(written.values()) > 0
    rejects = obs.registry().counter("lease_fence_rejects_total").total()
    assert rejects >= 1


def test_elastic_failed_unit_resume(fixture_dirs, goldens, tmp_path):
    """A unit that raises on every host fails the run with the standard
    resume message; a later elastic resume (failure cleared) completes
    byte-identically."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "fixed.flag")

    class FailOnce:
        def __init__(self, inner):
            self.inner = inner

        def fingerprint(self):
            return self.inner.fingerprint()

        def __call__(self, texts, bucket):
            if bucket == 3 and not os.path.exists(flag):
                raise RuntimeError("injected failure for bucket 3")
            return self.inner(texts, bucket)

    proc = FailOnce(_bert_processor(vocab, out))
    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out, proc, "hostA", ttl=0.5)
    # Completed units are journaled; the failed one is not.
    done = os.listdir(os.path.join(out, "_done"))
    assert any(n.startswith("group-") for n in done)
    assert not os.path.exists(os.path.join(out, "_done", "group-3.json"))

    with open(flag, "w") as f:
        f.write("ok\n")
    _run_elastic(corpus, out, proc, "hostA", ttl=5.0, resume=True)
    assert gs.hash_outputs(out) == goldens["binned_masked"]


def test_elastic_refuses_mismatched_plan(fixture_dirs, tmp_path):
    """A second host joining with different arguments (a different unit
    plan) must refuse loudly, exactly like a mismatched resume."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _bert_processor(vocab, out)
    flag_never = str(tmp_path / "never")

    class FailAlways:
        def __init__(self, inner):
            self.inner = inner

        def fingerprint(self):
            return self.inner.fingerprint()

        def __call__(self, texts, bucket):
            if not os.path.exists(flag_never):
                raise RuntimeError("keep the run unfinished")
            return self.inner(texts, bucket)

    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out, FailAlways(proc), "hostA", ttl=0.5)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline(
            {"wikipedia": corpus}, out, proc, elastic=True, lease_ttl=5.0,
            holder_id="hostB", **dict(_RUN_KW, num_blocks=24))
    # Elastic and static layouts are mutually exclusive per directory.
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc,
                             resume=True, **_RUN_KW)


def test_elastic_rejects_multihost_comm(fixture_dirs, tmp_path):
    from lddl_tpu.parallel.distributed import ThreadGroupCommunicator
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    proc = _bert_processor(vocab, out)
    shared = ThreadGroupCommunicator._Shared(2)
    comm = ThreadGroupCommunicator(0, 2, shared)
    with pytest.raises(ValueError, match="elastic"):
        run_sharded_pipeline({"wikipedia": corpus}, out, proc,
                             elastic=True, comm=comm, **_RUN_KW)


class _DropAndLog:
    """Picklable: returns a legitimately-EMPTY result ({}) for one bucket
    (a zero-sample unit journals `{}`) and appends every processed bucket
    id to a log file, so a resume can prove which units were redone."""

    def __init__(self, inner, drop_bucket, log_path, fail_bucket=None,
                 fail_flag=None):
        self.inner = inner
        self.drop_bucket = drop_bucket
        self.log_path = log_path
        self.fail_bucket = fail_bucket
        self.fail_flag = fail_flag

    def fingerprint(self):
        return self.inner.fingerprint()

    def __call__(self, texts, bucket):
        with open(self.log_path, "a") as f:
            f.write("{}\n".format(bucket))
        if self.fail_bucket == bucket and not os.path.exists(self.fail_flag):
            raise RuntimeError("injected failure for bucket {}".format(
                bucket))
        if bucket == self.drop_bucket:
            return {}
        return self.inner(texts, bucket)


def test_empty_unit_record_reads_as_done(fixture_dirs, tmp_path):
    """A gather unit whose buckets produce zero samples journals an empty
    {} record — which must read as DONE: an elastic resume may not redo
    it (done-ness is record existence, not record truthiness), and the
    final bytes must match a static run of the same plan."""
    td, corpus, vocab = fixture_dirs
    static_out = str(tmp_path / "static")
    out = str(tmp_path / "out")
    flag = str(tmp_path / "fixed.flag")
    ref_log = str(tmp_path / "ref.log")
    run1_log = str(tmp_path / "run1.log")
    resume_log = str(tmp_path / "resume.log")

    run_sharded_pipeline(
        {"wikipedia": corpus}, static_out,
        _DropAndLog(_bert_processor(vocab, static_out), 5, ref_log),
        **_RUN_KW)

    # Elastic run 1: bucket 5 journals {}, bucket 7 fails -> run raises
    # with _done intact (bucket 5's empty record among it).
    proc = _DropAndLog(_bert_processor(vocab, out), 5, run1_log,
                       fail_bucket=7, fail_flag=flag)
    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out, proc, "hostA", ttl=0.5)
    assert os.path.exists(os.path.join(out, "_done", "group-5.json"))

    with open(flag, "w") as f:
        f.write("ok\n")
    proc = _DropAndLog(_bert_processor(vocab, out), 5, resume_log,
                       fail_bucket=7, fail_flag=flag)
    _run_elastic(corpus, out, proc, "hostA", ttl=5.0, resume=True)
    redone = set(int(x) for x in open(resume_log).read().split())
    assert 5 not in redone, "empty-record unit was redone on resume"
    assert 7 in redone
    assert gs.hash_outputs(out) == gs.hash_outputs(static_out)


def test_finalize_with_stale_retired_ledger_dir(fixture_dirs, goldens,
                                                tmp_path):
    """A finalizer that died between its ledger rename and rmtree leaves
    `_done.retired.<holder>` behind; a later run reusing the SAME holder
    id must still retire the live ledger — the rename onto the existing
    dir would fail ENOTEMPTY, which must not be mistaken for 'already
    retired by someone else' (that would leave `_done/` in the finished
    dataset forever)."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    stale = os.path.join(out, "_done.retired.hostA")
    os.makedirs(stale)
    with open(os.path.join(stale, "group-0.json"), "w") as f:
        f.write("{}")
    _run_elastic(corpus, out, _bert_processor(vocab, out), "hostA")
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    assert not os.path.isdir(os.path.join(out, "_done"))
    assert not any(n.startswith("_done.retired")
                   for n in sorted(os.listdir(out)))


class _KillWorkerOnce:
    """Picklable: SIGKILLs its own pool-worker process for one bucket on
    the first attempt (flag file marks the kill as spent)."""

    def __init__(self, inner, kill_bucket, flag_path):
        self.inner = inner
        self.kill_bucket = kill_bucket
        self.flag_path = flag_path

    def fingerprint(self):
        return self.inner.fingerprint()

    def __call__(self, texts, bucket):
        if bucket == self.kill_bucket and not os.path.exists(self.flag_path):
            import signal
            with open(self.flag_path, "w") as f:
                f.write("killed\n")
            os.kill(os.getpid(), signal.SIGKILL)
        return self.inner(texts, bucket)


def test_elastic_pool_worker_death_is_reclaimed(fixture_dirs, goldens,
                                                tmp_path):
    """Elastic claim loop over a local spawn pool (num_workers=2) with a
    pool worker SIGKILLed mid-unit: in-flight leases are released, the
    pool is rebuilt, the killed unit is re-claimed and re-done, and the
    output still matches the goldens."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag = str(tmp_path / "killed.flag")
    proc = _KillWorkerOnce(_bert_processor(vocab, out), 5, flag)
    _run_elastic(corpus, out, proc, "poolhost", ttl=5.0, num_workers=2)
    assert os.path.exists(flag)  # the kill really happened
    assert gs.hash_outputs(out) == goldens["binned_masked"]


def _fail_always(inner, flag_never):
    class FailAlways:
        def __init__(self, inner):
            self.inner = inner

        def fingerprint(self):
            return self.inner.fingerprint()

        def __call__(self, texts, bucket):
            if not os.path.exists(flag_never):
                raise RuntimeError("host dies before finishing any bucket")
            return self.inner(texts, bucket)

    return FailAlways(inner)


def test_adaptive_plan_crash_resume_byte_identity(fixture_dirs, goldens,
                                                  tmp_path):
    """Crash with a half-adapted plan on disk: the journaled plan record
    survives while some main-unit records are gone (as a SIGKILLed fleet
    leaves things). The resume must adopt the SAME plan — never recompute
    a different partition under the same unit indices — redo only the
    missing units, and finish byte-identical to the goldens."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag_never = str(tmp_path / "never")

    # Phase 1 — a real adaptive run that dies at gather: probes, the plan
    # record, and every scatter main are journaled in _done.
    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out,
                     _fail_always(_bert_processor(vocab, out), flag_never),
                     "deadhost", ttl=0.3)
    plan_path = os.path.join(out, "_done", "scatter-plan.json")
    assert os.path.exists(plan_path)
    with open(plan_path) as f:
        plan1 = json.load(f)
    assert plan1["main"] and all(len(r) == 2 for r in plan1["main"])
    done = set(os.listdir(os.path.join(out, "_done")))
    assert "scatter-p0.json" in done  # probe records carry fixed ids
    assert "scatter-0.json" in done

    # Phase 2 — half-adapt the wreckage: drop one probe record and one
    # main record (their spool appends may survive; the sweep handles
    # that), keeping the plan record itself.
    os.remove(os.path.join(out, "_done", "scatter-p0.json"))
    os.remove(os.path.join(out, "_done", "scatter-0.json"))

    # Phase 3 — a survivor resumes, re-adopts the journaled plan, redoes
    # the two missing units, and the bytes still match the goldens.
    with open(flag_never, "w") as f:
        f.write("alive\n")
    _run_elastic(corpus, out,
                 _fail_always(_bert_processor(vocab, out), flag_never),
                 "survivor")
    assert gs.hash_outputs(out) == goldens["binned_masked"]
    assert not os.path.isdir(os.path.join(out, "_done"))


def test_adaptive_and_fixed_modes_refuse_cross_resume(fixture_dirs,
                                                      tmp_path):
    """The unit plan rides the resume fingerprint: an adaptive directory
    refuses a fixed-unit join and vice versa — two hosts must never run
    different partitions under the same unit indices."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    flag_never = str(tmp_path / "never")
    proc = _bert_processor(vocab, out)
    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out, _fail_always(proc, flag_never),
                     "hostA", ttl=0.5)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        _run_elastic(corpus, out, proc, "hostB", scatter_units=4)

    out2 = str(tmp_path / "out2")
    proc2 = _bert_processor(vocab, out2)
    with pytest.raises(RuntimeError, match="re-run with resume"):
        _run_elastic(corpus, out2, _fail_always(proc2, flag_never),
                     "hostA", ttl=0.5, scatter_units=4)
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        _run_elastic(corpus, out2, proc2, "hostB")


def test_fixed_scatter_units_still_golden(fixture_dirs, goldens, tmp_path):
    """An explicit --scatter-units pin (the classic fixed stride) remains
    byte-identical to the goldens."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    _run_elastic(corpus, out, _bert_processor(vocab, out), "fixedhost",
                 scatter_units=4)
    assert gs.hash_outputs(out) == goldens["binned_masked"]


def test_legacy_coordination_byte_identity(fixture_dirs, goldens, tmp_path,
                                           monkeypatch):
    """LDDL_TPU_COORD_LEGACY=1 (per-lease renewals, unsnapshotted claim
    scans, barrier gather) against the batched/incremental default:
    identical bytes for the pinned binned v1 goldens AND for a packed
    schema-v2 pair run — the coordination rework must be invisible in
    the output."""
    td, corpus, vocab = fixture_dirs

    legacy_out = str(tmp_path / "legacy")
    monkeypatch.setenv("LDDL_TPU_COORD_LEGACY", "1")
    _run_elastic(corpus, legacy_out, _bert_processor(vocab, legacy_out),
                 "legacyhost", scatter_units=4)
    assert gs.hash_outputs(legacy_out) == goldens["binned_masked"]

    def packed_proc(out_dir):
        from lddl_tpu.preprocess import BertPretrainConfig, get_tokenizer
        from lddl_tpu.preprocess.runner import BertBucketProcessor
        tok = get_tokenizer(vocab_file=vocab)
        cfg = BertPretrainConfig(max_seq_length=32, masking=False,
                                 schema_version=2)
        return BertBucketProcessor(tok, cfg, 4242, out_dir, None, "parquet",
                                   pack_seq_length=64, pack_max_per_row=4)

    packed_legacy = str(tmp_path / "packed_legacy")
    _run_elastic(corpus, packed_legacy, packed_proc(packed_legacy),
                 "legacyhost", scatter_units=4)
    monkeypatch.delenv("LDDL_TPU_COORD_LEGACY")
    packed_new = str(tmp_path / "packed_new")
    _run_elastic(corpus, packed_new, packed_proc(packed_new), "newhost")
    assert gs.hash_outputs(packed_new) == gs.hash_outputs(packed_legacy)


def test_elastic_no_global_shuffle(fixture_dirs, goldens, tmp_path):
    """Elastic block mode (no scatter phase): blocks are the units."""
    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    static_out = str(tmp_path / "static")
    proc = _bert_processor(vocab, out)
    sproc = _bert_processor(vocab, static_out)
    kw = dict(_RUN_KW, global_shuffle=False)
    run_sharded_pipeline({"wikipedia": corpus}, static_out, sproc, **kw)
    run_sharded_pipeline({"wikipedia": corpus}, out, proc, elastic=True,
                         lease_ttl=5.0, holder_id="solo", **kw)
    assert gs.hash_outputs(out) == gs.hash_outputs(static_out)
