"""The concurrency invariant analyzer (lddl_tpu/analysis/concurrency)
and the regression pins for the races it surfaced.

Layers:

1. Fixture corpus — for EACH of the four rules: at least one
   interprocedural true positive (the racy/unsafe effect lives in a
   different function or file than the boundary that makes it unsafe)
   and at least one locked/sanitized negative that must stay silent.
2. Engine exemptions — the observability registry allow-list, the
   flush-on-TERM blocking sanction (locks stay unsanctioned), and the
   env-source exemption.
3. Integration — suppressions and the content-hash cache apply to the
   concurrency findings exactly as to the dataflow ones (cfacts ride
   the same cache entries).
4. Regression pins for the true positives this analyzer found in the
   real tree (fleet._hb / fleet._ev_segment / series._segment writes
   moved under their RLocks, backend._instances_lock made reentrant,
   faults._state growing a lock) — concurrent functional smokes plus
   the full-tree gate staying at zero.
"""

import json
import os
import textwrap
import threading

from lddl_tpu import analysis
from lddl_tpu.analysis import concurrency


def write_tree(root, files):
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))


def run_tree(tmp_path, files, rules=None, cache=False, **kw):
    write_tree(tmp_path, files)
    top = sorted({rel.split("/")[0] for rel in files})
    return analysis.run_check(
        top, root=str(tmp_path), baseline_path=kw.pop("baseline_path", ""),
        rules=analysis.get_rules(rules) if rules else None,
        cache_path=str(tmp_path / "cache.json") if cache else None, **kw)


def findings(report, rule):
    return [f for f in report.new if f.rule == rule]


# ----------------------------------------------------------- thread-escape


THREAD_ESCAPE_TP = {
    "app/state.py": """\
        CACHE = {}
        """,
    "app/worker.py": """\
        import threading

        from app import state

        def start():
            t = threading.Thread(
                target=lambda: state.CACHE.update({"k": 1}))
            t.start()
            return t

        def record(v):
            state.CACHE["x"] = v
        """,
}


def test_thread_escape_through_lambda(tmp_path):
    """The boundary is a lambda handed to Thread(target=); the other
    side's write lives in a different function — neither alone is a
    finding, the cross-thread pair is."""
    report = run_tree(tmp_path, THREAD_ESCAPE_TP, rules=["thread-escape"])
    hits = findings(report, "thread-escape")
    assert len(hits) == 2, [f.format() for f in report.new]
    # Line 7: the lambda's .update() on the thread side; line 12: the
    # main-side subscript write in record().
    assert {(f.path, f.line) for f in hits} == {
        ("app/worker.py", 7), ("app/worker.py", 12)}
    assert "app.state.CACHE" in hits[0].message


def test_thread_escape_locked_negative(tmp_path):
    files = {
        "app/state.py": """\
            import threading
            CACHE = {}
            LOCK = threading.Lock()
            """,
        "app/worker.py": """\
            import threading

            from app import state

            def start():
                t = threading.Thread(target=_loop)
                t.start()

            def _loop():
                with state.LOCK:
                    state.CACHE.update({"k": 1})

            def record(v):
                with state.LOCK:
                    state.CACHE["x"] = v
            """,
    }
    report = run_tree(tmp_path, files, rules=["thread-escape"])
    assert findings(report, "thread-escape") == []


def test_thread_escape_through_param_mutation(tmp_path):
    """The fleet.rotating_path bug class: the global is passed INTO a
    helper that mutates its parameter — the write happens two frames
    away from the global's name."""
    files = {
        "app/seg.py": """\
            import threading

            STATE = {}

            def bump(d):
                d["n"] = 1

            def on_thread():
                bump(STATE)

            def start():
                threading.Thread(target=on_thread).start()

            def main_side():
                bump(STATE)
            """,
    }
    report = run_tree(tmp_path, files, rules=["thread-escape"])
    hits = findings(report, "thread-escape")
    assert {(f.path, f.line) for f in hits} == {
        ("app/seg.py", 9), ("app/seg.py", 15)}


def test_thread_escape_entry_lock_negative(tmp_path):
    """A helper only ever CALLED with the lock held counts as guarded
    (must-hold entry analysis) — the write itself has no lexical
    ``with``."""
    files = {
        "app/seg.py": """\
            import threading

            STATE = {}
            LOCK = threading.Lock()

            def bump():
                STATE["n"] = 1

            def on_thread():
                with LOCK:
                    bump()

            def start():
                threading.Thread(target=on_thread).start()

            def main_side():
                with LOCK:
                    bump()
            """,
    }
    report = run_tree(tmp_path, files, rules=["thread-escape"])
    assert findings(report, "thread-escape") == []


def test_thread_escape_registry_exempt(tmp_path):
    """The sanctioned observability registry is the one shared-state
    surface allowed to manage its own discipline (allow-listed)."""
    files = {
        "lddl_tpu/observability/registry.py":
            THREAD_ESCAPE_TP["app/worker.py"],
        "app/state.py": THREAD_ESCAPE_TP["app/state.py"],
        "app/worker.py": THREAD_ESCAPE_TP["app/worker.py"],
    }
    report = run_tree(tmp_path, files, rules=["thread-escape"])
    hits = findings(report, "thread-escape")
    # The same racy code fires in app/worker.py but NOT in the
    # allow-listed registry path.
    assert hits and all(f.path == "app/worker.py" for f in hits)


def test_thread_escape_immutable_global_negative(tmp_path):
    """Rebinding-style scalars and tuples are not escaped MUTABLE
    state; only shared containers fire."""
    files = {
        "app/state.py": """\
            LIMIT = (1, 2)
            """,
        "app/worker.py": """\
            import threading

            from app import state

            def start():
                threading.Thread(target=_loop).start()

            def _loop():
                return state.LIMIT
            """,
    }
    report = run_tree(tmp_path, files, rules=["thread-escape"])
    assert findings(report, "thread-escape") == []


# -------------------------------------------------------------- lock-order


def test_lock_order_inversion_across_functions(tmp_path):
    """A takes B through one call chain, B takes A through another —
    neither function alone shows both locks."""
    files = {
        "app/sync.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def ab():
                with A:
                    take_b()

            def take_b():
                with B:
                    pass

            def ba():
                with B:
                    take_a()

            def take_a():
                with A:
                    pass
            """,
    }
    report = run_tree(tmp_path, files, rules=["lock-order"])
    hits = findings(report, "lock-order")
    assert len(hits) == 1, [f.format() for f in report.new]
    assert "both orders" in hits[0].message
    assert "app.sync.A" in hits[0].message
    assert "app.sync.B" in hits[0].message


def test_lock_order_consistent_negative(tmp_path):
    files = {
        "app/sync.py": """\
            import threading

            A = threading.Lock()
            B = threading.Lock()

            def one():
                with A:
                    with B:
                        pass

            def two():
                with A:
                    inner()

            def inner():
                with B:
                    pass
            """,
    }
    report = run_tree(tmp_path, files, rules=["lock-order"])
    assert findings(report, "lock-order") == []


def test_lock_order_self_deadlock(tmp_path):
    """A non-reentrant Lock re-acquired down the call chain deadlocks;
    the RLock twin stays silent."""
    files = {
        "app/sync.py": """\
            import threading

            A = threading.Lock()
            R = threading.RLock()

            def outer():
                with A:
                    inner()

            def inner():
                with A:
                    pass

            def outer_r():
                with R:
                    inner_r()

            def inner_r():
                with R:
                    pass
            """,
    }
    report = run_tree(tmp_path, files, rules=["lock-order"])
    hits = findings(report, "lock-order")
    assert len(hits) == 1
    assert "app.sync.A" in hits[0].message
    assert "re-acquired" in hits[0].message or "already" in \
        hits[0].message


# ----------------------------------------------------------- signal-safety


def test_signal_handler_blocking_call(tmp_path):
    """The blocking write lives two calls below the handler, and the
    handler itself is a nested def (the exporters.install_signal_flush
    shape)."""
    files = {
        "svc/handlers.py": """\
            import signal

            def install():
                def _on_term(signum, frame):
                    save()
                signal.signal(signal.SIGTERM, _on_term)

            def save():
                flush_to_disk()

            def flush_to_disk():
                with open("/tmp/x", "w") as f:
                    f.write("bye")
            """,
    }
    report = run_tree(tmp_path, files, rules=["signal-safety"])
    hits = findings(report, "signal-safety")
    assert len(hits) == 1, [f.format() for f in report.new]
    assert hits[0].path == "svc/handlers.py" and hits[0].line == 12
    assert "write-mode open()" in hits[0].message


def test_signal_handler_nonreentrant_lock(tmp_path):
    files = {
        "svc/handlers.py": """\
            import signal
            import threading

            L = threading.Lock()
            R = threading.RLock()

            def install():
                signal.signal(signal.SIGTERM, _on_term)

            def _on_term(signum, frame):
                finish()

            def finish():
                with L:
                    pass
                with R:
                    pass
            """,
    }
    report = run_tree(tmp_path, files, rules=["signal-safety"])
    hits = findings(report, "signal-safety")
    # The Lock fires, the RLock (PR 10's fix idiom) does not.
    assert len(hits) == 1
    assert "svc.handlers.L" in hits[0].message
    assert "RLock" in hits[0].message


def test_signal_safety_not_on_handler_path_negative(tmp_path):
    """The identical blocking/locking code with no signal registration
    reaching it stays silent."""
    files = {
        "svc/handlers.py": """\
            import threading

            L = threading.Lock()

            def finish():
                with L:
                    with open("/tmp/x", "w") as f:
                        f.write("bye")
            """,
    }
    report = run_tree(tmp_path, files, rules=["signal-safety"])
    assert findings(report, "signal-safety") == []


def test_signal_safety_observability_sanction_is_blocking_only(tmp_path):
    """Inside the observability package the flush-on-TERM blocking I/O
    is sanctioned — but a non-reentrant lock still fires (that class is
    never sanctioned)."""
    files = {
        "lddl_tpu/observability/exp.py": """\
            import signal
            import threading

            L = threading.Lock()

            def install():
                signal.signal(signal.SIGTERM, _on_term)

            def _on_term(signum, frame):
                with L:
                    with open("/tmp/x", "w") as f:
                        f.write("bye")
            """,
    }
    report = run_tree(tmp_path, files, rules=["signal-safety"])
    hits = findings(report, "signal-safety")
    assert len(hits) == 1
    assert "Lock" in hits[0].message
    assert "open" not in hits[0].message


# ---------------------------------------------------- env-read-after-spawn


def test_env_read_after_spawn_interprocedural(tmp_path):
    """The spawn hides inside a helper; the late read is in the caller
    — only the cross-function view shows read-follows-spawn."""
    files = {
        "run/pool.py": """\
            import concurrent.futures as cf
            import os

            def spawn_pool():
                return cf.ProcessPoolExecutor(2)

            def main():
                pool = spawn_pool()
                n = os.environ.get("LDDL_TPU_WORKERS", "1")
                return pool, n
            """,
    }
    report = run_tree(tmp_path, files, rules=["env-read-after-spawn"])
    hits = findings(report, "env-read-after-spawn")
    assert len(hits) == 1, [f.format() for f in report.new]
    assert hits[0].path == "run/pool.py" and hits[0].line == 9
    assert "LDDL_TPU_WORKERS" in hits[0].message


def test_env_read_before_spawn_negative(tmp_path):
    """The PR 18 runner idiom — pin config, then spawn — is the
    sanctioned order."""
    files = {
        "run/pool.py": """\
            import concurrent.futures as cf
            import os

            def main():
                n = os.environ.get("LDDL_TPU_WORKERS", "1")
                os.environ.setdefault("LDDL_TPU_NATIVE_THREADS", n)
                pool = cf.ProcessPoolExecutor(int(n))
                return pool
            """,
    }
    report = run_tree(tmp_path, files, rules=["env-read-after-spawn"])
    assert findings(report, "env-read-after-spawn") == []


def test_env_read_exempt_source_negative(tmp_path):
    """Observability gating reads (enabled()-style, re-read per hook by
    design) do not count as sources even via calls."""
    files = {
        "run/pool.py": """\
            import concurrent.futures as cf

            from lddl_tpu.observability import gate

            def main():
                pool = cf.ProcessPoolExecutor(2)
                if gate.enabled():
                    return pool
            """,
        "lddl_tpu/observability/gate.py": """\
            import os

            def enabled():
                return bool(os.environ.get("LDDL_TPU_FLEET_DIR"))
            """,
    }
    report = run_tree(tmp_path, files, rules=["env-read-after-spawn"])
    assert findings(report, "env-read-after-spawn") == []


# ------------------------------------------------------------- integration


def test_suppression_applies_to_concurrency_findings(tmp_path):
    files = dict(THREAD_ESCAPE_TP)
    files["app/worker.py"] = files["app/worker.py"].replace(
        'state.CACHE["x"] = v',
        'state.CACHE["x"] = v  # lddl: disable=thread-escape')
    report = run_tree(tmp_path, files, rules=["thread-escape"])
    hits = findings(report, "thread-escape")
    assert len(hits) == 1 and hits[0].line == 7
    assert any(f.rule == "thread-escape" and f.line == 12
               for f in report.suppressed)


def test_concurrency_facts_ride_the_cache(tmp_path):
    """Second run serves every file from cache (cfacts round-trip) and
    reproduces the identical findings."""
    cold = run_tree(tmp_path, THREAD_ESCAPE_TP, rules=["thread-escape"],
                    cache=True)
    warm = run_tree(tmp_path, THREAD_ESCAPE_TP, rules=["thread-escape"],
                    cache=True)
    assert warm.files_cached == warm.files == cold.files
    assert [(f.path, f.line, f.rule) for f in warm.new] == \
        [(f.path, f.line, f.rule) for f in cold.new]
    blob = json.loads((tmp_path / "cache.json").read_text())
    assert all("cfacts" in entry for entry in blob["files"].values())


def test_rule_ids_registered():
    assert set(concurrency.CONCURRENCY_RULE_IDS) <= set(analysis.RULE_IDS)


# ------------------------------------- regression pins for real-tree fixes


def test_concurrent_flush_events_loses_nothing(tmp_path):
    """fleet.flush_events raced the heartbeat thread on the shared
    _ev_segment dict (rotating_path mutates it outside _lock before the
    fix); N threads flushing while events stream in must land every
    event exactly once."""
    from lddl_tpu.observability import fleet

    fleet._reset_for_tests()
    try:
        fleet.configure(str(tmp_path), holder_id="hA", ttl=5,
                        interval=60)
        n_events = 120
        for i in range(n_events):
            fleet.record("unit.claimed", unit="u{}".format(i), epoch=0)
        errors = []

        def flusher():
            try:
                for _ in range(10):
                    fleet.flush_events()
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)

        threads = [threading.Thread(target=flusher) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fleet.flush_events()
        assert errors == []
        spool = fleet.spool_dir()
        got = []
        for name in sorted(os.listdir(spool)):
            if name.startswith("events-pid"):
                events, torn = fleet.read_jsonl(
                    os.path.join(spool, name))
                assert torn == 0
                got.extend(ev["args"]["unit"] for ev in events
                           if ev.get("kind") == "unit.claimed")
        assert sorted(got) == sorted("u{}".format(i)
                                     for i in range(n_events))
    finally:
        fleet._reset_for_tests()
        os.environ.pop("LDDL_TPU_FLEET_DIR", None)


def test_concurrent_series_flush_loses_nothing(tmp_path):
    """series.flush raced the sampler thread on _segment the same way;
    concurrent flushes must persist every point exactly once."""
    from lddl_tpu.observability import fleet, series

    fleet._reset_for_tests()
    try:
        fleet.configure(str(tmp_path), holder_id="hA", ttl=5,
                        interval=60)
        n_points = 80
        for _ in range(n_points):
            assert series.sample() is not None
        errors = []

        def flusher():
            try:
                for _ in range(10):
                    series.flush()
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)

        threads = [threading.Thread(target=flusher) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        series.flush()
        assert errors == []
        spool = fleet.spool_dir()
        got = 0
        for name in sorted(os.listdir(spool)):
            if name.startswith(series.SEGMENT_PREFIX):
                points, torn = fleet.read_jsonl(
                    os.path.join(spool, name))
                assert torn == 0
                got += len(points)
        assert got == n_points
    finally:
        fleet._reset_for_tests()
        os.environ.pop("LDDL_TPU_FLEET_DIR", None)


def test_hb_handles_published_under_lock(tmp_path):
    """ensure_started publishes the heartbeat thread/stop handles under
    _lock now; the analyzer gate below enforces it statically, this
    pins the functional behavior (start + reset race-free)."""
    from lddl_tpu.observability import fleet

    fleet._reset_for_tests()
    try:
        os.environ["LDDL_TPU_FLEET_DIR"] = str(tmp_path)
        os.environ["LDDL_TPU_FLEET_HEARTBEAT_S"] = "30"
        fleet.ensure_started()
        with fleet._lock:
            t = fleet._hb["thread"]
        assert t is not None and t.daemon
        fleet._reset_for_tests()
        assert fleet._hb["thread"] is None
        assert not t.is_alive() or t.join(5) is None
    finally:
        fleet._reset_for_tests()
        os.environ.pop("LDDL_TPU_FLEET_DIR", None)
        os.environ.pop("LDDL_TPU_FLEET_HEARTBEAT_S", None)


def test_backend_instances_lock_is_reentrant():
    """get_backend sits on the SIGTERM flush path: a signal interrupting
    a frame that holds the instances lock must be able to re-enter
    (threading.Lock here was the PR 10 bug class)."""
    from lddl_tpu.resilience import backend

    assert backend._instances_lock.acquire(blocking=False)
    try:
        # Reentrant: a second acquire from the same thread succeeds.
        assert backend._instances_lock.acquire(blocking=False)
        backend._instances_lock.release()
    finally:
        backend._instances_lock.release()


def test_faults_state_refresh_is_locked():
    """faults._refresh mutates the shared clause state from whatever
    thread hits a hook; concurrent arm/refresh churn must never corrupt
    it or raise."""
    from lddl_tpu.resilience import faults

    faults.disarm()
    try:
        errors = []

        def churn(spec):
            try:
                for _ in range(50):
                    faults.arm(spec)
                    faults._refresh()
            except Exception as e:  # noqa: BLE001 - the assertion
                errors.append(e)

        threads = [
            threading.Thread(target=churn, args=(spec,))
            for spec in ("sink-write:eio:p=0.0",
                         "journal-read:eio:p=0.0")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errors == []
        assert faults._refresh() is not None
        # The same thread can refresh while holding the lock (reentrant
        # — a signal-interrupted hook must not deadlock its own state).
        with faults._state_lock:
            faults._refresh()
    finally:
        faults.disarm()
