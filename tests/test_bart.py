"""BART pipeline: chunking, preprocess e2e, denoising loader."""

import numpy as np
import pytest

from lddl_tpu.balance import balance_shards
from lddl_tpu.loader import get_bart_pretrain_data_loader
from lddl_tpu.preprocess import (
    BartPretrainConfig,
    build_wordpiece_vocab,
    get_tokenizer,
    run_bart_preprocess,
)
from lddl_tpu.preprocess.bart import chunks_from_text
from lddl_tpu.utils import rng as lrng
from lddl_tpu.utils.fs import get_all_parquets_under


def test_chunks_from_text():
    config = BartPretrainConfig(target_seq_length=16, short_seq_prob=0.0)
    text = " ".join("Word one two three four five six seven." for _ in range(6))
    g = lrng.sample_rng(0, 1)
    chunks = chunks_from_text(text, config, g)
    assert len(chunks) >= 2
    # Greedy accumulation: every chunk except the last crosses the target.
    for c in chunks[:-1]:
        assert len(c.split()) >= 13  # target 16 - 3
    # All words preserved in order.
    assert " ".join(chunks).split() == text.split()


def test_chunks_short_seq_prob():
    config = BartPretrainConfig(target_seq_length=64, short_seq_prob=1.0)
    text = " ".join("Alpha beta gamma delta epsilon." for _ in range(40))
    chunks = chunks_from_text(text, config, lrng.sample_rng(0, 2))
    # With prob 1.0 every target redraws short, so chunks vary in length.
    lens = {len(c.split()) for c in chunks}
    assert len(lens) > 2


@pytest.fixture(scope="module")
def bart_pipeline(tmp_path_factory, request):
    root = tmp_path_factory.mktemp("bart")
    source = root / "corpus" / "source"
    source.mkdir(parents=True)
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa").split()
    g = np.random.Generator(np.random.Philox(key=[0, 13]))
    with open(source / "0.txt", "w") as f:
        for d in range(40):
            sents = []
            for _ in range(int(g.integers(4, 10))):
                n = int(g.integers(5, 12))
                sents.append(" ".join(
                    words[int(g.integers(0, len(words)))] for _ in range(n)
                ).capitalize() + ".")
            f.write("doc-{} {}\n".format(d, " ".join(sents)))
    vocab = build_wordpiece_vocab([" ".join(words)] * 3,
                                  str(root / "vocab.txt"), vocab_size=200)
    run_bart_preprocess(
        {"wiki": str(root / "corpus")}, str(root / "pre"),
        config=BartPretrainConfig(target_seq_length=48),
        num_blocks=3, sample_ratio=1.0, seed=0)
    balance_shards(str(root / "pre"), str(root / "bal"), 3)
    return {"root": root, "vocab": vocab, "bal": str(root / "bal")}


def test_bart_preprocess_schema(bart_pipeline):
    import pyarrow.parquet as pq
    paths = get_all_parquets_under(bart_pipeline["bal"])
    assert len(paths) == 3
    t = pq.read_table(paths[0])
    assert t.column_names == ["sentences"]
    assert t.num_rows > 0
    assert all(isinstance(s, str) and s for s in
               t.column("sentences").to_pylist())


def test_bart_loader(bart_pipeline):
    loader = get_bart_pretrain_data_loader(
        bart_pipeline["bal"], batch_size=8,
        vocab_file=bart_pipeline["vocab"], max_seq_length=64,
        num_workers=1, base_seed=3, log_level=50)
    tok = get_tokenizer(vocab_file=bart_pipeline["vocab"])
    mask_id = tok.convert_tokens_to_ids("[MASK]")
    n = 0
    saw_mask = False
    for b in loader:
        n += 1
        B, L = b["input_ids"].shape
        assert b["decoder_input_ids"].shape == (B, L)
        assert b["labels"].shape == (B, L)
        saw_mask |= bool((b["input_ids"] == mask_id).any())
        # Decoder input is the shift-right of labels.
        valid = b["labels"] != -1
        for i in range(B):
            d_len = valid[i].sum()
            np.testing.assert_array_equal(
                b["decoder_input_ids"][i, 1:d_len],
                b["labels"][i, :d_len - 1])
        # Encoder shorter-or-equal: infilling collapses spans.
        assert (b["attention_mask"].sum(axis=1) <= valid.sum(axis=1) + 8).all()
    assert n == len(loader)
    assert saw_mask


def test_bart_loader_deterministic(bart_pipeline):
    mk = lambda: get_bart_pretrain_data_loader(
        bart_pipeline["bal"], batch_size=8,
        vocab_file=bart_pipeline["vocab"], max_seq_length=64,
        base_seed=3, log_level=50)
    a = [b["input_ids"] for b in mk()]
    c = [b["input_ids"] for b in mk()]
    for x, y in zip(a, c):
        np.testing.assert_array_equal(x, y)
