"""Observability: registry semantics, tracing, exporters, stage spans
(lint), trace_summary tool, and — the load-bearing part — telemetry
INERTNESS: byte-identical pipeline output with metrics on vs off, and a
near-zero disabled-mode cost guard."""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from lddl_tpu import observability as obs
from lddl_tpu.observability import exporters, tracing

# The package exports a ``registry()`` accessor under the same name as the
# submodule, so fetch the MODULE explicitly.
reg_mod = importlib.import_module("lddl_tpu.observability.registry")


@pytest.fixture(autouse=True)
def _obs_isolation():
    """Every test starts disabled with an empty registry and leaves no
    env/exporter-thread residue for the rest of the suite."""
    prev_dir = os.environ.get(reg_mod.ENV_DIR)
    prev_rank = os.environ.get(reg_mod.ENV_RANK)
    obs.registry().reset()
    tracing._reset_for_tests()
    os.environ.pop(reg_mod.ENV_DIR, None)
    yield
    exporters.stop_periodic_export()
    for key, prev in ((reg_mod.ENV_DIR, prev_dir),
                      (reg_mod.ENV_RANK, prev_rank)):
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev
    obs.registry().reset()
    tracing._reset_for_tests()


# ------------------------------------------------------------ registry


def test_disabled_helpers_record_nothing():
    assert not obs.enabled()
    obs.inc("x_total", 5)
    obs.set_gauge("g", 1.0)
    obs.observe("h", 2.0)
    assert obs.registry().names() == []


def test_disabled_span_is_shared_noop():
    s1 = obs.span("a")
    s2 = obs.span("b", k=1)
    assert s1 is s2  # shared singleton: no per-call allocation
    with s1:
        pass
    obs.event("e")
    assert tracing.pending_events() == 0


def test_counter_gauge_histogram_semantics(tmp_path):
    obs.configure(dir=str(tmp_path))
    reg = obs.registry()
    c = reg.counter("req_total")
    c.inc()
    c.inc(2, stage="a")
    c.inc(3, stage="a")
    assert c.value() == 1
    assert c.value(stage="a") == 5
    assert c.total() == 6
    c.inc(-7)  # counters are monotonic: negative deltas clamp to 0
    assert c.value() == 1

    g = reg.gauge("fill")
    g.set(0.5)
    g.set(0.25, worker=1)
    assert g.value() == 0.5
    assert g.value(worker=1) == 0.25

    h = reg.histogram("lat")
    for v in (0.001, 0.002, 0.004, 3.0, 0.0):
        h.observe(v)
    st = h.stats()
    assert st["count"] == 5
    assert st["min"] == 0.0 and st["max"] == 3.0
    assert abs(st["sum"] - 3.007) < 1e-9
    # log-bucketed: 0.001->2^-9, 0.002->2^-8, 0.004->2^-7, 3.0->2^2,
    # 0.0 -> the None underflow bucket
    assert sum(st["buckets"].values()) == 5
    assert st["buckets"][None] == 1

    # same name, different type: a genuine instrumentation bug, raises
    with pytest.raises(TypeError):
        reg.gauge("req_total")


def test_registry_thread_safety(tmp_path):
    obs.configure(dir=str(tmp_path))
    c = obs.registry().counter("n_total")

    def worker():
        for _ in range(10000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 80000


def test_enablement_is_env_inherited(tmp_path):
    # The env var is the source of truth, so spawned workers inherit it.
    assert not obs.enabled()
    os.environ[reg_mod.ENV_DIR] = str(tmp_path)
    assert obs.enabled()
    assert obs.metrics_dir() == str(tmp_path)
    del os.environ[reg_mod.ENV_DIR]
    assert not obs.enabled()


# ------------------------------------------------------------- tracing


def test_span_emits_chrome_trace_events(tmp_path):
    obs.configure(dir=str(tmp_path), rank=3)
    with obs.span("stage.outer", shard=7):
        with obs.span("stage.inner"):
            pass
    obs.event("stage.tick", n=1)
    path = obs.flush()
    assert os.path.basename(path) == "trace-rank3-pid{}.jsonl".format(
        os.getpid())
    events = [json.loads(l) for l in open(path)]
    by_name = {e["name"]: e for e in events}
    assert by_name["stage.outer"]["ph"] == "X"
    assert by_name["stage.outer"]["args"] == {"shard": 7}
    assert by_name["stage.inner"]["ph"] == "X"
    assert by_name["stage.tick"]["ph"] == "i"
    # the inner span nests inside the outer one on the same timeline
    outer, inner = by_name["stage.outer"], by_name["stage.inner"]
    assert outer["ts"] <= inner["ts"]
    assert inner["dur"] <= outer["dur"]
    assert by_name["process_name"]["ph"] == "M"  # Perfetto metadata


def test_span_records_error_but_propagates(tmp_path):
    obs.configure(dir=str(tmp_path))
    with pytest.raises(ValueError):
        with obs.span("stage.fails"):
            raise ValueError("boom")
    events = [json.loads(l) for l in open(obs.flush())]
    ev = [e for e in events if e["name"] == "stage.fails"][0]
    assert ev["args"]["error"] == "ValueError"


# ----------------------------------------------------------- exporters


def test_prom_and_jsonl_and_summary_exports(tmp_path):
    obs.configure(dir=str(tmp_path), rank=0)
    obs.inc("loader_real_tokens_total", 90)
    obs.inc("loader_padded_slots_total", 100)
    obs.inc("resilience_retry_attempts_total", 2, op="read")
    obs.observe("loader_batch_latency_seconds", 0.004)

    prom = open(obs.export_prom()).read()
    assert "# TYPE loader_real_tokens_total counter" in prom
    assert "loader_real_tokens_total 90" in prom
    assert 'resilience_retry_attempts_total{op="read"} 2' in prom
    assert 'loader_batch_latency_seconds_bucket{le="+Inf"} 1' in prom
    assert "loader_batch_latency_seconds_count 1" in prom

    line = json.loads(open(obs.export_jsonl()).read().splitlines()[-1])
    assert line["metrics"]["loader_real_tokens_total"]["values"][""] == 90

    s = obs.summary()
    assert s["padding_efficiency"] == pytest.approx(0.9)
    assert s["retries"] == 2
    summary_path = obs.write_summary()
    assert json.load(open(summary_path))["real_tokens"] == 90


def test_export_failure_is_inert(tmp_path):
    # An unwritable metrics dir must not raise into the pipeline.
    target = tmp_path / "file"
    target.write_text("not a dir")
    os.environ[reg_mod.ENV_DIR] = str(target / "sub")
    obs.inc("x_total")
    with obs.span("s"):
        pass
    assert obs.export_prom() is None
    assert obs.export_jsonl() is None
    assert obs.write_summary() is None


# ------------------------------------------------- lint: stage spans


def test_every_stage_entry_point_opens_a_top_level_span():
    """The public entry point of each pipeline stage must open its
    top-level span, so traces always carry the stage skeleton. The span
    names are stable API (README table). Migrated from a grep to the AST
    analyzer's stage-span rule (single source of truth — see
    tests/test_analysis.py)."""
    from lddl_tpu import analysis
    from lddl_tpu.analysis.rules import STAGE_SPANS
    assert set(STAGE_SPANS.items()) == {
        ("lddl_tpu/preprocess/runner.py", ("preprocess.run",)),
        ("lddl_tpu/preprocess/steal.py", ("preprocess.gather",
                                          "preprocess.finalize")),
        ("lddl_tpu/balance/balancer.py", ("balance.run",)),
        ("lddl_tpu/loader/dataloader.py", ("loader.epoch",)),
        ("lddl_tpu/ingest/incremental.py", ("ingest.run",)),
    }
    report = analysis.run_check(
        ["lddl_tpu"], rules=analysis.get_rules(["stage-span"]))
    assert report.errors == []
    assert report.new == [], (
        "stage entry points without a top-level span:\n{}".format(
            "\n".join(f.format() for f in report.new)))
    # The rule still fails a stage file that loses its span.
    findings, _ = analysis.analyze_source(
        "def balance_shards(a, b):\n    return None\n",
        "lddl_tpu/balance/balancer.py", analysis.get_rules(["stage-span"]))
    assert [f.rule for f in findings] == ["stage-span"]


# ------------------------------------------------------ trace_summary


def _load_trace_summary():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "trace_summary.py")
    spec = importlib.util.spec_from_file_location("trace_summary", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_summary_tool(tmp_path, capsys):
    obs.configure(dir=str(tmp_path))
    with obs.span("preprocess.run"):
        with obs.span("preprocess.scatter"):
            pass
    with obs.span("loader.epoch"):
        pass
    obs.event("resilience.retry", op="read")
    obs.flush()

    ts = _load_trace_summary()
    spans, instants = ts.collect(ts.resolve_paths([str(tmp_path)]))
    assert spans["preprocess.run"]["count"] == 1
    assert spans["preprocess.scatter"]["total_us"] <= \
        spans["preprocess.run"]["total_us"]
    assert instants["resilience.retry"] == 1
    stages = ts.rollup_stages(spans)
    assert set(stages) == {"preprocess", "loader"}
    assert stages["preprocess"]["count"] == 2

    assert ts.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "per-stage wall time:" in out
    assert "preprocess" in out and "loader" in out
    assert "resilience.retry" in out


# ------------------------------------------ inertness: the real proof


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Demo corpus + wordpiece vocab shared by the inertness tests
    (same recipe as tests/test_loader.py, smaller)."""
    root = tmp_path_factory.mktemp("obs_corpus")
    source = root / "corpus" / "source"
    source.mkdir(parents=True)
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    g = np.random.Generator(np.random.Philox(key=[0, 23]))
    docs = []
    for d in range(48):
        sents = []
        for _ in range(int(g.integers(2, 8))):
            n = int(g.integers(4, 12))
            sents.append(" ".join(
                words[int(g.integers(0, len(words)))] for _ in range(n)
            ).capitalize() + ".")
        docs.append("doc-{} {}".format(d, " ".join(sents)))
    for shard in range(3):
        with open(source / "{}.txt".format(shard), "w") as f:
            for line in docs[shard::3]:
                f.write(line + "\n")
    from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer
    vocab = build_wordpiece_vocab([" ".join(words)] * 3,
                                  str(root / "vocab.txt"), vocab_size=300)
    return {"root": root, "corpus": str(root / "corpus"),
            "vocab": vocab, "tokenizer": get_tokenizer(vocab_file=vocab)}


def _run_pipeline(corpus, out_root, bin_size=None):
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess
    pre = os.path.join(str(out_root), "pre")
    bal = os.path.join(str(out_root), "bal")
    run_bert_preprocess(
        {"wiki": corpus["corpus"]}, pre, corpus["tokenizer"],
        config=BertPretrainConfig(max_seq_length=64, duplicate_factor=2,
                                  masking=True),
        num_blocks=4, sample_ratio=1.0, seed=0, bin_size=bin_size)
    balance_shards(pre, bal, 4)
    return pre, bal


@pytest.fixture(scope="module")
def binned_off(corpus, tmp_path_factory):
    """Telemetry-OFF binned pipeline run (module-shared reference)."""
    assert reg_mod.metrics_dir() is None
    return _run_pipeline(corpus, tmp_path_factory.mktemp("binned_off"),
                         bin_size=16)


@pytest.fixture(scope="module")
def unbinned_off(corpus, tmp_path_factory):
    """Telemetry-OFF unbinned pipeline run (module-shared reference)."""
    assert reg_mod.metrics_dir() is None
    return _run_pipeline(corpus, tmp_path_factory.mktemp("unbinned_off"),
                         bin_size=None)


def _parquet_bytes(d):
    return {
        name: open(os.path.join(d, name), "rb").read()
        for name in sorted(os.listdir(d)) if ".parquet" in name
    }


def _first_batches(path, vocab, n=6, base_seed=11):
    """First ``n`` batches of one epoch. The epoch is DRAINED fully —
    abandoning it mid-stream would leave the worker thread reading shards
    while the caller moves on (e.g. into faults.disarm()/summary()),
    which is exactly the nondeterminism these tests must not have."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        path, vocab_file=vocab, batch_size=16, num_workers=1,
        shuffle_buffer_size=64, shuffle_buffer_warmup_factor=4,
        base_seed=base_seed)
    out = []
    for i, batch in enumerate(loader):
        if i < n:
            out.append({k: np.asarray(v).copy() for k, v in batch.items()})
    return out


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for ba, bb in zip(a, b):
        assert sorted(ba) == sorted(bb)
        for k in ba:
            np.testing.assert_array_equal(ba[k], bb[k])


@pytest.mark.fault
def test_pipeline_bytes_identical_with_observability_on(corpus, binned_off,
                                                        tmp_path):
    """The inertness contract, end to end: preprocess -> balance -> load
    twice in the same environment, telemetry off vs on; shard files and
    the first N batches must be byte-identical (fresh same-env runs, not
    the pinned goldens), and the instrumented run must actually have
    recorded stage telemetry."""
    assert not obs.enabled()
    pre_off, bal_off = binned_off
    batches_off = _first_batches(bal_off, corpus["vocab"])

    obs.configure(dir=str(tmp_path / "metrics"))
    pre_on, bal_on = _run_pipeline(corpus, tmp_path / "on", bin_size=16)
    batches_on = _first_batches(bal_on, corpus["vocab"])
    snap = obs.registry().snapshot()
    trace = obs.flush()
    obs.disable()

    for d_off, d_on in ((pre_off, pre_on), (bal_off, bal_on)):
        off_bytes, on_bytes = _parquet_bytes(d_off), _parquet_bytes(d_on)
        assert sorted(off_bytes) == sorted(on_bytes)
        for name in off_bytes:
            assert off_bytes[name] == on_bytes[name], (
                "shard {} bytes differ with observability enabled".format(
                    name))
    _assert_batches_equal(batches_off, batches_on)

    # ...and the instrumented run was not silently dark:
    assert sum(snap["preprocess_samples_total"]["values"].values()) > 0
    assert sum(snap["loader_batches_total"]["values"].values()) > 0
    assert snap["loader_padding_efficiency"]["values"][""] > 0
    names = [json.loads(l)["name"] for l in open(trace)]
    for required in ("preprocess.run", "preprocess.scatter",
                     "preprocess.gather", "balance.run", "loader.epoch"):
        assert required in names, "missing span {}".format(required)


@pytest.mark.fault
def test_faulted_stream_identical_and_retries_counted(corpus, unbinned_off,
                                                      tmp_path, monkeypatch):
    """Acceptance: with LDDL_TPU_FAULTS armed at p=0.2 EIO the batch
    stream is byte-identical to an uninjected same-env run, and the
    end-of-run summary reports nonzero retry counters."""
    from lddl_tpu.resilience import faults
    _, bal = unbinned_off
    clean = _first_batches(bal, corpus["vocab"], n=8)

    # More attempts + tiny backoff: with p=0.2 per guarded op the chance
    # of exhausting 8 attempts on one op is 0.2^8 ~ 3e-6 (keeps the test
    # deterministic-in-practice without weakening the injected rate).
    monkeypatch.setenv("LDDL_TPU_RETRY_ATTEMPTS", "8")
    monkeypatch.setenv("LDDL_TPU_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("LDDL_TPU_RETRY_MAX_DELAY_S", "0.01")
    obs.configure(dir=str(tmp_path / "metrics"))
    # The injector's per-clause RNG mixes in the PID (so spawned workers
    # draw independent streams) — with only a handful of guarded ops in
    # this short load, one unlucky pid can draw zero injections (~0.8^k).
    # Identity must hold on EVERY attempt; for the counter assertions,
    # re-arm with fresh seeds until at least one fault actually fired.
    summary = None
    try:
        for seed in (7, 11, 23, 41, 59):
            faults.arm("*:eio:p=0.2:seed={}".format(seed))
            faulted = _first_batches(bal, corpus["vocab"], n=8)
            _assert_batches_equal(clean, faulted)
            summary = obs.summary()
            if summary["faults_injected"] > 0:
                break
    finally:
        faults.disarm()
    obs.disable()

    assert summary["faults_injected"] > 0
    assert summary["retries"] > 0
    assert summary["retries"] >= summary["faults_injected"]


def test_padding_efficiency_reproduces_bin_gap(corpus, binned_off,
                                               unbinned_off, tmp_path):
    """The paper's headline: binned loading wastes fewer padded slots.
    Measure both layouts with the new gauge on the demo corpus — the
    binned run must come out strictly more token-efficient."""

    def efficiency(bal, fixed):
        obs.registry().reset()
        obs.configure(dir=str(tmp_path / "metrics"))
        from lddl_tpu.loader import get_bert_pretrain_data_loader
        loader = get_bert_pretrain_data_loader(
            bal, vocab_file=corpus["vocab"], batch_size=16, num_workers=1,
            shuffle_buffer_size=64, shuffle_buffer_warmup_factor=4,
            base_seed=11, fixed_seq_lengths=fixed)
        for _ in loader:
            pass
        eff = obs.registry().gauge("loader_padding_efficiency").value()
        obs.disable()
        return eff

    eff_unbinned = efficiency(unbinned_off[1], [64])
    eff_binned = efficiency(binned_off[1], [16, 32, 48, 64])
    assert eff_binned > eff_unbinned, (
        "binned padding efficiency {} not better than unbinned {}".format(
            eff_binned, eff_unbinned))


# ------------------------------------------- disabled-mode cost guard


@pytest.mark.slow
def test_disabled_mode_overhead_near_zero():
    """No-op-mode micro-benchmark guard: a disabled instrumentation call
    must stay within a few dict-lookups of free, so the loader hot path
    can afford it unconditionally (acceptance: < 2% loader throughput
    regression with telemetry off)."""
    import timeit
    assert not obs.enabled()
    n = 200000
    t_inc = timeit.timeit(lambda: obs.inc("x_total"), number=n) / n
    t_span = timeit.timeit(lambda: obs.span("s"), number=n) / n
    t_enabled = timeit.timeit(obs.enabled, number=n) / n
    # Generous CI bound: each disabled call is one env lookup (~0.2us
    # measured); 5us catches an accidental O(real work) regression
    # without flaking on slow shared runners.
    assert t_inc < 5e-6, "disabled inc() costs {:.2e}s/call".format(t_inc)
    assert t_span < 5e-6, "disabled span() costs {:.2e}s/call".format(t_span)
    assert t_enabled < 5e-6
