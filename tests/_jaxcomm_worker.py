"""Subprocess body for test_jax_communicator_collectives: exercises
JaxCommunicator (rank/world/barrier/allreduce) over a real 2-process
jax.distributed group on the CPU backend."""

import sys

import numpy as np


def main():
    rank, world, coordinator = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=world, process_id=rank)

    from lddl_tpu.parallel.distributed import (JaxCommunicator,
                                               get_communicator)
    comm = get_communicator()
    assert isinstance(comm, JaxCommunicator), type(comm)
    assert comm.rank == rank and comm.world_size == world

    # int64 above 2^31: the payload must survive jax's int32
    # canonicalization (shipped as raw bytes, reduced on host).
    big = 3_000_000_000
    total = comm.allreduce_sum([big + rank, rank, 1])
    assert total.tolist() == [2 * big + sum(range(world)),
                              sum(range(world)), world], total
    mx = comm.allreduce_max([big + rank, rank])
    assert mx.tolist() == [big + world - 1, world - 1], mx
    comm.barrier()
    print("COLLECTIVES_OK")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
