"""Byte-reproducibility of the full preprocess pipeline.

Pins the exact shard bytes (tests/golden_spool.json, captured from the
round-2 per-(bucket, block) spool layout) so any spool/shuffle refactor
must preserve the seeded permutation bit-for-bit, and any vocab-trainer or
pipeline-math change shows up as an explicit golden regeneration in the
diff rather than a silent drift.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("spool_golden")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


@pytest.fixture(scope="module")
def goldens():
    with open(gs.GOLDEN_FILE) as f:
        return json.load(f)


class _SpoolCounter:
    """process_bucket stand-in that reports how many spool files exist at
    gather time (picklable for the spawn pool)."""

    def __init__(self, out_dir):
        self.out_dir = out_dir

    def __call__(self, texts, bucket):
        spool = os.path.join(self.out_dir, "_shuffle")
        count = sum(
            len([f for f in files if not f.startswith(".")])
            for _, _, files in os.walk(spool))  # "." = phase markers
        return {"spoolcount-{}".format(bucket): count}


@pytest.mark.parametrize("case,binned", [("unbinned", False),
                                         ("binned_masked", True)])
def test_output_matches_golden(fixture_dirs, goldens, case, binned):
    td, corpus, vocab = fixture_dirs
    out = os.path.join(td, "out_" + case)
    hashes = gs.run_case(corpus, vocab, out, binned)
    assert hashes == goldens[case]


def test_output_invariant_to_workers(fixture_dirs, goldens):
    """The process-pool fan-out must not change a single byte."""
    td, corpus, vocab = fixture_dirs
    out = os.path.join(td, "out_workers")
    hashes = gs.run_case(corpus, vocab, out, True, num_workers=3)
    assert hashes == goldens["binned_masked"]


def test_output_invariant_to_radix_width(fixture_dirs, goldens):
    """Forcing coarse groups (4 groups over 12 fine buckets, multi-bucket
    gather units) must not change a single byte: the per-bucket canonical
    order is layout-independent."""
    td, corpus, vocab = fixture_dirs
    out = os.path.join(td, "out_radix")
    hashes = gs.run_case(corpus, vocab, out, True, spool_groups=4)
    assert hashes == goldens["binned_masked"]


def test_output_invariant_to_radix_and_workers(fixture_dirs, goldens):
    td, corpus, vocab = fixture_dirs
    out = os.path.join(td, "out_radix_w")
    hashes = gs.run_case(corpus, vocab, out, True, spool_groups=4,
                         num_workers=3)
    assert hashes == goldens["binned_masked"]


def test_spool_file_count_bounded(fixture_dirs, tmp_path):
    """Spool files are O(groups x writers), never O(blocks^2): with 12
    blocks, 4 groups, 2 pool writers, at most 8 spool files exist at
    gather time (the old layout would create up to 144)."""
    from lddl_tpu.preprocess.runner import (_num_spool_groups,
                                            run_sharded_pipeline)
    # The default radix at the 12.5 GB north-star block count:
    assert _num_spool_groups(4096) == 512  # x16 workers = 8192 files
    assert _num_spool_groups(64) == 64

    td, corpus, vocab = fixture_dirs
    out = str(tmp_path / "out")
    written = run_sharded_pipeline({"wikipedia": corpus}, out,
                                   _SpoolCounter(out), num_blocks=12,
                                   sample_ratio=1.0, seed=7, spool_groups=4,
                                   num_workers=2)
    counts = [n for n in written.values()]
    assert counts and max(counts) <= 4 * 2, written


def test_vocab_builder_deterministic(tmp_path):
    v1 = gs.build_vocab(str(tmp_path))
    toks1 = open(v1).read().splitlines()
    os.remove(v1)
    v2 = gs.build_vocab(str(tmp_path))
    assert toks1 == open(v2).read().splitlines()


def test_vocab_builder_isolates_symbol_punctuation(tmp_path):
    """Chars BERT pre-tokenizers isolate (ASCII symbol ranges, not just
    category P) must enter the alphabet standalone: '2+2' may never bury
    '+' as a continuation-only symbol."""
    from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer
    path = build_wordpiece_vocab(["the sum 2+2 equals 4 $5 a=b"] * 3,
                                 str(tmp_path / "v.txt"), vocab_size=100)
    toks = set(open(path).read().splitlines())
    assert {"+", "$", "="} <= toks
    tok = get_tokenizer(vocab_file=path)
    assert "[UNK]" not in tok.tokenize("2+2 $5 a=b")
