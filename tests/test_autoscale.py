"""The telemetry-driven autoscaler (lddl_tpu/observability/autoscale.py):
decision policy over synthetic aggregate reports, spawn/retire plumbing,
journaling into the fleet event log, and the clock-free guarantee the
analyzer enforces (autoscale.py is deliberately NOT wall-clock
allowlisted)."""

import os

import pytest

from lddl_tpu import observability as obs
from lddl_tpu.observability import fleet, tracing
from lddl_tpu.observability.autoscale import Autoscaler, backlog_of


def _report(backlog=0, wedged=False, pending=None, extra_hosts=()):
    hosts = {"h0": {"gauges": {"ingest_backlog_docs": backlog}}}
    for name, b in extra_hosts:
        hosts[name] = {"gauges": {"ingest_backlog_docs": b}}
    return {"hosts": hosts, "health": {"wedged": wedged},
            "pending_work": pending}


class _Fleet:
    """Recording spawn/retire callables; handles are increasing ints."""

    def __init__(self):
        self.spawned, self.retired = [], []

    def spawn(self):
        h = len(self.spawned)
        self.spawned.append(h)
        return h

    def retire(self, h):
        self.retired.append(h)


@pytest.fixture
def clean_telemetry(monkeypatch):
    for name in ("LDDL_TPU_METRICS_DIR", "LDDL_TPU_FLEET_DIR",
                 "LDDL_TPU_FLEET_HOLDER", "LDDL_TPU_FLEET_TTL"):
        monkeypatch.delenv(name, raising=False)
    obs.registry().reset()
    tracing._reset_for_tests()
    fleet._reset_for_tests()
    yield
    obs.registry().reset()
    tracing._reset_for_tests()
    fleet._reset_for_tests()


def _scaler(fl, **kw):
    kw.setdefault("backlog_slo_docs", 100)
    kw.setdefault("max_helpers", 2)
    kw.setdefault("drain_rounds", 2)
    return Autoscaler("/nowhere", fl.spawn, fl.retire, **kw)


# ------------------------------------------------------------------ policy


def test_backlog_of_takes_fleet_max():
    rep = _report(backlog=5, extra_hosts=(("h1", 40), ("h2", 7)))
    assert backlog_of(rep) == 40
    assert backlog_of({"hosts": {"h0": {"gauges": {}}}}) == 0
    assert backlog_of({}) == 0


def test_scale_up_on_backlog_until_ceiling(clean_telemetry):
    fl = _Fleet()
    a = _scaler(fl)
    assert a.observe(_report(backlog=500))["decision"] == "scale_up"
    assert a.observe(_report(backlog=500))["decision"] == "scale_up"
    # Ceiling: still hot, but max_helpers run already.
    assert a.observe(_report(backlog=500))["decision"] is None
    assert a.helper_count == 2 and fl.spawned == [0, 1]


def test_scale_up_on_wedge_without_backlog(clean_telemetry):
    fl = _Fleet()
    a = _scaler(fl)
    ob = a.observe(_report(backlog=0, wedged=True))
    assert ob["decision"] == "scale_up"
    assert a.decisions[-1] == ("scale_up", "wedged")


def test_scale_down_needs_consecutive_calm_rounds(clean_telemetry):
    fl = _Fleet()
    a = _scaler(fl, drain_rounds=3)
    a.observe(_report(backlog=500))
    assert a.helper_count == 1
    # calm, calm, NOT calm (pending work) -> the calm streak resets.
    assert a.observe(_report())["decision"] is None
    assert a.observe(_report())["decision"] is None
    assert a.observe(_report(pending="delta preprocess"))["decision"] is None
    assert a.observe(_report())["decision"] is None
    assert a.observe(_report())["decision"] is None
    assert a.observe(_report())["decision"] == "scale_down"
    assert a.helper_count == 0 and fl.retired == [0]


def test_scale_down_floor_and_lifo_retirement(clean_telemetry):
    fl = _Fleet()
    a = _scaler(fl, min_helpers=1, drain_rounds=1)
    a.observe(_report(backlog=500))
    a.observe(_report(backlog=500))
    assert a.helper_count == 2
    assert a.observe(_report())["decision"] == "scale_down"
    assert fl.retired == [1]  # most recent helper leaves first
    # Floor: min_helpers stays running however calm it gets.
    assert a.observe(_report())["decision"] is None
    assert a.helper_count == 1


def test_shutdown_retires_everything(clean_telemetry):
    fl = _Fleet()
    a = _scaler(fl)
    a.observe(_report(backlog=500))
    a.observe(_report(backlog=500))
    a.shutdown()
    assert a.helper_count == 0
    assert fl.retired == [1, 0]
    assert [d for d in a.decisions if d[0] == "scale_down"] == \
        [("scale_down", "service shutdown")] * 2


def test_constructor_validation():
    fl = _Fleet()
    with pytest.raises(ValueError, match="backlog_slo_docs"):
        Autoscaler("/x", fl.spawn, fl.retire, backlog_slo_docs=0,
                   max_helpers=1)
    with pytest.raises(ValueError, match="min_helpers"):
        Autoscaler("/x", fl.spawn, fl.retire, backlog_slo_docs=1,
                   max_helpers=1, min_helpers=2)


# ------------------------------------------------------------- journaling


def test_decisions_are_journaled_as_fleet_events(clean_telemetry, tmp_path):
    root = str(tmp_path)
    spool = fleet.configure(root, holder_id="ctrl", ttl=5, interval=60)
    fl = _Fleet()
    a = _scaler(fl, drain_rounds=1)
    a.observe(_report(backlog=500))
    a.observe(_report())
    fleet.flush_events()
    events, torn = fleet.read_jsonl(os.path.join(
        spool, "events-pid{}.jsonl".format(os.getpid())))
    assert torn == 0
    kinds = [ev["kind"] for ev in events]
    assert "autoscale.scale_up" in kinds and "autoscale.scale_down" in kinds
    up = events[kinds.index("autoscale.scale_up")]["args"]
    assert up["backlog_docs"] == 500 and up["slo_docs"] == 100
    c = obs.registry().counter("autoscale_decisions_total")
    assert c.value(action="scale_up") == 1
    assert c.value(action="scale_down") == 1


def test_step_reads_real_aggregate(clean_telemetry, tmp_path):
    """End-to-end through fleet.aggregate: a published backlog gauge in a
    spool drives a real scale_up."""
    root = str(tmp_path)
    fleet.configure(root, holder_id="svc", ttl=5, interval=60)
    obs.set_gauge("ingest_backlog_docs", 900)
    fleet.heartbeat()
    fl = _Fleet()
    a = Autoscaler(root, fl.spawn, fl.retire, backlog_slo_docs=100,
                   max_helpers=2, drain_rounds=2)
    ob = a.step()
    assert ob["backlog_docs"] == 900
    assert ob["decision"] == "scale_up"
    assert fl.spawned == [0]


# ----------------------------------------------------- clock-free contract


def test_autoscale_not_wall_clock_allowlisted():
    """The analyzer must COVER autoscale.py: scale decisions derive from
    the aggregate report, never from a clock read of their own. A glob
    allow over observability/* would silently exempt it."""
    from lddl_tpu.analysis.flow_rules import WallClockFlowRule
    from lddl_tpu.analysis.rules import WallClockRule
    for allow in (WallClockRule.allow, WallClockFlowRule.allow):
        assert "lddl_tpu/observability/*" not in allow
        assert not any("autoscale" in pat for pat in allow)
