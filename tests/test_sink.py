"""Async durable sink (preprocess/sink.py): byte identity, fault and
chaos coverage for the double-buffered shard-writer thread.

The writer is pure deferred execution of the existing resilience.io
publish path, so every pin here is an equality: serial (depth 0) and
async (any depth) runs must produce byte-identical shard trees and
manifests across binned / packed / BART / schema-v1-golden configs;
faults injected INSIDE the writer thread must fail the owning unit
loudly before it is journaled; and a SIGKILL mid-deferred-publish must
resume to byte identity with a clean run.
"""

import hashlib
import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu.preprocess import sink  # noqa: E402
from lddl_tpu.resilience import faults  # noqa: E402


@pytest.fixture(scope="module")
def fixture_dirs(tmp_path_factory):
    td = tmp_path_factory.mktemp("sink")
    corpus = gs.build_corpus(str(td / "corpus"))
    vocab = gs.build_vocab(str(td))
    return str(td), corpus, vocab


def _tree_digest(out_dir):
    """{relative name: sha256} over every published file (shards, txt,
    .manifest.json, .num_samples.json) — manifests are part of the
    byte-identity contract."""
    digests = {}
    for root, dirs, files in os.walk(out_dir):
        dirs.sort()
        for name in sorted(files):
            path = os.path.join(root, name)
            rel = os.path.relpath(path, out_dir)
            with open(path, "rb") as f:
                digests[rel] = hashlib.sha256(f.read()).hexdigest()
    return digests


def _run_bert(corpus, vocab, out, depth=None, monkeypatch=None, **kw):
    from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                     run_bert_preprocess)
    if depth is not None:
        monkeypatch.setenv("LDDL_TPU_SINK_DEPTH", str(depth))
    try:
        cfg_kw = kw.pop("config_kw", {})
        cfg = BertPretrainConfig(max_seq_length=32, masking=True, **cfg_kw)
        run_bert_preprocess(
            {"wikipedia": corpus}, out, get_tokenizer(vocab_file=vocab),
            config=cfg, num_blocks=8, sample_ratio=0.9, seed=4242,
            progress_interval=0.0, **kw)
    finally:
        if depth is not None:
            monkeypatch.delenv("LDDL_TPU_SINK_DEPTH", raising=False)
    return _tree_digest(out)


def test_async_vs_serial_byte_identity_binned(fixture_dirs, tmp_path,
                                              monkeypatch):
    """Binned masked schema-v2 shards + manifest: depth 0 (inline), the
    default depth 2, and a deep queue are all byte-identical."""
    _, corpus, vocab = fixture_dirs
    serial = _run_bert(corpus, vocab, str(tmp_path / "serial"), depth=0,
                       monkeypatch=monkeypatch, bin_size=8)
    async2 = _run_bert(corpus, vocab, str(tmp_path / "async2"), depth=2,
                       monkeypatch=monkeypatch, bin_size=8)
    async8 = _run_bert(corpus, vocab, str(tmp_path / "async8"), depth=8,
                       monkeypatch=monkeypatch, bin_size=8)
    assert serial == async2 == async8
    assert any(n.endswith(".manifest.json") for n in serial)
    assert any("parquet" in n for n in serial)


def test_async_vs_serial_byte_identity_packed(fixture_dirs, tmp_path,
                                              monkeypatch):
    """The offline-packed sink (FFD inside the deferred closure) is
    byte-identical serial vs async."""
    _, corpus, vocab = fixture_dirs
    kw = dict(pack_seq_length=64, pack_max_per_row=4)
    serial = _run_bert(corpus, vocab, str(tmp_path / "serial"), depth=0,
                       monkeypatch=monkeypatch, **kw)
    async2 = _run_bert(corpus, vocab, str(tmp_path / "async2"), depth=2,
                       monkeypatch=monkeypatch, **kw)
    assert serial == async2
    assert any("parquet" in n for n in serial)


def test_async_vs_serial_byte_identity_bart(fixture_dirs, tmp_path,
                                            monkeypatch):
    """BART (schema-v2: tokenizer-fed id columns) serial vs async."""
    from lddl_tpu.preprocess import get_tokenizer
    from lddl_tpu.preprocess.bart import (BartPretrainConfig,
                                          run_bart_preprocess)
    _, corpus, vocab = fixture_dirs

    def run(out, depth):
        monkeypatch.setenv("LDDL_TPU_SINK_DEPTH", str(depth))
        try:
            run_bart_preprocess(
                {"wikipedia": corpus}, out,
                config=BartPretrainConfig(target_seq_length=32),
                num_blocks=8, sample_ratio=0.9, seed=4242,
                progress_interval=0.0,
                tokenizer=get_tokenizer(vocab_file=vocab))
        finally:
            monkeypatch.delenv("LDDL_TPU_SINK_DEPTH", raising=False)
        return _tree_digest(out)

    assert run(str(tmp_path / "serial"), 0) == run(str(tmp_path / "a2"), 2)


def test_async_matches_schema_v1_golden(fixture_dirs, tmp_path,
                                        monkeypatch):
    """The pinned v1 golden-spool bytes survive the async sink — and the
    v1 parquet layout itself is untouched by the v2 layout change."""
    _, corpus, vocab = fixture_dirs
    with open(gs.GOLDEN_FILE) as f:
        goldens = json.load(f)
    monkeypatch.setenv("LDDL_TPU_SINK_DEPTH", "2")
    got_async = gs.run_case(corpus, vocab, str(tmp_path / "async"),
                            binned=True)
    monkeypatch.setenv("LDDL_TPU_SINK_DEPTH", "0")
    got_serial = gs.run_case(corpus, vocab, str(tmp_path / "serial"),
                             binned=True)
    assert got_async == got_serial == goldens["binned_masked"]


def test_writer_thread_eio_fails_unit_loudly(fixture_dirs, tmp_path,
                                             monkeypatch):
    """An eio at the sink-write site (fires ON the writer thread) fails
    the owning unit: the run raises naming failed units, the failed
    unit is NOT journaled, and a resume completes to byte identity."""
    _, corpus, vocab = fixture_dirs
    clean = _run_bert(corpus, vocab, str(tmp_path / "clean"),
                      bin_size=8)
    out = str(tmp_path / "out")
    faults.arm("sink-write:eio:nth=2")
    try:
        with pytest.raises(RuntimeError, match="preprocess failed"):
            _run_bert(corpus, vocab, out, bin_size=8)
    finally:
        faults.disarm()
    ledger = os.path.join(out, "_done")
    records = [n for n in sorted(os.listdir(ledger))
               if n.startswith("group-")]
    assert 0 < len(records) < 8  # healthy units journaled, failed one not
    got = _run_bert(corpus, vocab, out, bin_size=8, resume=True)
    assert got == clean
    assert not [n for n in got if ".tmp." in n]  # debris swept


def test_writer_thread_io_eio_exhaustion_fails_unit(fixture_dirs, tmp_path,
                                                    monkeypatch):
    """eio injected at the resilience.io open site of the deferred
    write_table_atomic (every attempt, so retries exhaust) surfaces as a
    loud unit failure at the producer — never a silent drop."""
    _, corpus, vocab = fixture_dirs
    clean = _run_bert(corpus, vocab, str(tmp_path / "clean"), bin_size=8)
    out = str(tmp_path / "out")
    monkeypatch.setenv("LDDL_TPU_RETRY_ATTEMPTS", "2")
    monkeypatch.setenv("LDDL_TPU_RETRY_BASE_DELAY_S", "0.01")
    faults.arm("open:eio:p=1:path=part.3.")
    try:
        with pytest.raises(RuntimeError, match="preprocess failed"):
            _run_bert(corpus, vocab, out, bin_size=8)
    finally:
        faults.disarm()
    monkeypatch.delenv("LDDL_TPU_RETRY_ATTEMPTS")
    monkeypatch.delenv("LDDL_TPU_RETRY_BASE_DELAY_S")
    got = _run_bert(corpus, vocab, out, bin_size=8, resume=True)
    assert got == clean
    assert not [n for n in got if ".tmp." in n]


_KILL_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {tests!r})
import golden_spool as gs
from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                 run_bert_preprocess)
run_bert_preprocess(
    {{"wikipedia": {corpus!r}}}, {out!r},
    get_tokenizer(vocab_file={vocab!r}),
    config=BertPretrainConfig(max_seq_length=32, masking=True),
    num_blocks=8, sample_ratio=0.9, seed=4242, bin_size=8,
    progress_interval=0.0, resume={resume})
"""


def test_sigkill_mid_deferred_publish_resumes_to_byte_identity(
        fixture_dirs, tmp_path, monkeypatch):
    """THE chaos acceptance pin: a SIGKILL landing on the writer thread
    mid-deferred-publish (after several units are already journaled)
    kills the process uncleanly; a resume converges to a tree
    byte-identical to an uninterrupted run, with no ``*.tmp.*`` debris
    under any published name."""
    _, corpus, vocab = fixture_dirs
    clean = _run_bert(corpus, vocab, str(tmp_path / "clean"), bin_size=8)
    out = str(tmp_path / "out")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "LDDL_TPU_FAULTS": "sink-write:kill:nth=5:flag={}".format(
            tmp_path / "killed.flag"),
    })
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT.format(
            repo=repo, tests=os.path.dirname(os.path.abspath(__file__)),
            corpus=corpus, out=out, vocab=vocab, resume="False")],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == -9, proc.stderr  # genuinely SIGKILLed
    assert os.path.exists(str(tmp_path / "killed.flag"))
    # Some units journaled before the kill, not all (mid-run death).
    done = os.path.join(out, "_done")
    journaled = [n for n in sorted(os.listdir(done))
                 if n.startswith("group-")] if os.path.isdir(done) else []
    assert len(journaled) < 8
    proc = subprocess.run(
        [sys.executable, "-c", _KILL_SCRIPT.format(
            repo=repo, tests=os.path.dirname(os.path.abspath(__file__)),
            corpus=corpus, out=out, vocab=vocab, resume="True")],
        env={k: v for k, v in env.items() if k != "LDDL_TPU_FAULTS"},
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    got = _tree_digest(out)
    assert got == clean
    assert not [n for n in got if ".tmp." in n]


def test_shard_writer_unit_isolation_and_error_at_collect(tmp_path):
    """ShardWriter semantics: a closure that raises fails ONLY its unit
    (remaining closures of that unit are skipped), later units complete,
    and the failure surfaces at collect with the original exception."""
    w = sink.ShardWriter(depth=2)
    ran = []
    try:
        w.submit("u1", lambda: ran.append("a") or {"a": 1})
        w.submit("u1", lambda: (_ for _ in ()).throw(OSError(5, "boom")))
        w.submit("u1", lambda: ran.append("skipped") or {"c": 1})
        w.end_unit("u1")
        w.submit("u2", lambda: ran.append("b") or {"b": 2})
        w.end_unit("u2")
        done = {u: (written, exc) for u, written, exc in w.drain()}
    finally:
        w.close()
    assert ran == ["a", "b"]  # post-failure closure of u1 skipped
    written1, exc1 = done["u1"]
    assert isinstance(exc1, OSError) and "boom" in str(exc1)
    written2, exc2 = done["u2"]
    assert exc2 is None and written2 == {"b": 2}


def test_shard_writer_fence_rechecked_before_publish(tmp_path):
    """The fence is re-checked ON the writer thread immediately before
    each deferred publish: a fence that turns False after enqueue stops
    the publish (LeaseLost), so a stolen unit cannot write late bytes."""
    from lddl_tpu.resilience.leases import LeaseLost
    state = {"held": True}
    w = sink.ShardWriter(depth=2)
    wrote = []
    try:
        state["held"] = False  # stolen between compute and publish
        w.submit("u", lambda: wrote.append(1) or {"p": 1},
                 fence=lambda: state["held"])
        w.end_unit("u")
        (unit, written, exc), = w.drain()
    finally:
        w.close()
    assert wrote == [] and written == {}
    assert isinstance(exc, LeaseLost)


def test_shard_writer_unmatched_end_unit_fails_loudly_no_deadlock():
    """A duplicate/unmatched end_unit is a caller bug, but it must
    surface as a completed-with-error unit — never kill the writer
    thread (a dead thread would deadlock drain()/close() on
    queue.join() with no diagnostic)."""
    w = sink.ShardWriter(depth=2)
    try:
        w.submit("u", lambda: {"a": 1})
        w.end_unit("u")
        w.end_unit("u")  # unmatched: no open unit anymore
        w.submit("v", lambda: {"b": 2})
        w.end_unit("v")
        done = w.drain()  # must not hang
    finally:
        w.close()  # must not hang
    assert [(u, written) for u, written, exc in done if exc is None] == \
        [("u", {"a": 1}), ("v", {"b": 2})]
    # The unmatched end surfaced as its own loud failure entry.
    [bad] = [(u, exc) for u, written, exc in done if exc is not None]
    assert bad[0] == "u" and "unmatched end_unit" in str(bad[1])


def test_sink_depth_knob_and_inline_mode(monkeypatch):
    """LDDL_TPU_SINK_DEPTH=0 disables the thread (closures run inline on
    the producer); junk values fall back to the default depth."""
    monkeypatch.setenv("LDDL_TPU_SINK_DEPTH", "0")
    w = sink.ShardWriter()
    assert w._thread is None
    w.submit("u", lambda: {"x": 1})
    w.end_unit("u")
    (unit, written, exc), = w.drain()
    assert written == {"x": 1} and exc is None
    w.close()
    monkeypatch.setenv("LDDL_TPU_SINK_DEPTH", "junk")
    assert sink.sink_depth() == sink.DEFAULT_DEPTH
    monkeypatch.delenv("LDDL_TPU_SINK_DEPTH")
    assert sink.sink_depth() == sink.DEFAULT_DEPTH


def test_sink_stats_accumulate(fixture_dirs, tmp_path, monkeypatch):
    """The process-local overlap stats (profiler feed) grow with a run:
    tasks == deferred publishes, units == completed units."""
    _, corpus, vocab = fixture_dirs
    before = sink.stats_snapshot()
    _run_bert(corpus, vocab, str(tmp_path / "out"), bin_size=8)
    after = sink.stats_snapshot()
    assert after["tasks"] >= before["tasks"] + 8
    assert after["units"] >= before["units"] + 8
    assert after["write_s"] > before["write_s"]
