"""Loader: shuffle buffer, determinism, dp-group sharding, binning sync,
dynamic masking, mesh placement."""

import os

import numpy as np
import pytest

from lddl_tpu.loader import (
    ShuffleBuffer,
    dp_info_of_process,
    get_bert_pretrain_data_loader,
    process_dp_info,
    to_device_batch,
)
from lddl_tpu.utils import rng as lrng
from lddl_tpu.utils.types import File


@pytest.fixture(scope="module")
def pipeline(tmp_path_factory):
    """corpus -> vocab -> preprocess (unbinned dynamic + binned static)
    -> balanced shards, shared by all loader tests."""
    import numpy as np
    root = tmp_path_factory.mktemp("pipeline")
    source = root / "corpus" / "source"
    source.mkdir(parents=True)
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    g = np.random.Generator(np.random.Philox(key=[0, 11]))
    docs = []
    for d in range(60):
        sents = []
        for _ in range(int(g.integers(2, 8))):
            n = int(g.integers(4, 12))
            sents.append(" ".join(
                words[int(g.integers(0, len(words)))] for _ in range(n)
            ).capitalize() + ".")
        docs.append("doc-{} {}".format(d, " ".join(sents)))
    for shard in range(3):
        with open(source / "{}.txt".format(shard), "w") as f:
            for line in docs[shard::3]:
                f.write(line + "\n")

    from lddl_tpu.preprocess import (BertPretrainConfig, build_wordpiece_vocab,
                                     get_tokenizer, run_bert_preprocess)
    from lddl_tpu.balance import balance_shards
    vocab = build_wordpiece_vocab([" ".join(words)] * 3,
                                  str(root / "vocab.txt"), vocab_size=300)
    tok = get_tokenizer(vocab_file=vocab)

    run_bert_preprocess(
        {"wiki": str(root / "corpus")}, str(root / "pre_dyn"), tok,
        config=BertPretrainConfig(max_seq_length=64, duplicate_factor=2),
        num_blocks=4, sample_ratio=1.0, seed=0)
    balance_shards(str(root / "pre_dyn"), str(root / "bal_dyn"), 4)

    run_bert_preprocess(
        {"wiki": str(root / "corpus")}, str(root / "pre_bin"), tok,
        config=BertPretrainConfig(max_seq_length=64, duplicate_factor=2,
                                  masking=True),
        num_blocks=4, sample_ratio=1.0, seed=0, bin_size=16)
    balance_shards(str(root / "pre_bin"), str(root / "bal_bin"), 4)

    return {"root": root, "vocab": vocab, "tokenizer": tok,
            "dyn": str(root / "bal_dyn"), "bin": str(root / "bal_bin")}


def test_shuffle_buffer_yields_all(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    path = str(tmp_path / "f.parquet")
    pq.write_table(pa.table({"A": [str(i) for i in range(100)]}), path)

    def decode(b):
        for v in b.column("A").to_pylist():
            yield v

    buf = ShuffleBuffer([File(path, 100)], 100, decode, size=16,
                        warmup_factor=2, g=lrng.sample_rng(0, 1))
    out = list(buf)
    assert sorted(out, key=int) == [str(i) for i in range(100)]
    assert out != [str(i) for i in range(100)]  # actually shuffled
    # Deterministic under the same stream.
    buf2 = ShuffleBuffer([File(path, 100)], 100, decode, size=16,
                         warmup_factor=2, g=lrng.sample_rng(0, 1))
    assert list(buf2) == out
    # Truncation respected.
    buf3 = ShuffleBuffer([File(path, 100)], 99, decode, size=16,
                         warmup_factor=2, g=lrng.sample_rng(0, 1))
    assert len(list(buf3)) == 99


# Raw-sample identity (v1 token strings / v2 id arrays) — one definition,
# shared with the multiprocess worker.
from _loader_worker import sample_key as _sample_key  # noqa: E402


def _loader(pipeline, kind, **kw):
    defaults = dict(
        batch_size=16,
        num_workers=1,
        shuffle_buffer_size=64,
        shuffle_buffer_warmup_factor=4,
        vocab_file=pipeline["vocab"],
        base_seed=7,
    )
    defaults.update(kw)
    return get_bert_pretrain_data_loader(pipeline[kind], **defaults)


def test_unbinned_loader_shapes(pipeline):
    loader = _loader(pipeline, "dyn")
    batches = list(loader)
    assert len(batches) == len(loader)
    total = sum(len(b["input_ids"]) for b in batches)
    assert total == len(loader.dataset)
    for b in batches:
        n, L = b["input_ids"].shape
        assert L % 8 == 0  # sequence_length_alignment
        assert b["token_type_ids"].shape == (n, L)
        assert b["attention_mask"].shape == (n, L)
        assert b["labels"].shape == (n, L)
        assert b["next_sentence_labels"].shape == (n,)
        # attention_mask marks a prefix; padding is zero.
        assert ((b["input_ids"] != 0) <= (b["attention_mask"] == 1)).all()
        # Dynamic masking produced some labels.
    assert any((b["labels"] != -1).any() for b in batches)


def test_epoch_determinism_and_resume(pipeline):
    l1 = _loader(pipeline, "dyn")
    e0 = [b["input_ids"] for b in l1]
    e1 = [b["input_ids"] for b in l1]
    # Same loader, consecutive epochs differ.
    assert not all(
        a.shape == b.shape and (a == b).all() for a, b in zip(e0, e1))
    # Fresh loader reproduces epoch 0 exactly.
    l2 = _loader(pipeline, "dyn")
    f0 = [b["input_ids"] for b in l2]
    assert len(e0) == len(f0)
    for a, b in zip(e0, f0):
        np.testing.assert_array_equal(a, b)
    # Resume: start_epoch=1 reproduces the second epoch.
    l3 = _loader(pipeline, "dyn", start_epoch=1)
    g1 = [b["input_ids"] for b in l3]
    for a, b in zip(e1, g1):
        np.testing.assert_array_equal(a, b)


def test_dp_group_sharding(pipeline):
    # TP/PP peers (same dp_rank) -> identical batches.
    a = _loader(pipeline, "dyn", dp_rank=0, num_dp_groups=2)
    b = _loader(pipeline, "dyn", dp_rank=0, num_dp_groups=2)
    for ba, bb in zip(a, b):
        np.testing.assert_array_equal(ba["input_ids"], bb["input_ids"])
        np.testing.assert_array_equal(ba["labels"], bb["labels"])
    # The two dp groups exactly partition the epoch: their sample multisets
    # union to the full loader's multiset (content can repeat due to
    # duplicate_factor, so compare multisets, not sets).
    full = _loader(pipeline, "dyn", return_raw_samples=True)
    a = _loader(pipeline, "dyn", dp_rank=0, num_dp_groups=2,
                return_raw_samples=True)
    c = _loader(pipeline, "dyn", dp_rank=1, num_dp_groups=2,
                return_raw_samples=True)
    sa = [_sample_key(s) for batch in a for s in batch]
    sc = [_sample_key(s) for batch in c for s in batch]
    sf = [_sample_key(s) for batch in full for s in batch]
    assert sa and sc
    assert len(sa) == len(sc) == len(sf) // 2
    # Which samples get dropped at the truncation boundary may differ
    # between layouts: with balanced counts base/base+1, up to
    # (num_files - 1) extras exist, and each side of the comparison can
    # drop a different one -> at most 2*(num_files-1) mismatched entries.
    import collections
    ca = collections.Counter(sa + sc)
    cf = collections.Counter(sf)
    mismatch = sum(((ca - cf) + (cf - ca)).values())
    assert mismatch <= 2 * (4 - 1)


def test_binned_loader_sync_and_shapes(pipeline):
    fixed = [16, 32, 48, 64]
    l1 = _loader(pipeline, "bin", fixed_seq_lengths=fixed)
    l2 = _loader(pipeline, "bin", fixed_seq_lengths=fixed)
    shapes = set()
    picks1, picks2 = [], []
    for b1, b2 in zip(l1, l2):
        # Identical bin choice and content on a simulated second rank.
        np.testing.assert_array_equal(b1["input_ids"], b2["input_ids"])
        L = b1["input_ids"].shape[1]
        shapes.add(L)
        picks1.append(L)
        lens = b1["attention_mask"].sum(axis=1)
        # Every sample in the batch fits its bin's padded shape: static
        # shapes bounded by the bin count.
        assert (lens <= L).all()
        assert L in fixed
    assert len(shapes) >= 2
    # Static masking path: labels decoded from stored positions.
    assert any((b["labels"] != -1).any() for b in _loader(
        pipeline, "bin", fixed_seq_lengths=fixed))


def test_binned_loader_multi_worker_determinism(pipeline):
    l1 = _loader(pipeline, "bin", num_workers=2)
    l2 = _loader(pipeline, "bin", num_workers=2)
    n = 0
    for b1, b2 in zip(l1, l2):
        np.testing.assert_array_equal(b1["input_ids"], b2["input_ids"])
        n += 1
    assert n == len(l1)


def test_process_workers_match_thread_workers(pipeline):
    """worker_mode='process' must reproduce the thread loader bit-for-bit:
    same batches, same order, same dynamic masks (the worker stream and
    collate RNG are pure functions of (seed, epoch, dp, worker))."""
    for kind in ("dyn", "bin"):
        lt = _loader(pipeline, kind, num_workers=2)
        lp = _loader(pipeline, kind, num_workers=2, worker_mode="process")
        bt, bp = list(lt), list(lp)
        assert len(bt) == len(bp)
        for x, y in zip(bt, bp):
            assert sorted(x) == sorted(y)
            for key in x:
                import numpy as np
                np.testing.assert_array_equal(x[key], y[key], err_msg=key)


def test_process_mode_falls_back_on_single_core(pipeline, monkeypatch):
    """On a single-core host, worker_mode='process' is a measured
    pathology (LOADER_BENCH.json w4proc rows); the loader must fall back
    to threads with a warning instead of running it — unless the
    explicit force env (used by the process-mode correctness tests above)
    is set."""
    import os
    import warnings
    from lddl_tpu.loader.dataloader import DataLoader

    monkeypatch.delenv("LDDL_TPU_FORCE_PROCESS_WORKERS", raising=False)
    # The mode check sizes itself from the affinity-aware count
    # (utils.cpus.usable_cpu_count), so patch both probes.
    monkeypatch.setattr(os, "cpu_count", lambda: 1)
    monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0},
                        raising=False)
    lt = _loader(pipeline, "dyn", num_workers=2)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lp = _loader(pipeline, "dyn", num_workers=2, worker_mode="process")
    assert any("falling back to thread" in str(w.message) for w in caught)
    # Fallback means the THREAD path actually runs (no process pool) and
    # batches are unchanged (stream purity).
    assert lp._worker_mode == "thread"
    for x, y in zip(list(lt), list(lp)):
        for key in x:
            np.testing.assert_array_equal(x[key], y[key], err_msg=key)

    # >= 2 cores: process mode sticks.
    monkeypatch.setattr(os, "cpu_count", lambda: 8)
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(range(8)), raising=False)
    assert DataLoader._check_process_mode(None) == "process"


def test_process_worker_failure_surfaces(pipeline, tmp_path):
    """A dying worker process raises in the consumer, not a hang."""
    import pytest
    loader = _loader(pipeline, "dyn", num_workers=1, worker_mode="process")
    # Poison the dataset: point one file at a non-parquet path.
    loader.dataset._files[0] = str(tmp_path / "missing.parquet")
    with pytest.raises(Exception):
        list(loader)


def test_process_workers_persist_across_epochs(pipeline):
    """Process workers are spawned ONCE and reused epoch to epoch
    (reference: persistent_workers=True), with per-epoch streams still
    correct (epoch 0 of a fresh loader == epoch 0 of another)."""
    l1 = _loader(pipeline, "dyn", num_workers=2, worker_mode="process")
    e0 = [b["input_ids"] for b in l1]
    pids_after_e0 = sorted(p.pid for p in l1._procs)
    e1 = [b["input_ids"] for b in l1]
    assert sorted(p.pid for p in l1._procs) == pids_after_e0  # reused
    assert not all(a.shape == b.shape and (a == b).all()
                   for a, b in zip(e0, e1))  # epochs differ
    l2 = _loader(pipeline, "dyn", num_workers=2, worker_mode="process")
    f0 = [b["input_ids"] for b in l2]
    for a, b in zip(e0, f0):
        np.testing.assert_array_equal(a, b)
    l1.shutdown_workers()
    l2.shutdown_workers()
    assert l1._procs is None


def test_process_pool_abandoned_iterator_does_not_leak_epochs(pipeline):
    """A partially-consumed iterator kept alive must not leak its epoch's
    leftover batches into the next epoch (the pool is torn down and
    respawned), and its later GC must not kill the successor pool."""
    loader = _loader(pipeline, "dyn", num_workers=2, worker_mode="process")
    it = iter(loader)
    first = next(it)                       # epoch 0, abandoned mid-stream
    e1 = [b["input_ids"] for b in loader]  # epoch 1, clean
    assert e1                              # full epoch served
    total = sum(len(x) for x in e1)
    assert total == len(loader.dataset)
    del it                                 # GC the stale iterator
    import gc
    gc.collect()
    e2 = [b["input_ids"] for b in loader]  # epoch 2 still works
    assert sum(len(x) for x in e2) == len(loader.dataset)
    loader.shutdown_workers()


def _killing_decode(b):
    """decode_record_batch that SIGKILLs its own worker process mid-file
    (picklable for the spawn worker)."""
    import os
    import signal
    yield "first"
    os.kill(os.getpid(), signal.SIGKILL)


def test_process_worker_sigkill_raises_not_hangs(pipeline, tmp_path):
    """A worker killed without enqueueing anything (OOM killer, native
    segfault) must raise in the consumer within the liveness timeout."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    import pytest
    from lddl_tpu.loader import DataLoader, ParquetDataset

    path = str(tmp_path / "shard-0.parquet")
    pq.write_table(pa.table({"A": [str(i) for i in range(64)]}), path)
    ds = ParquetDataset([path], base_seed=0, num_workers=1,
                        shuffle_buffer_size=8, shuffle_buffer_warmup_factor=2,
                        decode_record_batch=_killing_decode)
    loader = DataLoader(ds, batch_size=4, worker_mode="process")
    with pytest.raises(RuntimeError, match="died|failed"):
        list(loader)


def test_dynamic_masking_stats(pipeline):
    loader = _loader(pipeline, "dyn", batch_size=32)
    masked = 0
    eligible = 0
    mask_tok = 0
    from lddl_tpu.preprocess import get_tokenizer
    tok = get_tokenizer(vocab_file=pipeline["vocab"])
    mask_id = tok.convert_tokens_to_ids("[MASK]")
    for b in loader:
        lab = b["labels"]
        masked += (lab != -1).sum()
        mask_tok += ((lab != -1) & (b["input_ids"] == mask_id)).sum()
        eligible += b["attention_mask"].sum() - 3 * len(lab)
    assert 0.10 < masked / eligible < 0.20
    assert 0.75 < mask_tok / masked < 0.85


def test_process_dp_info_single_process():
    import jax
    from lddl_tpu.parallel import make_mesh
    mesh = make_mesh({"dp": 2, "tp": 4})
    dp_rank, num_groups = process_dp_info(mesh)
    # Single process owns every device -> one group.
    assert (dp_rank, num_groups) == (0, 1)


def test_to_device_batch_mesh_sharding(pipeline):
    import jax
    from lddl_tpu.parallel import make_mesh
    mesh = make_mesh({"dp": 4, "tp": 2})
    loader = _loader(pipeline, "dyn", batch_size=8)
    batch = next(iter(loader))
    global_batch = to_device_batch(batch, mesh)
    arr = global_batch["input_ids"]
    assert arr.shape == batch["input_ids"].shape
    np.testing.assert_array_equal(np.asarray(arr), batch["input_ids"])
    # Sharded over dp: each device holds batch/4 rows.
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(2, batch["input_ids"].shape[1])}


def test_loader_validation(pipeline):
    with pytest.raises(ValueError, match="not divisible"):
        _loader(pipeline, "dyn", num_dp_groups=3)
    with pytest.raises(ValueError, match="not divisible"):
        _loader(pipeline, "dyn", num_workers=3)
    with pytest.raises(ValueError):
        get_bert_pretrain_data_loader(
            "/nonexistent", vocab_file=pipeline["vocab"])


def _reference_collate(tok, samples, seq_len, ignore_index=-1):
    """Per-row loop encoding (the pre-vectorization implementation) used as
    the parity oracle for BertCollate's scatter-based encode."""
    n = len(samples)
    static = len(samples[0]) == 5
    from lddl_tpu.utils.fs import deserialize_np_array
    cls_id = tok.convert_tokens_to_ids("[CLS]")
    sep_id = tok.convert_tokens_to_ids("[SEP]")
    a_ids = [tok.convert_tokens_to_ids(s[0].split()) for s in samples]
    b_ids = [tok.convert_tokens_to_ids(s[1].split()) for s in samples]
    input_ids = np.zeros((n, seq_len), dtype=np.int32)
    token_type_ids = np.zeros((n, seq_len), dtype=np.int32)
    attention_mask = np.zeros((n, seq_len), dtype=np.int32)
    special_tokens_mask = np.ones((n, seq_len), dtype=bool)
    labels = np.full((n, seq_len), ignore_index, dtype=np.int32)
    for i, (a, b) in enumerate(zip(a_ids, b_ids)):
        la, lb = len(a), len(b)
        end = la + lb + 3
        input_ids[i, 0] = cls_id
        input_ids[i, 1:1 + la] = a
        input_ids[i, 1 + la] = sep_id
        input_ids[i, 2 + la:2 + la + lb] = b
        input_ids[i, end - 1] = sep_id
        token_type_ids[i, 2 + la:end] = 1
        attention_mask[i, :end] = 1
        special_tokens_mask[i, 1:1 + la] = False
        special_tokens_mask[i, 2 + la:end - 1] = False
        if static:
            positions = deserialize_np_array(samples[i][3]).astype(np.int64)
            label_ids = tok.convert_tokens_to_ids(samples[i][4].split())
            labels[i, positions] = np.asarray(label_ids, dtype=np.int32)
    return (input_ids, token_type_ids, attention_mask, special_tokens_mask,
            labels)


def _synthetic_samples(tok, n, static, seed=7):
    g = np.random.Generator(np.random.Philox(key=[0, seed]))
    vocab_tokens = [t for t in tok.get_vocab() if not t.startswith("[")]
    samples = []
    from lddl_tpu.utils.fs import serialize_np_array
    for _ in range(n):
        la, lb = int(g.integers(1, 20)), int(g.integers(1, 20))
        a = " ".join(vocab_tokens[int(g.integers(0, len(vocab_tokens)))]
                     for _ in range(la))
        b = " ".join(vocab_tokens[int(g.integers(0, len(vocab_tokens)))]
                     for _ in range(lb))
        rn = int(g.integers(0, 2))
        if static:
            k = int(g.integers(0, min(5, la + lb + 2)))
            pos = np.sort(g.choice(np.arange(1, la + lb + 2), size=k,
                                   replace=False)).astype(np.uint16)
            labs = " ".join(
                vocab_tokens[int(g.integers(0, len(vocab_tokens)))]
                for _ in range(k))
            samples.append((a, b, rn, serialize_np_array(pos), labs))
        else:
            samples.append((a, b, rn))
    return samples


@pytest.mark.parametrize("static", (False, True))
def test_collate_matches_row_loop_reference(pipeline, static):
    from lddl_tpu.loader.bert import BertCollate
    tok = pipeline["tokenizer"]
    samples = _synthetic_samples(tok, 37, static)
    collate = BertCollate(tok, fixed_seq_length=48)
    g = lrng.sample_rng(3, 0xC011, 0, 0, 0)
    batch = collate(samples, g=None if static else g)
    (ids, tt, am, stm, labels) = _reference_collate(tok, samples, 48)
    np.testing.assert_array_equal(batch["token_type_ids"], tt)
    np.testing.assert_array_equal(batch["attention_mask"], am)
    np.testing.assert_array_equal(
        batch["next_sentence_labels"],
        np.asarray([int(s[2]) for s in samples], dtype=np.int32))
    if static:
        np.testing.assert_array_equal(batch["input_ids"], ids)
        np.testing.assert_array_equal(batch["labels"], labels)
    else:
        # Same RNG stream + identical pre-mask encode => identical draws.
        g2 = lrng.sample_rng(3, 0xC011, 0, 0, 0)
        ref_ids, ref_labels = collate._mask_tokens(ids, stm, g2)
        np.testing.assert_array_equal(batch["input_ids"], ref_ids)
        np.testing.assert_array_equal(batch["labels"], ref_labels)


def test_collate_throughput_floor(pipeline):
    """Perf regression guard on the vectorized collate: pre-vectorization it
    ran ~50k samples/s on this corpus shape; the scatter-based encode does
    >100k. A 10x margin below keeps the test robust on slow CI."""
    import time
    from lddl_tpu.loader.bert import BertCollate
    tok = pipeline["tokenizer"]
    samples = _synthetic_samples(tok, 64, False)
    collate = BertCollate(tok, fixed_seq_length=64)
    g = lrng.sample_rng(3, 0xC011, 0, 0, 0)
    collate(samples, g=g)  # warm
    t0 = time.perf_counter()
    iters = 30
    for _ in range(iters):
        collate(samples, g=g)
    rate = 64 * iters / (time.perf_counter() - t0)
    assert rate > 10_000, "collate regressed to {:.0f} samples/s".format(rate)


class _FakeDevice:
    """Synthetic device carrying only process_index, for layout tests."""

    def __init__(self, process_index):
        self.process_index = process_index

    def __repr__(self):
        return "dev(p{})".format(self.process_index)


def _device_grid(proc_of_coords, shape):
    arr = np.empty(shape, dtype=object)
    for coords in np.ndindex(*shape):
        arr[coords] = _FakeDevice(proc_of_coords(coords))
    return arr


def test_dp_info_dp_across_hosts():
    """(dp=4, tp=2), each host owns one full dp slice (its tp pair):
    4 groups, dp_rank == host index."""
    devices = _device_grid(lambda c: c[0], (4, 2))
    for p in range(4):
        assert dp_info_of_process(devices, ("dp", "tp"), p) == (p, 4)


def test_dp_info_tp_across_hosts():
    """(dp=2, tp=4), tp split across two hosts per dp block: TP peers on
    different hosts share a dp_rank."""
    devices = _device_grid(lambda c: c[0] * 2 + c[1] // 2, (2, 4))
    assert dp_info_of_process(devices, ("dp", "tp"), 0) == (0, 2)
    assert dp_info_of_process(devices, ("dp", "tp"), 1) == (0, 2)
    assert dp_info_of_process(devices, ("dp", "tp"), 2) == (1, 2)
    assert dp_info_of_process(devices, ("dp", "tp"), 3) == (1, 2)


def test_dp_info_combined_data_axes():
    """(dp=2, fsdp=2, tp=2): batch blocks flatten over BOTH data axes; one
    host per (dp, fsdp) coordinate gives 4 groups ordered by block."""
    devices = _device_grid(lambda c: c[0] * 2 + c[1], (2, 2, 2))
    for p in range(4):
        assert dp_info_of_process(devices, ("dp", "fsdp", "tp"),
                                  p) == (p, 4)


def test_dp_info_host_spans_blocks():
    """A host owning several whole dp blocks is one group; peers must
    cover the same set."""
    # (dp=4, tp=2): host 0 owns dp blocks {0,1}, host 1 owns {2,3}.
    devices = _device_grid(lambda c: c[0] // 2, (4, 2))
    assert dp_info_of_process(devices, ("dp", "tp"), 0) == (0, 2)
    assert dp_info_of_process(devices, ("dp", "tp"), 1) == (1, 2)


def test_dp_info_invalid_overlapping_layout():
    """A host straddling dp blocks that another host covers only partially
    is rejected."""
    # (dp=2, tp=2): p0 owns (0,t0),(1,t0); p1 owns (0,t1); p2 owns (1,t1).
    def proc(c):
        if c[1] == 0:
            return 0
        return 1 + c[0]
    devices = _device_grid(proc, (2, 2))
    with pytest.raises(ValueError, match="multiple process groups"):
        dp_info_of_process(devices, ("dp", "tp"), 0)


def test_dp_info_no_data_axes():
    devices = _device_grid(lambda c: c[0], (4,))
    assert dp_info_of_process(devices, ("tp",), 2) == (0, 1)


def test_dp_info_unknown_process_raises():
    devices = _device_grid(lambda c: 0, (2, 2))
    with pytest.raises(RuntimeError, match="owns no devices"):
        dp_info_of_process(devices, ("dp", "tp"), 7)


@pytest.mark.skipif((os.cpu_count() or 1) < 4,
                    reason="loader worker scaling needs >= 4 cores; this "
                           "host cannot show a multi-worker win (VERDICT "
                           "r4 #8 — the 1-CPU bench host measures w4 == w1)")
def test_thread_workers_scale_on_multicore(tmp_path_factory):
    """On a real multi-core host, 4 thread workers must beat 1 on the
    dynamic-masking loader path (parquet decode + numpy collate release
    the GIL). Self-proves the scaling claim on the first capable host;
    ref anchor: lddl/torch/bert.py:386 (multi-worker DataLoader).

    Builds its own multi-MB corpus (only on capable hosts — the build is
    skipped with the test) so per-epoch work dwarfs the per-epoch thread
    spawn/round-robin overhead a tiny fixture would let dominate."""
    import sys
    import time
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from benchmarks.loader_bench import _build_dataset

    tmp = str(tmp_path_factory.mktemp("scale"))
    datasets, vocab = _build_dataset(tmp, mb=4.0,
                                     which=("dynamic_unbinned",))
    path = datasets["dynamic_unbinned"]

    def epoch_time(workers):
        loader = get_bert_pretrain_data_loader(
            path, vocab_file=vocab, batch_size=64, num_workers=workers,
            base_seed=7)
        # Warmup epoch (fills shuffle buffers, opens files), then measure.
        for _ in loader:
            pass
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            n = sum(1 for _ in loader)
            best = min(best, time.perf_counter() - t0)
            assert n > 0
        return best

    t1, t4 = epoch_time(1), epoch_time(4)
    assert t4 < t1, (
        "4 thread workers no faster than 1 on a {}-core host: "
        "w1={:.3f}s w4={:.3f}s".format(os.cpu_count(), t1, t4))
