"""The determinism / SPMD-safety analyzer (lddl_tpu/analysis).

Three layers:

1. Framework mechanics — suppressions, baseline matching, JSON output,
   exit codes.
2. Per-rule fixtures — every rule gets at least one true-positive bad
   snippet AND one suppressed/allowlisted case, so reintroducing any
   guarded pattern anywhere in the tree demonstrably fails CI.
3. The CI gate — a full run over lddl_tpu/, tools/, and benchmarks/
   must produce zero non-baselined findings, with a bounded, justified
   baseline; plus ordered-iteration determinism proofs for the shard
   enumeration paths the rule audited.
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from lddl_tpu import analysis

REPO_ROOT = analysis.REPO_ROOT


def check(source, path, rules=None):
    """Findings for one in-memory snippet under a virtual repo path."""
    findings, _ = analysis.analyze_source(
        textwrap.dedent(source), path,
        analysis.get_rules(rules) if rules else None)
    return findings


def rule_ids(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------- framework


def test_every_rule_is_registered_once():
    ids = [r.id for r in analysis.all_rules()]
    assert len(ids) == len(set(ids))
    assert set(ids) == {
        # file-scope (syntactic) rules
        "global-rng", "wall-clock", "atomic-publish", "unsorted-iteration",
        "swallowed-error", "stage-span", "jit-host-effect",
        "manifest-determinism", "python-hot-loop",
        # project-scope (interprocedural flow) rules — tests/test_dataflow.py
        "wall-clock-flow", "rng-flow", "fs-order-flow",
        "publish-path-flow", "lease-isolation",
        # concurrency rules — tests/test_concurrency_rules.py
        "thread-escape", "lock-order", "signal-safety",
        "env-read-after-spawn",
    }


def test_unknown_rule_filter_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        analysis.get_rules(["no-such-rule"])


def test_inline_suppression_same_line():
    src = "import os\nnames = os.listdir(d)  # lddl: disable=unsorted-iteration\n"
    findings, suppressed = analysis.analyze_source(src, "lddl_tpu/x.py")
    assert rule_ids(findings) == []
    assert rule_ids(suppressed) == ["unsorted-iteration"]


def test_inline_suppression_comment_line_above():
    src = ("import os\n"
           "# why: count only -- lddl: disable=unsorted-iteration\n"
           "names = os.listdir(d)\n")
    findings, suppressed = analysis.analyze_source(src, "lddl_tpu/x.py")
    assert rule_ids(findings) == []
    assert rule_ids(suppressed) == ["unsorted-iteration"]


def test_suppression_is_rule_specific():
    src = "import os\nnames = os.listdir(d)  # lddl: disable=wall-clock\n"
    findings, _ = analysis.analyze_source(src, "lddl_tpu/x.py")
    assert rule_ids(findings) == ["unsorted-iteration"]


def test_baseline_matches_on_rule_path_and_line_text():
    src = "import os\nnames = os.listdir(d)\n"
    findings, _ = analysis.analyze_source(src, "lddl_tpu/x.py")
    [f] = findings
    entry = analysis.baseline_entry(f, "grandfathered")
    new, old = analysis.split_baselined([f], [entry])
    assert (new, old) == ([], [f])
    # A different line text (the code changed) is a NEW finding again.
    entry2 = dict(entry, match="something_else()")
    new, old = analysis.split_baselined([f], [entry2])
    assert (new, old) == ([f], [])


# ---------------------------------------------------------- rule fixtures


def test_global_rng_true_positives():
    src = """
    import random
    import numpy as np

    def pick(files):
        random.shuffle(files)
        g = np.random.default_rng(0)
        np.random.seed(1)
        return files
    """
    ids = rule_ids(check(src, "lddl_tpu/loader/x.py", ["global-rng"]))
    assert ids == ["global-rng"] * 3


def test_global_rng_allows_keyed_streams_and_allowlisted_files():
    src = """
    import numpy as np
    from lddl_tpu.utils.rng import sample_rng

    def pick(seed):
        g = sample_rng(seed)          # keyed stream: fine
        r = g.random(4)               # method on a Generator: fine
        k = np.random.Philox(key=[1]) # explicit keying: fine
        return r, k
    """
    assert check(src, "lddl_tpu/loader/x.py", ["global-rng"]) == []
    # The allowlisted owners may construct whatever they need.
    bad = "import numpy as np\ng = np.random.default_rng(0)\n"
    assert check(bad, "lddl_tpu/utils/rng.py", ["global-rng"]) == []
    assert check(bad, "lddl_tpu/models/testing.py", ["global-rng"]) == []


def test_wall_clock_true_positive_and_aliased_import():
    src = """
    import time
    from datetime import datetime

    def shard_name(i):
        return "shard-{}-{}".format(i, time.time())

    def stamp():
        return datetime.now()
    """
    ids = rule_ids(check(src, "lddl_tpu/preprocess/x.py", ["wall-clock"]))
    assert ids == ["wall-clock"] * 2


def test_wall_clock_allows_observability_and_monotonic():
    bad = "import time\nts = time.time()\n"
    assert check(bad, "lddl_tpu/observability/tracing.py",
                 ["wall-clock"]) == []
    assert check(bad, "benchmarks/foo_bench.py", ["wall-clock"]) == []
    ok = "import time\nt0 = time.monotonic()\nt1 = time.perf_counter()\n"
    assert check(ok, "lddl_tpu/preprocess/x.py", ["wall-clock"]) == []


def test_atomic_publish_flags_moves_everywhere():
    src = """
    import os
    import shutil

    def publish(tmp, dst):
        os.replace(tmp, dst)
        os.rename(tmp, dst)
        shutil.move(tmp, dst)
    """
    ids = rule_ids(check(src, "lddl_tpu/preprocess/x.py",
                         ["atomic-publish"]))
    assert ids == ["atomic-publish"] * 3
    # ...including outside the shard packages (the old grep lint's scope).
    ids = rule_ids(check(src, "lddl_tpu/observability/x.py",
                         ["atomic-publish"]))
    assert ids == ["atomic-publish"] * 3


def test_atomic_publish_flags_raw_parquet_and_write_open():
    src = """
    import pyarrow.parquet as pq

    def sink(table, path, rows):
        pq.write_table(table, path)
        with open(path + ".txt", "w") as f:
            f.write(rows)
    """
    ids = rule_ids(check(src, "lddl_tpu/preprocess/x.py",
                         ["atomic-publish"]))
    assert ids == ["atomic-publish"] * 2


def test_atomic_publish_allows_resilience_io_and_reads():
    src = "import os\nos.replace('a', 'b')\n"
    assert check(src, "lddl_tpu/resilience/io.py", ["atomic-publish"]) == []
    ok = "rows = open(path).read()\nmore = open(path, 'rb').read()\n"
    assert check(ok, "lddl_tpu/preprocess/x.py", ["atomic-publish"]) == []


def test_unsorted_iteration_true_positives():
    src = """
    import glob
    import os

    def shards(d):
        return [n for n in os.listdir(d) if ".parquet" in n]

    def parts(d):
        for p in glob.glob(d + "/part.*"):
            yield p
    """
    ids = rule_ids(check(src, "lddl_tpu/balance/x.py",
                         ["unsorted-iteration"]))
    assert ids == ["unsorted-iteration"] * 2


def test_unsorted_iteration_allows_sorted_and_reductions():
    src = """
    import glob
    import os

    def shards(d):
        return sorted(n for n in os.listdir(d) if ".parquet" in n)

    def count(d):
        return len(os.listdir(d))

    def names(d):
        return set(os.listdir(d)) | {s for s in glob.glob(d + "/*")}
    """
    assert check(src, "lddl_tpu/balance/x.py", ["unsorted-iteration"]) == []


def test_swallowed_error_true_positives():
    src = """
    def load(path):
        try:
            return open(path).read()
        except:
            return None

    def sweep(path):
        import os
        try:
            os.remove(path)
        except OSError:
            pass
    """
    ids = rule_ids(check(src, "lddl_tpu/loader/x.py", ["swallowed-error"]))
    assert ids == ["swallowed-error"] * 2


def test_swallowed_error_allows_handled_oserror():
    src = """
    def read_or_default(path):
        try:
            return open(path).read()
        except OSError:
            return ""
    """
    assert check(src, "lddl_tpu/loader/x.py", ["swallowed-error"]) == []


def test_stage_span_missing_span_is_flagged():
    src = """
    def balance_shards(in_dir, out_dir):
        return do_work(in_dir, out_dir)
    """
    ids = rule_ids(check(src, "lddl_tpu/balance/balancer.py",
                         ["stage-span"]))
    assert ids == ["stage-span"]
    # Non-entry files carry no span obligation.
    assert check(src, "lddl_tpu/balance/other.py", ["stage-span"]) == []


def test_stage_span_present_span_passes():
    src = """
    from .. import observability as obs

    def balance_shards(in_dir, out_dir):
        with obs.span("balance.run"):
            return do_work(in_dir, out_dir)
    """
    assert check(src, "lddl_tpu/balance/balancer.py", ["stage-span"]) == []


def test_stage_span_covers_elastic_and_ingest_entry_points():
    """The elastic claim loop and the streaming-ingest service are stage
    entry points too: steal.py owes BOTH its gather and finalize spans
    (one finding per missing name), incremental.py owes ingest.run."""
    bare = """
    def run_elastic_pipeline(spec):
        return claim(spec)
    """
    ids = rule_ids(check(bare, "lddl_tpu/preprocess/steal.py",
                         ["stage-span"]))
    assert ids == ["stage-span", "stage-span"]
    partial = """
    from .. import observability as obs

    def run_elastic_pipeline(spec):
        with obs.span("preprocess.gather", elastic=True):
            return claim(spec)
    """
    assert len(check(partial, "lddl_tpu/preprocess/steal.py",
                     ["stage-span"])) == 1  # finalize still missing
    full = partial + """
    def _finalize(spec):
        with obs.span("preprocess.finalize"):
            return done(spec)
    """
    assert check(full, "lddl_tpu/preprocess/steal.py", ["stage-span"]) == []
    assert rule_ids(check(bare, "lddl_tpu/ingest/incremental.py",
                          ["stage-span"])) == ["stage-span"]
    ok = """
    from .. import observability as obs

    def ingest_once(root):
        with obs.span("ingest.run", root=root):
            return body(root)
    """
    assert check(ok, "lddl_tpu/ingest/incremental.py", ["stage-span"]) == []


def test_jit_host_effect_true_positives():
    src = """
    import functools
    import jax
    from .. import observability as obs

    def _impl(x, scale):
        print("tracing", x)
        obs.inc("steps_total")
        return float(x) * scale

    def make(scale):
        impl = functools.partial(_impl, scale=scale)
        return jax.jit(impl)

    @jax.jit
    def decorated(x):
        import time
        t = time.perf_counter()
        return x * t
    """
    ids = rule_ids(check(src, "lddl_tpu/ops/x.py", ["jit-host-effect"]))
    assert sorted(ids) == ["jit-host-effect"] * 4


def test_jit_host_effect_ignores_unjitted_and_other_packages():
    src = """
    def helper(x):
        print("host-side is fine here")
        return float(x)
    """
    assert check(src, "lddl_tpu/ops/x.py", ["jit-host-effect"]) == []
    jit_src = """
    import jax

    @jax.jit
    def f(x):
        print(x)
        return x
    """
    # Rule is scoped to ops/ and models/ only.
    assert check(jit_src, "lddl_tpu/loader/x.py", ["jit-host-effect"]) == []
    assert rule_ids(check(jit_src, "lddl_tpu/models/x.py",
                          ["jit-host-effect"])) == ["jit-host-effect"]


def test_manifest_determinism_true_positive():
    src = """
    import os
    import time

    def build_manifest(names):
        return {"at": time.time(), "pid": os.getpid(),
                "shards": sorted(names)}

    def _ledger_write(out_dir, written):
        import uuid
        return {"id": str(uuid.uuid4()), "written": written}
    """
    ids = rule_ids(check(src, "lddl_tpu/resilience/x.py",
                         ["manifest-determinism"]))
    assert ids == ["manifest-determinism"] * 3


def test_manifest_determinism_ignores_other_functions():
    src = """
    import time

    def progress_meter():
        return time.time()
    """
    assert check(src, "lddl_tpu/resilience/x.py",
                 ["manifest-determinism"]) == []


# ------------------------------------------------------------ the CI gate


def test_python_hot_loop_true_positives():
    src = """
        import numpy as np

        def decode(b):
            for row in b.to_pydict()["A"]:
                yield row

        def collate(token_lists, vocab):
            return np.fromiter(
                (vocab[t] for ts in token_lists for t in ts),
                dtype=np.int32)

        def lens(col):
            return [v.as_py() for v in col]
    """
    findings = check(src, "lddl_tpu/loader/custom.py",
                     rules=["python-hot-loop"])
    assert rule_ids(findings) == ["python-hot-loop"] * 3


def test_python_hot_loop_scoped_to_loader_and_suppressible():
    src = """
        def anywhere(col):
            return col.to_pylist()
    """
    # The rule covers the loader AND the offline hot stages (preprocess/
    # balance, whose per-token loops the ROADMAP's native-preprocess item
    # targets) — but not e.g. models/ or tools/.
    assert rule_ids(check(src, "lddl_tpu/preprocess/x.py",
                          rules=["python-hot-loop"])) == ["python-hot-loop"]
    assert rule_ids(check(src, "lddl_tpu/balance/x.py",
                          rules=["python-hot-loop"])) == ["python-hot-loop"]
    assert check(src, "lddl_tpu/models/x.py",
                 rules=["python-hot-loop"]) == []
    assert check(src, "tools/x.py", rules=["python-hot-loop"]) == []
    supp = """
        def legacy(b):
            return b.to_pydict()  # v1 shards -- lddl: disable=python-hot-loop
    """
    assert check(supp, "lddl_tpu/loader/x.py",
                 rules=["python-hot-loop"]) == []
    # Per-SAMPLE (single-generator) fromiter and map() stay allowed:
    # lengths and offsets are per-row work, not per-token.
    ok = """
        import numpy as np

        def lens(seqs):
            return np.fromiter((len(s) for s in seqs), dtype=np.int64)
    """
    assert check(ok, "lddl_tpu/loader/x.py",
                 rules=["python-hot-loop"]) == []


def test_full_tree_has_zero_non_baselined_findings():
    """THE gate: every invariant holds over lddl_tpu/, tools/, and
    benchmarks/ right now, modulo the committed, justified baseline."""
    report = analysis.run_check(["lddl_tpu", "tools", "benchmarks"])
    assert report.errors == []
    assert report.new == [], "\n".join(f.format() for f in report.new)


def test_baseline_is_bounded_and_justified():
    entries = analysis.load_baseline(
        os.path.join(REPO_ROOT, analysis.DEFAULT_BASELINE))
    assert 0 < len(entries) <= 10
    for e in entries:
        assert e.get("reason", "").strip(), \
            "baseline entry without a justification: {}".format(e)
        assert "TODO" not in e["reason"]


def test_introducing_a_bad_snippet_fails_the_tree(tmp_path):
    """End-to-end: drop one bad fixture file into an analyzed tree and the
    checker (API and CLI alike) must go red."""
    pkg = tmp_path / "lddl_tpu_fixture"
    pkg.mkdir()
    bad = pkg / "regression.py"
    bad.write_text("import os\n\n"
                   "def publish(tmp, dst):\n"
                   "    os.replace(tmp, dst)\n")
    report = analysis.run_check([str(bad)], root=str(tmp_path))
    assert [f.rule for f in report.new] == ["atomic-publish"]
    assert not report.ok


def test_cli_json_mode_and_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lddl_check", "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert payload["files"] > 50
    assert len(payload["baselined"]) <= 10

    # A tree with a violation exits 1 and reports it in JSON.
    bad = tmp_path / "bad.py"
    bad.write_text("import random\nrandom.shuffle([1, 2])\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lddl_check", str(bad),
         "--baseline", "", "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, env=env)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert [f["rule"] for f in payload["findings"]] == ["global-rng"]


def test_nonexistent_path_is_a_loud_error():
    """A typo'd path must not make the gate silently green (0 files,
    exit 0)."""
    with pytest.raises(FileNotFoundError, match="lddl_tpuu"):
        analysis.run_check(["lddl_tpuu"])
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lddl_check", "lddl_tpuu"],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 2
    assert "does not exist" in proc.stderr


def test_write_baseline_refuses_filtered_runs(tmp_path):
    """--write-baseline from a --rules/paths-filtered run would silently
    drop every grandfathered entry outside the filter."""
    baseline = tmp_path / "b.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for extra in (["--rules", "wall-clock"], ["lddl_tpu"]):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lddl_check", "--write-baseline",
             "--baseline", str(baseline)] + extra,
            cwd=REPO_ROOT, capture_output=True, text=True, env=env)
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "full run" in proc.stderr
        assert not baseline.exists()


def test_ci_check_script():
    """The tier-1 static gate (--full): analyzer + syntax pass + SARIF
    artifact for code-review tooling."""
    sarif_path = os.path.join(REPO_ROOT, "lddl_check.sarif")
    proc = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "ci_check.sh"),
         "--full"],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ci_check: OK" in proc.stdout
    assert "SARIF artifact" in proc.stdout
    with open(sarif_path) as f:
        sarif = json.load(f)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "lddl-check"
    # Zero gating results; grandfathered debt rides along as "unchanged".
    assert all(r.get("baselineState") == "unchanged"
               for r in run["results"])
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "fs-order-flow" in rule_ids
    os.unlink(sarif_path)


def test_ci_check_script_default_is_changed_only():
    """Without --full the gate reports only files changed vs HEAD — the
    pre-commit fast path (analysis still spans the tree via the cache)."""
    proc = subprocess.run(
        ["bash", os.path.join(REPO_ROOT, "tools", "ci_check.sh")],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ci_check: OK" in proc.stdout
    assert "SARIF" not in proc.stdout


def test_full_tree_run_is_inside_its_time_budget():
    """The analyzer rides tier-1 on every test run: a cold full-tree run
    (parse + per-file rules + whole-program fixpoint, no cache) must stay
    well under a minute on the 2-CPU CI box, and the wall time must be
    reported so regressions are visible in CI output."""
    report = analysis.run_check(["lddl_tpu", "tools", "benchmarks"],
                                cache_path=None)
    assert report.elapsed_s < 60.0, \
        "analyzer blew its budget: {:.1f}s".format(report.elapsed_s)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lddl_check"],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert re.search(r"in \d+\.\d\ds", proc.stdout), proc.stdout


def test_cli_changed_only_mode(tmp_path):
    """--changed-only restricts the REPORT to changed files while the
    analysis still spans the paths; with a clean tree it reports nothing
    and exits 0."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lddl_check", "--changed-only"],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_changed_only_sees_files_in_untracked_directories(tmp_path):
    """A brand-new package directory shows up as `?? newdir/` in plain
    porcelain output; -uall must expand it so its .py files are not
    silently excluded from the changed-only report."""
    from tools.lddl_check import changed_python_files
    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    pkg = tmp_path / "newpkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("x = 1\n")
    changed = changed_python_files(str(tmp_path))
    assert changed == {"newpkg/mod.py"}


def test_cli_sarif_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import random\nrandom.shuffle([1, 2])\n")
    out = tmp_path / "report.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lddl_check", str(bad),
         "--baseline", "", "--sarif", str(out)],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 1
    sarif = json.loads(out.read_text())
    [result] = sarif["runs"][0]["results"]
    assert result["ruleId"] == "global-rng"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 2


# ----------------------------- ordered-iteration determinism (satellite)


def test_shard_enumeration_is_immune_to_filesystem_order(monkeypatch):
    """Satellite proof: the shard-listing helpers cannot leak FS order.
    os.walk/os.listdir are patched to yield entries REVERSED; every
    enumeration the pipeline consumes must come back sorted anyway."""
    from lddl_tpu.resilience import integrity
    from lddl_tpu.utils import fs

    real_walk, real_listdir = os.walk, os.listdir

    def reversed_walk(top, **kw):
        for dirpath, dirnames, filenames in real_walk(top, **kw):
            yield dirpath, list(reversed(sorted(dirnames))), \
                list(reversed(sorted(filenames)))

    def reversed_listdir(path):
        return list(reversed(sorted(real_listdir(path))))

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        for name in ("part.2.parquet", "part.0.parquet", "part.1.parquet",
                     ".num_samples.json"):
            with open(os.path.join(d, name), "w") as f:
                f.write("x")
        monkeypatch.setattr(os, "walk", reversed_walk)
        monkeypatch.setattr(os, "listdir", reversed_listdir)

        paths = fs.get_all_parquets_under(d)
        assert paths == sorted(paths) and len(paths) == 3

        names = integrity._parquet_basenames(d)
        assert names == ["part.0.parquet", "part.1.parquet",
                         "part.2.parquet"]


def test_balancer_stale_guard_reports_deterministically(monkeypatch,
                                                        tmp_path):
    """balance/balancer.py's dirty-output guard (the audited site) now
    sorts its listing: the reported example shard is the lexicographic
    first regardless of FS enumeration order."""
    from lddl_tpu.balance.balancer import balance_shards

    out = tmp_path / "out"
    out.mkdir()
    for name in ("zzz.parquet", "aaa.parquet"):
        (out / name).write_text("x")
    real_listdir = os.listdir
    monkeypatch.setattr(
        os, "listdir",
        lambda p: list(reversed(sorted(real_listdir(p)))))
    with pytest.raises(ValueError, match=r"e\.g\. aaa\.parquet"):
        balance_shards(str(tmp_path / "nothing"), str(out), 2)
