"""Preprocessor: sentence split, pair creation, masking, binning, e2e run."""

import os

import numpy as np
import pytest

from lddl_tpu.preprocess import (
    BertPretrainConfig,
    build_wordpiece_vocab,
    create_masked_lm_predictions,
    create_pairs_from_document,
    get_tokenizer,
    num_bins,
    bin_id_of_num_tokens,
    run_bert_preprocess,
    split_sentences,
)
from lddl_tpu.preprocess.bert import (TokenizerInfo, documents_from_texts,
                                       materialize_rows, pairs_from_documents)


def _rows(documents, config, tok, seed, bucket=0, scope=(1, 2)):
    instances = pairs_from_documents(documents, config, seed, bucket)
    return materialize_rows(instances, config, TokenizerInfo(tok), 0, scope)
from lddl_tpu.preprocess.readers import plan_blocks, read_block_lines
from lddl_tpu.preprocess.runner import vocab_words_of
from lddl_tpu.utils import rng as lrng
from lddl_tpu.utils.fs import (
    deserialize_np_array,
    get_all_parquets_under,
    get_all_bin_ids,
    get_num_samples_of_parquet,
)


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    texts = [" ".join(words)] * 4
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    return build_wordpiece_vocab(texts, str(path), vocab_size=200)


@pytest.fixture(scope="module")
def tokenizer(vocab_file):
    return get_tokenizer(vocab_file=vocab_file)


def test_split_sentences_basic():
    s = split_sentences("Hello world. This is fine! Is it? Yes.")
    assert s == ["Hello world.", "This is fine!", "Is it?", "Yes."]


def test_split_sentences_abbreviations():
    s = split_sentences("Dr. Smith went to Washington. He arrived at 3 p.m. "
                        "It was raining.")
    assert "Dr. Smith went to Washington." in s
    # 'p.m.' boundary followed by uppercase is ambiguous; we only require
    # that the abbreviation itself never produces a 1-word fragment "Dr."
    assert all(len(x) > 4 for x in s)


def test_split_sentences_initials_and_decimals():
    s = split_sentences("J. R. Tolkien wrote it. The value is 3.14 exactly. Done.")
    assert s[0].startswith("J. R. Tolkien")
    assert any("3.14" in x for x in s)


def test_split_sentences_non_upper_starts():
    """Round-3 rules: a sentence may start with bullets/quotes/dashes —
    anything but a lowercase letter (punkt behavior)."""
    s = split_sentences('He agreed. "Fine," she said. - item one follows.')
    assert s[0] == "He agreed."
    s = split_sentences("Conditions are met: * Redistributions must keep "
                        "the notice. * Binaries too.")
    assert len(s) == 2


def test_split_sentences_lowercase_after_bang_only():
    s = split_sentences("What a day! so we left. but we did not return.")
    assert s[0] == "What a day!"          # lowercase start after ! splits
    assert len(s) == 2                    # '.' + lowercase does not


def test_split_sentences_enumerator_attachment():
    """Bare enumerators glue to the PRECEDING sentence, punkt-style, and
    their own dot provides the boundary."""
    s = split_sentences("See the License. 2. Grant of Patent License. "
                        "Subject to terms.")
    assert s[0] == "See the License. 2."
    assert s[1] == "Grant of Patent License."
    # A bare year still starts its own sentence.
    s = split_sentences("It happened. 1991 was the year it began.")
    assert s == ["It happened.", "1991 was the year it began."]


def test_plan_blocks_and_read(tiny_corpus):
    from lddl_tpu.preprocess.readers import discover_source_files
    files = discover_source_files({"wikipedia": tiny_corpus})
    assert len(files) == 4
    blocks = plan_blocks(files, 8)
    # Every line appears exactly once across blocks.
    all_lines = []
    for b in blocks:
        all_lines.extend(read_block_lines(b))
    expected = []
    for p in files:
        with open(p, "rb") as f:
            expected.extend(l.rstrip(b"\n") for l in f)
    assert sorted(all_lines) == sorted(expected)


def test_documents_from_texts(tokenizer):
    docs = documents_from_texts(
        ["Alpha beta gamma. Delta epsilon zeta.", "", "Eta theta."],
        tokenizer)
    assert len(docs) == 2
    assert len(docs[0]) == 2  # two sentences
    # Token ids: Python ints on the hf engine, zero-copy int32 numpy
    # views on the native engine — both integer-valued sequences.
    assert all(int(t) == t for t in docs[0][0])


def test_pair_creation_invariants(tokenizer):
    texts = [
        "Alpha beta gamma delta. Epsilon zeta eta theta. Iota kappa lambda mu. "
        "Nu xi omicron pi. Rho sigma tau upsilon.",
        "Beta alpha delta gamma. Zeta epsilon theta eta. Kappa iota mu lambda.",
        "Gamma delta alpha beta. Eta zeta theta epsilon.",
    ] * 3
    documents = documents_from_texts(texts, tokenizer)
    config = BertPretrainConfig(max_seq_length=32, duplicate_factor=2)
    rows = _rows(documents, config, tokenizer, seed=0, bucket=1)
    assert len(rows) > 0
    saw_random, saw_next = False, False
    for r in rows:
        a = r["A"].split()
        b = r["B"].split()
        assert 1 <= len(a) and 1 <= len(b)
        assert len(a) + len(b) <= config.max_seq_length - 3
        assert r["num_tokens"] == len(a) + len(b) + 3
        saw_random |= r["is_random_next"]
        saw_next |= not r["is_random_next"]
    assert saw_random and saw_next


def test_pair_creation_deterministic(tokenizer):
    texts = ["Alpha beta gamma delta. Epsilon zeta eta theta. Iota kappa."] * 4
    documents = documents_from_texts(texts, tokenizer)
    config = BertPretrainConfig(max_seq_length=24)
    r1 = _rows(documents, config, tokenizer, seed=9, bucket=2)
    r2 = _rows(documents, config, tokenizer, seed=9, bucket=2)
    assert r1 == r2
    r3 = _rows(documents, config, tokenizer, seed=9, bucket=3)
    assert r1 != r3  # different stream -> different pairs (w.h.p.)


def test_masking_stats(tokenizer):
    vocab_words = vocab_words_of(tokenizer)
    g = lrng.sample_rng(3, 0)
    n_masked = 0
    n_mask_tok = 0
    n_total = 0
    for _ in range(200):
        tokens = ["[CLS]"] + ["alpha"] * 30 + ["[SEP]"] + ["beta"] * 30 + ["[SEP]"]
        orig = list(tokens)
        positions, labels = create_masked_lm_predictions(
            tokens, vocab_words, g, 0.15, 20)
        assert positions == sorted(positions)
        assert len(positions) == len(labels)
        assert len(positions) <= 20
        for p, lab in zip(positions, labels):
            assert orig[p] == lab
            assert tokens[p] != "[CLS]" and tokens[p] != "[SEP]"
            n_mask_tok += tokens[p] == "[MASK]"
        # Unmasked positions unchanged.
        changed = set(positions)
        for i, (t0, t1) in enumerate(zip(orig, tokens)):
            if i not in changed:
                assert t0 == t1
        n_masked += len(positions)
        n_total += len(tokens)
    # ~15% of 63 tokens -> ~9.45/seq; 80% of those become [MASK].
    assert 0.10 < n_masked / n_total < 0.20
    assert 0.70 < n_mask_tok / n_masked < 0.90


def test_bin_math():
    assert num_bins(128, 32) == 4
    with pytest.raises(ValueError):
        num_bins(128, 24)
    assert bin_id_of_num_tokens(1, 32, 4) == 0
    assert bin_id_of_num_tokens(32, 32, 4) == 0
    assert bin_id_of_num_tokens(33, 32, 4) == 1
    assert bin_id_of_num_tokens(128, 32, 4) == 3
    assert bin_id_of_num_tokens(500, 32, 4) == 3  # clamped


def test_e2e_preprocess_unbinned(tiny_corpus, tokenizer, tmp_path):
    out = str(tmp_path / "out")
    written = run_bert_preprocess(
        {"wikipedia": tiny_corpus}, out, tokenizer,
        config=BertPretrainConfig(max_seq_length=32, duplicate_factor=1),
        num_blocks=4, sample_ratio=1.0, seed=0)
    paths = get_all_parquets_under(out)
    assert len(paths) >= 1
    assert get_all_bin_ids(paths) == []
    assert sum(written.values()) == sum(
        get_num_samples_of_parquet(p) for p in paths)
    assert sum(written.values()) > 10


def test_e2e_preprocess_binned_masked(tiny_corpus, tokenizer, tmp_path):
    out = str(tmp_path / "out")
    run_bert_preprocess(
        {"wikipedia": tiny_corpus}, out, tokenizer,
        config=BertPretrainConfig(max_seq_length=64, duplicate_factor=1,
                                  masking=True),
        num_blocks=3, sample_ratio=1.0, seed=0, bin_size=16)
    paths = get_all_parquets_under(out)
    bin_ids = get_all_bin_ids(paths)
    assert len(bin_ids) >= 2  # fixture has varied lengths
    import pyarrow.parquet as pq
    t = pq.read_table(paths[0])
    assert set(t.column_names) == {
        "A", "B", "is_random_next", "num_tokens",
        "masked_lm_positions", "masked_lm_labels",
        # schema v2 (the default): token-id twins the loader decodes
        # zero-copy; text columns stay alongside for v1 readers.
        "A_ids", "B_ids", "masked_lm_positions_ids", "masked_lm_label_ids",
        "bin_id"}
    row = t.to_pylist()[0]
    pos = deserialize_np_array(row["masked_lm_positions"])
    labels = row["masked_lm_labels"].split()
    assert len(pos) == len(labels)
    # The id columns are exact twins of the text/binary columns.
    vocab = tokenizer.get_vocab()
    assert row["A_ids"] == [vocab[t_] for t_ in row["A"].split()]
    assert row["B_ids"] == [vocab[t_] for t_ in row["B"].split()]
    assert row["masked_lm_positions_ids"] == pos.tolist()
    assert row["masked_lm_label_ids"] == [vocab[t_] for t_ in labels]
    seq = (["[CLS]"] + row["A"].split() + ["[SEP]"] + row["B"].split()
           + ["[SEP]"])
    assert row["num_tokens"] == len(seq)
    # Bin invariant: num_tokens within the file's bin.
    b = row["bin_id"]
    assert b * 16 < row["num_tokens"] <= (b + 1) * 16 or b == 3


def test_e2e_multirank_matches_single_rank(tiny_corpus, tokenizer, tmp_path):
    """Sharded SPMD run produces exactly the same shard set as 1 rank."""
    from lddl_tpu.parallel import ThreadGroupCommunicator
    cfg = dict(
        config=BertPretrainConfig(max_seq_length=32, duplicate_factor=1),
        num_blocks=4, sample_ratio=1.0, seed=0)

    out1 = str(tmp_path / "single")
    run_bert_preprocess({"wikipedia": tiny_corpus}, out1, tokenizer, **cfg)

    out4 = str(tmp_path / "four")
    ThreadGroupCommunicator.spawn(
        4, lambda comm: run_bert_preprocess(
            {"wikipedia": tiny_corpus}, out4, tokenizer, comm=comm, **cfg))

    import pyarrow.parquet as pq
    p1 = get_all_parquets_under(out1)
    p4 = get_all_parquets_under(out4)
    assert [os.path.basename(p) for p in p1] == [os.path.basename(p) for p in p4]
    for a, b in zip(p1, p4):
        assert pq.read_table(a).equals(pq.read_table(b))


def test_e2e_pool_matches_sequential(tiny_corpus, tokenizer, tmp_path):
    """num_workers>1 (spawn process pool) writes exactly the same shards
    as the sequential path — bucket work is side-effect-isolated and
    deterministic, so fan-out must be invisible in the output."""
    cfg = dict(
        config=BertPretrainConfig(max_seq_length=32, duplicate_factor=1,
                                  masking=True),
        num_blocks=4, sample_ratio=1.0, seed=0, bin_size=8)

    out1 = str(tmp_path / "seq")
    run_bert_preprocess({"wikipedia": tiny_corpus}, out1, tokenizer, **cfg)

    out2 = str(tmp_path / "pool")
    run_bert_preprocess({"wikipedia": tiny_corpus}, out2, tokenizer,
                        num_workers=2, **cfg)

    import pyarrow.parquet as pq
    p1 = get_all_parquets_under(out1)
    p2 = get_all_parquets_under(out2)
    assert [os.path.basename(p) for p in p1] == [
        os.path.basename(p) for p in p2]
    assert len(p1) > 1
    for a, b in zip(p1, p2):
        assert pq.read_table(a).equals(pq.read_table(b))


def test_tokenizer_picklable_after_native_use(tokenizer):
    """Regression: documents_from_texts caches a TokenizerInfo (holding the
    ctypes-backed native engine) on the tokenizer; the tokenizer — and the
    cached info — must still pickle afterwards, or any num_workers>1 run
    whose parent touched the tokenizer first would crash at pool spawn."""
    import pickle

    def as_lists(docs):
        return [[list(map(int, s)) for s in d] for d in docs]

    docs = as_lists(documents_from_texts(["alpha beta. gamma delta."],
                                         tokenizer))
    assert docs
    info = getattr(tokenizer, "_lddl_tpu_tok_info", None)
    tok2 = pickle.loads(pickle.dumps(tokenizer))
    if info is not None:
        info2 = pickle.loads(pickle.dumps(info))
        # The rebuilt info must lazily reconstruct a working engine.
        docs2 = documents_from_texts(["alpha beta. gamma delta."], info2)
        assert as_lists(docs2) == docs
    assert as_lists(documents_from_texts(["alpha beta. gamma delta."],
                                         tok2)) == docs


def test_native_tokenizer_pickle_roundtrip(tokenizer):
    from lddl_tpu import native
    import pickle

    if not native.available():
        pytest.skip("native engine unavailable")
    info = TokenizerInfo(tokenizer)
    nat = info.native_tokenizer()
    if nat is None:
        pytest.skip("native engine incompatible with tokenizer")
    nat2 = pickle.loads(pickle.dumps(nat))
    a = nat.tokenize_docs(["alpha beta. gamma delta."])
    b = nat2.tokenize_docs(["alpha beta. gamma delta."])
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_txt_output(tiny_corpus, tokenizer, tmp_path):
    out = str(tmp_path / "out")
    written = run_bert_preprocess(
        {"wikipedia": tiny_corpus}, out, tokenizer,
        config=BertPretrainConfig(max_seq_length=32, duplicate_factor=1),
        num_blocks=2, sample_ratio=1.0, seed=0, output_format="txt")
    assert all(p.endswith(".txt") for p in written)
    line = open(list(written)[0]).readline()
    assert line.startswith("is_random_next: ")
    assert "[CLS]" in line and "[SEP]" in line


def test_write_shard_columns_empty_bucket(tmp_path):
    """Empty buckets: unbinned writes an empty shard (schema intact),
    binned writes nothing — matching the row path and the reference."""
    from lddl_tpu.preprocess.binning import write_shard_columns
    import pyarrow.parquet as pq
    out = str(tmp_path)
    written = write_shard_columns({}, 0, out, 7, masking=True, bin_size=None)
    [(path, n)] = written.items()
    assert n == 0
    t = pq.read_table(path)
    assert t.num_rows == 0
    assert set(t.schema.names) >= {"A", "B", "masked_lm_positions"}
    assert write_shard_columns({}, 0, out, 8, masking=True, bin_size=32) == {}
