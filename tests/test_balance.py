"""Balancer: ±1 invariant, content preservation, per-bin balancing, SPMD."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from lddl_tpu.balance import balance_shards, generate_num_samples_cache
from lddl_tpu.parallel import ThreadGroupCommunicator
from lddl_tpu.utils.fs import (
    get_all_parquets_under,
    get_num_samples_of_parquet,
    read_num_samples_cache,
)


def _write_unbalanced(dir_path, sizes, bin_id=None, tag=0):
    os.makedirs(dir_path, exist_ok=True)
    postfix = "" if bin_id is None else "_{}".format(bin_id)
    rows = 0
    for i, n in enumerate(sizes):
        uid = ["{}-{}-{}".format(tag, i, j) for j in range(n)]
        t = pa.table({
            "A": uid,
            "B": ["b"] * n,
            "is_random_next": [False] * n,
            "num_tokens": pa.array([5] * n, type=pa.uint16()),
        })
        pq.write_table(
            t, os.path.join(dir_path, "part.{}.parquet{}".format(i, postfix)))
        rows += n
    return rows


def _collect_ids(paths):
    ids = []
    for p in paths:
        ids.extend(pq.read_table(p).column("A").to_pylist())
    return ids


def test_balance_basic(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    total = _write_unbalanced(src, [50, 3, 17, 0, 30])
    counts = balance_shards(src, dst, num_shards=4)
    assert sum(counts.values()) == total
    vals = sorted(counts.values())
    assert vals[-1] - vals[0] <= 1
    # Content preserved exactly (no loss, no duplication).
    src_ids = _collect_ids(get_all_parquets_under(src))
    dst_ids = _collect_ids(get_all_parquets_under(dst))
    assert sorted(src_ids) == sorted(dst_ids)
    # Cache written and accurate.
    cache = read_num_samples_cache(dst)
    assert cache == counts
    for name, n in counts.items():
        assert get_num_samples_of_parquet(os.path.join(dst, name)) == n


def test_balance_already_balanced(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    _write_unbalanced(src, [10, 10, 10])
    counts = balance_shards(src, dst, num_shards=3)
    assert sorted(counts.values()) == [10, 10, 10]


def test_balance_binned(tmp_path):
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    t0 = _write_unbalanced(src, [40, 2], bin_id=0, tag=0)
    t1 = _write_unbalanced(src, [7, 31, 1], bin_id=1, tag=1)
    counts = balance_shards(src, dst, num_shards=2)
    bin0 = {k: v for k, v in counts.items() if k.endswith("_0")}
    bin1 = {k: v for k, v in counts.items() if k.endswith("_1")}
    assert sum(bin0.values()) == t0 and sum(bin1.values()) == t1
    for group in (bin0, bin1):
        vals = sorted(group.values())
        assert vals[-1] - vals[0] <= 1


@pytest.mark.parametrize("world", [2, 4])
def test_balance_spmd_matches_single(tmp_path, world):
    sizes = [23, 1, 64, 9, 0, 41, 13]
    src = str(tmp_path / "src")
    total = _write_unbalanced(src, sizes)

    dst1 = str(tmp_path / "dst1")
    counts1 = balance_shards(src, dst1, num_shards=4)

    dstN = str(tmp_path / "dstN")
    results = ThreadGroupCommunicator.spawn(
        world, lambda comm: balance_shards(src, dstN, 4, comm=comm))
    for counts in results:
        assert counts == counts1
    assert sum(counts1.values()) == total
    # Same rows overall, and per-shard counts match the single-rank run.
    assert sorted(_collect_ids(get_all_parquets_under(dstN))) == \
        sorted(_collect_ids(get_all_parquets_under(dst1)))
    for name, n in counts1.items():
        assert get_num_samples_of_parquet(os.path.join(dstN, name)) == n


def test_generate_num_samples_cache(tmp_path):
    src = str(tmp_path / "src")
    _write_unbalanced(src, [5, 8])
    counts = generate_num_samples_cache(src)
    assert counts == {"part.0.parquet": 5, "part.1.parquet": 8}
    assert read_num_samples_cache(src) == counts


def test_balance_validates_input(tmp_path):
    with pytest.raises(ValueError):
        balance_shards(str(tmp_path / "empty"), str(tmp_path / "o"), 2)
    src = str(tmp_path / "src")
    _write_unbalanced(src, [5])
    with pytest.raises(ValueError):
        balance_shards(src, str(tmp_path / "o"), 0)
    # More shards than samples is a user error, not silent zero-shards.
    with pytest.raises(ValueError, match="at least one sample"):
        balance_shards(src, str(tmp_path / "o2"), 9)
    # Dirty output dir refused.
    dst = str(tmp_path / "dst")
    balance_shards(src, dst, 2)
    with pytest.raises(ValueError, match="already contains"):
        balance_shards(src, dst, 2)


def test_balance_drained_output_file_removed(tmp_path):
    """A shard forced to give away rows it had staged to disk must not
    leave a stale shard file behind."""
    src = str(tmp_path / "src")
    # Heavy skew: shard 1 (file part.1) starts huge, must both receive
    # custody (leftover writes) and later drain in multi-iteration runs.
    _write_unbalanced(src, [1, 60, 1, 2])
    dst = str(tmp_path / "dst")
    counts = balance_shards(src, dst, num_shards=4)
    on_disk = sorted(os.listdir(dst))
    expected = sorted(list(counts.keys())
                      + [".num_samples.json", ".manifest.json"])
    assert on_disk == expected
    for name, n in counts.items():
        assert get_num_samples_of_parquet(os.path.join(dst, name)) == n


class _MetaComm:
    """Communicator stub on which no transfer is ever owned: every _Shard
    operation runs metadata-only, so plans can be property-tested without
    parquet I/O (exactly what a non-owner rank executes)."""
    world_size = 1 << 30
    rank = world_size - 1  # unreachable transfer index -> never an owner

    def barrier(self):
        pass


def _plan(sizes, num_shards, stats=None):
    from lddl_tpu.balance.balancer import (_Shard, _converge,
                                           compute_targets)
    from lddl_tpu.utils.types import File
    files = [File("mem://{}".format(i), n) for i, n in enumerate(sizes)]
    total = sum(sizes)
    targets = compute_targets(total, num_shards)
    shards = [_Shard(i, files[i::num_shards], "mem://", stats=stats)
              for i in range(num_shards)]
    iters = _converge(shards, targets, _MetaComm())
    return shards, targets, iters


def _random_sizes(g):
    """Adversarial file-count scenarios: giant+empties, uniform, zipf-ish,
    totals straddling the ±1 boundary."""
    kind = int(g.integers(0, 4))
    n_files = int(g.integers(1, 40))
    if kind == 0:  # one giant file + many (near-)empty files
        sizes = [int(g.integers(0, 3)) for _ in range(n_files)]
        sizes[int(g.integers(0, n_files))] = int(g.integers(10_000, 1_000_000))
    elif kind == 1:  # uniform-ish
        sizes = [int(g.integers(0, 200)) for _ in range(n_files)]
    elif kind == 2:  # heavy-tailed
        sizes = [int(g.pareto(0.8) * 50) for _ in range(n_files)]
    else:  # totals straddling the boundary: k*s + r for tiny r
        n_shards_hint = int(g.integers(1, 13))
        k = int(g.integers(1, 50))
        r = int(g.integers(0, 2)) * int(g.integers(1, n_shards_hint + 1))
        total = k * n_shards_hint + min(r, n_shards_hint - 1)
        sizes = []
        left = total
        for _ in range(n_files - 1):
            take = int(g.integers(0, left + 1)) if left else 0
            sizes.append(take)
            left -= take
        sizes.append(left)
    return sizes


@pytest.mark.parametrize("seed", range(40))
def test_balance_plan_property(seed):
    """Any skew converges within the iteration bound to exact targets,
    and the implied I/O stays within a small multiple of a full pass."""
    g = np.random.default_rng(seed)
    sizes = _random_sizes(g)
    total = sum(sizes)
    num_shards = int(g.integers(1, 13))
    if total < num_shards:
        sizes.append(num_shards - total)
        total = sum(sizes)
    stats = {}
    shards, targets, iters = _plan(sizes, num_shards, stats=stats)
    assert iters <= 1  # single grouped sweep converges for any skew
    assert [s.num_samples for s in shards] == targets
    assert max(targets) - min(targets) <= 1
    # I/O quantification: reads of original rows never exceed one full
    # pass; re-reads (output-file append churn) stay within one extra
    # pass. The reference's pair-halving scheme is O(log skew) barrier
    # iterations with whole-shard re-reads each; ours is one sweep.
    assert stats.get("rows_read", 0) <= total
    assert stats.get("rows_reread", 0) <= total
    assert stats.get("rows_written", 0) <= 3 * total


def test_balance_plan_giant_plus_empties():
    stats = {}
    sizes = [0] * 30 + [100_000] + [1] * 5
    shards, targets, iters = _plan(sizes, 12, stats=stats)
    assert [s.num_samples for s in shards] == targets
    assert iters == 1  # grouped exact transfers: one sweep
    assert stats["rows_read"] <= sum(sizes)
    # The giant is loaded once for all 11 destinations: no leftover churn.
    assert stats.get("rows_reread", 0) <= sum(sizes) // 4


def test_balance_plan_straddle_boundary():
    # total = 7*5 + 4: four shards get base+1.
    sizes = [39]
    shards, targets, iters = _plan(sizes, 5)
    assert sorted(targets) == [7, 8, 8, 8, 8]
    assert [s.num_samples for s in shards] == targets


def test_balance_e2e_stress_giant_file(tmp_path):
    """Real-parquet stress: one giant + empties + tinies; exact counts,
    exact content multiset, recorded I/O stats."""
    src = str(tmp_path / "src")
    sizes = [0, 0, 2000, 1, 0, 3, 2, 0, 1, 1]
    total = _write_unbalanced(src, sizes)
    dst = str(tmp_path / "dst")
    stats = {}
    counts = balance_shards(src, dst, num_shards=8, stats=stats)
    vals = sorted(counts.values())
    assert sum(vals) == total and vals[-1] - vals[0] <= 1
    assert sorted(_collect_ids(get_all_parquets_under(src))) == \
        sorted(_collect_ids(get_all_parquets_under(dst)))
    assert stats["rows_read"] <= total
    assert stats["rows_written"] <= 6 * total


def test_balance_stats_match_across_ranks(tmp_path):
    """The stats are plan-implied and must be identical on every rank."""
    src = str(tmp_path / "src")
    _write_unbalanced(src, [23, 1, 64, 9, 0, 41, 13])
    out_dir = str(tmp_path / "dstN")  # shared by all ranks (SPMD contract)

    def run(comm):
        stats = {}
        balance_shards(src, out_dir, 4, comm=comm, stats=stats)
        return stats

    all_stats = ThreadGroupCommunicator.spawn(3, run)
    assert all(s == all_stats[0] for s in all_stats)
    assert all_stats[0]["rows_read"] > 0
