"""In-kernel thread pool (ABI v8): byte identity across thread counts.

PR 18 partitions the native engine's document/bucket ranges across
``LDDL_TPU_NATIVE_THREADS`` worker threads into per-thread output arenas
stitched back into the flat-segment ABI. Because the Philox replay is
per-sample-keyed and the pair streams per-document-keyed, partitioning
must be byte-invisible: 1-thread and N-thread runs emit identical arrays
in process and identical shards + manifests end to end. These tests pin
that, the thread refusal ladder (env parsing, kMaxThreads cap, n_items
clamp), torn-partition edges (empty slice, single giant document, more
threads than documents), and the busy-time telemetry counters.
"""

import hashlib
import os

import numpy as np
import pytest

from lddl_tpu import native
from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer
from lddl_tpu.preprocess.bert import TokenizerInfo
from lddl_tpu.utils import rng as lrng

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native engine did not build")

from test_native import DOCS  # noqa: E402  (shared corpus fixture)

from lddl_tpu.utils.cpus import usable_cpu_count  # noqa: E402

THREAD_COUNTS = sorted({1, 2, 4, usable_cpu_count()})


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("tvocab") / "vocab.txt"
    return build_wordpiece_vocab(DOCS * 3, str(path), vocab_size=400)


@pytest.fixture(scope="module")
def hf_tokenizer(vocab_file):
    return get_tokenizer(vocab_file=vocab_file)


@pytest.fixture()
def corpus_dir(tmp_path):
    source = tmp_path / "corpus" / "source"
    source.mkdir(parents=True)
    with open(source / "0.txt", "w", encoding="utf-8") as f:
        for i, d in enumerate(DOCS * 4):
            if d.strip():
                f.write("doc-{} {}\n".format(i, d.replace("\n", " ")
                                             .replace("\r", " ")
                                             .replace("\t", " ")
                                             .replace("\x00", "")))
    return str(tmp_path / "corpus")


def _tree_hashes(out_dir):
    """Digest EVERY output file — shards AND dotfile manifests — so a
    thread count that perturbed row ordering, shard sizing, or manifest
    contents (not just id payloads) is caught."""
    digests = {}
    for root, dirs, files in os.walk(out_dir):
        dirs.sort()
        for name in sorted(files):
            path = os.path.join(root, name)
            with open(path, "rb") as f:
                digests[os.path.relpath(path, out_dir)] = hashlib.sha256(
                    f.read()).hexdigest()
    return digests


# ---------------------------------------------------------------------------
# In-process kernel byte identity at every entry point
# ---------------------------------------------------------------------------


def _assert_same_arrays(ref, got, label):
    assert len(ref) == len(got)
    for i, (r, g) in enumerate(zip(ref, got)):
        if r is None or g is None:
            assert r is None and g is None
            continue
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg="{}[{}]".format(label, i))


def test_tokenize_docs_identity_across_threads(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d for d in DOCS if d.strip()] * 6
    nat.set_threads(1)
    ref = nat.tokenize_docs(texts)
    for nt in THREAD_COUNTS[1:] + [7]:
        nat.set_threads(nt)
        _assert_same_arrays(ref, nat.tokenize_docs(texts),
                            "tokenize@{}t".format(nt))


def test_bert_pairs_identity_across_threads(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    nat.set_threads(1)
    texts = [d for d in DOCS if d.strip()] * 4
    ids, sl, dc = nat.tokenize_docs(texts)
    ref = native.bert_pairs(ids, sl, dc, 48, 0.1, 3, 12345, 7,
                            info.cls_id, info.sep_id, threads=1)
    for nt in THREAD_COUNTS[1:] + [7]:
        got = native.bert_pairs(ids, sl, dc, 48, 0.1, 3, 12345, 7,
                                info.cls_id, info.sep_id, threads=nt)
        _assert_same_arrays(ref, got, "pairs@{}t".format(nt))


def test_fused_instances_identity_across_threads(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d for d in DOCS if d.strip()] * 4
    nat.set_threads(1)
    ref = nat.bert_instances(texts, 48, 0.1, 3, 9, 1, info.cls_id,
                             info.sep_id, want_ab=True)
    for nt in THREAD_COUNTS[1:] + [7]:
        nat.set_threads(nt)
        got = nat.bert_instances(texts, 48, 0.1, 3, 9, 1, info.cls_id,
                                 info.sep_id, want_ab=True)
        _assert_same_arrays(ref, got, "fused@{}t".format(nt))


def test_fused_masked_identity_across_threads(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d for d in DOCS if d.strip()] * 4
    key = lrng.sample_key_bytes(7, 0x3A5C, 3)

    def run():
        return nat.bert_instances_masked(
            texts, 48, 0.1, 2, 7, 3, info.cls_id, info.sep_id, key,
            info.mask_id, info.vocab_size, 0.15, 8, 48)

    nat.set_threads(1)
    ref = run()
    assert ref is not None
    for nt in THREAD_COUNTS[1:] + [7]:
        nat.set_threads(nt)
        _assert_same_arrays(ref, run(), "masked@{}t".format(nt))


def test_split_docs_identity_across_threads(hf_tokenizer):
    texts = [d for d in DOCS if d.strip()] * 5
    ref = native.split_docs(texts, threads=1)
    for nt in THREAD_COUNTS[1:] + [7]:
        _assert_same_arrays(ref, native.split_docs(texts, threads=nt),
                            "split@{}t".format(nt))


def test_mask_batch_identity_across_threads():
    g = np.random.default_rng(3)
    ids = g.integers(0, 30522, (40, 128)).astype(np.int32)
    cand = g.random((40, 128)) < 0.6
    ntp = g.integers(0, 20, 40).astype(np.int64)
    key = lrng.sample_key_bytes(7, 0x3A5C, 0)
    ref = native.mask_batch(key, ids, cand, ntp, 4, 30522, threads=1)
    assert ref is not None
    for nt in THREAD_COUNTS[1:] + [7]:
        got = native.mask_batch(key, ids, cand, ntp, 4, 30522, threads=nt)
        _assert_same_arrays(ref, got, "mask@{}t".format(nt))


# ---------------------------------------------------------------------------
# Torn-partition edges
# ---------------------------------------------------------------------------


def test_empty_input_at_width(hf_tokenizer):
    """Zero documents with a wide pool: every thread gets an empty slice;
    no crash, empty outputs."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    nat.set_threads(8)
    ids, sl, dc = nat.tokenize_docs([])
    assert len(ids) == 0 and len(sl) == 0 and len(dc) == 0
    got = nat.bert_instances([], 48, 0.1, 2, 7, 0, info.cls_id,
                             info.sep_id)
    assert all(len(a) == 0 for a in got[:4])
    assert native.split_docs([], threads=8) is not None


def test_single_giant_document_many_threads(hf_tokenizer):
    """One document, eight threads: the partitioner must hand the whole
    range to one worker (clamp to n_items) and still match 1-thread
    bytes."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    giant = [" ".join(d for d in DOCS if d.strip()) * 40]
    nat.set_threads(1)
    ref_tok = nat.tokenize_docs(giant)
    ref_inst = nat.bert_instances(giant, 48, 0.1, 2, 5, 2, info.cls_id,
                                  info.sep_id, want_ab=True)
    nat.set_threads(8)
    _assert_same_arrays(ref_tok, nat.tokenize_docs(giant), "giant-tok")
    _assert_same_arrays(ref_inst,
                        nat.bert_instances(giant, 48, 0.1, 2, 5, 2,
                                           info.cls_id, info.sep_id,
                                           want_ab=True), "giant-inst")


def test_fewer_documents_than_threads(hf_tokenizer):
    """n_docs < configured width: trailing threads get empty slices."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    texts = [d for d in DOCS if d.strip()][:3]
    nat.set_threads(1)
    ref = nat.tokenize_docs(texts)
    nat.set_threads(16)
    _assert_same_arrays(ref, nat.tokenize_docs(texts), "short-slice")


# ---------------------------------------------------------------------------
# Refusal ladder: env parsing, clamps, plan reasons
# ---------------------------------------------------------------------------


def test_resolve_threads_env_parsing(monkeypatch):
    monkeypatch.delenv("LDDL_TPU_NATIVE_THREADS", raising=False)
    assert native.resolve_threads() == 1          # unset -> serial
    monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", "")
    assert native.resolve_threads() == 1          # empty -> serial
    monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", "garbage")
    assert native.resolve_threads() == 1          # unparsable -> serial
    monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", "4")
    assert native.resolve_threads() == 4
    assert native.resolve_threads(2) == 2         # explicit beats env
    for auto in ("0", "auto", "AUTO"):
        monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", auto)
        assert native.resolve_threads() == usable_cpu_count()
    monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", "9999")
    assert native.resolve_threads() == 64         # kMaxThreads cap
    assert native.resolve_threads(-3) == 1        # floor


def test_thread_plan_reasons():
    assert native.thread_plan(4, 100) == (4, None)
    assert native.thread_plan(4, 2) == (2, "n_items")
    assert native.thread_plan(8, 1) == (1, "n_items")
    assert native.thread_plan(99, 1000) == (64, "cap")
    assert native.thread_plan(0, 10) == (1, "floor")
    assert native.thread_plan(-2, 10) == (1, "floor")
    assert native.thread_plan(1, 0) == (1, None)


def test_set_threads_clamps_in_kernel(hf_tokenizer):
    nat = TokenizerInfo(hf_tokenizer).native_tokenizer()
    nat.set_threads(4)
    assert nat.get_threads() == 4
    nat.set_threads(0)
    assert nat.get_threads() == 1
    nat.set_threads(9999)
    assert nat.get_threads() == 64


def test_tokenizer_width_follows_env(hf_tokenizer, monkeypatch):
    """A freshly constructed tokenizer (the pool-worker path: __reduce__
    args + inherited env) picks up LDDL_TPU_NATIVE_THREADS."""
    monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", "3")
    cls, args = TokenizerInfo(hf_tokenizer).native_tokenizer().__reduce__()
    assert cls(*args).get_threads() == 3


# ---------------------------------------------------------------------------
# Busy-time telemetry
# ---------------------------------------------------------------------------


def test_thread_busy_ns_accumulates(hf_tokenizer):
    nat = TokenizerInfo(hf_tokenizer).native_tokenizer()
    texts = [d for d in DOCS if d.strip()] * 6
    nat.set_threads(2)
    before = nat.thread_busy_ns()
    assert len(before) == 2                # one slot per configured thread
    assert all(v >= 0 for v in before)
    nat.tokenize_docs(texts)
    after = nat.thread_busy_ns()
    assert after[0] > before[0]            # caller thread always works
    assert all(a >= b for a, b in zip(after, before))  # cumulative
    nat.set_threads(4)
    assert len(nat.thread_busy_ns()) == 4  # follows the width


# ---------------------------------------------------------------------------
# End-to-end: shards + manifests identical across thread counts
# ---------------------------------------------------------------------------


def _run_pipeline(corpus_dir, out, tokenizer, monkeypatch, threads,
                  env=None, **kwargs):
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess
    cfg = dict(max_seq_length=48, duplicate_factor=2, masking=True,
               tokenizer_engine="native")
    cfg.update({k: kwargs.pop(k) for k in list(kwargs)
                if k in ("masking", "schema_version")})
    env = dict(env or {})
    env["LDDL_TPU_NATIVE_THREADS"] = str(threads)
    for key, value in env.items():
        monkeypatch.setenv(key, value)
    try:
        run_bert_preprocess(
            {"wikipedia": corpus_dir}, out, tokenizer,
            config=BertPretrainConfig(**cfg),
            num_blocks=3, sample_ratio=1.0, seed=7, **kwargs)
    finally:
        for key in env:
            monkeypatch.delenv(key, raising=False)
    return _tree_hashes(out)


@pytest.mark.parametrize("name,env,kwargs", [
    ("fused_masked_binned", {}, {"bin_size": 16}),
    ("staged", {"LDDL_TPU_NATIVE_FUSED": "0"}, {"bin_size": 16}),
    ("unmasked", {}, {"masking": False}),
    ("packed", {}, {"masking": False, "schema_version": 2,
                    "pack_seq_length": 64}),
])
def test_pipeline_identity_across_threads(hf_tokenizer, corpus_dir,
                                          tmp_path, monkeypatch, name, env,
                                          kwargs):
    """The headline configs (fused-masked-binned, staged, unmasked,
    offline-packed) emit byte-identical trees — shards AND manifests — at
    1 vs 4 kernel threads."""
    one = _run_pipeline(corpus_dir, str(tmp_path / "t1"), hf_tokenizer,
                        monkeypatch, 1, env=env, **dict(kwargs))
    four = _run_pipeline(corpus_dir, str(tmp_path / "t4"), hf_tokenizer,
                         monkeypatch, 4, env=env, **dict(kwargs))
    assert one == four
    assert any("parquet" in k for k in one)
    assert any(".manifest" in k for k in one)  # manifests ARE compared


def test_bart_pipeline_identity_across_threads(corpus_dir, tmp_path,
                                               monkeypatch):
    """BART's whole-bucket native split partitions across threads too;
    the emitted trees must not notice."""
    from lddl_tpu.preprocess import BartPretrainConfig, run_bart_preprocess

    def run(out, threads):
        monkeypatch.setenv("LDDL_TPU_NATIVE_THREADS", str(threads))
        try:
            run_bart_preprocess(
                {"wikipedia": corpus_dir}, out,
                config=BartPretrainConfig(target_seq_length=48),
                num_blocks=3, sample_ratio=1.0, seed=11)
        finally:
            monkeypatch.delenv("LDDL_TPU_NATIVE_THREADS", raising=False)
        return _tree_hashes(out)

    one = run(str(tmp_path / "t1"), 1)
    four = run(str(tmp_path / "t4"), 4)
    assert one == four
    assert one
