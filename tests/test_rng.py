"""RNG contract: determinism, stream independence, cross-"rank" identity."""

import numpy as np

from lddl_tpu.utils import rng as lrng


def test_world_stream_identical_across_ranks():
    # Every process constructs the world stream from (seed, epoch) alone, so
    # any two constructions agree draw-for-draw — the zero-communication
    # basis for global file shuffles and bin choices.
    a = lrng.world_rng(1234, 3)
    b = lrng.world_rng(1234, 3)
    np.testing.assert_array_equal(a.integers(0, 1 << 30, 100),
                                  b.integers(0, 1 << 30, 100))


def test_epoch_changes_stream():
    a = lrng.world_rng(1234, 3).integers(0, 1 << 30, 100)
    b = lrng.world_rng(1234, 4).integers(0, 1 << 30, 100)
    assert not np.array_equal(a, b)


def test_worker_streams_independent():
    seen = set()
    for dp_rank in range(4):
        for worker in range(3):
            g = lrng.worker_rng(7, 0, dp_rank, 4, worker, 3)
            seen.add(tuple(g.integers(0, 1 << 30, 8).tolist()))
    assert len(seen) == 12


def test_worker_stream_shared_by_tp_peers():
    # TP/PP peers pass the same dp_rank -> identical stream (identical batches).
    a = lrng.worker_rng(7, 2, 1, 4, 0, 2)
    b = lrng.worker_rng(7, 2, 1, 4, 0, 2)
    np.testing.assert_array_equal(a.integers(0, 100, 50), b.integers(0, 100, 50))


def test_world_worker_domain_separation():
    w = lrng.world_rng(7, 0).integers(0, 1 << 30, 8)
    k = lrng.worker_rng(7, 0, 0, 1, 0, 1).integers(0, 1 << 30, 8)
    assert not np.array_equal(w, k)


def test_shuffle_deterministic():
    a = lrng.shuffle(lrng.world_rng(5, 0), list(range(20)))
    b = lrng.shuffle(lrng.world_rng(5, 0), list(range(20)))
    assert a == b
    assert sorted(a) == list(range(20))
    assert a != list(range(20))


def test_choices_weighted():
    g = lrng.world_rng(5, 0)
    picks = lrng.choices(g, ["a", "b"], weights=[0.0, 1.0], k=20)
    assert picks == ["b"] * 20
    g = lrng.world_rng(5, 1)
    picks = lrng.choices(g, [0, 1, 2], weights=[1, 1, 1], k=3000)
    counts = np.bincount(picks, minlength=3)
    assert counts.min() > 800


def test_validation():
    import pytest
    with pytest.raises(ValueError):
        lrng.worker_rng(7, 0, 4, 4, 0, 1)
    with pytest.raises(ValueError):
        lrng.worker_rng(7, 0, 0, 4, 2, 2)


def test_counter_rng_frozen_goldens():
    """Literal goldens for the cross-engine SplitMix64 contract
    (utils/rng.py <-> lddl_tpu/native/lddl_native.cpp). These values are
    FROZEN: changing any constant or the draw scheme silently breaks
    reproducibility of previously preprocessed shards and the native
    engine's bit-parity — if this test fails, revert the RNG change."""
    assert lrng.stream_key(0x1DD1_0004, 12345, 7, 0, 3) == 0xC17DF576A6874A87
    r = lrng.CounterRNG(0x1DD1_0004, 12345, 7, 0, 3)
    assert [r.next_u64() for _ in range(4)] == [
        0x3F34554D8373CD39, 0xFFDF8E23A2B26E7B,
        0x450657E4DF8E009C, 0xEFA7A6498DDB4959]
    r = lrng.CounterRNG(1, 2, 3)
    got = [r.uniform() for _ in range(3)]
    expected = [0.559230607239236, 0.5177942814535528, 0.6176986217129953]
    assert got == expected  # exact: same doubles, not approx
    r = lrng.CounterRNG(42)
    assert [r.randint(0, 1000) for _ in range(6)] == [686, 429, 951, 704,
                                                      26, 229]
    perm = lrng.stable_shuffle_perm(10, 0x1DD1_0005, 5, 2)
    assert perm.tolist() == [7, 9, 8, 1, 4, 6, 0, 3, 5, 2]
