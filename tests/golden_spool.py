"""Shared fixture corpus + golden hashes for the shuffle-spool equivalence
test.

The goldens pin the exact output bytes of the ORIGINAL round-2 spool layout
(one file per (bucket, block), read back in sorted-filename order —
runner.py at commit e2b143b). The two-level radix spool that replaced it
must keep producing byte-identical shards: same seeded permutation, same
rows, same parquet bytes. Regenerate only if the pipeline's *math* changes
deliberately: python tests/golden_spool.py <out.json>.
"""

import glob
import hashlib
import json
import os

import numpy as np


def build_corpus(root):
    """Deterministic 3-file, 60-doc corpus (same generator family as
    conftest.tiny_corpus but standalone so goldens never depend on test
    collection order)."""
    source = os.path.join(root, "source")
    os.makedirs(source, exist_ok=True)
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    g = np.random.Generator(np.random.Philox(key=[0, 77]))
    docs = []
    for d in range(60):
        sents = []
        for _ in range(int(g.integers(2, 9))):
            n_words = int(g.integers(4, 14))
            picks = [words[int(g.integers(0, len(words)))]
                     for _ in range(n_words)]
            sents.append(" ".join(picks).capitalize() + ".")
        docs.append("doc-{} {}".format(d, " ".join(sents)))
    for shard in range(3):
        with open(os.path.join(source, "{}.txt".format(shard)), "w") as f:
            for line in docs[shard::3]:
                f.write(line + "\n")
    return root


def build_vocab(root):
    from lddl_tpu.preprocess import build_wordpiece_vocab
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    path = os.path.join(root, "vocab.txt")
    return build_wordpiece_vocab([" ".join(words)] * 4, path, vocab_size=200)


def run_case(corpus_root, vocab_file, out_dir, binned, **kw):
    from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                     run_bert_preprocess)
    tok = get_tokenizer(vocab_file=vocab_file)
    # schema_version=1 pinned: the goldens capture the original text-only
    # shard bytes, and the v1 writer path must keep producing them
    # byte-identically (v2 adds columns and is covered by
    # tests/test_schema_v2.py's batch-level byte-identity instead).
    cfg = BertPretrainConfig(max_seq_length=32, masking=binned,
                             schema_version=1)
    run_bert_preprocess(
        {"wikipedia": corpus_root}, out_dir, tok, config=cfg,
        num_blocks=12, sample_ratio=0.9, seed=4242,
        bin_size=8 if binned else None, global_shuffle=True, **kw)
    return hash_outputs(out_dir)


def hash_outputs(out_dir):
    out = {}
    for path in sorted(glob.glob(os.path.join(out_dir, "part.*"))):
        with open(path, "rb") as f:
            out[os.path.basename(path)] = hashlib.sha256(
                f.read()).hexdigest()
    return out


GOLDEN_FILE = os.path.join(os.path.dirname(__file__), "golden_spool.json")


def main(out_json):
    import tempfile
    goldens = {}
    with tempfile.TemporaryDirectory() as td:
        corpus = build_corpus(os.path.join(td, "corpus"))
        vocab = build_vocab(td)
        for name, binned in (("unbinned", False), ("binned_masked", True)):
            out_dir = os.path.join(td, "out_" + name)
            goldens[name] = run_case(corpus, vocab, out_dir, binned)
    with open(out_json, "w") as f:
        json.dump(goldens, f, indent=1, sort_keys=True)
    print("wrote", out_json)


if __name__ == "__main__":
    import sys
    main(sys.argv[1] if len(sys.argv) > 1 else GOLDEN_FILE)
