"""Native C++ engine: parity with the Python splitter and the HF tokenizer.

The native engine (lddl_tpu.native) replaces the preprocess hot loop
(sentence split + BERT normalize + WordPiece). Its correctness contract is
exact agreement with the Python-side semantics on BMP text, checked here
sentence-by-sentence and id-by-id.
"""

import pytest

from lddl_tpu import native
from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer
from lddl_tpu.preprocess.bert import TokenizerInfo, documents_from_texts
from lddl_tpu.preprocess.sentences import split_sentences

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native engine did not build")

DOCS = [
    "Hello world. This is a test! Dr. Smith went to Washington. "
    'He said "yes." Then left.',
    "U.S. policy changed in 1999. The E.U. responded. Prices rose 3.5 "
    "percent. Mr. J. R. Ewing agreed.",
    "Unicode: café naïve Zürich über Straße. "
    "“Quoted sentence.” Another one! "
    "中文处理测试。 Mixed 中 text.",
    "No terminator here",
    "",
    "   \t  ",
    "Ellipsis... And then? Yes!! Done. (Parenthetical. Sentence.) [Also.] "
    "'Quoted start.' Done again.",
    "Numbers 3.14 and 2.71 stay. Version 2.0 shipped! approx. thirty "
    "units. Fig. 4 shows it. Co. earnings rose.",
    "A single letter J. Smith initial. Multi dots U.S.A. next sentence "
    "Here. pp. 10-12 cited.",
    "Tabs\tand\nnewlines\rmix.  Double  spaces.   End!",
    "control\x01chars\x02here. \x00nul and � replacement. Fine.",
    "ALL CAPS SENTENCE. lowercase start stays glued? Yes and no. "
    "MixedCase Words Here.",
    # Separator / format characters where the HF fast normalizer's real
    # behavior was verified empirically: U+2028/U+2029 -> space, Cf chars
    # (soft hyphen, ZWJ, ZWSP, BOM) and C-category whitespace (NEL, VT)
    # -> removed, CJK compatibility ideograph U+F900 -> folds to U+8C48.
    "line separated. para separated here. "
    "soft­hyphen zero​width joined‍chars bom﻿mark. "
    "nelchar vtchar done. Compat 豈 ideograph.",
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    path = tmp_path_factory.mktemp("nvocab") / "vocab.txt"
    return build_wordpiece_vocab(DOCS * 3, str(path), vocab_size=400)


@pytest.fixture(scope="module")
def hf_tokenizer(vocab_file):
    return get_tokenizer(vocab_file=vocab_file)


def test_split_parity():
    got = native.split_docs(DOCS)
    for text, sents in zip(DOCS, got):
        assert sents == split_sentences(text), text


def test_split_parity_no_boundary_cases():
    cases = ["", ".", "...", "a.", "a. b", "a. B", '"a." B said.',
             "x!? Y", "e.g. something", "i.e. another", "No. 5 ranked",
             "end.)  Next", "end.” Next", "A.B.C. Next",
             # enumerators glue forward; years and mid-sentence numbers
             # still split
             "2. Grant of License. Subject to terms.",
             "It was chapter 2. Next sentence here.",
             "1999. The war ended.", "  10. Item ten. Done.",
             "123. Deep item. 1234. Year-like."]
    got = native.split_docs(cases)
    for text, sents in zip(cases, got):
        assert sents == split_sentences(text), repr(text)


def test_tokenize_parity_vs_hf(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    assert nat is not None
    ids, sent_lens, doc_counts = nat.tokenize_docs(DOCS)
    backend = hf_tokenizer._tokenizer
    k = 0
    pos = 0
    for d, text in enumerate(DOCS):
        expected_sents = [s for s in split_sentences(text)]
        kept = 0
        for s in expected_sents:
            ref = backend.encode(s, add_special_tokens=False).ids
            if not ref:
                continue
            n = int(sent_lens[k])
            assert ids[pos:pos + n].tolist() == ref, s
            k += 1
            pos += n
            kept += 1
        assert int(doc_counts[d]) == kept
    assert k == len(sent_lens) and pos == len(ids)


def test_documents_from_texts_engines_agree(hf_tokenizer):
    info = TokenizerInfo(hf_tokenizer)
    hf_docs = documents_from_texts(DOCS, hf_tokenizer, engine="hf")
    # The native engine returns zero-copy int32 numpy views per sentence
    # (same values, no per-token Python lists).
    native_docs = documents_from_texts(DOCS, info, engine="native")
    assert [[list(s) for s in d] for d in native_docs] == hf_docs


def test_no_lower_case_parity(tmp_path):
    vocab = build_wordpiece_vocab(DOCS * 2, str(tmp_path / "v.txt"),
                                  vocab_size=400, do_lower_case=False)
    tok = get_tokenizer(vocab_file=vocab, do_lower_case=False)
    info = TokenizerInfo(tok)
    nat = info.native_tokenizer()
    assert nat is not None
    backend = tok._tokenizer
    ids, sent_lens, _ = nat.tokenize_docs(DOCS)
    pos = 0
    k = 0
    for text in DOCS:
        for s in split_sentences(text):
            ref = backend.encode(s, add_special_tokens=False).ids
            if not ref:
                continue
            n = int(sent_lens[k])
            assert ids[pos:pos + n].tolist() == ref, s
            pos += n
            k += 1


def test_pair_engine_parity(hf_tokenizer):
    """The native pair-creation path must be a bit-exact replay of the
    Python engine: same instances, same order, same masking inputs."""
    from lddl_tpu.preprocess.bert import (BertPretrainConfig,
                                          instances_from_texts)
    texts = [d for d in DOCS if d.strip()] * 4
    info = TokenizerInfo(hf_tokenizer)
    cfg_native = BertPretrainConfig(max_seq_length=48, duplicate_factor=3,
                                    tokenizer_engine="native")
    cfg_hf = BertPretrainConfig(max_seq_length=48, duplicate_factor=3,
                                tokenizer_engine="hf")
    for seed, bucket in [(0, 0), (12345, 7), (99, 3)]:
        nb = instances_from_texts(list(texts), info, cfg_native, seed, bucket)
        pb = instances_from_texts(list(texts), info, cfg_hf, seed, bucket)
        assert len(nb) == len(pb) > 0
        assert nb.seq_lens.tolist() == pb.seq_lens.tolist()
        assert nb.a_lens.tolist() == pb.a_lens.tolist()
        assert nb.is_random_next.tolist() == pb.is_random_next.tolist()
        assert nb.seq_ids.tolist() == pb.seq_ids.tolist()


def test_e2e_engine_parity(hf_tokenizer, tmp_path):
    """Full preprocess runs (masked + binned) with the hf and native
    engines must write identical shard contents."""
    import pyarrow.parquet as pq
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess
    from lddl_tpu.utils.fs import get_all_parquets_under

    source = tmp_path / "corpus" / "source"
    source.mkdir(parents=True)
    with open(source / "0.txt", "w") as f:
        for i, d in enumerate(DOCS * 3):
            if d.strip():
                f.write("doc-{} {}\n".format(i, d.replace("\n", " ")
                                             .replace("\r", " ")
                                             .replace("\t", " ")
                                             .replace("\x00", "")))
    outs = {}
    for engine in ("hf", "native"):
        out = tmp_path / ("out_" + engine)
        run_bert_preprocess(
            {"wikipedia": str(tmp_path / "corpus")}, str(out), hf_tokenizer,
            config=BertPretrainConfig(max_seq_length=48, duplicate_factor=2,
                                      masking=True,
                                      tokenizer_engine=engine),
            num_blocks=3, sample_ratio=1.0, seed=7, bin_size=16)
        rows = {}
        for p in sorted(get_all_parquets_under(str(out))):
            rel = p[len(str(out)):]
            rows[rel] = pq.read_table(p).to_pylist()
        outs[engine] = rows
    assert outs["hf"] == outs["native"]
    assert sum(len(v) for v in outs["hf"].values()) > 0


def test_counter_rng_parity_goldens():
    """Pin the Python CounterRNG contract (the C++ mirror is covered by
    the engine-parity tests above; these goldens freeze the spec itself)."""
    from lddl_tpu.utils.rng import CounterRNG, stable_shuffle_perm
    r = CounterRNG(0x1DD1_0004, 1, 2, 3, 4)
    seq = [r.next_u64() for _ in range(3)]
    r2 = CounterRNG(0x1DD1_0004, 1, 2, 3, 4)
    assert [r2.next_u64() for _ in range(3)] == seq
    assert all(0.0 <= CounterRNG(i).uniform() < 1.0 for i in range(50))
    vals = [CounterRNG(9, 9, i).randint(0, 10) for i in range(200)]
    assert set(vals) == set(range(10))  # full range coverage w.h.p.
    perm = stable_shuffle_perm(16, 5, 6)
    assert sorted(perm.tolist()) == list(range(16))
    assert stable_shuffle_perm(16, 5, 6).tolist() == perm.tolist()


def test_memoization_consistency(hf_tokenizer):
    """Repeated words must tokenize identically through the memo cache."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    text = "Hello world. " * 50
    once, lens_once, _ = nat.tokenize_docs([text])
    again, lens_again, _ = nat.tokenize_docs([text])
    assert once.tolist() == again.tolist()
    assert lens_once.tolist() == lens_again.tolist()


ASTRAL_DOCS = [
    # Deseret (cased astral script): lowercases via the astral fold table.
    "Deseret \U00010400\U00010401\U00010402 text. More \U00010428 here.",
    # Astral punctuation (Aegean word separators) isolates like BMP punct.
    "words\U00010100separated\U00010101here. Next one.",
    # Astral Cf (musical format controls, tags) are removed by clean_text.
    "musical\U0001D173note\U0001D17Ahere. tag\U000E0041chars\U000E007F gone.",
    # SMP CJK extension B spaces like BMP CJK chars.
    "ext\U00020000b\U0002A6D6chars. Done.",
    # Math alphanumerics + emoji (no fold, not punct): grouped per HF rules.
    "math \U0001D400\U0001D41A symbols. emoji \U0001F600 mixed\U0001F601in.",
    # Plane-16 private use + unassigned astral codepoints.
    "private \U00100001use. unassigned \U0003FFFD cp.",
]


def test_astral_tokenize_parity_vs_hf(hf_tokenizer):
    """Above-BMP behavior matches BertTokenizerFast exactly: astral Cf/Cc
    removal, astral punctuation isolation, cased astral scripts, SMP CJK
    (ADVICE round 1: the old procedural fallback diverged here)."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    assert nat is not None
    ids, sent_lens, doc_counts = nat.tokenize_docs(ASTRAL_DOCS)
    backend = hf_tokenizer._tokenizer
    k = 0
    pos = 0
    for d, text in enumerate(ASTRAL_DOCS):
        kept = 0
        for s in split_sentences(text):
            ref = backend.encode(s, add_special_tokens=False).ids
            if not ref:
                continue
            n = int(sent_lens[k])
            assert ids[pos:pos + n].tolist() == ref, repr(s)
            k += 1
            pos += n
            kept += 1
        assert int(doc_counts[d]) == kept
    assert k == len(sent_lens) and pos == len(ids)


def test_memo_cap_does_not_change_results(hf_tokenizer):
    """A tokenizer whose memo never admits entries (cap=0 -> every word
    recomputes) produces identical ids: the cap only bounds memory."""
    import numpy as np
    from lddl_tpu.native import NativeTokenizer
    info = TokenizerInfo(hf_tokenizer)
    id_to_token = [hf_tokenizer.convert_ids_to_tokens(i)
                   for i in range(len(hf_tokenizer))]
    unk = hf_tokenizer.convert_tokens_to_ids("[UNK]")
    default = NativeTokenizer(id_to_token, unk)
    capped = NativeTokenizer(id_to_token, unk, memo_cap=0)
    ids1, lens1, counts1 = default.tokenize_docs(DOCS * 2)
    ids2, lens2, counts2 = capped.tokenize_docs(DOCS * 2)
    np.testing.assert_array_equal(ids1, ids2)
    np.testing.assert_array_equal(lens1, lens2)
    np.testing.assert_array_equal(counts1, counts2)


def test_unassigned_codepoints_kept_like_hf(hf_tokenizer):
    """Cn (unassigned) codepoints survive normalization and join words —
    Cc/Cf/Co are removed (probed against the Rust normalizer)."""
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    docs = ["a͸b stays. a­b removed. a\U0003FFFDb astral. "
            "a\U00100001b private."]
    ids, sent_lens, _ = nat.tokenize_docs(docs)
    backend = hf_tokenizer._tokenizer
    pos = 0
    for k, s in enumerate(split_sentences(docs[0])):
        ref = backend.encode(s, add_special_tokens=False).ids
        n = int(sent_lens[k])
        assert ids[pos:pos + n].tolist() == ref, repr(s)
        pos += n


@pytest.fixture(scope="module")
def learned_params():
    """Punkt params trained on a small English sample (needs nltk)."""
    pytest.importorskip("nltk")
    from lddl_tpu.preprocess.sentences import train_splitter_params
    sample = (DOCS * 4) + [
        "Mr. Smith met Dr. Jones. They agreed on No. 5. See Fig. 2 for "
        "details. The U.S. delegation left. i.e. everyone went home.",
        "The meeting ended. However, talks continued. If needed, see the "
        "appendix. We adjourned at 5 p.m. sharp. This was expected.",
    ] * 8
    return train_splitter_params(sample)


def test_learned_split_parity(learned_params):
    """The C++ learned-splitter decision procedure matches the Python one
    on real-ish docs AND on the static no-boundary edge cases."""
    from lddl_tpu.preprocess.sentences import split_sentences_learned
    blob = learned_params.serialize()
    cases = DOCS + [
        "", ".", "...", "a.", "a. b", "a. B", '"a." B said.',
        "x!? Y", "e.g. something", "i.e. another", "No. 5 ranked",
        "end.)  Next", "end.” Next", "A.B.C. Next",
        "2. Grant of License. Subject to terms.",
        "1999. The war ended.", "  10. Item ten. Done.",
        "Version v. 2.0 shipped. Mr. J. R. Ewing agreed.",
    ]
    got = native.split_docs(cases, splitter_blob=blob)
    for text, sents in zip(cases, got):
        assert sents == split_sentences_learned(text, learned_params), \
            repr(text)


def test_learned_split_fuzz_parity(learned_params):
    """Random unicode soup + sentence-ish punctuation: python and C++
    learned splitters agree byte-for-byte."""
    import numpy as np
    from lddl_tpu.preprocess.sentences import split_sentences_learned
    g = np.random.default_rng(23)
    blob = learned_params.serialize()
    vocab_words = ["mr", "dr", "No", "fig", "The", "they", "agreed",
                   "église", "café", "ẞig", "Iİı", "中文", "a", "B.",
                   "2.0", "3", "10", "...", "v.", "p.m", "(so)", '"q"',
                   "-x-", "##number##", "İstanbul",
                   # Greek final-sigma contexts: CPython lowers word-final
                   # U+03A3 to ς (context rule), which the C++ port must
                   # replicate for type equality against trained params.
                   "ΟΔΟΣ", "ΟΔΟΣ.", "ΣΟΦΙΑ", "Σ.", "ΑΣ'Σ", "abΣ"]
    puncts = [". ", "! ", "? ", ".  ", ".\t", ". “Next", " ", ", "]
    docs = []
    for _ in range(150):
        parts = []
        for _ in range(int(g.integers(3, 25))):
            parts.append(vocab_words[int(g.integers(0, len(vocab_words)))])
            parts.append(puncts[int(g.integers(0, len(puncts)))])
        docs.append("".join(parts))
    got = native.split_docs(docs, splitter_blob=blob)
    for text, sents in zip(docs, got):
        assert sents == split_sentences_learned(text, learned_params), \
            repr(text)


def test_learned_e2e_engine_parity(hf_tokenizer, learned_params, tmp_path):
    """splitter='learned' end-to-end: native and hf tokenizer engines
    produce byte-identical shards (the learned decision runs in C++ on
    one path and in Python on the other)."""
    import json
    import os
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess

    corpus = tmp_path / "corpus" / "source"
    corpus.mkdir(parents=True)
    with open(corpus / "0.txt", "w", encoding="utf-8") as f:
        for i, d in enumerate(DOCS * 3):
            if d.strip():
                f.write("doc-{} {}\n".format(i, d))

    hashes = {}
    for eng in ("native", "hf"):
        out = tmp_path / ("out_" + eng)
        run_bert_preprocess(
            {"wikipedia": str(tmp_path / "corpus")}, str(out), hf_tokenizer,
            config=BertPretrainConfig(max_seq_length=32, masking=True,
                                      tokenizer_engine=eng,
                                      splitter="learned"),
            num_blocks=4, sample_ratio=1.0, seed=777, bin_size=8)
        import hashlib
        digest = {}
        for name in sorted(os.listdir(out)):
            if "parquet" in name:
                import pyarrow.parquet as pq
                t = pq.read_table(os.path.join(out, name))
                digest[name] = hashlib.sha256(
                    json.dumps(t.to_pydict(), sort_keys=True,
                               default=str).encode()).hexdigest()
        hashes[eng] = digest
    assert hashes["native"] == hashes["hf"]
    assert any(hashes["native"])


def test_fuzz_unicode_parity_vs_hf(hf_tokenizer):
    """Random unicode soup (all planes, no surrogates) tokenizes
    identically to BertTokenizerFast."""
    import numpy as np
    g = np.random.default_rng(17)
    pools = [
        (0x20, 0x7F), (0xA0, 0x600), (0x1E00, 0x2100), (0x3000, 0xA000),
        (0xF900, 0x10000), (0x10000, 0x11000), (0x16000, 0x17000),
        (0x1D100, 0x1D800), (0x1E000, 0x1F000), (0x20000, 0x20100),
        (0x2F800, 0x2FA20), (0xE0000, 0xE0200), (0xF0000, 0xF0100),
        (0x10F000, 0x110000),
    ]
    docs = []
    for _ in range(60):
        cps = []
        for _ in range(int(g.integers(5, 60))):
            lo, hi = pools[int(g.integers(0, len(pools)))]
            cp = int(g.integers(lo, hi))
            if 0xD800 <= cp <= 0xDFFF:
                cp = 0x61
            cps.append(cp)
            if g.random() < 0.2:
                cps.append(0x20)
        docs.append("".join(map(chr, cps)) + ".")
    info = TokenizerInfo(hf_tokenizer)
    nat = info.native_tokenizer()
    ids, sent_lens, doc_counts = nat.tokenize_docs(docs)
    backend = hf_tokenizer._tokenizer
    k = 0
    pos = 0
    for d, text in enumerate(docs):
        kept = 0
        for s in split_sentences(text):
            ref = backend.encode(s, add_special_tokens=False).ids
            if not ref:
                continue
            n = int(sent_lens[k])
            assert ids[pos:pos + n].tolist() == ref, repr(s)
            k += 1
            pos += n
            kept += 1
        assert int(doc_counts[d]) == kept


def test_native_join_matches_python_fallback(hf_tokenizer):
    """The C memcpy join and the Python b''.join fallback build identical
    Arrow string columns."""
    import numpy as np
    from lddl_tpu import native as native_mod
    from lddl_tpu.preprocess.arrowcols import joined_token_strings
    info = TokenizerInfo(hf_tokenizer)
    table = info.token_byte_table()
    g = np.random.default_rng(5)
    flat, lens = [], []
    for _ in range(200):
        m = int(g.integers(0, 12))
        lens.append(m)
        flat.extend(int(g.integers(0, info.vocab_size)) for _ in range(m))
    flat = np.asarray(flat, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    a = joined_token_strings(flat, lens, table)
    orig = native_mod.join_tokens
    native_mod.join_tokens = lambda *args, **kw: None
    try:
        b = joined_token_strings(flat, lens, table)
    finally:
        native_mod.join_tokens = orig
    assert a.equals(b)
    assert a.to_pylist()[:3] == b.to_pylist()[:3]


def test_memo_cap_degenerate_values(hf_tokenizer):
    """Huge/zero memo caps must neither abort nor hang (the flat table
    clamps its pre-size; caps only ever bound memory)."""
    from lddl_tpu.native import NativeTokenizer
    id_to_token = [hf_tokenizer.convert_ids_to_tokens(i)
                   for i in range(len(hf_tokenizer))]
    unk = hf_tokenizer.convert_tokens_to_ids("[UNK]")
    ref = NativeTokenizer(id_to_token, unk).tokenize_docs(DOCS)
    for cap in (0, 1, 2**40, 2**63):
        nat = NativeTokenizer(id_to_token, unk, memo_cap=cap)
        got = nat.tokenize_docs(DOCS)
        import numpy as np
        for a, b in zip(ref, got):
            np.testing.assert_array_equal(a, b)
