"""Offline corpus-level sequence packing (preprocess/packing.py + the
loader's zero-copy prepacked path).

The load-bearing guarantees pinned here:

- FFD-packed shards carry the packed row schema, the footer pack-shape
  metadata, and the manifest ``__meta__.packed`` entry;
- packing is deterministic — byte-identical shards under reversed
  filesystem enumeration — and pure arithmetic (bounds respected);
- packed shards are SAMPLE-EQUIVALENT to the unpacked schema-v2 shards
  of the same run: the exploded sample multiset matches exactly,
  including the static-masking positions/labels bytes (masking happened
  before packing on the same frozen Philox streams);
- the loader auto-detects packed directories, streams rows zero-copy
  through BertPrepackedCollate (no load-time packing), reproduces its
  epochs deterministically, and reports pad_ratio at or below the greedy
  load-time packer's on the same corpus;
- the greedy load-time packer remains the fallback for unpacked dirs;
- the delta balancer refuses a packed-shape drift;
- the offline packer emits the pack-fill telemetry.
"""

import collections
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu import observability as obs  # noqa: E402
from lddl_tpu.loader import get_bert_pretrain_data_loader  # noqa: E402
from lddl_tpu.loader.bert import (BertPrepackedCollate,  # noqa: E402
                                  PackedBertLoader, PackedRow,
                                  decode_record_batch, packed_shape_of_dir)
from lddl_tpu.preprocess import packing as packing_mod  # noqa: E402
from lddl_tpu.resilience.io import read_table  # noqa: E402
from lddl_tpu.utils.fs import get_all_parquets_under  # noqa: E402

L_PACK = 64
P_MAX = 8


@pytest.fixture(scope="module")
def pipe(tmp_path_factory):
    """corpus -> vocab -> preprocess unpacked-v2 AND offline-packed
    (dynamic + static masking) -> balanced shards."""
    from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                     run_bert_preprocess)
    from lddl_tpu.balance import balance_shards
    root = tmp_path_factory.mktemp("packed_offline")
    corpus = gs.build_corpus(str(root / "corpus"))
    vocab = gs.build_vocab(str(root))
    tok = get_tokenizer(vocab_file=vocab)
    out = {"vocab": vocab, "tokenizer": tok, "root": root, "corpus": corpus}
    for kind, masking in (("dyn", False), ("sta", True)):
        for mode, pack in (("plain", None), ("packed", L_PACK)):
            pre = str(root / "pre_{}_{}".format(kind, mode))
            bal = str(root / "bal_{}_{}".format(kind, mode))
            run_bert_preprocess(
                {"wikipedia": corpus}, pre, tok,
                config=BertPretrainConfig(max_seq_length=32,
                                          masking=masking,
                                          duplicate_factor=2),
                num_blocks=4, sample_ratio=1.0, seed=0,
                pack_seq_length=pack, pack_max_per_row=P_MAX)
            balance_shards(pre, bal, 4)
            out[(kind, mode)] = bal
            out[(kind, mode, "pre")] = pre
    return out


def _explode_packed(paths):
    """Packed shards -> per-sample tuples (a, b, rn[, positions, labels])
    via the loader decode: the stored row content is re-split at the
    boundary columns, and the row-relative masking positions are rebased
    back to sample-relative for the comparison."""
    out = []
    for p in sorted(paths):
        for rb in read_table(p).to_batches():
            for row in decode_record_batch(rb):
                assert isinstance(row, PackedRow)
                m_off = 0
                for k in range(len(row.a_lens)):
                    al, bl = int(row.a_lens[k]), int(row.b_lens[k])
                    off = int(row.off[k])
                    tot = al + bl + 3
                    a = tuple(int(x) for x in
                              row.ids[off + 1:off + 1 + al])
                    b = tuple(int(x) for x in
                              row.ids[off + 2 + al:off + tot - 1])
                    if row.mlm_pos is not None:
                        ml = int(row.mask_lens[k])
                        pos = tuple(int(x) - off for x in
                                    row.mlm_pos[m_off:m_off + ml])
                        lab = tuple(int(x) for x in
                                    row.mlm_labels[m_off:m_off + ml])
                        m_off += ml
                        out.append((a, b, int(row.nsp[k]), pos, lab))
                    else:
                        out.append((a, b, int(row.nsp[k])))
    return out


def _explode_plain_v2(paths):
    out = []
    for p in sorted(paths):
        for rb in read_table(p).to_batches():
            for s in decode_record_batch(rb):
                a = tuple(int(x) for x in s[0])
                b = tuple(int(x) for x in s[1])
                if len(s) == 5:
                    out.append((a, b, int(s[2]),
                                tuple(int(x) for x in s[3]),
                                tuple(int(x) for x in s[4])))
                else:
                    out.append((a, b, int(s[2])))
    return out


# ------------------------------------------------------------- pure FFD


def test_ffd_pack_bounds_and_determinism():
    lengths = np.array([30, 10, 50, 64, 5, 5, 33, 31, 2, 64, 17])
    order, per_row = packing_mod.ffd_pack(lengths, 64, 4)
    assert sorted(order.tolist()) == list(range(len(lengths)))
    assert per_row.sum() == len(lengths)
    # Row bounds: token budget and max-per-row both respected.
    start = 0
    for count in per_row:
        row = order[start:start + count]
        assert len(row) <= 4
        assert lengths[row].sum() <= 64
        start += count
    # Deterministic: a second call is identical.
    order2, per_row2 = packing_mod.ffd_pack(lengths, 64, 4)
    np.testing.assert_array_equal(order, order2)
    np.testing.assert_array_equal(per_row, per_row2)
    # First-fit-DECREASING: the first row opens with the longest sample.
    assert lengths[order[0]] == 64


def test_ffd_pack_rejects_oversized_sample():
    with pytest.raises(ValueError, match="exceeds pack budget"):
        packing_mod.ffd_pack([10, 70], 64, 8)


def test_ffd_fill_at_least_streaming_first_fit(pipe):
    """FFD over the whole bucket must fill at least as tightly as the
    loader's streaming first-fit over the same lengths — the premise of
    moving packing offline."""
    from lddl_tpu.ops.packing import StreamPacker
    rng = np.random.default_rng  # noqa: F841 (keyed below, not used raw)
    lengths = []
    for p in sorted(get_all_parquets_under(pipe[("dyn", "plain")])):
        lengths.extend(int(v) for v in
                       read_table(p).column("num_tokens").to_pylist())
    lengths = np.asarray(lengths[:2000])
    order, per_row = packing_mod.ffd_pack(lengths, L_PACK, P_MAX)
    ffd_rows = len(per_row)
    packer = StreamPacker(L_PACK, emit_rows=16, max_per_row=P_MAX)
    stream_rows = 0
    for length in lengths:
        if packer.add(int(length)) is None:
            stream_rows += len(packer.emit_fullest())
            assert packer.add(int(length)) is not None
    while packer.open_rows:
        stream_rows += len(packer.emit_fullest())
    assert ffd_rows <= stream_rows


# ------------------------------------------------- shard format + meta


def test_packed_shard_structure_and_meta(pipe):
    import json
    import pyarrow.parquet as pq
    for kind, extra in (("dyn", set()),
                        ("sta", {"masked_lm_positions_ids",
                                 "masked_lm_label_ids", "pack_mask_lens"})):
        paths = get_all_parquets_under(pipe[(kind, "packed")])
        schema = pq.read_schema(paths[0])
        names = set(schema.names)
        assert {"input_ids", "pack_a_lens", "pack_b_lens",
                "pack_nsp", "num_tokens"} | extra == names
        assert packing_mod.pack_shape_of_schema(schema) == (L_PACK, P_MAX)
        with open(os.path.join(pipe[(kind, "packed")],
                               ".manifest.json")) as f:
            meta = json.load(f)["__meta__"]
        assert meta["packed"] == {"pack_seq_length": L_PACK,
                                  "pack_max_per_row": P_MAX}
        assert meta["schema_version"] == 2
        assert packed_shape_of_dir(pipe[(kind, "packed")]) == (L_PACK,
                                                               P_MAX)
        # Row invariant: every row's used tokens fit the budget, the
        # boundary columns are self-consistent, and the stored content
        # carries the [CLS]/[SEP] structure at the boundary offsets.
        tok = pipe["tokenizer"]
        cls_id = tok.convert_tokens_to_ids("[CLS]")
        sep_id = tok.convert_tokens_to_ids("[SEP]")
        t = read_table(paths[0])
        a = t.column("pack_a_lens").to_pylist()
        b = t.column("pack_b_lens").to_pylist()
        used = t.column("num_tokens").to_pylist()
        ids = t.column("input_ids").to_pylist()
        for al, bl, n, content in zip(a, b, used, ids):
            assert len(al) == len(bl) <= P_MAX
            assert sum(al) + sum(bl) + 3 * len(al) == n <= L_PACK
            assert len(content) == n
            off = 0
            for ak, bk in zip(al, bl):
                assert content[off] == cls_id
                assert content[off + 1 + ak] == sep_id
                assert content[off + ak + bk + 2] == sep_id
                off += ak + bk + 3
    assert packed_shape_of_dir(pipe[("dyn", "plain")]) is None


def test_ffd_determinism_under_reversed_fs(pipe, tmp_path, monkeypatch):
    """Packed shard bytes are a pure function of the plan: re-running the
    identical preprocess under REVERSED filesystem enumeration produces
    byte-identical part files."""
    import hashlib
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess

    def hashes(d):
        return {os.path.basename(p):
                hashlib.sha256(open(p, "rb").read()).hexdigest()
                for p in get_all_parquets_under(d)}

    want = hashes(pipe[("dyn", "packed", "pre")])
    real_walk, real_listdir = os.walk, os.listdir

    def reversed_walk(top, **kwargs):
        for dirpath, dirnames, filenames in real_walk(top, **kwargs):
            rd = list(reversed(sorted(dirnames)))
            yield dirpath, rd, list(reversed(sorted(filenames)))
            dirnames[:] = rd

    monkeypatch.setattr(os, "walk", reversed_walk)
    monkeypatch.setattr(
        os, "listdir", lambda p=".": list(reversed(sorted(real_listdir(p)))))
    redo = str(tmp_path / "redo")
    run_bert_preprocess(
        {"wikipedia": pipe["corpus"]}, redo, pipe["tokenizer"],
        config=BertPretrainConfig(max_seq_length=32, masking=False,
                                  duplicate_factor=2),
        num_blocks=4, sample_ratio=1.0, seed=0,
        pack_seq_length=L_PACK, pack_max_per_row=P_MAX)
    monkeypatch.undo()
    assert hashes(redo) == want


# ------------------------------------------------- sample equivalence


@pytest.mark.parametrize("kind", ("dyn", "sta"))
def test_packed_shards_sample_equivalent_to_unpacked(pipe, kind):
    """The acceptance pin: the packed corpus holds EXACTLY the load-time
    packer's input samples — same (a, b, nsp) multiset, and for static
    masking the same positions/labels bytes (masking ran before packing
    on the frozen Philox streams)."""
    packed = _explode_packed(
        get_all_parquets_under(pipe[(kind, "packed", "pre")]))
    plain = _explode_plain_v2(
        get_all_parquets_under(pipe[(kind, "plain", "pre")]))
    assert collections.Counter(packed) == collections.Counter(plain)
    assert len(packed) == len(plain) > 0


# ------------------------------------------------------------- loading


def test_loader_selects_prepacked_path_and_counts(pipe, tmp_path):
    loader = get_bert_pretrain_data_loader(
        pipe[("sta", "packed")], vocab_file=pipe["vocab"], batch_size=4,
        num_workers=2, base_seed=7)
    assert isinstance(loader._collate_fn, BertPrepackedCollate)
    assert not obs.enabled()
    obs.configure(dir=str(tmp_path / "metrics"))
    try:
        reg = obs.registry()
        packed0 = reg.counter("loader_decode_packed_batches_total").total()
        col0 = reg.counter("loader_decode_columnar_batches_total").total()
        batches = list(loader)
        # Deltas, not absolutes: the process-wide registry may carry
        # counts from earlier tests in the same session.
        assert reg.counter(
            "loader_decode_packed_batches_total").total() > packed0
        assert reg.counter(
            "loader_decode_columnar_batches_total").total() == col0
    finally:
        obs.disable()
    for batch in batches:
        n, width = batch["input_ids"].shape
        assert width == L_PACK
        assert batch["segments"].shape == (n, width)
        assert batch["cls_positions"].shape == (n, P_MAX)
        assert batch["next_sentence_labels"].shape == (n, P_MAX)
        # Segment ids are block-contiguous and boundary-consistent:
        # attention_mask marks exactly the used tokens.
        assert (batch["attention_mask"] == (batch["segments"] > 0)).all()


def test_offline_pad_ratio_not_worse_than_loadtime(pipe):
    loader = get_bert_pretrain_data_loader(
        pipe[("dyn", "packed")], vocab_file=pipe["vocab"], batch_size=4,
        num_workers=2, base_seed=7)
    real = slots = 0
    for batch in loader:
        real += int(batch["attention_mask"].sum())
        slots += int(batch["attention_mask"].size)
    offline_pad = 1.0 - real / slots
    lt = get_bert_pretrain_data_loader(
        pipe[("dyn", "plain")], vocab_file=pipe["vocab"], batch_size=16,
        num_workers=2, base_seed=7, pack_seq_length=L_PACK, pack_rows=4,
        pack_max_per_row=P_MAX)
    assert isinstance(lt, PackedBertLoader)  # greedy fallback survives
    for _ in lt:
        pass
    assert offline_pad <= lt.pad_ratio + 1e-9


def test_packed_loader_epochs_are_reproducible(pipe):
    kw = dict(vocab_file=pipe["vocab"], batch_size=4, num_workers=2,
              base_seed=11)
    a = get_bert_pretrain_data_loader(pipe[("sta", "packed")], **kw)
    b = get_bert_pretrain_data_loader(pipe[("sta", "packed")], **kw)
    for _ in range(2):
        batches_a, batches_b = list(a), list(b)
        assert len(batches_a) == len(batches_b) > 0
        for x, y in zip(batches_a, batches_b):
            assert sorted(x) == sorted(y)
            for key in x:
                np.testing.assert_array_equal(x[key], y[key], err_msg=key)


def test_packed_loader_validations(pipe):
    with pytest.raises(ValueError, match="packed offline at "
                                         "pack_seq_length"):
        get_bert_pretrain_data_loader(
            pipe[("dyn", "packed")], vocab_file=pipe["vocab"],
            batch_size=4, pack_seq_length=128, pack_rows=4)
    with pytest.raises(ValueError, match="return_raw_samples"):
        get_bert_pretrain_data_loader(
            pipe[("dyn", "packed")], vocab_file=pipe["vocab"],
            batch_size=4, return_raw_samples=True)
    with pytest.raises(ValueError, match="fixed_seq_lengths"):
        get_bert_pretrain_data_loader(
            pipe[("dyn", "packed")], vocab_file=pipe["vocab"],
            batch_size=4, fixed_seq_lengths=[64])


def test_prepacked_collate_refuses_plain_samples(pipe):
    collate = BertPrepackedCollate(pipe["tokenizer"], L_PACK, P_MAX)
    with pytest.raises(TypeError, match="PackedRow"):
        collate([("a b", "c d", False)])


# ------------------------------------------------------------ telemetry


def test_pack_fill_ratio_metrics(pipe, tmp_path):
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess
    assert not obs.enabled()
    obs.configure(dir=str(tmp_path / "metrics"))
    try:
        run_bert_preprocess(
            {"wikipedia": pipe["corpus"]}, str(tmp_path / "pre"),
            pipe["tokenizer"],
            config=BertPretrainConfig(max_seq_length=32, masking=False,
                                      duplicate_factor=2),
            num_blocks=4, sample_ratio=1.0, seed=0,
            pack_seq_length=L_PACK, pack_max_per_row=P_MAX)
        reg = obs.registry()
        placed = reg.counter("preprocess_pack_tokens_total").total()
        slotted = reg.counter("preprocess_pack_slot_tokens_total").total()
        assert 0 < placed <= slotted
        gauge = reg.gauge("preprocess_pack_fill_ratio").snapshot()["values"]
        assert abs(gauge[""] - placed / slotted) < 1e-9
        assert gauge[""] > 0.5  # FFD on short samples packs tightly
    finally:
        obs.disable()


# ------------------------------------------------------- delta balance


def test_delta_refuses_packed_shape_drift(pipe, tmp_path):
    from lddl_tpu.balance import delta as delta_mod
    from lddl_tpu.utils.fs import get_num_samples_of_parquet
    root = pipe[("dyn", "packed")]
    prior = {os.path.basename(p): get_num_samples_of_parquet(p)
             for p in get_all_parquets_under(root)}
    unpacked_parts = get_all_parquets_under(pipe[("dyn", "plain", "pre")])
    with pytest.raises(ValueError, match="packed row shape"):
        delta_mod.stage_delta_balance(
            root, 1, unpacked_parts, str(tmp_path / "stage"), prior=prior)


# ------------------------------------------------------- model contract


def test_packed_batch_feeds_packed_model(pipe):
    """One real offline-packed batch through one jitted packed train
    step: shapes, segments and per-slot NSP labels all line up with
    models.BertForPreTrainingPacked."""
    import jax
    from lddl_tpu.loader import to_device_batch
    from lddl_tpu.models import (BertConfig, create_train_state,
                                 make_sharded_train_step)
    from lddl_tpu.models.bert import BertForPreTrainingPacked
    from lddl_tpu.parallel import make_mesh
    loader = get_bert_pretrain_data_loader(
        pipe[("sta", "packed")], vocab_file=pipe["vocab"], batch_size=2,
        num_workers=1, base_seed=3)
    batch = next(iter(loader))
    vocab_size = -(-len(pipe["tokenizer"]) // 128) * 128
    cfg = BertConfig.tiny(vocab_size=vocab_size,
                          max_position_embeddings=L_PACK)
    mesh = make_mesh({"dp": 1}, devices=[jax.devices()[0]])
    model = BertForPreTrainingPacked(cfg)
    state, _ = create_train_state(cfg, mesh, batch, model=model)
    step = make_sharded_train_step(mesh, cfg, model=model)
    state, metrics = step(state, to_device_batch(batch, mesh), seed=0)
    assert np.isfinite(float(np.asarray(metrics["loss"])))
