"""Model + sharded train step: shapes, learning, sharding, mesh portability."""

import numpy as np
import pytest

import jax

from lddl_tpu.parallel import compat

from lddl_tpu.loader import to_device_batch
from lddl_tpu.models import (
    BertConfig,
    BertForPreTraining,
    create_train_state,
    make_sharded_train_step,
)
from lddl_tpu.models.train import make_eval_step, make_optimizer
from lddl_tpu.parallel import make_mesh


from lddl_tpu.models.testing import fake_pretrain_batch


def _fake_batch(cfg, B=8, L=32, seed=0):
    return fake_pretrain_batch(cfg.vocab_size, B, L, seed=seed)


@pytest.fixture(scope="module")
def tiny_cfg():
    return BertConfig.tiny()


def test_forward_shapes(tiny_cfg):
    model = BertForPreTraining(tiny_cfg)
    b = _fake_batch(tiny_cfg, B=2, L=16)
    variables = model.init(jax.random.PRNGKey(0), b["input_ids"],
                           b["token_type_ids"], b["attention_mask"])
    import flax.linen as nn
    mlm, nsp = model.apply(
        {"params": nn.meta.unbox(variables)["params"]},
        b["input_ids"], b["token_type_ids"], b["attention_mask"])
    assert mlm.shape == (2, 16, tiny_cfg.vocab_size)
    assert nsp.shape == (2, 2)
    assert mlm.dtype == np.float32


def test_param_shardings_on_mesh(tiny_cfg):
    mesh = make_mesh({"dp": 2, "tp": 4})
    batch = _fake_batch(tiny_cfg)
    state, shardings = create_train_state(tiny_cfg, mesh, batch)
    p = state.params
    # Column-parallel QKV/MLP shard their output dim over tp.
    assert p["layer_0"]["attention"]["query"]["kernel"].sharding.spec[-1] == "tp"
    assert p["layer_0"]["ffn"]["intermediate"]["kernel"].sharding.spec[-1] == "tp"
    # Row-parallel outputs shard their input dim.
    assert p["layer_0"]["attention"]["output"]["kernel"].sharding.spec[0] == "tp"
    assert p["layer_0"]["ffn"]["output"]["kernel"].sharding.spec[0] == "tp"
    # Vocab-sharded decoder (Megatron column-parallel logits); the
    # embedding TABLE rows ride fsdp only (absent on this mesh →
    # replicated) so the token gather partitions over the sharded ids
    # instead of embed-sharding its output (VERDICT r4 #2).
    emb_spec = p["embeddings"]["word_embeddings"]["embedding"].sharding.spec
    assert "tp" not in emb_spec, emb_spec
    assert p["mlm_decoder"]["kernel"].sharding.spec[-1] == "tp"
    # Adam mu mirrors param shardings.
    mu = state.opt_state[1][0].mu
    assert mu["layer_0"]["ffn"]["intermediate"]["kernel"].sharding.spec[-1] == "tp"


@pytest.mark.slow  # ~27s: full compile+train on CPU devices, budget-gated from tier-1
def test_no_full_vocab_table_all_gather_per_step(tiny_cfg):
    """The compiled fsdp×tp×sp train step must not all-gather the full
    [vocab, hidden] embedding table (VERDICT r4 #2: "vocab"→tp on the
    table made every step replicate it, and the embed-sharded gather
    output forced XLA into involuntary full rematerialization). With
    table rows on fsdp, the token gather partitions over the sharded
    ids; the largest gathers left are per-layer fsdp weight gathers."""
    import re
    import flax.linen as nn
    from lddl_tpu.models.bert import axis_rules_for
    from lddl_tpu.models import train as T

    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 2, "sp": 2})
    batch_np = _fake_batch(tiny_cfg, B=4, L=32)
    state, _ = create_train_state(tiny_cfg, mesh, batch_np)
    model = BertForPreTraining(tiny_cfg)
    step_fn = T._make_step_fn(model, T._resolve_batch_loss(None, -1), -1,
                              True)
    batch = to_device_batch(batch_np, mesh)
    with compat.set_mesh(mesh), nn.logical_axis_rules(axis_rules_for(mesh)):
        hlo = jax.jit(step_fn).lower(state, batch, 0).compile().as_text()
    # Match sync AND async forms: "= bf16[...] all-gather(" and
    # "= (bf16[...], bf16[...]) all-gather-start(" — the full-table shape
    # must appear on the RESULT side (between '=' and the opcode), which
    # also holds on XLA printers that omit the '%' name prefix.
    table = re.escape("{},{}]".format(tiny_cfg.vocab_size,
                                      tiny_cfg.hidden_size))
    pat = re.compile(r"= \(?[^=]*" + table + r"[^=]* all-gather(-start)?\(")
    offenders = [line.strip()[:120] for line in hlo.splitlines()
                 if pat.search(line)]
    assert not offenders, offenders


@pytest.mark.slow  # ~52s: full compile+train on CPU devices, budget-gated from tier-1
def test_train_step_learns(tiny_cfg):
    """Overfit one fixed batch: loss must drop by well over chance noise."""
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    batch_np = _fake_batch(tiny_cfg, B=8, L=32)
    opt = make_optimizer(learning_rate=3e-3, warmup_steps=5, total_steps=60)
    state, _ = create_train_state(tiny_cfg, mesh, batch_np, optimizer=opt)
    step = make_sharded_train_step(mesh, tiny_cfg)
    batch = to_device_batch(batch_np, mesh)
    first = None
    for i in range(60):
        state, metrics = step(state, batch, seed=3)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first - 2.0, (first, last)
    assert int(state.step) == 60


def test_blockwise_attention_dropout_warns():
    """ring/flash skip attention-prob dropout; configuring both must warn
    (silent model drift otherwise), and dropout 0 must stay silent."""
    import warnings
    for impl in ("ring", "flash"):
        with pytest.warns(UserWarning, match="skips attention-probability"):
            BertConfig.tiny(attention_impl=impl, attention_dropout=0.1)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            BertConfig.tiny(attention_impl=impl, attention_dropout=0.0)


@pytest.mark.slow  # ~80s: full compile+train on CPU devices, budget-gated from tier-1
def test_multi_step_matches_single_steps(tiny_cfg):
    """make_sharded_multi_step(N) over stacked batches is bit-equivalent to
    N sequential single steps with the same seed (the scanned body folds
    the seed with state.step exactly like the single-step path)."""
    from lddl_tpu.loader import to_device_step_batches
    from lddl_tpu.models import make_sharded_multi_step

    mesh = make_mesh({"dp": 2, "fsdp": 2, "sp": 2})
    n = 4
    batches_np = [_fake_batch(tiny_cfg, B=8, L=32, seed=100 + i)
                  for i in range(n)]
    opt = make_optimizer(warmup_steps=2, total_steps=20)

    state, _ = create_train_state(tiny_cfg, mesh, batches_np[0],
                                  optimizer=opt)
    step = make_sharded_train_step(mesh, tiny_cfg, donate=False)
    single_losses = []
    for b in batches_np:
        state, metrics = step(state, to_device_batch(b, mesh), seed=7)
        single_losses.append(float(metrics["loss"]))
    single_params = jax.device_get(state.params)

    state2, _ = create_train_state(tiny_cfg, mesh, batches_np[0],
                                   optimizer=opt)
    multi = make_sharded_multi_step(mesh, tiny_cfg, n, donate=False)
    stacked = to_device_step_batches(
        {k: np.stack([b[k] for b in batches_np]) for k in batches_np[0]},
        mesh)
    state2, metrics = multi(state2, stacked, seed=7)
    assert int(jax.device_get(state2.step)) == n
    multi_losses = [float(x) for x in jax.device_get(metrics["loss"])]
    assert np.allclose(multi_losses, single_losses, rtol=1e-5, atol=1e-6), (
        multi_losses, single_losses)
    for a, b in zip(jax.tree.leaves(single_params),
                    jax.tree.leaves(jax.device_get(state2.params))):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-5, atol=1e-6)


def test_attention_auto_selection(tiny_cfg):
    """attention_impl="auto" (the default) resolves by the measured rule —
    flash at L >= 256 (round-5 single-block kernels win/tie there, incl.
    the reference's L=512 headline) with attention_dropout == 0 and a
    blockwise-compatible call — and at the shortest bins produces
    bit-identical outputs to explicit dense (it IS dense there)."""
    from lddl_tpu.models.attention import resolve_auto_impl
    from lddl_tpu.models.bert import BertForPreTraining

    assert resolve_auto_impl(128, True, 0.0, head_dim=64) == "dense"
    assert resolve_auto_impl(256, True, 0.0, head_dim=64) == "flash"
    assert resolve_auto_impl(512, True, 0.0, head_dim=64) == "flash"
    # the former in-between band: single-block kernels extended to
    # l_pad <= 896 with one-row cells (1.71x kernel-level over dense at
    # L=768, FLASH_ATTENTION_BENCH.json; 46.4 vs 38.7 MFU in-model)
    assert resolve_auto_impl(768, True, 0.0, head_dim=64) == "flash"
    assert resolve_auto_impl(896, True, 0.0, head_dim=64) == "flash"
    assert resolve_auto_impl(1024, True, 0.0, head_dim=64) == "flash"
    # the long branch reasons in l_pad: 960 pads to 1024 (online win)
    assert resolve_auto_impl(960, True, 0.0, head_dim=64) == "flash"
    # selector mirrors the dispatcher's head-dim gate: d > 128 would
    # fall back to the (losing-at-512) online kernels, so stay dense
    assert resolve_auto_impl(512, True, 0.0, head_dim=256) == "dense"
    assert resolve_auto_impl(2048, True, 0.1, head_dim=64) == "dense"  # prob dropout
    assert resolve_auto_impl(2048, False, 0.0, head_dim=64) == "dense"  # causal/cross
    # deterministic (eval): dropout is a no-op, so flash is identical math
    # and auto may pick it even with attention_dropout > 0 (ADVICE r4).
    assert resolve_auto_impl(2048, True, 0.1, deterministic=True, head_dim=64) == "flash"
    assert resolve_auto_impl(128, True, 0.1, deterministic=True, head_dim=64) == "dense"
    assert BertConfig.tiny().attention_impl == "auto"

    batch = _fake_batch(tiny_cfg, B=4, L=64, seed=9)
    outs = {}
    for impl in ("auto", "dense"):
        cfg = BertConfig.tiny(attention_impl=impl)
        model = BertForPreTraining(cfg)
        variables = model.init(
            {"params": jax.random.PRNGKey(0)}, batch["input_ids"],
            batch["token_type_ids"], batch["attention_mask"],
            deterministic=True)
        outs[impl] = model.apply(variables, batch["input_ids"],
                                 batch["token_type_ids"],
                                 batch["attention_mask"],
                                 deterministic=True)
    np.testing.assert_array_equal(np.asarray(outs["auto"][0]),
                                  np.asarray(outs["dense"][0]))
    np.testing.assert_array_equal(np.asarray(outs["auto"][1]),
                                  np.asarray(outs["dense"][1]))


@pytest.mark.slow  # ~37s: full compile+train on CPU devices, budget-gated from tier-1
def test_mlm_gather_matches_dense_head(tiny_cfg):
    """The gathered MLM head (cfg.mlm_gather, default ON) must produce
    the same loss, metrics and updated params as the full [B, L, vocab]
    head when no row overflows the cap — unmasked logits never enter the
    loss, so gathering them away is a pure FLOP/memory cut."""
    from lddl_tpu.models.train import _mlm_gather_of, mlm_gather_cap

    mesh = make_mesh({"dp": 4, "sp": 2})
    batch_np = _fake_batch(tiny_cfg, B=8, L=64, seed=3)
    opt = make_optimizer(warmup_steps=2, total_steps=20)
    results = {}
    for gather in (True, False):
        cfg = BertConfig.tiny(mlm_gather=gather, hidden_dropout=0.0,
                              attention_dropout=0.0)
        state, _ = create_train_state(cfg, mesh, batch_np, optimizer=opt)
        step = make_sharded_train_step(mesh, cfg, donate=False)
        state, metrics = step(state, to_device_batch(batch_np, mesh), seed=7)
        results[gather] = (jax.device_get(state.params),
                           {k: float(v) for k, v in metrics.items()
                            if k != "mlm_dropped_labels"})
    assert results[True][1].keys() == results[False][1].keys()
    for k in results[False][1]:
        np.testing.assert_allclose(results[True][1][k], results[False][1][k],
                                   rtol=2e-5, atol=1e-6, err_msg=k)
    for a, b in zip(jax.tree.leaves(results[True][0]),
                    jax.tree.leaves(results[False][0])):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-5, atol=1e-6)

    # Overflow accounting: more masked labels than the cap -> the excess
    # is dropped AND reported, never silent.
    labels = np.zeros((2, 64), np.int32)  # every column masked
    cap = mlm_gather_cap(64)
    model = __import__("lddl_tpu.models.bert", fromlist=["x"]
                       ).BertForPreTraining(BertConfig.tiny())
    got = _mlm_gather_of(model, {"labels": labels})
    assert got is not None
    pos, gathered, dropped = got
    assert pos.shape == (2, cap) and gathered.shape == (2, cap)
    assert int(dropped) == 2 * (64 - cap)


def test_mlm_gather_positions_and_logit_shape(tiny_cfg):
    """Direct model.apply with masked_positions returns [B, P, vocab] and
    matches the corresponding columns of the full head's logits."""
    model = __import__("lddl_tpu.models.bert", fromlist=["x"]
                       ).BertForPreTraining(tiny_cfg)
    batch = _fake_batch(tiny_cfg, B=4, L=32, seed=5)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, batch["input_ids"],
        batch["token_type_ids"], batch["attention_mask"],
        deterministic=True)
    full, _ = model.apply(variables, batch["input_ids"],
                          batch["token_type_ids"], batch["attention_mask"],
                          deterministic=True)
    pos = np.stack([np.arange(8, dtype=np.int32)] * 4) * 2  # even columns
    sub, _ = model.apply(variables, batch["input_ids"],
                         batch["token_type_ids"], batch["attention_mask"],
                         deterministic=True, masked_positions=pos)
    assert sub.shape == (4, 8, tiny_cfg.vocab_size)
    np.testing.assert_allclose(
        np.asarray(sub),
        np.take_along_axis(np.asarray(full), pos[:, :, None], axis=1),
        rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # ~47s: full compile+train on CPU devices, budget-gated from tier-1
def test_mesh_portability_same_loss(tiny_cfg):
    """The same seed gives the same initial loss on different meshes —
    sharding must not change the math."""
    batch_np = _fake_batch(tiny_cfg, B=8, L=16, seed=5)
    losses = []
    for axes in ({"dp": 8}, {"dp": 2, "tp": 4}, {"dp": 2, "tp": 2, "sp": 2},
                 {"dp": 2, "fsdp": 2, "tp": 2}):
        mesh = make_mesh(axes)
        state, _ = create_train_state(tiny_cfg, mesh, batch_np, seed=11)
        ev = make_eval_step(mesh, tiny_cfg)
        metrics = ev(state.params, to_device_batch(batch_np, mesh))
        losses.append(float(metrics["loss"]))
    assert np.allclose(losses, losses[0], rtol=2e-2), losses


def test_attention_mask_blocks_padding(tiny_cfg):
    """Padding positions must not influence unpadded outputs."""
    model = BertForPreTraining(tiny_cfg)
    b = _fake_batch(tiny_cfg, B=2, L=16, seed=2)
    import flax.linen as nn
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), b["input_ids"],
                   b["token_type_ids"], b["attention_mask"]))["params"]
    mask = b["attention_mask"].copy()
    mask[:, 12:] = 0
    mlm1, _ = model.apply({"params": params}, b["input_ids"],
                          b["token_type_ids"], mask)
    ids2 = b["input_ids"].copy()
    ids2[:, 12:] = 1  # scramble padding content
    mlm2, _ = model.apply({"params": params}, ids2, b["token_type_ids"], mask)
    np.testing.assert_allclose(np.asarray(mlm1[:, :12]),
                               np.asarray(mlm2[:, :12]), atol=2e-2)


def _fake_bart_batch(cfg, B=4, L=24, seed=0):
    from lddl_tpu.models.testing import fake_bart_batch
    b = fake_bart_batch(cfg.vocab_size, B, L, seed=seed)
    b["attention_mask"][0, L - 5:] = 0
    b["input_ids"][0, L - 5:] = 0
    if B > 1:
        b["labels"][1, 10:] = -1  # padded targets ignored
    return b


def test_bart_forward_shapes():
    import flax.linen as nn
    from lddl_tpu.models import BartConfig, BartForPreTraining
    cfg = BartConfig.tiny()
    model = BartForPreTraining(cfg)
    b = _fake_bart_batch(cfg, B=2, L=16)
    variables = model.init(jax.random.PRNGKey(0), b["input_ids"],
                           b["attention_mask"], b["decoder_input_ids"])
    logits = model.apply({"params": nn.meta.unbox(variables)["params"]},
                         b["input_ids"], b["attention_mask"],
                         b["decoder_input_ids"])
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_bart_decoder_is_causal():
    """Changing a future decoder token must not change earlier logits."""
    import flax.linen as nn
    from lddl_tpu.models import BartConfig, BartForPreTraining
    import jax.numpy as jnp
    cfg = BartConfig.tiny(hidden_dropout=0.0, attention_dropout=0.0,
                          dtype=jnp.float32)
    model = BartForPreTraining(cfg)
    b = _fake_bart_batch(cfg, B=1, L=12)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), b["input_ids"], b["attention_mask"],
        b["decoder_input_ids"]))["params"]

    def logits_of(dec):
        return np.asarray(model.apply(
            {"params": params}, b["input_ids"], b["attention_mask"], dec,
            deterministic=True))

    base = logits_of(b["decoder_input_ids"])
    mutated = b["decoder_input_ids"].copy()
    mutated[0, 8] = (mutated[0, 8] + 1) % cfg.vocab_size
    changed = logits_of(mutated)
    np.testing.assert_allclose(base[0, :8], changed[0, :8],
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(base[0, 8:], changed[0, 8:])


@pytest.mark.slow  # ~99s: full compile+train on CPU devices, budget-gated from tier-1
def test_bart_train_step_learns():
    from lddl_tpu.models import (BartConfig, BartForPreTraining,
                                 bart_batch_loss, create_train_state,
                                 make_sharded_train_step)
    cfg = BartConfig.tiny()
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    model = BartForPreTraining(cfg)
    batch_np = _fake_bart_batch(cfg, B=4, L=32)
    state, _ = create_train_state(
        cfg, mesh, batch_np, model=model,
        optimizer=make_optimizer(learning_rate=5e-3, warmup_steps=1,
                                 total_steps=30))
    step = make_sharded_train_step(mesh, cfg, model=model,
                                   batch_loss=bart_batch_loss)
    batch = to_device_batch(batch_np, mesh)
    losses = []
    for i in range(8):
        state, metrics = step(state, batch, seed=0)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizes the fixed batch


@pytest.mark.slow  # ~82s: full compile+train on CPU devices, budget-gated from tier-1
def test_bart_loader_to_model_e2e(tmp_path):
    """Full BART path: preprocess chunks -> balance -> loader -> one
    sharded train step (the consumer the reference never had)."""
    import flax.linen as nn
    from lddl_tpu.preprocess import (build_wordpiece_vocab, get_tokenizer,
                                     run_bart_preprocess)
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.loader.bart import get_bart_pretrain_data_loader
    from lddl_tpu.models import (BartConfig, BartForPreTraining,
                                 bart_batch_loss, create_train_state,
                                 make_sharded_train_step)

    source = tmp_path / "corpus" / "source"
    source.mkdir(parents=True)
    words = "alpha beta gamma delta epsilon zeta eta theta".split()
    g = np.random.default_rng(0)
    with open(source / "0.txt", "w") as f:
        for d in range(30):
            sents = [" ".join(g.choice(words, 8)).capitalize() + "."
                     for _ in range(4)]
            f.write("doc-{} {}\n".format(d, " ".join(sents)))
    vocab = build_wordpiece_vocab([" ".join(words)] * 3,
                                  str(tmp_path / "v.txt"), vocab_size=120)
    tok = get_tokenizer(vocab_file=vocab)
    from lddl_tpu.preprocess.bart import BartPretrainConfig
    run_bart_preprocess({"w": str(tmp_path / "corpus")},
                        str(tmp_path / "pre"),
                        config=BartPretrainConfig(target_seq_length=48),
                        num_blocks=2, sample_ratio=1.0, seed=0)
    balance_shards(str(tmp_path / "pre"), str(tmp_path / "bal"), 2)
    loader = get_bart_pretrain_data_loader(
        str(tmp_path / "bal"), tokenizer=tok, batch_size=8,
        max_seq_length=64, fixed_seq_length=64, base_seed=3)
    batch_np = next(iter(loader))
    assert batch_np["input_ids"].shape[1] == 64

    # Pad model vocab up to a tp-divisible size (extra ids unused).
    cfg = BartConfig.tiny(vocab_size=((len(tok) + 7) // 8) * 8)
    mesh = make_mesh({"dp": 4, "tp": 2})
    model = BartForPreTraining(cfg)
    state, _ = create_train_state(cfg, mesh, batch_np, model=model,
                                  optimizer=make_optimizer(warmup_steps=1,
                                                           total_steps=5))
    step = make_sharded_train_step(mesh, cfg, model=model,
                                   batch_loss=bart_batch_loss)
    state, metrics = step(state, to_device_batch(batch_np, mesh), seed=0)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # ~55s: full compile+train on CPU devices, budget-gated from tier-1
def test_optimizer_mu_dtype_opt_in(tiny_cfg):
    """make_optimizer(mu_dtype=bf16) stores the first adam moment in
    bf16 (a memory-at-rest option; default stays fp32, which the on-chip
    A/B measured FASTER — STEP_PROFILE.json mu_bf16_ab_step_ms) and
    still trains."""
    import jax.numpy as jnp
    mesh = make_mesh({"dp": -1})
    batch = _fake_batch(tiny_cfg, B=8, L=32)
    for mu_dtype, expect in ((None, jnp.float32), (jnp.bfloat16,
                                                   jnp.bfloat16)):
        state, _ = create_train_state(
            tiny_cfg, mesh, batch,
            optimizer=make_optimizer(warmup_steps=1, total_steps=5,
                                     mu_dtype=mu_dtype))
        mu = state.opt_state[1][0].mu
        assert jax.tree.leaves(mu)[0].dtype == expect
        step = make_sharded_train_step(mesh, tiny_cfg)
        state, metrics = step(state, to_device_batch(batch, mesh), seed=0)
        assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # ~32s: full compile+train on CPU devices, budget-gated from tier-1
def test_fsdp_shards_params_and_optimizer(tiny_cfg):
    """With an fsdp mesh axis, weights and adam state live fully sharded
    (ZeRO-style): the 'embed' param dim maps to fsdp while the batch dim
    still rides (dp, fsdp)."""
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    batch = _fake_batch(tiny_cfg, B=8, L=32)
    state, _ = create_train_state(tiny_cfg, mesh, batch)
    p = state.params
    qkv = p["layer_0"]["attention"]["query"]["kernel"]
    assert qkv.sharding.spec[0] == "fsdp" and qkv.sharding.spec[-1] == "tp"
    # Embedding-table rows ride fsdp (embed dim replicated): the token
    # gather must come out (batch, seq)-sharded, not embed-sharded
    # (VERDICT r4 #2 — see LOGICAL_AXIS_RULES "embed_vocab").
    emb = p["embeddings"]["word_embeddings"]["embedding"]
    assert emb.sharding.spec == ("fsdp", None)
    mu = state.opt_state[1][0].mu
    assert mu["layer_0"]["attention"]["query"]["kernel"].sharding.spec[0] \
        == "fsdp"
    # The step runs and produces a finite loss.
    step = make_sharded_train_step(mesh, tiny_cfg)
    state, metrics = step(state, to_device_batch(batch, mesh), seed=0)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.parametrize("family", ("bert", "bart"))
@pytest.mark.slow  # ~119s: full compile+train on CPU devices, budget-gated from tier-1
def test_remat_same_loss_and_grads(family):
    """Rematerialized layers change memory, not math: one train step with
    remat on/off from identical init produces identical loss and params."""
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    if family == "bert":
        from lddl_tpu.models import BertConfig
        cfgs = [BertConfig.tiny(remat=r) for r in (False, True)]
        batch_np = _fake_batch(cfgs[0], B=4, L=32)
        make_kwargs = [dict() for _ in cfgs]
        models = [None, None]
    else:
        from lddl_tpu.models import (BartConfig, BartForPreTraining,
                                     bart_batch_loss)
        cfgs = [BartConfig.tiny(remat=r) for r in (False, True)]
        batch_np = _fake_bart_batch(cfgs[0], B=4, L=32)
        models = [BartForPreTraining(c) for c in cfgs]
        make_kwargs = [dict(model=m, batch_loss=bart_batch_loss)
                       for m in models]
    losses, params = [], []
    for cfg, m, kw in zip(cfgs, models, make_kwargs):
        opt = make_optimizer(warmup_steps=1, total_steps=5)
        state, _ = create_train_state(cfg, mesh, batch_np, model=m,
                                      optimizer=opt)
        step = make_sharded_train_step(mesh, cfg, **kw)
        state, metrics = step(state, to_device_batch(batch_np, mesh),
                              seed=0)
        losses.append(float(metrics["loss"]))
        params.append(jax.device_get(jax.tree.leaves(state.params)[0]))
    assert losses[0] == losses[1], losses
    np.testing.assert_array_equal(params[0], params[1])
