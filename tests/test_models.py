"""Model + sharded train step: shapes, learning, sharding, mesh portability."""

import numpy as np
import pytest

import jax

from lddl_tpu.loader import to_device_batch
from lddl_tpu.models import (
    BertConfig,
    BertForPreTraining,
    create_train_state,
    make_sharded_train_step,
)
from lddl_tpu.models.train import make_eval_step, make_optimizer
from lddl_tpu.parallel import make_mesh


from lddl_tpu.models.testing import fake_pretrain_batch


def _fake_batch(cfg, B=8, L=32, seed=0):
    return fake_pretrain_batch(cfg.vocab_size, B, L, seed=seed)


@pytest.fixture(scope="module")
def tiny_cfg():
    return BertConfig.tiny()


def test_forward_shapes(tiny_cfg):
    model = BertForPreTraining(tiny_cfg)
    b = _fake_batch(tiny_cfg, B=2, L=16)
    variables = model.init(jax.random.PRNGKey(0), b["input_ids"],
                           b["token_type_ids"], b["attention_mask"])
    import flax.linen as nn
    mlm, nsp = model.apply(
        {"params": nn.meta.unbox(variables)["params"]},
        b["input_ids"], b["token_type_ids"], b["attention_mask"])
    assert mlm.shape == (2, 16, tiny_cfg.vocab_size)
    assert nsp.shape == (2, 2)
    assert mlm.dtype == np.float32


def test_param_shardings_on_mesh(tiny_cfg):
    mesh = make_mesh({"dp": 2, "tp": 4})
    batch = _fake_batch(tiny_cfg)
    state, shardings = create_train_state(tiny_cfg, mesh, batch)
    p = state.params
    # Column-parallel QKV/MLP shard their output dim over tp.
    assert p["layer_0"]["attention"]["query"]["kernel"].sharding.spec[-1] == "tp"
    assert p["layer_0"]["intermediate"]["kernel"].sharding.spec[-1] == "tp"
    # Row-parallel outputs shard their input dim.
    assert p["layer_0"]["attention"]["output"]["kernel"].sharding.spec[0] == "tp"
    assert p["layer_0"]["ffn_output"]["kernel"].sharding.spec[0] == "tp"
    # Vocab-sharded embedding + decoder.
    assert p["embeddings"]["word_embeddings"]["embedding"].sharding.spec[0] == "tp"
    assert p["mlm_decoder"]["kernel"].sharding.spec[-1] == "tp"
    # Adam mu mirrors param shardings.
    mu = state.opt_state[1][0].mu
    assert mu["layer_0"]["intermediate"]["kernel"].sharding.spec[-1] == "tp"


def test_train_step_learns(tiny_cfg):
    """Overfit one fixed batch: loss must drop by well over chance noise."""
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    batch_np = _fake_batch(tiny_cfg, B=8, L=32)
    opt = make_optimizer(learning_rate=3e-3, warmup_steps=5, total_steps=60)
    state, _ = create_train_state(tiny_cfg, mesh, batch_np, optimizer=opt)
    step = make_sharded_train_step(mesh, tiny_cfg)
    batch = to_device_batch(batch_np, mesh)
    first = None
    for i in range(60):
        state, metrics = step(state, batch, seed=3)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first - 2.0, (first, last)
    assert int(state.step) == 60


def test_mesh_portability_same_loss(tiny_cfg):
    """The same seed gives the same initial loss on different meshes —
    sharding must not change the math."""
    batch_np = _fake_batch(tiny_cfg, B=8, L=16, seed=5)
    losses = []
    for axes in ({"dp": 8}, {"dp": 2, "tp": 4}, {"dp": 2, "tp": 2, "sp": 2}):
        mesh = make_mesh(axes)
        state, _ = create_train_state(tiny_cfg, mesh, batch_np, seed=11)
        ev = make_eval_step(mesh, tiny_cfg)
        metrics = ev(state.params, to_device_batch(batch_np, mesh))
        losses.append(float(metrics["loss"]))
    assert np.allclose(losses, losses[0], rtol=2e-2), losses


def test_attention_mask_blocks_padding(tiny_cfg):
    """Padding positions must not influence unpadded outputs."""
    model = BertForPreTraining(tiny_cfg)
    b = _fake_batch(tiny_cfg, B=2, L=16, seed=2)
    import flax.linen as nn
    params = nn.meta.unbox(
        model.init(jax.random.PRNGKey(0), b["input_ids"],
                   b["token_type_ids"], b["attention_mask"]))["params"]
    mask = b["attention_mask"].copy()
    mask[:, 12:] = 0
    mlm1, _ = model.apply({"params": params}, b["input_ids"],
                          b["token_type_ids"], mask)
    ids2 = b["input_ids"].copy()
    ids2[:, 12:] = 1  # scramble padding content
    mlm2, _ = model.apply({"params": params}, ids2, b["token_type_ids"], mask)
    np.testing.assert_allclose(np.asarray(mlm1[:, :12]),
                               np.asarray(mlm2[:, :12]), atol=2e-2)
