"""Communicator backends: thread-group SPMD semantics, mesh construction."""

import numpy as np
import pytest

from lddl_tpu.parallel import (
    LocalCommunicator,
    ThreadGroupCommunicator,
    make_mesh,
)
from lddl_tpu.parallel.mesh import data_parallel_size, mesh_data_axes


def test_local_communicator():
    c = LocalCommunicator()
    assert c.rank == 0 and c.world_size == 1
    c.barrier()
    np.testing.assert_array_equal(c.allreduce_sum([1, 2]), [1, 2])


def test_thread_group_allreduce():
    def body(comm):
        local = np.arange(4) + comm.rank
        total = comm.allreduce_sum(local)
        mx = comm.allreduce_max([comm.rank])
        comm.barrier()
        return total, mx

    results = ThreadGroupCommunicator.spawn(4, body)
    expected_sum = np.arange(4) * 4 + sum(range(4))
    for total, mx in results:
        np.testing.assert_array_equal(total, expected_sum)
        assert mx[0] == 3


def test_thread_group_error_propagates():
    def body(comm):
        if comm.rank == 1:
            raise RuntimeError("boom")
        comm.barrier()

    with pytest.raises(RuntimeError, match="boom"):
        ThreadGroupCommunicator.spawn(3, body)


def test_make_mesh_8_devices():
    mesh = make_mesh({"dp": 2, "tp": 4})
    assert mesh.shape == {"dp": 2, "tp": 4}
    assert data_parallel_size(mesh) == 2
    assert mesh_data_axes(mesh) == ("dp",)

    mesh = make_mesh({"dp": -1, "tp": 2})
    assert mesh.shape["dp"] == 4

    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    assert data_parallel_size(mesh) == 4

    with pytest.raises(ValueError):
        make_mesh({"dp": 3, "tp": 4})
