"""Sequence packing: packer invariants, packed forward == unpacked forward
per sample (block-diagonal attention + position restart), packed loader
e2e, packed train step (VERDICT r2 #4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lddl_tpu.ops.packing import (StreamPacker, packed_layout_arrays,
                                  round_up)


def test_stream_packer_first_fit():
    p = StreamPacker(capacity=10, emit_rows=2, max_per_row=3, horizon=2)
    assert p.add(6) == 0     # ordinals are the global stream counter
    assert p.add(5) == 1     # no room in row 0 -> new row
    assert p.add(4) == 2     # fits row 0 exactly
    assert p.add(5) == 3
    assert p.add(1) is None  # horizon full, nothing fits
    rows = p.emit_fullest()
    assert [[l for _, l in r] for r in rows] == [[6, 4], [5, 5]]
    layout = packed_layout_arrays(
        [[(0, 6), (2, 4)], [(1, 5), (3, 5)]], 10, 3)
    assert layout["pad_tokens"] == 0
    assert layout["row_of"].tolist() == [0, 1, 0, 1]
    assert layout["offset_of"].tolist() == [0, 0, 6, 5]
    # After emit the packer keeps counting globally.
    assert p.add(10) == 4
    assert p.flush() == [[(4, 10)]]
    assert p.open_rows == 0


def test_stream_packer_horizon_keeps_open_rows():
    """emit_fullest leaves nearly-empty rows open to catch later shorts."""
    p = StreamPacker(capacity=10, emit_rows=1, max_per_row=4, horizon=3)
    p.add(9)          # row 0: free 1
    p.add(5)          # row 1: free 5
    p.add(8)          # row 2: free 2
    assert p.add(7) is None
    rows = p.emit_fullest()       # fullest = row 0 (free 1)
    assert rows == [[(0, 9)]]
    assert p.open_rows == 2       # rows 1 and 2 stayed open
    assert p.add(7) is not None   # now fits a fresh row slot
    assert p.add(5) is not None   # lands in old row 1 (5 free)
    assert sorted(len(r) for r in p.flush()) == [1, 1, 2]


def test_stream_packer_max_per_row():
    p = StreamPacker(capacity=100, emit_rows=1, max_per_row=2, horizon=1)
    assert p.add(5) is not None
    assert p.add(5) is not None
    assert p.add(5) is None  # capacity left but slot cap hit


def test_stream_packer_oversize_rejected():
    p = StreamPacker(capacity=8, emit_rows=2, max_per_row=2)
    with pytest.raises(ValueError, match="exceeds pack capacity"):
        p.add(9)


def _random_samples(g, n, vocab, max_len=20):
    samples = []
    for i in range(n):
        la = int(g.integers(2, max_len))
        lb = int(g.integers(2, max_len))
        a = " ".join(vocab[int(g.integers(0, len(vocab)))] for _ in range(la))
        b = " ".join(vocab[int(g.integers(0, len(vocab)))] for _ in range(lb))
        samples.append((a, b, int(g.integers(0, 2))))
    return samples


@pytest.fixture(scope="module")
def packed_setup(tmp_path_factory):
    from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    path = tmp_path_factory.mktemp("packvocab") / "vocab.txt"
    vocab_file = build_wordpiece_vocab([" ".join(words)] * 3, str(path),
                                       vocab_size=300)
    tok = get_tokenizer(vocab_file=vocab_file)
    return words, vocab_file, tok


def test_packed_forward_matches_unpacked_per_sample(packed_setup):
    """The load-bearing property: with block-diagonal attention and
    per-sample position restart, every packed sample's MLM logits and NSP
    logits are IDENTICAL (to numerics) to running it alone."""
    from lddl_tpu.loader.bert import BertCollate, BertPackedCollate
    from lddl_tpu.models import BertConfig, BertForPreTrainingPacked
    import flax.linen as nn

    words, vocab_file, tok = packed_setup
    g = np.random.default_rng(3)
    samples = _random_samples(g, 6, words)

    L, R, P = 64, 3, 4
    packed_collate = BertPackedCollate(tok, L, R, P)
    from lddl_tpu.ops.packing import StreamPacker
    packer = StreamPacker(L, R, P)
    for s in samples:
        assert packer.add(len(s[0].split()) + len(s[1].split()) + 3) is not None
    rows = packer.flush()
    # Static-mask format not used; drive the dynamic path with a fixed rng
    # but compare LOGITS (mask-independent inputs): use the unmasked ids by
    # masking with mlm_prob=0 streams.
    packed_collate._mlm_prob = 0.0
    batch, stats = packed_collate(rows, samples,
                                  g=np.random.default_rng(0))
    assert stats["n_samples"] == 6

    cfg = BertConfig.tiny(vocab_size=len(tok), max_position_embeddings=L,
                          attention_dropout=0.0, hidden_dropout=0.0)
    model = BertForPreTrainingPacked(cfg)
    params = nn.meta.unbox(model.init(
        jax.random.PRNGKey(0), batch["input_ids"], batch["token_type_ids"],
        batch["attention_mask"], batch["segments"], batch["position_ids"],
        batch["cls_positions"], deterministic=True))["params"]
    mlm_p, nsp_p = model.apply(
        {"params": params}, batch["input_ids"], batch["token_type_ids"],
        batch["attention_mask"], batch["segments"], batch["position_ids"],
        batch["cls_positions"], deterministic=True)

    # Unpacked reference, one sample per row, same params.
    unpacked_collate = BertCollate(tok, fixed_seq_length=L)
    unpacked_collate._mlm_prob = 0.0
    ub = unpacked_collate(samples, g=np.random.default_rng(0))
    mlm_u, nsp_u = model.apply(
        {"params": params}, ub["input_ids"], ub["token_type_ids"],
        ub["attention_mask"], deterministic=True)

    layout = packed_layout_arrays(rows, L, P)
    for s_idx, s in enumerate(samples):
        length = len(s[0].split()) + len(s[1].split()) + 3
        r = int(layout["row_of"][s_idx])
        off = int(layout["offset_of"][s_idx])
        slot = int(layout["slot_of"][s_idx])
        got = np.asarray(mlm_p[r, off:off + length], np.float32)
        want = np.asarray(mlm_u[s_idx, :length], np.float32)
        np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
        got_nsp = np.asarray(nsp_p[r, slot], np.float32)
        want_nsp = np.asarray(nsp_u[s_idx], np.float32)
        np.testing.assert_allclose(got_nsp, want_nsp, rtol=5e-2, atol=5e-2)


def test_packed_flash_matches_packed_dense(packed_setup):
    """The flash kernel's in-kernel segment mask agrees with the dense
    block-diagonal bias."""
    from lddl_tpu.ops.flash_attention import flash_attention
    from lddl_tpu.ops.ring_attention import dense_attention_reference

    g = np.random.default_rng(0)
    b, l, h, d = 2, 128, 4, 32
    q = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    k = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    v = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.float32)
    seg = np.zeros((b, l), np.int32)
    seg[0, :50] = 1
    seg[0, 50:100] = 2     # two packed samples + pad tail
    seg[1, :128] = 1
    seg = jnp.asarray(seg)

    out_flash = flash_attention(q, k, v, segments=seg)
    # segments= defines both mask sides; mixing it with either is an error.
    with pytest.raises(ValueError, match="exclusive"):
        flash_attention(q, k, v, seg, segments=seg)

    # Dense reference with an explicit block-diagonal mask, per batch row.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    allowed = ((seg[:, None, :, None] == seg[:, None, None, :])
               & (seg[:, None, None, :] > 0))
    probs = jax.nn.softmax(jnp.where(allowed, scores, -1e9), axis=-1)
    out_dense = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    valid = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(out_flash)[valid], np.asarray(out_dense)[valid],
        rtol=2e-2, atol=2e-2)
    # Binary-mask compatibility: all-ones q side == old behavior.
    mask = (np.asarray(seg) > 0).astype(np.int32)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, jnp.asarray(mask))),
        np.asarray(dense_attention_reference(q, k, v, jnp.asarray(mask))),
        rtol=2e-2, atol=2e-2)


def _write_unbinned_shards(tmp_path, tok, words, n=400):
    import pyarrow as pa
    import pyarrow.parquet as pq
    g = np.random.default_rng(11)
    samples = _random_samples(g, n, words, max_len=25)
    table = pa.table({
        "A": [s[0] for s in samples],
        "B": [s[1] for s in samples],
        "is_random_next": [bool(s[2]) for s in samples],
        "num_tokens": [len(s[0].split()) + len(s[1].split()) + 3
                       for s in samples],
    })
    out = tmp_path / "shards"
    out.mkdir()
    pq.write_table(table.slice(0, n // 2), str(out / "shard-0.parquet"))
    pq.write_table(table.slice(n // 2), str(out / "shard-1.parquet"))
    return str(out)


@pytest.mark.slow  # ~39s: full compile+train on CPU devices, budget-gated from tier-1
def test_packed_loader_e2e_and_train_step(packed_setup, tmp_path):
    """Full path: shards -> packed loader -> sharded train step on a mesh;
    pad ratio far below the unpacked equivalent; no sample lost."""
    from lddl_tpu.loader import (get_bert_pretrain_data_loader,
                                 to_device_batch)
    from lddl_tpu.models import (BertConfig, BertForPreTrainingPacked,
                                 create_train_state, make_sharded_train_step)
    from lddl_tpu.models.train import make_optimizer
    from lddl_tpu.parallel import make_mesh

    words, vocab_file, tok = packed_setup
    path = _write_unbinned_shards(tmp_path, tok, words)
    L, R, P = 128, 8, 8
    loader = get_bert_pretrain_data_loader(
        path, vocab_file=vocab_file, batch_size=32, num_workers=2,
        shuffle_buffer_size=64, pack_seq_length=L, pack_rows=R,
        pack_max_per_row=P)
    batches = list(loader)
    assert loader.n_samples == 400          # nothing dropped
    assert loader.pad_ratio < 0.25, loader.pad_ratio  # tiny corpus; real
    # corpora with many samples per row pack far tighter (bench records it)
    for b in batches:
        assert b["input_ids"].shape == (R, L)
        assert b["segments"].max() <= P
        assert b["next_sentence_labels"].shape == (R, P)

    cfg = BertConfig.tiny(vocab_size=round_up(len(tok), 16),
                          max_position_embeddings=L,
                          attention_impl="dense")
    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    model = BertForPreTrainingPacked(cfg)
    state, _ = create_train_state(
        cfg, mesh, batches[0], model=model,
        optimizer=make_optimizer(warmup_steps=2, total_steps=10))
    step = make_sharded_train_step(mesh, cfg, model=model)
    state, metrics = step(state, to_device_batch(batches[0], mesh), seed=0)
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["nsp_accuracy"]) <= 1.0


def test_packed_static_masking_labels_shift_with_offsets(packed_setup,
                                                         tmp_path):
    """Statically-masked shards through the packed loader: stored
    masked_lm_positions are sample-relative, so packed labels must land at
    (row, sample_offset + position) — compare against the unpacked collate
    on the same samples."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    from lddl_tpu.ops.packing import packed_layout_arrays
    from lddl_tpu.utils.fs import serialize_np_array

    words, vocab_file, tok = packed_setup
    g = np.random.default_rng(5)
    samples = _random_samples(g, 60, words, max_len=20)
    # Build a static-mask schema by hand: mask 2 positions per sample.
    recs = []
    for a, b, nsp in samples:
        toks = a.split() + b.split()
        la = len(a.split())
        total = la + len(b.split()) + 3
        pos = sorted(int(p) for p in g.choice(
            np.arange(1, total - 1), size=2, replace=False))
        # positions index the encoded row: skip CLS/SEP slots for clarity
        lab = " ".join(words[int(g.integers(0, len(words)))] for _ in pos)
        recs.append((a, b, bool(nsp),
                     serialize_np_array(np.asarray(pos, np.int64)), lab,
                     total))
    table = pa.table({
        "A": [r[0] for r in recs], "B": [r[1] for r in recs],
        "is_random_next": [r[2] for r in recs],
        "masked_lm_positions": pa.array([r[3] for r in recs],
                                        type=pa.binary()),
        "masked_lm_labels": [r[4] for r in recs],
        "num_tokens": [r[5] for r in recs],
    })
    out = tmp_path / "static_shards"
    out.mkdir()
    pq.write_table(table.slice(0, 30), str(out / "shard-0.parquet"))
    pq.write_table(table.slice(30), str(out / "shard-1.parquet"))

    L, R, P = 128, 4, 8
    loader = get_bert_pretrain_data_loader(
        str(out), vocab_file=vocab_file, batch_size=16, num_workers=1,
        shuffle_buffer_size=16, pack_seq_length=L, pack_rows=R,
        pack_max_per_row=P)
    raw = get_bert_pretrain_data_loader(
        str(out), vocab_file=vocab_file, batch_size=16, num_workers=1,
        shuffle_buffer_size=16, return_raw_samples=True)
    from lddl_tpu.loader.bert import BertCollate
    unpacked_collate = BertCollate(tok, fixed_seq_length=L)

    # Encode every sample unpacked; match packed spans by content (packing
    # permutes stream order within a batch).
    remaining = []
    for batch in raw:
        for s in batch:
            ub = unpacked_collate([s])
            length = int(ub["attention_mask"][0].sum())
            remaining.append((ub["input_ids"][0, :length],
                              ub["labels"][0, :length]))
    n_labels_packed = 0
    matched = 0
    for batch in loader:
        for r in range(R):
            seg = batch["segments"][r]
            for slot in range(1, int(seg.max()) + 1):
                span = np.flatnonzero(seg == slot)
                if span.size == 0:
                    continue
                off, length = int(span[0]), int(span.size)
                ids = batch["input_ids"][r, off:off + length]
                labels = batch["labels"][r, off:off + length]
                hits = [i for i, (uids, _) in enumerate(remaining)
                        if uids.shape == ids.shape and (uids == ids).all()]
                assert hits, "packed span matches no unpacked sample"
                i = hits[0]
                np.testing.assert_array_equal(labels, remaining[i][1])
                del remaining[i]
                matched += 1
                n_labels_packed += int((labels != -1).sum())
    assert matched == 60 and not remaining
    assert n_labels_packed == 2 * 60  # every stored mask position landed


def test_packed_reproducible_at_fixed_worker_count(packed_setup, tmp_path):
    """Packed batches are a pure function of (seed, epoch, worker count):
    re-running with the same config is bit-identical, including the
    threaded collate (per-batch RNG streams). Worker count DOES change the
    sample stream order (round-robin service), same as the unpacked
    loader and the reference's DataLoader workers — that is config, not
    nondeterminism."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    words, vocab_file, tok = packed_setup
    path = _write_unbinned_shards(tmp_path, tok, words)

    def run(workers):
        loader = get_bert_pretrain_data_loader(
            path, vocab_file=vocab_file, batch_size=32, num_workers=workers,
            shuffle_buffer_size=64, pack_seq_length=128, pack_rows=8)
        return list(loader)

    for workers in (1, 2):
        b1, b2 = run(workers), run(workers)
        assert len(b1) == len(b2)
        for x, y in zip(b1, b2):
            for key in x:
                np.testing.assert_array_equal(x[key], y[key])
