"""Loader shard-I/O pipeline (lddl_tpu/loader/shardcache.py): ranged
backend reads, the generation-keyed read-through shard cache, prefetch
byte identity across backends and worker modes, and the fault-contract
plumbing through the threaded path.

The one invariant everything here pins: prefetch depth and cache budget
are SCHEDULING knobs — they must never change a delivered byte, only
when it was fetched.
"""

import hashlib
import os
import threading

import pytest

from lddl_tpu import observability as obs
from lddl_tpu.loader import shardcache
from lddl_tpu.resilience import backend as storage
from lddl_tpu.resilience import faults
from lddl_tpu.resilience import io as rio
from lddl_tpu.utils.types import File

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture
def mock_bk(monkeypatch):
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    return storage.get_backend()


def _metrics(monkeypatch, tmp_path):
    monkeypatch.setenv("LDDL_TPU_METRICS_DIR", str(tmp_path / "metrics"))
    obs.registry().reset()
    return obs.registry()


def _parquet_bytes(values):
    """Real (tiny) parquet bytes for column A=values."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    sink = pa.BufferOutputStream()
    pq.write_table(pa.table({"A": [str(v) for v in values]}), sink)
    return sink.getvalue().to_pybytes()


def _write_shards(root, n_shards, rows_per_shard=8):
    """n_shards local parquet files with distinct payloads; returns the
    File list the loader-side API consumes."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    files = []
    for i in range(n_shards):
        p = os.path.join(str(root), "shard-{}.parquet".format(i))
        pq.write_table(
            pa.table({"A": ["s{}r{}".format(i, r)
                            for r in range(rows_per_shard)]}), p)
        files.append(File(p, rows_per_shard))
    return files


def _column(table):
    return table.column("A").to_pylist()


# ---------------------------------------------------- ranged local reads


def test_local_ranged_get_reads_only_the_range(tmp_path, monkeypatch):
    """LocalBackend.get(start, length) must seek+read just the range —
    never fall back to a whole-file read (the footer census depends on
    this staying O(footer), not O(shard))."""
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    p = str(tmp_path / "blob")
    payload = bytes(range(256)) * 8
    with open(p, "wb") as f:
        f.write(payload)

    # Whole-file reads delegate to rio.read_bytes; the ranged path must
    # not touch it.
    def _no_full_read(path):
        raise AssertionError("ranged get fell back to a full read")
    monkeypatch.setattr(rio, "read_bytes", _no_full_read)

    preads = []
    real_pread = os.pread

    def recording_pread(fd, n, offset):
        preads.append((n, offset))
        return real_pread(fd, n, offset)
    monkeypatch.setattr(os, "pread", recording_pread)

    bk = storage.get_backend()
    assert bk.get(p, start=5, length=7) == payload[5:12]
    assert sum(n for n, _ in preads) <= 7 + 0  # never asks past the range
    assert all(off >= 5 for _, off in preads)
    # Open-ended tail read stays ranged too (lseek+read loop).
    assert bk.get(p, start=len(payload) - 3) == payload[-3:]
    # And through the retry-wrapped io helper.
    assert rio.read_range(p, 0, 4) == payload[:4]


# -------------------------------------------------------- cache semantics


def test_cache_generation_advance_never_serves_stale(mock_bk, tmp_path,
                                                     monkeypatch):
    _metrics(monkeypatch, tmp_path)
    p = str(tmp_path / "obj.parquet")
    v1 = _parquet_bytes(["old-1", "old-2"])
    v2 = _parquet_bytes(["new-1", "new-2", "new-3"])
    mock_bk.put_atomic(p, v1)

    cache = shardcache.ShardCache(1 << 20)
    assert cache.get(p) == v1          # miss -> fetch+insert
    assert cache.get(p) == v1          # hit
    mock_bk.put_atomic(p, v2)          # generation advance (maybe_refresh)
    assert cache.get(p) == v2          # version probe misses -> refetch
    assert cache.get(p) == v2
    reg = obs.registry()
    assert reg.counter("loader_shard_cache_hits_total").value() == 2
    assert reg.counter("loader_shard_cache_misses_total").value() == 2


def test_cache_eviction_respects_budget_under_concurrent_gets(
        tmp_path, monkeypatch):
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    _metrics(monkeypatch, tmp_path)
    payloads = {}
    for i in range(8):
        p = str(tmp_path / "s{}.parquet".format(i))
        payloads[p] = _parquet_bytes(["x{}y{}".format(i, r)
                                      for r in range(20)])
        with open(p, "wb") as f:
            f.write(payloads[p])
    one = len(next(iter(payloads.values())))
    budget = int(one * 3.5)  # room for 3 shards, never 4
    cache = shardcache.ShardCache(budget)

    errors = []

    def worker(order):
        try:
            for p in order:
                got = cache.get(p)
                assert got == payloads[p]
                assert cache.cached_bytes() <= budget
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    paths = sorted(payloads)
    threads = [threading.Thread(target=worker,
                                args=(paths[k:] + paths[:k],))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert cache.cached_bytes() <= budget
    assert len(cache) <= 3
    assert obs.registry().counter(
        "loader_shard_cache_evictions_total").value() > 0
    # An over-budget single shard is served but never pinned in cache.
    small = shardcache.ShardCache(10)
    p0 = paths[0]
    assert small.get(p0) == payloads[p0]
    assert small.cached_bytes() == 0


# --------------------------------------------- pipeline = sync, bytewise


def _tables_digest(files):
    h = hashlib.sha256()
    order = []
    for f, table in shardcache.shard_tables(files):
        order.append(f.path)
        h.update(repr(_column(table)).encode())
    return order, h.hexdigest()


def _pipeline_env(monkeypatch, depth, cache_bytes):
    monkeypatch.setenv("LDDL_TPU_LOADER_PREFETCH_SHARDS", str(depth))
    monkeypatch.setenv("LDDL_TPU_LOADER_CACHE_BYTES", str(cache_bytes))


def test_shard_tables_identity_local_and_mock(tmp_path, monkeypatch):
    files = _write_shards(tmp_path, 6)
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    _pipeline_env(monkeypatch, 0, 0)
    sync = _tables_digest(files)
    _pipeline_env(monkeypatch, 3, 1 << 20)
    assert _tables_digest(files) == sync      # pipeline on, cold cache
    assert _tables_digest(files) == sync      # warm cache epoch
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    _pipeline_env(monkeypatch, 0, 0)
    assert _tables_digest(files) == sync      # mock backend, sync
    _pipeline_env(monkeypatch, 3, 2 << 20)
    assert _tables_digest(files) == sync      # mock backend, pipelined


def test_shard_tables_generation_pickup_through_cache(mock_bk, tmp_path,
                                                      monkeypatch):
    p = str(tmp_path / "gen.parquet")
    mock_bk.put_atomic(p, _parquet_bytes(["gen1-a", "gen1-b"]))
    files = [File(p, 2)]
    _pipeline_env(monkeypatch, 2, 3 << 20)
    [(_, t1)] = list(shardcache.shard_tables(files))
    assert _column(t1) == ["gen1-a", "gen1-b"]
    mock_bk.put_atomic(p, _parquet_bytes(["gen2-a"]))
    [(_, t2)] = list(shardcache.shard_tables([File(p, 1)]))
    assert _column(t2) == ["gen2-a"]  # cached gen-1 entry must not serve


def test_sync_killswitch_is_plain_read_table(tmp_path, monkeypatch):
    """Depth 0 + cache 0 on the local backend is the pre-pipeline code
    path verbatim: one rio.read_table per shard, no threads, no backend
    byte-plumbing."""
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    _pipeline_env(monkeypatch, 0, 0)
    files = _write_shards(tmp_path, 2)
    calls = []
    real = rio.read_table

    def recording(path, *a, **kw):
        calls.append(path)
        return real(path, *a, **kw)
    monkeypatch.setattr(rio, "read_table", recording)
    out = list(shardcache.shard_tables(files))
    assert calls == [f.path for f in files]
    assert [_column(t) for _, t in out] == [
        ["s0r{}".format(r) for r in range(8)],
        ["s1r{}".format(r) for r in range(8)]]


def test_truncate_fault_surfaces_through_pipeline(tmp_path, monkeypatch):
    """A torn read inside a prefetcher thread must surface to the
    consumer as the same named ValueError the synchronous path raises —
    not hang, not kill the thread silently."""
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    _pipeline_env(monkeypatch, 2, 0)
    files = _write_shards(tmp_path, 3)
    faults.arm("read:truncate:nth=1")
    with pytest.raises(ValueError, match="injected truncated parquet"):
        list(shardcache.shard_tables(files))


def test_early_consumer_exit_leaks_no_threads(tmp_path, monkeypatch):
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    _pipeline_env(monkeypatch, 2, 0)
    files = _write_shards(tmp_path, 6)
    before = threading.active_count()
    gen = shardcache.shard_tables(files)
    next(gen)
    gen.close()  # mid-epoch abandon (ShuffleBuffer quota met)
    assert threading.active_count() == before


# ------------------------------------------------- footer-ranged census


def test_footer_census_is_ranged_only_on_mock(mock_bk, tmp_path,
                                              monkeypatch):
    from lddl_tpu.utils.fs import get_num_samples_of_parquet
    p = str(tmp_path / "census.parquet")
    mock_bk.put_atomic(p, _parquet_bytes(["r{}".format(i)
                                          for i in range(37)]))

    def _no_full_fetch(path):
        raise AssertionError("census fetched full shard bytes")
    monkeypatch.setattr(mock_bk, "get_versioned", _no_full_fetch)
    real_get = mock_bk.get

    def ranged_only(path, start=None, length=None):
        assert start is not None or length is not None, \
            "census issued a whole-object get"
        return real_get(path, start=start, length=length)
    monkeypatch.setattr(mock_bk, "get", ranged_only)
    assert get_num_samples_of_parquet(p) == 37


# ------------------------------------------------------ thread budgeting


def test_io_thread_count_and_pool_budget(monkeypatch):
    from lddl_tpu.utils.cpus import (loader_io_threads, pool_cpu_budget,
                                     usable_cpu_count)
    assert shardcache.io_thread_count(0) == 0
    assert shardcache.io_thread_count(2) == 3   # 2 fetchers + decode
    assert shardcache.io_thread_count(64) == \
        shardcache.MAX_FETCH_THREADS + 1
    monkeypatch.setenv("LDDL_TPU_LOADER_PREFETCH_SHARDS", "0")
    assert loader_io_threads() == 0
    monkeypatch.setenv("LDDL_TPU_LOADER_PREFETCH_SHARDS", "8")
    assert loader_io_threads() == shardcache.MAX_FETCH_THREADS + 1
    assert pool_cpu_budget() == usable_cpu_count()
    assert pool_cpu_budget(reserve=usable_cpu_count() + 10) == 1


# ------------------------------------- loader-level identity, both modes


@pytest.fixture(scope="module")
def small_pipeline(tmp_path_factory):
    """A tiny corpus -> vocab -> preprocess -> balance, just enough for
    loader-level identity digests."""
    import numpy as np
    root = tmp_path_factory.mktemp("shardcache_pipeline")
    source = root / "corpus" / "source"
    source.mkdir(parents=True)
    words = ("alpha beta gamma delta epsilon zeta eta theta iota "
             "kappa").split()
    g = np.random.Generator(np.random.Philox(key=[0, 23]))
    with open(source / "0.txt", "w") as f:
        for d in range(40):
            sents = [" ".join(words[int(g.integers(0, len(words)))]
                              for _ in range(int(g.integers(4, 10))))
                     .capitalize() + "." for _ in range(int(g.integers(2, 6)))]
            f.write("doc-{} {}\n".format(d, " ".join(sents)))
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.preprocess import (BertPretrainConfig,
                                     build_wordpiece_vocab, get_tokenizer,
                                     run_bert_preprocess)
    vocab = build_wordpiece_vocab([" ".join(words)] * 3,
                                  str(root / "vocab.txt"), vocab_size=300)
    run_bert_preprocess(
        {"wiki": str(root / "corpus")}, str(root / "pre"),
        get_tokenizer(vocab_file=vocab),
        config=BertPretrainConfig(max_seq_length=64, duplicate_factor=2,
                                  masking=True),
        num_blocks=4, sample_ratio=1.0, seed=0)
    balance_shards(str(root / "pre"), str(root / "bal"), 4)
    return {"bal": str(root / "bal"), "vocab": vocab}


def _loader_digest(path, vocab, **kw):
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        path, vocab_file=vocab, batch_size=8, **kw)
    h = hashlib.sha256()
    n = 0
    for batch in loader:
        for key in sorted(batch):
            h.update(key.encode())
            h.update(bytes(memoryview(batch[key]).cast("B")))
        n += int(batch["input_ids"].shape[0])
    return n, h.hexdigest()


@pytest.mark.parametrize("worker_mode", ["thread", "process"])
def test_loader_identity_pipeline_on_off(small_pipeline, monkeypatch,
                                         worker_mode):
    monkeypatch.delenv(storage.ENV_VAR, raising=False)
    kw = {"num_workers": 2, "worker_mode": worker_mode}
    _pipeline_env(monkeypatch, 0, 0)
    base = _loader_digest(small_pipeline["bal"], small_pipeline["vocab"],
                          **kw)
    assert base[0] > 0
    _pipeline_env(monkeypatch, 4, 4 << 20)
    assert _loader_digest(small_pipeline["bal"], small_pipeline["vocab"],
                          **kw) == base
    monkeypatch.setenv(storage.ENV_VAR, "mock")
    assert _loader_digest(small_pipeline["bal"], small_pipeline["vocab"],
                          **kw) == base
