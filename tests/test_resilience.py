"""Fault-injection harness + resilient I/O layer + shard integrity.

Fast injector-based tests (tier-1, marked ``fault``); the real
process-death chaos tests live in tests/test_chaos.py (``slow``).
"""

import errno
import json
import os
import sys

import numpy as np
import pyarrow as pa
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import golden_spool as gs  # noqa: E402

from lddl_tpu.resilience import faults  # noqa: E402
from lddl_tpu.resilience import integrity  # noqa: E402
from lddl_tpu.resilience import io as rio  # noqa: E402

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _always_disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setenv("LDDL_TPU_RETRY_BASE_DELAY_S", "0.001")
    monkeypatch.setenv("LDDL_TPU_RETRY_MAX_DELAY_S", "0.01")


# ---------------------------------------------------------------- faults


def test_fault_spec_parsing_rejects_malformed():
    with pytest.raises(faults.FaultSpecError):
        faults._parse("read")  # no kind
    with pytest.raises(faults.FaultSpecError):
        faults._parse("read:frobnicate:p=0.5")  # unknown kind
    with pytest.raises(faults.FaultSpecError):
        faults._parse("read:eio")  # neither p nor nth
    with pytest.raises(faults.FaultSpecError):
        faults._parse("read:eio:p=0.5:nth=3")  # both
    with pytest.raises(faults.FaultSpecError):
        faults._parse("read:eio:p=0.5:wat=1")  # unknown option


def test_nth_injects_exactly_once():
    faults.arm("read:eio:nth=2")
    assert faults.fault_point("read", "/x") is None
    with pytest.raises(OSError) as ei:
        faults.fault_point("read", "/x")
    assert ei.value.errno == errno.EIO
    for _ in range(5):  # nth defaults to max=1: spent
        assert faults.fault_point("read", "/x") is None


def test_probability_with_max_cap():
    faults.arm("read:estale:p=1.0:max=2")
    for _ in range(2):
        with pytest.raises(OSError) as ei:
            faults.fault_point("read", "/x")
        assert ei.value.errno == getattr(errno, "ESTALE", errno.EIO)
    assert faults.fault_point("read", "/x") is None


def test_path_substring_and_op_filters():
    faults.arm("open:eio:nth=1:path=shard-")
    assert faults.fault_point("read", "/d/shard-1") is None  # wrong op
    assert faults.fault_point("open", "/d/part-1") is None   # wrong path
    with pytest.raises(OSError):
        faults.fault_point("open", "/d/shard-1")


def test_flag_file_is_a_cross_process_once_latch(tmp_path):
    flag = str(tmp_path / "spent")
    faults.arm("read:eio:nth=1:flag={}".format(flag))
    with pytest.raises(OSError):
        faults.fault_point("read", "/x")
    assert os.path.exists(flag)  # latched for OTHER processes too
    # Re-arming (fresh counters, like a respawned worker) must not re-fire.
    faults.disarm()
    faults.arm("read:eio:nth=1:flag={}".format(flag))
    assert faults.fault_point("read", "/x") is None


def test_disarmed_fault_point_is_noop():
    assert faults.fault_point("read", "/x") is None
    assert not faults.armed()


# ------------------------------------------------------------ with_retries


def test_with_retries_heals_transient_errors():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(errno.EIO, "flaky")
        return "ok"

    assert rio.with_retries(flaky, desc="t") == "ok"
    assert len(calls) == 3


def test_with_retries_fails_immediately_on_permanent_errors():
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError(errno.ENOENT, "gone", "/x")

    with pytest.raises(FileNotFoundError):
        rio.with_retries(missing, desc="t")
    assert len(calls) == 1  # ENOENT is not transient: no retry


def test_with_retries_exhaustion_names_operation_and_attempts():
    def always():
        raise OSError(errno.EIO, "still broken")

    with pytest.raises(OSError, match="frob failed after 3 attempt"):
        rio.with_retries(always, desc="frob", attempts=3)


def test_is_transient_classification():
    assert rio.is_transient(OSError(errno.EIO, "x"))
    assert rio.is_transient(OSError(getattr(errno, "ESTALE", errno.EIO), "x"))
    assert not rio.is_transient(OSError(errno.ENOENT, "x"))
    assert not rio.is_transient(ValueError("x"))


def test_with_retries_deadline_raises_original_derived_error():
    """Deadline expiry is not a bare timeout: the raised OSError carries
    the last underlying error's errno/filename and chains from it."""
    def always():
        raise OSError(errno.EIO, "mount flapping", "/srv/x")

    with pytest.raises(OSError, match="frob failed after 1 attempt") as ei:
        rio.with_retries(always, desc="frob", attempts=99, deadline_s=0.0)
    assert ei.value.errno == errno.EIO
    assert ei.value.filename == "/srv/x"
    assert isinstance(ei.value.__cause__, OSError)
    assert "mount flapping" in str(ei.value.__cause__)


def test_with_retries_jitter_stays_in_documented_bounds(monkeypatch):
    """Backoff delay is base * 2^(attempt-1) scaled by uniform jitter in
    [0.5, 1.5] — the bounds the module documents (unkeyed on purpose, so
    retry storms desynchronize across ranks)."""
    slept = []
    monkeypatch.setattr(rio.time, "sleep", slept.append)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise OSError(errno.EIO, "flaky")
        return "ok"

    base = 0.1
    assert rio.with_retries(flaky, desc="t", attempts=10, deadline_s=3600,
                            base_delay_s=base, max_delay_s=60.0) == "ok"
    assert len(slept) == 3
    for k, delay in enumerate(slept):
        nominal = base * (2 ** k)
        assert 0.5 * nominal <= delay <= 1.5 * nominal, (k, delay)


def test_with_retries_attempts_one_means_exactly_one_call():
    calls = []

    def always():
        calls.append(1)
        raise OSError(errno.EIO, "x")

    with pytest.raises(OSError, match="after 1 attempt"):
        rio.with_retries(always, desc="t", attempts=1)
    assert len(calls) == 1


def test_fsync_dir_retries_transient_then_succeeds(tmp_path, monkeypatch):
    """A single transient EIO no longer silently skips the directory
    fsync (the durability hole): the fsync retries through the
    classifier and completes."""
    calls = []
    real_fsync = os.fsync

    def flaky_fsync(fd):
        calls.append(1)
        if len(calls) == 1:
            raise OSError(errno.EIO, "flaky dir fsync")
        return real_fsync(fd)

    monkeypatch.setattr(rio.os, "fsync", flaky_fsync)
    rio._fsync_dir(str(tmp_path / "some-file"))
    assert len(calls) == 2  # retried once, then durably synced


def test_fsync_dir_swallows_terminal_refusal(tmp_path, monkeypatch):
    """Non-transient refusals (FAT/FUSE EINVAL) stay best-effort: no
    retry storm, no exception undoing a completed replace."""
    calls = []

    def refuse(fd):
        calls.append(1)
        raise OSError(errno.EINVAL, "fsync not supported on directory")

    monkeypatch.setattr(rio.os, "fsync", refuse)
    rio._fsync_dir(str(tmp_path / "some-file"))  # must not raise
    assert len(calls) == 1  # EINVAL is not transient: no retries


def test_open_append_retries_transient_open(tmp_path):
    faults.arm("open:eio:nth=1:path=spool-a")
    f = rio.open_append(str(tmp_path / "spool-a"))
    try:
        f.write(b"x")
    finally:
        f.close()
    assert (tmp_path / "spool-a").read_bytes() == b"x"


# ------------------------------------------------------------ atomic I/O


def test_atomic_write_roundtrip_and_no_tmp_leftovers(tmp_path):
    path = str(tmp_path / "cache.json")
    rio.atomic_write(path, '{"a": 1}')
    assert json.load(open(path)) == {"a": 1}
    rio.atomic_write(path, b'{"a": 2}')
    assert json.load(open(path)) == {"a": 2}
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp." in n] == []


def test_atomic_write_failure_preserves_old_content(tmp_path):
    path = str(tmp_path / "cache.json")
    rio.atomic_write(path, "old")
    faults.arm("replace:eio:p=1.0")
    with pytest.raises(OSError):
        rio.atomic_write(path, "new", retries=False)
    faults.disarm()
    assert open(path).read() == "old"  # complete old file, never torn
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp." in n] == []


def test_atomic_write_retries_through_transient_replace_errors(tmp_path):
    path = str(tmp_path / "cache.json")
    faults.arm("replace:eio:nth=1")
    rio.atomic_write(path, "content")
    assert open(path).read() == "content"


def test_read_bytes_retries_and_truncation_injection(tmp_path):
    path = str(tmp_path / "payload.bin")
    rio.atomic_write(path, b"0123456789")
    faults.arm("open:eio:nth=1")
    assert rio.read_bytes(path) == b"0123456789"  # healed by retry
    faults.arm("read:truncate:nth=1")
    assert len(rio.read_bytes(path, retries=False)) < 10


def test_read_table_retries_transient_open_errors(tmp_path):
    path = str(tmp_path / "t.parquet")
    rio.write_table_atomic(pa.table({"x": list(range(7))}), path)
    faults.arm("open:eio:nth=1")
    assert rio.read_table(path).num_rows == 7


# ------------------------------------------------------- fs.py satellites


def test_get_num_samples_names_the_corrupt_shard(tmp_path):
    from lddl_tpu.utils.fs import get_num_samples_of_parquet
    bad = str(tmp_path / "part.0.parquet")
    with open(bad, "wb") as f:
        f.write(b"this is not parquet")
    with pytest.raises(ValueError, match="part.0.parquet"):
        get_num_samples_of_parquet(bad)


def test_get_num_samples_retries_transient_errors(tmp_path):
    from lddl_tpu.utils.fs import get_num_samples_of_parquet
    path = str(tmp_path / "part.0.parquet")
    rio.write_table_atomic(pa.table({"x": [1, 2, 3]}), path)
    faults.arm("open:eio:nth=1")
    assert get_num_samples_of_parquet(path) == 3


def test_corrupt_num_samples_cache_reads_as_absent(tmp_path):
    from lddl_tpu.utils.fs import (NUM_SAMPLES_CACHE_NAME,
                                   read_num_samples_cache)
    d = str(tmp_path)
    with open(os.path.join(d, NUM_SAMPLES_CACHE_NAME), "w") as f:
        f.write('{"torn": ')  # torn write from a crashed publisher
    assert read_num_samples_cache(d) is None


def test_num_samples_cache_staleness_on_key_mismatch(tmp_path):
    from lddl_tpu.utils.fs import num_samples_cache_is_stale
    d = str(tmp_path)
    rio.write_table_atomic(pa.table({"x": [1]}),
                           os.path.join(d, "shard-0.parquet"))
    rio.write_table_atomic(pa.table({"x": [1]}),
                           os.path.join(d, "shard-1.parquet"))
    good = {"shard-0.parquet": 1, "shard-1.parquet": 1}
    assert not num_samples_cache_is_stale(d, good)
    assert num_samples_cache_is_stale(d, {"shard-0.parquet": 1})  # missing
    assert num_samples_cache_is_stale(d, dict(good, ghost=3))     # extra
    assert num_samples_cache_is_stale(d, None)


def test_dataset_recomputes_counts_from_stale_cache(tmp_path):
    """A cache whose keys mismatch the shards on disk must be ignored
    (recompute from footers), not trusted."""
    from lddl_tpu.loader.datasets import ParquetDataset
    from lddl_tpu.utils.fs import write_num_samples_cache
    d = str(tmp_path)
    paths = []
    for i in range(2):
        p = os.path.join(d, "shard-{}.parquet".format(i))
        rio.write_table_atomic(pa.table({"x": list(range(5))}), p)
        paths.append(p)
    # Cache describes a DIFFERENT shard set with absurd counts.
    write_num_samples_cache(d, {"shard-0.parquet": 999, "ghost.parquet": 7})

    def decode(b):
        yield from b.to_pydict()["x"]

    ds = ParquetDataset(paths, decode_record_batch=decode)
    assert ds.num_samples_per_file == 5  # recomputed, not 999


# ------------------------------------------------------------- integrity


def _make_shards(d, n_shards=4, rows=6):
    paths = []
    for i in range(n_shards):
        p = os.path.join(str(d), "shard-{}.parquet".format(i))
        rio.write_table_atomic(
            pa.table({"x": [i * 100 + r for r in range(rows)]}), p)
        paths.append(p)
    return paths


def test_manifest_roundtrip_and_verify_ok(tmp_path):
    paths = _make_shards(tmp_path)
    manifest = integrity.build_manifest(str(tmp_path))
    # One entry per shard plus the reserved __meta__ block (schema
    # version record; never a parquet basename, so lookups skip it).
    assert set(manifest) == ({os.path.basename(p) for p in paths}
                             | {"__meta__"})
    assert manifest["__meta__"]["schema_version"] in (1, 2)
    on_disk = integrity.read_manifest(str(tmp_path))
    assert on_disk == manifest
    good, excluded = integrity.verify_shards(paths)
    assert good == paths and excluded == []


def test_manifest_build_is_spmd_consistent(tmp_path):
    """Rank-strided checksumming must produce the identical manifest on
    every rank (each entry computed by exactly one rank + sum-allreduce)."""
    from lddl_tpu.parallel.distributed import ThreadGroupCommunicator
    _make_shards(tmp_path, n_shards=5)
    results = ThreadGroupCommunicator.spawn(
        3, lambda comm: integrity.build_manifest(str(tmp_path), comm=comm))
    assert results[0] == results[1] == results[2]
    assert integrity.read_manifest(str(tmp_path)) == results[0]


def test_truncated_shard_fails_startup_by_name(tmp_path):
    paths = _make_shards(tmp_path)
    integrity.build_manifest(str(tmp_path))
    with open(paths[2], "r+b") as f:
        f.truncate(os.path.getsize(paths[2]) // 2)
    with pytest.raises(integrity.ShardIntegrityError, match="shard-2"):
        integrity.verify_shards(paths)


def test_truncated_shard_quarantine_excludes_exactly_it(tmp_path):
    paths = _make_shards(tmp_path)
    integrity.build_manifest(str(tmp_path))
    with open(paths[1], "r+b") as f:
        f.truncate(3)
    with pytest.warns(UserWarning, match="QUARANTINED"):
        good, excluded = integrity.verify_shards(paths,
                                                 on_corrupt="quarantine")
    assert good == [paths[0], paths[2], paths[3]]
    assert [p for p, _ in excluded] == [paths[1]]
    assert "size mismatch" in excluded[0][1]


def test_same_size_corruption_caught_by_crc(tmp_path):
    paths = _make_shards(tmp_path)
    integrity.build_manifest(str(tmp_path))
    size = os.path.getsize(paths[0])
    with open(paths[0], "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff\xfe")
    # Size check alone cannot see it...
    good, _ = integrity.verify_shards(paths, on_corrupt="quarantine",
                                      check_crc=False)
    assert good == paths
    # ...full CRC verification does.
    with pytest.warns(UserWarning, match="crc32 mismatch"):
        good, excluded = integrity.verify_shards(
            paths, on_corrupt="quarantine", check_crc=True)
    assert [p for p, _ in excluded] == [paths[0]]


def test_verify_retries_transient_stat_errors(tmp_path):
    """A transient EIO during the startup stat of a HEALTHY shard must
    not read as corruption (no spurious quarantine/refusal)."""
    paths = _make_shards(tmp_path)
    integrity.build_manifest(str(tmp_path))
    faults.arm("open:eio:nth=1")
    good, excluded = integrity.verify_shards(paths)
    assert good == paths and excluded == []


def test_verify_is_rank_strided_and_spmd_consistent(tmp_path):
    """Multi-rank verify stripes the checks and allreduces the verdicts:
    every rank must exclude the IDENTICAL shard set (a rank-divergent
    list would desync the SPMD epoch)."""
    from lddl_tpu.parallel.distributed import ThreadGroupCommunicator
    paths = _make_shards(tmp_path, n_shards=5)
    integrity.build_manifest(str(tmp_path))
    with open(paths[3], "r+b") as f:
        f.truncate(4)
    import warnings as _w

    def check(comm):
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            good, excluded = integrity.verify_shards(
                paths, on_corrupt="quarantine", comm=comm)
        return good, [p for p, _ in excluded]

    results = ThreadGroupCommunicator.spawn(3, check)
    assert results[0] == results[1] == results[2]
    assert results[0][1] == [paths[3]]


def test_truncate_fault_surfaces_at_parquet_read(tmp_path):
    """A read:truncate fault must not silently no-op at parquet read
    sites: it surfaces as a permanent parse-style error (false-green
    chaos runs are worse than no chaos runs)."""
    path = str(tmp_path / "t.parquet")
    rio.write_table_atomic(pa.table({"x": [1, 2]}), path)
    faults.arm("read:truncate:nth=1")
    with pytest.raises(ValueError, match="truncated parquet read"):
        rio.read_table(path, retries=False)
    from lddl_tpu.utils.fs import get_num_samples_of_parquet
    faults.arm("read:truncate:nth=1")
    with pytest.raises(ValueError, match="corrupt or truncated"):
        get_num_samples_of_parquet(path)


def test_whole_bin_quarantined_names_the_quarantine(bert_shard_dir,
                                                    tmp_path):
    """Quarantining every shard of a MIDDLE bin leaves a bin-id gap; the
    contiguity error must point at the quarantine, not the preprocessor."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    from lddl_tpu.preprocess.binning import make_schema
    _, vocab = bert_shard_dir
    d = str(tmp_path / "binned")
    os.makedirs(d)
    schema = make_schema(masking=False, binned=True)
    for b in range(3):
        for i in range(2):
            rows = {
                "A": ["alpha beta"] * 3,
                "B": ["gamma delta"] * 3,
                "is_random_next": [False, True, False],
                "num_tokens": [7, 7, 7],
                "bin_id": [b] * 3,
            }
            rio.write_table_atomic(
                pa.table(rows, schema=schema),
                os.path.join(d, "shard-{}.parquet_{}".format(i, b)))
    integrity.build_manifest(d)
    for i in range(2):  # corrupt ALL of bin 1
        victim = os.path.join(d, "shard-{}.parquet_1".format(i))
        with open(victim, "r+b") as f:
            f.truncate(4)
    with pytest.warns(UserWarning):
        with pytest.raises(ValueError,
                           match="quarantined at startup"):
            get_bert_pretrain_data_loader(d, vocab_file=vocab, batch_size=2,
                                          on_corrupt="quarantine",
                                          return_raw_samples=True)


def test_size_mode_manifest_has_no_crc_and_still_verifies(tmp_path,
                                                          monkeypatch):
    """LDDL_TPU_MANIFEST=size records byte lengths only (zero extra read
    passes); verification still catches truncation by size and skips the
    crc re-hash gracefully even when asked for it."""
    monkeypatch.setenv("LDDL_TPU_MANIFEST", "size")
    paths = _make_shards(tmp_path)
    manifest = integrity.build_manifest(str(tmp_path))
    assert all("crc32" not in e for e in manifest.values())
    good, excluded = integrity.verify_shards(paths, check_crc=True)
    assert good == paths
    with open(paths[0], "r+b") as f:
        f.truncate(3)
    with pytest.raises(integrity.ShardIntegrityError, match="shard-0"):
        integrity.verify_shards(paths)


def test_missing_manifest_trusts_shards(tmp_path):
    paths = _make_shards(tmp_path)
    good, excluded = integrity.verify_shards(paths)
    assert good == paths and excluded == []


def test_verify_rejects_unknown_policy(tmp_path):
    with pytest.raises(ValueError, match="on_corrupt"):
        integrity.verify_shards([], on_corrupt="shrug")


# ------------------------------------------- loader startup integration


@pytest.fixture(scope="module")
def bert_shard_dir(tmp_path_factory):
    """Four tiny balanced BERT-schema shards + cache + manifest."""
    d = tmp_path_factory.mktemp("bert_shards")
    from lddl_tpu.preprocess.binning import make_schema
    from lddl_tpu.utils.fs import write_num_samples_cache
    schema = make_schema(masking=False, binned=False)
    counts = {}
    for i in range(4):
        rows = {
            "A": ["alpha beta"] * 3,
            "B": ["gamma delta"] * 3,
            "is_random_next": [False, True, False],
            "num_tokens": [7, 7, 7],
        }
        name = "shard-{}.parquet".format(i)
        rio.write_table_atomic(pa.table(rows, schema=schema),
                               os.path.join(str(d), name))
        counts[name] = 3
    write_num_samples_cache(str(d), counts)
    vocab = gs.build_vocab(str(d))
    integrity.build_manifest(str(d))
    return str(d), vocab


def test_loader_quarantines_truncated_shard_at_startup(bert_shard_dir,
                                                       tmp_path):
    import shutil
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    src, vocab = bert_shard_dir
    d = str(tmp_path / "shards")
    shutil.copytree(src, d)
    victim = os.path.join(d, "shard-2.parquet")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 3)

    # Default policy refuses to start, naming the shard.
    with pytest.raises(integrity.ShardIntegrityError, match="shard-2"):
        get_bert_pretrain_data_loader(d, vocab_file=vocab, batch_size=2)

    # Quarantine starts on the 3 survivors and logs the exclusion.
    with pytest.warns(UserWarning, match="shard-2"):
        loader = get_bert_pretrain_data_loader(
            d, vocab_file=vocab, batch_size=2, on_corrupt="quarantine",
            return_raw_samples=True)
    assert len(loader.dataset) == 9  # 3 shards x 3 samples; counts explicit
    assert sum(len(b) for b in loader) == 9  # and it actually iterates


def test_loader_env_var_policy_default(bert_shard_dir, tmp_path, monkeypatch):
    import shutil
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    src, vocab = bert_shard_dir
    d = str(tmp_path / "shards")
    shutil.copytree(src, d)
    victim = os.path.join(d, "shard-0.parquet")
    with open(victim, "r+b") as f:
        f.truncate(5)
    monkeypatch.setenv("LDDL_TPU_ON_CORRUPT", "quarantine")
    with pytest.warns(UserWarning, match="shard-0"):
        loader = get_bert_pretrain_data_loader(
            d, vocab_file=vocab, batch_size=2, return_raw_samples=True)
    assert len(loader.dataset) == 9


# ------------------------------------- end-to-end fault-masking identity


def test_pipeline_identical_under_injected_transient_eio(tmp_path,
                                                         monkeypatch):
    """The acceptance bar: with transient EIO injected on shard reads at
    p=0.2, a full mini preprocess -> balance -> load run produces batch
    streams identical to the fault-free run (every fault healed by
    retries, nothing silently skipped)."""
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    monkeypatch.setenv("LDDL_TPU_RETRY_ATTEMPTS", "10")

    corpus = gs.build_corpus(str(tmp_path / "corpus"))
    vocab = gs.build_vocab(str(tmp_path))

    def run(tag, arm_spec):
        pre = str(tmp_path / ("pre_" + tag))
        shards = str(tmp_path / ("shards_" + tag))
        if arm_spec:
            faults.arm(arm_spec)
        try:
            gs.run_case(corpus, vocab, pre, binned=False)
            balance_shards(pre, shards, num_shards=4)
            loader = get_bert_pretrain_data_loader(
                shards, vocab_file=vocab, batch_size=4,
                return_raw_samples=True)
            return [s for batch in loader for s in batch]
        finally:
            faults.disarm()

    clean = run("clean", None)
    faulty = run("faulty", "read:eio:p=0.2:seed=11,open:eio:p=0.1:seed=12")
    assert len(clean) > 0
    assert faulty == clean


# ------------------------------------------- loader worker supervision


@pytest.fixture(autouse=True)
def _fast_death_detection(monkeypatch):
    from lddl_tpu.loader.dataloader import DataLoader
    monkeypatch.setattr(DataLoader, "_POLL_TIMEOUT_S", 0.5)


def _process_loader(shard_dir, vocab, num_workers=2):
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    return get_bert_pretrain_data_loader(
        shard_dir, vocab_file=vocab, batch_size=2, num_workers=num_workers,
        return_raw_samples=True, worker_mode="process",
        shuffle_buffer_size=8, shuffle_buffer_warmup_factor=2)


def test_killed_worker_restarts_once_with_identical_batches(bert_shard_dir,
                                                            tmp_path):
    """SIGKILL a persistent process worker mid-epoch: the supervisor must
    restart it ONCE and replay its pure (seed, epoch, dp, worker) stream,
    leaving the consumer-visible batch sequence unchanged."""
    src, vocab = bert_shard_dir

    clean_loader = _process_loader(src, vocab)
    try:
        clean = list(clean_loader)
    finally:
        clean_loader.shutdown_workers()
    assert len(clean) > 2

    flag = str(tmp_path / "killed.flag")
    faults.arm("worker:kill:nth=2:path=w0:flag={}".format(flag))
    loader = _process_loader(src, vocab)
    try:
        with pytest.warns(UserWarning, match="worker 0 died.*restarting"):
            faulty = list(loader)
    finally:
        faults.disarm()
        loader.shutdown_workers()
    assert os.path.exists(flag)  # the kill really happened
    assert faulty == clean


def test_worker_dying_twice_fails_fast_with_named_error(bert_shard_dir,
                                                        tmp_path):
    """No flag latch: the restarted worker hits the same kill again. The
    second death must raise a named-worker error, not loop forever."""
    src, vocab = bert_shard_dir
    faults.arm("worker:kill:nth=2:path=w0")
    loader = _process_loader(src, vocab)
    try:
        with pytest.warns(UserWarning, match="worker 0 died"):
            with pytest.raises(RuntimeError,
                               match="worker 0 died again after a restart"):
                list(loader)
    finally:
        faults.disarm()
        loader.shutdown_workers()


# ---------------------------------------------------- lint: atomic writes


def test_no_raw_os_replace_outside_resilience_io():
    """Every publish into a shard directory must go through
    resilience.io.atomic_write/atomic_publish (fsync + replace + dir
    fsync). A raw os.replace elsewhere re-opens the torn-publish window
    this PR closed. Migrated from a grep to the AST analyzer's
    atomic-publish rule (single source of truth, also catches os.rename /
    shutil.move / raw write-mode opens — see tests/test_analysis.py)."""
    from lddl_tpu import analysis
    report = analysis.run_check(
        ["lddl_tpu"], rules=analysis.get_rules(["atomic-publish"]))
    assert report.errors == []
    assert report.new == [], (
        "raw publish outside resilience/io.py -- route these through "
        "resilience.io.atomic_write/atomic_publish:\n{}".format(
            "\n".join(f.format() for f in report.new)))
    # The rule itself still rejects the original violation if
    # reintroduced anywhere in the package.
    findings, _ = analysis.analyze_source(
        "import os\nos.replace('tmp', 'dst')\n", "lddl_tpu/balance/x.py",
        analysis.get_rules(["atomic-publish"]))
    assert [f.rule for f in findings] == ["atomic-publish"]
