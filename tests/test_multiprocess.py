"""REAL multi-process execution of the distributed path.

These tests launch actual OS processes that each call
``jax.distributed.initialize`` (CPU backend, localhost coordinator, gloo
collectives) and run the full preprocess + balance pipeline through the
production CLIs with ``--multihost`` — the exact code path a TPU pod run
takes (lddl_tpu.parallel.distributed.JaxCommunicator), minus only the
hardware. Output must be byte-identical with a single-process run: rank
fan-out is not allowed to be observable in the shards.

Reference counterpart: the mpirun/srun recipes
(/root/reference/examples/local_example.sh:56-81,
/root/reference/examples/slurm_example.sub:72-103) — which the reference
can only exercise on a real cluster; here it runs in CI.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_world(argv_of_rank, world, timeout=240):
    """Launch ``world`` processes (argv_of_rank(rank) -> argv), wait for
    all, raise with collected output on any failure."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(argv_of_rank(r), stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True, env=env,
                         cwd=REPO_ROOT)
        for r in range(world)
    ]
    outs = []
    failed = False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
        failed = failed or p.returncode != 0
    if failed:
        raise AssertionError(
            "multi-process run failed:\n" + "\n=== rank ===\n".join(outs))
    return outs


@pytest.fixture
def mp_corpus(tmp_path):
    """Corpus with varied sentence lengths so every bin is populated."""
    source = tmp_path / "corpus" / "source"
    source.mkdir(parents=True)
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    g = np.random.Generator(np.random.Philox(key=[0, 11]))
    docs = []
    for d in range(64):
        sents = []
        for _ in range(int(g.integers(2, 8))):
            n = 1 + int(g.integers(0, 13))
            sents.append(" ".join(
                words[int(g.integers(0, len(words)))]
                for _ in range(n)).capitalize() + ".")
        docs.append("doc-{} {}".format(d, " ".join(sents)))
    for shard in range(4):
        with open(source / "{}.txt".format(shard), "w") as f:
            for line in docs[shard::4]:
                f.write(line + "\n")
    return str(tmp_path / "corpus")


@pytest.fixture
def mp_vocab(tmp_path_factory):
    from lddl_tpu.preprocess import build_wordpiece_vocab
    words = ("alpha beta gamma delta epsilon zeta eta theta iota kappa "
             "lambda mu nu xi omicron pi rho sigma tau upsilon").split()
    path = tmp_path_factory.mktemp("mp_vocab") / "vocab.txt"
    return build_wordpiece_vocab([" ".join(words)] * 4, str(path),
                                 vocab_size=200)


def _preprocess_argv(corpus, vocab, out, extra):
    return [sys.executable, "-m", "lddl_tpu.cli.preprocess_bert_pretrain",
            "--wikipedia", corpus, "--sink", out, "--vocab-file", vocab,
            "--target-seq-length", "32", "--duplicate-factor", "1",
            "--masking", "--bin-size", "16", "--num-blocks", "4",
            "--sample-ratio", "1.0", "--seed", "0",
            "--local-workers", "1"] + extra


def _balance_argv(indir, outdir, extra):
    return [sys.executable, "-m", "lddl_tpu.cli.balance_shards",
            "--indir", indir, "--outdir", outdir, "--num-shards", "4"] + extra


def _multihost_flags(port, world, rank):
    return ["--multihost",
            "--coordinator-address", "127.0.0.1:{}".format(port),
            "--num-processes", str(world), "--process-id", str(rank)]


@pytest.mark.parametrize("world", [2, 3])
def test_multiprocess_preprocess_balance_parity(mp_corpus, mp_vocab,
                                                tmp_path, world):
    """2-3 real jax.distributed processes preprocess + balance; output is
    byte-identical to the single-process run of the same CLIs."""
    import pyarrow.parquet as pq

    # Single-process reference run (same CLIs, no --multihost).
    ref_pre = str(tmp_path / "ref_pre")
    ref_bal = str(tmp_path / "ref_bal")
    _spawn_world(
        lambda r: _preprocess_argv(mp_corpus, mp_vocab, ref_pre, []), 1)
    _spawn_world(lambda r: _balance_argv(ref_pre, ref_bal, []), 1)

    # Multi-process run.
    mp_pre = str(tmp_path / "mp_pre")
    mp_bal = str(tmp_path / "mp_bal")
    port = _free_port()
    _spawn_world(
        lambda r: _preprocess_argv(
            mp_corpus, mp_vocab, mp_pre,
            _multihost_flags(port, world, r)), world)
    port = _free_port()
    _spawn_world(
        lambda r: _balance_argv(mp_pre, mp_bal,
                                _multihost_flags(port, world, r)), world)

    for ref_dir, mp_dir in ((ref_pre, mp_pre), (ref_bal, mp_bal)):
        ref_files = sorted(
            n for n in os.listdir(ref_dir) if ".parquet" in n)
        mp_files = sorted(n for n in os.listdir(mp_dir) if ".parquet" in n)
        assert ref_files == mp_files and ref_files
        for name in ref_files:
            a = pq.read_table(os.path.join(ref_dir, name))
            b = pq.read_table(os.path.join(mp_dir, name))
            assert a.equals(b), "shard {} differs across world sizes".format(
                name)

    # The balanced output carries the sample-count cache (the loader's
    # startup census shortcut) and equal per-shard counts.
    import json
    with open(os.path.join(mp_bal, ".num_samples.json")) as f:
        counts = json.load(f)
    per_bin = {}
    for name, n in counts.items():
        bin_id = name.rsplit("_", 1)[-1] if "_" in name else ""
        per_bin.setdefault(bin_id, []).append(n)
    for bin_id, ns in per_bin.items():
        assert max(ns) - min(ns) <= 1, (bin_id, ns)


def test_jax_communicator_collectives():
    """JaxCommunicator's allreduce/barrier across 2 real processes,
    including values above 2^31 (the int64-as-bytes shipping contract)."""
    port = _free_port()
    script = os.path.join(os.path.dirname(__file__), "_jaxcomm_worker.py")
    outs = _spawn_world(
        lambda r: [sys.executable, script, str(r), "2",
                   "127.0.0.1:{}".format(port)], 2)
    for out in outs:
        assert "COLLECTIVES_OK" in out, out


def test_multiprocess_loader_census_and_dp_contract(mp_corpus, mp_vocab,
                                                    tmp_path):
    """The production loader under a REAL 2-process jax.distributed group:
    the shard census runs through JaxCommunicator (cache removed), the two
    dp partitions exactly cover the single-process epoch, and both ranks
    produce an identical encoded stream for the same dp group."""
    import json as _json
    from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                     run_bert_preprocess)
    from lddl_tpu.balance import balance_shards

    tok = get_tokenizer(vocab_file=mp_vocab)
    pre = str(tmp_path / "pre")
    bal = str(tmp_path / "bal")
    run_bert_preprocess(
        {"wiki": mp_corpus}, pre, tok,
        config=BertPretrainConfig(max_seq_length=32, duplicate_factor=1),
        num_blocks=4, sample_ratio=1.0, seed=0)
    balance_shards(pre, bal, 4)
    os.remove(os.path.join(bal, ".num_samples.json"))  # force comm census

    # Ground truth: the full epoch's sample multiset, single process.
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    full_loader = get_bert_pretrain_data_loader(
        bal, vocab_file=mp_vocab, batch_size=8, base_seed=5,
        return_raw_samples=True)
    from _loader_worker import sample_key
    full = sorted(sample_key(s) for b in full_loader for s in b)

    port = _free_port()
    script = os.path.join(os.path.dirname(__file__), "_loader_worker.py")
    outs = _spawn_world(
        lambda r: [sys.executable, script, str(r), "2",
                   "127.0.0.1:{}".format(port), bal, mp_vocab], 2)
    partitions = []
    identities = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("SAMPLES "):
                partitions.append(_json.loads(line[len("SAMPLES "):]))
            elif line.startswith("IDENTITY "):
                identities.append(line.split()[1])
    assert len(partitions) == 2 and len(identities) == 2, outs
    assert partitions[0] and partitions[1]
    # The dp partitions tile the epoch (up to the truncation slack the
    # thread-rank test also allows: each side may drop different extras).
    import collections
    union = collections.Counter(partitions[0] + partitions[1])
    mismatch = sum(((union - collections.Counter(full))
                    + (collections.Counter(full) - union)).values())
    assert mismatch <= 2 * 3, mismatch
    # TP/PP peers: identical encoded stream.
    assert identities[0] == identities[1]
