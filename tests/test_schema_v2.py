"""Schema v2 (token-id columnar shards): byte-identity of the full
preprocess -> balance -> load pipeline against schema v1, per-shard path
selection in mixed directories, qserde queue framing, and the device
prefetch wrapper.

The acceptance contract: identical (seed, epoch, rank, worker) =>
identical batch bytes for v1 vs v2 shards, thread vs process workers,
telemetry on and off."""

import json
import os

import numpy as np
import pytest

import golden_spool as gs
from lddl_tpu import observability as obs
from lddl_tpu.loader import get_bert_pretrain_data_loader, prefetch_to_device


def _assert_batches_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for key in x:
            np.testing.assert_array_equal(x[key], y[key], err_msg=key)


@pytest.fixture(scope="module")
def pipe(tmp_path_factory):
    """corpus -> vocab -> preprocess v1 AND v2 (unbinned dynamic + binned
    static) -> balanced shards."""
    from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                     run_bert_preprocess)
    from lddl_tpu.balance import balance_shards
    root = tmp_path_factory.mktemp("schema_v2")
    corpus = gs.build_corpus(str(root / "corpus"))
    vocab = gs.build_vocab(str(root))
    tok = get_tokenizer(vocab_file=vocab)
    out = {"vocab": vocab, "tokenizer": tok, "root": root}
    for kind, masking, bin_size in (("dyn", False, None), ("bin", True, 16)):
        for v in (1, 2):
            pre = str(root / "pre_{}_{}".format(kind, v))
            bal = str(root / "bal_{}_{}".format(kind, v))
            run_bert_preprocess(
                {"wikipedia": corpus}, pre, tok,
                config=BertPretrainConfig(max_seq_length=64, masking=masking,
                                          duplicate_factor=2,
                                          schema_version=v),
                num_blocks=4, sample_ratio=1.0, seed=0, bin_size=bin_size)
            balance_shards(pre, bal, 4)
            out[(kind, v)] = bal
    return out


def _loader(pipe, path, **kw):
    defaults = dict(batch_size=16, num_workers=2, shuffle_buffer_size=64,
                    shuffle_buffer_warmup_factor=4,
                    vocab_file=pipe["vocab"], base_seed=7)
    defaults.update(kw)
    return get_bert_pretrain_data_loader(path, **defaults)


def test_v2_shards_carry_id_columns_and_manifest_version(pipe):
    import pyarrow.parquet as pq
    from lddl_tpu.utils.fs import get_all_parquets_under
    for kind, id_cols in (("dyn", {"A_ids", "B_ids"}),
                          ("bin", {"A_ids", "B_ids",
                                   "masked_lm_positions_ids",
                                   "masked_lm_label_ids"})):
        for v in (1, 2):
            paths = get_all_parquets_under(pipe[(kind, v)])
            names = set(pq.read_schema(paths[0]).names)
            assert id_cols <= names if v == 2 else not (id_cols & names)
            with open(os.path.join(pipe[(kind, v)],
                                   ".manifest.json")) as f:
                meta = json.load(f)["__meta__"]
            assert meta["schema_version"] == v


@pytest.mark.parametrize("kind", ("dyn", "bin"))
def test_v1_v2_byte_identity_thread(pipe, kind):
    """Same (seed, epoch, rank, worker) => identical batches from text
    and columnar shards, across two consecutive epochs."""
    l1 = _loader(pipe, pipe[(kind, 1)])
    l2 = _loader(pipe, pipe[(kind, 2)])
    for _ in range(2):  # epoch 0 AND epoch 1 (fresh RNG state per epoch)
        _assert_batches_equal(list(l1), list(l2))


def test_v1_v2_byte_identity_process_and_queue_accounting(pipe, monkeypatch):
    """Process workers (qserde protocol-5 framing over the queue) must
    reproduce the v1 thread stream bit-for-bit, and account the framed
    bytes they shipped."""
    monkeypatch.setenv("LDDL_TPU_FORCE_PROCESS_WORKERS", "1")
    ref = list(_loader(pipe, pipe[("dyn", 1)]))
    lp = _loader(pipe, pipe[("dyn", 2)], worker_mode="process")
    try:
        got = list(lp)
        _assert_batches_equal(ref, got)
        assert lp.queue_batches == len(got)
        assert lp.queue_bytes > 0
    finally:
        lp.shutdown_workers()


def test_v1_v2_byte_identity_packed(pipe):
    kw = dict(pack_seq_length=64, pack_rows=4, pack_max_per_row=8)
    p1 = list(_loader(pipe, pipe[("dyn", 1)], **kw))
    p2 = list(_loader(pipe, pipe[("dyn", 2)], **kw))
    _assert_batches_equal(p1, p2)


def test_v1_v2_byte_identity_with_telemetry(pipe, tmp_path):
    """Telemetry armed: batches stay byte-identical AND the per-schema
    decode counters prove each path actually ran."""
    assert not obs.enabled()
    off = list(_loader(pipe, pipe[("bin", 2)]))
    obs.configure(dir=str(tmp_path / "metrics"))
    try:
        on2 = list(_loader(pipe, pipe[("bin", 2)]))
        on1 = list(_loader(pipe, pipe[("bin", 1)]))
        reg = obs.registry()
        assert reg.counter("loader_decode_columnar_batches_total").total() > 0
        assert reg.counter("loader_decode_legacy_batches_total").total() > 0
    finally:
        obs.disable()
    _assert_batches_equal(off, on2)
    _assert_batches_equal(on1, on2)


def test_mixed_directory_per_shard_selection(pipe, tmp_path):
    """Half v1 shards + half v2 shards in ONE directory: per-shard path
    selection must not change a single batch byte vs the pure-v1 dir."""
    import shutil
    from lddl_tpu.balance import generate_num_samples_cache
    from lddl_tpu.resilience.integrity import build_manifest
    mixed = tmp_path / "mixed"
    mixed.mkdir()
    for i in range(4):
        src = pipe[("dyn", 1 if i < 2 else 2)]
        shutil.copy(os.path.join(src, "shard-{}.parquet".format(i)),
                    mixed / "shard-{}.parquet".format(i))
    generate_num_samples_cache(str(mixed))
    build_manifest(str(mixed))
    # A mixed directory declares BOTH versions, not an arbitrary one.
    with open(mixed / ".manifest.json") as f:
        assert json.load(f)["__meta__"] == {"schema_versions": [1, 2]}
    ref = list(_loader(pipe, pipe[("dyn", 1)]))
    got = list(_loader(pipe, str(mixed)))
    _assert_batches_equal(ref, got)


def test_resume_fingerprints_distinguish_schema_but_not_v1_upgrades(pipe):
    """v2 output bytes differ from v1, so fingerprints must differ; the
    v1 fingerprint must NOT include the schema_version field at all, so
    runs started before the field existed stay resumable."""
    import dataclasses
    import json as json_mod
    from lddl_tpu.preprocess import BertPretrainConfig
    from lddl_tpu.preprocess.runner import (BertBucketProcessor,
                                            processor_fingerprint,
                                            splitter_digest)
    from lddl_tpu.preprocess.binning import DEFAULT_PARQUET_COMPRESSION

    def fp(v):
        cfg = BertPretrainConfig(max_seq_length=64, schema_version=v)
        return BertBucketProcessor(pipe["tokenizer"], cfg, 1, "/tmp/x",
                                   None, "parquet").fingerprint()

    assert fp(1) != fp(2)
    # Pre-upgrade replay: the old code hashed the config dataclass (which
    # had no schema_version field) directly.
    cfg = BertPretrainConfig(max_seq_length=64, schema_version=1)
    legacy_view = dataclasses.asdict(cfg)
    del legacy_view["schema_version"]
    proc = BertBucketProcessor(pipe["tokenizer"], cfg, 1, "/tmp/x", None,
                               "parquet")
    legacy = processor_fingerprint(
        "BertBucketProcessor", proc.tok_info.vocab_digest,
        json_mod.dumps(legacy_view, sort_keys=True, default=str), 1, None,
        "parquet", splitter_digest(None),
        "codec=" + DEFAULT_PARQUET_COMPRESSION)
    assert fp(1) == legacy


def test_bart_v1_v2_byte_identity(pipe):
    from lddl_tpu.preprocess.bart import (BartPretrainConfig,
                                          run_bart_preprocess)
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.loader.bart import get_bart_pretrain_data_loader
    root = pipe["root"]
    dirs = {}
    for v, tok in ((1, None), (2, pipe["tokenizer"])):
        pre = str(root / "bart_pre_{}".format(v))
        bal = str(root / "bart_bal_{}".format(v))
        run_bart_preprocess({"wikipedia": str(root / "corpus")}, pre,
                            config=BartPretrainConfig(target_seq_length=48),
                            num_blocks=4, sample_ratio=1.0, seed=0,
                            tokenizer=tok)
        balance_shards(pre, bal, 4)
        dirs[v] = bal
    kw = dict(vocab_file=pipe["vocab"], batch_size=8, num_workers=2,
              base_seed=3, max_seq_length=48, shuffle_buffer_size=64,
              shuffle_buffer_warmup_factor=4)
    b1 = list(get_bart_pretrain_data_loader(dirs[1], **kw))
    b2 = list(get_bart_pretrain_data_loader(dirs[2], **kw))
    _assert_batches_equal(b1, b2)


# ------------------------------------------------------------------ qserde


def test_qserde_roundtrip_preserves_arrays_and_structure():
    from lddl_tpu.loader import qserde
    base = np.arange(64, dtype=np.int32)
    batch = {
        "input_ids": np.arange(12, dtype=np.int32).reshape(3, 4),
        "f64": np.linspace(0, 1, 5),
        "views": [base[3:9], base[40:40]],  # incl. an empty slice
        "meta": ("x", 3, True, b"raw"),
    }
    out = qserde.decode(qserde.encode(batch))
    assert sorted(out) == sorted(batch)
    np.testing.assert_array_equal(out["input_ids"], batch["input_ids"])
    assert out["input_ids"].dtype == np.int32
    np.testing.assert_array_equal(out["f64"], batch["f64"])
    for a, b in zip(out["views"], batch["views"]):
        np.testing.assert_array_equal(a, b)
    assert out["meta"] == batch["meta"]
    # Consumers may mutate batches (thread mode hands over writable
    # arrays; process mode must match).
    out["input_ids"][0, 0] = 99
    assert out["input_ids"][0, 0] == 99


def test_qserde_raw_sample_batches():
    """The packed path ships RAW sample tuples through process workers:
    tuples of int32 views (v2) or strings (v1) survive framing."""
    from lddl_tpu.loader import qserde
    flat = np.arange(100, dtype=np.int32)
    batch = [(flat[0:7], flat[7:9], np.bool_(True)),
             ("alpha beta", "gamma", 0)]
    out = qserde.decode(qserde.encode(batch))
    np.testing.assert_array_equal(out[0][0], flat[0:7])
    np.testing.assert_array_equal(out[0][1], flat[7:9])
    assert bool(out[0][2]) is True
    assert out[1] == batch[1]


# --------------------------------------------------------------- prefetch


def test_prefetch_to_device_order_and_reiteration():
    batches = [{"input_ids": np.full((2, 2), i)} for i in range(7)]
    moved = []

    def fake_put(b):
        moved.append(int(b["input_ids"][0, 0]))
        return {"input_ids": b["input_ids"] + 100}

    wrapped = prefetch_to_device(batches, device_put=fake_put, depth=2)
    for epoch in range(2):  # re-iterable, like DataLoader
        got = [int(b["input_ids"][0, 0]) for b in wrapped]
        assert got == [100 + i for i in range(7)]
    assert moved == list(range(7)) * 2
    assert len(wrapped) == 7


def test_prefetch_to_device_propagates_errors():
    def boom():
        yield {"input_ids": np.zeros((1, 1))}
        raise RuntimeError("loader exploded")

    class Once:
        def __iter__(self):
            return boom()

    wrapped = prefetch_to_device(Once(), device_put=lambda b: b)
    with pytest.raises(RuntimeError, match="loader exploded"):
        list(wrapped)
