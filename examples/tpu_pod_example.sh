#!/bin/bash
# Multi-host (TPU pod) recipe: preprocess + balance + mock training across
# all hosts of a pod slice, coordinated by jax.distributed.
#
# Reference counterpart: examples/slurm_example.sub (srun --mpi=pmix over
# 128 tasks/node). The TPU-native replacement needs NO MPI and no Slurm:
# one process per host, jax.distributed for the collectives, and a local
# process pool (--local-workers) for the reference's intra-node rank
# fan-out. The preprocess/balance stages also run on TPU-less CPU
# clusters — pass JAX_PLATFORMS=cpu and the CLIs pick gloo collectives.
#
# Two launch styles:
#
#   (a) TPU pod (e.g. v5e-16, 2 hosts): run the SAME command on every host;
#       coordinator/rank come from the TPU metadata, so --multihost alone
#       is enough. With gcloud:
#
#         gcloud compute tpus tpu-vm ssh $TPU_NAME --worker=all --command \
#           "cd lddl_tpu && bash examples/tpu_pod_example.sh run_all"
#
#   (b) Any cluster / localhost simulation: pass the wiring explicitly --
#       this script's `simulate` mode launches NUM_HOSTS local processes
#       with --coordinator-address/--num-processes/--process-id, which is
#       also exactly how you would wire a CPU preprocess cluster.
#
# Storage: $DATA must be shared across hosts (GCS via gcsfuse, or NFS) --
# the same mount that serves the training shards. The preprocessor's
# shuffle spool and the balancer's ownership-striped I/O ride on it.
#
# Reproducible environment: docker/tpu.Dockerfile (pinned deps in
# docker/requirements.lock); build with docker/build.sh and run this
# script inside, or pip-install the same pins directly on the hosts.
set -euo pipefail

DATA=${DATA:-/tmp/lddl_tpu_pod_example}
SEQ_LEN=${SEQ_LEN:-128}
BIN_SIZE=${BIN_SIZE:-32}
NUM_SHARDS=${NUM_SHARDS:-16}
NUM_BLOCKS=${NUM_BLOCKS:-64}
NUM_HOSTS=${NUM_HOSTS:-2}          # simulate mode only
COORD_PORT=${COORD_PORT:-12321}    # simulate mode only
cd "$(dirname "$0")/.."

prepare_corpus() {  # rank-0 only; synthetic stand-in for download_wikipedia
  rm -rf "$DATA"; mkdir -p "$DATA"
  python - "$DATA" <<'EOF'
import sys, bench, shutil, os
os.makedirs(sys.argv[1], exist_ok=True)
corpus = os.path.join(sys.argv[1], "wiki")
n, _ = bench.make_corpus(corpus, target_mb=4, shards=8)
print("corpus bytes:", n)
EOF
  python - "$DATA" <<'EOF'
import sys, glob
from lddl_tpu.preprocess import build_wordpiece_vocab
texts = []
for p in sorted(glob.glob(sys.argv[1] + "/wiki/source/*.txt"))[:1]:
    with open(p, encoding="utf-8") as f:
        for i, line in enumerate(f):
            texts.append(line.split(None, 1)[1])
            if i > 500: break
build_wordpiece_vocab(texts, sys.argv[1] + "/vocab.txt", vocab_size=8192)
EOF
}

# The three pipeline stages; arguments are forwarded as extra flags
# (e.g. the multihost wiring). Mirrors slurm_example.sub:74-118 stage for
# stage.
preprocess() {
  python -m lddl_tpu.cli.preprocess_bert_pretrain \
    --wikipedia "$DATA/wiki" \
    --sink "$DATA/pretrain" \
    --vocab-file "$DATA/vocab.txt" \
    --target-seq-length "$SEQ_LEN" \
    --bin-size "$BIN_SIZE" \
    --num-blocks "$NUM_BLOCKS" \
    --masking \
    "$@"
}

balance() {
  python -m lddl_tpu.cli.balance_shards \
    --indir "$DATA/pretrain" \
    --outdir "$DATA/balanced" \
    --num-shards "$NUM_SHARDS" \
    "$@"
}

mock_train() {
  python benchmarks/mock_train.py \
    --path "$DATA/balanced" \
    --vocab-file "$DATA/vocab.txt" \
    --batch-size 16 --epochs 1
}

case "${1:-simulate}" in
  # ---- (a) on a real pod: same command on every host ----------------------
  run_all)
    # Corpus prep runs on worker 0 only (TPU VMs export TPU_WORKER_ID).
    # No explicit barrier needed: the other workers' preprocess blocks in
    # jax.distributed.initialize until worker 0 joins, which it does only
    # after prepare_corpus returns.
    if [ "${TPU_WORKER_ID:-0}" = "0" ]; then
      prepare_corpus
    fi
    preprocess --multihost
    balance --multihost
    mock_train
    ;;

  # ---- (b) localhost simulation of NUM_HOSTS hosts ------------------------
  simulate)
    prepare_corpus
    export JAX_PLATFORMS=cpu  # CPU collectives (gloo) — no TPU needed
    pids=()
    for rank in $(seq 0 $((NUM_HOSTS - 1))); do
      preprocess --multihost \
        --coordinator-address "127.0.0.1:$COORD_PORT" \
        --num-processes "$NUM_HOSTS" --process-id "$rank" \
        > "$DATA/preprocess.$rank.log" 2>&1 &
      pids+=($!)
    done
    for p in "${pids[@]}"; do wait "$p"; done
    echo "preprocess done on $NUM_HOSTS hosts"

    pids=()
    for rank in $(seq 0 $((NUM_HOSTS - 1))); do
      balance --multihost \
        --coordinator-address "127.0.0.1:$((COORD_PORT + 1))" \
        --num-processes "$NUM_HOSTS" --process-id "$rank" \
        > "$DATA/balance.$rank.log" 2>&1 &
      pids+=($!)
    done
    for p in "${pids[@]}"; do wait "$p"; done
    echo "balance done on $NUM_HOSTS hosts"

    mock_train
    echo "pod example OK: shards in $DATA/balanced"
    ;;

  *)
    echo "usage: $0 [run_all|simulate]" >&2; exit 2 ;;
esac
