#!/bin/bash
# End-to-end single-host recipe (reference parity: examples/local_example.sh).
# Zero-egress friendly: uses a synthetic Wikipedia-like corpus; swap step 1
# for `download_wikipedia --outdir $DATA/wiki` on a connected machine.
set -euo pipefail

DATA=${DATA:-/tmp/lddl_tpu_example}
SEQ_LEN=${SEQ_LEN:-128}
BIN_SIZE=${BIN_SIZE:-32}
NUM_SHARDS=${NUM_SHARDS:-8}
cd "$(dirname "$0")/.."

rm -rf "$DATA"
mkdir -p "$DATA"

echo "== 1. corpus (synthetic; see download_wikipedia for the real one) =="
python - "$DATA" <<'EOF'
import os, sys, bench
n, _ = bench.make_corpus(os.path.join(sys.argv[1], "wiki"), target_mb=4,
                         shards=4)
print("corpus bytes:", n)
EOF

echo "== 2. vocab =="
python - "$DATA" <<'EOF'
import sys, glob
from lddl_tpu.preprocess import build_wordpiece_vocab
texts = []
for p in glob.glob(sys.argv[1] + "/wiki/source/*.txt"):
    with open(p) as f:
        for i, line in enumerate(f):
            texts.append(line.split(None, 1)[1])
            if i > 500: break
build_wordpiece_vocab(texts, sys.argv[1] + "/vocab.txt", vocab_size=8192)
EOF

echo "== 3. preprocess (binned, static masking) =="
# add "--splitter learned" for punkt-grade segmentation (corpus-trained
# parameters; see SPLITTER_DRIFT.json — F1 0.99 vs punkt)
python -m lddl_tpu.cli.preprocess_bert_pretrain \
  --wikipedia "$DATA/wiki" \
  --sink "$DATA/pre" \
  --vocab-file "$DATA/vocab.txt" \
  --target-seq-length "$SEQ_LEN" \
  --bin-size "$BIN_SIZE" \
  --masking \
  --duplicate-factor 2 \
  --sample-ratio 1.0 \
  --num-blocks 8

echo "== 4. balance =="
python -m lddl_tpu.cli.balance_shards \
  --indir "$DATA/pre" --outdir "$DATA/bal" --num-shards "$NUM_SHARDS"

echo "== 5. mock training (2 simulated dp groups) =="
for RANK in 0 1; do
  python benchmarks/mock_train.py \
    --path "$DATA/bal" \
    --vocab-file "$DATA/vocab.txt" \
    --batch-size 32 \
    --epochs 1 \
    --log-freq 20 \
    --dp-rank "$RANK" --num-dp-groups 2 \
    --fixed-seq-lengths 32 64 96 128 \
    --seq-len-dir "$DATA/seqlens"
done

echo "== 6. validate binning + sync =="
python benchmarks/validate_seqlen.py \
  --seq-len-dir "$DATA/seqlens" --bin-size "$BIN_SIZE"

echo "== 7. BART family (preprocess -> balance -> loader) =="
python -m lddl_tpu.cli.preprocess_bart_pretrain \
  --wikipedia "$DATA/wiki" \
  --sink "$DATA/bart_pre" \
  --target-seq-length 128 \
  --num-blocks 8 \
  --sample-ratio 1.0 \
  --seed 0
python -m lddl_tpu.cli.balance_shards \
  --indir "$DATA/bart_pre" --outdir "$DATA/bart_bal" --num-shards 4
python benchmarks/mock_train.py \
  --family bart \
  --path "$DATA/bart_bal" \
  --vocab-file "$DATA/vocab.txt" \
  --batch-size 32 \
  --epochs 1 \
  --log-freq 20 \
  --fixed-seq-lengths 128

echo "== 8. sequence packing (unbinned preprocess -> packed loader) =="
python -m lddl_tpu.cli.preprocess_bert_pretrain \
  --wikipedia "$DATA/wiki" \
  --sink "$DATA/pre_unb" \
  --vocab-file "$DATA/vocab.txt" \
  --target-seq-length "$SEQ_LEN" \
  --duplicate-factor 2 \
  --sample-ratio 1.0 \
  --num-blocks 8
python -m lddl_tpu.cli.balance_shards \
  --indir "$DATA/pre_unb" --outdir "$DATA/bal_unb" --num-shards "$NUM_SHARDS"
python - "$DATA" <<'EOF'
import sys
from lddl_tpu.loader import get_bert_pretrain_data_loader
loader = get_bert_pretrain_data_loader(
    sys.argv[1] + "/bal_unb", vocab_file=sys.argv[1] + "/vocab.txt",
    batch_size=32, pack_seq_length=256, pack_rows=8)
n = sum(1 for _ in loader)
print("packed: {} batches of [8, 256], {} samples, pad ratio {:.2%}".format(
    n, loader.n_samples, loader.pad_ratio))
EOF

echo "example complete: $DATA"
