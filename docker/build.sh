#!/bin/bash
# Build the lddl_tpu image (ref: docker/build.sh).
#   docker/build.sh [tag] [jax_extra]
#   jax_extra: tpu (default) | cpu  — cpu for preprocess-only clusters.
set -e
TAG=${1:-"lddl-tpu:latest"}
JAX_EXTRA=${2:-"tpu"}

docker build \
  -f docker/tpu.Dockerfile \
  --network=host \
  --rm \
  -t "${TAG}" \
  --build-arg JAX_EXTRA="${JAX_EXTRA}" \
  .
echo "built ${TAG}"
