# lddl_tpu container for TPU VM hosts (and CPU-only preprocess clusters).
# Mirrors the reference's docker/ngc_pyt.Dockerfile role
# (ref: docker/ngc_pyt.Dockerfile): a pinned, reproducible environment for
# the pod recipe (examples/tpu_pod_example.sh).
#
# Base: a plain Python image — JAX with TPU support installs from the
# libtpu releases; there is no vendor base image requirement on TPU VMs.
ARG PYTHON_TAG=3.12-slim-bookworm
FROM python:${PYTHON_TAG}

ENV LANG=C.UTF-8 \
    LC_ALL=C.UTF-8 \
    PIP_NO_CACHE_DIR=1

# g++ builds the native tokenize engine on first use (lddl_tpu.native).
RUN apt-get update -qq && \
    apt-get install -y --no-install-recommends g++ git && \
    rm -rf /var/lib/apt/lists/*

WORKDIR /workspace/lddl_tpu
ADD . .

# TPU hosts: jax[tpu]; CPU-only preprocess clusters can override
# JAX_EXTRA=cpu at build time (smaller install, same APIs).
ARG JAX_EXTRA=tpu
RUN pip install -r docker/requirements.lock && \
    pip install "jax[${JAX_EXTRA}]" && \
    pip install ./

# Pre-build the native engine + Unicode tables so first use in the pod
# does not pay the build cost per worker.
RUN python -m lddl_tpu.native.build
