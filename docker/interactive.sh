#!/bin/bash
# Interactive shell in the lddl_tpu image (ref: docker/interactive.sh).
#   docker/interactive.sh ["-v /data:/data ..."] [cmd] [image]
# --privileged + /dev exposure are what TPU VM runtimes need to reach the
# accelerator; preprocess-only runs can drop both.
MOUNTS=$1
CMD=${2:-"bash"}
IMAGE=${3:-"lddl-tpu:latest"}

docker run \
  --init \
  -it \
  --rm \
  --network=host \
  --privileged \
  -v "$PWD":/workspace/lddl_tpu \
  ${MOUNTS} \
  "${IMAGE}" \
  ${CMD}
