"""Benchmark: BERT-pretrain preprocessing throughput (MB raw text/sec/chip).

Mirrors the driver target in BASELINE.json: the Wikipedia BERT-pretrain
preprocess hot path (sentence split -> WordPiece -> NSP pairs -> static MLM
masking -> binned parquet shards).

Baseline derivation (BASELINE.md): the reference preprocesses full English
Wikipedia (~12.5 GB extracted text) in <120 s on 32 DGX-A100 nodes
= 256 GPUs -> ~0.41 MB/s/chip.

Honesty notes (round-2 redesign):
- The corpus is adversarial to the native engine's WordPiece memo: a
  ~30k-type procedural lexicon drawn on a Zipf(1.07) rank-frequency curve
  (heavy tail of rare words, like real Wikipedia), with accented latin,
  digit-bearing tokens, CJK characters and varied punctuation, against a
  WordPiece vocab trained on only a small sample — so rare words split
  into multiple pieces and the memo cannot approach a 100% hit rate.
- The measured configuration IS the CLI default: tokenizer_engine="auto"
  (native C++ when available), masking engine "numpy", and
  num_workers=os.cpu_count() — the full-host process-pool fan-out.
- Engine variants (hf tokenizer, jax/TPU masking) are measured in the same
  run on a smaller slice and reported under "variants".

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REFERENCE_MB_PER_SEC_PER_CHIP = 12500.0 / 120.0 / 256.0

_ACCENTS = list("éàüñöçåèêôîûáíóúäß")
_CJK = [chr(c) for c in range(0x4E00, 0x4E60)]
_LETTERS = "etaoinshrdlucmfwypvbgkqjxz"
_LETTER_P = np.array([
    12.7, 9.1, 8.2, 7.5, 7.0, 6.7, 6.3, 6.1, 6.0, 4.3, 4.0, 2.8, 2.8, 2.4,
    2.4, 2.4, 2.0, 2.0, 1.9, 1.5, 1.0, 0.8, 0.15, 0.15, 0.15, 0.07])
_LETTER_P = _LETTER_P / _LETTER_P.sum()


def make_lexicon(g, n_types=30000):
    """Procedural word types: letter-frequency-weighted latin strings with
    an adversarial sprinkle of accents, digits and CJK so a sample-trained
    WordPiece vocab must split the tail into multiple pieces."""
    lengths = g.integers(2, 13, size=n_types)
    letters = np.array(list(_LETTERS))
    words = []
    for i in range(n_types):
        n = int(lengths[i])
        w = "".join(letters[g.choice(26, size=n, p=_LETTER_P)])
        r = g.random()
        if r < 0.05:  # accented
            pos = int(g.integers(0, n))
            w = w[:pos] + _ACCENTS[int(g.integers(0, len(_ACCENTS)))] + w[pos + 1:]
        elif r < 0.07:  # digit-bearing (years, measures)
            w = str(int(g.integers(0, 10000))) if g.random() < 0.5 else (
                w + str(int(g.integers(0, 100))))
        elif r < 0.075:  # CJK run
            w = "".join(_CJK[int(g.integers(0, len(_CJK)))]
                        for _ in range(int(g.integers(1, 4))))
        words.append(w)
    return words


def make_corpus(out_root, target_mb, shards=4, seed=0, n_types=30000,
                zipf_a=1.07):
    """Deterministic Wikipedia-like corpus: one doc per line, doc-id first,
    Zipf-distributed word types. Returns (bytes_written, distinct_types) —
    the realized distinct-type count (procedural generation collides on
    short words, so it is below n_types)."""
    source = os.path.join(out_root, "source")
    os.makedirs(source)
    g = np.random.default_rng(seed)
    lexicon = np.asarray(make_lexicon(g, n_types=n_types), dtype=object)
    ranks = np.arange(1, n_types + 1, dtype=np.float64)
    cdf = np.cumsum(1.0 / ranks ** zipf_a)
    cdf /= cdf[-1]
    punct = np.array([".", ".", ".", ".", "!", "?"], dtype=object)

    target_bytes = int(target_mb * 1024 * 1024)
    written = 0
    doc_id = 0
    files = [open(os.path.join(source, "{}.txt".format(i)), "w",
                  encoding="utf-8")
             for i in range(shards)]
    try:
        while written < target_bytes:
            n_sents = int(g.integers(8, 40))
            sent_lens = g.integers(6, 32, size=n_sents)
            total = int(sent_lens.sum())
            word_idx = np.searchsorted(cdf, g.random(total))
            doc_words = lexicon[word_idx]
            sents = []
            pos = 0
            for sl in sent_lens:
                s = " ".join(doc_words[pos:pos + int(sl)])
                pos += int(sl)
                sents.append(s.capitalize()
                             + str(punct[int(g.integers(0, len(punct)))]))
            line = "wiki-{} {}\n".format(doc_id, " ".join(sents))
            f = files[doc_id % shards]
            f.write(line)
            written += len(line.encode("utf-8"))
            doc_id += 1
    finally:
        for f in files:
            f.close()
    return written, len(set(lexicon.tolist()))


def _timed_run(corpus_dir, corpus_bytes, out_dir, tokenizer, *,
               tokenizer_engine, mask_engine, num_workers, num_blocks=None,
               splitter="rules"):
    if num_blocks is None:
        num_blocks = max(8, 2 * (num_workers or 1))
    from lddl_tpu.preprocess import BertPretrainConfig, run_bert_preprocess
    t0 = time.time()
    written = run_bert_preprocess(
        {"wikipedia": corpus_dir},
        out_dir,
        tokenizer,
        config=BertPretrainConfig(max_seq_length=128, duplicate_factor=1,
                                  masking=True, engine=mask_engine,
                                  tokenizer_engine=tokenizer_engine,
                                  splitter=splitter),
        num_blocks=num_blocks,
        sample_ratio=1.0,
        seed=12345,
        bin_size=32,
        num_workers=num_workers,
    )
    elapsed = time.time() - t0
    n_samples = sum(written.values())
    assert n_samples > 0
    return (corpus_bytes / 1024 / 1024) / elapsed, n_samples


def host_calibration():
    """Seconds for a fixed pure-CPU workload (numpy + bytecode mix close
    to the pipeline's profile). Bigger = slower host RIGHT NOW; divide two
    rounds' calibrations to normalize their headline numbers."""
    g = np.random.default_rng(0)
    a = g.random((512, 512))
    t0 = time.perf_counter()
    for _ in range(20):
        (a @ a).sum()
        np.partition(g.random((4096, 128)), 19, axis=1)
        total = 0
        for i in range(200_000):
            total += i
    return round(time.perf_counter() - t0, 3)


def main():
    target_mb = float(os.environ.get("BENCH_MB", "24"))
    variant_mb = float(os.environ.get("BENCH_VARIANT_MB", "6"))
    from lddl_tpu.utils.cpus import usable_cpu_count
    workers = usable_cpu_count()  # matches the CLI default
    # (--local-workers 0): affinity-aware, not os.cpu_count()
    tmp = tempfile.mkdtemp(prefix="lddl_bench_")
    try:
        from lddl_tpu.preprocess import build_wordpiece_vocab, get_tokenizer

        main_corpus = os.path.join(tmp, "corpus")
        main_bytes, n_distinct = make_corpus(main_corpus, target_mb, seed=0)
        small_corpus = os.path.join(tmp, "corpus_small")
        small_bytes, _ = make_corpus(small_corpus, variant_mb, seed=1)

        # Vocab trained on a ~1.5 MB sample only: the corpus tail is OOV
        # by construction, so WordPiece must actually split words.
        sample = []
        sample_bytes = 0
        with open(os.path.join(main_corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 1_500_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
        tokenizer = get_tokenizer(vocab_file=vocab)

        # Warmup on a 1 MB slice: pays the once-per-process costs (imports,
        # native engine build/check, tokenizer byte tables) outside the
        # timed window, so the headline measures steady-state throughput —
        # the regime the 12.5 GB north-star run lives in. (Pool spawn is
        # NOT excluded: each run creates its own pool, and the headline
        # keeps that cost, as the reference keeps its dask-mpi startup.)
        warm_corpus = os.path.join(tmp, "corpus_warm")
        warm_bytes, _ = make_corpus(warm_corpus, 1, seed=2)
        _timed_run(warm_corpus, warm_bytes, os.path.join(tmp, "out_warm"),
                   tokenizer, tokenizer_engine="auto", mask_engine="numpy",
                   num_workers=workers)

        # Headline: the CLI-default configuration (native tokenizer engine
        # when available, numpy masking, full-host process pool). Best of
        # 3 runs: the bench host is a shared VM whose effective CPU speed
        # drifts 10-30% across hours (round-3's recorded 11.60 vs 16.13
        # was mostly this, not code), so a single sample conflates host
        # weather with code; best-of measures capability. The calibration
        # field records the host's speed at bench time (fixed pure-CPU
        # workload) so cross-round comparisons can see the drift.
        runs = []
        for i in range(3):
            v, n_samples = _timed_run(
                main_corpus, main_bytes,
                os.path.join(tmp, "out_main_{}".format(i)), tokenizer,
                tokenizer_engine="auto", mask_engine="numpy",
                num_workers=workers)
            runs.append(v)
        value = max(runs)

        variants = {}
        for name, tok_eng, mask_eng, n_workers, splitter in (
                ("native+numpy", "auto", "numpy", workers, "rules"),
                ("hf+numpy", "hf", "numpy", workers, "rules"),
                # punkt-grade segmentation end-to-end (corpus-trained
                # params; includes the per-run punkt training cost).
                ("native+learned_splitter", "auto", "numpy", workers,
                 "learned"),
                # jax variant runs single-process: N pool workers sharing
                # one chip is pathological, so give it its best case
                # (still loses - see MASK_ENGINE_BENCH.json).
                ("native+jax_mask_w1", "auto", "jax", 1, "rules"),
        ):
            try:
                v, _ = _timed_run(
                    small_corpus, small_bytes,
                    os.path.join(tmp, "out_" + name.replace("+", "_")),
                    tokenizer, tokenizer_engine=tok_eng, mask_engine=mask_eng,
                    num_workers=n_workers,
                    num_blocks=max(8, 2 * workers), splitter=splitter)
                variants[name] = round(v, 4)
            except Exception as e:  # variant failure must not kill the bench
                variants[name] = "error: {}".format(e)

        print(json.dumps({
            "metric": "MB raw text/sec/chip (Wiki BERT-pretrain preprocess)",
            "value": round(value, 4),
            "unit": "MB/s/chip",
            "vs_baseline": round(value / REFERENCE_MB_PER_SEC_PER_CHIP, 3),
            "config": {
                "num_workers": workers,
                "host_cpu_count": os.cpu_count(),
                "nproc": usable_cpu_count(),
                "host_can_show_scaling": usable_cpu_count() >= 2,
                "native_threads_env":
                    os.environ.get("LDDL_TPU_NATIVE_THREADS"),
                "headline_runs_mb_per_s": [round(r, 4) for r in runs],
                "host_calibration_s": host_calibration(),
                "corpus_mb": round(main_bytes / 1024 / 1024, 2),
                "n_samples": n_samples,
                "lexicon_distinct_types": n_distinct,
                "zipf_a": 1.07,
                "vocab_size": 30522,
            },
            "variants_mb_per_s_on_{}mb".format(int(variant_mb)): variants,
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
