"""Benchmark: BERT-pretrain preprocessing throughput (MB raw text/sec/chip).

Mirrors the driver target in BASELINE.json: the Wikipedia BERT-pretrain
preprocess hot path (sentence split -> WordPiece -> NSP pairs -> static MLM
masking -> binned parquet shards).

Baseline derivation (BASELINE.md): the reference preprocesses full English
Wikipedia (~12.5 GB extracted text) in <120 s on 32 DGX-A100 nodes
= 256 GPUs -> ~0.41 MB/s/chip. We run the same pipeline stage on a
synthetic Wikipedia-like corpus and report MB/s on this host's single chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

REFERENCE_MB_PER_SEC_PER_CHIP = 12500.0 / 120.0 / 256.0

_WORDS = (
    "the of and in to a is was for on as by with he she it at from his her "
    "their this that which were are be has had not but also an or its new "
    "first one two three time year years city state world war government "
    "university school system national history people group member company "
    "development research music film work life family house water area "
    "north south east west century during between under about after before "
    "known called made used found became included according population").split()


def make_corpus(target_mb=24, shards=4, seed=0):
    """Deterministic Wikipedia-like corpus: one doc per line, doc-id first."""
    tmp = tempfile.mkdtemp(prefix="lddl_bench_")
    source = os.path.join(tmp, "corpus", "source")
    os.makedirs(source)
    g = np.random.default_rng(seed)
    target_bytes = int(target_mb * 1024 * 1024)
    written = 0
    doc_id = 0
    files = [open(os.path.join(source, "{}.txt".format(i)), "w")
             for i in range(shards)]
    try:
        while written < target_bytes:
            n_sents = int(g.integers(8, 40))
            sents = []
            for _ in range(n_sents):
                n = int(g.integers(8, 30))
                words = [_WORDS[int(g.integers(0, len(_WORDS)))]
                         for _ in range(n)]
                sents.append(" ".join(words).capitalize() + ".")
            line = "wiki-{} {}\n".format(doc_id, " ".join(sents))
            f = files[doc_id % shards]
            f.write(line)
            written += len(line)
            doc_id += 1
    finally:
        for f in files:
            f.close()
    return tmp, written


def main():
    target_mb = float(os.environ.get("BENCH_MB", "24"))
    tmp, corpus_bytes = make_corpus(target_mb=target_mb)
    try:
        from lddl_tpu.preprocess import (BertPretrainConfig,
                                         build_wordpiece_vocab, get_tokenizer,
                                         run_bert_preprocess)
        vocab = build_wordpiece_vocab(
            [" ".join(_WORDS)] * 8, os.path.join(tmp, "vocab.txt"),
            vocab_size=4096)
        tokenizer = get_tokenizer(vocab_file=vocab)

        out_dir = os.path.join(tmp, "out")
        t0 = time.time()
        written = run_bert_preprocess(
            {"wikipedia": os.path.join(tmp, "corpus")},
            out_dir,
            tokenizer,
            config=BertPretrainConfig(max_seq_length=128, duplicate_factor=1,
                                      masking=True),
            num_blocks=8,
            sample_ratio=1.0,
            seed=12345,
            bin_size=32,
        )
        elapsed = time.time() - t0
        n_samples = sum(written.values())
        assert n_samples > 0

        mb = corpus_bytes / 1024 / 1024
        value = mb / elapsed
        print(json.dumps({
            "metric": "MB raw text/sec/chip (Wiki BERT-pretrain preprocess)",
            "value": round(value, 4),
            "unit": "MB/s/chip",
            "vs_baseline": round(value / REFERENCE_MB_PER_SEC_PER_CHIP, 3),
        }))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
