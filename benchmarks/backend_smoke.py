"""CI smoke for the pluggable storage backend: one small corpus taken
through the full preprocess -> balance -> load round trip twice — once
on the default LocalBackend, once on the MockObjectStore
(``--storage-backend mock``) — with byte identity asserted end to end.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_BENCH=1``. The
byte-identity half is GATING: the storage backend is coordination and
publish *plumbing* and must never reach shard bytes (the invariant
tests/test_backend.py pins in-process; this smoke pins it across the
real CLI surface, worker spawn env inheritance included). The wall
times are informational only — the mock store pays multipart staging +
commit-record IO by design and is not a performance claim. Prints one
JSON line::

    {"identical": true, "shards": N, "samples": {"local": n, "mock": n},
     "wall_s": {"local": ..., "mock": ...}, "loader_identical": true}
"""

import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402


def _tree_digests(out_dir):
    """sha256 of every published (visible) file under ``out_dir``,
    keyed by relative path. Mock-store sidecar dirs (``.obj.*``) and
    telemetry/scratch are implementation detail, not published state —
    the identity claim is about what a data-plane consumer can read."""
    out = {}
    # Deterministic by construction: dirnames are pruned+sorted in place
    # (os.walk honors that) and filenames sorted before hashing.
    for dirpath, dirnames, filenames in os.walk(out_dir):  # lddl: disable=unsorted-iteration
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith((".obj.", ".telemetry",
                                                  ".tmp.")))
        for name in sorted(filenames):
            if name.startswith(".") or ".tmp." in name:
                continue
            path = os.path.join(dirpath, name)
            h = hashlib.sha256()
            with open(path, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[os.path.relpath(path, out_dir)] = h.hexdigest()
    return out


def _load_samples(bal_dir, vocab):
    """Stream every balanced shard through the real loader; return
    (n_samples, digest-of-batch-tensors) so load-path equivalence is
    checked on decoded tensors, not just file bytes."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    loader = get_bert_pretrain_data_loader(
        bal_dir, vocab_file=vocab, batch_size=8, num_workers=0)
    h = hashlib.sha256()
    n = 0
    for batch in loader:
        for key in sorted(batch):
            h.update(key.encode())
            h.update(bytes(memoryview(batch[key]).cast("B")))
        n += int(batch["input_ids"].shape[0])
    return n, h.hexdigest()


def main():
    target_mb = float(os.environ.get("LDDL_TPU_BACKEND_SMOKE_MB", "1"))
    tmp = tempfile.mkdtemp(prefix="lddl_backend_smoke_")
    try:
        from lddl_tpu.preprocess import build_wordpiece_vocab

        corpus = os.path.join(tmp, "corpus")
        bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 300_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=8000)

        report = {"wall_s": {}, "samples": {}}
        pre_digests = {}
        bal_digests = {}
        loads = {}
        for name in ("local", "mock"):
            pre = os.path.join(tmp, "pre_" + name)
            bal = os.path.join(tmp, "bal_" + name)
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            # The flag (not the env var) is the surface under test: it
            # must pin the env for the CLI's own workers itself.
            env.pop("LDDL_TPU_STORAGE_BACKEND", None)
            t0 = time.perf_counter()
            for cmd in (
                [sys.executable, "-m",
                 "lddl_tpu.cli.preprocess_bert_pretrain",
                 "--wikipedia", corpus, "--sink", pre,
                 "--vocab-file", vocab, "--masking",
                 "--bin-size", "32", "--num-blocks", "8",
                 "--seed", "7", "--local-workers", "2",
                 "--storage-backend", name],
                [sys.executable, "-m", "lddl_tpu.cli.balance_shards",
                 "--indir", pre, "--outdir", bal, "--num-shards", "4",
                 "--storage-backend", name],
            ):
                rc = subprocess.call(cmd, env=env,
                                     stdout=subprocess.DEVNULL,
                                     stderr=subprocess.STDOUT)
                if rc != 0:
                    print("backend smoke: {} leg failed rc={} ({})".format(
                        name, rc, cmd[2]), file=sys.stderr)
                    return 1
            report["wall_s"][name] = round(time.perf_counter() - t0, 1)
            pre_digests[name] = _tree_digests(pre)
            bal_digests[name] = _tree_digests(bal)
            os.environ["LDDL_TPU_STORAGE_BACKEND"] = name
            try:
                n, digest = _load_samples(bal, vocab)
            finally:
                os.environ.pop("LDDL_TPU_STORAGE_BACKEND", None)
            report["samples"][name] = n
            loads[name] = digest
        report["shards"] = sum(1 for p in bal_digests["local"]
                               if ".parquet" in p)
        report["identical"] = (
            bool(pre_digests["local"])
            and pre_digests["local"] == pre_digests["mock"]
            and bal_digests["local"] == bal_digests["mock"])
        report["loader_identical"] = (loads["local"] == loads["mock"]
                                      and report["samples"]["local"] > 0)
        print(json.dumps(report, sort_keys=True))
        if not (report["identical"] and report["loader_identical"]):
            print("backend smoke: local and mock backends shipped "
                  "DIFFERENT bytes", file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
