"""CI smoke for the diagnosis surface: a tiny fleet-armed preprocess ->
balance -> load run, then ``tools/pipeline_status.py`` driven the way an
operator (or CI gate) would drive it.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_BENCH=1``.
GATING — this is a correctness alarm for the observability pipeline,
not a performance number:

- ``pipeline_status --json --window`` must parse, report windowed rates
  from the series segments, and carry the loader bound-verdict
  attribution block (the loader leg really iterated batches);
- a deliberately-tripped alert rule must force exit code 2, and the
  relaxed rules file must then exit 0 with the resolve journaled.

Prints one JSON line with what it found.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402

_LOADER_DRIVER = """
import os, sys, time
data, vocab = sys.argv[1], sys.argv[2]
os.environ["LDDL_TPU_FLEET_DIR"] = data
os.environ["LDDL_TPU_FLEET_HOLDER"] = "loaderhost"
os.environ["LDDL_TPU_FLEET_INTERVAL_S"] = "0.2"
from lddl_tpu.loader import get_bert_pretrain_data_loader
loader = get_bert_pretrain_data_loader(
    data, vocab_file=vocab, batch_size=8, num_workers=0)
n = 0
for batch in loader:
    time.sleep(0.002)  # a (tiny) consumer step, so step_gap is real
    n += 1
print("BATCHES", n)
"""


def _status(data, *extra):
    proc = subprocess.run(
        [sys.executable, "-m", "tools.pipeline_status", data, "--json"]
        + list(extra),
        capture_output=True, text=True, cwd=ROOT)
    try:
        doc = json.loads(proc.stdout)
    except ValueError:
        print("status smoke: --json did not parse (rc={}):\n{}\n{}".format(
            proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:]),
            file=sys.stderr)
        return proc.returncode, None
    return proc.returncode, doc


def main():
    target_mb = float(os.environ.get("LDDL_TPU_STATUS_SMOKE_MB", "0.5"))
    tmp = tempfile.mkdtemp(prefix="lddl_status_smoke_")
    try:
        from lddl_tpu.preprocess import build_wordpiece_vocab

        corpus = os.path.join(tmp, "corpus")
        bench.make_corpus(corpus, target_mb, seed=0)
        sample, sample_bytes = [], 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 300_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=8000)
        pre = os.path.join(tmp, "pre")
        data = os.path.join(tmp, "data")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        t0 = time.perf_counter()
        for cmd in (
            [sys.executable, "-m",
             "lddl_tpu.cli.preprocess_bert_pretrain",
             "--wikipedia", corpus, "--sink", pre,
             "--vocab-file", vocab, "--masking",
             "--bin-size", "32", "--num-blocks", "8",
             "--seed", "7", "--local-workers", "2"],
            [sys.executable, "-m", "lddl_tpu.cli.balance_shards",
             "--indir", pre, "--outdir", data, "--num-shards", "4",
             "--fleet-telemetry"],
            [sys.executable, "-c", _LOADER_DRIVER, data, vocab],
        ):
            rc = subprocess.call(cmd, env=env, stdout=subprocess.DEVNULL,
                                 stderr=subprocess.STDOUT)
            if rc != 0:
                print("status smoke: leg failed rc={} ({})".format(
                    rc, cmd[2][:60]), file=sys.stderr)
                return 1
        report = {"pipeline_wall_s": round(time.perf_counter() - t0, 1)}

        rc, doc = _status(data, "--window", "600")
        if doc is None:
            return 1
        if rc != 0:
            print("status smoke: healthy run exited {} ({})".format(
                rc, doc.get("health", {}).get("verdicts")),
                file=sys.stderr)
            return 1
        attr = doc.get("attribution")
        if not attr or "verdict" not in attr:
            print("status smoke: no attribution verdict in the rollup "
                  "(loader leg left no stage counters?)", file=sys.stderr)
            return 1
        window = doc.get("window") or {}
        if not window.get("rates"):
            print("status smoke: --window reported no series rates",
                  file=sys.stderr)
            return 1
        report["verdict"] = attr["verdict"]
        report["input_share"] = round(attr.get("input_share", 0.0), 3)
        report["windowed_metrics"] = len(window["rates"])

        rules = os.path.join(tmp, "rules.json")
        with open(rules, "w") as f:
            json.dump({"rules": [
                {"name": "tripped", "type": "threshold",
                 "metric": "totals.counters.units_completed",
                 "op": ">=", "value": 0},
            ]}, f)
        rc, doc = _status(data, "--alerts", rules)
        if doc is None:
            return 1
        if rc != 2 or doc["alerts"]["firing"] != ["tripped"]:
            print("status smoke: tripped alert rule did not force exit 2 "
                  "(rc={}, firing={})".format(
                      rc, doc.get("alerts", {}).get("firing")),
                  file=sys.stderr)
            return 1
        with open(rules, "w") as f:
            json.dump({"rules": [
                {"name": "tripped", "type": "threshold",
                 "metric": "totals.counters.units_completed",
                 "op": "<", "value": 0},
            ]}, f)
        rc, doc = _status(data, "--alerts", rules)
        if doc is None:
            return 1
        kinds = [t["kind"] for t in doc["alerts"]["transitions"]]
        if rc != 0 or kinds != ["alert.resolved"]:
            print("status smoke: relaxed rules did not resolve cleanly "
                  "(rc={}, transitions={})".format(rc, kinds),
                  file=sys.stderr)
            return 1
        report["alert_fire_resolve"] = True
        print(json.dumps(report, sort_keys=True))
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
