"""Offline validation of sequence binning from mock_train.py .npz dumps.

Reference parity: benchmarks/make_training_seqlen_plots.py — verifies from
recorded traces that (1) per-iteration min/max sequence lengths stay within
one bin width, (2) every dp group selected the SAME bin each iteration
(zero-communication sync), and (3) quantifies padding waste. Emits a text
verdict (CI-friendly) and optional matplotlib plots.
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def attach_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len-dir", required=True,
                   help="directory of lens_<dp_rank>.npz dumps")
    p.add_argument("--bin-size", type=int, required=True)
    p.add_argument("--plots-dir", default=None,
                   help="write .png plots here (optional)")
    return p


def main():
    args = attach_args().parse_args()
    paths = sorted(glob.glob(os.path.join(args.seq_len_dir, "lens_*.npz")))
    if not paths:
        raise SystemExit("no lens_*.npz under {}".format(args.seq_len_dir))
    ranks = {}
    for p in paths:
        rank = int(os.path.basename(p)[len("lens_"):-len(".npz")])
        ranks[rank] = np.load(p)
    print("loaded {} rank dumps".format(len(ranks)))

    failures = 0

    # (1) per-iteration spread within one bin width, per rank.
    for rank, d in sorted(ranks.items()):
        spread = d["max_lens"] - d["min_lens"]
        # Samples inside one (lo, lo+bin_size] bin differ by at most
        # bin_size - 1 tokens, so spread >= bin_size proves a bin mix.
        bad = int((spread >= args.bin_size).sum())
        print("rank {}: max in-batch seq-len spread = {} "
              "(bin size {}) -> {}".format(
                  rank, int(spread.max()), args.bin_size,
                  "OK" if bad == 0 else "{} violations".format(bad)))
        failures += bad

    # (2) all ranks chose the same bin (batch padded len) every iteration.
    lens_matrix = np.stack([d["batch_lens"] for _, d in sorted(ranks.items())])
    sync_diff = lens_matrix.max(axis=0) - lens_matrix.min(axis=0)
    bad_sync = int((sync_diff != 0).sum())
    print("bin sync across ranks: {}".format(
        "OK (identical every iteration)" if bad_sync == 0 else
        "{} iterations diverged".format(bad_sync)))
    failures += bad_sync

    # (3) padding waste.
    total_pad = 0
    total_slots = 0
    for _, d in sorted(ranks.items()):
        # Approximation from min/max: exact per-token stats live in
        # mock_train's printed pad ratio; here we bound it.
        total_pad += int((d["batch_lens"] - d["min_lens"]).sum())
        total_slots += int(d["batch_lens"].sum())
    print("padding upper-bound ratio: {:.4f}".format(
        total_pad / max(total_slots, 1)))

    if args.plots_dir:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        os.makedirs(args.plots_dir, exist_ok=True)
        fig, ax = plt.subplots()
        for rank, d in sorted(ranks.items()):
            ax.plot(d["max_lens"] - d["min_lens"], label="rank {}".format(rank))
        ax.axhline(args.bin_size, color="red", linestyle="--",
                   label="bin size")
        ax.set_xlabel("iteration")
        ax.set_ylabel("in-batch seq-len spread")
        ax.legend()
        fig.savefig(os.path.join(args.plots_dir, "rank_diff.png"))
        fig, ax = plt.subplots()
        ax.plot(sync_diff)
        ax.set_xlabel("iteration")
        ax.set_ylabel("max cross-rank padded-len diff (0 = in sync)")
        fig.savefig(os.path.join(args.plots_dir, "global_diff.png"))
        fig, ax = plt.subplots()
        lens = np.concatenate([d["max_lens"] for _, d in sorted(ranks.items())])
        ax.hist(lens, bins=32)
        ax.set_xlabel("max seq len per iteration")
        fig.savefig(os.path.join(args.plots_dir, "seqlen_hist.png"))
        print("plots -> {}".format(args.plots_dir))

    if failures:
        print("FAIL: {} violations".format(failures))
        sys.exit(1)
    print("PASS")


if __name__ == "__main__":
    main()
