"""On-chip payoff of sequence packing: useful (non-pad) tokens/s through
the REAL jitted train step, fed by the REAL loader (VERDICT round 3
item 2 — the packing feature's reason to exist, measured).

Regimes (same corpus, preprocessed at max_seq_length=128 — the
reference's phase-1 config, where samples are much shorter than a
TPU-friendly row):

- packed:  loader packs samples into fixed [R, 512] rows with segment
           ids; BertForPreTrainingPacked; ~1% pad, one compiled shape.
- binned:  static per-bin shapes (bin_size 32) at the samples' native
           lengths; one compiled step per bin shape; ~4% pad but small
           rows (the reference's binning regime, README binning table).
- fixed:   every batch padded to the full 128 (no binning) — the naive
           fixed-shape baseline; highest pad.

Metric: useful_tokens_per_s = sum over timed steps of REAL sample tokens
(packed: segments != 0; unpacked: attention_mask == 1) / elapsed. Each
regime runs its idiomatic batch size at an equal ~4k useful-token budget
per step. Compile time is excluded (steady-state, like MODEL_BENCH).

Writes PACKING_BENCH.json. Usage:
    python benchmarks/packing_bench.py [--quick]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from collections import defaultdict

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np

import bench  # repo-root corpus/vocab helpers


def build_dataset(tmp, corpus_mb, bin_size):
    """Preprocess the same corpus binned AND unbinned (packing requires
    unbinned shards — rows are always exactly pack_seq_length wide).
    Returns (binned_shards, unbinned_shards, vocab)."""
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.preprocess import (BertPretrainConfig,
                                     build_wordpiece_vocab, get_tokenizer,
                                     run_bert_preprocess)
    corpus = os.path.join(tmp, "corpus")
    bench.make_corpus(corpus, corpus_mb, seed=11)
    sample, sb = [], 0
    with open(os.path.join(corpus, "source", "0.txt"), encoding="utf-8") as f:
        for line in f:
            sample.append(line.split(None, 1)[1])
            sb += len(line)
            if sb > 1_000_000:
                break
    vocab = build_wordpiece_vocab(sample, os.path.join(tmp, "vocab.txt"),
                                  vocab_size=30522)
    tokenizer = get_tokenizer(vocab_file=vocab)
    shards = {}
    for tag, bins in (("binned", bin_size), ("unbinned", None)):
        out = os.path.join(tmp, "parts_" + tag)
        run_bert_preprocess(
            {"wikipedia": corpus}, out, tokenizer,
            config=BertPretrainConfig(max_seq_length=128, duplicate_factor=2,
                                      masking=True),
            num_blocks=8, sample_ratio=1.0, seed=4242, bin_size=bins,
            num_workers=1)
        shards[tag] = os.path.join(tmp, "shards_" + tag)
        balance_shards(out, shards[tag], num_shards=4)
    return shards["binned"], shards["unbinned"], vocab


def collect_batches(loader_kwargs, shards, vocab, want_steps, batch_size):
    """Pull real batches, grouped by shape; return {shape: [batch, ...]}."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader
    loader = get_bert_pretrain_data_loader(
        shards, vocab_file=vocab, batch_size=batch_size, base_seed=77,
        **loader_kwargs)
    groups = defaultdict(list)
    need = want_steps * 4
    n = 0
    for batch in loader:
        key = tuple(batch["input_ids"].shape)
        if batch["input_ids"].shape[0] == batch_size:
            groups[key].append(batch)
        n += 1
        if n >= need:
            break
    return groups


def useful_tokens(batch):
    if "segments" in batch:
        return int((np.asarray(batch["segments"]) > 0).sum())
    return int(np.asarray(batch["attention_mask"]).sum())


def run_regime(name, groups, model, cfg, mesh, n_steps, reps):
    import jax
    from lddl_tpu.loader import to_device_step_batches
    from lddl_tpu.models import create_train_state, make_sharded_multi_step
    from lddl_tpu.models.train import make_optimizer

    total_useful = 0
    total_s = 0.0
    total_steps = 0
    compiles = 0
    for shape, batches in sorted(groups.items(), key=lambda kv: -len(kv[1])):
        if len(batches) < n_steps:
            continue
        use = batches[:n_steps]
        stacked_np = {k: np.stack([b[k] for b in use]) for k in use[0]}
        state, _ = create_train_state(
            cfg, mesh, use[0], model=model,
            optimizer=make_optimizer(warmup_steps=5,
                                     total_steps=n_steps * (reps + 1) + 5))
        multi = make_sharded_multi_step(mesh, cfg, n_steps, model=model)
        stacked = to_device_step_batches(stacked_np, mesh)
        state, metrics = multi(state, stacked, seed=0)  # compile + warm
        float(np.asarray(metrics["loss"])[-1])  # true sync (readback)
        compiles += 1
        t0 = time.perf_counter()
        for r in range(reps):
            state, metrics = multi(state, stacked, seed=r + 1)
        float(np.asarray(metrics["loss"])[-1])
        dt = time.perf_counter() - t0
        shape_useful = sum(useful_tokens(b) for b in use)
        total_useful += shape_useful * reps
        total_s += dt
        total_steps += n_steps * reps
        del state, metrics, stacked
    if total_steps == 0:
        return {"regime": name, "error": "no shape group reached n_steps"}
    return {
        "regime": name,
        "compiled_shapes": compiles,
        "timed_steps": total_steps,
        "useful_tokens_per_s": round(total_useful / total_s, 1),
        "step_ms": round(total_s / total_steps * 1e3, 3),
        "useful_tokens_per_step": round(total_useful / total_steps, 1),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny model + short runs (harness smoke test)")
    p.add_argument("--corpus-mb", type=float, default=6.0)
    p.add_argument("--n-steps", type=int, default=None)
    p.add_argument("--reps", type=int, default=2)
    args = p.parse_args()

    import jax
    from lddl_tpu.models import BertConfig
    from lddl_tpu.models.bert import BertForPreTraining, BertForPreTrainingPacked
    from lddl_tpu.parallel import make_mesh

    n_steps = args.n_steps or (4 if args.quick else 16)
    device = jax.devices()[0]
    mesh = make_mesh({"dp": 1}, devices=[device])
    if args.quick:
        base = dict(vocab_size=30592, hidden_size=128, num_layers=2,
                    num_heads=4, intermediate_size=256)
        make = BertConfig.bert_base
    else:
        base = {}
        make = BertConfig.bert_base
    cfg = make(attention_dropout=0.0, max_position_embeddings=512, **base)

    tmp = tempfile.mkdtemp(prefix="lddl_packbench_")
    try:
        binned_shards, unbinned_shards, vocab = build_dataset(
            tmp, args.corpus_mb, bin_size=32)
        regimes = []
        # packed: [8, 512] rows, segments in-batch (unbinned shards).
        groups = collect_batches(
            dict(pack_seq_length=512, pack_rows=8), unbinned_shards, vocab,
            n_steps, batch_size=8)
        regimes.append(("packed_512x8", BertForPreTrainingPacked(cfg),
                        groups))
        # binned: native per-bin shapes, 32 rows.
        groups = collect_batches({}, binned_shards, vocab, n_steps,
                                 batch_size=32)
        regimes.append(("binned_native", BertForPreTraining(cfg), groups))
        # fixed: everything padded to 128 (unbinned shards, one shape).
        groups = collect_batches(
            dict(fixed_seq_lengths=128), unbinned_shards, vocab, n_steps,
            batch_size=32)
        regimes.append(("fixed_128", BertForPreTraining(cfg), groups))

        results = []
        for name, model, groups in regimes:
            row = run_regime(name, groups, model, cfg, mesh, n_steps,
                             args.reps)
            row["batch_shapes"] = sorted(
                [list(map(int, s)) + [len(v)] for s, v in groups.items()])
            print(row, flush=True)
            results.append(row)

        packed = next((r for r in results
                       if r["regime"].startswith("packed")
                       and "useful_tokens_per_s" in r), None)
        binned = next((r for r in results
                       if r["regime"].startswith("binned")
                       and "useful_tokens_per_s" in r), None)
        conclusion = None
        if packed and binned:
            ratio = (packed["useful_tokens_per_s"]
                     / binned["useful_tokens_per_s"])
            conclusion = (
                "packed {}x binned useful-token throughput. Packing rows "
                "much longer than the samples adds O(L^2) attention FLOPs "
                "(block-diagonal masks do not skip the cross-sample "
                "blocks), so with tight bins the pad reclaim can net out "
                "negative; packing pays vs naive fixed-length padding and "
                "where a single static shape is required (pipeline "
                "stages). Default recommendation: binned shards."
                .format(round(ratio, 3)))
        payload = {
            "conclusion": conclusion,
            "device": str(device),
            "model": "bert_base (samples preprocessed at max_seq_length="
                     "128, duplicate_factor=2)",
            "method": ("useful tokens = non-pad sample tokens through the "
                       "jitted multi-step train scan fed by real loader "
                       "batches; {} steps/dispatch, {} reps, compile "
                       "excluded; readback-synced (block_until_ready is "
                       "not a reliable barrier on the tunneled runtime)"
                       .format(n_steps, args.reps)),
            "packed_vs_binned_useful_tokens": (
                round(packed["useful_tokens_per_s"]
                      / binned["useful_tokens_per_s"], 3)
                if packed and binned else None),
            "results": results,
        }
        with open(os.path.join(ROOT, "PACKING_BENCH.json"), "w") as f:
            json.dump(payload, f, indent=1)
        print(json.dumps({"packed_vs_binned":
                          payload["packed_vs_binned_useful_tokens"]}))
        print("wrote PACKING_BENCH.json")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
