"""Quantify sentence-splitter drift vs NLTK punkt.

The rule-based splitter (lddl_tpu.preprocess.sentences) replaces the
reference's pretrained-punkt call (lddl/dask/bert/pretrain.py:82). Every
boundary difference shifts downstream NSP pair boundaries, so the drift
must be a measured number, not an assumption.

Punkt source, in order of preference:
1. the pretrained English model, when nltk_data is present (what the
   reference uses);
2. a PunktTrainer trained unsupervised on the input sample itself — the
   documented way punkt models are built, usable offline.

Metrics (punkt as the reference):
- boundary precision/recall/F1 over character end-offsets of sentences;
- % of documents whose boundary sets match exactly;
- sentence-length (whitespace tokens) histogram shift: total-variation
  distance between the two normalized histograms — the downstream
  num_tokens effect.

Usage:
  python benchmarks/splitter_drift.py [--input FILE ...] \
      [--out SPLITTER_DRIFT.json]

Without --input, harvests real English prose available offline: license
texts under site-packages (legal prose, abbreviation-heavy) and Python
stdlib docstrings (technical prose).
"""

import argparse
import collections
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _harvest_default_sample(max_bytes=1_500_000):
    """Real English prose reachable without egress."""
    texts = []
    total = 0
    # 1. License / notice files: legal English, dense with Inc., Ltd.,
    #    U.S., e.g., No. — the abbreviation cases that stress a splitter.
    import glob
    import site
    candidates = []
    for sp in site.getsitepackages():
        candidates += sorted(glob.glob(
            os.path.join(sp, "**", "*NOTICES*.txt"), recursive=True))
        candidates += sorted(glob.glob(
            os.path.join(sp, "**", "LICENSE*"), recursive=True))
    for path in sorted(set(candidates)):
        try:
            with open(path, encoding="utf-8", errors="ignore") as f:
                texts.append(f.read())
                total += len(texts[-1])
        except OSError:
            continue
        if total > max_bytes // 2:
            break
    # 2. Stdlib docstrings: technical prose with versions, refs, etc.
    import pydoc
    mods = ["os", "json", "logging", "argparse", "subprocess", "threading",
            "multiprocessing", "socket", "email", "http.client", "tarfile",
            "difflib", "pickle", "datetime", "decimal", "unittest", "re"]
    for name in mods:
        try:
            mod = __import__(name, fromlist=["x"])
        except ImportError:
            continue
        doc = pydoc.render_doc(mod, renderer=pydoc.plaintext)
        texts.append(doc)
        total += len(doc)
        if total > max_bytes:
            break
    return texts


def _paragraphs(texts, min_len=200, max_len=4000):
    """One-line-ish prose paragraphs (what the pipeline feeds the
    splitter: documents are single lines by the source contract)."""
    paras = []
    seen = set()
    for text in texts:
        for block in re.split(r"\n\s*\n", text):
            flat = " ".join(block.split())
            # Keep prose-looking paragraphs: mostly words, some sentence
            # punctuation, not tables/code. Dedupe: the same license text
            # ships in dozens of packages and would dominate both the
            # punkt training set and the counts.
            if not (min_len <= len(flat) <= max_len) or flat in seen:
                continue
            letters = sum(c.isalpha() or c.isspace() for c in flat)
            if letters / len(flat) < 0.8 or "." not in flat:
                continue
            seen.add(flat)
            paras.append(flat)
    return paras


def _punkt(paras):
    """(tokenizer.tokenize, source_tag)."""
    try:
        import nltk.data
        tok = nltk.data.load("tokenizers/punkt/english.pickle")
        return tok.tokenize, "pretrained-english"
    except LookupError:
        from nltk.tokenize.punkt import PunktSentenceTokenizer, PunktTrainer
        trainer = PunktTrainer()
        trainer.INCLUDE_ALL_COLLOCS = True
        trainer.train("\n".join(paras), finalize=False)
        tok = PunktSentenceTokenizer(trainer.get_params())
        return tok.tokenize, "self-trained"


def _boundaries(text, sentences):
    """Character end-offset of each sentence within ``text`` (whitespace-
    insensitive: offsets count non-space chars consumed)."""
    ends = []
    consumed = 0
    for s in sentences:
        consumed += sum(1 for c in s if not c.isspace())
        ends.append(consumed)
    return set(ends[:-1])  # the final boundary is trivially shared


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--input", nargs="*", default=None)
    p.add_argument("--out", default=os.path.join(ROOT,
                                                 "SPLITTER_DRIFT.json"))
    args = p.parse_args()

    from lddl_tpu.preprocess.sentences import (split_sentences,
                                               split_sentences_learned,
                                               train_splitter_params)

    if args.input:
        texts = [open(f, encoding="utf-8", errors="ignore").read()
                 for f in args.input]
    else:
        texts = _harvest_default_sample()
    paras = _paragraphs(texts)
    if not paras:
        raise SystemExit("no prose paragraphs found in the sample")

    punkt_tokenize, punkt_src = _punkt(paras)
    learned = train_splitter_params(paras)

    def measure(split_fn):
        tp = fp = fn = 0
        identical_docs = 0
        ours_hist = collections.Counter()
        punkt_hist = collections.Counter()
        n_sent_ours = n_sent_punkt = 0
        miss_categories = collections.Counter()
        for text in paras:
            ours = split_fn(text)
            ref = [s for s in punkt_tokenize(text) if s.strip()]
            b_ours = _boundaries(text, ours)
            b_ref = _boundaries(text, ref)
            tp += len(b_ours & b_ref)
            fp += len(b_ours - b_ref)
            fn += len(b_ref - b_ours)
            identical_docs += b_ours == b_ref
            # Categorize punkt-only boundaries by what follows them.
            nonspace = [c for c in text if not c.isspace()]
            for b in (b_ref - b_ours):
                nxt = nonspace[b] if b < len(nonspace) else ""
                if nxt.islower():
                    miss_categories["punkt_only_next_lowercase"] += 1
                elif not nxt.isalnum():
                    miss_categories["punkt_only_next_punctuation"] += 1
                else:
                    miss_categories["punkt_only_next_upper_or_digit"] += 1
            for s in ours:
                ours_hist[min(len(s.split()), 128)] += 1
            for s in ref:
                punkt_hist[min(len(s.split()), 128)] += 1
            n_sent_ours += len(ours)
            n_sent_punkt += len(ref)
        precision = tp / max(tp + fp, 1)
        recall = tp / max(tp + fn, 1)
        f1 = 2 * precision * recall / max(precision + recall, 1e-9)
        keys = set(ours_hist) | set(punkt_hist)
        tv = 0.5 * sum(abs(ours_hist[k] / n_sent_ours
                           - punkt_hist[k] / n_sent_punkt) for k in keys)
        return {
            "boundary_precision": round(precision, 4),
            "boundary_recall": round(recall, 4),
            "boundary_f1": round(f1, 4),
            "identical_doc_fraction": round(identical_docs / len(paras), 4),
            "sentences": {"ours": n_sent_ours, "punkt": n_sent_punkt},
            "seq_len_hist_total_variation": round(tv, 4),
            "punkt_only_breakdown": dict(miss_categories),
        }

    payload = {
        "punkt_source": punkt_src,
        "sample": {"paragraphs": len(paras),
                   "bytes": sum(len(t) for t in paras)},
        "rules": measure(split_sentences),
        "learned": measure(lambda t: split_sentences_learned(t, learned)),
        "learned_params": {
            "abbrev_types": len(learned.abbrev_types),
            "collocations": len(learned.collocations),
            "sent_starters": len(learned.sent_starters),
            "ortho_context": len(learned.ortho_context),
        },
        "note": ("'rules' = the static rule-based splitter (pipeline "
                 "default, zero dependencies); 'learned' = corpus-trained "
                 "punkt parameters + the punkt decision procedure "
                 "(--splitter learned; nltk needed at train time only, "
                 "decision runs in Python AND the C++ engine, "
                 "fuzz-pinned). The oracle is punkt trained on the same "
                 "sample" + (" (self-trained: the pretrained English "
                             "model needs egress this image lacks)"
                             if punkt_src == "self-trained" else "") +
                 "; residual 'learned' diffs are punkt-internal word-"
                 "tokenization edge cases."),
    }
    print(json.dumps(payload, indent=1))
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote", args.out)


if __name__ == "__main__":
    main()
