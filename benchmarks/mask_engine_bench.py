"""Isolated masking-kernel benchmark: numpy host kernel vs jit'd JAX.

Answers the round-1 verdict question ("put the TPU in the hot path — or
prove it shouldn't be") with a measurement: per-chunk wall time and
rows/s for the static-masking kernel at bench-realistic shapes, on
whatever backend JAX resolves (the real TPU chip under the driver; CPU
when forced).

Writes MASK_ENGINE_BENCH.json at the repo root.

Usage: python benchmarks/mask_engine_bench.py [--rows-log2 8 15]
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np

from lddl_tpu.utils.rng import sample_rng


def _inputs(n, width, vocab, seed):
    # Keyed Philox stream (utils.rng contract) instead of ad-hoc numpy
    # seeding: bench inputs stay bit-identical across numpy releases.
    g = sample_rng(seed)
    lens = g.integers(8, width, n)
    ids = g.integers(10, vocab, (n, width)).astype(np.int32)
    valid = np.arange(width)[None, :] < lens[:, None]
    candidate = valid.copy()
    candidate[:, 0] = False
    from lddl_tpu.ops import plan_num_to_predict
    num = plan_num_to_predict(lens, 0.15, 76)
    return ids, candidate, num


def _time(fn, *args, reps=5):
    fn(*args)  # warm (includes any jit compile)
    t0 = time.perf_counter()
    for _ in range(reps):
        fn(*args)
    return (time.perf_counter() - t0) / reps


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows-log2", type=int, nargs=2, default=(8, 15))
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--vocab", type=int, default=30522)
    p.add_argument("--out", default=os.path.join(ROOT,
                                                 "MASK_ENGINE_BENCH.json"))
    args = p.parse_args()

    import jax
    from lddl_tpu.ops import make_jax_masker, mask_batch_numpy
    from lddl_tpu.utils import rng as lrng

    backend = jax.devices()[0].platform
    masker = make_jax_masker(103, args.vocab)
    results = []
    for log2 in range(args.rows_log2[0], args.rows_log2[1] + 1):
        n = 1 << log2
        ids, candidate, num = _inputs(n, args.width, args.vocab, seed=log2)

        def run_numpy():
            mask_batch_numpy(ids, candidate, num, lrng.sample_rng(1, log2),
                             103, args.vocab)

        def run_jax():
            masker(ids, candidate, num, seed=log2)

        t_np = _time(run_numpy)
        t_jx = _time(run_jax)
        results.append({
            "rows": n,
            "width": args.width,
            "numpy_ms": round(t_np * 1e3, 3),
            "jax_ms": round(t_jx * 1e3, 3),
            "numpy_rows_per_s": round(n / t_np),
            "jax_rows_per_s": round(n / t_jx),
            "jax_speedup": round(t_np / t_jx, 3),
        })
        print(results[-1], flush=True)

    payload = {"jax_backend": backend, "results": results}
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote", args.out)


if __name__ == "__main__":
    main()
