"""Loader throughput benchmark: records samples/s + ms/batch to the repo.

Builds a Zipf corpus with bench.make_corpus (the adversarial generator the
preprocessing benchmark uses), preprocesses it in both shard schemas
(binned+static and unbinned+dynamic, schema v1 text-only and schema v2
token-id columnar), balances, then runs benchmarks/mock_train.py as a
subprocess per configuration — the measured numbers are exactly what the
reference-style harness prints (ref: benchmarks/torch_train.py:188-199).

Noise control: every configuration runs ``--runs`` times (default 3) and
reports the MEDIAN sustained rate (host-noise artifacts like the round-4
w4proc phantom regression, VERDICT r4 #6, cannot recur as a single bad
sample); process-mode rows also record the framed pickle bytes/batch the
worker queues actually carried.

Writes LOADER_BENCH.json at the repo root:
    {"configs": {name: {"samples_per_s": .., "ms_per_batch": ..,
                        "sustained_samples_per_s": <median>,
                        "sustained_runs": [..], "pad_ratio": ..,
                        "queue_bytes_per_batch": ..}},
     "schema_v2_speedup": {..}, ...}

Usage: python benchmarks/loader_bench.py [--mb 8] [--runs 3] [--smoke]
       [--out LOADER_BENCH.json]
"""

import argparse
import json
import os
import re
import statistics
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from lddl_tpu.utils.cpus import usable_cpu_count  # noqa: E402

# (masking, bin_size, schema_version, pack_seq_length, pack_max_per_row)
# per buildable dataset. The v1 datasets keep their historical names so
# rows stay comparable across bench rounds; the *_v2 twins hold the same
# corpus in columnar shards; the packed_off_* twins hold it pre-packed by
# the OFFLINE FFD sink (preprocess/packing.py) so the loader streams
# already-packed rows zero-copy.
_DATASET_SPECS = {
    "static_binned": (True, 32, 1, None, 8),
    "dynamic_unbinned": (False, None, 1, None, 8),
    "static_binned_v2": (True, 32, 2, None, 8),
    "dynamic_unbinned_v2": (False, None, 2, None, 8),
    "static_unbinned_v2": (True, None, 2, None, 8),
    "packed_off_L128": (False, None, 2, 128, 16),
    "packed_off_L512": (False, None, 2, 512, 64),
    "packed_off_L512_static": (True, None, 2, 512, 64),
}


def _build_dataset(tmp, mb, which=None):
    """``which``: build only the named dataset(s) (keys of
    _DATASET_SPECS); None builds all (the full bench)."""
    from bench import make_corpus
    from lddl_tpu.preprocess import (BertPretrainConfig, build_wordpiece_vocab,
                                     get_tokenizer, run_bert_preprocess)
    from lddl_tpu.balance import balance_shards

    corpus = os.path.join(tmp, "corpus")
    make_corpus(corpus, mb, seed=0)
    sample = []
    sample_bytes = 0
    with open(os.path.join(corpus, "source", "0.txt"), encoding="utf-8") as f:
        for line in f:
            sample.append(line.split(None, 1)[1])
            sample_bytes += len(line)
            if sample_bytes > 1_000_000:
                break
    vocab = build_wordpiece_vocab(sample, os.path.join(tmp, "vocab.txt"),
                                  vocab_size=30522)
    tok = get_tokenizer(vocab_file=vocab)

    datasets = {}
    for name, (masking, bin_size, schema, pack_L, pack_P) \
            in _DATASET_SPECS.items():
        if which is not None and name not in which:
            continue
        pre = os.path.join(tmp, "pre_" + name)
        bal = os.path.join(tmp, "bal_" + name)
        run_bert_preprocess(
            {"wikipedia": corpus}, pre, tok,
            config=BertPretrainConfig(max_seq_length=128, duplicate_factor=1,
                                      masking=masking,
                                      schema_version=schema),
            num_blocks=8, sample_ratio=1.0, seed=12345, bin_size=bin_size,
            pack_seq_length=pack_L, pack_max_per_row=pack_P,
            num_workers=usable_cpu_count())
        balance_shards(pre, bal, 8)
        datasets[name] = bal
    return datasets, vocab


_THROUGHPUT_RE = re.compile(
    r"loader throughput: ([\d.]+) samples/s avg, ([\d.]+) ms/batch avg")
_SUSTAINED_RE = re.compile(r"loader sustained: ([\d.]+) samples/s")
_EPOCH_RE = re.compile(r"epoch \d+ sustained: ([\d.]+) samples/s")
_PAD_RE = re.compile(r"padded-zero ratio: ([\d.]+)")
_STEP_RE = re.compile(r"train step: ([\d.]+) ms avg")
_QUEUE_RE = re.compile(r"loader queue: ([\d.]+) bytes/batch")


def _run_mock_train_once(path, vocab, extra, batch_size, epochs=2):
    cmd = [sys.executable, os.path.join(ROOT, "benchmarks", "mock_train.py"),
           "--path", path, "--vocab-file", vocab, "--epochs", str(epochs),
           "--batch-size", str(batch_size), "--log-freq", "1000000"] + extra
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=ROOT)
    if proc.returncode != 0:
        raise RuntimeError("mock_train failed ({}):\n{}".format(
            proc.returncode, proc.stderr[-4000:]))
    out = proc.stdout
    m = _THROUGHPUT_RE.search(out)
    ms = _SUSTAINED_RE.search(out)
    if m is None or ms is None:
        raise RuntimeError(
            "mock_train output missing summary lines:\n" + out[-4000:])
    result = {"samples_per_s": float(m.group(1)),
              "ms_per_batch": float(m.group(2)),
              "sustained_samples_per_s": float(ms.group(1))}
    epoch_rates = [float(r) for r in _EPOCH_RE.findall(out)]
    if epoch_rates:
        result["epoch_samples_per_s"] = epoch_rates
        if len(epoch_rates) >= 2:
            # Epoch 0 is the cold pass; the last epoch runs against a
            # warm shard cache (the warm_epoch acceptance number).
            result["warm_epoch_samples_per_s"] = epoch_rates[-1]
    for key, rx in (("pad_ratio", _PAD_RE), ("train_step_ms", _STEP_RE),
                    ("queue_bytes_per_batch", _QUEUE_RE)):
        found = rx.search(out)
        if found:
            result[key] = float(found.group(1))
    return result


def _run_mock_train(path, vocab, extra, batch_size, runs=3, epochs=2):
    """Median-of-``runs`` sustained rate (plus the matching burst/latency
    numbers from the median run) so one noisy host interval cannot fake a
    regression; the raw per-run sustained rates are recorded alongside."""
    samples = [_run_mock_train_once(path, vocab, extra, batch_size,
                                    epochs=epochs)
               for _ in range(runs)]
    sustained = [s["sustained_samples_per_s"] for s in samples]
    median = statistics.median_low(sustained)
    result = dict(samples[sustained.index(median)])
    result["sustained_runs"] = sustained
    return result


_CACHE_PROBE_SHARDS = 32


def _build_cache_probe(tmp, vocab, sample_ratio):
    """Datasets for the cache/prefetch headline pair: a small sample of
    the bench corpus balanced into MANY small shards (latency hiding
    scales with op COUNT, not bytes), built once per backend — the mock
    twin's shards must be real versioned store objects, so its build
    runs with LDDL_TPU_STORAGE_BACKEND=mock end to end."""
    from lddl_tpu.preprocess import (BertPretrainConfig, get_tokenizer,
                                     run_bert_preprocess)
    from lddl_tpu.balance import balance_shards

    corpus = os.path.join(tmp, "corpus")
    tok = get_tokenizer(vocab_file=vocab)
    out = {}
    for backend in ("local", "mock"):
        pre = os.path.join(tmp, "cache_pre_" + backend)
        bal = os.path.join(tmp, "cache_bal_" + backend)
        if backend == "mock":
            os.environ["LDDL_TPU_STORAGE_BACKEND"] = "mock"
        try:
            run_bert_preprocess(
                {"wikipedia": corpus}, pre, tok,
                config=BertPretrainConfig(max_seq_length=128,
                                          duplicate_factor=1, masking=True,
                                          schema_version=2),
                num_blocks=_CACHE_PROBE_SHARDS, sample_ratio=sample_ratio,
                seed=12345, bin_size=None,
                num_workers=usable_cpu_count())
            balance_shards(pre, bal, _CACHE_PROBE_SHARDS)
        finally:
            os.environ.pop("LDDL_TPU_STORAGE_BACKEND", None)
        out[backend] = bal
    return out


_CACHE_PROBE_EPOCHS = 8


def _cache_prefetch_block(probe, vocab, args):
    """The tentpole measurement: loader sustained rate over the mock
    object store with per-op latency injected, shard prefetch+cache ON
    vs the synchronous baseline (prefetch 0, cache 0), with the local-FS
    path as the target to chase. All three legs run the same shard
    count, batch size, epoch count, and median-of-runs protocol. The
    trio runs MORE epochs than the throughput configs: the synchronous
    path pays the per-op latency every epoch while the cache pays one
    cold fetch pass total, so the sustained rate over E epochs is the
    steady-state claim (the per-epoch rates record the cold/warm
    split; warm_epoch_samples_per_s is the last epoch)."""
    lat = args.backend_latency_ms
    w1 = ["--num-workers", "1"]
    local = _run_mock_train(probe["local"], vocab, w1, args.batch_size,
                            runs=args.runs, epochs=_CACHE_PROBE_EPOCHS)
    print("cache_local", local, flush=True)
    sync = _run_mock_train(
        probe["mock"], vocab,
        w1 + ["--storage-backend", "mock",
              "--backend-latency-ms", str(lat),
              "--prefetch-shards", "0", "--cache-bytes", "0"],
        args.batch_size, runs=args.runs, epochs=_CACHE_PROBE_EPOCHS)
    print("cache_mock_sync", sync, flush=True)
    pref = _run_mock_train(
        probe["mock"], vocab,
        w1 + ["--storage-backend", "mock",
              "--backend-latency-ms", str(lat)],
        args.batch_size, runs=args.runs, epochs=_CACHE_PROBE_EPOCHS)
    print("cache_mock_prefetch", pref, flush=True)
    key = "sustained_samples_per_s"
    wkey = "warm_epoch_samples_per_s"
    block = {
        "backend_latency_ms": lat,
        "shards": _CACHE_PROBE_SHARDS,
        "epochs": _CACHE_PROBE_EPOCHS,
        "local": local,
        "mock_sync": sync,
        "mock_prefetch": pref,
        "prefetch_over_sync": round(pref[key] / max(sync[key], 1e-9), 3),
        "prefetch_over_local": round(pref[key] / max(local[key], 1e-9), 3),
    }
    if wkey in pref and wkey in local:
        block["warm_epoch_over_local_epoch"] = round(
            pref[wkey] / max(local[wkey], 1e-9), 3)
    return block


def _median_of(fn, runs):
    """Median sustained rate over ``runs`` single-epoch measurements (the
    packed pairs are single-epoch loops, so host noise needs the same
    treatment mock_train configs get)."""
    samples = [fn() for _ in range(max(1, runs))]
    rates = [s["samples_per_s"] for s in samples]
    result = dict(samples[rates.index(statistics.median_low(rates))])
    result["sustained_runs"] = rates
    return result


def _run_packed(path, vocab, batch_size, L=128, rows=16, max_per_row=16,
                runs=3):
    """Load-time (greedy) packing efficiency + throughput (VERDICT r2 #4:
    the pad-FLOPs binning leaves behind — LOADER_BENCH pad_ratio 3.9%
    binned / 12.8% unbinned — reclaimed by packing; measured, not
    assumed). Kept as the baseline the offline-packed path must beat."""
    import time
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    def once():
        loader = get_bert_pretrain_data_loader(
            path, vocab_file=vocab, batch_size=batch_size, num_workers=2,
            pack_seq_length=L, pack_rows=rows,
            pack_max_per_row=max_per_row)
        t0 = time.perf_counter()
        n_batches = 0
        for _ in loader:
            n_batches += 1
        dt = time.perf_counter() - t0
        return {
            "samples_per_s": round(loader.n_samples / dt, 1),
            "sustained_samples_per_s": round(loader.n_samples / dt, 1),
            "ms_per_batch": round(dt / max(n_batches, 1) * 1e3, 2),
            "pad_ratio": round(loader.pad_ratio, 4),
            "pack_seq_length": L,
            "pack_rows": rows,
            "n_samples": loader.n_samples,
        }

    return _median_of(once, runs)


def _run_packed_offline(path, vocab, rows, runs=3):
    """Offline-packed (pre-packed schema-v2 shards): the loader streams
    already-FFD-packed rows zero-copy and only scatter-encodes; the row
    shape comes off the shard metadata. Sample counts and pad are read
    from the batches themselves (real NSP slots / attention mask)."""
    import time
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    def once():
        loader = get_bert_pretrain_data_loader(
            path, vocab_file=vocab, batch_size=rows, num_workers=2)
        t0 = time.perf_counter()
        n_batches = n_samples = real = slots = 0
        L = None
        for batch in loader:
            n_batches += 1
            L = batch["input_ids"].shape[1]
            n_samples += int((batch["next_sentence_labels"] != -1).sum())
            real += int(batch["attention_mask"].sum())
            slots += int(batch["attention_mask"].size)
        dt = time.perf_counter() - t0
        return {
            "samples_per_s": round(n_samples / dt, 1),
            "sustained_samples_per_s": round(n_samples / dt, 1),
            "ms_per_batch": round(dt / max(n_batches, 1) * 1e3, 2),
            "pad_ratio": round(1.0 - real / max(slots, 1), 4),
            "pack_seq_length": L,
            "pack_rows": rows,
            "n_samples": n_samples,
            "offline_packed": True,
        }

    return _median_of(once, runs)


# Offline-packed config -> its load-time-packer baseline (same corpus,
# same row shape): the acceptance pair for the offline packer — samples/s
# must go UP at equal-or-better pad_ratio.
_PACKED_OFFLINE_PAIRS = (
    ("packed_offline_L128_w2", "packed_L128_w2_v2"),
    ("packed_offline_L512_w2", "packed_L512_w2_v2"),
    ("packed_offline_L512_static", "packed_L512_v2_static"),
)


def _packed_offline_speedup(results):
    out = {}
    for off_name, base_name in _PACKED_OFFLINE_PAIRS:
        off, base = results.get(off_name), results.get(base_name)
        if not off or not base:
            continue
        out[off_name] = {
            "loadtime_samples_per_s": base["samples_per_s"],
            "offline_samples_per_s": off["samples_per_s"],
            "offline_over_loadtime": round(
                off["samples_per_s"] / max(base["samples_per_s"], 1e-9), 3),
            "loadtime_pad_ratio": base["pad_ratio"],
            "offline_pad_ratio": off["pad_ratio"],
            "pad_ratio_not_worse": (off["pad_ratio"] <= base["pad_ratio"]),
        }
    return out


# v2 configs whose schema-v1 twin runs under a historical name (same
# dataset, batch size, and worker flags) — _schema_speedup pairs them so
# the comparison is never silently dropped.
_V1_TWIN_ALIASES = {
    "schema_v2_unbinned_w4proc": "dynamic_unbinned_w4proc",
}


def _schema_speedup(results):
    """v2-over-v1 sustained ratio per paired config (same corpus, batch
    size, worker mode — the same-run comparison the acceptance criterion
    names), with the pad_ratio parity check alongside."""
    out = {}
    for v2name, row in results.items():
        if not v2name.startswith("schema_v2_"):
            continue
        v1name = v2name.replace("schema_v2_", "schema_v1_")
        base = results.get(v1name) or results.get(
            _V1_TWIN_ALIASES.get(v2name, ""))
        if not base:
            continue
        ratio = (row["sustained_samples_per_s"]
                 / max(base["sustained_samples_per_s"], 1e-9))
        out[v2name.replace("schema_v2_", "")] = {
            "v1_sustained": base["sustained_samples_per_s"],
            "v2_sustained": row["sustained_samples_per_s"],
            "v2_over_v1": round(ratio, 3),
            "pad_ratio_unchanged": (row.get("pad_ratio")
                                    == base.get("pad_ratio")),
        }
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mb", type=float, default=8.0)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--runs", type=int, default=3,
                   help="measurements per config; the median sustained "
                        "rate is reported")
    p.add_argument("--out", default=None,
                   help="default LOADER_BENCH.json (LOADER_BENCH_SMOKE"
                        ".json with --smoke)")
    p.add_argument("--smoke", action="store_true",
                   help="CI artifact mode: 1 MB corpus, single run, only "
                        "the v1-vs-v2 unbinned pair plus the offline-vs-"
                        "loadtime packed pair — a JSON health sample, not "
                        "a quotable benchmark")
    p.add_argument("--with-model", action="store_true",
                   help="also measure with a jitted tiny-BERT train step")
    p.add_argument("--backend-latency-ms", type=float, default=20.0,
                   help="per-op latency injected into the mock object "
                        "store for the cache_prefetch_speedup pair (the "
                        "first-class knob replacing hand-built "
                        "LDDL_TPU_FAULTS specs)")
    p.add_argument("--cache-only", action="store_true",
                   help="measure ONLY the shard cache/prefetch pair and "
                        "merge the cache_prefetch_speedup block into an "
                        "existing --out artifact (cheap re-measurement "
                        "of the tentpole without rebuilding every "
                        "dataset)")
    args = p.parse_args()
    if args.smoke:
        args.mb = min(args.mb, 1.0)
        args.runs = 1
    if args.out is None:
        args.out = os.path.join(ROOT, "LOADER_BENCH_SMOKE.json"
                                if args.smoke else "LOADER_BENCH.json")

    tmp = tempfile.mkdtemp(prefix="lddl_loader_bench_")
    try:
        if args.cache_only:
            # Build only the corpus + vocab (which=() skips every
            # dataset spec) and the probe twins, then merge the block
            # into the existing artifact.
            _, vocab = _build_dataset(tmp, args.mb, which=())
            probe = _build_cache_probe(tmp, vocab,
                                       sample_ratio=min(1.0,
                                                        6.0 / args.mb))
            block = _cache_prefetch_block(probe, vocab, args)
            doc = {}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    doc = json.load(f)
            doc["cache_prefetch_speedup"] = block
            with open(args.out, "w") as f:
                json.dump(doc, f, indent=1)
            print("cache_prefetch_speedup", block, flush=True)
            print("wrote", args.out)
            return
        which = (("dynamic_unbinned", "dynamic_unbinned_v2",
                  "packed_off_L128")
                 if args.smoke else None)
        datasets, vocab = _build_dataset(tmp, args.mb, which=which)
        cache_block = None
        if not args.smoke:
            # The tentpole pair (prefetch+cache vs synchronous over the
            # latency-injected mock store); the CI smoke equivalent is
            # benchmarks/cache_smoke.py.
            probe = _build_cache_probe(tmp, vocab,
                                       sample_ratio=min(1.0,
                                                        6.0 / args.mb))
            cache_block = _cache_prefetch_block(probe, vocab, args)
        dyn, dyn2 = datasets["dynamic_unbinned"], datasets["dynamic_unbinned_v2"]
        configs = {
            # v1/v2 same-run pairs (the schema_v2_speedup inputs).
            "schema_v1_unbinned_w1": (dyn, ["--num-workers", "1"]),
            "schema_v2_unbinned_w1": (dyn2, ["--num-workers", "1"]),
        }
        if not args.smoke:
            sb, sb2 = datasets["static_binned"], datasets["static_binned_v2"]
            configs.update({
                "schema_v1_binned_w1": (sb, ["--num-workers", "1"]),
                "schema_v2_binned_w1": (sb2, ["--num-workers", "1"]),
                # Historical configs (v1 datasets, same names as previous
                # rounds so the rows stay comparable).
                "dynamic_unbinned_w1": (dyn, ["--num-workers", "1"]),
                "dynamic_unbinned_w4": (dyn, ["--num-workers", "4"]),
                "static_binned_w1": (sb, ["--num-workers", "1"]),
                "static_binned_w4": (sb, ["--num-workers", "4"]),
                "dynamic_unbinned_w4proc": (
                    dyn, ["--num-workers", "4", "--worker-mode", "process"]),
                "static_binned_w4proc": (
                    sb, ["--num-workers", "4", "--worker-mode", "process"]),
                # v2 through the process-worker queue (qserde framing).
                "schema_v2_unbinned_w4proc": (
                    dyn2, ["--num-workers", "4", "--worker-mode", "process"]),
            })
        if args.with_model:
            configs["static_binned_w4_model"] = (
                datasets["static_binned"],
                ["--num-workers", "4", "--with-model", "tiny",
                 "--fixed-seq-lengths", "32", "64", "96", "128"])
        results = {}
        # The packed pairs run in smoke mode too (CI artifact): the
        # offline-vs-loadtime ratio is the packer's health number.
        results["packed_L128_w2_v2"] = _run_packed(
            dyn2, vocab, args.batch_size, runs=args.runs)
        print("packed_L128_w2_v2", results["packed_L128_w2_v2"],
              flush=True)
        results["packed_offline_L128_w2"] = _run_packed_offline(
            datasets["packed_off_L128"], vocab, rows=16, runs=args.runs)
        print("packed_offline_L128_w2", results["packed_offline_L128_w2"],
              flush=True)
        if not args.smoke:
            results["packed_L128_w2"] = _run_packed(
                dyn, vocab, args.batch_size, runs=args.runs)
            print("packed_L128_w2", results["packed_L128_w2"], flush=True)
            # STEP_PROFILE's headline training config runs seq_len=512:
            # measure the packed paths at that budget too, not only L128.
            results["packed_L512_w2_v2"] = _run_packed(
                dyn2, vocab, args.batch_size, L=512, rows=4,
                max_per_row=64, runs=args.runs)
            print("packed_L512_w2_v2", results["packed_L512_w2_v2"],
                  flush=True)
            results["packed_offline_L512_w2"] = _run_packed_offline(
                datasets["packed_off_L512"], vocab, rows=4,
                runs=args.runs)
            print("packed_offline_L512_w2",
                  results["packed_offline_L512_w2"], flush=True)
            # Static masking at the headline L512 budget: the packed
            # pair with no load-time dynamic-masking cost on either side
            # (phase-2 pretraining's static-shard configuration).
            results["packed_L512_v2_static"] = _run_packed(
                datasets["static_unbinned_v2"], vocab, args.batch_size,
                L=512, rows=4, max_per_row=64, runs=args.runs)
            print("packed_L512_v2_static",
                  results["packed_L512_v2_static"], flush=True)
            results["packed_offline_L512_static"] = _run_packed_offline(
                datasets["packed_off_L512_static"], vocab, rows=4,
                runs=args.runs)
            print("packed_offline_L512_static",
                  results["packed_offline_L512_static"], flush=True)
        for name, (path, extra) in configs.items():
            results[name] = _run_mock_train(path, vocab, extra,
                                            args.batch_size, runs=args.runs)
            print(name, results[name], flush=True)
            # Worker-scaling verdict (VERDICT r4 #8), recorded here; the
            # hard assert lives in tests/test_loader.py::
            # test_thread_workers_scale_on_multicore, which un-skips on
            # the first >= 4-core host. On < 4 cores w4 == w1 is the
            # expected (and honest) result.
            scaling = None
            w1 = results.get("static_binned_w1")
            w4 = results.get("static_binned_w4")
            if w1 and w4:
                key = "sustained_samples_per_s"
                multicore = usable_cpu_count() >= 4
                wins = w4[key] > w1[key]
                scaling = {
                    "metric": key,
                    "thread_w4_over_w1": round(w4[key] / w1[key], 3),
                    "host_can_show_scaling": multicore,
                    "verdict": ("w4 > w1" if wins else "w4 <= w1 ({})".
                                format("INVESTIGATE: multi-core host"
                                       if multicore else
                                       "expected on a < 4-core host")),
                }
            payload = {
                "unit": "samples/s (loader-only wall clock incl. decode, "
                        "shuffle buffer, collate, dynamic masking)",
                "corpus_mb": args.mb,
                "batch_size": args.batch_size,
                "cpu_count": os.cpu_count(),
                "usable_cpus": usable_cpu_count(),
                # Stamped next to every scaling number (ISSUE 15): a
                # < 4-core bench host cannot exhibit worker scaling, so
                # readers of the artifact must not treat flat ratios
                # from such a host as a regression.
                "host_can_show_scaling": usable_cpu_count() >= 2,
                "runs_per_config": args.runs,
                "smoke": args.smoke,
                "worker_scaling": scaling,
                "schema_v2_speedup": _schema_speedup(results),
                "packed_offline_speedup": _packed_offline_speedup(results),
                "cache_prefetch_speedup": cache_block,
                "configs": results,
            }
            # Written incrementally so a late-config crash keeps the rest.
            with open(args.out, "w") as f:
                json.dump(payload, f, indent=1)
        print("wrote", args.out)
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
