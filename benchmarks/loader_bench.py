"""Loader throughput benchmark: records samples/s + ms/batch to the repo.

Builds a Zipf corpus with bench.make_corpus (the adversarial generator the
preprocessing benchmark uses), preprocesses it twice (binned+static and
unbinned+dynamic), balances, then runs benchmarks/mock_train.py as a
subprocess per configuration — the measured numbers are exactly what the
reference-style harness prints (ref: benchmarks/torch_train.py:188-199).

Writes LOADER_BENCH.json at the repo root:
    {"configs": {name: {"samples_per_s": .., "ms_per_batch": ..,
                        "pad_ratio": ..}}, ...}

Usage: python benchmarks/loader_bench.py [--mb 8] [--out LOADER_BENCH.json]
"""

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _build_dataset(tmp, mb, which=None):
    """``which``: build only the named dataset(s) ("static_binned" /
    "dynamic_unbinned"); None builds both (the full bench)."""
    from bench import make_corpus
    from lddl_tpu.preprocess import (BertPretrainConfig, build_wordpiece_vocab,
                                     get_tokenizer, run_bert_preprocess)
    from lddl_tpu.balance import balance_shards

    corpus = os.path.join(tmp, "corpus")
    make_corpus(corpus, mb, seed=0)
    sample = []
    sample_bytes = 0
    with open(os.path.join(corpus, "source", "0.txt"), encoding="utf-8") as f:
        for line in f:
            sample.append(line.split(None, 1)[1])
            sample_bytes += len(line)
            if sample_bytes > 1_000_000:
                break
    vocab = build_wordpiece_vocab(sample, os.path.join(tmp, "vocab.txt"),
                                  vocab_size=30522)
    tok = get_tokenizer(vocab_file=vocab)

    datasets = {}
    for name, masking, bin_size in (("static_binned", True, 32),
                                    ("dynamic_unbinned", False, None)):
        if which is not None and name not in which:
            continue
        pre = os.path.join(tmp, "pre_" + name)
        bal = os.path.join(tmp, "bal_" + name)
        run_bert_preprocess(
            {"wikipedia": corpus}, pre, tok,
            config=BertPretrainConfig(max_seq_length=128, duplicate_factor=1,
                                      masking=masking),
            num_blocks=8, sample_ratio=1.0, seed=12345, bin_size=bin_size,
            num_workers=os.cpu_count())
        balance_shards(pre, bal, 8)
        datasets[name] = bal
    return datasets, vocab


_THROUGHPUT_RE = re.compile(
    r"loader throughput: ([\d.]+) samples/s avg, ([\d.]+) ms/batch avg")
_SUSTAINED_RE = re.compile(r"loader sustained: ([\d.]+) samples/s")
_PAD_RE = re.compile(r"padded-zero ratio: ([\d.]+)")
_STEP_RE = re.compile(r"train step: ([\d.]+) ms avg")


def _run_mock_train(path, vocab, extra, batch_size):
    cmd = [sys.executable, os.path.join(ROOT, "benchmarks", "mock_train.py"),
           "--path", path, "--vocab-file", vocab, "--epochs", "2",
           "--batch-size", str(batch_size), "--log-freq", "1000000"] + extra
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=ROOT)
    if proc.returncode != 0:
        raise RuntimeError("mock_train failed ({}):\n{}".format(
            proc.returncode, proc.stderr[-4000:]))
    out = proc.stdout
    m = _THROUGHPUT_RE.search(out)
    ms = _SUSTAINED_RE.search(out)
    if m is None or ms is None:
        raise RuntimeError(
            "mock_train output missing summary lines:\n" + out[-4000:])
    result = {"samples_per_s": float(m.group(1)),
              "ms_per_batch": float(m.group(2)),
              "sustained_samples_per_s": float(ms.group(1))}
    m = _PAD_RE.search(out)
    if m:
        result["pad_ratio"] = float(m.group(1))
    m = _STEP_RE.search(out)
    if m:
        result["train_step_ms"] = float(m.group(1))
    return result


def _run_packed(path, vocab, batch_size, L=128, rows=16):
    """Sequence-packing efficiency + throughput (VERDICT r2 #4: the
    pad-FLOPs binning leaves behind — LOADER_BENCH pad_ratio 3.9% binned /
    12.8% unbinned — reclaimed by packing; measured, not assumed)."""
    import time
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    loader = get_bert_pretrain_data_loader(
        path, vocab_file=vocab, batch_size=batch_size, num_workers=2,
        pack_seq_length=L, pack_rows=rows, pack_max_per_row=16)
    t0 = time.perf_counter()
    n_batches = 0
    for _ in loader:
        n_batches += 1
    dt = time.perf_counter() - t0
    return {
        "samples_per_s": round(loader.n_samples / dt, 1),
        "ms_per_batch": round(dt / max(n_batches, 1) * 1e3, 2),
        "pad_ratio": round(loader.pad_ratio, 4),
        "pack_seq_length": L,
        "pack_rows": rows,
        "n_samples": loader.n_samples,
    }


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mb", type=float, default=8.0)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--out", default=os.path.join(ROOT, "LOADER_BENCH.json"))
    p.add_argument("--with-model", action="store_true",
                   help="also measure with a jitted tiny-BERT train step")
    args = p.parse_args()

    tmp = tempfile.mkdtemp(prefix="lddl_loader_bench_")
    try:
        datasets, vocab = _build_dataset(tmp, args.mb)
        configs = {
            "dynamic_unbinned_w1": (datasets["dynamic_unbinned"],
                                    ["--num-workers", "1"]),
            "dynamic_unbinned_w4": (datasets["dynamic_unbinned"],
                                    ["--num-workers", "4"]),
            "static_binned_w1": (datasets["static_binned"],
                                 ["--num-workers", "1"]),
            "static_binned_w4": (datasets["static_binned"],
                                 ["--num-workers", "4"]),
            "dynamic_unbinned_w4proc": (
                datasets["dynamic_unbinned"],
                ["--num-workers", "4", "--worker-mode", "process"]),
            "static_binned_w4proc": (
                datasets["static_binned"],
                ["--num-workers", "4", "--worker-mode", "process"]),
        }
        if args.with_model:
            configs["static_binned_w4_model"] = (
                datasets["static_binned"],
                ["--num-workers", "4", "--with-model", "tiny",
                 "--fixed-seq-lengths", "32", "64", "96", "128"])
        results = {}
        results["packed_L128_w2"] = _run_packed(
            datasets["dynamic_unbinned"], vocab, args.batch_size)
        print("packed_L128_w2", results["packed_L128_w2"], flush=True)
        for name, (path, extra) in configs.items():
            results[name] = _run_mock_train(path, vocab, extra,
                                            args.batch_size)
            print(name, results[name], flush=True)
            # Worker-scaling verdict (VERDICT r4 #8), recorded here; the
            # hard assert lives in tests/test_loader.py::
            # test_thread_workers_scale_on_multicore, which un-skips on
            # the first >= 4-core host. On < 4 cores w4 == w1 is the
            # expected (and honest) result.
            scaling = None
            w1 = results.get("static_binned_w1")
            w4 = results.get("static_binned_w4")
            if w1 and w4:
                # Sustained rate (post-warmup), the headline metric —
                # burst samples_per_s is buffer-fill noise on small runs.
                key = ("sustained_samples_per_s"
                       if "sustained_samples_per_s" in w4
                       else "samples_per_s")
                multicore = (os.cpu_count() or 1) >= 4
                wins = w4[key] > w1[key]
                scaling = {
                    "metric": key,
                    "thread_w4_over_w1": round(w4[key] / w1[key], 3),
                    "host_can_show_scaling": multicore,
                    "verdict": ("w4 > w1" if wins else "w4 <= w1 ({})".
                                format("INVESTIGATE: multi-core host"
                                       if multicore else
                                       "expected on a < 4-core host")),
                }
            payload = {
                "unit": "samples/s (loader-only wall clock incl. decode, "
                        "shuffle buffer, collate, dynamic masking)",
                "corpus_mb": args.mb,
                "batch_size": args.batch_size,
                "cpu_count": os.cpu_count(),
                "worker_scaling": scaling,
                "configs": results,
            }
            # Written incrementally so a late-config crash keeps the rest.
            with open(args.out, "w") as f:
                json.dump(payload, f, indent=1)
        print("wrote", args.out)
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
