"""CI smoke pair for elastic coordination: 2 worksteal processes on one
small corpus, legacy vs batched coordination — byte identity asserted,
lease-op ratio reported.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_BENCH=1``. The
byte-identity half is GATING (the coordination protocol must never show
up in shard bytes — the same invariant the chaos suite pins — so a
divergence exits nonzero); the lease-ops-per-unit ratio half is
informational (a 2-process minute-long smoke on a busy CI box is
weather; the committed SCALE_RUN.json phase 7 is the measurement of
record). Prints one JSON line::

    {"identical": true, "ops_per_unit": {"legacy": ..., "batched": ...},
     "ops_per_unit_ratio": ..., "units": {...}, "wall_s": {...},
     "host_can_show_scaling": false}
"""

import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402
from lddl_tpu.utils.cpus import usable_cpu_count  # noqa: E402


def _parquet_digests(out_dir):
    out = {}
    for name in sorted(os.listdir(out_dir)):
        if ".parquet" in name and ".tmp." not in name:
            h = hashlib.sha256()
            with open(os.path.join(out_dir, name), "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[name] = h.hexdigest()
    return out


def _counter(out_dir, metric, label=None):
    """Sum a counter across every host's telemetry spool snapshots
    (per-holder merge: the newest pid snapshot per holder dir already
    carries that process's full counts)."""
    total = 0
    tel = os.path.join(out_dir, ".telemetry")
    if not os.path.isdir(tel):
        return total
    for holder in sorted(os.listdir(tel)):
        d = os.path.join(tel, holder)
        if not os.path.isdir(d):
            continue
        merged = {}
        for name in sorted(os.listdir(d)):
            if not (name.startswith("snapshot-pid")
                    and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(d, name), encoding="utf-8") as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue
            values = ((snap.get("metrics") or {}).get(metric)
                      or {}).get("values") or {}
            for k, v in values.items():
                merged[k] = merged.get(k, 0) + v
        total += sum(v for k, v in merged.items()
                     if label is None or k == label)
    return total


def main():
    target_mb = float(os.environ.get("LDDL_TPU_ELASTIC_SMOKE_MB", "2"))
    tmp = tempfile.mkdtemp(prefix="lddl_elastic_smoke_")
    try:
        from lddl_tpu.preprocess import build_wordpiece_vocab

        corpus = os.path.join(tmp, "corpus")
        bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 300_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=8000)

        def cli(sink, holder):
            return [sys.executable, "-m",
                    "lddl_tpu.cli.preprocess_bert_pretrain",
                    "--wikipedia", corpus, "--sink", sink,
                    "--vocab-file", vocab, "--masking",
                    "--bin-size", "32", "--num-blocks", "16",
                    "--seed", "7", "--local-workers", "1",
                    "--elastic", "--lease-ttl", "5",
                    "--elastic-host-id", holder, "--fleet-telemetry"]

        report = {"ops_per_unit": {}, "ops_per_unit_ratio": None,
                  "units": {}, "wall_s": {},
                  "host_can_show_scaling": usable_cpu_count() >= 2}
        digests = {}
        for mode, env_extra in (("legacy", {"LDDL_TPU_COORD_LEGACY": "1"}),
                                ("batched", {})):
            sink = os.path.join(tmp, mode)
            env = dict(os.environ, JAX_PLATFORMS="cpu", **env_extra)
            t0 = time.perf_counter()
            procs = [subprocess.Popen(cli(sink, "s{}".format(i)), env=env,
                                      stdout=subprocess.DEVNULL,
                                      stderr=subprocess.STDOUT)
                     for i in range(2)]
            rcs = [p.wait(timeout=1200) for p in procs]
            report["wall_s"][mode] = round(time.perf_counter() - t0, 1)
            if rcs != [0, 0]:
                print("elastic smoke: {} leg failed rc={}".format(
                    mode, rcs), file=sys.stderr)
                return 1
            ops = _counter(sink, "lease_ops_total")
            units = _counter(sink, "elastic_units_completed_total")
            report["units"][mode] = units
            report["ops_per_unit"][mode] = round(ops / max(units, 1), 2)
            digests[mode] = _parquet_digests(sink)
        report["identical"] = (digests["legacy"] == digests["batched"]
                               and bool(digests["legacy"]))
        if report["ops_per_unit"]["batched"]:
            report["ops_per_unit_ratio"] = round(
                report["ops_per_unit"]["legacy"]
                / report["ops_per_unit"]["batched"], 2)
        print(json.dumps(report, sort_keys=True))
        if not report["identical"]:
            print("elastic smoke: legacy and batched coordination shipped "
                  "DIFFERENT bytes", file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
