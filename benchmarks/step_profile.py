"""Per-op device-time breakdown of one BERT pretraining train step.

Answers "where do the non-MFU milliseconds go" (VERDICT round 3 item 3)
with measured data: traces ONE jitted step via jax.profiler (tracing
several steps overflows the trace buffer and silently drops most leaf
events — measured), parses the Chrome trace's /device:TPU leaf events
(each carries hlo_category, model_flops, bytes_accessed and the jax op
path), and writes STEP_PROFILE.json:

- device-busy ms for the step + MFU on device-busy time,
- totals per hlo_category (matmul fusions vs loop fusions vs rng ...),
- totals per model component (embeddings / attention / ffn / mlm head /
  optimizer / dropout-rng / loss, from the tf_op path),
- the top individual ops with achieved TFLOP/s and GB/s.

Run on the real chip:
    python benchmarks/step_profile.py [--model bert_large] [--seq-len 512]
        [--batch 8] [--no-gather]
"""

import argparse
import glob
import gzip
import json
import os
import re
import sys
import tempfile
import time
from collections import defaultdict

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np

from lddl_tpu.utils.cpus import usable_cpu_count  # noqa: E402

_COMPONENTS = (
    ("optimizer", re.compile(r"transpose\(jvp\(|/adam|clip_by_global_norm|"
                             r"apply_updates|where|add_any")),
    ("embeddings", re.compile(r"/embeddings/")),
    ("attention", re.compile(r"/attention/")),
    ("ffn", re.compile(r"/ffn/")),
    ("layer_other", re.compile(r"/layer_\d+/")),
    ("mlm_head", re.compile(r"/mlm_|take_along_axis")),
    ("nsp_head", re.compile(r"/pooler|/nsp_classifier")),
    ("loss", re.compile(r"softmax_cross_entropy|/loss|argmax|top_k")),
    ("dropout_rng", re.compile(r"dropout|threefry|random_bits|fold_in")),
)


def component_of(tf_op):
    # The backward pass reuses forward op paths under transpose(jvp(...)),
    # so test model components FIRST and the optimizer bucket catches the
    # update-only ops.
    for name, rx in _COMPONENTS[1:]:
        if rx.search(tf_op):
            return name
    if _COMPONENTS[0][1].search(tf_op):
        return "optimizer"
    return "other"


def parse_one_step_trace(trace_dir):
    # sorted(): paths[0] below must not be a filesystem-order lottery.
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        raise RuntimeError("no chrome trace produced under " + trace_dir)
    with gzip.open(paths[0]) as f:
        tr = json.load(f)
    events = tr.get("traceEvents", [])
    device_pids = {e["pid"] for e in events
                   if e.get("ph") == "M" and e.get("name") == "process_name"
                   and "TPU" in str(e.get("args", {}).get("name", ""))}
    leaves = []
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        name = str(e.get("name", "?"))
        if name.startswith("jit_") or name.isdigit():
            continue  # step/program containers, not leaf ops
        args = e.get("args") or {}
        leaves.append({
            "name": name,
            "dur_us": float(e.get("dur", 0.0)),
            "category": str(args.get("hlo_category", "?")),
            "tf_op": str(args.get("tf_op", "")),
            "flops": float(args.get("model_flops", 0) or 0),
            "bytes": float(args.get("bytes_accessed", 0) or 0),
        })
    return leaves


_STEP_RE = re.compile(r"train step: ([\d.]+) ms avg")
_SUSTAINED_RE = re.compile(r"loader sustained: ([\d.]+) samples/s")
_PAD_RE = re.compile(r"padded-zero ratio: ([\d.]+)")


def _mock_train_packed(path, vocab, extra, epochs=2, with_model=True):
    import subprocess
    cmd = [sys.executable,
           os.path.join(ROOT, "benchmarks", "mock_train.py"),
           "--path", path, "--vocab-file", vocab, "--epochs", str(epochs),
           "--log-freq", "1000000"] + extra
    if with_model:
        cmd += ["--with-model", "tiny"]
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=ROOT)
    if proc.returncode != 0:
        raise RuntimeError("mock_train failed ({}):\n{}".format(
            proc.returncode, proc.stderr[-4000:]))
    out = proc.stdout
    row = {}
    keys = [("sustained_samples_per_s", _SUSTAINED_RE),
            ("pad_ratio", _PAD_RE)]
    if with_model:
        keys.append(("train_step_ms", _STEP_RE))
    for key, rx in keys:
        m = rx.search(out)
        if m is None:
            raise RuntimeError("mock_train output missing {}:\n{}".format(
                key, out[-2000:]))
        row[key] = float(m.group(1))
    return row


def packed_compare(args):
    """Offline-packed vs greedy load-time packing through the REAL model
    train step (``mock_train --with-model tiny``): same corpus, same
    (pack_seq_length x rows) batch shape, so any step-time / wall-clock
    delta is the packing path, not the math. The result is merged into
    STEP_PROFILE.json under ``packed_offline_comparison`` — the existing
    device-trace fields (recorded on the TPU round) are preserved."""
    import json as _json
    import tempfile as _tf
    sys.path.insert(0, ROOT)
    from bench import make_corpus
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.preprocess import (BertPretrainConfig,
                                     build_wordpiece_vocab, get_tokenizer,
                                     run_bert_preprocess)
    import jax
    L, rows, per_row = args.pack_seq_length, args.pack_rows, 16
    tmp = _tf.mkdtemp(prefix="lddl_packed_cmp_")
    try:
        corpus = os.path.join(tmp, "corpus")
        make_corpus(corpus, args.corpus_mb, seed=0)
        sample, sb = [], 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sb += len(line)
                if sb > 1_000_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
        tok = get_tokenizer(vocab_file=vocab)
        dirs = {}
        for name, pack in (("loadtime", None), ("offline", L)):
            pre = os.path.join(tmp, "pre_" + name)
            run_bert_preprocess(
                {"wikipedia": corpus}, pre, tok,
                config=BertPretrainConfig(max_seq_length=128,
                                          duplicate_factor=1),
                num_blocks=8, sample_ratio=1.0, seed=12345,
                pack_seq_length=pack, pack_max_per_row=per_row,
                num_workers=usable_cpu_count())
            bal = os.path.join(tmp, "bal_" + name)
            balance_shards(pre, bal, 8)
            dirs[name] = bal
        lt_flags = ["--batch-size", str(rows * per_row),
                    "--pack-seq-length", str(L), "--pack-rows", str(rows),
                    "--pack-max-per-row", str(per_row)]
        off_flags = ["--batch-size", str(rows)]
        loadtime = _mock_train_packed(dirs["loadtime"], vocab, lt_flags)
        offline = _mock_train_packed(dirs["offline"], vocab, off_flags)
        lt_loader = _mock_train_packed(dirs["loadtime"], vocab, lt_flags,
                                       with_model=False)
        off_loader = _mock_train_packed(dirs["offline"], vocab, off_flags,
                                        with_model=False)
        loadtime["loader_only_samples_per_s"] = \
            lt_loader["sustained_samples_per_s"]
        offline["loader_only_samples_per_s"] = \
            off_loader["sustained_samples_per_s"]
        comparison = {
            "device": getattr(jax.devices()[0], "device_kind",
                              str(jax.devices()[0])),
            "model": "tiny (mock_train --with-model; real jitted packed "
                     "train step, prefetch_to_device pipeline)",
            "pack_seq_length": L,
            "pack_rows": rows,
            "pack_max_per_row": per_row,
            "loadtime_packer": loadtime,
            "offline_packed": offline,
            "loader_speedup_offline_over_loadtime": round(
                offline["loader_only_samples_per_s"]
                / max(loadtime["loader_only_samples_per_s"], 1e-9), 3),
            "step_ms_delta_pct": round(
                (offline["train_step_ms"] / max(loadtime["train_step_ms"],
                                                1e-9) - 1.0) * 100.0, 2),
            "real_tokens_per_step_gain_pct": round(
                ((1.0 - offline["pad_ratio"])
                 / max(1.0 - loadtime["pad_ratio"], 1e-9) - 1.0) * 100.0,
                2),
            "note": "same corpus, same [rows x L] batch shape, two "
                    "measurements per config: end-to-end with the jitted "
                    "packed train step (train_step_ms — identical shapes "
                    "must give matching step cost; the delta is noise "
                    "bounds) and loader-only (loader_only_samples_per_s — "
                    "the input-pipeline rate the training loop sees, "
                    "where the offline packer's win lives). The "
                    "training-side lift = the loader headroom plus "
                    "real_tokens_per_step_gain_pct (corpus-level FFD fill "
                    "vs streaming first-fit) at unchanged step cost.",
        }
        doc = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                doc = _json.load(f)
        doc["packed_offline_comparison"] = comparison
        with open(args.out, "w") as f:
            _json.dump(doc, f, indent=1)
        print(_json.dumps(comparison, indent=1))
        print("wrote", args.out)
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def attribution_profile(args):
    """Run the REAL jitted train step (``mock_train --with-model tiny``)
    with telemetry armed and merge the loader critical-path attribution
    — bound verdict, input share, per-stage wall shares — into
    STEP_PROFILE.json under ``loader_attribution`` (existing
    device-trace fields preserved, same merge discipline as
    ``--packed-compare``)."""
    import json as _json
    import tempfile as _tf
    sys.path.insert(0, ROOT)
    from bench import make_corpus
    from lddl_tpu.balance import balance_shards
    from lddl_tpu.preprocess import (BertPretrainConfig,
                                     build_wordpiece_vocab, get_tokenizer,
                                     run_bert_preprocess)
    import jax
    tmp = _tf.mkdtemp(prefix="lddl_attr_")
    try:
        corpus = os.path.join(tmp, "corpus")
        make_corpus(corpus, args.corpus_mb, seed=0)
        sample, sb = [], 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sb += len(line)
                if sb > 1_000_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
        tok = get_tokenizer(vocab_file=vocab)
        pre = os.path.join(tmp, "pre")
        run_bert_preprocess(
            {"wikipedia": corpus}, pre, tok,
            config=BertPretrainConfig(max_seq_length=128,
                                      duplicate_factor=1),
            num_blocks=8, sample_ratio=1.0, seed=12345,
            num_workers=usable_cpu_count())
        bal = os.path.join(tmp, "bal")
        balance_shards(pre, bal, 8)
        mdir = os.path.join(tmp, "metrics")
        _mock_train_packed(bal, vocab, ["--batch-size", str(args.batch),
                                        "--metrics-dir", mdir])
        summaries = sorted(glob.glob(os.path.join(mdir, "summary-*.json")))
        if not summaries:
            raise RuntimeError("mock_train left no summary under " + mdir)
        attr = None
        for sp in summaries:
            with open(sp) as f:
                attr = _json.load(f).get("loader_attribution") or attr
        if attr is None:
            raise RuntimeError("no loader_attribution in " + summaries[-1])
        attr = dict(attr, device=getattr(
            jax.devices()[0], "device_kind", str(jax.devices()[0])))
        doc = {}
        if os.path.exists(args.out):
            with open(args.out) as f:
                doc = _json.load(f)
        doc["loader_attribution"] = attr
        with open(args.out, "w") as f:
            _json.dump(doc, f, indent=1)
        print(_json.dumps(attr, indent=1))
        print("wrote", args.out)
    finally:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return 0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="bert_large",
                   choices=["bert_base", "bert_large", "tiny"])
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--no-gather", action="store_true",
                   help="profile the full-sequence MLM head instead")
    p.add_argument("--attention-impl", default="auto",
                   choices=["auto", "dense", "flash"],
                   help="auto (the production default) resolves per the "
                        "measured map in attention.resolve_auto_impl")
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--out", default=os.path.join(ROOT, "STEP_PROFILE.json"))
    p.add_argument("--packed-compare", action="store_true",
                   help="skip the device trace: measure offline-packed vs "
                        "load-time-packed end to end through mock_train "
                        "--with-model tiny and merge the result into the "
                        "artifact (runs on any backend, CPU included)")
    p.add_argument("--corpus-mb", type=float, default=4.0,
                   help="--packed-compare corpus size")
    p.add_argument("--pack-seq-length", type=int, default=512,
                   help="--packed-compare row budget")
    p.add_argument("--pack-rows", type=int, default=4,
                   help="--packed-compare rows per batch")
    p.add_argument("--attribution", action="store_true",
                   help="skip the device trace: run mock_train "
                        "--with-model tiny with telemetry armed and merge "
                        "the loader critical-path attribution (bound "
                        "verdict + per-stage shares) into the artifact "
                        "(runs on any backend, CPU included)")
    args = p.parse_args()
    if args.packed_compare:
        return packed_compare(args)
    if args.attribution:
        return attribution_profile(args)

    import jax
    from lddl_tpu.loader import to_device_batch
    from lddl_tpu.models import (BertConfig, create_train_state,
                                 make_sharded_train_step)
    from lddl_tpu.models.testing import fake_pretrain_batch
    from lddl_tpu.models.train import make_optimizer, mlm_gather_cap
    from lddl_tpu.parallel import make_mesh
    from model_bench import PEAK_BF16_TFLOPS, matmul_flops_per_step

    device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device))
    mesh = make_mesh({"dp": 1}, devices=[device])
    cfg = getattr(BertConfig, args.model)(
        attention_dropout=0.0, mlm_gather=not args.no_gather,
        attention_impl=args.attention_impl,
        max_position_embeddings=max(512, args.seq_len))
    batch_np = fake_pretrain_batch(cfg.vocab_size, args.batch, args.seq_len,
                                   seed=7, segment_split=True)
    state, _ = create_train_state(
        cfg, mesh, batch_np,
        optimizer=make_optimizer(warmup_steps=10, total_steps=1000))
    step = make_sharded_train_step(mesh, cfg, donate=False)
    batch = to_device_batch(batch_np, mesh)

    # Warmup: compile + one run (readback = true synchronization; the
    # tunneled runtime's block_until_ready is not a reliable barrier).
    state, metrics = step(state, batch, seed=0)
    float(np.asarray(metrics["loss"]))

    trace_dir = tempfile.mkdtemp(prefix="lddl_step_profile_")
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        state, metrics = step(state, batch, seed=1)
        float(np.asarray(metrics["loss"]))
    wall_s = time.perf_counter() - t0

    leaves = parse_one_step_trace(trace_dir)
    total_us = sum(l["dur_us"] for l in leaves)

    by_cat = defaultdict(lambda: [0.0, 0.0, 0.0, 0])   # us, flops, bytes, n
    by_comp = defaultdict(lambda: [0.0, 0])
    for l in leaves:
        c = by_cat[l["category"]]
        c[0] += l["dur_us"]; c[1] += l["flops"]; c[2] += l["bytes"]
        c[3] += 1
        comp = component_of(l["tf_op"])
        by_comp[comp][0] += l["dur_us"]; by_comp[comp][1] += 1

    def cat_rows():
        rows = []
        for cat, (us, flops, byts, n) in sorted(by_cat.items(),
                                                key=lambda kv: -kv[1][0]):
            rows.append({
                "category": cat, "ms": round(us / 1e3, 3),
                "share_pct": round(100 * us / total_us, 2), "ops": n,
                "achieved_tflops": round(flops / (us * 1e6), 2) if us else 0,
                "achieved_gbps": round(byts / (us * 1e3), 1) if us else 0,
            })
        return rows

    def comp_rows():
        return [{"component": k, "ms": round(v[0] / 1e3, 3),
                 "share_pct": round(100 * v[0] / total_us, 2), "ops": v[1]}
                for k, v in sorted(by_comp.items(), key=lambda kv: -kv[1][0])]

    top_ops = sorted(leaves, key=lambda l: -l["dur_us"])[:args.top]

    n_pred = (mlm_gather_cap(args.seq_len) if cfg.mlm_gather else None)
    if n_pred is not None and n_pred >= args.seq_len:
        n_pred = None
    flops = matmul_flops_per_step(cfg, args.batch, args.seq_len, n_pred)
    peak = PEAK_BF16_TFLOPS.get(kind)
    device_step_s = total_us / 1e6

    payload = {
        "device_kind": kind,
        "model": args.model,
        "batch": args.batch,
        "attention_impl": args.attention_impl,
        "seq_len": args.seq_len,
        "mlm_gather_positions": n_pred,
        "wall_s_incl_dispatch": round(wall_s, 3),
        "device_busy_ms": round(device_step_s * 1e3, 3),
        "model_tflops_per_step": round(flops / 1e12, 3),
        "mfu_on_device_busy_time": (
            round(flops / device_step_s / (peak * 1e12), 4) if peak else None),
        "leaf_ops": len(leaves),
        "note": ("one traced step; per-op device time, hlo_category, "
                 "model_flops and bytes_accessed from the jax.profiler "
                 "chrome trace. Dispatch/host gaps are excluded, so this "
                 "MFU is the device-busy ceiling, slightly above "
                 "MODEL_BENCH's wall-clock MFU."),
        "by_hlo_category": cat_rows(),
        "by_component": comp_rows(),
        "top_ops": [
            {
                "op": l["name"][:80],
                "ms": round(l["dur_us"] / 1e3, 3),
                "share_pct": round(100 * l["dur_us"] / total_us, 2),
                "category": l["category"],
                "tf_op": l["tf_op"][:160],
                "achieved_tflops": (round(l["flops"] / (l["dur_us"] * 1e6), 2)
                                    if l["dur_us"] else 0),
                "achieved_gbps": (round(l["bytes"] / (l["dur_us"] * 1e3), 1)
                                  if l["dur_us"] else 0),
            }
            for l in top_ops
        ],
    }
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps({k: payload[k] for k in
                      ("device_busy_ms", "mfu_on_device_busy_time",
                       "leaf_ops")}))
    for row in payload["by_hlo_category"]:
        print("{share_pct:6.2f}%  {ms:8.3f} ms  [{ops:5d} ops]  {category}"
              .format(**row))
    print("--- by component:")
    for row in payload["by_component"]:
        print("{share_pct:6.2f}%  {ms:8.3f} ms  [{ops:5d} ops]  {component}"
              .format(**row))
    print("wrote", args.out)


if __name__ == "__main__":
    main()
