"""Pallas fused attention on the resolved JAX backend: correctness vs the
XLA dense reference + scan-amortized KERNEL speed per shape.

Writes FLASH_ATTENTION_BENCH.json at the repo root. Each timed dispatch
runs N_SCAN forward+backward attention iterations inside one lax.scan
(grads fed back into the carry so nothing is dead code), which amortizes
the tunneled chip's ~100 ms remote-dispatch floor to noise — the same
methodology as MODEL_BENCH's multi-step train dispatches, but isolating
the attention op. This is the direct kernel-level speed record the
round-4 review asked for (previously only numerics were meaningful here);
correctness columns are unchanged.

Usage: python benchmarks/flash_attention_bench.py
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np

N_SCAN = 50
REPS = 4


def _fb_loop(attn, n_iters):
    """Scan of fwd+bwd iterations; grads fold into the carry."""
    import jax
    import jax.numpy as jnp
    import jax.lax as lax

    def step(c, _):
        q, k, v = c

        def loss(q, k, v):
            return (attn(q, k, v) ** 2).sum().astype(jnp.float32)

        l, (dq, dk, dv) = jax.value_and_grad(loss, (0, 1, 2))(q, k, v)
        return (q + dq.astype(q.dtype) * 1e-6,
                k + dk.astype(k.dtype) * 1e-6,
                v + dv.astype(v.dtype) * 1e-6), l

    def loop(q, k, v):
        _, ls = lax.scan(step, (q, k, v), None, length=n_iters)
        return ls[-1]

    return loop


def _time_loop(fn, args, reps, n_iters):
    import jax
    f = jax.jit(fn)
    r = f(*args)
    r.block_until_ready()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        f(*args).block_until_ready()
        ts.append(time.perf_counter() - t0)
    return min(ts) / n_iters, sorted(ts)[len(ts) // 2] / n_iters


def main():
    import jax
    import jax.numpy as jnp
    from lddl_tpu.ops.flash_attention import flash_attention
    from lddl_tpu.ops.ring_attention import dense_attention_reference

    def dense_bf16(q, k, v, mask):
        """XLA fused dense attention exactly as the model's dense path
        computes it: bf16 operands AND bf16 softmax statistics
        (jax.nn.softmax on the bf16 score tensor), matching
        models/attention.py's dense branch."""
        scale = 1.0 / (q.shape[-1] ** 0.5)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e9)
        p = jax.nn.softmax(s + bias.astype(s.dtype), axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    from lddl_tpu.utils.rng import sample_rng
    g = sample_rng(0)
    results = []
    # bert_base short bin, the two headline L=512 shapes, long context
    # (B=4 matches MODEL_BENCH's L=2048 row — B=1 leaves only 12 grid
    # rows and under-utilizes the kernel's (b, h) grid).
    for (tag, b, l, h, d) in [("base_L128", 8, 128, 12, 64),
                              ("base_L512", 32, 512, 12, 64),
                              ("large_L512", 12, 512, 16, 64),
                              ("base_L768", 16, 768, 12, 64),
                              ("base_L896", 12, 896, 12, 64),
                              ("base_L2048", 4, 2048, 12, 64)]:
        q = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.bfloat16)
        k = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.bfloat16)
        v = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.bfloat16)
        mask = np.ones((b, l), np.int32)
        mask[0, l - l // 8:] = 0
        mask = jnp.asarray(mask)

        fa = jax.jit(lambda q, k, v, m: flash_attention(q, k, v, m))
        dn = jax.jit(dense_attention_reference)
        err = float(np.abs(np.asarray(fa(q, k, v, mask), np.float32)
                           - np.asarray(dn(q, k, v, mask),
                                        np.float32)).max())
        # Gradient parity on hardware: pallas backward vs XLA dense vjp.
        gf = jax.jit(jax.grad(
            lambda q_, k_, v_: (flash_attention(q_, k_, v_, mask)
                                .astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(
            lambda q_, k_, v_: (dense_attention_reference(q_, k_, v_, mask)
                                .astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
        gerr = float(max(np.abs(np.asarray(a, np.float32)
                                - np.asarray(b_, np.float32)).max()
                         for a, b_ in zip(gf, gd)))

        best_fa, med_fa = _time_loop(
            _fb_loop(lambda a, b_, c: flash_attention(a, b_, c, mask),
                     N_SCAN), (q, k, v), REPS, N_SCAN)
        best_dn, med_dn = _time_loop(
            _fb_loop(lambda a, b_, c: dense_bf16(a, b_, c, mask),
                     N_SCAN), (q, k, v), REPS, N_SCAN)
        results.append(dict(
            tag=tag, shape=[b, l, h, d], max_abs_err=err,
            grad_max_abs_err=gerr,
            pallas_fb_ms=round(best_fa * 1e3, 4),
            xla_dense_fb_ms=round(best_dn * 1e3, 4),
            pallas_fb_ms_median=round(med_fa * 1e3, 4),
            xla_dense_fb_ms_median=round(med_dn * 1e3, 4),
            speedup=round(best_dn / best_fa, 3)))
        print(results[-1], flush=True)

    payload = {
        "device": str(jax.devices()[0]),
        "n_scan_iters": N_SCAN,
        "reps": REPS,
        "results": results,
        "note": ("Kernel-level record: *_fb_ms = per-iteration wall time "
                 "of ONE attention forward+backward, from a lax.scan of "
                 "{} iterations per dispatch (best of {} dispatches; "
                 "median column shows host spread) — the ~100 ms tunneled "
                 "dispatch floor is amortized out. max_abs_err / "
                 "grad_max_abs_err (bf16 rounding scale) remain the "
                 "hardware-correctness record vs the fp32 dense "
                 "reference. speedup > 1 means the pallas kernels beat "
                 "XLA's fused dense attention at that shape; the auto "
                 "selection (models/attention.resolve_auto_impl) follows "
                 "the measured map incl. the in-model numbers in "
                 "MODEL_BENCH.json.").format(N_SCAN, REPS),
    }
    with open(os.path.join(ROOT, "FLASH_ATTENTION_BENCH.json"), "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote FLASH_ATTENTION_BENCH.json")


if __name__ == "__main__":
    main()
