"""Pallas fused attention on the resolved JAX backend: correctness vs the
XLA dense reference + wall-time envelope per shape.

Writes FLASH_ATTENTION_BENCH.json at the repo root. On the tunneled
single-chip host the wall times ride an ~100ms remote-dispatch floor, so
the meaningful recorded value there is max_abs_err on real hardware.

Usage: python benchmarks/flash_attention_bench.py
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from lddl_tpu.ops.flash_attention import flash_attention
    from lddl_tpu.ops.ring_attention import dense_attention_reference

    g = np.random.default_rng(0)
    results = []
    for (b, l, h, d) in [(8, 128, 12, 64), (4, 512, 12, 64),
                         (1, 2048, 12, 64)]:
        q = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.bfloat16)
        k = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.bfloat16)
        v = jnp.asarray(g.standard_normal((b, l, h, d)), jnp.bfloat16)
        mask = np.ones((b, l), np.int32)
        mask[0, l - l // 8:] = 0
        mask = jnp.asarray(mask)
        fa = jax.jit(lambda q, k, v, m: flash_attention(q, k, v, m))
        dn = jax.jit(dense_attention_reference)
        err = float(np.abs(np.asarray(fa(q, k, v, mask), np.float32)
                           - np.asarray(dn(q, k, v, mask),
                                        np.float32)).max())
        # Gradient parity on hardware: pallas backward vs XLA dense vjp.
        gf = jax.jit(jax.grad(
            lambda q_, k_, v_: (flash_attention(q_, k_, v_, mask)
                                .astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(
            lambda q_, k_, v_: (dense_attention_reference(q_, k_, v_, mask)
                                .astype(jnp.float32) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)
        gerr = float(max(np.abs(np.asarray(a, np.float32)
                                - np.asarray(b_, np.float32)).max()
                         for a, b_ in zip(gf, gd)))
        t0 = time.perf_counter()
        for _ in range(5):
            fa(q, k, v, mask).block_until_ready()
        t_fa = (time.perf_counter() - t0) / 5
        t0 = time.perf_counter()
        for _ in range(5):
            dn(q, k, v, mask).block_until_ready()
        t_dn = (time.perf_counter() - t0) / 5
        results.append(dict(shape=[b, l, h, d], max_abs_err=err,
                            grad_max_abs_err=gerr,
                            pallas_ms=round(t_fa * 1e3, 2),
                            xla_dense_ms=round(t_dn * 1e3, 2)))
        print(results[-1], flush=True)
    payload = {
        "device": str(jax.devices()[0]),
        "results": results,
        "note": ("NUMERICS artifact only: max_abs_err (bf16 rounding "
                 "scale) is the hardware-correctness record. The *_ms "
                 "columns are single-dispatch wall times on a tunneled "
                 "chip = ~100 ms dispatch floor, NOT kernel time. The "
                 "authoritative speed record is MODEL_BENCH.json "
                 "(in-model multi-step scan) and STEP_PROFILE.json "
                 "(device-busy per-op times)."),
    }
    with open(os.path.join(ROOT, "FLASH_ATTENTION_BENCH.json"), "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote FLASH_ATTENTION_BENCH.json")


if __name__ == "__main__":
    main()
