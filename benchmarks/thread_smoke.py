"""CI smoke for the in-kernel thread pool: 1-thread vs N-thread shard
byte identity on one small corpus — GATING — plus an informational
per-thread-count standalone tokenize MB/s line.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_BENCH=1``. The
full preprocess pipeline (fused-masked headline config) runs twice,
``LDDL_TPU_NATIVE_THREADS=1`` vs ``=N`` (N = min(4, usable cores) forced
to at least 2 so the partitioned code path actually executes even on a
1-core host), and every output byte — shards AND manifests — must match:
the Philox replay is per-sample-keyed and the pair streams per-document-
keyed, so partitioning can never change bytes. Prints one JSON line::

    {"identical": true, "n_threads": ...,
     "tokenize_mb_per_s_by_threads": {"1": ..., "2": ...}}

The MB/s rows are weather on a busy 1-core CI box — the committed
PROFILE_PREPROCESS.json is the measurement of record; byte identity is
the alarm this smoke exists for.
"""

import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402
from lddl_tpu.utils.cpus import usable_cpu_count  # noqa: E402


def _tree_digest(out_dir):
    h = hashlib.sha256()
    for root, dirs, files in sorted(os.walk(out_dir)):
        dirs.sort()
        for name in sorted(files):
            h.update(name.encode())
            with open(os.path.join(root, name), "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def main():
    target_mb = float(os.environ.get("LDDL_TPU_THREAD_SMOKE_MB", "2"))
    tmp = tempfile.mkdtemp(prefix="lddl_thread_smoke_")
    try:
        from lddl_tpu import native
        from lddl_tpu.preprocess import (
            BertPretrainConfig, build_wordpiece_vocab, get_tokenizer,
            run_bert_preprocess)

        if not native.available():
            print(json.dumps({"smoke": "native-thread identity pair",
                              "skipped": "native engine unavailable"}))
            return 0

        corpus = os.path.join(tmp, "corpus")
        nbytes, _ = bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 500_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=8000)
        tokenizer = get_tokenizer(vocab_file=vocab)

        def run(name, threads):
            os.environ["LDDL_TPU_NATIVE_THREADS"] = str(threads)
            try:
                out = os.path.join(tmp, name)
                run_bert_preprocess(
                    {"wikipedia": corpus}, out, tokenizer,
                    config=BertPretrainConfig(max_seq_length=128,
                                              duplicate_factor=1,
                                              masking=True),
                    num_blocks=8, sample_ratio=1.0, seed=12345,
                    bin_size=32, num_workers=1)
            finally:
                del os.environ["LDDL_TPU_NATIVE_THREADS"]
            return _tree_digest(out)

        # Force >= 2 threads so the partitioned code path runs even where
        # only one core is usable (correctness is core-count-independent).
        n_threads = max(2, min(4, usable_cpu_count()))
        run("warm", 1)  # native build + tokenizer tables outside the pair
        d1 = run("t1", 1)
        dn = run("tn", n_threads)
        identical = d1 == dn

        # Informational per-thread-count tokenize MB/s (fresh tokenizer
        # per row so every count pays the same memo warm-up).
        from lddl_tpu.preprocess.bert import TokenizerInfo
        rows = {}
        data = [t.encode("utf-8") for t in sample]
        sbytes = float(sum(len(d) for d in data))
        for nt in sorted({1, 2, n_threads}):
            cls, args = TokenizerInfo(tokenizer).native_tokenizer().\
                __reduce__()
            nat = cls(*args)
            nat.set_threads(nt)
            nat.tokenize_docs(data[:8])
            t0 = time.perf_counter()
            reps = 0
            elapsed = 0.0
            while elapsed < 0.5:
                nat.tokenize_docs(data)
                reps += 1
                elapsed = time.perf_counter() - t0
            rows[str(nt)] = round(sbytes * reps / elapsed / 1e6, 2)

        print(json.dumps({
            "smoke": "native-thread identity pair",
            "corpus_mb": round(nbytes / 1024 / 1024, 2),
            "n_threads": n_threads,
            "identical": identical,
            "usable_cpus": usable_cpu_count(),
            "tokenize_mb_per_s_by_threads": rows,
        }))
        return 0 if identical else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
