"""Mock training loop: loader perf harness + correctness probe.

Reference parity: benchmarks/torch_train.py — throughput/latency meters,
per-iteration seq-len and padded-zero stats, batch-shape asserts, --debug
raw-sample inspection with de-masking round-trip, per-rank .npz dumps for
offline validation (benchmarks/validate_seqlen.py). Plus what the
reference could not do: ``--with-model`` runs a real jitted BERT train
step on a device mesh, measuring end-to-end step time instead of loader
time alone.

Single-process simulation of a multi-rank layout: pass --dp-rank/
--num-dp-groups (runs this rank's loader exactly as it would run in the
full job).
"""

import argparse
import os
import sys
import time

# Allow running by path from anywhere: put the repo root on sys.path.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


class AverageMeter:

    def __init__(self, warmup=2, keep=False):
        self.warmup = warmup
        self.keep = keep
        self.reset()

    def reset(self):
        self.val = 0
        self.avg = 0
        self.max = float("-inf")
        self.min = float("inf")
        self.sum = 0
        self.count = 0
        self.iters = 0
        self.vals = []

    def update(self, val, n=1):
        self.iters += 1
        self.val = val
        if self.iters > self.warmup:
            self.sum += val * n
            self.max = max(val, self.max)
            self.min = min(val, self.min)
            self.count += n
            self.avg = self.sum / self.count
            if self.keep:
                self.vals.append(val)


class Histogram:

    def __init__(self):
        self.counts = {}

    def update(self, key, n=1):
        self.counts[key] = self.counts.get(key, 0) + n


def attach_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--path", required=True, help="balanced shard dir")
    p.add_argument("--vocab-file", required=True)
    p.add_argument("--family", choices=("bert", "bart"), default="bert",
                   help="which loader/model contract to drive")
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--start-epoch", type=int, default=0)
    p.add_argument("--num-workers", type=int, default=1)
    p.add_argument("--worker-mode", choices=("thread", "process"),
                   default="thread")
    p.add_argument("--log-freq", type=int, default=100)
    p.add_argument("--seed", type=int, default=12345)
    p.add_argument("--dp-rank", type=int, default=0)
    p.add_argument("--num-dp-groups", type=int, default=1)
    p.add_argument("--fixed-seq-lengths", type=int, nargs="*", default=None)
    p.add_argument("--pack-seq-length", type=int, default=None,
                   help="sequence packing row budget: on an UNPACKED dir "
                        "this enables the greedy load-time packer (needs "
                        "--pack-rows); offline-packed dirs are detected "
                        "automatically and this only validates the budget")
    p.add_argument("--pack-rows", type=int, default=None,
                   help="packed rows per batch (load-time packer: "
                        "required with --pack-seq-length; offline-packed "
                        "dirs default to --batch-size)")
    p.add_argument("--pack-max-per-row", type=int, default=8)
    p.add_argument("--seq-len-dir", default=None,
                   help="dump lens_<dp_rank>.npz here for validate_seqlen.py")
    p.add_argument("--debug", action="store_true")
    p.add_argument("--with-model", choices=("tiny", "base"), default=None,
                   help="run a real jitted train step per batch")
    p.add_argument("--mesh", default=None,
                   help="axes for --with-model, e.g. dp=2,tp=2,sp=2 "
                        "(default: all devices on dp)")
    p.add_argument("--attention-impl",
                   choices=("auto", "dense", "ring", "flash"),
                   default="auto", help="for --with-model (auto = measured "
                   "per-seq-length dense/flash selection)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize layers (--with-model)")
    p.add_argument("--on-corrupt", choices=("fail", "quarantine"),
                   default=None,
                   help="startup shard-integrity policy against the "
                        ".manifest.json: fail = refuse to start naming the "
                        "corrupt shard(s); quarantine = exclude them "
                        "loudly and run on the survivors (default: "
                        "$LDDL_TPU_ON_CORRUPT, then fail)")
    p.add_argument("--storage-backend", choices=("local", "mock"),
                   default=None,
                   help="route shard I/O through this StorageBackend "
                        "(default: inherit LDDL_TPU_STORAGE_BACKEND)")
    p.add_argument("--backend-latency-ms", type=float, default=None,
                   help="inject this per-operation latency into the mock "
                        "object store (LDDL_TPU_MOCK_LATENCY_MS) — the "
                        "first-class knob behind loader_bench's "
                        "cache_prefetch_speedup pair")
    p.add_argument("--prefetch-shards", type=int, default=None,
                   help="loader shard read-ahead depth "
                        "(LDDL_TPU_LOADER_PREFETCH_SHARDS; 0 disables "
                        "the shard I/O pipeline)")
    p.add_argument("--cache-bytes", type=int, default=None,
                   help="loader shard-cache byte budget "
                        "(LDDL_TPU_LOADER_CACHE_BYTES; 0 disables "
                        "caching)")
    p.add_argument("--metrics-dir", default=None,
                   help="arm lddl_tpu.observability and write metric "
                        "snapshots (.jsonl), a Prometheus textfile, "
                        "Chrome-trace JSONL (Perfetto) and an end-of-run "
                        "summary-*.json here; also prints the telemetry "
                        "report (padding efficiency, resilience activity) "
                        "after the run")
    return p


def _debug_print(loader, tokenizer):
    from lddl_tpu.utils.fs import deserialize_np_array

    def toks(v):
        # v1 raw samples carry space-joined token strings; schema-v2
        # carries int32 id arrays — render both as token lists.
        if isinstance(v, str):
            return v.split()
        return tokenizer.convert_ids_to_tokens([int(i) for i in v])

    for i, batch in enumerate(loader):
        for sample in batch[:2]:
            if len(sample) == 5:
                a, b, rn, pos_b, labels = sample
                seq = ["[CLS]"] + toks(a) + ["[SEP]"] + toks(b) + ["[SEP]"]
                pos = (deserialize_np_array(pos_b)
                       if isinstance(pos_b, (bytes, bytearray)) else pos_b)
                labs = toks(labels)
                print("is_random_next:", rn)
                print("masked:", " ".join(seq))
                for p, l in zip(pos, labs):
                    seq[int(p)] = l
                print("demasked:", " ".join(seq))
            else:
                print("is_random_next:", sample[2])
                print("[CLS] {} [SEP] {} [SEP]".format(
                    " ".join(toks(sample[0])), " ".join(toks(sample[1]))))
        if i >= 2:
            return


def _telemetry_report(obs):
    """End-of-run telemetry: headline numbers on stdout + summary json,
    prom textfile and trace flush in the metrics dir."""
    s = obs.summary()
    print("telemetry: padding efficiency {} ({} real tokens / {} slots)"
          .format("{:.4f}".format(s["padding_efficiency"])
                  if s["padding_efficiency"] is not None else "n/a",
                  s["real_tokens"], s["padded_slots"]))
    print("telemetry: resilience activity: {} retries, {} faults "
          "injected, {} worker restarts, {} quarantined shards".format(
              s["retries"], s["faults_injected"], s["worker_restarts"],
              s["quarantined_shards"]))
    reg = obs.registry()
    hist = reg.get("loader_batch_latency_seconds")
    if hist is not None:
        st = hist.stats()
        if st:
            print("telemetry: batch latency mean {:.2f} ms over {} "
                  "batches (max {:.2f} ms)".format(
                      1e3 * st["sum"] / max(st["count"], 1), st["count"],
                      1e3 * st["max"]))
    bins = reg.get("loader_bin_choice_total")
    if bins is not None:
        print("telemetry: bin choices {}".format(bins.snapshot()["values"]))
    # Critical-path attribution: where did the batch wall go, and is the
    # step input-bound? (snapshot() also publishes the verdict gauges so
    # the fleet rollup carries them.)
    report = obs.attribution.snapshot()
    if report is not None:
        print(obs.attribution.format_report(report, indent="telemetry: "))
    obs.export_prom()
    obs.export_jsonl()
    path = obs.write_summary()
    if path:
        print("telemetry: wrote {}".format(path))


def _warm_parquet_reader():
    """The first pyarrow.parquet use in a process pays ~0.4 s of lazy
    imports and IO-thread-pool spin-up; pay it on a throwaway in-memory
    table BEFORE the timed loop so the 'sustained' meter measures the
    loader pipeline, not pyarrow's one-time init (which, on small bench
    corpora, dominated epoch 0 and diluted every config equally)."""
    import io
    import pyarrow as pa
    import pyarrow.parquet as pq
    buf = io.BytesIO()
    pq.write_table(pa.table({"x": [0]}), buf)
    buf.seek(0)
    pq.read_table(buf)


def _queue_cost(loader):
    """(bytes, batches) shipped over process-worker queues, summed over
    the wrapped DataLoaders (Binned holds one per bin; packed mode wraps
    an inner raw-sample loader). Zero in thread mode."""
    dls = [loader]
    if getattr(loader, "_dataloaders", None) is not None:
        dls = loader._dataloaders
    elif getattr(loader, "_inner", None) is not None:
        dls = [loader._inner]
    return (sum(getattr(d, "queue_bytes", 0) for d in dls),
            sum(getattr(d, "queue_batches", 0) for d in dls))


def main():
    args = attach_args().parse_args()
    from lddl_tpu.loader import (get_bert_pretrain_data_loader,
                                 prefetch_to_device, to_device_batch)
    # The observability hooks are inert no-ops unless armed, so no
    # conditional plumbing: configure() is the only gated call.
    from lddl_tpu import observability as obs

    if args.metrics_dir:
        obs.configure(dir=args.metrics_dir, periodic=True)

    # Shard I/O knobs resolve to the env BEFORE any loader (and so any
    # backend instance or prefetch thread) is built: the mock store
    # caches its latency knob at construction, and the shard pipeline
    # resolves its depth/budget per stream.
    if args.storage_backend:
        os.environ["LDDL_TPU_STORAGE_BACKEND"] = args.storage_backend
    if args.backend_latency_ms is not None:
        os.environ["LDDL_TPU_MOCK_LATENCY_MS"] = str(args.backend_latency_ms)
    if args.prefetch_shards is not None:
        os.environ["LDDL_TPU_LOADER_PREFETCH_SHARDS"] = \
            str(args.prefetch_shards)
    if args.cache_bytes is not None:
        os.environ["LDDL_TPU_LOADER_CACHE_BYTES"] = str(args.cache_bytes)

    offline_shape = None
    packed = False
    if args.family == "bart":
        from lddl_tpu.loader.bart import get_bart_pretrain_data_loader
        if args.debug:
            raise SystemExit("--debug is a BERT raw-sample inspector; "
                             "the BART loader has no debug formatter")
        if args.fixed_seq_lengths and len(args.fixed_seq_lengths) != 1:
            raise SystemExit("--family bart takes a single "
                             "--fixed-seq-lengths value (BART shards are "
                             "unbinned)")
        fixed = (args.fixed_seq_lengths[0] if args.fixed_seq_lengths
                 else None)
        loader = get_bart_pretrain_data_loader(
            args.path,
            dp_rank=args.dp_rank,
            num_dp_groups=args.num_dp_groups,
            batch_size=args.batch_size,
            num_workers=args.num_workers,
            worker_mode=args.worker_mode,
            vocab_file=args.vocab_file,
            max_seq_length=fixed or 128,
            fixed_seq_length=fixed,
            base_seed=args.seed,
            start_epoch=args.start_epoch,
            return_raw_samples=args.debug,
            on_corrupt=args.on_corrupt,
        )
    else:
        # Packed mode: explicit flags (load-time packer on unpacked
        # shards) or auto-detected offline-packed shards — either way the
        # batch contract below is the packed one.
        from lddl_tpu.loader.bert import packed_shape_of_dir
        offline_shape = packed_shape_of_dir(args.path)
        packed = args.pack_seq_length is not None or offline_shape
        loader = get_bert_pretrain_data_loader(
            args.path,
            dp_rank=args.dp_rank,
            num_dp_groups=args.num_dp_groups,
            batch_size=args.batch_size,
            num_workers=args.num_workers,
            worker_mode=args.worker_mode,
            vocab_file=args.vocab_file,
            fixed_seq_lengths=args.fixed_seq_lengths,
            pack_seq_length=args.pack_seq_length,
            pack_rows=args.pack_rows,
            pack_max_per_row=args.pack_max_per_row,
            base_seed=args.seed,
            start_epoch=args.start_epoch,
            return_raw_samples=args.debug,
            on_corrupt=args.on_corrupt,
        )
    if args.debug:
        from lddl_tpu.preprocess import get_tokenizer
        _debug_print(loader, get_tokenizer(vocab_file=args.vocab_file))
        return

    step = None
    mesh = None
    if args.with_model:
        import jax
        # Environments with an accelerator plugin registered at interpreter
        # startup can shadow JAX_PLATFORMS; re-assert the env choice via
        # config before first device use (no-op if already initialized).
        if os.environ.get("JAX_PLATFORMS"):
            try:
                jax.config.update("jax_platforms",
                                  os.environ["JAX_PLATFORMS"])
            except RuntimeError:
                pass
        from lddl_tpu.models import (BartConfig, BartForPreTraining,
                                     BertConfig, bart_batch_loss,
                                     create_train_state,
                                     make_sharded_train_step)
        from lddl_tpu.parallel import make_mesh
        axes = {"dp": -1}
        if args.mesh:
            axes = {k: int(v) for k, v in
                    (kv.split("=") for kv in args.mesh.split(","))}
        mesh = make_mesh(axes)
        # Init from a synthetic batch: pulling one from the loader would
        # advance the dataset's epoch counter and skip the first epoch's
        # data (param init only needs the batch key/shape contract).
        init_len = (args.fixed_seq_lengths[0] if args.fixed_seq_lengths
                    else 128)
        if args.family == "bart":
            cfg = (BartConfig.tiny if args.with_model == "tiny"
                   else BartConfig.bart_base)(
                       attention_impl=args.attention_impl,
                       remat=args.remat)
            from lddl_tpu.models.testing import fake_bart_batch
            sample = fake_bart_batch(cfg.vocab_size, args.batch_size,
                                     init_len, seed=args.seed)
            model = BartForPreTraining(cfg)
            state, _ = create_train_state(cfg, mesh, sample, model=model)
            step_fn = make_sharded_train_step(
                mesh, cfg, model=model, batch_loss=bart_batch_loss)
        elif packed:
            # Packed batches (load-time or offline) feed the packed
            # model: block-diagonal attention over segments, per-slot
            # [CLS] pooling, [R, P] NSP labels.
            from lddl_tpu.models.bert import BertForPreTrainingPacked
            from lddl_tpu.models.testing import fake_packed_pretrain_batch
            L = args.pack_seq_length or offline_shape[0]
            P = (offline_shape[1] if offline_shape
                 else args.pack_max_per_row)
            rows = args.pack_rows or args.batch_size
            make_cfg = (BertConfig.tiny if args.with_model == "tiny"
                        else BertConfig.bert_base)
            cfg_kw = dict(attention_impl=args.attention_impl,
                          remat=args.remat)
            if make_cfg(**cfg_kw).max_position_embeddings < L:
                # Packed rows are L wide; size the position table to fit.
                cfg_kw["max_position_embeddings"] = L
            cfg = make_cfg(**cfg_kw)
            model = BertForPreTrainingPacked(cfg)
            sample = fake_packed_pretrain_batch(cfg.vocab_size, rows, L, P,
                                                seed=args.seed)
            state, _ = create_train_state(cfg, mesh, sample, model=model)
            step_fn = make_sharded_train_step(mesh, cfg, model=model)
        else:
            cfg = (BertConfig.tiny if args.with_model == "tiny"
                   else BertConfig.bert_base)(
                       attention_impl=args.attention_impl,
                       remat=args.remat)
            from lddl_tpu.models.testing import fake_pretrain_batch
            sample = fake_pretrain_batch(cfg.vocab_size, args.batch_size,
                                         init_len, seed=args.seed)
            state, _ = create_train_state(cfg, mesh, sample)
            step_fn = make_sharded_train_step(mesh, cfg)

        def step(batch):
            # Batches arrive already device-resident and mesh-sharded via
            # prefetch_to_device (host collate + H2D overlap the previous
            # step instead of serializing with it).
            nonlocal state
            state, metrics = step_fn(state, batch, seed=args.seed)
            return metrics

    _warm_parquet_reader()
    batch_time = AverageMeter(warmup=2)
    throughput = AverageMeter(warmup=2)
    seq_len_hist = Histogram()
    pad_hist = Histogram()
    all_min_lens, all_max_lens, all_batch_lens = [], [], []
    step_time = AverageMeter(warmup=2)
    total_samples = 0
    total_wall = 0.0

    batches = loader
    if step is not None:
        # Double-buffered device prefetch: the next batch's collate and
        # H2D transfer overlap with the current train step. The per-batch
        # length stats ride along PRECOMPUTED ON THE HOST (inside the
        # prefetch thread) — summing the device copy in the consumer
        # would force a host-device sync before every step dispatch and
        # re-serialize exactly the overlap being measured.
        batches = prefetch_to_device(
            loader,
            device_put=lambda b: (b["attention_mask"].sum(axis=1),
                                  to_device_batch(b, mesh)))

    with obs.span("mock_train.run", epochs=args.epochs,
                  batch_size=args.batch_size):
        for epoch in range(args.start_epoch, args.start_epoch + args.epochs):
            epoch_t0 = time.perf_counter()
            epoch_samples = 0
            t0 = time.perf_counter()
            for i, batch in enumerate(batches):
                if step is not None:
                    lens, batch = batch  # host stats + device batch
                else:
                    lens = batch["attention_mask"].sum(axis=1)
                n, L = batch["input_ids"].shape
                # Shape contracts (ref torch_train.py:171-175) — shape is
                # metadata, so these never sync a device batch.
                assert batch["attention_mask"].shape == (n, L)
                assert batch["labels"].shape == (n, L)
                if args.family == "bart":
                    assert batch["decoder_input_ids"].shape == (n, L)
                elif "segments" in batch:
                    # Packed contract: per-token segment ids + per-slot
                    # [CLS] columns / NSP labels.
                    assert batch["segments"].shape == (n, L)
                    assert batch["cls_positions"].shape == \
                        batch["next_sentence_labels"].shape
                    assert batch["next_sentence_labels"].shape[0] == n
                else:
                    assert batch["token_type_ids"].shape == (n, L)
                    assert batch["next_sentence_labels"].shape == (n,)
                seq_len_hist.update(L, n)
                pad_hist.update(L, int((L - lens).sum()))
                all_min_lens.append(int(lens.min()))
                all_max_lens.append(int(lens.max()))
                all_batch_lens.append(L)
                if step is not None:
                    ts = time.perf_counter()
                    metrics = step(batch)
                    float(metrics["loss"])  # sync
                    step_time.update(time.perf_counter() - ts)
                dt = time.perf_counter() - t0
                batch_time.update(dt)
                throughput.update(n / dt)
                epoch_samples += n
                if (i + 1) % args.log_freq == 0:
                    print("epoch {} it {}: {:.1f} samples/s, {:.2f} ms/batch"
                          .format(epoch, i + 1, throughput.avg,
                                  batch_time.avg * 1e3))
                t0 = time.perf_counter()
            total_samples += epoch_samples
            epoch_wall = time.perf_counter() - epoch_t0
            total_wall += epoch_wall
            # Per-epoch sustained rate: epoch 0 is the cold-cache pass,
            # later epochs show the warm shard cache (loader_bench's
            # warm_epoch criterion parses these lines).
            print("epoch {} sustained: {:.1f} samples/s ({} samples / "
                  "{:.2f} s)".format(epoch,
                                     epoch_samples / max(epoch_wall, 1e-9),
                                     epoch_samples, epoch_wall))

    total_tokens = sum(k * v for k, v in seq_len_hist.counts.items())
    total_pad = sum(pad_hist.counts.values())
    print("loader throughput: {:.1f} samples/s avg, {:.2f} ms/batch avg"
          .format(throughput.avg, batch_time.avg * 1e3))
    # Per-batch rate averages overstate sustained speed once prefetch hides
    # batches behind consumption; samples over wall clock is the honest one.
    print("loader sustained: {:.1f} samples/s ({} samples / {:.2f} s)"
          .format(total_samples / max(total_wall, 1e-9), total_samples,
                  total_wall))
    if step is not None:
        print("train step: {:.2f} ms avg on mesh {}".format(
            step_time.avg * 1e3, dict(mesh.shape)))
    print("padded-zero ratio: {:.4f} ({} pad / {} slots)".format(
        total_pad / max(total_tokens, 1), total_pad, total_tokens))
    qbytes, qbatches = _queue_cost(loader)
    if qbatches:
        print("loader queue: {:.0f} bytes/batch over {} batches".format(
            qbytes / qbatches, qbatches))
    if args.seq_len_dir:
        os.makedirs(args.seq_len_dir, exist_ok=True)
        np.savez(
            os.path.join(args.seq_len_dir,
                         "lens_{}.npz".format(args.dp_rank)),
            min_lens=np.asarray(all_min_lens),
            max_lens=np.asarray(all_max_lens),
            batch_lens=np.asarray(all_batch_lens),
        )
        print("wrote {}/lens_{}.npz".format(args.seq_len_dir, args.dp_rank))
    if args.metrics_dir:
        # Observability cross-check: the loader's own sustained rate goes
        # into the summary so the instrumented numbers sit next to the
        # meter the benchmark has always printed.
        obs.set_gauge("mock_train_sustained_samples_per_second",
                      total_samples / max(total_wall, 1e-9))
        _telemetry_report(obs)


if __name__ == "__main__":
    main()
