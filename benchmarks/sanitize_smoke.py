"""CI smoke for the sanitizer-hardened native engine: rebuild the
kernel under TSan+UBSan and run the 1-vs-N entry-point identity suite
(tests/test_native_threads.py) against it — GATING on any sanitizer
report.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_SANITIZE=1``.
Four steps, each in a subprocess so the instrumented .so never loads
into the driver process:

1. build — ``LDDL_TPU_NATIVE_SANITIZE=tsan,ubsan python -m
   lddl_tpu.native.build``. GATING: a failed build falling back to the
   HF path would pass the identity suite vacuously.
2. availability assert — ``native.available()`` must be True under the
   sanitized env. dlopen'ing a TSan .so requires the TSan runtime in
   the process, so steps 2-3 run under ``LD_PRELOAD=libtsan.so``
   (located via ``g++ -print-file-name``). This step exists so a
   preload/runtime problem fails LOUDLY instead of silently demoting
   the suite to the fallback engine.
3. identity suite — pytest tests/test_native_threads.py with
   ``TSAN_OPTIONS=exitcode=66 halt_on_error=0 log_path=...`` and
   ``UBSAN_OPTIONS=halt_on_error=1``: TSan collects every report into
   the log files and forces a nonzero exit; UBSan aborts on first
   report. benchmarks/tsan_suppressions.txt silences ONLY
   uninstrumented third-party noise (pyarrow's bundled mimalloc) —
   the kernel itself stays fully checked.
4. verdict — fail on nonzero pytest exit OR any report text in the
   TSan logs.

Skips loudly (exit 0 + JSON line) only when the toolchain cannot do
the job at all: no g++/libtsan on the host. Prints one JSON line::

    {"smoke": "native sanitize (tsan+ubsan)", "passed": true,
     "sanitizer_reports": 0, ...}
"""

import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

MODES = "tsan,ubsan"
SUPPRESSIONS = os.path.join(ROOT, "benchmarks", "tsan_suppressions.txt")


def _find_libtsan():
    try:
        out = subprocess.run(["g++", "-print-file-name=libtsan.so"],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    path = out.stdout.strip()
    # When gcc can't find the file it echoes the bare name back.
    if out.returncode == 0 and os.path.isabs(path) \
            and os.path.exists(path):
        return path
    return None


def main():
    libtsan = _find_libtsan()
    if libtsan is None:
        print(json.dumps({"smoke": "native sanitize (tsan+ubsan)",
                          "skipped": "g++/libtsan unavailable"}))
        return 0

    log_dir = tempfile.mkdtemp(prefix="lddl_sanitize_smoke_")
    try:
        env = dict(os.environ)
        env["LDDL_TPU_NATIVE_SANITIZE"] = MODES
        env["JAX_PLATFORMS"] = "cpu"

        # 1. Build the instrumented kernel (no preload needed: the
        # compiler links the runtime; only LOADING needs it).
        build = subprocess.run(
            [sys.executable, "-m", "lddl_tpu.native.build"],
            cwd=ROOT, env=env, capture_output=True, text=True)
        if build.returncode != 0:
            print(json.dumps({
                "smoke": "native sanitize (tsan+ubsan)", "passed": False,
                "failed_step": "build",
                "stderr_tail": build.stderr[-2000:]}))
            return 1

        env["LD_PRELOAD"] = libtsan
        env["TSAN_OPTIONS"] = (
            "exitcode=66 halt_on_error=0 log_path={} suppressions={}"
            .format(os.path.join(log_dir, "tsan_report"), SUPPRESSIONS))
        env["UBSAN_OPTIONS"] = "halt_on_error=1 print_stacktrace=1"

        # 2. The sanitized engine must actually be the one under test.
        avail = subprocess.run(
            [sys.executable, "-c",
             "from lddl_tpu import native; "
             "raise SystemExit(0 if native.available() else 3)"],
            cwd=ROOT, env=env, capture_output=True, text=True)
        if avail.returncode != 0:
            print(json.dumps({
                "smoke": "native sanitize (tsan+ubsan)", "passed": False,
                "failed_step": "availability (sanitized engine did not "
                               "load; identity suite would be vacuous)",
                "stderr_tail": avail.stderr[-2000:]}))
            return 1

        # 3. The 1-vs-N entry-point identity suite under the
        # instrumented kernel.
        suite = subprocess.run(
            [sys.executable, "-m", "pytest",
             "tests/test_native_threads.py", "-q",
             "-p", "no:cacheprovider"],
            cwd=ROOT, env=env, capture_output=True, text=True)

        # 4. Verdict: the suite must pass AND the TSan logs must be
        # report-free (halt_on_error=0 collects every report instead of
        # stopping at the first, so one run shows the full set).
        reports = 0
        for path in sorted(glob.glob(os.path.join(log_dir,
                                                  "tsan_report.*"))):
            with open(path, encoding="utf-8", errors="replace") as f:
                reports += f.read().count("WARNING: ThreadSanitizer")
        passed = suite.returncode == 0 and reports == 0
        result = {
            "smoke": "native sanitize (tsan+ubsan)",
            "passed": passed,
            "suite_exit": suite.returncode,
            "sanitizer_reports": reports,
            "libtsan": libtsan,
        }
        if not passed:
            result["stdout_tail"] = suite.stdout[-2000:]
            tails = [open(p, encoding="utf-8", errors="replace").read()
                     for p in sorted(glob.glob(
                         os.path.join(log_dir, "tsan_report.*")))]
            result["tsan_report_tail"] = "".join(tails)[-4000:]
        print(json.dumps(result))
        return 0 if passed else 1
    finally:
        shutil.rmtree(log_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
