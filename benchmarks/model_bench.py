"""Real-chip model-stack benchmark: step time + achieved MFU for the jitted
BERT-base pretraining train step, dense vs pallas flash attention.

The whole point vs FLASH_ATTENTION_BENCH.json: every timed dispatch runs
``n_steps`` optimizer steps inside ONE XLA computation
(models.make_sharded_multi_step's lax.scan), so the ~100 ms tunneled-chip
dispatch floor is amortized to noise and the recorded per-step time is the
device's, not the host's.

MFU counts matmul FLOPs only (the standard convention): per token forward,
``layers*(8h^2 + 4h*ffn + 4L*h) + 2h*vocab + 2h^2``, and training = 3x
forward (backward is 2x). attention_dropout is 0 for both impls so dense
and flash run the same math (flash, like ring, never applies prob dropout).

Writes MODEL_BENCH.json at the repo root. Reference consumer contract this
replaces: the mock trainer's loader-only throughput print
(/root/reference/benchmarks/torch_train.py:188-199) — the reference has no
model, so this file is the rebuild's beyond-parity perf record.

Usage: python benchmarks/model_bench.py [--quick]
  --quick: tiny model/shapes, CPU-friendly smoke test of the harness.
"""

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np

# Peak dense bf16 TFLOP/s by device kind (public spec sheets).
PEAK_BF16_TFLOPS = {
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v4": 275.0,
    "TPU v6 lite": 918.0,   # v6e / Trillium
}


def matmul_flops_per_step(cfg, batch, seq_len, n_pred=None):
    """Model matmul FLOPs per optimizer step. ``n_pred`` = positions the
    MLM head actually projects per row (the gathered head,
    train.mlm_gather_cap); None = full-sequence head."""
    h, ffn = cfg.hidden_size, cfg.intermediate_size
    enc_per_token = cfg.num_layers * (8 * h * h + 4 * h * ffn
                                      + 4 * seq_len * h)
    head_per_pos = 2 * h * cfg.vocab_size + 2 * h * h  # decode + transform
    head_positions = seq_len if n_pred is None else n_pred
    per_row_fwd = (enc_per_token * seq_len + head_per_pos * head_positions)
    # Always 3x forward: MFU counts MODEL flops, so remat's recompute is
    # excluded (counting it would be HFU and inflate remat rows by ~33%).
    return 3 * per_row_fwd * batch


def _run_multi_step(mesh, cfg, batches, n_steps, reps, model=None,
                    batch_loss=None):
    """Shared timing skeleton for every row: build state, compile+warm one
    multi-step dispatch, time ``reps`` more. Synchronization is a host
    readback of the last loss (block_until_ready is not a reliable
    barrier on the tunneled runtime). Returns
    (step_s, first_loss, last_loss, warmup_s)."""
    from lddl_tpu.loader import to_device_step_batches
    from lddl_tpu.models import create_train_state, make_sharded_multi_step
    from lddl_tpu.models.train import make_optimizer

    stacked_np = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    state, _ = create_train_state(
        cfg, mesh, batches[0], model=model,
        optimizer=make_optimizer(warmup_steps=10,
                                 total_steps=n_steps * (reps + 1) + 10))
    multi = make_sharded_multi_step(mesh, cfg, n_steps, model=model,
                                    batch_loss=batch_loss)
    stacked = to_device_step_batches(stacked_np, mesh)

    t0 = time.perf_counter()
    state, metrics = multi(state, stacked, seed=0)
    first_loss = float(np.asarray(metrics["loss"])[0])  # readback = sync
    warmup_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for r in range(reps):
        state, metrics = multi(state, stacked, seed=r + 1)
    last_loss = float(np.asarray(metrics["loss"])[-1])
    elapsed = time.perf_counter() - t0
    assert np.isfinite(first_loss) and np.isfinite(last_loss), \
        (first_loss, last_loss)
    # Free the donated-state chain before the next config compiles.
    del state, metrics, stacked
    return elapsed / (reps * n_steps), first_loss, last_loss, warmup_s


def bart_matmul_flops_per_step(cfg, batch, seq_len):
    """BART denoising train-step matmul FLOPs (enc + dec self/cross + LM
    head over ALL decoder positions — denoising reconstructs every token,
    so the BERT-style masked-position gather does not apply)."""
    h, ffn = cfg.hidden_size, cfg.intermediate_size
    e = ld = seq_len
    enc = cfg.num_encoder_layers * (8 * h * h + 4 * h * ffn + 4 * e * h) * e
    dec_self = cfg.num_decoder_layers * (8 * h * h + 4 * ld * h) * ld
    dec_cross = cfg.num_decoder_layers * (
        (4 * h * h + 4 * e * h) * ld      # q/out projections + attention
        + 4 * h * h * e)                  # k/v projections over enc out
    dec_ffn = cfg.num_decoder_layers * 4 * h * ffn * ld
    head = 2 * h * cfg.vocab_size * ld
    return 3 * batch * (enc + dec_self + dec_cross + dec_ffn + head)


def bench_bart(mesh, batch, seq_len, n_steps, reps, peak_flops,
               attention_impl="dense"):
    """One BART row: same multi-step scan method as the BERT rows.
    ``attention_impl`` drives the ENCODER's bidirectional self-attention
    only — the decoder's causal and cross-attention calls always take the
    dense path inside MultiHeadAttention (blockwise kernels serve
    bidirectional self-attention)."""
    from lddl_tpu.models.bart import (BartConfig, BartForPreTraining,
                                      bart_batch_loss)
    from lddl_tpu.models.testing import fake_bart_batch

    # Floor at the preset's own 1024 so the "bart_base" label stays true
    # (BertConfig's floor is 512 because ITS preset default is 512).
    cfg = BartConfig.bart_base(attention_dropout=0.0,
                               attention_impl=attention_impl,
                               max_position_embeddings=max(1024, seq_len))
    batches = [fake_bart_batch(cfg.vocab_size, batch, seq_len, seed=2000 + i)
               for i in range(n_steps)]
    step_s, first_loss, last_loss, warmup_s = _run_multi_step(
        mesh, cfg, batches, n_steps, reps, model=BartForPreTraining(cfg),
        batch_loss=bart_batch_loss)
    flops = bart_matmul_flops_per_step(cfg, batch, seq_len)
    return {
        "model": "bart_base",
        "attention_impl": attention_impl,
        "batch": batch,
        "seq_len": seq_len,
        "n_steps_per_dispatch": n_steps,
        "timed_steps": reps * n_steps,
        "step_ms": round(step_s * 1e3, 3),
        "tokens_per_s": round(batch * seq_len / step_s, 1),
        "model_tflops_per_step": round(flops / 1e12, 3),
        "mfu": round(flops / step_s / peak_flops, 4) if peak_flops else None,
        "first_loss": round(first_loss, 4),
        "last_loss": round(last_loss, 4),
        "warmup_dispatch_s": round(warmup_s, 2),
    }


def bench_config(mesh, cfg, batch, seq_len, n_steps, reps, peak_flops):
    from lddl_tpu.models.testing import fake_pretrain_batch
    from lddl_tpu.models.train import mlm_gather_cap

    batches = [fake_pretrain_batch(cfg.vocab_size, batch, seq_len,
                                   seed=1000 + i, segment_split=True)
               for i in range(n_steps)]
    n_pred = (mlm_gather_cap(seq_len)
              if getattr(cfg, "mlm_gather", False) else None)
    if n_pred is not None and n_pred >= seq_len:
        n_pred = None

    step_s, first_loss, last_loss, warmup_s = _run_multi_step(
        mesh, cfg, batches, n_steps, reps)
    flops = matmul_flops_per_step(cfg, batch, seq_len, n_pred)
    return {
        "attention_impl": cfg.attention_impl,
        "batch": batch,
        "seq_len": seq_len,
        "mlm_gather_positions": n_pred,  # None = full-sequence MLM head
        "remat": cfg.remat,
        "n_steps_per_dispatch": n_steps,
        "timed_steps": reps * n_steps,
        "step_ms": round(step_s * 1e3, 3),
        "tokens_per_s": round(batch * seq_len / step_s, 1),
        "model_tflops_per_step": round(flops / 1e12, 3),
        "mfu": round(flops / step_s / peak_flops, 4) if peak_flops else None,
        "first_loss": round(first_loss, 4),
        "last_loss": round(last_loss, 4),
        "warmup_dispatch_s": round(warmup_s, 2),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny shapes on whatever backend is resolved")
    p.add_argument("--n-steps", type=int, default=None,
                   help="optimizer steps per dispatch (default 32; 4 quick)")
    p.add_argument("--reps", type=int, default=None,
                   help="timed dispatches per config (default 2)")
    args = p.parse_args()

    import jax
    from lddl_tpu.models import BertConfig
    from lddl_tpu.parallel import make_mesh

    device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device))
    peak = PEAK_BF16_TFLOPS.get(kind)
    peak_flops = peak * 1e12 if peak else None
    mesh = make_mesh({"dp": 1}, devices=[device])

    reps = args.reps or 2

    # Per-row (batch, n_steps) are TUNED for wall MFU on the one v5e chip
    # (round-5 sweeps): the optimizer's ~10 ms/step fixed elementwise cost
    # and the ~5.5 ms scan-iteration + dispatch overheads amortize with
    # batch and steps-per-dispatch — bert_large L=512 measured 42.3% at
    # (B=8, n=32) vs 53.5% at (B=12, n=128) with identical per-step math.
    # The batch optimum is IMPL-SPECIFIC: dense peaks at B=12 (B=16/24
    # lose, 45.5/43.9% — its [B,H,L,L] probs residual eats HBM) while
    # flash keeps scaling to B=16 (56.2% > 53.8% at B=20 > 51.3% at
    # B=24); tune per shape AND per impl.
    if args.quick:
        configs = [("bert_base", 4, 64, 4), ("bert_base", 4, 128, 4)]
        base = dict(vocab_size=1024, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128)
    else:
        # bert_large @ L=512 is the reference's own headline pretraining
        # config (phase2), served by the round-5 single-block kernels
        # (auto picks flash for 256 <= l_pad <= 896 and l_pad >= 1024,
        # dense only at the shortest bins — attention.resolve_auto_impl);
        # base @ 1024 pins the online kernels' side; base @ 2048
        # exercises the long-context story.
        # bert_base @ 768 pins the former in-between band (one-row
        # single-block cells); bert_large @ B=16 is the flash-only tuned
        # optimum — the kernels skip dense's ~100 MB/layer probs
        # residual, which flips the batch sweep (dense peaks at B=12,
        # flash at B=16: 56.2% vs 53.8%@B=20, 51.3%@B=24, round-5 sweep).
        # bert_base @ B=64 is the flash batch optimum (49.3@32 < 49.6@48
        # < 50.7@64; B=96 crashes the worker — HBM limit with the
        # stacked multi-step batches).
        configs = [("bert_base", 32, 512, 96), ("bert_base", 8, 1024, 48),
                   ("bert_base", 4, 2048, 48), ("bert_base", 16, 768, 64),
                   ("bert_base", 64, 512, 48),
                   ("bert_large", 12, 512, 128),
                   ("bert_large", 16, 512, 96)]
        base = {}

    results = []
    variants = [("dense", True), ("flash", True)]
    if not args.quick:
        # The measured cost of the full-sequence MLM head, on the
        # reference's headline config only.
        variants.append(("dense", False))
    for family, batch, seq_len, cfg_steps in configs:
        n_steps = args.n_steps or cfg_steps
        for impl, gather in variants:
            if not gather and (family, batch,
                               seq_len) != ("bert_large", 12, 512):
                continue
            make = getattr(BertConfig, family)
            cfg = make(
                attention_impl=impl, attention_dropout=0.0,
                mlm_gather=gather,
                max_position_embeddings=max(512, seq_len), **base)
            try:
                row = bench_config(mesh, cfg, batch, seq_len, n_steps, reps,
                                   peak_flops)
            except Exception as e:  # e.g. OOM at a large dense shape
                row = {"attention_impl": impl, "batch": batch,
                       "seq_len": seq_len,
                       "error": "{}: {}".format(type(e).__name__,
                                                str(e)[:300])}
            row["model"] = family
            print(row, flush=True)
            results.append(row)

    if not args.quick:
        # The second model family: BART denoising (encoder-decoder) at the
        # reference BART preprocessor's target length scale, plus the
        # L=1024 dense/flash pair pinning the encoder's crossover
        # (VERDICT r4 #5).
        for batch, seq_len, cfg_steps, impl in (
                (16, 512, 96, "dense"), (16, 512, 96, "flash"),
                (8, 1024, 48, "dense"), (8, 1024, 48, "flash")):
            try:
                row = bench_bart(mesh, batch, seq_len,
                                 args.n_steps or cfg_steps, reps,
                                 peak_flops, attention_impl=impl)
            except Exception as e:
                row = {"model": "bart_base", "batch": batch,
                       "seq_len": seq_len, "attention_impl": impl,
                       "error": "{}: {}".format(type(e).__name__,
                                                str(e)[:300])}
            print(row, flush=True)
            results.append(row)

    payload = {
        "device": str(device),
        "device_kind": kind,
        "peak_bf16_tflops": peak,
        "model": ("tiny surrogates" if args.quick
                  else "per-row (bert_base + bert_large + bart_base)"),
        "method": ("each timed dispatch = n_steps_per_dispatch optimizer "
                   "steps (per-row, tuned) in one jitted lax.scan "
                   "(make_sharded_multi_step); per-step time = wall / "
                   "({} dispatches x n_steps); MFU = matmul-FLOPs / "
                   "step_time / peak_bf16".format(reps)),
        "results": results,
    }
    # --quick is a harness smoke test: never clobber the recorded
    # real-chip artifact with tiny-surrogate rows.
    name = "MODEL_BENCH_QUICK.json" if args.quick else "MODEL_BENCH.json"
    with open(os.path.join(ROOT, name), "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote " + name)


if __name__ == "__main__":
    main()
