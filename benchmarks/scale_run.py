"""North-star scale rehearsal: the full pipeline on a >= 1 GB corpus with
--resume exercised mid-run (VERDICT round 3 item 8).

Phases, all through the REAL CLIs (fresh processes, the user surface):
1. generate a 1 GB Wikipedia-like corpus + train a 30k WordPiece vocab;
2. preprocess (CLI defaults: duplicate_factor 5, masking, binning) —
   SIGKILLed mid-gather, then resumed with --resume; wall time, spool
   file count, peak RSS (VmHWM of the worker tree) and the redo fraction
   are recorded;
3. balance to training shards;
4. one loader pass (sustained samples/s over >= 60 s);
5. a 2-process multihost-simulate preprocess leg on a slice of the same
   corpus (the tpu_pod_example wiring) checking multi-rank output counts;
6. streaming ingestion on the same slice: ingest corpus A, then delta B
   incrementally (ingest_watch --once), recording delta-bytes-written vs
   full-rerun bytes and a mid-service follow-mode loader picking up the
   new generation at an epoch boundary;
7. coordination cost: the same elastic preprocess twice on the slice
   (2 hosts each) — legacy per-lease coordination (LDDL_TPU_COORD_LEGACY
   + fixed --scatter-units) vs the default batched-keeper + adaptive
   plan — recording lease filesystem ops per completed unit (the ratio
   is the PR's acceptance number), gather-overlap seconds, and, from a
   third leg with one host SIGKILLed, the reclamation latency between
   the victim's last lease touch and the thief's steal (fleet event
   walls);
8. a full autoscale episode: ingest_watch --autoscale on a landing
   burst — backlog spike over the SLO → scale_up (helper joins the
   in-flight generation) → drain → scale_down — with the decisions read
   back from the fleet event log and pipeline_status.

Writes SCALE_RUN.json. Usage:
    python benchmarks/scale_run.py [--corpus-mb 1024] [--keep]
    python benchmarks/scale_run.py --only coordination --corpus-mb 6
The second form runs only phases 7-8 on a freshly generated slice and
MERGES them into an existing SCALE_RUN.json, preserving the committed
full-corpus numbers for the other phases.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench
from lddl_tpu.utils.cpus import usable_cpu_count


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


class RssTracker(threading.Thread):
    """Polls VmHWM (peak RSS) of a process and its direct children."""

    def __init__(self, pid):
        super().__init__(daemon=True)
        self.pid = pid
        self.peak_kb = 0
        self._stop = threading.Event()

    @staticmethod
    def _hwm_kb(pid):
        try:
            with open("/proc/{}/status".format(pid)) as f:
                for line in f:
                    if line.startswith("VmHWM:"):
                        return int(line.split()[1])
        except OSError:
            return 0
        return 0

    @staticmethod
    def _children(pid):
        try:
            out = subprocess.run(
                ["pgrep", "-P", str(pid)], capture_output=True, text=True)
            return [int(x) for x in out.stdout.split()]
        except Exception:
            return []

    def run(self):
        while not self._stop.is_set():
            total = self._hwm_kb(self.pid)
            for c in self._children(self.pid):
                total += self._hwm_kb(c)
            self.peak_kb = max(self.peak_kb, total)
            time.sleep(1.0)

    def stop(self):
        self._stop.set()


def run_cli(args, timeout=None, kill_after_groups=None, out_dir=None):
    """Run a CLI subprocess; optionally SIGKILL it once the ledger shows
    >= kill_after_groups completed gather units. Returns (returncode,
    wall_s, peak_rss_mb, killed)."""
    t0 = time.time()
    proc = subprocess.Popen(args, env=_env(), stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT)
    tracker = RssTracker(proc.pid)
    tracker.start()
    killed = False
    ledger = os.path.join(out_dir or "", "_done")
    while proc.poll() is None:
        time.sleep(1.0)
        if kill_after_groups is not None and os.path.isdir(ledger):
            done = len([n for n in os.listdir(ledger)
                        if n.startswith("group-")])
            if done >= kill_after_groups:
                proc.send_signal(signal.SIGKILL)
                killed = True
                proc.wait()
                break
        if timeout and time.time() - t0 > timeout:
            proc.kill()
            raise RuntimeError("phase timed out: {}".format(args[:4]))
    wall = time.time() - t0
    tracker.stop()
    return proc.returncode, round(wall, 1), round(tracker.peak_kb / 1024, 1), killed


def count_spool_files(out_dir):
    spool = os.path.join(out_dir, "_shuffle")
    n = 0
    # Pure count; the walk order cannot be observed.
    for _, _, files in os.walk(spool):  # lddl: disable=unsorted-iteration
        n += len([f for f in files if not f.startswith(".")])
    return n


def _spool_metrics(sink):
    """Per-holder counter values merged across pids from the telemetry
    spool snapshots. ``lease_ops_total`` (and the other coordination
    counters) are deliberately NOT fleet rollup counters, so the
    benchmark reads the raw registry snapshots the spools carry."""
    tel = os.path.join(sink, ".telemetry")
    out = {}
    if not os.path.isdir(tel):
        return out
    for holder in sorted(os.listdir(tel)):
        d = os.path.join(tel, holder)
        if not os.path.isdir(d):
            continue
        merged = {}
        for name in sorted(os.listdir(d)):
            if not (name.startswith("snapshot-pid")
                    and name.endswith(".json")):
                continue
            with open(os.path.join(d, name)) as f:
                snap = json.load(f)
            for metric, data in (snap.get("metrics") or {}).items():
                if data.get("type") != "counter":
                    continue
                dst = merged.setdefault(metric, {})
                for label, v in data.get("values", {}).items():
                    dst[label] = dst.get(label, 0) + v
        out[holder] = merged
    return out


def _counter_sum(spools, metric, label=None):
    total = 0
    for merged in spools.values():
        vals = merged.get(metric, {})
        total += vals.get(label, 0) if label else sum(vals.values())
    return total


def _fleet_events(sink):
    from lddl_tpu.observability import fleet as fl
    tel = os.path.join(sink, ".telemetry")
    events = []
    if not os.path.isdir(tel):
        return events
    for holder in sorted(os.listdir(tel)):
        d = os.path.join(tel, holder)
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            if name.startswith("events-pid") and name.endswith(".jsonl"):
                recs, _ = fl.read_jsonl(os.path.join(d, name))
                events.extend(recs)
    return events


def _steal_latencies(events):
    """Wall seconds from the victim's last touch of a unit (claim,
    renewal, or its own steal) to the thief's ``unit.stolen`` event for
    that unit — the reclamation latency an operator actually waits
    through (~ lease TTL + one claim-loop poll)."""
    lats = []
    for ev in events:
        if ev.get("kind") != "unit.stolen":
            continue
        a = ev.get("args") or {}
        unit, prev = a.get("unit"), a.get("prev_holder")
        prior = [e.get("wall") for e in events
                 if e.get("kind") in ("unit.claimed", "unit.renewed",
                                      "unit.stolen")
                 and (e.get("args") or {}).get("unit") == unit
                 and (e.get("args") or {}).get("holder") == prev
                 and e.get("wall") is not None
                 and e.get("wall") < ev.get("wall", 0.0)]
        if prior:
            lats.append(ev["wall"] - max(prior))
    return sorted(lats)


def _parquet_digests(sink):
    import hashlib
    out = {}
    for name in sorted(os.listdir(sink)):
        if ".parquet" in name and ".tmp." not in name:
            h = hashlib.sha256()
            with open(os.path.join(sink, name), "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[name] = h.hexdigest()
    return out


def phase_coordination(tmp, vocab, coord_corpus, payload, n_hosts=3,
                       lease_ttl=10.0):
    """Phase 7: lease filesystem ops per completed unit, legacy vs
    batched+adaptive coordination, plus steal latency under a host
    kill. The legs run the coordination-BOUND shape from the issue
    motivation — many blocks per unit, hosts > cores welcome — because
    that is where per-unit lease traffic dominates: legacy pays one
    fence read per block/bucket inside every unit body, while the
    batched legs answer those checks from the deadline cache. Output
    bytes must be identical across every leg (the coordination
    protocol must never show up in the data)."""

    def coord_cli(sink, holder, extra):
        return [sys.executable, "-m",
                "lddl_tpu.cli.preprocess_bert_pretrain",
                "--wikipedia", coord_corpus, "--sink", sink,
                "--vocab-file", vocab, "--masking", "--bin-size", "64",
                "--num-blocks", "256", "--seed", "99",
                "--local-workers", "1", "--elastic",
                "--lease-ttl", str(lease_ttl),
                "--elastic-host-id", holder, "--fleet-telemetry"] + extra

    def run_hosts(sink, extra, env_extra, kill_host0=False):
        env = dict(_env(), JAX_PLATFORMS="cpu",
                   LDDL_TPU_FLEET_INTERVAL_S="1", **env_extra)
        tag = os.path.basename(sink)
        logs = [open(os.path.join(tmp, "{}_{}.log".format(tag, i)), "w")
                for i in range(n_hosts)]
        t0 = time.time()
        if kill_host0:
            env0 = dict(env)
            env0["LDDL_TPU_FAULTS"] = "replace:kill:nth=1:path=_done/group-"
            procs = [subprocess.Popen(coord_cli(sink, "c0", extra),
                                      env=env0, stdout=logs[0],
                                      stderr=subprocess.STDOUT)]
            # Same head start as phase 5b: the victim must reach a
            # gather publish before a sibling can drain the queue.
            records = os.path.join(sink, "_done")
            deadline = time.time() + 120
            while time.time() < deadline and procs[0].poll() is None:
                if os.path.isdir(records) and any(
                        n.startswith("scatter-")
                        for n in os.listdir(records)):
                    break
                time.sleep(0.2)
            rest = range(1, n_hosts)
        else:
            procs = []
            rest = range(n_hosts)
        for i in rest:
            procs.append(subprocess.Popen(
                coord_cli(sink, "c{}".format(i), extra), env=env,
                stdout=logs[i], stderr=subprocess.STDOUT))
        rcs = [q.wait(timeout=1800) for q in procs]
        wall = time.time() - t0
        for f in logs:
            f.close()
        return rcs, wall

    legs, digests = {}, {}
    for mode, extra, env_extra in (
            ("legacy", ["--scatter-units", "16"],
             {"LDDL_TPU_COORD_LEGACY": "1"}),
            ("batched_adaptive", [], {})):
        sink = os.path.join(tmp, "coord_" + mode)
        rcs, wall = run_hosts(sink, extra, env_extra)
        assert rcs == [0] * n_hosts, \
            "coordination {} leg failed: {}".format(mode, rcs)
        spools = _spool_metrics(sink)
        ops_by_op = {}
        for merged in spools.values():
            for label, v in merged.get("lease_ops_total", {}).items():
                op = label.split("=", 1)[-1]
                ops_by_op[op] = ops_by_op.get(op, 0) + v
        ops = sum(ops_by_op.values())
        units = _counter_sum(spools, "elastic_units_completed_total")
        legs[mode] = {
            "wall_s": round(wall, 1),
            "units_completed": units,
            "lease_fs_ops": ops,
            "lease_fs_ops_by_op": ops_by_op,
            "ops_per_unit": round(ops / max(units, 1), 2),
            "renews": _counter_sum(spools, "lease_renews_total"),
            "gather_overlap_s": round(_counter_sum(
                spools, "gather_overlap_seconds_total"), 2),
        }
        digests[mode] = _parquet_digests(sink)
        print("coordination {}: {}".format(mode, legs[mode]), flush=True)
    assert digests["legacy"] == digests["batched_adaptive"], \
        "coordination mode changed output bytes"

    ratio = (legs["legacy"]["ops_per_unit"]
             / max(legs["batched_adaptive"]["ops_per_unit"], 1e-9))
    total_ratio = (legs["legacy"]["lease_fs_ops"]
                   / max(legs["batched_adaptive"]["lease_fs_ops"], 1))
    assert ratio >= 3.0, \
        "batched coordination saved only {:.2f}x ops/unit".format(ratio)

    # 7c: reclamation latency under a kill (default coordination).
    steal_sink = os.path.join(tmp, "coord_steal")
    rcs, steal_wall = run_hosts(steal_sink, [], {}, kill_host0=True)
    assert rcs[0] == -signal.SIGKILL, \
        "c0 was supposed to be SIGKILLed: {}".format(rcs)
    assert rcs[1:] == [0] * (n_hosts - 1), "survivor failed: {}".format(rcs)
    assert _parquet_digests(steal_sink) == digests["batched_adaptive"], \
        "kill leg changed output bytes"
    lats = _steal_latencies(_fleet_events(steal_sink))
    assert lats, "no unit.stolen events in the kill leg"

    payload["phases"]["coordination_cost"] = {
        "hosts_per_leg": n_hosts,
        "lease_ttl_s": lease_ttl,
        "legacy": legs["legacy"],
        "batched_adaptive": legs["batched_adaptive"],
        "ops_per_unit_ratio": round(ratio, 2),
        "total_ops_ratio": round(total_ratio, 2),
        "bytes_identical_across_modes": True,
        "steal_leg": {
            "wall_s": round(steal_wall, 1),
            "steals": len(lats),
            "steal_latency_s_median": round(lats[len(lats) // 2], 2),
            "steal_latency_s_max": round(lats[-1], 2),
        },
        "host_can_show_scaling": usable_cpu_count() >= 2,
    }
    print(payload["phases"]["coordination_cost"], flush=True)


def phase_autoscale(tmp, vocab, coord_corpus, payload):
    """Phase 8: one full autoscale episode through the real ingest_watch
    CLI — a landing burst over the SLO scales a helper up into the
    in-flight generation, the drain scales it back down, and both
    decisions are read back from the fleet event log."""
    landing = os.path.join(tmp, "autoscale_landing")
    os.makedirs(os.path.join(landing, "source"), exist_ok=True)
    src = os.path.join(coord_corpus, "source")
    for name in sorted(os.listdir(src)):
        shutil.copy(os.path.join(src, name),
                    os.path.join(landing, "source", name))
    sink = os.path.join(tmp, "autoscale_root")
    # The burst must hold the backlog gauge above the SLO for longer
    # than the first control round (interval/2), or the thermostat has
    # nothing to observe: the whole landing set plus a high duplicate
    # factor keeps generation 0 in flight for several control rounds,
    # which is also what gives the scaled-up helper time to join it.
    argv = [sys.executable, "-m", "lddl_tpu.cli.ingest_watch",
            "--landing", landing, "--sink", sink, "--vocab-file", vocab,
            "--masking", "--bin-size", "64", "--num-shards", "16",
            "--seed", "99", "--local-workers", "1",
            "--duplicate-factor", "16",
            "--elastic", "--lease-ttl", "10", "--elastic-host-id", "svc",
            "--fleet-telemetry", "--autoscale",
            "--backlog-slo-docs", "64", "--max-helpers", "1",
            "--drain-rounds", "1", "--interval", "2", "--max-rounds", "4"]
    t0 = time.time()
    with open(os.path.join(tmp, "autoscale.log"), "w") as lf:
        rc = subprocess.run(argv, env=dict(_env(), JAX_PLATFORMS="cpu",
                                           LDDL_TPU_FLEET_INTERVAL_S="1"),
                            stdout=lf, stderr=subprocess.STDOUT,
                            timeout=1800).returncode
    wall = time.time() - t0
    assert rc == 0, "autoscale watch leg failed rc={}".format(rc)

    events = _fleet_events(sink)
    episode = [dict(kind=ev["kind"], **(ev.get("args") or {}))
               for ev in sorted(events, key=lambda e: e.get("wall", 0.0))
               if ev.get("kind", "").startswith("autoscale.")]
    kinds = sorted({e["kind"] for e in episode})
    assert "autoscale.scale_up" in kinds, episode
    assert "autoscale.scale_down" in kinds, episode

    # The decisions must also be visible through the operator surface.
    status = subprocess.run(
        [sys.executable, "-m", "tools.pipeline_status", sink, "--json"],
        env=dict(_env(), JAX_PLATFORMS="cpu"), capture_output=True,
        text=True)
    report = json.loads(status.stdout)
    ev_counts = {}
    for hostrep in report.get("hosts", {}).values():
        for k, v in (hostrep.get("event_counts") or {}).items():
            if k.startswith("autoscale."):
                ev_counts[k] = ev_counts.get(k, 0) + v
    assert ev_counts.get("autoscale.scale_up", 0) >= 1, ev_counts

    spools = _spool_metrics(sink)
    payload["phases"]["autoscale_episode"] = {
        "wall_s": round(wall, 1),
        "backlog_slo_docs": 64,
        "max_helpers": 1,
        "duplicate_factor": 16,
        "episode": episode,
        "decisions_total": {
            "scale_up": _counter_sum(spools, "autoscale_decisions_total",
                                     label="action=scale_up"),
            "scale_down": _counter_sum(spools, "autoscale_decisions_total",
                                       label="action=scale_down"),
        },
        "helper_joined_generation": any(
            ev.get("kind") == "generation.joined" for ev in events),
        "status_exit": status.returncode,
        "status_event_counts": ev_counts,
        "host_can_show_scaling": usable_cpu_count() >= 2,
    }
    print(payload["phases"]["autoscale_episode"], flush=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--corpus-mb", type=float, default=1024.0)
    p.add_argument("--num-blocks", type=int, default=256)
    p.add_argument("--keep", action="store_true",
                   help="keep the work dir for inspection")
    p.add_argument("--workdir", default=None)
    p.add_argument("--only", choices=("all", "coordination"),
                   default="all",
                   help="coordination: run only the coordination-cost + "
                        "autoscale phases (7-8) on a freshly generated "
                        "slice and merge them into an existing "
                        "SCALE_RUN.json, preserving the committed "
                        "full-corpus numbers for the other phases")
    args = p.parse_args()

    tmp = args.workdir or tempfile.mkdtemp(prefix="lddl_scale_",
                                           dir="/tmp")
    os.makedirs(tmp, exist_ok=True)
    payload = {"corpus_mb": args.corpus_mb, "num_blocks": args.num_blocks,
               "host_cpu_count": os.cpu_count(),
               "host_usable_cpus": usable_cpu_count(),
               "host_can_show_scaling": usable_cpu_count() >= 2,
               "phases": {}}
    try:
        if args.only == "coordination":
            corpus = os.path.join(tmp, "corpus")
            if not os.path.isdir(corpus):
                bench.make_corpus(corpus, min(args.corpus_mb, 32.0),
                                  shards=4, seed=0)
            from lddl_tpu.preprocess import build_wordpiece_vocab
            sample, sb = [], 0
            with open(os.path.join(corpus, "source", "0.txt"),
                      encoding="utf-8") as f:
                for line in f:
                    sample.append(line.split(None, 1)[1])
                    sb += len(line)
                    if sb > 1_500_000:
                        break
            vocab = build_wordpiece_vocab(
                sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
            phase_coordination(tmp, vocab, corpus, payload)
            phase_autoscale(tmp, vocab, corpus, payload)
            doc_path = os.path.join(ROOT, "SCALE_RUN.json")
            doc = payload
            if os.path.exists(doc_path):
                with open(doc_path) as f:
                    doc = json.load(f)
                doc.setdefault("phases", {}).update(payload["phases"])
                doc["coordination_corpus_mb"] = min(args.corpus_mb, 32.0)
            with open(doc_path, "w") as f:
                json.dump(doc, f, indent=1)
            print("merged coordination phases into SCALE_RUN.json")
            return
        # --- phase 1: corpus + vocab --------------------------------------
        corpus = os.path.join(tmp, "corpus")
        t0 = time.time()
        if not os.path.isdir(corpus):
            nbytes, _ = bench.make_corpus(corpus, args.corpus_mb, shards=16,
                                          seed=0)
        else:
            nbytes = sum(
                os.path.getsize(os.path.join(corpus, "source", f))
                for f in os.listdir(os.path.join(corpus, "source")))
        gen_s = time.time() - t0
        from lddl_tpu.preprocess import build_wordpiece_vocab
        sample, sb = [], 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sb += len(line)
                if sb > 1_500_000:
                    break
        t0 = time.time()
        vocab = build_wordpiece_vocab(sample, os.path.join(tmp, "vocab.txt"),
                                      vocab_size=30522)
        payload["phases"]["corpus_and_vocab"] = {
            "corpus_gen_s": round(gen_s, 1),
            "corpus_bytes": nbytes,
            "vocab_train_s": round(time.time() - t0, 1),
        }
        print(payload["phases"]["corpus_and_vocab"], flush=True)

        # --- phase 2: preprocess, killed mid-run, then resumed ------------
        out = os.path.join(tmp, "pre")
        cli = [sys.executable, "-m", "lddl_tpu.cli.preprocess_bert_pretrain",
               "--wikipedia", corpus, "--sink", out,
               "--vocab-file", vocab, "--masking",
               "--bin-size", "64", "--num-blocks", str(args.num_blocks),
               "--seed", "99", "--sample-ratio", "0.9"]
        ngroups = min(args.num_blocks, max(64, args.num_blocks // 8))
        kill_at = max(2, ngroups // 3)
        rc, wall1, rss1, killed = run_cli(
            cli, kill_after_groups=kill_at, out_dir=out)
        assert killed, "first preprocess leg was supposed to be killed"
        spool_files = count_spool_files(out)
        done_before = len([n for n in os.listdir(os.path.join(out, "_done"))
                           if n.startswith("group-")])
        rc, wall2, rss2, _ = run_cli(cli + ["--resume"], out_dir=out)
        assert rc == 0, "resume leg failed rc={}".format(rc)
        shard_files = [n for n in sorted(os.listdir(out))
                       if ".parquet" in n]
        n_samples = 0
        import pyarrow.parquet as pq
        for n in shard_files:
            n_samples += pq.read_metadata(os.path.join(out, n)).num_rows
        payload["phases"]["preprocess"] = {
            "killed_after_groups": done_before,
            "groups_total": ngroups,
            "leg1_wall_s": wall1, "leg1_peak_rss_mb": rss1,
            "resume_wall_s": wall2, "resume_peak_rss_mb": rss2,
            "spool_files_at_kill": spool_files,
            "shards": len(shard_files), "samples": n_samples,
            "mb_per_s_resume_leg": round(
                nbytes / 1024 / 1024 / max(wall2, 1e-9), 2),
        }
        print(payload["phases"]["preprocess"], flush=True)

        # --- phase 3: balance ---------------------------------------------
        shards = os.path.join(tmp, "shards")
        t0 = time.time()
        rc, wall, rss, _ = run_cli(
            [sys.executable, "-m", "lddl_tpu.cli.balance_shards",
             "--indir", out, "--outdir", shards, "--num-shards", "64"])
        assert rc == 0
        payload["phases"]["balance"] = {"wall_s": wall, "peak_rss_mb": rss}
        print(payload["phases"]["balance"], flush=True)

        # --- phase 4: loader sustained pass -------------------------------
        from lddl_tpu.loader import get_bert_pretrain_data_loader
        loader = get_bert_pretrain_data_loader(
            shards, vocab_file=vocab, batch_size=256, base_seed=5)
        t0 = time.time()
        n = 0
        for batch in loader:
            n += batch["input_ids"].shape[0]
            if time.time() - t0 > 75:
                break
        dt = time.time() - t0
        payload["phases"]["loader"] = {
            "samples": n, "wall_s": round(dt, 1),
            "samples_per_s": round(n / dt, 1),
        }
        print(payload["phases"]["loader"], flush=True)

        # --- phase 5: N-process elastic work stealing with a host kill ----
        # Replaces the old barrier-coupled 2-process --multihost simulate:
        # the elastic claim loop needs no coordinator, any host may die
        # mid-unit, and the survivors reclaim its work. One host IS
        # SIGKILLed mid-gather (fault injector, dies holding a unit's
        # lease); per-host units/steals come from the CLI's elastic
        # summary lines, and byte-level integrity from the sample count
        # matching the 1-process baseline.
        sim_corpus = os.path.join(tmp, "sim_corpus")
        if not os.path.isdir(sim_corpus):
            os.makedirs(os.path.join(sim_corpus, "source"))
            # first 2 source shards of the big corpus (~ corpus/8)
            for i in range(2):
                shutil.copy(
                    os.path.join(corpus, "source", "{}.txt".format(i)),
                    os.path.join(sim_corpus, "source", "{}.txt".format(i)))
        sim_bytes = sum(
            os.path.getsize(os.path.join(sim_corpus, "source", f))
            for f in os.listdir(os.path.join(sim_corpus, "source")))

        def elastic_cli(sink, holder, fleet=False):
            argv = [sys.executable, "-m",
                    "lddl_tpu.cli.preprocess_bert_pretrain",
                    "--wikipedia", sim_corpus, "--sink", sink,
                    "--vocab-file", vocab, "--masking", "--bin-size", "64",
                    "--num-blocks", "64", "--seed", "99",
                    "--sample-ratio", "0.9", "--local-workers", "1",
                    "--elastic", "--lease-ttl", "10",
                    "--elastic-host-id", holder]
            if fleet:
                # Per-host telemetry spools under <sink>/.telemetry/ —
                # phase 5b's kill scenario then doubles as the fleet
                # acceptance run: pipeline_status must reconstruct the
                # cluster's story from the spools alone.
                argv.append("--fleet-telemetry")
            return argv

        def count_samples(sink):
            n = 0
            for name in sorted(os.listdir(sink)):
                if ".parquet" in name and ".tmp." not in name:
                    n += pq.read_metadata(os.path.join(sink, name)).num_rows
            return n

        # 5a: single-elastic-host baseline (the scaling denominator).
        base_out = os.path.join(tmp, "sim_pre_1p")
        t0 = time.time()
        rc = subprocess.run(elastic_cli(base_out, "base"),
                            env=dict(_env(), JAX_PLATFORMS="cpu"),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.STDOUT).returncode
        base_wall = time.time() - t0
        assert rc == 0, "elastic 1-proc baseline failed rc={}".format(rc)
        base_samples = count_samples(base_out)

        # 5b: N hosts, one SIGKILLed at its first gather ledger publish.
        # host0 (the victim) gets a head start so it is guaranteed to
        # reach a gather publish even on a small slice where a fast
        # sibling could otherwise drain the whole queue first; the
        # survivors join the in-progress run via the fingerprint
        # manifest and steal the unit host0 dies holding.
        n_hosts = 3
        sim_out = os.path.join(tmp, "sim_pre_np")
        t0 = time.time()
        # Host stdout goes to FILES, not pipes: an undrained 64KB pipe
        # would block a chatty host mid-claim-loop (its keeper thread
        # still renewing, so nothing could ever steal its units) and
        # deadlock the whole phase.
        log_paths = [os.path.join(tmp, "host{}.log".format(r))
                     for r in range(n_hosts)]
        log_files = [open(p, "w") for p in log_paths]
        env0 = dict(_env(), JAX_PLATFORMS="cpu")
        env0["LDDL_TPU_FAULTS"] = "replace:kill:nth=1:path=_done/group-"
        env0["LDDL_TPU_FLEET_INTERVAL_S"] = "1"
        procs = [subprocess.Popen(
            elastic_cli(sim_out, "host0", fleet=True), env=env0,
            stdout=log_files[0], stderr=subprocess.STDOUT)]
        sc_records = os.path.join(sim_out, "_done")
        deadline = time.time() + 120
        while time.time() < deadline and procs[0].poll() is None:
            if os.path.isdir(sc_records) and any(
                    n.startswith("scatter-")
                    for n in os.listdir(sc_records)):
                break  # host0 is mid-scatter: safely ahead
            time.sleep(0.2)
        for rank in range(1, n_hosts):
            procs.append(subprocess.Popen(
                elastic_cli(sim_out, "host{}".format(rank), fleet=True),
                env=dict(_env(), JAX_PLATFORMS="cpu",
                         LDDL_TPU_FLEET_INTERVAL_S="1"),
                stdout=log_files[rank], stderr=subprocess.STDOUT))
        for q in procs:
            try:
                q.wait(timeout=3600)
            except subprocess.TimeoutExpired:
                for p2 in procs:
                    p2.kill()
                raise RuntimeError("elastic phase host hung")
        for f in log_files:
            f.close()
        host_logs = []
        for p in log_paths:
            with open(p) as f:
                host_logs.append(f.read())
        rcs = [q.returncode for q in procs]
        sim_wall = time.time() - t0
        assert rcs[0] == -signal.SIGKILL, \
            "host0 was supposed to be SIGKILLed: rcs={}".format(rcs)
        assert rcs[1:] == [0] * (n_hosts - 1), \
            "survivor legs failed: {}".format(rcs)
        sim_samples = count_samples(sim_out)
        assert sim_samples == base_samples, \
            "elastic N-proc output diverged: {} vs {}".format(
                sim_samples, base_samples)

        import re
        per_host = {}
        summary_re = re.compile(
            r"elastic summary: holder=(\S+) units=(\d+) steals=(\d+) "
            r"fence_rejects=(\d+)")
        for rank, text in enumerate(host_logs):
            m = summary_re.search(text or "")
            per_host["host{}".format(rank)] = (
                {"units_completed": int(m.group(2)),
                 "steals": int(m.group(3)),
                 "fence_rejects": int(m.group(4))}
                if m else {"killed_mid_run": True})
        mbps_1p = sim_bytes / 1024 / 1024 / max(base_wall, 1e-9)
        mbps_np = sim_bytes / 1024 / 1024 / max(sim_wall, 1e-9)
        payload["phases"]["elastic_worksteal"] = {
            "hosts": n_hosts, "killed_host": "host0",
            "wall_s_1proc": round(base_wall, 1),
            "wall_s_nproc_with_kill": round(sim_wall, 1),
            "samples": sim_samples,
            "per_host": per_host,
            "steals_total": sum(h.get("steals", 0)
                                for h in per_host.values()),
            "mb_per_s_1proc": round(mbps_1p, 2),
            "mb_per_s_nproc": round(mbps_np, 2),
            "scaling_ratio": round(mbps_np / max(mbps_1p, 1e-9), 2),
            "host_can_show_scaling": usable_cpu_count() >= 2,
        }
        # Fleet-telemetry acceptance, from the spool artifacts alone:
        # pipeline_status --json must see the SIGKILLed host as the one
        # stalled host and total the journaled ground truth; the merged
        # Chrome trace must span every host (victim's pre-kill buffer
        # included). Exit 2 = unhealthy-by-design (the dead host).
        merged_trace = os.path.join(tmp, "fleet_merged_trace.json")
        status = subprocess.run(
            [sys.executable, "-m", "tools.pipeline_status", sim_out,
             "--json", "--merge-trace", merged_trace],
            env=dict(_env(), JAX_PLATFORMS="cpu"), capture_output=True,
            text=True)
        assert status.returncode == 2, (status.returncode, status.stderr)
        fleet_report = json.loads(status.stdout)
        assert fleet_report["health"]["stalled_hosts"] == ["host0"]
        assert (fleet_report["totals"]["counters"]["units_completed"]
                == sum(h.get("units_completed", 0)
                       for h in per_host.values())
                + fleet_report["hosts"]["host0"]["counters"]
                ["units_completed"])
        with open(merged_trace) as f:
            lanes = {ev["args"]["name"].split(" ")[0] for ev in json.load(f)
                     if ev.get("ph") == "M"
                     and ev.get("name") == "process_name"}
        assert lanes == {"host{}".format(r) for r in range(n_hosts)}, lanes
        payload["phases"]["elastic_worksteal"]["fleet"] = {
            "stalled_hosts": fleet_report["health"]["stalled_hosts"],
            "verdicts": fleet_report["health"]["verdicts"],
            "units_total": fleet_report["totals"]["counters"]
            ["units_completed"],
            "steals_total": fleet_report["totals"]["counters"]["steals"],
            "fence_rejects_total": fleet_report["totals"]["counters"]
            ["fence_rejects"],
            "merged_trace_lanes": sorted(lanes),
        }
        print(payload["phases"]["elastic_worksteal"], flush=True)

        # --- phase 6: streaming ingestion (delta vs full-rerun cost) ------
        # Corpus A (2 source shards) is ingested through the real
        # ingest_watch CLI as generation 0; a follow-mode loader starts
        # streaming it; delta B (1 more source shard) lands and is
        # ingested incrementally. Recorded: bytes written for the delta
        # vs the bytes a full from-scratch rerun over A∪B writes (the
        # ratio is the whole point of the delta balancer), prior-shard
        # byte identity, sample-census equivalence vs the from-scratch
        # run, and the loader picking up generation 1 at its next epoch
        # boundary without restart.
        import hashlib
        from lddl_tpu.utils.fs import get_all_parquets_under

        def shard_state(root):
            out = {}
            for pth in get_all_parquets_under(root):
                h = hashlib.sha256()
                with open(pth, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                out[os.path.relpath(pth, root)] = (os.path.getsize(pth),
                                                   h.hexdigest())
            return out

        def count_rows(paths):
            return sum(pq.read_metadata(pth).num_rows for pth in paths)

        ing_land = os.path.join(tmp, "ingest_landing")
        os.makedirs(os.path.join(ing_land, "source"), exist_ok=True)
        for i in range(2):  # corpus A
            shutil.copy(os.path.join(corpus, "source", "{}.txt".format(i)),
                        os.path.join(ing_land, "source", "{}.txt".format(i)))
        a_bytes = sum(
            os.path.getsize(os.path.join(ing_land, "source", f))
            for f in os.listdir(os.path.join(ing_land, "source")))

        def ingest_cli(sink):
            return [sys.executable, "-m", "lddl_tpu.cli.ingest_watch",
                    "--landing", ing_land, "--sink", sink,
                    "--vocab-file", vocab, "--masking", "--bin-size", "64",
                    "--num-shards", "64", "--seed", "99", "--once"]

        ing_root = os.path.join(tmp, "ingest_root")
        rc, wall_a, rss_a, _ = run_cli(ingest_cli(ing_root))
        assert rc == 0, "ingest of corpus A failed rc={}".format(rc)
        snap_a = shard_state(ing_root)

        # A loader is mid-service on generation 0 while the delta lands.
        follow_loader = get_bert_pretrain_data_loader(
            ing_root, vocab_file=vocab, batch_size=256, base_seed=5,
            follow_generations=True)
        epoch0 = sum(b["input_ids"].shape[0] for b in follow_loader)

        # Delta B is deliberately SMALL relative to A (first ~1/8 of one
        # source shard): the recorded ratio is the service's whole value
        # proposition — a small delta must cost delta-sized writes, not a
        # full-corpus rewrite.
        delta_src = os.path.join(ing_land, "source", "2.txt")
        src2 = os.path.join(corpus, "source", "2.txt")
        take = os.path.getsize(src2) // 8
        with open(src2, "rb") as fin, open(delta_src, "wb") as fout:
            got = 0
            for line in fin:
                fout.write(line)
                got += len(line)
                if got >= take:
                    break
        b_bytes = os.path.getsize(delta_src)
        rc, wall_b, rss_b, _ = run_cli(ingest_cli(ing_root))
        assert rc == 0, "ingest of delta B failed rc={}".format(rc)
        snap_b = shard_state(ing_root)

        rewritten = {rel for rel, st in snap_b.items()
                     if rel in snap_a and snap_a[rel] != st}
        assert not rewritten, \
            "delta ingest rewrote prior shards: {}".format(sorted(rewritten))
        delta_bytes = sum(st[0] for rel, st in snap_b.items()
                          if rel not in snap_a or snap_a[rel] != st)

        # Full-rerun comparator: a from-scratch ingest over A∪B.
        full_root = os.path.join(tmp, "ingest_full")
        rc, wall_full, _, _ = run_cli(ingest_cli(full_root))
        assert rc == 0, "full-rerun comparator failed rc={}".format(rc)
        full_bytes = sum(st[0] for st in shard_state(full_root).values())
        # Census sanity vs the from-scratch run. NOT exact equality by
        # design: BERT pair generation is bucket-grouping-dependent (NSP
        # negatives draw sibling documents; RNG streams are keyed per
        # (bucket, pass, doc index)), so a monolithic A∪B run groups —
        # and therefore samples — slightly differently than A then B.
        # The exact invariant (incremental == from-scratch replay of the
        # same ingest sequence, crash/FS-order-proof, byte-identical) is
        # pinned by tests/test_ingest.py; here we bound gross data loss.
        carry_d = os.path.join(ing_root, ".ingest", "carry")
        carry_rows = count_rows(
            [os.path.join(carry_d, n) for n in sorted(os.listdir(carry_d))]
            if os.path.isdir(carry_d) else [])
        grown_rows = count_rows(get_all_parquets_under(ing_root))
        full_rows = count_rows(get_all_parquets_under(full_root))
        assert abs(grown_rows + carry_rows - full_rows) < 0.05 * full_rows, \
            "incremental census diverged: {}+{} vs {}".format(
                grown_rows, carry_rows, full_rows)

        # The SAME loader object crosses an epoch boundary and must see
        # generation 1 without restart.
        epoch1 = sum(b["input_ids"].shape[0] for b in follow_loader)
        assert epoch1 > epoch0, \
            "follow-mode loader missed the new generation"

        payload["phases"]["incremental_ingest"] = {
            "corpus_a_bytes": a_bytes,
            "delta_b_bytes": b_bytes,
            "ingest_a_wall_s": wall_a,
            "ingest_b_wall_s": wall_b,
            "full_rerun_wall_s": wall_full,
            "delta_bytes_written": delta_bytes,
            "full_rerun_bytes": full_bytes,
            "delta_to_full_bytes_ratio": round(
                delta_bytes / max(full_bytes, 1), 4),
            "prior_shards_rewritten": 0,
            "grown_rows_visible": grown_rows,
            "carry_rows_parked": carry_rows,
            "full_rerun_rows": full_rows,
            "loader_epoch0_samples": epoch0,
            "loader_epoch1_samples": epoch1,
            "generation_picked_up_mid_service": True,
        }
        print(payload["phases"]["incremental_ingest"], flush=True)

        # --- phases 7-8: coordination cost + autoscale episode ------------
        phase_coordination(tmp, vocab, sim_corpus, payload)
        phase_autoscale(tmp, vocab, sim_corpus, payload)

        payload["note"] = (
            "all phases through the real CLIs on a single host; preprocess "
            "leg 1 is SIGKILLed once ~1/3 of gather units are ledgered and "
            "the --resume leg finishes the run (spool reused: scatter "
            "marker present). Phase 5 runs the lease-based elastic "
            "work-stealing preprocess on a corpus slice: a 1-process "
            "baseline, then N independent --elastic hosts with host0 "
            "SIGKILLed at its first gather ledger publish (dies holding a "
            "lease); survivors steal, finish, and the sample census must "
            "match the baseline exactly. Phase 6 runs the streaming "
            "ingestion service on the same slice: corpus A through "
            "ingest_watch --once, a follow-mode loader mid-service, then "
            "delta B ingested incrementally — bytes written for the delta "
            "vs a from-scratch rerun over A∪B is the recorded ratio, "
            "prior shards must stay byte-identical, and the loader must "
            "pick up generation 1 at its next epoch boundary without "
            "restart. Phase 7 reruns the elastic preprocess twice with "
            "two hosts each — legacy per-lease coordination vs the "
            "batched keeper + adaptive plan — and records lease "
            "filesystem ops per completed unit from lease_ops_total in "
            "the spool snapshots (output bytes identical across modes), "
            "plus steal latency from fleet event walls under a host "
            "kill. Phase 8 records one full autoscale episode (backlog "
            "spike -> scale_up -> helper joins -> drain -> scale_down) "
            "through ingest_watch --autoscale, decisions read back from "
            "the fleet event log. host_can_show_scaling flags whether "
            "this host has enough cores (>= 4) for the concurrency "
            "ratios to mean anything. Peak RSS = VmHWM summed over the "
            "worker tree, 1 s polling.")
        with open(os.path.join(ROOT, "SCALE_RUN.json"), "w") as f:
            json.dump(payload, f, indent=1)
        print("wrote SCALE_RUN.json")
    finally:
        if not args.keep:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
