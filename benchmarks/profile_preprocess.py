"""Attribute preprocess time: cProfile the single-worker headline config.

Usage: python benchmarks/profile_preprocess.py [MB] [--out PATH]
Prints the top cumulative/tottime entries and writes the sink breakdown
JSON to ``--out`` (default: PROFILE_PREPROCESS.json at the repo root —
the committed attribution artifact VERDICT r4 #4 asks for; point --out
elsewhere when profiling scratch experiments so the committed artifact
is not clobbered). The run is single-worker so the profile sees the
worker's actual work; the headline bench adds a process pool around
exactly this per-bucket pipeline.

Sink buckets (module-level attribution, C++ engine time shows up under
the ctypes call):
  tokenize_native  — the one-pass C++ split+normalize+WordPiece engine
  masking          — ops/masking numpy batch masking
  arrow_write      — parquet/arrow column building + write (incl. lz4)
  spool_io         — radix spool scatter/gather text IO
  pairs/instances  — pair assembly from tokenized sentences
  other_python     — everything else
"""

import cProfile
import io
import json
import os
import pstats
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402  (repo-root bench.py: corpus + vocab helpers)


_SINKS = (
    # Order matters: first match wins (mask_batch lives in native/__init__
    # but is masking work, so it must match before the tokenize needle).
    ("masking", ("ops/masking", "mask_batch")),
    ("tokenize_native", ("native/__init__", "ctypes")),
    ("durable_publish_io", ("posix.fsync", "zlib.crc32", "resilience/io",
                            "resilience/integrity")),
    ("arrow_write", ("arrowcols", "binning", "pyarrow", "parquet")),
    ("spool_io", ("_read_group", "_scatter", "_scan_block", "_spool_one",
                  "_write_txt", "spool", "readers")),
    ("pairs_instances", ("preprocess/bert", "pairs_from", "instances_from")),
)


def _sink_of(func):
    filename, _, name = func
    key = "{}:{}".format(filename.replace(os.sep, "/"), name)
    for sink, needles in _SINKS:
        if any(n in key for n in needles):
            return sink
    return "other_python"


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("mb", nargs="?", type=float, default=24.0)
    ap.add_argument("--out",
                    default=os.path.join(ROOT, "PROFILE_PREPROCESS.json"))
    ns = ap.parse_args()
    target_mb = ns.mb
    tmp = tempfile.mkdtemp(prefix="lddl_prof_")
    try:
        from lddl_tpu.preprocess import (
            BertPretrainConfig, build_wordpiece_vocab, get_tokenizer,
            run_bert_preprocess)

        corpus = os.path.join(tmp, "corpus")
        nbytes, _ = bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 1_500_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
        tokenizer = get_tokenizer(vocab_file=vocab)

        def run(out_name, corpus_dir):
            return run_bert_preprocess(
                {"wikipedia": corpus_dir}, os.path.join(tmp, out_name),
                tokenizer,
                config=BertPretrainConfig(
                    max_seq_length=128, duplicate_factor=1, masking=True,
                    engine="numpy", tokenizer_engine="auto"),
                num_blocks=8, sample_ratio=1.0, seed=12345, bin_size=32,
                num_workers=1)

        # Warmup (native build, tokenizer tables) outside the profile.
        warm = os.path.join(tmp, "warm")
        bench.make_corpus(warm, 1, seed=2)
        run("out_warm", warm)

        from lddl_tpu.preprocess import sink as sink_mod
        sink_before = sink_mod.stats_snapshot()
        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        run("out_main", corpus)
        prof.disable()
        elapsed = time.perf_counter() - t0
        sink_after = sink_mod.stats_snapshot()

        buf = io.StringIO()
        st = pstats.Stats(prof, stream=buf)
        st.sort_stats("cumulative").print_stats(40)
        st.sort_stats("tottime").print_stats(30)
        print(buf.getvalue())

        # Before/after: carry the prior artifact's headline + sink
        # breakdown forward so a perf PR's attribution shift is readable
        # from the committed artifact alone.
        previous = None
        if os.path.exists(ns.out):
            try:
                with open(ns.out) as f:
                    prior = json.load(f)
                previous = {
                    "mb_per_s_single_worker":
                        prior.get("mb_per_s_single_worker"),
                    "elapsed_s": prior.get("elapsed_s"),
                    "host_calibration_s": prior.get("host_calibration_s"),
                    "sinks_tottime_s": prior.get("sinks_tottime_s"),
                }
            except (ValueError, OSError):
                previous = None

        # Aggregate tottime into named sinks + top functions, and write
        # the committed artifact.
        sinks = {}
        rows = []
        # NB: pstats.Stats(prof) consumes the profiler's raw entries;
        # the (file, line, func) -> (cc, nc, tt, ct, callers) table
        # lives on the Stats object afterwards.
        for func, (cc, nc, tt, ct, callers) in st.stats.items():
            sinks[_sink_of(func)] = sinks.get(_sink_of(func), 0.0) + tt
            rows.append((tt, ct, "{}:{}:{}".format(
                os.sep.join(func[0].split(os.sep)[-2:]), func[1], func[2])))
        rows.sort(reverse=True)
        total = sum(s for s in sinks.values()) or 1.0
        payload = {
            "config": "headline (native tokenizer engine, numpy masking, "
                      "bin 32, L 128), single worker",
            "corpus_mb": round(nbytes / 1024 / 1024, 2),
            "elapsed_s": round(elapsed, 2),
            "mb_per_s_single_worker": round(nbytes / 1024 / 1024 / elapsed,
                                            3),
            "host_calibration_s": bench.host_calibration(),
            # Same stamp SCALE_RUN/LOADER_BENCH carry: whether this
            # measurement host had the cores to show parallel scaling
            # (a 1-core CI box profiles attribution fine but its MB/s
            # must not be read as a multi-worker claim).
            "host_can_show_scaling": (os.cpu_count() or 1) >= 4,
            "sinks_tottime_s": {
                k: {"s": round(v, 3), "share_pct": round(100 * v / total, 1)}
                for k, v in sorted(sinks.items(), key=lambda kv: -kv[1])},
            "top_functions_tottime": [
                {"tottime_s": round(tt, 3), "cumtime_s": round(ct, 3),
                 "where": where}
                for tt, ct, where in rows[:12]],
            "note": "cProfile adds interpreter overhead (~10-25%); use "
                    "shares, not absolute seconds, and compare MB/s only "
                    "against other single-worker profiled runs.",
            # Async-sink attribution note: cProfile instruments only the
            # producer thread, so with the shard writer on (the default)
            # sinks_tottime_s IS the producer-side wall — parquet encode/
            # fsync/publish seconds that moved to the writer thread are
            # accounted here instead, from preprocess.sink's process-
            # cumulative stats.
            "sink_overlap": {
                "async_depth": sink_mod.sink_depth(),
                "writer_write_s": round(
                    sink_after["write_s"] - sink_before["write_s"], 3),
                "producer_stall_s": round(
                    sink_after["stall_s"] - sink_before["stall_s"], 3),
                "deferred_publishes": (sink_after["tasks"]
                                       - sink_before["tasks"]),
                "units": sink_after["units"] - sink_before["units"],
            },
        }
        if previous is not None:
            payload["previous"] = previous
        with open(ns.out, "w") as f:
            json.dump(payload, f, indent=1)
        print("wrote", ns.out)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
