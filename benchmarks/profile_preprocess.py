"""Attribute preprocess time: cProfile the single-worker headline config.

Usage: python benchmarks/profile_preprocess.py [MB] [--out PATH]
Prints the top cumulative/tottime entries and writes the sink breakdown
JSON to ``--out`` (default: PROFILE_PREPROCESS.json at the repo root —
the committed attribution artifact VERDICT r4 #4 asks for; point --out
elsewhere when profiling scratch experiments so the committed artifact
is not clobbered). The run is single-worker so the profile sees the
worker's actual work; the headline bench adds a process pool around
exactly this per-bucket pipeline.

Sink buckets (module-level attribution, C++ engine time shows up under
the ctypes call):
  tokenize_native  — the one-pass C++ split+normalize+WordPiece engine
  masking          — ops/masking numpy batch masking
  arrow_write      — parquet/arrow column building + write (incl. lz4)
  spool_io         — radix spool scatter/gather text IO
  pairs/instances  — pair assembly from tokenized sentences
  other_python     — everything else
"""

import cProfile
import io
import json
import os
import pstats
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402  (repo-root bench.py: corpus + vocab helpers)
from lddl_tpu.utils.cpus import usable_cpu_count  # noqa: E402


_SINKS = (
    # Order matters: first match wins (mask_batch lives in native/__init__
    # but is masking work, so it must match before the tokenize needle).
    ("masking", ("ops/masking", "mask_batch")),
    ("tokenize_native", ("native/__init__", "ctypes")),
    ("durable_publish_io", ("posix.fsync", "zlib.crc32", "resilience/io",
                            "resilience/integrity")),
    ("arrow_write", ("arrowcols", "binning", "pyarrow", "parquet")),
    ("spool_io", ("_read_group", "_scatter", "_scan_block", "_spool_one",
                  "_write_txt", "spool", "readers")),
    ("pairs_instances", ("preprocess/bert", "pairs_from", "instances_from")),
)


def _sink_of(func):
    filename, _, name = func
    key = "{}:{}".format(filename.replace(os.sep, "/"), name)
    for sink, needles in _SINKS:
        if any(n in key for n in needles):
            return sink
    return "other_python"


def _fresh_native(tokenizer):
    """A NativeTokenizer mirroring ``tokenizer``'s vocab, or None."""
    from lddl_tpu import native
    from lddl_tpu.preprocess.bert import TokenizerInfo
    if not native.available():
        return None
    info = TokenizerInfo(tokenizer)
    nat = info.native_tokenizer()
    if nat is None:
        return None
    # Rebuild fresh from the pickled ctor args so each measurement starts
    # with cold memo caches (no cross-thread-count warm-up bias).
    cls, args = nat.__reduce__()
    return cls(*args)


def native_thread_bench(tokenizer, texts, seconds=1.0):
    """Standalone tokenize MB/s at thread counts {1, 2, 4, nproc}.

    Informational on a 1-core host (the pool runs but cannot speed up);
    on >= 2 usable cores the 2-thread row is the scaling criterion
    (tokenize >= 1.6x at 2 threads, ISSUE 18). A fresh tokenizer per
    count keeps the word-memo warm-up identical across rows."""
    import time as _time
    data = [t.encode("utf-8") for t in texts]
    nbytes = float(sum(len(d) for d in data))
    rows = {}
    for nt in sorted({1, 2, 4, usable_cpu_count()}):
        nat = _fresh_native(tokenizer)
        if nat is None:
            return None
        nat.set_threads(nt)
        nat.tokenize_docs(data[:8])  # pool + table warm-up
        t0 = _time.perf_counter()
        reps = 0
        elapsed = 0.0
        while elapsed < seconds:
            nat.tokenize_docs(data)
            reps += 1
            elapsed = _time.perf_counter() - t0
        rows[str(nt)] = round(nbytes * reps / elapsed / 1e6, 2)
    speedup_2t = (round(rows["2"] / rows["1"], 3)
                  if "1" in rows and "2" in rows and rows["1"] else None)
    return {
        "tokenize_mb_per_s_by_threads": rows,
        "speedup_2_threads": speedup_2t,
        "meets_2t_criterion": (None if usable_cpu_count() < 2
                               or speedup_2t is None
                               else speedup_2t >= 1.6),
    }


def sentence_memo_bench(tokenizer, texts, dup=8):
    """MB/s on a bucket whose sentences repeat ``dup``x vs a unique
    stream — the in-kernel sentence-level token-run memo (ISSUE 18
    satellite) should make the repeated bucket tokenize faster per byte;
    the ratio is that win (1.0 = no memo effect)."""
    import time as _time
    base = texts[:max(1, len(texts) // dup)]
    repeated = [t.encode("utf-8") for t in base] * dup
    unique = [t.encode("utf-8") for t in texts[:len(repeated)]]

    def mbps(data):
        nat = _fresh_native(tokenizer)
        if nat is None:
            return None
        nat.tokenize_docs(data[:8])
        nbytes = float(sum(len(d) for d in data))
        t0 = _time.perf_counter()
        reps = 0
        elapsed = 0.0
        while elapsed < 0.5:
            nat.tokenize_docs(data)
            reps += 1
            elapsed = _time.perf_counter() - t0
        return nbytes * reps / elapsed / 1e6
    r, u = mbps(repeated), mbps(unique)
    if r is None or u is None or not u:
        return None
    return {"repeated_mb_per_s": round(r, 2),
            "unique_mb_per_s": round(u, 2),
            "memo_speedup": round(r / u, 3)}


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("mb", nargs="?", type=float, default=24.0)
    ap.add_argument("--out",
                    default=os.path.join(ROOT, "PROFILE_PREPROCESS.json"))
    ns = ap.parse_args()
    target_mb = ns.mb
    tmp = tempfile.mkdtemp(prefix="lddl_prof_")
    try:
        from lddl_tpu.preprocess import (
            BertPretrainConfig, build_wordpiece_vocab, get_tokenizer,
            run_bert_preprocess)

        corpus = os.path.join(tmp, "corpus")
        nbytes, _ = bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 1_500_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
        tokenizer = get_tokenizer(vocab_file=vocab)

        def run(out_name, corpus_dir):
            return run_bert_preprocess(
                {"wikipedia": corpus_dir}, os.path.join(tmp, out_name),
                tokenizer,
                config=BertPretrainConfig(
                    max_seq_length=128, duplicate_factor=1, masking=True,
                    engine="numpy", tokenizer_engine="auto"),
                num_blocks=8, sample_ratio=1.0, seed=12345, bin_size=32,
                num_workers=1)

        # Warmup (native build, tokenizer tables) outside the profile.
        warm = os.path.join(tmp, "warm")
        bench.make_corpus(warm, 1, seed=2)
        run("out_warm", warm)

        from lddl_tpu.preprocess import sink as sink_mod
        sink_before = sink_mod.stats_snapshot()
        prof = cProfile.Profile()
        t0 = time.perf_counter()
        prof.enable()
        run("out_main", corpus)
        prof.disable()
        elapsed = time.perf_counter() - t0
        sink_after = sink_mod.stats_snapshot()

        buf = io.StringIO()
        st = pstats.Stats(prof, stream=buf)
        st.sort_stats("cumulative").print_stats(40)
        st.sort_stats("tottime").print_stats(30)
        print(buf.getvalue())

        # Before/after: carry the prior artifact's headline + sink
        # breakdown forward so a perf PR's attribution shift is readable
        # from the committed artifact alone.
        previous = None
        if os.path.exists(ns.out):
            try:
                with open(ns.out) as f:
                    prior = json.load(f)
                previous = {
                    "mb_per_s_single_worker":
                        prior.get("mb_per_s_single_worker"),
                    "elapsed_s": prior.get("elapsed_s"),
                    "host_calibration_s": prior.get("host_calibration_s"),
                    "sinks_tottime_s": prior.get("sinks_tottime_s"),
                }
            except (ValueError, OSError):
                previous = None

        # Aggregate tottime into named sinks + top functions, and write
        # the committed artifact.
        sinks = {}
        rows = []
        # NB: pstats.Stats(prof) consumes the profiler's raw entries;
        # the (file, line, func) -> (cc, nc, tt, ct, callers) table
        # lives on the Stats object afterwards.
        for func, (cc, nc, tt, ct, callers) in st.stats.items():
            sinks[_sink_of(func)] = sinks.get(_sink_of(func), 0.0) + tt
            rows.append((tt, ct, "{}:{}:{}".format(
                os.sep.join(func[0].split(os.sep)[-2:]), func[1], func[2])))
        rows.sort(reverse=True)
        total = sum(s for s in sinks.values()) or 1.0
        payload = {
            "config": "headline (native tokenizer engine, numpy masking, "
                      "bin 32, L 128), single worker",
            "corpus_mb": round(nbytes / 1024 / 1024, 2),
            "elapsed_s": round(elapsed, 2),
            "mb_per_s_single_worker": round(nbytes / 1024 / 1024 / elapsed,
                                            3),
            "host_calibration_s": bench.host_calibration(),
            # Same stamp SCALE_RUN/LOADER_BENCH carry: whether this
            # measurement host had the cores to show parallel scaling
            # (a 1-core CI box profiles attribution fine but its MB/s
            # must not be read as a multi-worker claim).
            "host_can_show_scaling": usable_cpu_count() >= 2,
            "sinks_tottime_s": {
                k: {"s": round(v, 3), "share_pct": round(100 * v / total, 1)}
                for k, v in sorted(sinks.items(), key=lambda kv: -kv[1])},
            "top_functions_tottime": [
                {"tottime_s": round(tt, 3), "cumtime_s": round(ct, 3),
                 "where": where}
                for tt, ct, where in rows[:12]],
            "note": "cProfile adds interpreter overhead (~10-25%); use "
                    "shares, not absolute seconds, and compare MB/s only "
                    "against other single-worker profiled runs.",
            # Async-sink attribution note: cProfile instruments only the
            # producer thread, so with the shard writer on (the default)
            # sinks_tottime_s IS the producer-side wall — parquet encode/
            # fsync/publish seconds that moved to the writer thread are
            # accounted here instead, from preprocess.sink's process-
            # cumulative stats.
            "sink_overlap": {
                "async_depth": sink_mod.sink_depth(),
                "writer_write_s": round(
                    sink_after["write_s"] - sink_before["write_s"], 3),
                "producer_stall_s": round(
                    sink_after["stall_s"] - sink_before["stall_s"], 3),
                "deferred_publishes": (sink_after["tasks"]
                                       - sink_before["tasks"]),
                "units": sink_after["units"] - sink_before["units"],
            },
        }
        if previous is not None:
            payload["previous"] = previous
        # Per-thread-count standalone tokenize MB/s (informational on a
        # 1-core host; the 2-thread criterion row on multi-core) and the
        # sentence-memo win on repeated-sentence buckets.
        payload["native_thread_scaling"] = native_thread_bench(
            tokenizer, sample)
        payload["sentence_memo"] = sentence_memo_bench(tokenizer, sample)
        with open(ns.out, "w") as f:
            json.dump(payload, f, indent=1)
        print("wrote", ns.out)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
