"""Attribute preprocess time: cProfile the single-worker headline bench run.

Usage: python benchmarks/profile_preprocess.py [MB]
Prints the top cumulative-time entries plus a phase breakdown
(scatter / gather-read / bucket-process), to attribute regressions like
the round-3 one (VERDICT.md round 3, item 1).
"""

import cProfile
import io
import os
import pstats
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402  (repo-root bench.py: corpus + vocab helpers)


def main():
    target_mb = float(sys.argv[1]) if len(sys.argv) > 1 else 24.0
    tmp = tempfile.mkdtemp(prefix="lddl_prof_")
    try:
        from lddl_tpu.preprocess import (
            BertPretrainConfig, build_wordpiece_vocab, get_tokenizer,
            run_bert_preprocess)

        corpus = os.path.join(tmp, "corpus")
        nbytes, _ = bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 1_500_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=30522)
        tokenizer = get_tokenizer(vocab_file=vocab)

        # Warmup (native build, tokenizer tables) outside the profile.
        warm = os.path.join(tmp, "warm")
        bench.make_corpus(warm, 1, seed=2)
        run_bert_preprocess(
            {"wikipedia": warm}, os.path.join(tmp, "out_warm"), tokenizer,
            config=BertPretrainConfig(max_seq_length=128, duplicate_factor=1,
                                      masking=True, engine="numpy",
                                      tokenizer_engine="auto"),
            num_blocks=8, sample_ratio=1.0, seed=12345, bin_size=32,
            num_workers=1)

        prof = cProfile.Profile()
        prof.enable()
        run_bert_preprocess(
            {"wikipedia": corpus}, os.path.join(tmp, "out_main"), tokenizer,
            config=BertPretrainConfig(max_seq_length=128, duplicate_factor=1,
                                      masking=True, engine="numpy",
                                      tokenizer_engine="auto"),
            num_blocks=8, sample_ratio=1.0, seed=12345, bin_size=32,
            num_workers=1)
        prof.disable()

        buf = io.StringIO()
        st = pstats.Stats(prof, stream=buf)
        st.sort_stats("cumulative").print_stats(40)
        st.sort_stats("tottime").print_stats(30)
        print(buf.getvalue())
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
