"""CI smoke for the loader shard-I/O pipeline: one tiny dataset built
on the MockObjectStore, then streamed through the real BERT loader
three times — synchronous baseline (``LDDL_TPU_LOADER_PREFETCH_SHARDS=0``
``LDDL_TPU_LOADER_CACHE_BYTES=0``), prefetch+cache cold, and
prefetch+cache warm (second pass over the same shared cache) — with
per-op store latency injected so the pipeline actually has something
to hide.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_BENCH=1``. The
byte-identity half is GATING: prefetch depth and cache budget are
*scheduling* knobs and must never change a single delivered tensor
byte (the invariant tests/test_shardcache.py pins per-layer; this
smoke pins it across the assembled loader). The wall times / speedup
are informational only — a 1-core CI box and a 10 ms injected latency
are not the headline measurement (that is LOADER_BENCH.json's
``cache_prefetch_speedup`` block). Prints one JSON line::

    {"identical": true, "samples": n, "shards": N, "latency_ms": ...,
     "wall_s": {"sync": ..., "prefetch_cold": ..., "prefetch_warm": ...},
     "speedup_cold": ..., "speedup_warm": ...}
"""

import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402

_SHARDS = 8


def _load_once(bal_dir, vocab):
    """One full pass through the real loader; returns
    (n_samples, digest-of-batch-tensors, wall_s). Identity is checked
    on decoded tensors — the bytes training would consume — not on
    shard files."""
    from lddl_tpu.loader import get_bert_pretrain_data_loader

    loader = get_bert_pretrain_data_loader(
        bal_dir, vocab_file=vocab, batch_size=8, num_workers=0)
    h = hashlib.sha256()
    n = 0
    t0 = time.perf_counter()
    for batch in loader:
        for key in sorted(batch):
            h.update(key.encode())
            h.update(bytes(memoryview(batch[key]).cast("B")))
        n += int(batch["input_ids"].shape[0])
    return n, h.hexdigest(), time.perf_counter() - t0


def _leg(bal_dir, vocab, prefetch_env):
    """Run one loader leg with the given pipeline env overrides applied
    for the duration of the pass only."""
    saved = {}
    for key, value in prefetch_env.items():
        saved[key] = os.environ.pop(key, None)
        if value is not None:
            os.environ[key] = value
    try:
        return _load_once(bal_dir, vocab)
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


def main():
    target_mb = float(os.environ.get("LDDL_TPU_CACHE_SMOKE_MB", "0.5"))
    latency_ms = float(os.environ.get("LDDL_TPU_CACHE_SMOKE_LATENCY_MS",
                                      "10"))
    tmp = tempfile.mkdtemp(prefix="lddl_cache_smoke_")
    # Both knobs must be pinned BEFORE the first touch of the store:
    # backend instances are cached per process and the mock store reads
    # its latency once at construction.
    os.environ["LDDL_TPU_STORAGE_BACKEND"] = "mock"
    os.environ["LDDL_TPU_MOCK_LATENCY_MS"] = str(latency_ms)
    try:
        from lddl_tpu.balance import balance_shards
        from lddl_tpu.preprocess import (BertPretrainConfig,
                                         build_wordpiece_vocab,
                                         get_tokenizer,
                                         run_bert_preprocess)
        from lddl_tpu.utils.cpus import usable_cpu_count

        corpus = os.path.join(tmp, "corpus")
        bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 300_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=8000)

        pre = os.path.join(tmp, "pre")
        bal = os.path.join(tmp, "bal")
        run_bert_preprocess(
            {"wikipedia": corpus}, pre, get_tokenizer(vocab_file=vocab),
            config=BertPretrainConfig(max_seq_length=128,
                                      duplicate_factor=1, masking=True,
                                      schema_version=2),
            num_blocks=_SHARDS, seed=7, bin_size=None,
            num_workers=usable_cpu_count())
        balance_shards(pre, bal, _SHARDS)

        n_sync, d_sync, t_sync = _leg(
            bal, vocab, {"LDDL_TPU_LOADER_PREFETCH_SHARDS": "0",
                         "LDDL_TPU_LOADER_CACHE_BYTES": "0"})
        n_cold, d_cold, t_cold = _leg(
            bal, vocab, {"LDDL_TPU_LOADER_PREFETCH_SHARDS": None,
                         "LDDL_TPU_LOADER_CACHE_BYTES": None})
        # Same env, same process: the shared shard cache built during
        # the cold pass is still resident — this IS the warm epoch.
        n_warm, d_warm, t_warm = _leg(
            bal, vocab, {"LDDL_TPU_LOADER_PREFETCH_SHARDS": None,
                         "LDDL_TPU_LOADER_CACHE_BYTES": None})

        report = {
            "identical": (n_sync > 0 and n_sync == n_cold == n_warm
                          and d_sync == d_cold == d_warm),
            "samples": n_sync,
            "shards": _SHARDS,
            "latency_ms": latency_ms,
            "wall_s": {"sync": round(t_sync, 2),
                       "prefetch_cold": round(t_cold, 2),
                       "prefetch_warm": round(t_warm, 2)},
            "speedup_cold": round(t_sync / max(t_cold, 1e-9), 2),
            "speedup_warm": round(t_sync / max(t_warm, 1e-9), 2),
        }
        print(json.dumps(report, sort_keys=True))
        if not report["identical"]:
            print("cache smoke: prefetch/cache changed delivered bytes "
                  "(sync {} cold {} warm {})".format(d_sync, d_cold,
                                                     d_warm),
                  file=sys.stderr)
            return 1
        return 0
    finally:
        os.environ.pop("LDDL_TPU_STORAGE_BACKEND", None)
        os.environ.pop("LDDL_TPU_MOCK_LATENCY_MS", None)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
