"""CI smoke pair for the async durable sink: serial vs async on one small
corpus — byte identity asserted, throughput pair reported.

Run by ``tools/ci_check.sh`` under ``LDDL_TPU_CI_SMOKE_BENCH=1`` (non-
gating for the timing, but the byte-identity assertion is real: a smoke
that shipped different bytes would be a correctness alarm, so it exits
nonzero). Prints one JSON line::

    {"serial_mb_per_s": ..., "async_mb_per_s": ..., "identical": true,
     "sink": {writer_write_s, producer_stall_s, ...}}

Timing caveat: a 4 MB corpus on a busy CI box is weather, not signal —
the committed PROFILE_PREPROCESS.json / BENCH_r*.json artifacts are the
measurements of record; this pair exists so a sink regression (async
slower than serial by a wide margin, or bytes diverging) is visible per
commit.
"""

import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
import bench  # noqa: E402


def _tree_digest(out_dir):
    h = hashlib.sha256()
    for root, dirs, files in sorted(os.walk(out_dir)):
        dirs.sort()
        for name in sorted(files):
            h.update(name.encode())
            with open(os.path.join(root, name), "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def main():
    target_mb = float(os.environ.get("LDDL_TPU_SINK_SMOKE_MB", "4"))
    tmp = tempfile.mkdtemp(prefix="lddl_sink_smoke_")
    try:
        from lddl_tpu.preprocess import (
            BertPretrainConfig, build_wordpiece_vocab, get_tokenizer,
            run_bert_preprocess)
        from lddl_tpu.preprocess import sink as sink_mod

        corpus = os.path.join(tmp, "corpus")
        nbytes, _ = bench.make_corpus(corpus, target_mb, seed=0)
        sample = []
        sample_bytes = 0
        with open(os.path.join(corpus, "source", "0.txt"),
                  encoding="utf-8") as f:
            for line in f:
                sample.append(line.split(None, 1)[1])
                sample_bytes += len(line)
                if sample_bytes > 500_000:
                    break
        vocab = build_wordpiece_vocab(
            sample, os.path.join(tmp, "vocab.txt"), vocab_size=8000)
        tokenizer = get_tokenizer(vocab_file=vocab)

        def run(name, depth):
            os.environ["LDDL_TPU_SINK_DEPTH"] = str(depth)
            try:
                out = os.path.join(tmp, name)
                t0 = time.perf_counter()
                run_bert_preprocess(
                    {"wikipedia": corpus}, out, tokenizer,
                    config=BertPretrainConfig(max_seq_length=128,
                                              duplicate_factor=1,
                                              masking=True),
                    num_blocks=8, sample_ratio=1.0, seed=12345,
                    bin_size=32, num_workers=1)
                elapsed = time.perf_counter() - t0
            finally:
                del os.environ["LDDL_TPU_SINK_DEPTH"]
            return nbytes / 1024 / 1024 / elapsed, _tree_digest(out)

        # Warm once (native build, tokenizer tables) so the pair compares
        # sink modes, not one-time costs.
        run("warm", 0)
        before = sink_mod.stats_snapshot()
        serial_mb_s, serial_digest = run("serial", 0)
        async_mb_s, async_digest = run("async", 2)
        after = sink_mod.stats_snapshot()
        identical = serial_digest == async_digest
        print(json.dumps({
            "smoke": "async-sink serial-vs-async pair",
            "corpus_mb": round(nbytes / 1024 / 1024, 2),
            "serial_mb_per_s": round(serial_mb_s, 3),
            "async_mb_per_s": round(async_mb_s, 3),
            "identical": identical,
            "sink": {k: round(after[k] - before[k], 3)
                     for k in ("write_s", "stall_s", "tasks", "units")},
        }))
        return 0 if identical else 1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
