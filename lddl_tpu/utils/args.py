"""argparse helpers shared by all CLIs.

Reference parity: lddl/utils.py:81-95 (attach_bool_arg),
lddl/download/utils.py:42-51 (parse_str_of_num_bytes).
"""

import argparse


def attach_bool_arg(parser, flag_name, default=False, help_str=None):
    """Attach paired ``--x / --no-x`` boolean flags."""
    attr_name = flag_name.replace("-", "_")
    group = parser.add_mutually_exclusive_group()
    help_str = help_str if help_str is not None else flag_name
    group.add_argument(
        "--" + flag_name,
        dest=attr_name,
        action="store_true",
        help=help_str + " (default: {})".format(default),
    )
    group.add_argument(
        "--no-" + flag_name,
        dest=attr_name,
        action="store_false",
        help="disable: " + help_str,
    )
    parser.set_defaults(**{attr_name: default})


def parse_str_of_num_bytes(s, return_str=False):
    """'512M'/'4G'/'128K'/plain int -> byte count."""
    try:
        power = "kmg".find(s[-1].lower()) + 1
        size = float(s[:-1]) * 1024**power if power > 0 else float(s)
    except (ValueError, IndexError):
        raise argparse.ArgumentTypeError("Invalid size: {}".format(s))
    if size < 0:
        raise argparse.ArgumentTypeError("Size must be non-negative: {}".format(s))
    if return_str:
        return s
    return int(size)
