"""Deterministic, counter-based RNG streams.

Reference parity: lddl/random.py:28-55. The reference threads CPython
``random``-module state blobs through pure functions so many independent
deterministic streams can share one global generator. We instead give every
scope its *own* counter-based ``numpy`` Philox generator, keyed by
``(base_seed, epoch, scope...)`` — the same determinism contract
(streams are independent, reproducible, and resumable by re-seeding from
``base_seed + epoch``) without mutable global state. Counter-based keying is
also what ``jax.random`` uses on-device, so host and device streams follow
one mental model.

RNG contract (frozen; tests/test_rng.py pins golden values):

- ``world_rng(seed, epoch)``: one stream shared by ALL processes. Every
  rank draws identical values — this is what makes the epoch-global file
  shuffle and the per-iteration bin choice communication-free.
  (ref: lddl/torch/datasets.py:247-249, lddl/torch/dataloader.py:44-50)
- ``worker_rng(seed, epoch, dp_rank, num_dp_groups, worker, num_workers)``:
  one stream per (dp_rank, worker). All ranks inside one data-parallel
  group (i.e. tensor/pipeline-parallel peers) share a stream, so they
  produce identical batches. (ref: lddl/torch_mp/datasets.py:257-260)
"""

import hashlib
import struct

import numpy as np

# Domain-separation tags so world/worker/other streams can never collide
# even with identical numeric parameters.
_WORLD_TAG = 0x1DD1_0001
_WORKER_TAG = 0x1DD1_0002
_SAMPLE_TAG = 0x1DD1_0003


def _key_bytes(*scope):
    # Philox is counter-based: a 128-bit key fully determines the stream.
    # Fold the scope tuple into the key with blake2b — stable bytes across
    # numpy/python versions, collision-resistant across scopes.
    return hashlib.blake2b(
        struct.pack("<{}Q".format(len(scope)), *(int(s) % 2**64 for s in scope)),
        digest_size=16).digest()


def _generator(*scope):
    key = np.frombuffer(_key_bytes(*scope), dtype=np.uint64)
    return np.random.Generator(np.random.Philox(key=key))


def world_rng(base_seed, epoch):
    """Stream identical on every process for (base_seed, epoch)."""
    return _generator(_WORLD_TAG, np.uint64(base_seed), np.uint64(epoch), 0)


def worker_rng(base_seed, epoch, dp_rank, num_dp_groups, worker, num_workers):
    """Stream per (epoch, dp_rank, worker); shared by all TP/PP peers of a
    data-parallel group. Mirrors the reference seed layout
    ``base_seed + (epoch * num_dp + dp_rank) * workers + worker``
    (lddl/torch_mp/datasets.py:257-260) but with collision-free keying."""
    if not (0 <= dp_rank < num_dp_groups):
        raise ValueError("dp_rank {} out of range [0, {})".format(dp_rank, num_dp_groups))
    if not (0 <= worker < num_workers):
        raise ValueError("worker {} out of range [0, {})".format(worker, num_workers))
    return _generator(
        _WORKER_TAG,
        np.uint64(base_seed),
        np.uint64(epoch),
        np.uint64(dp_rank) << np.uint64(32) | np.uint64(worker),
    )


def sample_rng(base_seed, *scope):
    """A one-off stream for preprocessing scopes (e.g. one per input block),
    keyed by arbitrary non-negative ints."""
    key = [_SAMPLE_TAG, np.uint64(base_seed)]
    for s in scope:
        key.append(np.uint64(s))
    return _generator(*key)


def sample_key_bytes(base_seed, *scope):
    """The 16-byte Philox key of ``sample_rng(base_seed, *scope)``'s
    stream — what the native engine needs to REPLAY that exact stream in
    C++ (lddl_tpu.native.mask_batch). Frozen alongside the stream layout;
    tests pin Generator(Philox(key=sample_key_bytes(...))) ==
    sample_rng(...) draw-for-draw."""
    key = [_SAMPLE_TAG, np.uint64(base_seed)]
    for s in scope:
        key.append(np.uint64(s))
    return _key_bytes(*key)


def shuffle(rng, seq):
    """In-place shuffle of a list using ``rng``.

    Vectorized (C-speed) yet version-stable: the permutation is the stable
    argsort of one batch of raw uniform draws. Philox's raw double stream
    is bit-stable across numpy releases, unlike ``Generator.permutation``
    internals (NEP 19), so shard contents stay reproducible across
    environments. Stream contract: one ``random(len(seq))`` draw per call.
    """
    perm = np.argsort(rng.random(len(seq)), kind="stable")
    if hasattr(seq, "take_"):
        # Zero-copy span views (readers.DocSpans) permute their offset
        # arrays in place — same single-draw stream contract, no per-doc
        # Python objects.
        seq.take_(perm)
        return seq
    seq[:] = [seq[i] for i in perm]
    return seq


# ---------------------------------------------------------------------------
# Cross-engine counter RNG (SplitMix64 contract).
#
# Pair creation runs in either the Python engine or the native C++ engine;
# both must emit bit-identical samples. numpy Generator internals are not
# reproducible from C++, so the pair-creation randomness is FROZEN as this
# counter-based SplitMix64 scheme (documented here, mirrored in
# lddl_tpu/native/lddl_native.cpp, pinned by tests/test_rng.py goldens):
#
#   key      = fold(parts): k := mix64(k + p_i) starting from 0xA0761D6478BD642F
#   draw(i)  = mix64(key + (i+1) * 0x9E3779B97F4A7C15),  i = 0, 1, 2, ...
#   uniform  = (draw >> 11) * 2^-53                      in [0, 1)
#   randint(lo, hi) = lo + draw % (hi - lo)              (frozen incl. the
#                                                         negligible mod bias)
#   shuffle perm(n) = stable argsort of [uniform(0..n-1)]
# ---------------------------------------------------------------------------

_MASK64 = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15
_KEY_INIT = 0xA0761D6478BD642F


def mix64(z):
    """SplitMix64 finalizer (Steele et al.) on a 64-bit int."""
    z &= _MASK64
    z ^= z >> 30
    z = (z * 0xBF58476D1CE4E5B9) & _MASK64
    z ^= z >> 27
    z = (z * 0x94D049BB133111EB) & _MASK64
    z ^= z >> 31
    return z


def stream_key(*parts):
    """Fold integer scope parts into a 64-bit stream key."""
    k = _KEY_INIT
    for p in parts:
        k = mix64((k + (int(p) & _MASK64)) & _MASK64)
    return k


class CounterRNG:
    """Sequential draws from one SplitMix64 stream (the frozen contract
    above). Scalar and pure-Python by design: this is the reference
    implementation the native engine must match draw-for-draw."""

    __slots__ = ("key", "i")

    def __init__(self, *parts):
        self.key = stream_key(*parts)
        self.i = 0

    def next_u64(self):
        self.i += 1
        return mix64((self.key + self.i * _GOLDEN) & _MASK64)

    def uniform(self):
        return (self.next_u64() >> 11) * (2.0 ** -53)

    def randint(self, lo, hi):
        """One draw in [lo, hi) — hi exclusive, hi > lo."""
        return lo + self.next_u64() % (hi - lo)


def stable_shuffle_perm(n, *parts):
    """Permutation of range(n): stable argsort of the stream's first n
    uniforms. Vectorized (uint64 numpy ops are bit-exact vs the scalar
    contract); the C++ engine mirrors it with std::stable_sort."""
    key = np.uint64(stream_key(*parts))
    idx = np.arange(1, n + 1, dtype=np.uint64)
    z = key + idx * np.uint64(_GOLDEN)
    z ^= z >> np.uint64(30)
    z *= np.uint64(0xBF58476D1CE4E5B9)
    z ^= z >> np.uint64(27)
    z *= np.uint64(0x94D049BB133111EB)
    z ^= z >> np.uint64(31)
    u = (z >> np.uint64(11)).astype(np.float64) * (2.0 ** -53)
    return np.argsort(u, kind="stable")


def choices(rng, population, weights, k=1):
    """Weighted sampling with replacement (like random.choices)."""
    w = np.asarray(weights, dtype=np.float64)
    total = w.sum()
    if total <= 0:
        raise ValueError("weights must sum to a positive value")
    p = w / total
    idx = rng.choice(len(population), size=k, replace=True, p=p)
    return [population[int(i)] for i in idx]
