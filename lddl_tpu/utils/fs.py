"""Filesystem, parquet, and shard-naming helpers.

Reference parity: lddl/utils.py (mkdir:32, expand_outdir_and_mkdir:36,
get_all_files_paths_under:42, get_all_parquets_under:47, get_all_bin_ids:54,
get_file_paths_for_bin_id:70, get_num_samples_of_parquet:77,
serialize_np_array:98, deserialize_np_array:105).

The bin-id filename protocol is load-bearing across three stages
(preprocessor -> balancer -> loader): a shard that belongs to sequence-length
bin ``k`` carries the *extension* ``.parquet_<k>``; bin ids must be contiguous
from 0. This module is the single owner of that protocol.
"""

import io
import json
import os
import re

import numpy as np
import pyarrow.parquet as pq

from ..resilience import faults
from ..resilience.io import atomic_write, with_retries

# Name of the per-directory sample-count cache written by the balancer and
# consumed by the loader so startup does not need to touch every footer.
# (ref: lddl/dask/load_balance.py:372-378, lddl/torch/datasets.py:166-187)
NUM_SAMPLES_CACHE_NAME = ".num_samples.json"

# Reserved key inside .num_samples.json holding {basename: byte_length}
# for per-entry staleness checks on growing (multi-generation) shard
# directories. Never a parquet basename (leading underscore-dunder), so
# count consumers that iterate the cache skip it by path lookup.
NUM_SAMPLES_SIZES_KEY = "__sizes__"

# Streaming-ingestion generation subdirectories: the root directory holds
# generation 0's shards; each incremental ingest publishes its tail into
# gen-<NNNN>/ so prior generations' bytes are never rewritten.
GENERATION_DIR_RE = re.compile(r"^gen-(\d{4,})$")


def mkdir(d):
    os.makedirs(d, exist_ok=True)


def expand_outdir_and_mkdir(outdir):
    outdir = os.path.abspath(os.path.expanduser(outdir))
    mkdir(outdir)
    return outdir


def get_all_files_paths_under(root):
    """All file paths (recursively) under ``root``, sorted for determinism.

    Hidden directories (any path component starting with ``.``) are
    skipped: the streaming-ingestion service keeps its journal, staging
    corpora, and in-flight preprocess scratch under ``<root>/.ingest/``,
    and those part files must never be mistaken for published shards."""
    out = []
    # Walk order is unobservable: results accumulate into one list that
    # is sorted before returning. -- lddl: disable=unsorted-iteration
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if not d.startswith(".")]
        out.extend(os.path.join(dirpath, f) for f in filenames)
    return sorted(out)


def _is_parquet_path(path):
    name = os.path.basename(path)
    if name.startswith("."):
        return False
    ext = name.split(".")[-1]
    return ext == "parquet" or ext.startswith("parquet_")


def get_all_parquets_under(path):
    """All parquet shards (binned or not) under ``path``."""
    return [p for p in get_all_files_paths_under(path) if _is_parquet_path(p)]


def get_bin_id_of_path(path):
    """Bin id encoded in the file extension, or None for unbinned shards."""
    ext = os.path.basename(path).split(".")[-1]
    if ext.startswith("parquet_"):
        suffix = ext[len("parquet_"):]
        if suffix.isdigit():
            return int(suffix)
    return None

def get_all_bin_ids(file_paths):
    """The sorted set of bin ids present; asserts they are contiguous from 0.

    Contiguity is a pipeline invariant: the loader sizes its per-bin
    dataloader list by ``max_bin_id + 1`` and the synchronized bin chooser
    indexes into it. (ref: lddl/utils.py:54-67)
    """
    bin_ids = sorted({
        b for b in (get_bin_id_of_path(p) for p in file_paths) if b is not None
    })
    for expected, actual in enumerate(bin_ids):
        if expected != actual:
            raise ValueError(
                "bin ids must be contiguous from 0; found {}".format(bin_ids))
    return bin_ids


def get_file_paths_for_bin_id(file_paths, bin_id):
    return [p for p in file_paths if get_bin_id_of_path(p) == bin_id]


def generation_dir_name(generation):
    """Directory name of one ingest generation's shards under the dataset
    root. Generation 0 is the root itself (classic balanced layout), so
    only generations >= 1 get a subdirectory."""
    if generation < 1:
        raise ValueError(
            "generation 0 lives in the dataset root, not a subdirectory")
    return "gen-{:04d}".format(generation)


def get_generation_of_path(root, path):
    """Which ingest generation a shard path belongs to: N for paths under
    ``<root>/gen-<NNNN>/``, 0 for shards directly in the root."""
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    head = rel.split(os.sep, 1)[0]
    m = GENERATION_DIR_RE.match(head)
    return int(m.group(1)) if m else 0


def read_footer_metadata(path):
    """Parquet ``FileMetaData`` via footer-first ranged reads through
    the active storage backend: an 8-byte tail probe (footer length +
    magic), then the footer itself — on an object store, metadata
    consumers (num_samples census, packed-shape sniff) never fetch full
    shards. Retries happen inside read_range; implausible footer shapes
    raise RuntimeError (callers wrap or treat as unreadable)."""
    import pyarrow as pa

    from ..resilience.io import object_head, read_range
    size, _ = object_head(path)
    if size is None:
        raise FileNotFoundError(path)
    if size < 12:
        raise RuntimeError(
            "parquet shard implausibly small ({} byte(s))".format(size))
    tail = read_range(path, size - 8, 8)
    if len(tail) != 8 or tail[4:8] != b"PAR1":
        raise RuntimeError("bad parquet footer magic")
    footer_len = int.from_bytes(tail[:4], "little")
    if footer_len <= 0 or footer_len + 8 > size:
        raise RuntimeError(
            "implausible parquet footer length {}".format(footer_len))
    foot = read_range(path, size - 8 - footer_len, footer_len + 8)
    return pq.read_metadata(pa.BufferReader(foot))


def get_num_samples_of_parquet(path):
    """Number of rows in a parquet shard, from metadata (no data read —
    footer-first ranged reads when a non-local storage backend is
    active, so the census never fetches full objects).

    Transient storage errors retry (resilience.io); a corrupt/truncated
    footer raises a ValueError that NAMES the shard instead of a bare
    pyarrow error with no path in it."""

    def _read():
        faults.fault_point("open", path)
        if faults.fault_point("read", path) == "truncate":
            # Falls into the named-ValueError wrap below, like a real
            # torn footer would.
            raise RuntimeError("injected truncated footer read")
        from ..resilience.io import backend_if_nonlocal
        if backend_if_nonlocal() is not None:
            return read_footer_metadata(path).num_rows
        return pq.ParquetFile(path).metadata.num_rows

    try:
        return with_retries(_read, desc="parquet footer {}".format(path))
    except OSError:
        raise
    except Exception as e:
        raise ValueError(
            "corrupt or truncated parquet shard {}: {}: {}".format(
                path, type(e).__name__, e)) from e


def read_num_samples_cache(dir_path):
    """Load the .num_samples.json cache ({basename: count}) if present.
    A corrupt/torn cache reads as absent (the caller recomputes) rather
    than crashing startup."""
    cache_path = os.path.join(dir_path, NUM_SAMPLES_CACHE_NAME)
    if os.path.isfile(cache_path):
        try:
            with open(cache_path, "r") as f:
                cache = json.load(f)
        except (OSError, ValueError):
            return None
        return cache if isinstance(cache, dict) else None
    return None


def num_samples_cache_is_stale(dir_path, cache):
    """True when the cache's key set differs from the parquet shard
    basenames actually on disk: a crash window or a partial re-balance can
    durably publish a cache describing a different shard set, and trusting
    it would silently mis-count an epoch. Stale caches are recomputed."""
    if cache is None:
        return True
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return True
    on_disk = {n for n in names if _is_parquet_path(n)}
    return {k for k in cache if k != NUM_SAMPLES_SIZES_KEY} != on_disk


def trusted_num_samples_entries(dir_path, cache):
    """Split one directory's cache into (trusted {basename: count},
    untrusted set-of-basenames-on-disk).

    Legacy caches (no ``__sizes__`` map) keep the all-or-nothing contract:
    a key-set mismatch distrusts the whole cache. Sized caches (written by
    the ingest service) validate **per entry** — an entry is trusted iff
    its recorded byte length matches the file on disk — so appending a
    generation or flushing a tail invalidates only the shards that
    actually changed instead of forcing a full directory re-count."""
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return {}, set()
    on_disk = [n for n in names if _is_parquet_path(n)]
    if cache is None:
        return {}, set(on_disk)
    sizes = cache.get(NUM_SAMPLES_SIZES_KEY)
    if not isinstance(sizes, dict):
        if num_samples_cache_is_stale(dir_path, cache):
            return {}, set(on_disk)
        return dict(cache), set()
    trusted, untrusted = {}, set()
    for name in on_disk:
        entry_ok = False
        if name in cache and name in sizes:
            try:
                entry_ok = os.path.getsize(
                    os.path.join(dir_path, name)) == sizes[name]
            except OSError:
                entry_ok = False
        if entry_ok:
            trusted[name] = cache[name]
        else:
            untrusted.add(name)
    return trusted, untrusted


def write_num_samples_cache(dir_path, counts, with_sizes=False):
    """Store {basename: count} next to the shards. Durable AND atomic
    (resilience.io.atomic_write): the old tmp+rename path skipped fsync,
    so a crash shortly after could durably publish an EMPTY cache file.

    ``with_sizes=True`` (the ingest service's mode) additionally records
    each shard's byte length under the reserved ``__sizes__`` key so
    growing directories can be validated per entry (see
    ``trusted_num_samples_entries``)."""
    cache_path = os.path.join(dir_path, NUM_SAMPLES_CACHE_NAME)
    payload = dict(counts)
    if with_sizes:
        sizes = {}
        for name in sorted(counts):
            try:
                sizes[name] = os.path.getsize(os.path.join(dir_path, name))
            # A racing unlink just leaves the entry size-less: it then
            # reads as untrusted and is recounted from its footer.
            except OSError:  # lddl: disable=swallowed-error
                pass
        payload[NUM_SAMPLES_SIZES_KEY] = sizes
    atomic_write(cache_path, json.dumps(payload, sort_keys=True))


def serialize_np_array(a):
    """numpy 1-D array -> bytes, for storing arrays in parquet columns.

    Used for static-masking outputs (masked positions / labels) which are
    ragged per-row int arrays. (ref: lddl/utils.py:98-106 — which uses the
    .npy container; that costs a ~128-byte header plus Python-side header
    formatting per row, so we use a 4-byte tag + raw little-endian payload
    instead and keep an .npy-compatible read path for old shards.)
    """
    a = np.ascontiguousarray(a)
    code = a.dtype.str.encode()  # e.g. b'<u2'
    if len(code) != 3 or a.ndim != 1:
        buf = io.BytesIO()  # rare shapes/dtypes: fall back to .npy
        np.save(buf, a, allow_pickle=False)
        return buf.getvalue()
    return b"R" + code + a.tobytes()


def deserialize_np_array(b):
    if b[:1] == b"R":
        if len(b) < 4:
            raise ValueError(
                "truncated array payload: {} byte(s) with 'R' tag, need at "
                "least 4 (1-byte tag + 3-byte dtype code)".format(len(b)))
        try:
            dtype = np.dtype(b[1:4].decode())
        except (TypeError, UnicodeDecodeError) as e:
            raise ValueError(
                "corrupt array payload: 'R' tag with invalid dtype code "
                "{!r} ({} bytes total)".format(bytes(b[1:4]), len(b))) from e
        if (len(b) - 4) % dtype.itemsize:
            raise ValueError(
                "truncated array payload: {} data byte(s) after the "
                "'R{}' tag is not a multiple of itemsize {}".format(
                    len(b) - 4, dtype.str, dtype.itemsize))
        return np.frombuffer(b, dtype=dtype, offset=4)
    if not bytes(b[:6]) == b"\x93NUMPY":
        # Empty or torn bytes would otherwise fall through to np.load and
        # raise an opaque "Failed to interpret file as a pickle" error.
        raise ValueError(
            "array payload of {} byte(s) has neither the 'R' raw tag nor "
            "the .npy magic; the shard bytes are likely truncated or "
            "corrupt".format(len(b)))
    return np.load(io.BytesIO(b), allow_pickle=False)
