from .types import File
from .fs import (
    mkdir,
    expand_outdir_and_mkdir,
    get_all_files_paths_under,
    get_all_parquets_under,
    get_all_bin_ids,
    get_file_paths_for_bin_id,
    get_num_samples_of_parquet,
    serialize_np_array,
    deserialize_np_array,
    NUM_SAMPLES_CACHE_NAME,
)
from .args import attach_bool_arg, parse_str_of_num_bytes
from .cpus import usable_cpu_count
from . import rng

__all__ = [
    "File",
    "mkdir",
    "expand_outdir_and_mkdir",
    "get_all_files_paths_under",
    "get_all_parquets_under",
    "get_all_bin_ids",
    "get_file_paths_for_bin_id",
    "get_num_samples_of_parquet",
    "serialize_np_array",
    "deserialize_np_array",
    "NUM_SAMPLES_CACHE_NAME",
    "attach_bool_arg",
    "parse_str_of_num_bytes",
    "usable_cpu_count",
    "rng",
]
