"""Value types shared across pipeline stages.

Reference parity: lddl/types.py:26-33 (class File).
"""

import dataclasses


@dataclasses.dataclass
class File:
    """A data shard on disk together with its sample count.

    The currency of the load balancer and the datasets: every stage that
    needs to reason about "how many samples live where" passes these around
    instead of re-reading parquet footers.
    """

    path: str
    num_samples: int
