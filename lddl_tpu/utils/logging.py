"""Scoped dataset logging.

Reference parity: lddl/torch/log.py:40-133 (DummyLogger, DatasetLogger).
A DatasetLogger hands out real loggers only on the process/worker responsible
for a given scope ('node' -> node-rank 0 & worker 0, 'rank' -> worker 0,
'worker' -> everyone), so multi-host multi-worker runs do not multiply log
lines. Optionally writes one file per scope under ``log_dir``.
"""

import logging
import os
import pathlib


class DummyLogger:
    def debug(self, *args, **kwargs):
        pass

    def info(self, *args, **kwargs):
        pass

    def warning(self, *args, **kwargs):
        pass

    def error(self, *args, **kwargs):
        pass

    def critical(self, *args, **kwargs):
        pass

    def exception(self, *args, **kwargs):
        pass

    def log(self, *args, **kwargs):
        pass


class DatasetLogger:

    def __init__(
        self,
        log_dir=None,
        log_level=logging.INFO,
        rank=0,
        local_rank=0,
        node_rank=None,
        worker_rank=0,
    ):
        if node_rank is None:
            # Real host identity by default (side-effect-free; 0 when
            # jax.distributed is not initialized) — every construction
            # site gets correct 'node:' scoping without plumbing.
            from ..parallel.distributed import node_info
            node_rank, _ = node_info()
        self._log_dir = log_dir
        self._log_level = log_level
        self._rank = rank
        self._local_rank = local_rank
        self._node_rank = node_rank
        self._worker_rank = worker_rank
        if log_dir is not None:
            pathlib.Path(log_dir).mkdir(parents=True, exist_ok=True)
        self._loggers = {}

    def __getstate__(self):
        # logging.Logger objects don't pickle (process-mode loader workers
        # ship the dataset, which carries this); they rebuild lazily.
        state = self.__dict__.copy()
        state["_loggers"] = {}
        return state

    @property
    def rank(self):
        return self._rank

    @property
    def worker_rank(self):
        return self._worker_rank

    def _build_logger(self, scope):
        name = "lddl_tpu.{}.rank{}.worker{}".format(
            scope, self._rank, self._worker_rank)
        logger = logging.getLogger(name)
        logger.setLevel(self._log_level)
        logger.propagate = False
        fmt = logging.Formatter(
            "%(asctime)s - node:{} rank:{} worker:{} - %(levelname)s - "
            "%(message)s".format(self._node_rank, self._rank, self._worker_rank))
        if not logger.handlers:
            sh = logging.StreamHandler()
            sh.setFormatter(fmt)
            logger.addHandler(sh)
            if self._log_dir is not None:
                fh = logging.FileHandler(
                    os.path.join(
                        self._log_dir,
                        "{}-rank{}-worker{}.log".format(
                            scope, self._rank, self._worker_rank)))
                fh.setFormatter(fmt)
                logger.addHandler(fh)
        return logger

    def to(self, scope):
        """Return a real logger only on the process/worker owning ``scope``."""
        if scope == "node":
            responsible = (self._rank == 0 and self._local_rank == 0
                           and self._worker_rank == 0)
        elif scope == "rank":
            responsible = self._worker_rank == 0
        elif scope == "worker":
            responsible = True
        else:
            raise ValueError("unknown log scope {!r}".format(scope))
        if not responsible:
            return DummyLogger()
        if scope not in self._loggers:
            self._loggers[scope] = self._build_logger(scope)
        return self._loggers[scope]
