"""CPU-count detection that respects cgroup/affinity limits.

``os.cpu_count()`` reports the machine's cores, not this process's
allowance — inside a cgroup-limited container or after sched_setaffinity
it overcounts, so every pool/probe-plan/thread-pool sized from it
oversubscribes the host. ``usable_cpu_count()`` is the one sizing
primitive the whole tree uses instead (ISSUE 18 satellite bugfix).
"""

import os


def usable_cpu_count():
    """Number of CPUs THIS process may run on: the scheduling-affinity
    set where the platform exposes it (Linux), else ``os.cpu_count()``.
    Never returns less than 1."""
    try:
        return max(1, len(os.sched_getaffinity(0)))
    except (AttributeError, OSError):  # non-Linux / restricted proc
        return max(1, os.cpu_count() or 1)
