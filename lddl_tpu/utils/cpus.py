"""CPU-count detection that respects cgroup/affinity limits.

``os.cpu_count()`` reports the machine's cores, not this process's
allowance — inside a cgroup-limited container or after sched_setaffinity
it overcounts, so every pool/probe-plan/thread-pool sized from it
oversubscribes the host. ``usable_cpu_count()`` is the one sizing
primitive the whole tree uses instead (ISSUE 18 satellite bugfix).
"""

import os


def usable_cpu_count():
    """Number of CPUs THIS process may run on: the scheduling-affinity
    set where the platform exposes it (Linux), else ``os.cpu_count()``.
    Never returns less than 1."""
    try:
        return max(1, len(os.sched_getaffinity(0)))
    except (AttributeError, OSError):  # non-Linux / restricted proc
        return max(1, os.cpu_count() or 1)


def loader_io_threads():
    """Threads ONE loader worker stream adds for shard I/O when the
    prefetch pipeline is enabled (fetcher pool + decode-ahead — see
    loader/shardcache.py), 0 when ``LDDL_TPU_LOADER_PREFETCH_SHARDS=0``.
    Sizing call sites subtract this via :func:`pool_cpu_budget` so
    elastic workers x loader threads never oversubscribe the affinity
    mask."""
    try:
        from ..loader.shardcache import io_thread_count
    except ImportError:  # pragma: no cover - loader deps absent
        return 0
    return io_thread_count()


def pool_cpu_budget(reserve=0):
    """:func:`usable_cpu_count` minus ``reserve`` helper threads, floored
    at 1 — the base every pool derives worker/thread counts from when
    helper threads (loader shard fetch/decode-ahead) share the affinity
    mask."""
    return max(1, usable_cpu_count() - max(0, reserve))
