"""Intake journal: the durable record of which documents were ingested.

The streaming-ingestion service turns the four offline stages into a
long-lived loop: scan a landing directory (or an explicit file list),
diff it against this journal, and preprocess only the delta. The journal
is keyed by **content hash** — a document's identity is its bytes, never
its path, mtime, or position in the landing directory — so re-delivered
files, renamed files, and duplicate documents all diff to nothing.

Durability layout under ``<root>/.ingest/``::

    journal/gen-<NNNN>.json   authoritative per-generation segments:
                              one immutable, atomically-published record
                              per published generation ({"generation",
                              "fingerprint", "hashes", "carry", "docs"})
    journal.json              compaction cache of the union (fast load);
                              a torn cache degrades to re-scanning the
                              segments with a warning — never a crash,
                              and never silent trust in torn bytes
    carry/                    carryover shards (rows journaled but not
                              yet shard-visible; see balance/delta.py)
    work/gen-<NNNN>/          in-flight generation scratch (staging
                              corpus, preprocess output, balance staging)

Everything here is published through ``resilience.io.atomic_write`` and
read through retried reads, with dedicated ``journal-read`` /
``journal-publish`` fault-injection sites so the chaos harness can tear
and kill at exactly these records. Journal bytes are deterministic:
content hashes and generation numbers only — no wall clock, no pids, no
filesystem order (hash lists are sorted).
"""

import hashlib
import json
import logging
import os

from .. import observability as obs
from ..resilience import faults
from ..resilience import io as rio

INGEST_DIR = ".ingest"
JOURNAL_CACHE_NAME = "journal.json"
SEGMENT_DIR = "journal"
CARRY_DIR = "carry"
WORK_DIR = "work"
INTAKE_NAME = "intake.json"

_log = logging.getLogger("lddl_tpu.ingest.journal")


def ingest_root(root):
    return os.path.join(root, INGEST_DIR)


def segment_dir(root):
    return os.path.join(ingest_root(root), SEGMENT_DIR)


def segment_path(root, generation):
    return os.path.join(segment_dir(root),
                        "gen-{:04d}.json".format(generation))


def carry_dir(root):
    return os.path.join(ingest_root(root), CARRY_DIR)


def work_dir(root, generation):
    return os.path.join(ingest_root(root), WORK_DIR,
                        "gen-{:04d}".format(generation))


def intake_path(root, generation):
    return os.path.join(work_dir(root, generation), INTAKE_NAME)


def doc_content_hash(text):
    """Stable content identity of one document: blake2b over its raw
    bytes. The journal, staging corpus doc ids, and dedup all use this —
    no other field of a document participates in its identity."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return hashlib.blake2b(text, digest_size=16).hexdigest()


def read_record(path):
    """One journal record through the dedicated ``journal-read`` fault
    site and the retried JSON reader: returns (value, status) with status
    in {"ok", "missing", "torn"} — a truncate fault downgrades an
    otherwise-clean read to "torn", like flaky storage would."""
    action = faults.fault_point("journal-read", path)
    rec, status = rio.read_json(path)
    if action == "truncate" and status == "ok":
        return None, "torn"
    return rec, status


def publish_record(path, payload, exclusive=False):
    """Atomically publish one journal record (``journal-publish`` fault
    site). ``payload`` must already be deterministic content — every
    caller serializes with sort_keys.

    ``exclusive=True`` marks a record that must commit exactly once (the
    per-generation segment — THE ingest commit point). On the local
    backend that stays today's atomic write (ingest is single-writer by
    contract; the segment hole/torn checks guard the sequence). On a CAS
    backend (resilience/backend.py) it becomes a conditional create: a
    raced duplicate commit of IDENTICAL content is idempotent and
    absorbed, while conflicting content for the same generation refuses
    loudly instead of silently overwriting the authoritative record."""
    faults.fault_point("journal-publish", path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    data = json.dumps(payload, sort_keys=True)
    if exclusive:
        if rio.put_exclusive(path, data) == "conflict":
            current, status = rio.read_json(path)
            if status == "ok" and current == payload:
                obs.inc("ingest_journal_idempotent_commits_total")
                return
            raise ValueError(
                "conflicting concurrent commit of journal record {}: "
                "another writer already published DIFFERENT content for "
                "this generation — refusing to overwrite the "
                "authoritative segment".format(path))
        return
    rio.atomic_write(path, data)


class Journal:
    """The loaded union of all published generation segments.

    ``entries``: {doc_hash: generation}; ``generation``: latest published
    generation (-1 when nothing was ever published); ``fingerprint``: the
    processor digest every generation must match (config drift across
    generations would mix incompatible shard bytes in one directory);
    ``carry``: {bin_key: carry_file_basename} for rows journaled but not
    yet visible as shards.
    """

    def __init__(self, root, entries=None, generation=-1, fingerprint=None,
                 carry=None):
        self.root = root
        self.entries = entries or {}
        self.generation = generation
        self.fingerprint = fingerprint
        self.carry = carry or {}

    # ------------------------------------------------------------- load

    @classmethod
    def load(cls, root):
        """Load the journal: cache fast path, segment re-scan fallback.

        A torn cache (flaky storage serving half a file — the writer is
        atomic) degrades to re-scanning the per-generation segments with
        a warning, mirroring the torn-lease/torn-ledger handling: torn
        bytes are never trusted and never fatal. A torn *segment* IS
        fatal — segments are the ground truth, and guessing at missing
        ingested-document hashes would silently re-ingest (duplicate)
        data."""
        cache_path = os.path.join(ingest_root(root), JOURNAL_CACHE_NAME)
        rec, status = read_record(cache_path)
        if status == "ok" and cls._cache_valid(rec):
            return cls(root, entries=dict(rec["entries"]),
                       generation=int(rec["generation"]),
                       fingerprint=rec.get("fingerprint"),
                       carry=dict(rec.get("carry") or {}))
        if status == "torn" or (status == "ok" and not cls._cache_valid(rec)):
            _log.warning(
                "torn/unparseable journal cache %s; re-scanning the "
                "per-generation segments (the cache is a compaction — "
                "segments are authoritative)", cache_path)
            obs.inc("ingest_journal_rescans_total")
        return cls._load_from_segments(root)

    @staticmethod
    def _cache_valid(rec):
        return (isinstance(rec, dict)
                and isinstance(rec.get("entries"), dict)
                and isinstance(rec.get("generation"), int))

    @classmethod
    def _load_from_segments(cls, root):
        seg_dir = segment_dir(root)
        journal = cls(root)
        if not os.path.isdir(seg_dir):
            return journal
        seen = set()
        for name in sorted(os.listdir(seg_dir)):
            path = os.path.join(seg_dir, name)
            rec, status = read_record(path)
            if status == "missing":
                continue
            if status == "torn" or not isinstance(rec, dict) \
                    or "generation" not in rec:
                raise ValueError(
                    "journal segment {} is torn or unparseable; segments "
                    "are the authoritative ingest record and are written "
                    "atomically, so this implicates the storage medium — "
                    "restore the file before ingesting (re-scanning would "
                    "silently duplicate already-ingested documents)".format(
                        path))
            g = int(rec["generation"])
            seen.add(g)
            for h in rec.get("hashes", ()):
                journal.entries[h] = g
            if g > journal.generation:
                journal.generation = g
                journal.fingerprint = rec.get("fingerprint")
                journal.carry = dict(rec.get("carry") or {})
        # Generations publish strictly in sequence, so the segment set
        # must be exactly 0..N. A hole means a LOST segment: its hashes
        # are absent from the union, and ingesting on top would silently
        # re-ingest (duplicate) those documents — same loud stop as a
        # torn segment.
        if seen and seen != set(range(journal.generation + 1)):
            missing = sorted(set(range(journal.generation + 1)) - seen)
            raise ValueError(
                "journal segment(s) for generation(s) {} are missing from "
                "{} (segments present: {}); the ingest sequence cannot "
                "have holes — restore the lost segment(s) before "
                "ingesting (re-scanning would silently duplicate their "
                "documents)".format(missing, seg_dir, sorted(seen)))
        return journal

    # ---------------------------------------------------------- publish

    def publish_generation(self, generation, hashes, fingerprint,
                           carry=None, doc_bytes=0):
        """Commit one generation: atomic segment publish (the commit
        point — a crash before this line leaves the generation fully
        redoable from its intake record, a crash after it leaves only
        idempotent cleanup), then recompact the cache."""
        if generation != self.generation + 1:
            raise ValueError(
                "generation {} published out of order (journal is at "
                "{})".format(generation, self.generation))
        payload = {
            "generation": generation,
            "fingerprint": fingerprint,
            "hashes": sorted(hashes),
            "carry": dict(carry or {}),
            "docs": len(hashes),
            "doc_bytes": int(doc_bytes),
        }
        publish_record(segment_path(self.root, generation), payload,
                       exclusive=True)
        for h in hashes:
            self.entries[h] = generation
        self.generation = generation
        self.fingerprint = fingerprint
        self.carry = dict(carry or {})
        self._write_cache()
        obs.inc("ingest_generations_published_total")

    def _write_cache(self):
        publish_record(
            os.path.join(ingest_root(self.root), JOURNAL_CACHE_NAME),
            {"entries": self.entries, "generation": self.generation,
             "fingerprint": self.fingerprint, "carry": self.carry})

    # ------------------------------------------------------------- work

    def next_generation(self):
        return self.generation + 1

    def pending_work(self):
        """The intake record of a crashed, not-yet-published generation
        (or None): its work dir exists with an intake.json whose
        generation is exactly journal.generation + 1. Stale work dirs of
        ALREADY-published generations (a crash between segment publish
        and cleanup) are swept here."""
        wroot = os.path.join(ingest_root(self.root), WORK_DIR)
        if not os.path.isdir(wroot):
            return None
        pending = None
        for name in sorted(os.listdir(wroot)):
            path = os.path.join(wroot, name, INTAKE_NAME)
            rec, status = read_record(path)
            if status == "torn":
                _log.warning(
                    "torn intake record %s; discarding the in-flight "
                    "generation's scratch (nothing was published, so the "
                    "delta is simply re-detected from the landing "
                    "directory)", path)
                import shutil
                shutil.rmtree(os.path.join(wroot, name), ignore_errors=True)
                continue
            if rec is None:
                continue
            g = int(rec["generation"])
            if g <= self.generation:
                import shutil  # published: only cleanup was interrupted
                shutil.rmtree(os.path.join(wroot, name), ignore_errors=True)
            elif g == self.generation + 1:
                pending = rec
            else:
                raise ValueError(
                    "work dir {} claims generation {} but the journal is "
                    "at {}; the ingest sequence cannot skip generations "
                    "— remove the stray work dir if it is debris".format(
                        os.path.join(wroot, name), g, self.generation))
        return pending


# -------------------------------------------------------------- landing scan


def iter_landing_documents(landing=None, files=None):
    """Yield (content_hash, text_bytes) for every non-empty document in
    the landing directory (downloader output contract: one document per
    line, first token is the id) or an explicit ``files`` list. Files are
    visited in sorted order, but the journal diff is order-insensitive by
    construction (identity is the content hash)."""
    from ..preprocess.readers import split_id_text
    if (landing is None) == (files is None):
        raise ValueError("give exactly one of landing= or files=")
    if files is None:
        from ..preprocess.readers import discover_source_files
        files = discover_source_files({"landing": landing})
    for path in sorted(files):
        with open(path, "rb") as f:
            for line in f:
                line = line.rstrip(b"\n")
                if not line.strip():
                    continue
                _, text = split_id_text(line)
                if not text.strip():
                    continue
                yield doc_content_hash(text), text


def diff_landing(journal, landing=None, files=None):
    """The preprocess work set: {content_hash: text_bytes} for documents
    in the landing set but not in the journal. Duplicate documents within
    one scan collapse to a single entry (content identity), counted in
    the returned stats."""
    new_docs = {}
    seen = dupes = 0
    for h, text in iter_landing_documents(landing=landing, files=files):
        seen += 1
        if h in journal.entries or h in new_docs:
            dupes += h in new_docs
            continue
        new_docs[h] = text
    return new_docs, {"docs_seen": seen, "docs_new": len(new_docs),
                      "dupes_in_scan": dupes}
