"""Streaming ingestion: incremental preprocess + delta balance as a
long-lived service over a growing corpus (see journal.py and
incremental.py for the design)."""

from .incremental import ingest_once, join_pending_generation, watch
from .journal import Journal, diff_landing, doc_content_hash

__all__ = ["Journal", "diff_landing", "doc_content_hash", "ingest_once",
           "join_pending_generation", "watch"]
