"""Incremental ingest: one journal-diffed generation through the pipeline.

``ingest_once`` is the service's unit of work: scan the landing set, diff
it against the intake journal, and — only if there is a delta — run
preprocess + delta balance over just those documents, publishing the
result as the next **generation** of the dataset:

    generation 0   classic balanced layout in the dataset root
                   (``shard-<i>.parquet[_<bin>]``) — byte-compatible with
                   the offline pipeline's output, so existing loaders and
                   tooling see nothing new
    generation N   ``gen-<NNNN>/shard-<i>.parquet[_<bin>]`` — appended
                   shards sized to the row budget generation 0 fixed
                   (see balance/delta.py); prior generations' bytes and
                   the preprocess resume fingerprints that produced them
                   are never touched

The publish sequence is ordered so that every crash point is either
redoable or idempotent, and the **journal segment publish is the single
commit point**:

    1. staging corpus written (work dir; deterministic bytes: documents
       sorted by content hash, hash as the doc id)
    2. intake record published (freezes the doc set, the prior-shard
       snapshot, and every knob that shapes bytes — a resumed generation
       replays THESE, never a fresh scan)
    3. preprocess into the work dir (the existing runner, serial or
       elastic work-stealing; crash-resumable via its unit ledger)
    4. delta balance staged + plan marker (nothing in the root mutates)
    5. staged bytes published (idempotent copies), caches + per-dir
       integrity manifests refreshed, root manifest ``__meta__`` gains
       {"generation": N, "generations": {gen: [shards]}} LAST — the
       loader's generation-pickup gate
    6. journal segment published (COMMIT), then scratch swept

A crash before 6 leaves the journal unchanged: re-running ``ingest_once``
resumes the same generation from its intake record and republishes
byte-identical output. A crash after 6 leaves only sweeping to redo.
"""

import os
import shutil

from .. import observability as obs
from ..resilience import io as rio
from ..resilience.integrity import build_manifest
from ..utils.fs import (
    _is_parquet_path,
    generation_dir_name,
    get_all_parquets_under,
    get_num_samples_of_parquet,
    read_num_samples_cache,
    trusted_num_samples_entries,
    write_num_samples_cache,
)
from ..balance import delta as delta_mod
from . import journal as journal_mod


def _snapshot_prior(root):
    """{relpath: count} of every published shard under ``root`` (all
    generations), counts from per-entry-trusted caches with footer reads
    only for untrusted entries. Sorted relpaths; pure function of the
    published state."""
    paths = get_all_parquets_under(root)
    out = {}
    by_dir = {}
    for p in paths:
        by_dir.setdefault(os.path.dirname(p), []).append(p)
    for d in sorted(by_dir):
        trusted, _ = trusted_num_samples_entries(
            d, read_num_samples_cache(d))
        for p in sorted(by_dir[d]):
            name = os.path.basename(p)
            n = trusted.get(name)
            out[os.path.relpath(p, root)] = (
                int(n) if n is not None else get_num_samples_of_parquet(p))
    return out


def _write_staging_corpus(staging_dir, new_docs):
    """The delta as a downloader-contract corpus: one document per line,
    content hash as the doc id, documents in sorted-hash order — byte
    deterministic regardless of landing-directory iteration order."""
    source = os.path.join(staging_dir, "source")
    os.makedirs(source, exist_ok=True)
    parts = []
    for h in sorted(new_docs):
        parts.append(h.encode())
        parts.append(b" ")
        parts.append(new_docs[h])
        parts.append(b"\n")
    rio.atomic_write(os.path.join(source, "0.txt"), b"".join(parts))


def _default_num_blocks(ndocs):
    return max(1, min(64, ndocs // 8 + 1))


def _generations_meta(root, latest):
    """The root manifest's ``__meta__`` extension: the monotonically
    increasing latest generation plus each generation's shard list
    (relpaths), read off the published directories in sorted order."""
    gens = {}
    for gen in range(latest + 1):
        d = root if gen == 0 else os.path.join(root,
                                               generation_dir_name(gen))
        names = []
        if os.path.isdir(d):
            names = [n for n in sorted(os.listdir(d)) if _is_parquet_path(n)]
        prefix = "" if gen == 0 else generation_dir_name(gen) + "/"
        gens[str(gen)] = [prefix + n for n in names]
    return {"generation": latest, "generations": gens}


def _refresh_dir_bookkeeping(root, dirs, latest_generation, known_counts):
    """Refresh ``.num_samples.json`` (with per-entry sizes) and the
    integrity manifest for every directory whose shards changed; the ROOT
    manifest is always refreshed LAST with the generation meta — it is
    the loader's pickup gate, so nothing newer than it is ever visible.

    ``known_counts`` ({relpath: count}, the shards this ingest round just
    published) override the cache: a rewritten shard whose new byte
    length happens to collide with the cached one must not smuggle a
    stale count through the per-entry size check."""
    ordered = sorted(d for d in dirs if os.path.abspath(d)
                     != os.path.abspath(root))
    for d in ordered + [root]:
        names = [n for n in sorted(os.listdir(d)) if _is_parquet_path(n)] \
            if os.path.isdir(d) else []
        # Recount only entries the existing cache cannot vouch for.
        trusted, _ = trusted_num_samples_entries(
            d, read_num_samples_cache(d))
        counts = {}
        for n in names:
            rel = os.path.relpath(os.path.join(d, n), root)
            if rel in known_counts:
                counts[n] = int(known_counts[rel])
            elif n in trusted:
                counts[n] = int(trusted[n])
            else:
                counts[n] = get_num_samples_of_parquet(os.path.join(d, n))
        if counts or os.path.abspath(d) == os.path.abspath(root):
            write_num_samples_cache(d, counts, with_sizes=True)
        extra = None
        if os.path.abspath(d) == os.path.abspath(root):
            extra = _generations_meta(root, latest_generation)
        build_manifest(d, extra_meta=extra)


def ingest_once(
    root,
    tokenizer,
    landing=None,
    files=None,
    config=None,
    num_shards=8,
    bin_size=None,
    seed=12345,
    num_blocks=None,
    num_workers=1,
    flush_tail=False,
    comm=None,
    log=None,
    elastic=False,
    lease_ttl=30.0,
    holder_id=None,
    scatter_units=None,
    pack_seq_length=None,
    pack_max_per_row=8,
):
    """Diff the landing set against the journal and ingest the delta as
    one generation. Returns a report dict ({"noop": True} when there is
    nothing to do). Safe to re-run after any crash: an in-flight
    generation resumes from its intake record.

    ``flush_tail=True`` folds the carryover remainder into the prior tail
    (touches the minimum set of prior shards — see balance/delta.py)
    instead of deferring it; use it in maintenance windows, not while a
    loader is streaming the directory mid-epoch.

    ``pack_seq_length`` grows packed corpora by generations: every
    delta's instances are FFD-packed against the same budget the prior
    generations fixed (the pack shape rides the processor fingerprint,
    so drift refuses like any other config drift), and carry/remainder
    semantics are untouched — carryover rows are whole packed rows.
    """
    log = log or (lambda msg: None)
    # Long-lived service: heartbeats must run even on noop rounds so the
    # fleet status report can tell "idle" from "dead" (no-op when fleet
    # telemetry is not armed).
    obs.fleet.ensure_started()
    with obs.span("ingest.run", root=root):
        return _ingest_once_body(
            root, tokenizer, landing, files, config, num_shards, bin_size,
            seed, num_blocks, num_workers, flush_tail, comm, log, elastic,
            lease_ttl, holder_id, scatter_units, pack_seq_length,
            pack_max_per_row)


def _ingest_once_body(root, tokenizer, landing, files, config, num_shards,
                      bin_size, seed, num_blocks, num_workers, flush_tail,
                      comm, log, elastic, lease_ttl, holder_id,
                      scatter_units, pack_seq_length=None,
                      pack_max_per_row=8):
    from ..preprocess.bert import BertPretrainConfig
    from ..preprocess.runner import BertBucketProcessor, run_bert_preprocess

    config = config or BertPretrainConfig()
    if config.splitter == "learned":
        raise ValueError(
            "ingest requires splitter='rules': learned splitter parameters "
            "are trained per corpus sample, so every delta would tokenize "
            "under different parameters — incompatible with a journal that "
            "promises one document ingests to one set of bytes")
    os.makedirs(root, exist_ok=True)
    journal = journal_mod.Journal.load(root)
    fingerprint = BertBucketProcessor(
        tokenizer, config, seed, root, bin_size, "parquet",
        pack_seq_length=pack_seq_length,
        pack_max_per_row=pack_max_per_row).fingerprint()
    if journal.fingerprint is not None \
            and journal.fingerprint != fingerprint:
        raise ValueError(
            "ingest configuration drift: the journal was built with "
            "processor fingerprint {} but this invocation computes {}; "
            "mixing them would put incompatible bytes in one dataset — "
            "restore the original arguments or start a fresh root".format(
                journal.fingerprint, fingerprint))

    # Adoption: a pre-existing balanced directory with no journal becomes
    # generation 0 as-is (its documents are unknown to the journal, so
    # dedup starts from this point forward).
    if journal.generation < 0 and get_all_parquets_under(root):
        log("ingest: adopting existing balanced directory as generation 0")
        # Publish the generation gate FIRST: an adopted offline manifest
        # has no __meta__.generation, and a gateless directory "follows
        # whatever is on disk" — a follow-mode loader hitting an epoch
        # boundary while generation 1's shards are mid-publish would see
        # the half-published set. Gate before journal so a crash between
        # the two re-enters this branch (journal still empty) and both
        # writes re-run idempotently; the reverse order would skip the
        # branch and leave the directory permanently gateless.
        _refresh_dir_bookkeeping(root, {root}, 0, {})
        journal.publish_generation(0, [], fingerprint)

    pending = journal.pending_work()
    if pending is not None:
        generation = int(pending["generation"])
        if pending.get("fingerprint") != fingerprint:
            raise ValueError(
                "in-flight generation {} was started with fingerprint {} "
                "but this invocation computes {}; resume with the original "
                "arguments".format(generation, pending.get("fingerprint"),
                                   fingerprint))
        intake = pending
        obs.fleet.record("generation.intake", generation=generation,
                         docs=len(intake["hashes"]), resumed=True)
        log("ingest: resuming in-flight generation {} ({} document(s) "
            "from its intake record)".format(generation,
                                             len(intake["hashes"])))
    else:
        new_docs, scan_stats = journal_mod.diff_landing(
            journal, landing=landing, files=files)
        obs.inc("ingest_docs_seen_total", scan_stats["docs_seen"])
        # Backlog = discovered-but-uncommitted documents; drops back to 0
        # at the journal commit below. The fleet wedge verdict keys on it.
        obs.set_gauge("ingest_backlog_docs", len(new_docs))
        carry_rows = _carry_row_count(root, journal)
        if not new_docs and not (flush_tail and carry_rows):
            obs.fleet.record("ingest.scan", docs_seen=scan_stats["docs_seen"],
                             docs_new=0, noop=True)
            log("ingest: no new documents ({} seen, all journaled)".format(
                scan_stats["docs_seen"]))
            return {"noop": True, "generation": journal.generation,
                    "docs_seen": scan_stats["docs_seen"],
                    "carry_rows": carry_rows}
        generation = journal.next_generation()
        wdir = journal_mod.work_dir(root, generation)
        if os.path.isdir(wdir):
            # No (valid) intake record -> the previous attempt crashed
            # before freezing its doc set; its scratch is unusable.
            shutil.rmtree(wdir)
        gen_dir = (os.path.join(root, generation_dir_name(generation))
                   if generation >= 1 else None)
        if gen_dir is not None and os.path.isdir(gen_dir):
            # Unpublished debris (the journal commits last): a fresh scan
            # may produce a different plan, so stale shards must not mix.
            shutil.rmtree(gen_dir)
        _write_staging_corpus(os.path.join(wdir, "staging"), new_docs)
        intake = {
            "generation": generation,
            "fingerprint": fingerprint,
            "hashes": sorted(new_docs),
            "doc_bytes": sum(len(t) for t in new_docs.values()),
            "prior": _snapshot_prior(root),
            "carry_in": sorted(journal.carry.values()),
            "num_shards": int(num_shards),
            "num_blocks": (int(num_blocks) if num_blocks
                           else _default_num_blocks(len(new_docs))),
            "seed": int(seed),
            "bin_size": bin_size,
            "flush": bool(flush_tail),
            "pack_seq_length": (int(pack_seq_length)
                                if pack_seq_length else None),
            "pack_max_per_row": int(pack_max_per_row),
        }
        journal_mod.publish_record(
            journal_mod.intake_path(root, generation), intake)
        obs.fleet.record("generation.intake", generation=generation,
                         docs=len(intake["hashes"]),
                         doc_bytes=intake["doc_bytes"], resumed=False)
        log("ingest: generation {}: {} new document(s) of {} seen".format(
            generation, scan_stats["docs_new"], scan_stats["docs_seen"]))

    wdir = journal_mod.work_dir(root, generation)
    staging = os.path.join(wdir, "staging")
    pre_dir = os.path.join(wdir, "pre")
    part_paths = []
    if intake["hashes"]:
        with obs.span("ingest.preprocess", generation=generation):
            run_bert_preprocess(
                {"ingest": staging},
                pre_dir,
                tokenizer,
                config=config,
                num_blocks=intake["num_blocks"],
                sample_ratio=1.0,
                seed=intake["seed"],
                bin_size=intake["bin_size"],
                global_shuffle=True,
                comm=comm,
                log=log,
                num_workers=num_workers,
                resume=os.path.isdir(pre_dir),
                elastic=elastic,
                lease_ttl=lease_ttl,
                holder_id=holder_id,
                scatter_units=scatter_units,
                emit_manifest=False,
                # A resumed generation replays its FROZEN intake record
                # (legacy records carry no pack keys: unpacked).
                pack_seq_length=intake.get("pack_seq_length"),
                pack_max_per_row=intake.get("pack_max_per_row", 8),
            )
        part_paths = get_all_parquets_under(pre_dir)
        obs.fleet.record("generation.preprocess", generation=generation,
                         shards=len(part_paths))

    stage_dir = os.path.join(wdir, "balance")
    plan = delta_mod.read_plan(stage_dir)
    if plan is None:
        if os.path.isdir(stage_dir):
            shutil.rmtree(stage_dir)  # marker-less partial staging
        carry_in = [os.path.join(journal_mod.carry_dir(root), name)
                    for name in intake["carry_in"]]
        with obs.span("ingest.delta_balance", generation=generation):
            plan = delta_mod.stage_delta_balance(
                root, generation, part_paths, stage_dir,
                prior=intake["prior"], carry_in_paths=carry_in,
                num_shards=intake["num_shards"],
                flush=intake.get("flush", False), log=log)

    published = delta_mod.publish_delta_balance(
        root, stage_dir, plan, carry_dir=journal_mod.carry_dir(root),
        log=log)
    obs.fleet.record("generation.delta_balance", generation=generation,
                     new_shards=len(published["new"]),
                     touched_prior=len(published["touched"]))

    changed_dirs = {os.path.dirname(os.path.join(root, rel))
                    for rel in list(published["new"])
                    + list(published["touched"])}
    known_counts = dict(published["new"])
    known_counts.update(published["touched"])
    _refresh_dir_bookkeeping(root, changed_dirs or {root}, generation,
                             known_counts)
    obs.fleet.record("generation.gate_advance", generation=generation)

    journal.publish_generation(generation, intake["hashes"], fingerprint,
                               carry=published["carry"],
                               doc_bytes=intake.get("doc_bytes", 0))
    obs.fleet.record("generation.committed", generation=generation,
                     docs=len(intake["hashes"]))
    obs.set_gauge("ingest_backlog_docs", 0)

    # Post-commit sweep (idempotent; redone by pending_work on a crash):
    # consumed carry inputs, then the whole work dir.
    cdir = journal_mod.carry_dir(root)
    keep = set(journal.carry.values())
    # Backend-routed sweep: on the mock store the carry files are
    # objects, and a raw unlink of only the view would leave them
    # readable through their commit records (silent resurrection).
    names = rio.list_dir(cdir)
    for name in names or ():
        if name not in keep:
            rio.remove(os.path.join(cdir, name))
    shutil.rmtree(wdir, ignore_errors=True)

    carry_rows = sum(
        plan["bins"][k]["carry"].get(name, 0)
        for k in plan["bins"] for name in plan["bins"][k]["carry"])
    samples_new = sum(plan["bins"][k]["consumed"] for k in plan["bins"])
    report = {
        "noop": False,
        "generation": generation,
        "docs": len(intake["hashes"]),
        "samples_visible": samples_new,
        "carry_rows": carry_rows,
        "new_shards": len(published["new"]),
        "touched_prior_shards": sorted(published["touched"]),
    }
    if obs.enabled():
        obs.inc("ingest_docs_total", len(intake["hashes"]),
                generation=generation)
        obs.inc("ingest_shards_appended_total", len(published["new"]),
                generation=generation)
        obs.set_gauge("ingest_generation", generation)
        obs.set_gauge("ingest_carry_rows", carry_rows)
    log("ingest: generation {} published: {} doc(s), {} new shard(s), "
        "{} row(s) carried, {} prior shard(s) touched".format(
            generation, report["docs"], report["new_shards"], carry_rows,
            len(published["touched"])))
    return report


def _carry_row_count(root, journal):
    total = 0
    cdir = journal_mod.carry_dir(root)
    for name in sorted(journal.carry.values()):
        path = os.path.join(cdir, name)
        if os.path.isfile(path):
            total += get_num_samples_of_parquet(path)
    return total


def join_pending_generation(root, tokenizer, *, config=None, num_workers=1,
                            lease_ttl=30.0, holder_id=None,
                            scatter_units=None, comm=None, log=None):
    """Join the in-flight generation's ELASTIC preprocess as a helper
    host — the autoscaler's scale-up unit (observability/autoscale.py).

    A helper never scans the landing dir, never balances, never commits
    the journal: it replays the primary's FROZEN intake record (doc set
    and knobs were fixed at intake time, so every joining host computes
    the identical plan) and enters the same lease claim loop, stealing
    scatter/gather units exactly like any elastic peer. It exits when
    the preprocess phase is done (or there is nothing to join); the
    primary's ingest round does the rest.

    Returns a report dict: ``{"joined": False, "why": ...}`` when there
    was nothing to do, else ``{"joined": True, "generation": N}``."""
    from ..preprocess.bert import BertPretrainConfig
    from ..preprocess.runner import BertBucketProcessor, run_bert_preprocess

    log = log or (lambda msg: None)
    obs.fleet.ensure_started()
    config = config or BertPretrainConfig()
    journal = journal_mod.Journal.load(root)
    pending = journal.pending_work()
    if pending is None:
        return {"joined": False, "why": "no in-flight generation"}
    generation = int(pending["generation"])
    if not pending["hashes"]:
        return {"joined": False, "why": "pending generation has no "
                                        "documents (flush-only round)"}
    # Same drift refusal as the primary, against the intake-frozen knobs:
    # a helper with a different processor config would journal units
    # whose bytes mean something else.
    fingerprint = BertBucketProcessor(
        tokenizer, config, int(pending["seed"]), root, pending["bin_size"],
        "parquet",
        pack_seq_length=pending.get("pack_seq_length"),
        pack_max_per_row=pending.get("pack_max_per_row", 8)).fingerprint()
    if pending.get("fingerprint") != fingerprint:
        raise ValueError(
            "helper configuration drift: in-flight generation {} was "
            "started with fingerprint {} but this helper computes {}; "
            "launch the helper with the primary's arguments".format(
                generation, pending.get("fingerprint"), fingerprint))
    wdir = journal_mod.work_dir(root, generation)
    staging = os.path.join(wdir, "staging")
    pre_dir = os.path.join(wdir, "pre")
    if not os.path.isdir(staging):
        return {"joined": False, "why": "staging corpus not on disk yet"}
    if os.path.isdir(pre_dir) and get_all_parquets_under(pre_dir) \
            and not os.path.isdir(os.path.join(pre_dir, "_done")):
        # Shards present and the unit ledger already retired: the
        # preprocess finished; the primary is balancing/committing and
        # a late joiner would only redo finished work.
        return {"joined": False, "why": "preprocess already finalized"}
    log("ingest helper: joining generation {} ({} document(s))".format(
        generation, len(pending["hashes"])))
    with obs.span("ingest.join", generation=generation):
        run_bert_preprocess(
            {"ingest": staging},
            pre_dir,
            tokenizer,
            config=config,
            num_blocks=int(pending["num_blocks"]),
            sample_ratio=1.0,
            seed=int(pending["seed"]),
            bin_size=pending["bin_size"],
            global_shuffle=True,
            comm=comm,
            log=log,
            num_workers=num_workers,
            resume=os.path.isdir(pre_dir),
            elastic=True,
            lease_ttl=lease_ttl,
            holder_id=holder_id,
            scatter_units=scatter_units,
            emit_manifest=False,
            pack_seq_length=pending.get("pack_seq_length"),
            pack_max_per_row=pending.get("pack_max_per_row", 8),
        )
    obs.fleet.record("generation.joined", generation=generation,
                     holder=str(holder_id or ""))
    return {"joined": True, "generation": generation}


def watch(root, tokenizer, landing, interval_s=30.0, max_rounds=0,
          log=None, **kwargs):
    """The polling service loop: ``ingest_once`` forever (or
    ``max_rounds`` times), sleeping ``interval_s`` between scans. Each
    round is independently crash-safe; the loop itself holds no state.
    Reports are returned only in bounded (``max_rounds``) mode — the
    forever loop never returns, and accumulating a dict per round for
    months would be a slow leak."""
    import time
    log = log or (lambda msg: None)
    rounds = 0
    reports = [] if max_rounds else None
    while True:
        report = ingest_once(root, tokenizer, landing=landing, log=log,
                             **kwargs)
        rounds += 1
        if max_rounds:
            reports.append(report)
            if rounds >= max_rounds:
                return reports
        time.sleep(interval_s)
